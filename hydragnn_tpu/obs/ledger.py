"""Goodput & MFU ledger: where every second of a step's wall clock went.

The MFU campaign (ROADMAP) is driven by the introspection loop, yet until
this module nothing TOLD you what fraction of a run was useful compute.
Three pieces close that gap:

- :class:`GoodputLedger` — per-epoch wall-time attribution. Every window
  (``epoch_start`` .. next ``epoch_start``/run end) is classified into
  :data:`CATEGORIES` by composing signals that already exist: the timed
  step dispatches (``RunTelemetry.on_step``), the backend-compile duration
  accumulator (``jax.monitoring`` listener), checkpoint ``snapshot_s``/
  ``write_s``, the stream/data-wait accounting, the divergence guard's
  measured restore time, and the eval spans the epoch driver marks. The
  result is one schema-gated ``goodput`` event per epoch plus
  ``hydragnn_train_goodput_fraction{category=...}`` gauges whose fractions
  sum to 1 by construction.
- **MFU** — per-bucket ``hydragnn_train_mfu{bucket=...}`` computed as
  ``flops_per_step x steps_per_sec / peak_flops``, where ``flops_per_step``
  is the XLA cost-model figure the introspection layer already captures,
  ``steps_per_sec`` is measured over the window's compile-free step
  dispatch time, and ``peak_flops`` comes from the device-kind table below
  (precision-aware: bf16 vs f32 peaks follow ``resolve_precision``;
  ``HYDRAGNN_PEAK_FLOPS`` overrides, unknown kinds warn once).
- **Fleet rollup** — ``python -m hydragnn_tpu.obs fleet <dir>`` merges the
  per-host event streams of an elastic run (rank 0's ``events.jsonl`` plus
  the ``events-host<k>.jsonl`` streams the other hosts write in elastic
  mode) into one cross-host timeline, reads the step-time digests the
  elastic ``Heartbeat`` leases carry, flags stragglers (host p50 exceeding
  the leave-one-out fleet median by a configurable factor), and prices
  ``world_resize`` recovery windows as lost goodput. The same digests feed
  live ``fleet_step_p50_seconds{host=...}`` gauges on the leader's
  ``/metrics`` (:func:`poll_fleet_gauges`, run at scrape time).

Everything here is advisory accounting: no path may raise into the
training loop, and a category the run has no signal for simply reads 0.
"""

import glob
import json
import math
import os
import threading
import time
import warnings
from typing import Callable, Dict, List, Optional

# wall-time categories, in exposition order. "other" is the residual —
# host-side bookkeeping, logging, loader setup — so fractions always sum
# to 1 regardless of which signals a run actually has.
CATEGORIES = (
    "compute",
    "data_stall",
    "collective",
    "checkpoint",
    "compile",
    "guard_recovery",
    "eval",
    "other",
)

# peak dense-matmul FLOP/s per chip by PJRT device kind. bf16 is the MXU
# peak; f32 is the (half-rate) figure mixed_precision=False runs are
# honestly judged against. benchmarks/model_bench.py consumes this same
# table (bf16 column) so the bench MFU and the live gauge cannot drift.
PEAK_FLOPS: Dict[str, Dict[str, float]] = {
    "TPU v2": {"bf16": 45e12, "f32": 22.5e12},
    "TPU v3": {"bf16": 123e12, "f32": 61.5e12},
    "TPU v4": {"bf16": 275e12, "f32": 137.5e12},
    "TPU v5 lite": {"bf16": 197e12, "f32": 98.5e12},
    "TPU v5e": {"bf16": 197e12, "f32": 98.5e12},
    "TPU v5": {"bf16": 459e12, "f32": 229.5e12},  # v5p
    "TPU v6 lite": {"bf16": 918e12, "f32": 459e12},  # v6e / Trillium
}

# hot-path programs whose buckets count as TRAINING compute for MFU —
# eval/predict buckets also carry flops gauges but run at different
# step cadence, so pricing them with the train step rate would lie
TRAIN_PROGRAMS = frozenset(
    {"train_step", "train_multi", "epoch_scan", "fit_scan",
     "partitioned_train_step"}
)

_peak_warned: set = set()

# the run's resolved compute precision (models/create.resolve_precision;
# steps.py records it when it builds the step programs)
_precision = {"mixed": False, "source": "default"}


def note_precision(mixed: bool, source: str = "explicit"):
    """The step builder resolved the run's compute precision — recorded so
    the MFU denominator picks the matching peak column."""
    _precision["mixed"] = bool(mixed)
    _precision["source"] = str(source)


def current_precision() -> Dict:
    return dict(_precision)


def resolve_peak_flops(
    device_kind: Optional[str] = None, mixed: Optional[bool] = None
) -> Optional[float]:
    """Peak FLOP/s for MFU: ``HYDRAGNN_PEAK_FLOPS`` (absolute FLOP/s,
    operator override — also the only way to get an MFU on CPU/unknown
    chips) > the :data:`PEAK_FLOPS` table row for this device kind at the
    run's precision. Unknown kinds warn ONCE per kind and return None —
    an absent MFU is better than one against an invented denominator."""
    env = os.getenv("HYDRAGNN_PEAK_FLOPS")
    if env is not None and env.strip() != "":
        try:
            return float(env)
        except ValueError:
            if "env" not in _peak_warned:
                _peak_warned.add("env")
                warnings.warn(
                    f"HYDRAGNN_PEAK_FLOPS={env!r} is not a number — "
                    "ignored",
                    stacklevel=2,
                )
    if device_kind is None:
        try:
            import jax

            device_kind = jax.devices()[0].device_kind
        except Exception:
            return None
    row = PEAK_FLOPS.get(device_kind)
    if row is None:
        if device_kind not in _peak_warned:
            _peak_warned.add(device_kind)
            warnings.warn(
                f"no peak-FLOPs entry for device kind {device_kind!r} — "
                "MFU unavailable (set HYDRAGNN_PEAK_FLOPS to override)",
                stacklevel=2,
            )
        return None
    if mixed is None:
        mixed = _precision["mixed"]
    return row["bf16"] if mixed else row["f32"]


class GoodputLedger:
    """Per-epoch wall-time attribution for one telemetry run.

    Owned by ``RunTelemetry``; every mutator is cheap (a lock + float
    adds) and tolerant of being called from the checkpoint writer thread.
    Windows open at ``epoch_begin`` and close at the NEXT ``epoch_begin``
    (or ``finalize``), so post-epoch work — the resumable checkpoint save,
    scalar flushes — lands in the epoch that caused it."""

    def __init__(
        self,
        registry=None,
        emit: Optional[Callable] = None,
        compile_seconds: Optional[Callable[[], float]] = None,
        clock: Callable[[], float] = time.time,
    ):
        self._registry = registry
        self._emit = emit or (lambda *a, **k: None)
        self._compile_seconds = compile_seconds or (lambda: 0.0)
        self._clock = clock
        # reentrant: _reset_window guards its own writes while its only
        # caller (epoch_begin) already holds the lock
        self._lock = threading.RLock()
        # per-bucket flops + per-step collective bytes of captured TRAIN
        # programs (record_compile forwards every capture here;
        # run-scoped, unlike introspect.captured() which is
        # process-global)
        self._train_flops: Dict[str, float] = {}
        self._train_coll_bytes: Dict[str, float] = {}
        self._open = False
        self._epoch = 0

    # ---- window lifecycle ----------------------------------------------
    def _reset_window(self):
        with self._lock:
            self._t_open = self._clock()
            self._compile_at_open = self._compile_seconds()
            self._steps = 0
            self._step_s = 0.0
            self._compile_in_step_s = 0.0
            self._data_stall_s = 0.0
            self._checkpoint_s = 0.0
            self._guard_s = 0.0
            self._eval_s = 0.0
            self._compile_in_eval_s = 0.0
            self._train_wall_s = 0.0
            # open eval span bookkeeping (eval compile/data-wait time must
            # not double-count against the eval category)
            self._eval_t0 = None
            self._eval_compile_at = 0.0
            self._eval_stall_at = 0.0

    def epoch_begin(self, epoch: int):
        with self._lock:
            payload = self._close_window_locked() if self._open else None
            self._reset_window()
            self._open = True
            self._epoch = int(epoch)
        if payload is not None:
            self._publish(payload)

    def finalize(self):
        """Run teardown: close (and publish) the last open window."""
        with self._lock:
            payload = self._close_window_locked() if self._open else None
            self._open = False
        if payload is not None:
            self._publish(payload)

    # ---- recording hooks -----------------------------------------------
    def on_step(self, seconds: float, count: int = 1,
                compile_s: float = 0.0):
        """One train-step dispatch: ``compile_s`` is the backend-compile
        time that landed INSIDE this dispatch (0 for warm steps)."""
        with self._lock:
            if not self._open:
                return
            self._steps += int(count)
            self._step_s += float(seconds)
            self._compile_in_step_s += min(
                max(float(compile_s), 0.0), float(seconds)
            )

    def note_program(self, rec: Dict):
        """A compile capture landed (obs/introspect.py via
        ``record_compile``): remember train-program FLOPs (MFU) and
        per-step collective bytes (the collective-time estimate)."""
        if rec.get("name") not in TRAIN_PROGRAMS:
            return
        cost = rec.get("cost") or {}
        coll = rec.get("collectives") or {}
        with self._lock:
            if cost.get("flops"):
                self._train_flops[rec["bucket"]] = float(cost["flops"])
            if coll:
                self._train_coll_bytes[rec["bucket"]] = float(
                    sum(coll.values())
                )

    def data_wait(self, seconds: float):
        """The consumer waited on the data plane (host-side collate /
        H2D transfer / stream pipeline)."""
        with self._lock:
            if self._open:
                self._data_stall_s += max(float(seconds), 0.0)

    def checkpoint_cost(self, seconds: float):
        with self._lock:
            if self._open:
                self._checkpoint_s += max(float(seconds), 0.0)

    def guard_cost(self, seconds: float):
        with self._lock:
            if self._open:
                self._guard_s += max(float(seconds), 0.0)

    def _collective_estimate(self) -> float:
        """Estimated collective seconds for this window: per-step
        collective result bytes (PR 10's HLO accounting, riding the
        compile captures) x steps / the operator-declared interconnect
        bandwidth ``HYDRAGNN_ICI_BYTES_PER_S``. Deliberately 0 without
        that knob — a labeled estimate beats a silent invented constant,
        and on CPU there is nothing to estimate."""
        if not self._steps or not self._train_coll_bytes:
            return 0.0
        bw = os.getenv("HYDRAGNN_ICI_BYTES_PER_S")
        if not bw:
            return 0.0
        try:
            bw = float(bw)
        except ValueError:
            return 0.0
        if bw <= 0:
            return 0.0
        # the busiest train bucket bounds the estimate (one step runs one
        # bucket; which one each step ran is not tracked)
        return self._steps * max(self._train_coll_bytes.values()) / bw

    def note_train_wall(self, seconds: float):
        """The epoch driver's measured training wall (the whole-dispatch
        staged/fit paths have no per-step hook; this is their compute
        signal)."""
        with self._lock:
            if self._open and seconds is not None:
                self._train_wall_s += max(float(seconds), 0.0)

    def eval_begin(self):
        with self._lock:
            if not self._open:
                return
            self._eval_t0 = time.perf_counter()
            self._eval_compile_at = self._compile_seconds()
            self._eval_stall_at = self._data_stall_s

    def eval_end(self):
        """Close an eval span: the span's compile time and data waits stay
        in THEIR categories; only the remainder is eval."""
        with self._lock:
            if not self._open or self._eval_t0 is None:
                return
            wall = time.perf_counter() - self._eval_t0
            compile_in_eval = max(
                self._compile_seconds() - self._eval_compile_at, 0.0
            )
            stall_in_eval = max(
                self._data_stall_s - self._eval_stall_at, 0.0
            )
            self._eval_t0 = None
            self._eval_s += max(wall - compile_in_eval - stall_in_eval, 0.0)
            # remembered so the staged-path compute deduction below can
            # exclude it — eval compile must not be subtracted from the
            # TRAIN wall as well
            self._compile_in_eval_s += compile_in_eval

    # ---- window close ---------------------------------------------------
    def _close_window_locked(self) -> Optional[Dict]:
        """Fold the window's accumulators into the goodput payload
        (returned for publication OUTSIDE the lock). None when the window
        saw no attributable time at all (e.g. a predict-only run)."""
        wall = max(self._clock() - self._t_open, 0.0)
        compile_s = max(self._compile_seconds() - self._compile_at_open, 0.0)
        collective_s = self._collective_estimate()
        if self._steps:
            # streaming path: compute is the compile-free step dispatch
            compute_s = max(self._step_s - self._compile_in_step_s, 0.0)
        else:
            # whole-dispatch paths (staged epochs / fit chunks): the
            # driver's measured train wall IS device compute, minus the
            # window's TRAIN-side compile share (compile that happened
            # inside an eval span was already kept out of eval and must
            # not be deducted from the train wall too)
            compute_s = max(
                self._train_wall_s
                - max(compile_s - self._compile_in_eval_s, 0.0),
                0.0,
            )
        compute_s = max(compute_s - collective_s, 0.0)
        seconds = {
            "compute": compute_s,
            "data_stall": self._data_stall_s,
            "collective": collective_s,
            "checkpoint": self._checkpoint_s,
            "compile": compile_s,
            "guard_recovery": self._guard_s,
            "eval": self._eval_s,
        }
        known = sum(seconds.values())
        if known <= 0.0 and wall <= 0.0:
            return None
        seconds["other"] = max(wall - known, 0.0)
        denom = known + seconds["other"]  # == max(wall, known)
        fractions = {
            k: (seconds[k] / denom if denom > 0 else 0.0)
            for k in CATEGORIES
        }
        payload = {
            "epoch": self._epoch,
            "wall_s": round(wall, 6),
            "seconds": {k: round(v, 6) for k, v in seconds.items()},
            "fractions": fractions,
            "goodput_fraction": fractions["compute"],
            "steps": self._steps,
            "step_s": round(self._step_s, 6),
        }
        if collective_s > 0:
            # bandwidth-model figure, not a measurement — labeled so a
            # reader never mistakes it for one
            payload["collective_estimated"] = True
        mfu = self._mfu_locked()
        if mfu:
            payload["mfu"] = mfu
        return payload

    def _mfu_locked(self) -> Dict[str, Dict]:
        """Per-bucket MFU over this window's compile-free step time.

        Which bucket each step ran is not tracked, so ``steps_per_sec``
        is the window's BLENDED rate across all train buckets; with more
        than one bucket in play each entry carries ``rate: "blended"``
        (a mix shift moves the figure as much as a perf change — the
        budget-floor docs call this out)."""
        basis_s = self._step_s - self._compile_in_step_s
        if not self._train_flops or self._steps <= 0 or basis_s <= 0.0:
            return {}
        peak = resolve_peak_flops()
        if not peak:
            return {}
        steps_per_sec = self._steps / basis_s
        blended = len(self._train_flops) > 1
        out = {}
        for bucket, flops in sorted(self._train_flops.items()):
            out[bucket] = {
                "mfu": flops * steps_per_sec / peak,
                "flops": flops,
                "steps_per_sec": steps_per_sec,
                "peak_flops": peak,
                **({"rate": "blended"} if blended else {}),
            }
        return out

    def _publish(self, payload: Dict):
        try:
            self._emit("goodput", **payload)
            if self._registry is not None:
                for cat, frac in payload["fractions"].items():
                    self._registry.set_labeled(
                        "goodput_fraction", frac, category=cat
                    )
                for bucket, m in (payload.get("mfu") or {}).items():
                    self._registry.set_labeled(
                        "mfu", m["mfu"], bucket=bucket
                    )
        except Exception:
            pass  # accounting must never kill the run


# ---- fleet rollup ----------------------------------------------------------


def _median(values: List[float]) -> float:
    import statistics

    return float(statistics.median(values))


def flag_stragglers(
    per_host: Dict[str, Dict],
    factor: float = 2.0,
    min_steps: int = 3,
) -> List[str]:
    """Hosts whose step-time p50 exceeds ``factor`` x the leave-one-out
    median of the other qualified hosts' p50s. Leave-one-out (not the
    whole-fleet median) so a 2-host fleet can still flag its slow half;
    hosts with fewer than ``min_steps`` recorded steps neither flag nor
    count toward anyone's baseline (their p50 is noise)."""
    qualified = {
        h: s["p50"]
        for h, s in per_host.items()
        if s.get("p50") is not None and s.get("count", 0) >= min_steps
    }
    if len(qualified) < 2:
        return []
    flagged = []
    for host, p50 in qualified.items():
        others = [v for h, v in qualified.items() if h != host]
        baseline = _median(others)
        if baseline > 0 and p50 > factor * baseline:
            flagged.append(host)
    return sorted(flagged)


def _read_json(path: str) -> Optional[Dict]:
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def discover_fleet(root: str):
    """(event stream paths, worker lease paths) under a run/coordination
    directory — accepts the elastic smoke layout (``<dir>/logs/<run>/
    events*.jsonl`` + ``<dir>/elastic-coord/workers/host-*.json``), a bare
    run dir, or a coordination dir itself."""
    # '**' matches zero directories too, so one recursive glob per
    # pattern covers both the root-level and nested layouts
    streams = sorted(
        glob.glob(os.path.join(root, "**", "events*.jsonl"), recursive=True)
    )
    leases = sorted(
        glob.glob(os.path.join(root, "**", "workers", "host-*.json"),
                  recursive=True)
    )
    return streams, leases


def _host_of_stream(path: str) -> Optional[str]:
    """``events-host3.jsonl`` -> "3"; the shared ``events.jsonl`` has no
    fixed host (ranks 0 of successive generations append to it) — per-
    record attribution walks the manifests instead."""
    base = os.path.basename(path)
    if base.startswith("events-host") and base.endswith(".jsonl"):
        return base[len("events-host"):-len(".jsonl")]
    return None


def build_fleet_report(
    root: str,
    straggler_factor: float = 2.0,
    min_steps: int = 3,
) -> Dict:
    """Merge an elastic run's per-host observability into one report:
    cross-host timeline, per-host step-time distributions, straggler
    flags, and ``world_resize`` recovery priced as lost goodput."""
    from hydragnn_tpu.obs.report import load_events

    stream_paths, lease_paths = discover_fleet(root)
    records = []
    for path in stream_paths:
        fixed_host = _host_of_stream(path)
        host = fixed_host
        for rec in load_events(path):
            if rec.get("event") == "run_manifest" and fixed_host is None:
                # rank 0's stream: successive generations' rank 0 may be
                # different hosts — the manifest marks each segment
                host = str(rec.get("host", rec.get("run", "rank0")))
            rec = dict(rec)
            rec["_host"] = host if host is not None else "rank0"
            rec["_stream"] = os.path.basename(path)
            records.append(rec)
    records.sort(key=lambda r: (r.get("ts") or 0.0, r.get("seq") or 0))

    # per-host step stats from BOTH sources, then keep whichever saw more
    # steps per host: the heartbeat digest carries real quantiles but can
    # be stale for a host that died between its last lease write and its
    # final steps (a hard kill skips the flush), while the per-host
    # goodput events record every completed epoch's step count/time (mean
    # only) as they happen.
    leases: Dict[str, Dict] = {}
    for path in lease_paths:
        lease = _read_json(path)
        if not lease:
            continue
        host = str(lease.get("host", os.path.basename(path)))
        digest = lease.get("step_digest") or {}
        entry = {
            "count": int(digest.get("count", 0)),
            "p50": digest.get("p50"),
            "p99": digest.get("p99"),
            "sum": digest.get("sum"),
            "step": lease.get("step"),
            "epoch": lease.get("epoch"),
            "done": bool(lease.get("done")),
            "source": "heartbeat",
        }
        if entry["count"] and entry.get("sum") is not None:
            entry["mean"] = float(entry["sum"]) / entry["count"]
        leases[host] = entry
    from_events: Dict[str, Dict] = {}
    for rec in records:
        if rec.get("event") != "goodput":
            continue
        host = rec["_host"]
        steps = rec.get("steps") or 0
        step_s = rec.get("step_s") or 0.0
        if not steps:
            continue
        if (rec.get("seconds") or {}).get("compile"):
            # warmup/recompile windows: their step time is compile, not
            # pace — including them would read every freshly (re)spawned
            # host as a straggler
            continue
        entry = from_events.setdefault(
            host, {"count": 0, "sum": 0.0, "p50": None, "source": "events"}
        )
        entry["count"] += int(steps)
        entry["sum"] = float(entry.get("sum") or 0.0) + float(step_s)
        entry["mean"] = entry["sum"] / max(entry["count"], 1)
        entry["p50"] = entry["mean"]  # events carry no quantiles
    per_host: Dict[str, Dict] = {}
    for host in set(leases) | set(from_events):
        lease = leases.get(host)
        ev = from_events.get(host)
        best = max(
            (e for e in (lease, ev) if e is not None),
            key=lambda e: e.get("count", 0),
        )
        if lease is not None and best is not lease:
            # keep the lease's liveness fields on the events-derived stats
            best = {**best, "step": lease.get("step"),
                    "epoch": lease.get("epoch"),
                    "done": lease.get("done", False)}
        per_host[host] = best

    stragglers = flag_stragglers(
        per_host, factor=straggler_factor, min_steps=min_steps
    )

    ts = [r["ts"] for r in records
          if isinstance(r.get("ts"), (int, float))]
    wall = (ts[-1] - ts[0]) if len(ts) > 1 else 0.0
    resizes = []
    lost_s = 0.0
    lost_host_s = 0.0
    for rec in records:
        if rec.get("event") != "world_resize":
            continue
        recovery = float(rec.get("recovery_s") or 0.0)
        lost_s += recovery
        lost_host_s += recovery * int(rec.get("new_world") or 1)
        resizes.append(
            {
                "gen": rec.get("gen"),
                "old_world": rec.get("old_world"),
                "new_world": rec.get("new_world"),
                "recovery_s": recovery,
                "t": round(float(rec.get("ts", 0.0)) - (ts[0] if ts else 0.0), 3),
            }
        )

    goodputs = [
        r.get("goodput_fraction")
        for r in records
        if r.get("event") == "goodput"
        and isinstance(r.get("goodput_fraction"), (int, float))
    ]

    timeline = [
        {
            "t": round(float(r.get("ts", 0.0)) - (ts[0] if ts else 0.0), 3),
            "host": r["_host"],
            "event": r["event"],
            "stream": r["_stream"],
        }
        for r in records
        if r.get("event")
        in ("run_manifest", "host_lost", "world_resize", "stall",
            "guard_restore", "checkpoint_restored", "resume", "run_end",
            "early_stop", "wallclock_stop", "drift_alert")
    ]

    # model-quality rollup: merge drift/sink events from every stream
    # (old streams carry none — the section stays None and renderers
    # omit it, so pre-observatory fleets keep rendering unchanged)
    quality = None
    from hydragnn_tpu.obs.drift import QUALITY_EVENTS, build_drift_report

    quality_records = [
        r for r in records if r.get("event") in QUALITY_EVENTS
    ]
    if quality_records:
        quality = build_drift_report(quality_records)

    return {
        "root": root,
        "streams": [os.path.basename(p) for p in stream_paths],
        "hosts": per_host,
        "stragglers": stragglers,
        "straggler_factor": straggler_factor,
        "events": len(records),
        "wall_s": round(wall, 3),
        "resizes": resizes,
        "lost_goodput_s": round(lost_s, 3),
        "lost_goodput_host_s": round(lost_host_s, 3),
        "lost_goodput_fraction": (
            round(lost_s / wall, 6) if wall > 0 else 0.0
        ),
        "mean_goodput_fraction": (
            round(sum(goodputs) / len(goodputs), 6) if goodputs else None
        ),
        "quality": quality,
        "timeline": timeline,
    }


def _fmt_s(v) -> str:
    if v is None or (isinstance(v, float) and not math.isfinite(v)):
        return "-"
    return f"{float(v):.6g}"


def render_fleet_text(report: Dict) -> str:
    lines = [
        "== fleet rollup ==",
        f"root: {report['root']}",
        f"streams: {', '.join(report['streams']) or '(none)'}",
        f"events: {report['events']}  wall: {report['wall_s']}s  "
        f"mean goodput: {_fmt_s(report['mean_goodput_fraction'])}",
        f"resizes: {len(report['resizes'])}  lost goodput: "
        f"{report['lost_goodput_s']}s wall "
        f"({report['lost_goodput_host_s']}s host-seconds, "
        f"{report['lost_goodput_fraction']:.2%} of fleet wall)",
        "",
        "-- hosts (step-time digests) --",
    ]
    for host in sorted(report["hosts"]):
        s = report["hosts"][host]
        tag = " STRAGGLER" if host in report["stragglers"] else ""
        done = " done" if s.get("done") else ""
        lines.append(
            f"host {host}: steps={s.get('count', 0)} "
            f"p50={_fmt_s(s.get('p50'))}s p99={_fmt_s(s.get('p99'))}s "
            f"mean={_fmt_s(s.get('mean'))}s "
            f"[{s.get('source', '?')}]{done}{tag}"
        )
    if report["stragglers"]:
        lines.append(
            f"stragglers (p50 > {report['straggler_factor']}x fleet "
            f"median): {', '.join(report['stragglers'])}"
        )
    else:
        lines.append("stragglers: none")
    if report["resizes"]:
        lines += ["", "-- world resizes --"]
        for r in report["resizes"]:
            lines.append(
                f"{r['t']:>10.3f}s  gen {r['gen']}: {r['old_world']} -> "
                f"{r['new_world']} hosts, recovery {r['recovery_s']}s"
            )
    q = report.get("quality")
    if q:
        lines += ["", "-- model quality (fleet-merged drift events) --"]
        lines.append(
            f"windows: {q.get('windows', 0)}  alert events: "
            f"{len(q.get('alerts') or [])}  active: "
            f"{len(q.get('alerts_active') or [])}"
        )
        for key in q.get("alerts_active") or []:
            lines.append(f"ACTIVE ALERT: {key}")
        sink = q.get("sink")
        if sink:
            lines.append(
                f"feedback sink: accepted={sink.get('accepted')} "
                f"deduped={sink.get('deduped')} "
                f"graphs={sink.get('graphs')} packs={sink.get('packs')}"
            )
    if report["timeline"]:
        lines += ["", "-- cross-host timeline (s after first event) --"]
        for item in report["timeline"]:
            lines.append(
                f"{item['t']:>10.3f}  host {item['host']:<8} "
                f"{item['event']:<20} [{item['stream']}]"
            )
    return "\n".join(lines) + "\n"


def render_fleet_markdown(report: Dict) -> str:
    lines = [
        f"# Fleet rollup: {report['root']}",
        "",
        f"streams: {', '.join(report['streams']) or '(none)'}  ",
        f"events: {report['events']}  wall: {report['wall_s']}s  "
        f"mean goodput: {_fmt_s(report['mean_goodput_fraction'])}  ",
        f"lost goodput: {report['lost_goodput_s']}s wall / "
        f"{report['lost_goodput_host_s']}s host-seconds "
        f"({report['lost_goodput_fraction']:.2%})",
        "",
        "## Hosts",
        "",
        "| host | steps | p50 (s) | p99 (s) | mean (s) | source | straggler |",
        "| --- | --- | --- | --- | --- | --- | --- |",
    ]
    for host in sorted(report["hosts"]):
        s = report["hosts"][host]
        lines.append(
            f"| {host} | {s.get('count', 0)} | {_fmt_s(s.get('p50'))} | "
            f"{_fmt_s(s.get('p99'))} | {_fmt_s(s.get('mean'))} | "
            f"{s.get('source', '?')} | "
            f"{'YES' if host in report['stragglers'] else ''} |"
        )
    if report["resizes"]:
        lines += ["", "## World resizes", ""]
        for r in report["resizes"]:
            lines.append(
                f"- t={r['t']}s gen {r['gen']}: {r['old_world']} -> "
                f"{r['new_world']} hosts, recovery {r['recovery_s']}s"
            )
    q = report.get("quality")
    if q:
        lines += ["", "## Model quality (fleet-merged drift events)", ""]
        lines.append(
            f"windows: {q.get('windows', 0)}  alert events: "
            f"{len(q.get('alerts') or [])}  active: "
            f"{len(q.get('alerts_active') or [])}  "
        )
        for key in q.get("alerts_active") or []:
            lines.append(f"- ACTIVE ALERT: `{key}`")
    return "\n".join(lines) + "\n"


def render_fleet_json(report: Dict) -> str:
    return json.dumps(report, indent=2, sort_keys=True) + "\n"


FLEET_RENDERERS = {
    "text": render_fleet_text,
    "markdown": render_fleet_markdown,
    "json": render_fleet_json,
}


def poll_fleet_gauges(
    coord_dir: str,
    registry,
    straggler_factor: float = 2.0,
    min_steps: int = 3,
    stale_s: Optional[float] = None,
    now: Optional[float] = None,
):
    """Scrape-time fleet poll on the leader: read every LIVE worker
    lease's step-time digest into ``fleet_step_p50_seconds{host=...}``
    and count stragglers into ``fleet_straggler_hosts``. Lease files are
    never deleted, so liveness is judged the same way the elastic
    watchdog judges it: ``done=True`` (clean finish), a tombstone under
    ``dead/``, or a lease older than ``stale_s`` (default 4x
    HYDRAGNN_ELASTIC_LEASE_S, floor 30 s — well past detection, so a
    merely-slow host still shows) all drop the host from the live view;
    without this a dead straggler would pin ``fleet_straggler_hosts``
    >= 1 forever AFTER the resize that healed it. One readdir + one
    small JSON read per host, at scrape cadence only — never in the
    step loop."""
    try:
        if stale_s is None:
            try:
                lease = float(os.getenv("HYDRAGNN_ELASTIC_LEASE_S", "6.0"))
            except ValueError:
                lease = 6.0
            stale_s = max(lease * 4.0, 30.0)
        now = time.time() if now is None else now
        per_host: Dict[str, Dict] = {}
        # membership is a LIVE view: a host that died/finished since the
        # last scrape must drop out of the exposition, not freeze
        registry.clear_labeled("fleet_step_p50_seconds")
        for path in sorted(
            glob.glob(os.path.join(coord_dir, "workers", "host-*.json"))
        ):
            lease = _read_json(path)
            if not lease or lease.get("done"):
                continue
            host = str(lease.get("host", os.path.basename(path)))
            ts = lease.get("ts")
            if ts is not None and now - float(ts) > stale_s:
                continue
            if _read_json(
                os.path.join(coord_dir, "dead", f"host-{host}.json")
            ) is not None:
                continue
            digest = lease.get("step_digest") or {}
            if digest.get("p50") is not None:
                registry.set_labeled(
                    "fleet_step_p50_seconds",
                    float(digest["p50"]),
                    host=host,
                )
            per_host[host] = {
                "p50": digest.get("p50"),
                "count": digest.get("count", 0),
            }
        registry.set(
            "fleet_straggler_hosts",
            float(
                len(
                    flag_stragglers(
                        per_host, factor=straggler_factor,
                        min_steps=min_steps,
                    )
                )
            ),
        )
    except Exception:
        pass  # a flaky shared FS must not break /metrics
