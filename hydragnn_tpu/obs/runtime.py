"""Run-scoped telemetry: the glue between training code and obs primitives.

One :class:`RunTelemetry` per training run bundles the three tentpole
pieces — the structured event stream (``events.jsonl``), the live
``/metrics``+``/healthz`` endpoint, and the training metrics registry —
behind module-level hook functions (:func:`emit`, :func:`epoch_complete`,
:func:`guard_skip`, ...) that the epoch driver, trainer, divergence
guard, and checkpoint layer call unconditionally. (The trainer's per-step
path resolves :func:`active` once per epoch and calls
``metrics.on_step`` directly — one global read per epoch, not per step.)

The hooks follow the fault-injection harness pattern
(``utils/faults.py``): with no active telemetry each call is ONE global
read and a return, so instrumented code costs nothing when observability
is off — the acceptance bar is "telemetry-disabled epoch-loop wall time
within noise of baseline", enforced by ``tests/test_observability.py``.

Enablement (rank 0 only; other ranks keep the no-op hooks):

- events + metrics: on by default for driver runs; ``HYDRAGNN_TELEMETRY=0``
  or ``config["Telemetry"]["enable"] = false`` disables.
- HTTP endpoint: opt-in — ``HYDRAGNN_OBS_PORT=<port>`` (0 = ephemeral)
  or ``config["Telemetry"]["port"]``.
"""

import hashlib
import json
import os
import time
from typing import Dict, Optional

from hydragnn_tpu.obs.events import SCHEMA_VERSION, RunEventLog
from hydragnn_tpu.obs.metrics import (
    DEFAULT_LATENCY_BOUNDS,
    EPOCH_LATENCY_BOUNDS,
    MetricsRegistry,
)

_active: Optional["RunTelemetry"] = None


class TrainingMetrics:
    """The training run's live series — everything ``/metrics`` reports.

    Built on the shared :class:`MetricsRegistry` core; serving's
    ``ServeMetrics`` is the other client of the same machinery."""

    def __init__(self):
        r = MetricsRegistry("hydragnn_train")
        r.counter("epochs_total", "Completed epochs")
        r.counter("steps_total", "Dispatched optimizer steps")
        r.counter("guard_skips_total", "Non-finite steps/epochs skipped")
        r.counter("guard_restores_total", "Last-good restores (halved LR)")
        r.counter("checkpoints_saved_total", "Checkpoint files written")
        r.counter("compiles_total", "XLA compilations observed")
        r.gauge("epoch", "Current epoch index")
        r.gauge("train_loss", "Last epoch training loss")
        r.gauge("val_loss", "Last epoch validation loss")
        r.gauge("test_loss", "Last epoch test loss")
        r.gauge("graphs_per_second", "Last epoch training throughput")
        r.gauge("nodes_per_second", "Last epoch real-node-row throughput")
        r.gauge(
            "padding_waste_ratio",
            "Padded node rows carrying no real node (training batches)",
        )
        r.gauge(
            "heartbeat_age_seconds",
            "Seconds since the training loop last reported progress",
        )
        r.histogram(
            "epoch_seconds", "Epoch wall time", bounds=EPOCH_LATENCY_BOUNDS
        )
        r.histogram(
            "step_dispatch_seconds",
            "Host-side train-step dispatch latency",
            bounds=DEFAULT_LATENCY_BOUNDS,
        )
        self.registry = r
        self.last_beat = time.time()

    def beat(self):
        self.last_beat = time.time()

    def on_step(self, seconds: float, count: int = 1):
        self.registry.inc("steps_total", count)
        self.registry.observe("step_dispatch_seconds", seconds)
        # steps ARE progress: without this, heartbeat_age grows for the
        # whole of a long epoch and stall alerts fire on healthy runs
        self.last_beat = time.time()

    def on_epoch(
        self,
        epoch: int,
        train_loss: float,
        val_loss: float,
        test_loss: float,
        seconds: Optional[float] = None,
        graphs_per_sec: Optional[float] = None,
        nodes_per_sec: Optional[float] = None,
        padding_waste: Optional[float] = None,
    ):
        r = self.registry
        r.inc("epochs_total")
        r.set("epoch", float(epoch))
        r.set("train_loss", float(train_loss))
        r.set("val_loss", float(val_loss))
        r.set("test_loss", float(test_loss))
        if seconds is not None:
            r.observe("epoch_seconds", seconds)
        if graphs_per_sec is not None:
            r.set("graphs_per_second", float(graphs_per_sec))
        if nodes_per_sec is not None:
            r.set("nodes_per_second", float(nodes_per_sec))
        if padding_waste is not None:
            r.set("padding_waste_ratio", float(padding_waste))
        self.beat()

    def render_prometheus(self) -> str:
        self.registry.set(
            "heartbeat_age_seconds", max(time.time() - self.last_beat, 0.0)
        )
        return self.registry.render_prometheus()

    def snapshot(self) -> Dict:
        return self.registry.snapshot()


_compile_listener_registered = False
# process-global backend-compile count: always bumped once the listener is
# installed, whether or not a telemetry run is active. The recompile
# sentinel (analysis/guards.py) diffs it around a warmed-up region.
_compile_events = 0


def _register_compile_listener():
    """Count XLA compilations via jax's monitoring events when the API is
    available (it is internal-ish; absence just leaves the counter at 0).
    ONE process-global listener routing to whatever telemetry is active —
    jax has no unregister API, so a per-run listener would leak a closure
    (and retain its metrics) for every run in a long-lived process."""
    global _compile_listener_registered
    if _compile_listener_registered:
        return
    try:
        from jax import monitoring

        def _on_duration(event: str, duration: float = 0.0, **kwargs):
            # '/jax/core/compile/backend_compile_duration' fires once per
            # actual XLA compilation (cache hits don't reach the backend)
            global _compile_events
            if "backend_compile" in event:
                _compile_events += 1
                t = _active
                if t is not None:
                    t.metrics.registry.inc("compiles_total")

        if hasattr(monitoring, "register_event_duration_secs_listener"):
            monitoring.register_event_duration_secs_listener(_on_duration)
            _compile_listener_registered = True
    except Exception:
        pass


def install_compile_listener() -> bool:
    """Public idempotent installer (the sentinel's entry point). Returns
    whether the listener is live — False means the monitoring API is
    unavailable and :func:`compile_events` will stay at 0."""
    _register_compile_listener()
    return _compile_listener_registered


def compile_events() -> int:
    """Backend compilations observed since the listener was installed."""
    return _compile_events


def _config_hash(config: dict) -> str:
    try:
        blob = json.dumps(config, sort_keys=True, default=str)
    except (TypeError, ValueError):
        blob = repr(config)
    return hashlib.sha256(blob.encode()).hexdigest()[:12]


def _git_rev() -> str:
    try:
        import subprocess

        out = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"],
            capture_output=True,
            text=True,
            timeout=5,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
        return out.stdout.strip() or "unknown"
    except Exception:
        return "unknown"


class RunTelemetry:
    """Everything observable about one training run, under one lifetime.

    Satisfies the :class:`~hydragnn_tpu.obs.http.ObservabilityServer`
    provider protocol (``health()`` + ``.metrics.render_prometheus()``),
    so the serving listener exposes a live training job unchanged."""

    def __init__(
        self,
        run_name: str,
        log_dir: str,
        port: Optional[int] = None,
        events: bool = True,
    ):
        self.run_name = run_name
        self.log_dir = log_dir
        self.metrics = TrainingMetrics()
        self.events: Optional[RunEventLog] = (
            RunEventLog(os.path.join(log_dir, "events.jsonl"))
            if events
            else None
        )
        self.server = None
        self._closed = False
        _register_compile_listener()
        if port is not None:
            from hydragnn_tpu.obs.http import ObservabilityServer

            self.server = ObservabilityServer(self, port=port).start()

    # ---- provider protocol ---------------------------------------------
    def health(self) -> Dict:
        s = self.metrics.snapshot()
        return {
            "status": "ok" if not self._closed else "stopped",
            "run": self.run_name,
            "epoch": int(s["epoch"]),
            "epochs_total": s["epochs_total"],
            "heartbeat_age_s": round(
                max(time.time() - self.metrics.last_beat, 0.0), 3
            ),
        }

    @property
    def address(self):
        return None if self.server is None else self.server.address

    # ---- lifecycle -----------------------------------------------------
    def emit(self, event: str, **fields):
        if self.events is not None:
            self.events.emit(event, **fields)

    def emit_manifest(self, config: dict, run_name: str):
        import jax

        devices = jax.devices()
        self.emit(
            "run_manifest",
            schema_version=SCHEMA_VERSION,
            run=run_name,
            config_hash=_config_hash(config),
            git_rev=_git_rev(),
            world_size=jax.process_count(),
            device_kind=devices[0].platform if devices else "none",
            device_count=len(devices),
            num_epoch=int(
                config.get("NeuralNetwork", {})
                .get("Training", {})
                .get("num_epoch", 0)
            ),
        )

    def close(self, status: str = "complete"):
        if self._closed:
            return
        self._closed = True
        self.emit("run_end", status=status)
        if self.events is not None:
            self.events.close()
        if self.server is not None:
            self.server.stop()
            self.server = None


# ---- module-level hooks (no-op fast path when no run is active) ----------


def active() -> Optional[RunTelemetry]:
    return _active


def activate(telemetry: RunTelemetry):
    global _active
    prev = _active
    _active = telemetry
    if prev is not None and prev is not telemetry:
        # a run that never deactivated (crashed between init and its
        # cleanup) must not leak its event-stream handle into this one
        prev.close(status="abandoned")
    return telemetry


def deactivate(status: str = "complete"):
    global _active
    t = _active
    _active = None
    if t is not None:
        t.close(status)


def emit(event: str, **fields):
    t = _active
    if t is not None:
        t.emit(event, **fields)


def epoch_complete(
    epoch: int,
    train_loss,
    val_loss,
    test_loss,
    seconds=None,
    graphs_per_sec=None,
    nodes_per_sec=None,
    padding_waste=None,
    mode: str = "stream",
):
    t = _active
    if t is None:
        return
    t.metrics.on_epoch(
        int(epoch),
        float(train_loss),
        float(val_loss),
        float(test_loss),
        seconds=seconds,
        graphs_per_sec=graphs_per_sec,
        nodes_per_sec=nodes_per_sec,
        padding_waste=padding_waste,
    )
    t.emit(
        "epoch",
        epoch=int(epoch),
        train_loss=float(train_loss),
        val_loss=float(val_loss),
        test_loss=float(test_loss),
        mode=mode,
        **(
            {}
            if seconds is None
            else {
                "wall_time_s": round(float(seconds), 6),
                "graphs_per_sec": (
                    None
                    if graphs_per_sec is None
                    else round(float(graphs_per_sec), 3)
                ),
                "nodes_per_sec": (
                    None
                    if nodes_per_sec is None
                    else round(float(nodes_per_sec), 3)
                ),
            }
        ),
        **(
            {}
            if padding_waste is None
            else {"padding_waste": round(float(padding_waste), 6)}
        ),
    )


def guard_skip(scope: str, skipped: int, streak: int = 0):
    t = _active
    if t is None:
        return
    t.metrics.registry.inc("guard_skips_total")
    t.emit("guard_skip", scope=scope, skipped=int(skipped),
           streak=int(streak))


def guard_restore(restores: int, lr: float):
    t = _active
    if t is None:
        return
    t.metrics.registry.inc("guard_restores_total")
    t.emit("guard_restore", restores=int(restores), lr=float(lr))


def checkpoint_saved(name: str, kind: str, **fields):
    t = _active
    if t is None:
        return
    t.metrics.registry.inc("checkpoints_saved_total")
    t.emit("checkpoint_saved", name=name, kind=kind, **fields)


def checkpoint_restored(name: str, source: str):
    t = _active
    if t is None:
        return
    t.emit("checkpoint_restored", name=name, source=source)


# ---- run construction ----------------------------------------------------


def init_run_telemetry(
    config: dict, log_name: str, path: str = "./logs/"
) -> Optional[RunTelemetry]:
    """Build + activate telemetry for a driver run, honoring the env/config
    knobs (module docstring). Returns None (hooks stay no-ops) on
    non-zero ranks or when disabled."""
    from hydragnn_tpu.parallel.distributed import get_comm_size_and_rank

    _, rank = get_comm_size_and_rank()
    if rank != 0:
        return None
    tcfg = config.get("Telemetry", {}) or {}
    env = os.getenv("HYDRAGNN_TELEMETRY")
    enabled = (
        env.strip().lower() not in ("", "0", "false", "no", "off")
        if env is not None
        else bool(tcfg.get("enable", True))
    )
    if not enabled:
        return None
    port_env = os.getenv("HYDRAGNN_OBS_PORT")
    port: Optional[int]
    if port_env is not None and port_env.strip() != "":
        port = int(port_env)
    elif tcfg.get("port") is not None:
        port = int(tcfg["port"])
    else:
        port = None
    telemetry = RunTelemetry(
        log_name, os.path.join(path, log_name), port=port
    )
    telemetry.emit_manifest(config, log_name)
    return activate(telemetry)
