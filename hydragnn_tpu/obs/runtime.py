"""Run-scoped telemetry: the glue between training code and obs primitives.

One :class:`RunTelemetry` per training run bundles the three tentpole
pieces — the structured event stream (``events.jsonl``), the live
``/metrics``+``/healthz`` endpoint, and the training metrics registry —
behind module-level hook functions (:func:`emit`, :func:`epoch_complete`,
:func:`guard_skip`, ...) that the epoch driver, trainer, divergence
guard, and checkpoint layer call unconditionally. (The trainer's per-step
path resolves :func:`active` once per epoch and calls
``metrics.on_step`` directly — one global read per epoch, not per step.)

The hooks follow the fault-injection harness pattern
(``utils/faults.py``): with no active telemetry each call is ONE global
read and a return, so instrumented code costs nothing when observability
is off — the acceptance bar is "telemetry-disabled epoch-loop wall time
within noise of baseline", enforced by ``tests/test_observability.py``.

Enablement (rank 0 only; other ranks keep the no-op hooks):

- events + metrics: on by default for driver runs; ``HYDRAGNN_TELEMETRY=0``
  or ``config["Telemetry"]["enable"] = false`` disables.
- HTTP endpoint: opt-in — ``HYDRAGNN_OBS_PORT=<port>`` (0 = ephemeral)
  or ``config["Telemetry"]["port"]``.
"""

import hashlib
import json
import os
import time
from typing import Dict, List, Optional

from hydragnn_tpu.obs.events import SCHEMA_VERSION, RunEventLog
from hydragnn_tpu.obs.metrics import (
    DEFAULT_LATENCY_BOUNDS,
    EPOCH_LATENCY_BOUNDS,
    MetricsRegistry,
)

_active: Optional["RunTelemetry"] = None


class FlightRecorder:
    """Ring buffer of the last K step-dispatch times + stall detection.

    A step counts as a STALL when its dispatch time strictly exceeds
    ``stall_factor`` x the rolling median of the buffered window (median,
    not mean — one earlier stall must not drag the threshold up). No
    stall can fire until ``min_fill`` steps are buffered, so warmup and
    first-epoch compile steps never alert; the caller additionally skips
    recording steps that contained an XLA compile (their wall time IS
    compile time). Not thread-safe by design — one training thread owns
    it; ``snapshot()`` from other threads reads a consistent-enough copy
    for diagnostics.
    """

    def __init__(self, capacity: int = 64, stall_factor: float = 8.0,
                 min_fill: int = 8):
        if capacity < 1:
            raise ValueError("capacity must be >= 1")
        self.capacity = int(capacity)
        self.stall_factor = float(stall_factor)
        # clamped into [1, capacity]: a window smaller than min_fill
        # could otherwise never satisfy the fill gate, silently disabling
        # detection for the operator who SHRANK it to react faster
        self.min_fill = max(min(int(min_fill), self.capacity), 1)
        self._buf: List[float] = [0.0] * self.capacity
        self._count = 0  # total steps ever recorded

    def record(self, seconds: float) -> Optional[Dict]:
        """Add one step time; returns the stall payload (step/seconds/
        median/factor) when the step stalled, else None. The check runs
        against the window BEFORE this step enters it — a stalled step is
        judged by its predecessors, then buffered so a genuine regime
        change re-baselines the median within a window."""
        stall = None
        filled = min(self._count, self.capacity)
        if filled >= self.min_fill:
            window = sorted(self._buf[:filled] if self._count < self.capacity
                            else self._buf)
            mid = filled // 2
            median = (
                window[mid]
                if filled % 2
                else 0.5 * (window[mid - 1] + window[mid])
            )
            if seconds > self.stall_factor * median:
                stall = {
                    "step": self._count,
                    "seconds": seconds,
                    "median": median,
                    "factor": self.stall_factor,
                }
        self._buf[self._count % self.capacity] = float(seconds)
        self._count += 1
        return stall

    @property
    def count(self) -> int:
        return self._count

    def snapshot(self) -> List[float]:
        """Buffered step times, oldest first."""
        if self._count < self.capacity:
            return self._buf[: self._count]
        i = self._count % self.capacity
        return self._buf[i:] + self._buf[:i]


class TrainingMetrics:
    """The training run's live series — everything ``/metrics`` reports.

    Built on the shared :class:`MetricsRegistry` core; serving's
    ``ServeMetrics`` is the other client of the same machinery."""

    def __init__(self):
        # scrape-time poll hooks (device memory is built in; the elastic
        # fleet poll registers here) — run on every render, never in the
        # step loop
        self.extra_polls = []
        r = MetricsRegistry("hydragnn_train")
        r.counter("epochs_total", "Completed epochs")
        r.counter("steps_total", "Dispatched optimizer steps")
        r.counter("guard_skips_total", "Non-finite steps/epochs skipped")
        r.counter("guard_restores_total", "Last-good restores (halved LR)")
        r.counter("checkpoints_saved_total", "Checkpoint files written")
        r.counter("compiles_total", "XLA compilations observed")
        r.gauge("epoch", "Current epoch index")
        r.gauge("train_loss", "Last epoch training loss")
        r.gauge("val_loss", "Last epoch validation loss")
        r.gauge("test_loss", "Last epoch test loss")
        r.gauge("graphs_per_second", "Last epoch training throughput")
        r.gauge("nodes_per_second", "Last epoch real-node-row throughput")
        r.gauge(
            "padding_waste_ratio",
            "Padded node rows carrying no real node (training batches)",
        )
        r.gauge(
            "heartbeat_age_seconds",
            "Seconds since the training loop last reported progress",
        )
        r.counter("stalls_total", "Steps exceeding the stall threshold")
        # elastic training (train/elastic.py): current world size and the
        # last re-mesh's detection->first-step recovery time
        r.gauge("world_size", "Processes in the current training world")
        r.gauge(
            "last_recovery_seconds",
            "Detection-to-first-step time of the last world resize",
        )
        # compiled-program accounting (obs/introspect.py): one label set
        # per (program, shape-signature) bucket
        r.labeled_gauge(
            "flops_per_step", "Compiled-program FLOPs (XLA cost model)"
        )
        r.labeled_gauge(
            "hbm_peak_bytes",
            "Compiled-program peak memory (arg+out+temp-aliased)",
        )
        # aggregation autotuner (ops/autotune.py): 1 on the (bucket,
        # choice) label set each bucket actually uses
        r.labeled_gauge(
            "aggregation_kernel",
            "Chosen aggregation kernel family per bucket (1 = active)",
        )
        # 2-D mesh collective accounting (parallel/collectives.py):
        # per-dispatch collective result bytes attributed to each mesh
        # axis, summed over every captured compiled program — a reshard
        # regression (all-gather storm) moves this before it moves wall
        r.labeled_gauge(
            "collective_bytes",
            "Compiled-program collective result bytes per mesh axis",
        )
        # streaming data plane (data/stream/): per-epoch pipeline health
        # — queue depth at last consumer get, seconds the step loop spent
        # blocked on the data plane, ingestion bandwidth, and the
        # shard-window residency high-waters the RAM bound rests on
        r.gauge("stream_queue_depth", "Collated batches ready ahead of the consumer")
        r.gauge(
            "stream_stall_seconds",
            "Seconds the consumer waited on the stream pipeline last epoch",
        )
        r.gauge("stream_bytes_per_second", "Streamed sample bytes/sec last epoch")
        r.gauge(
            "stream_open_shards_peak",
            "Most shards any source held resident at once",
        )
        r.gauge(
            "stream_resident_bytes_peak",
            "Peak host bytes pinned by stream window buffers",
        )
        r.counter("stream_samples_total", "Samples drawn from the stream mix")
        r.counter(
            "stream_oversize_dropped_total",
            "Samples dropped because no bucket of the plan could hold them",
        )
        r.labeled_gauge(
            "stream_source_fraction",
            "Fraction of last epoch's draws per mix source",
        )
        # goodput & MFU ledger (obs/ledger.py): per-category wall-time
        # fractions of the last closed epoch window (sum to 1), and
        # per-bucket model FLOPs utilization against the device's peak
        r.labeled_gauge(
            "goodput_fraction",
            "Last epoch's wall-time fraction per goodput category",
        )
        r.labeled_gauge(
            "mfu",
            "Model FLOPs utilization per train bucket (vs device peak)",
        )
        # fleet view (elastic runs; the leader polls peer heartbeat
        # digests at scrape time — obs/ledger.py poll_fleet_gauges)
        r.labeled_gauge(
            "fleet_step_p50_seconds",
            "Per-host step-time p50 from elastic heartbeat digests",
        )
        r.gauge(
            "fleet_straggler_hosts",
            "Hosts whose step p50 exceeds the fleet median threshold",
        )
        # live device memory, polled from device 0's memory_stats() at
        # scrape time (stays 0 on backends that report none, e.g. CPU)
        r.gauge("device_bytes_in_use", "Live device memory in use")
        r.gauge(
            "device_peak_bytes_in_use", "Peak device memory since start"
        )
        r.histogram(
            "epoch_seconds", "Epoch wall time", bounds=EPOCH_LATENCY_BOUNDS
        )
        r.histogram(
            "step_dispatch_seconds",
            "Host-side train-step dispatch latency",
            bounds=DEFAULT_LATENCY_BOUNDS,
        )
        self.registry = r
        self.last_beat = time.time()

    def beat(self):
        self.last_beat = time.time()

    def on_step(self, seconds: float, count: int = 1):
        self.registry.inc("steps_total", count)
        self.registry.observe("step_dispatch_seconds", seconds)
        # steps ARE progress: without this, heartbeat_age grows for the
        # whole of a long epoch and stall alerts fire on healthy runs
        self.last_beat = time.time()

    def on_epoch(
        self,
        epoch: int,
        train_loss: float,
        val_loss: float,
        test_loss: float,
        seconds: Optional[float] = None,
        graphs_per_sec: Optional[float] = None,
        nodes_per_sec: Optional[float] = None,
        padding_waste: Optional[float] = None,
    ):
        r = self.registry
        r.inc("epochs_total")
        r.set("epoch", float(epoch))
        r.set("train_loss", float(train_loss))
        r.set("val_loss", float(val_loss))
        r.set("test_loss", float(test_loss))
        if seconds is not None:
            r.observe("epoch_seconds", seconds)
        if graphs_per_sec is not None:
            r.set("graphs_per_second", float(graphs_per_sec))
        if nodes_per_sec is not None:
            r.set("nodes_per_second", float(nodes_per_sec))
        if padding_waste is not None:
            r.set("padding_waste_ratio", float(padding_waste))
        self.beat()

    def poll_device_memory(self):
        """Refresh the live-memory gauges from device 0 (the heartbeat's
        companion poll — runs at scrape time, never in the step loop)."""
        try:
            import jax

            stats = jax.local_devices()[0].memory_stats()
        except Exception:
            return
        if not stats:
            return
        self.registry.set(
            "device_bytes_in_use", float(stats.get("bytes_in_use", 0))
        )
        self.registry.set(
            "device_peak_bytes_in_use",
            float(stats.get("peak_bytes_in_use", 0)),
        )

    def render_prometheus(self) -> str:
        self.registry.set(
            "heartbeat_age_seconds", max(time.time() - self.last_beat, 0.0)
        )
        self.poll_device_memory()
        for poll in self.extra_polls:
            try:
                poll()
            except Exception:
                pass  # a poll hook must never break /metrics
        return self.registry.render_prometheus()

    def snapshot(self) -> Dict:
        return self.registry.snapshot()


_compile_listener_registered = False
# process-global backend-compile count: always bumped once the listener is
# installed, whether or not a telemetry run is active. The recompile
# sentinel (analysis/guards.py) diffs it around a warmed-up region.
_compile_events = 0
# ... and the matching duration integral: total backend-compile seconds,
# the goodput ledger's `compile` category signal (obs/ledger.py)
_compile_seconds = 0.0


def _register_compile_listener():
    """Count XLA compilations via jax's monitoring events when the API is
    available (it is internal-ish; absence just leaves the counter at 0).
    ONE process-global listener routing to whatever telemetry is active —
    jax has no unregister API, so a per-run listener would leak a closure
    (and retain its metrics) for every run in a long-lived process."""
    global _compile_listener_registered
    if _compile_listener_registered:
        return
    try:
        from jax import monitoring

        def _on_duration(event: str, duration: float = 0.0, **kwargs):
            # '/jax/core/compile/backend_compile_duration' fires once per
            # actual XLA compilation (cache hits don't reach the backend)
            global _compile_events, _compile_seconds
            if "backend_compile" in event:
                _compile_events += 1
                try:
                    _compile_seconds += float(duration)
                except (TypeError, ValueError):
                    pass
                t = _active
                if t is not None:
                    t.metrics.registry.inc("compiles_total")

        if hasattr(monitoring, "register_event_duration_secs_listener"):
            monitoring.register_event_duration_secs_listener(_on_duration)
            _compile_listener_registered = True
    except Exception:
        pass


def install_compile_listener() -> bool:
    """Public idempotent installer (the sentinel's entry point). Returns
    whether the listener is live — False means the monitoring API is
    unavailable and :func:`compile_events` will stay at 0."""
    _register_compile_listener()
    return _compile_listener_registered


def compile_events() -> int:
    """Backend compilations observed since the listener was installed."""
    return _compile_events


def compile_seconds() -> float:
    """Cumulative backend-compile wall seconds (0.0 when the monitoring
    API is unavailable — the ledger's compile category then reads 0)."""
    return _compile_seconds


def _config_hash(config: dict) -> str:
    try:
        blob = json.dumps(config, sort_keys=True, default=str)
    except (TypeError, ValueError):
        blob = repr(config)
    return hashlib.sha256(blob.encode()).hexdigest()[:12]


def _git_rev() -> str:
    try:
        import subprocess

        out = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"],
            capture_output=True,
            text=True,
            timeout=5,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
        return out.stdout.strip() or "unknown"
    except Exception:
        return "unknown"


class RunTelemetry:
    """Everything observable about one training run, under one lifetime.

    Satisfies the :class:`~hydragnn_tpu.obs.http.ObservabilityServer`
    provider protocol (``health()`` + ``.metrics.render_prometheus()``),
    so the serving listener exposes a live training job unchanged."""

    def __init__(
        self,
        run_name: str,
        log_dir: str,
        port: Optional[int] = None,
        events: bool = True,
        events_file: str = "events.jsonl",
    ):
        from hydragnn_tpu.obs.introspect import (
            TraceCapture,
            parse_profile_at_step,
        )
        from hydragnn_tpu.obs.ledger import GoodputLedger, poll_fleet_gauges

        self.run_name = run_name
        self.log_dir = log_dir
        self.metrics = TrainingMetrics()
        self.events: Optional[RunEventLog] = (
            RunEventLog(os.path.join(log_dir, events_file))
            if events
            else None
        )
        self.server = None
        self._closed = False
        # step-time flight recorder + on-demand trace capture — both
        # driven from on_step() on the training thread
        self.flight = FlightRecorder(
            capacity=int(os.getenv("HYDRAGNN_FLIGHT_STEPS", "64")),
            stall_factor=float(os.getenv("HYDRAGNN_STALL_FACTOR", "8.0")),
        )
        self.trace = TraceCapture(os.path.join(log_dir, "profile"))
        self._profile_at = parse_profile_at_step(
            os.getenv("HYDRAGNN_PROFILE_AT_STEP")
        )
        self._profile_steps = int(os.getenv("HYDRAGNN_PROFILE_STEPS", "3"))
        self.current_epoch = 0
        self._step_in_epoch = 0
        # per-axis collective-bytes running totals (record_compile)
        self._collective_totals: Dict[str, float] = {}
        self._compile_events_at_step = _compile_events
        self._compile_seconds_at_step = _compile_seconds
        # goodput & MFU ledger: per-epoch wall-time attribution + the
        # hydragnn_train_mfu{bucket=} gauges (obs/ledger.py)
        self.ledger = GoodputLedger(
            registry=self.metrics.registry,
            emit=self.emit,
            compile_seconds=compile_seconds,
        )
        # elastic runs: the leader's /metrics scrape also polls the peer
        # heartbeat digests into the fleet gauges
        coord_dir = os.getenv("HYDRAGNN_ELASTIC_DIR")
        if coord_dir:
            self.metrics.extra_polls.append(
                lambda: poll_fleet_gauges(
                    coord_dir, self.metrics.registry
                )
            )
        _register_compile_listener()
        if port is not None:
            from hydragnn_tpu.obs.http import ObservabilityServer

            self.server = ObservabilityServer(self, port=port).start()

    # ---- provider protocol ---------------------------------------------
    def health(self) -> Dict:
        s = self.metrics.snapshot()
        return {
            "status": "ok" if not self._closed else "stopped",
            "run": self.run_name,
            "epoch": int(s["epoch"]),
            "epochs_total": s["epochs_total"],
            "heartbeat_age_s": round(
                max(time.time() - self.metrics.last_beat, 0.0), 3
            ),
        }

    @property
    def address(self):
        return None if self.server is None else self.server.address

    # ---- per-step instrumentation --------------------------------------
    def on_step(self, seconds: float, count: int = 1):
        """One training-step dispatch completed: metrics, flight
        recorder / stall detection, trace-capture tick, env-armed
        profiling. Called from the training thread only."""
        self.metrics.on_step(seconds, count)
        # a step whose dispatch included an XLA compile is compile time,
        # not a stall — keep it out of the ring so it neither alerts nor
        # skews the rolling median (warmup is additionally covered by the
        # recorder's min_fill). Without compile visibility (no
        # jax.monitoring listener on this jax version) stalls are
        # recorded but never ALERTED: a guaranteed false alarm on every
        # mid-run novel-bucket compile is worse than no alarm.
        compiled_now = _compile_events != self._compile_events_at_step
        self._compile_events_at_step = _compile_events
        compile_delta = _compile_seconds - self._compile_seconds_at_step
        self._compile_seconds_at_step = _compile_seconds
        # goodput attribution + the elastic heartbeat's step-time digest
        # (the digest skips compile-heavy steps the same way the flight
        # recorder does — a 3-step host must not read as a straggler
        # because its first step compiled)
        self.ledger.on_step(
            seconds, count, compile_delta if compiled_now else 0.0
        )
        from hydragnn_tpu.train import elastic as _elastic

        _elastic.note_step_time(seconds, count, compiled=compiled_now)
        if not compiled_now:
            # per-step time: K-step scan dispatches must compare against
            # single-step dispatches on the same scale, or bucketed runs
            # mixing the two alert on every full group
            stall = self.flight.record(seconds / max(int(count), 1))
            if stall is not None and _compile_listener_registered:
                self.metrics.registry.inc("stalls_total")
                self.emit(
                    "stall",
                    step=int(stall["step"]),
                    seconds=round(float(stall["seconds"]), 6),
                    median=round(float(stall["median"]), 6),
                    factor=float(stall["factor"]),
                    epoch=int(self.current_epoch),
                )
        self._step_in_epoch += count
        if (
            self._profile_at is not None
            and self.current_epoch == self._profile_at[0]
            and self._step_in_epoch >= self._profile_at[1]
        ):
            self._profile_at = None
            self.profile(self._profile_steps)
        transition = self.trace.tick()
        if transition is not None:
            self.emit("profile", **transition)

    def on_epoch_start(self, epoch: int):
        self.current_epoch = int(epoch)
        self._step_in_epoch = 0
        # closes (and publishes) the previous goodput window — post-epoch
        # work like the resumable checkpoint save lands in ITS epoch
        self.ledger.epoch_begin(epoch)

    def on_dispatch_boundary(self):
        """Fit-path granularity: whole-training chunks dispatch as ONE
        XLA program with no per-step hook, so trace capture ticks (and
        HYDRAGNN_PROFILE_AT_STEP arming, resolved against the chunk's
        starting epoch — the step part is unsatisfiable here) advance at
        chunk boundaries instead. A ``/profile`` "step" on this path is
        one chunk; without this hook an arm request would wedge the
        endpoint in 'busy' forever."""
        if (
            self._profile_at is not None
            and self.current_epoch >= self._profile_at[0]
        ):
            self._profile_at = None
            self.profile(self._profile_steps)
        transition = self.trace.tick()
        if transition is not None:
            self.emit("profile", **transition)

    def record_compile(self, rec: Dict):
        """One novel (program, shape signature) was compiled: event +
        per-bucket cost/memory gauges (obs/introspect.py calls this)."""
        cost = rec.get("cost") or {}
        mem = rec.get("memory") or {}
        coll = rec.get("collectives") or {}
        bucket = rec["bucket"]
        self.ledger.note_program(rec)  # train-bucket FLOPs feed the MFU
        if cost.get("flops"):
            self.metrics.registry.set_labeled(
                "flops_per_step", float(cost["flops"]), bucket=bucket
            )
        if mem.get("peak_bytes"):
            self.metrics.registry.set_labeled(
                "hbm_peak_bytes", float(mem["peak_bytes"]), bucket=bucket
            )
        for axis, nbytes in coll.items():
            # cumulative across captured programs: the run's collective
            # footprint per axis, not the last bucket's
            self._collective_totals[axis] = (
                self._collective_totals.get(axis, 0.0) + float(nbytes)
            )
            self.metrics.registry.set_labeled(
                "collective_bytes",
                self._collective_totals[axis],
                axis=axis,
            )
        self.emit(
            "compile", name=rec["name"], bucket=bucket, cost=cost,
            memory=mem, **({"collectives": coll} if coll else {}),
        )

    def profile(self, steps: int) -> Dict:
        """Arm device-trace capture for the next ``steps`` steps — the
        ``/profile?steps=N`` provider hook (any thread)."""
        result = self.trace.arm(steps)
        if result.get("status") == "armed":
            self.emit("profile", **result)
        return result

    # ---- lifecycle -----------------------------------------------------
    def emit(self, event: str, **fields):
        if self.events is not None:
            self.events.emit(event, **fields)

    def emit_manifest(self, config: dict, run_name: str):
        import jax

        devices = jax.devices()
        self.metrics.registry.set("world_size", float(jax.process_count()))
        host = os.getenv("HYDRAGNN_ELASTIC_HOST")
        self.emit(
            "run_manifest",
            schema_version=SCHEMA_VERSION,
            run=run_name,
            config_hash=_config_hash(config),
            git_rev=_git_rev(),
            world_size=jax.process_count(),
            device_kind=devices[0].platform if devices else "none",
            device_count=len(devices),
            num_epoch=int(
                config.get("NeuralNetwork", {})
                .get("Training", {})
                .get("num_epoch", 0)
            ),
            # elastic runs: which HOST wrote this stream segment — the
            # fleet rollup attributes rank 0's shared events.jsonl to
            # hosts by walking these manifests across generations
            **({} if host is None else {"host": int(host)}),
        )

    def close(self, status: str = "complete"):
        if self._closed:
            return
        self._closed = True
        # the last epoch's goodput window closes with the run
        try:
            self.ledger.finalize()
        except Exception:
            pass
        # a run dying mid-capture must still flush a loadable trace
        flushed = self.trace.close()
        if flushed is not None:
            self.emit("profile", **flushed)
        self.emit("run_end", status=status)
        if self.events is not None:
            self.events.close()
        if self.server is not None:
            self.server.stop()
            self.server = None


# ---- module-level hooks (no-op fast path when no run is active) ----------


def active() -> Optional[RunTelemetry]:
    return _active


def activate(telemetry: RunTelemetry):
    global _active
    prev = _active
    _active = telemetry
    if prev is not None and prev is not telemetry:
        # a run that never deactivated (crashed between init and its
        # cleanup) must not leak its event-stream handle into this one
        prev.close(status="abandoned")
    return telemetry


def deactivate(status: str = "complete"):
    global _active
    t = _active
    _active = None
    if t is not None:
        t.close(status)


def emit(event: str, **fields):
    t = _active
    if t is not None:
        t.emit(event, **fields)


def epoch_start(epoch: int):
    """The epoch driver announces each epoch (resets the per-epoch step
    counter behind HYDRAGNN_PROFILE_AT_STEP's <epoch>:<step> target)."""
    t = _active
    if t is not None:
        t.on_epoch_start(epoch)


def dispatch_boundary():
    """The fit path announces each whole-chunk dispatch completing (see
    :meth:`RunTelemetry.on_dispatch_boundary`)."""
    t = _active
    if t is not None:
        t.on_dispatch_boundary()


def epoch_complete(
    epoch: int,
    train_loss,
    val_loss,
    test_loss,
    seconds=None,
    graphs_per_sec=None,
    nodes_per_sec=None,
    padding_waste=None,
    mode: str = "stream",
):
    t = _active
    if t is None:
        return
    t.metrics.on_epoch(
        int(epoch),
        float(train_loss),
        float(val_loss),
        float(test_loss),
        seconds=seconds,
        graphs_per_sec=graphs_per_sec,
        nodes_per_sec=nodes_per_sec,
        padding_waste=padding_waste,
    )
    if seconds is not None:
        # whole-dispatch epochs (staged / fit chunks) have no per-step
        # hook; the driver's measured train wall is their compute signal
        t.ledger.note_train_wall(seconds)
    t.emit(
        "epoch",
        epoch=int(epoch),
        train_loss=float(train_loss),
        val_loss=float(val_loss),
        test_loss=float(test_loss),
        mode=mode,
        **(
            {}
            if seconds is None
            else {
                "wall_time_s": round(float(seconds), 6),
                "graphs_per_sec": (
                    None
                    if graphs_per_sec is None
                    else round(float(graphs_per_sec), 3)
                ),
                "nodes_per_sec": (
                    None
                    if nodes_per_sec is None
                    else round(float(nodes_per_sec), 3)
                ),
            }
        ),
        **(
            {}
            if padding_waste is None
            else {"padding_waste": round(float(padding_waste), 6)}
        ),
    )


def guard_skip(scope: str, skipped: int, streak: int = 0):
    t = _active
    if t is None:
        return
    t.metrics.registry.inc("guard_skips_total")
    t.emit("guard_skip", scope=scope, skipped=int(skipped),
           streak=int(streak))


def guard_restore(restores: int, lr: float, seconds: float = 0.0):
    t = _active
    if t is None:
        return
    t.metrics.registry.inc("guard_restores_total")
    t.ledger.guard_cost(seconds)
    t.emit(
        "guard_restore", restores=int(restores), lr=float(lr),
        **({} if not seconds else {"seconds": round(float(seconds), 6)}),
    )


def checkpoint_saved(name: str, kind: str, **fields):
    t = _active
    if t is None:
        return
    t.metrics.registry.inc("checkpoints_saved_total")
    # goodput: a sync save costs the loop snapshot + serialize/write; an
    # async one only the device->host snapshot (the write overlaps)
    cost = float(fields.get("snapshot_s") or 0.0)
    if not fields.get("async"):
        cost += float(fields.get("write_s") or 0.0)
    t.ledger.checkpoint_cost(cost)
    t.emit("checkpoint_saved", name=name, kind=kind, **fields)


def checkpoint_restored(name: str, source: str):
    t = _active
    if t is None:
        return
    t.emit("checkpoint_restored", name=name, source=source)


def stream_epoch_stats(
    queue_depth: int = 0,
    stall_s: float = 0.0,
    bytes_per_sec: float = 0.0,
    open_shards_peak: int = 0,
    resident_bytes_peak: int = 0,
    samples: int = 0,
    oversize_dropped: int = 0,
    source_counts: Optional[Dict[str, int]] = None,
):
    """One epoch of the streaming data plane completed (data/stream/):
    refresh the ``stream_*`` gauge family. No event — the epoch event
    already carries the loss/throughput story; these are live-health
    series."""
    t = _active
    if t is None:
        return
    t.ledger.data_wait(stall_s)  # the goodput data_stall signal
    r = t.metrics.registry
    r.set("stream_queue_depth", float(queue_depth))
    r.set("stream_stall_seconds", float(stall_s))
    r.set("stream_bytes_per_second", float(bytes_per_sec))
    r.set("stream_open_shards_peak", float(open_shards_peak))
    r.set("stream_resident_bytes_peak", float(resident_bytes_peak))
    if samples:
        r.inc("stream_samples_total", int(samples))
    if oversize_dropped:
        r.inc("stream_oversize_dropped_total", int(oversize_dropped))
    if source_counts:
        total = max(sum(source_counts.values()), 1)
        for name, n in source_counts.items():
            r.set_labeled(
                "stream_source_fraction", n / total, source=name
            )


def world_resized(old_world: int, new_world: int, gen: int,
                  recovery_s: float, **fields):
    """Elastic re-mesh completed (train/elastic.py): event + gauges. The
    recovery time spans loss DETECTION to the first optimizer step at the
    new world size — everything an operator would otherwise do by hand."""
    t = _active
    if t is None:
        return
    t.metrics.registry.set("world_size", float(new_world))
    t.metrics.registry.set("last_recovery_seconds", float(recovery_s))
    t.emit(
        "world_resize",
        old_world=int(old_world),
        new_world=int(new_world),
        gen=int(gen),
        recovery_s=float(recovery_s),
        **fields,
    )


def eval_start():
    """The epoch driver is entering its val/test evaluation — opens a
    goodput eval span (compile time and data waits inside the span stay
    in their own categories)."""
    t = _active
    if t is not None:
        t.ledger.eval_begin()


def eval_complete():
    t = _active
    if t is not None:
        t.ledger.eval_end()


# ---- run construction ----------------------------------------------------


def init_run_telemetry(
    config: dict, log_name: str, path: str = "./logs/"
) -> Optional[RunTelemetry]:
    """Build + activate telemetry for a driver run, honoring the env/config
    knobs (module docstring). Returns None (hooks stay no-ops) on
    non-zero ranks — EXCEPT under elastic mode, where every host writes
    its own ``events-host<k>.jsonl`` next to rank 0's ``events.jsonl``
    (no HTTP endpoint, no shared-file contention) so the fleet rollup
    (``python -m hydragnn_tpu.obs fleet``) has a per-host record of
    stalls, goodput, and step times — a straggler is only visible from
    the host it lives on."""
    from hydragnn_tpu.parallel.distributed import get_comm_size_and_rank

    _, rank = get_comm_size_and_rank()
    tcfg = config.get("Telemetry", {}) or {}
    env = os.getenv("HYDRAGNN_TELEMETRY")
    enabled = (
        env.strip().lower() not in ("", "0", "false", "no", "off")
        if env is not None
        else bool(tcfg.get("enable", True))
    )
    if not enabled:
        return None
    if rank != 0:
        host = os.getenv("HYDRAGNN_ELASTIC_HOST")
        if not os.getenv("HYDRAGNN_ELASTIC_DIR") or host is None:
            return None
        telemetry = RunTelemetry(
            log_name,
            os.path.join(path, log_name),
            port=None,
            events_file=f"events-host{int(host)}.jsonl",
        )
        telemetry.emit_manifest(config, log_name)
        return activate(telemetry)
    port_env = os.getenv("HYDRAGNN_OBS_PORT")
    port: Optional[int]
    if port_env is not None and port_env.strip() != "":
        port = int(port_env)
    elif tcfg.get("port") is not None:
        port = int(tcfg["port"])
    else:
        port = None
    telemetry = RunTelemetry(
        log_name, os.path.join(path, log_name), port=port
    )
    telemetry.emit_manifest(config, log_name)
    return activate(telemetry)
