"""Compile-on-first-use build for the native (C++) runtime components.

No pip/pybind11 in the image, so bindings are ctypes over plain C ABIs and
the shared objects are built lazily with g++ into ``native/_build/``, keyed
by source mtime so edits trigger a rebuild.
"""

import os
import subprocess
import threading

_HERE = os.path.dirname(os.path.abspath(__file__))
_BUILD = os.path.join(_HERE, "_build")
_LOCK = threading.Lock()


def build_library(name: str, sources, extra_flags=()) -> str:
    """Build ``lib<name>.so`` from ``sources`` (paths relative to native/)
    if missing or stale; returns the .so path."""
    os.makedirs(_BUILD, exist_ok=True)
    out = os.path.join(_BUILD, f"lib{name}.so")
    srcs = [os.path.join(_HERE, s) for s in sources]
    with _LOCK:
        if os.path.exists(out) and all(
            os.path.getmtime(out) >= os.path.getmtime(s) for s in srcs
        ):
            return out
        # pid-unique tmp + atomic replace: concurrent trainer processes on
        # one host may race to build the same library on a cold cache
        tmp = f"{out}.{os.getpid()}.tmp"
        cmd = [
            "g++",
            "-O3",
            "-std=c++17",
            "-shared",
            "-fPIC",
            "-Wall",
            *extra_flags,
            *srcs,
            "-o",
            tmp,
            "-lpthread",
        ]
        # serializing the compile IS this lock's job: concurrent callers
        # must block until the one g++ build lands, not race it
        # threadlint: disable=blocking-under-lock
        proc = subprocess.run(cmd, capture_output=True, text=True)
        if proc.returncode != 0:
            raise RuntimeError(
                f"g++ failed building lib{name}.so:\n{proc.stderr}"
            )
        os.replace(tmp, out)
    return out


def load_library(name: str, sources, extra_flags=()):
    import ctypes

    return ctypes.CDLL(build_library(name, sources, extra_flags))
