"""ctypes binding for the GraphPack shard format (see graphpack.cpp).

Low-level API: ``PackWriter`` serializes {name: (array, counts)} variables to
one shard file; ``PackReader`` memory-maps it back with zero-copy per-sample
slices. The dataset-level API (multi-shard, GraphData in/out — the
AdiosWriter/AdiosDataset parity surface, ``hydragnn/utils/adiosdataset.py``)
lives in ``hydragnn_tpu/data/shard_store.py``.
"""

import ctypes
from typing import Dict, Optional, Tuple

import numpy as np

from hydragnn_tpu.native.build import load_library

_DTYPES = {
    np.dtype(np.float32): 0,
    np.dtype(np.float64): 1,
    np.dtype(np.int32): 2,
    np.dtype(np.int64): 3,
    np.dtype(np.uint8): 4,
}
_NP_DTYPES = {v: k for k, v in _DTYPES.items()}

_lib = None


def _load():
    global _lib
    if _lib is not None:
        return _lib
    lib = load_library("graphpack", ["graphpack.cpp"])
    lib.gpk_writer_create.restype = ctypes.c_void_p
    lib.gpk_writer_create.argtypes = [ctypes.c_char_p, ctypes.c_uint64]
    lib.gpk_writer_add_var.restype = ctypes.c_int
    lib.gpk_writer_add_var.argtypes = [
        ctypes.c_void_p,
        ctypes.c_char_p,
        ctypes.c_uint32,
        ctypes.c_uint32,
        ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_int64),
        ctypes.c_void_p,
        ctypes.c_uint64,
    ]
    lib.gpk_writer_finish.restype = ctypes.c_int
    lib.gpk_writer_finish.argtypes = [ctypes.c_void_p]
    lib.gpk_writer_abort.argtypes = [ctypes.c_void_p]
    lib.gpk_open.restype = ctypes.c_void_p
    lib.gpk_open.argtypes = [ctypes.c_char_p, ctypes.c_int]
    lib.gpk_close.argtypes = [ctypes.c_void_p]
    lib.gpk_num_samples.restype = ctypes.c_uint64
    lib.gpk_num_samples.argtypes = [ctypes.c_void_p]
    lib.gpk_num_vars.restype = ctypes.c_uint32
    lib.gpk_num_vars.argtypes = [ctypes.c_void_p]
    lib.gpk_var_name.restype = ctypes.c_char_p
    lib.gpk_var_name.argtypes = [ctypes.c_void_p, ctypes.c_uint32]
    lib.gpk_var_dtype.restype = ctypes.c_uint32
    lib.gpk_var_dtype.argtypes = [ctypes.c_void_p, ctypes.c_uint32]
    lib.gpk_var_ndim.restype = ctypes.c_uint32
    lib.gpk_var_ndim.argtypes = [ctypes.c_void_p, ctypes.c_uint32]
    lib.gpk_var_dims.argtypes = [
        ctypes.c_void_p,
        ctypes.c_uint32,
        ctypes.POINTER(ctypes.c_int64),
    ]
    lib.gpk_sample_ptr.restype = ctypes.c_void_p
    lib.gpk_sample_ptr.argtypes = [
        ctypes.c_void_p,
        ctypes.c_uint32,
        ctypes.c_uint64,
        ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_uint64),
    ]
    lib.gpk_var_ptr.restype = ctypes.c_void_p
    lib.gpk_var_ptr.argtypes = [
        ctypes.c_void_p,
        ctypes.c_uint32,
        ctypes.POINTER(ctypes.c_uint64),
    ]
    _lib = lib
    return lib


class PackWriter:
    """Writes one shard: variables are either variable-first-dim (per-sample
    ``counts``) or fixed-shape ``[num_samples, ...]``."""

    def __init__(self, path: str, num_samples: int):
        self._lib = _load()
        self._h = self._lib.gpk_writer_create(path.encode(), num_samples)
        if not self._h:
            raise OSError(f"cannot create {path}")
        self.num_samples = num_samples
        self._keepalive = []

    def add(
        self,
        name: str,
        data: np.ndarray,
        counts: Optional[np.ndarray] = None,
    ):
        """``counts is None``: fixed var, data is [num_samples, *per_sample];
        the stored dims are the per-sample shape. Else: variable var, data is
        the concatenation along dim 0 and ``counts[i]`` the per-sample
        extent; stored dims are ``(-1, *trailing)``."""
        data = np.ascontiguousarray(data)
        if data.dtype not in _DTYPES:
            raise TypeError(f"unsupported dtype {data.dtype} for {name}")
        if counts is not None:
            counts = np.ascontiguousarray(counts, dtype=np.int64)
            assert counts.shape == (self.num_samples,)
            assert int(counts.sum()) == data.shape[0], (
                f"{name}: counts sum {counts.sum()} != rows {data.shape[0]}"
            )
            dims = [-1] + list(data.shape[1:])
            cptr = counts.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))
            self._keepalive.append(counts)
        else:
            assert data.shape[0] == self.num_samples, (
                f"{name}: fixed var must lead with num_samples"
            )
            dims = list(data.shape[1:]) or [1]
            cptr = None
        dims_arr = (ctypes.c_int64 * len(dims))(*dims)
        self._keepalive.append(data)
        rc = self._lib.gpk_writer_add_var(
            self._h,
            name.encode(),
            _DTYPES[data.dtype],
            len(dims),
            dims_arr,
            cptr,
            data.ctypes.data_as(ctypes.c_void_p),
            data.nbytes,
        )
        if rc != 0:
            raise ValueError(f"gpk_writer_add_var({name}) failed: {rc}")

    def finish(self):
        rc = self._lib.gpk_writer_finish(self._h)
        self._h = None
        self._keepalive = []
        if rc != 0:
            raise OSError(f"gpk_writer_finish failed: {rc}")

    def abort(self):
        if self._h:
            self._lib.gpk_writer_abort(self._h)
            self._h = None


class PackReader:
    def __init__(self, path: str, preload: bool = False):
        self._lib = _load()
        self._h = self._lib.gpk_open(path.encode(), int(preload))
        if not self._h:
            raise OSError(f"cannot open GraphPack shard {path}")
        self.path = path
        self.num_samples = int(self._lib.gpk_num_samples(self._h))
        self.vars: Dict[str, Tuple[int, int, Tuple[int, ...]]] = {}
        for i in range(int(self._lib.gpk_num_vars(self._h))):
            name = self._lib.gpk_var_name(self._h, i).decode()
            dt = int(self._lib.gpk_var_dtype(self._h, i))
            nd = int(self._lib.gpk_var_ndim(self._h, i))
            dims = (ctypes.c_int64 * nd)()
            self._lib.gpk_var_dims(self._h, i, dims)
            self.vars[name] = (i, dt, tuple(int(d) for d in dims))

    def read(self, name: str, sample: int) -> np.ndarray:
        """Copy one sample's slice out as a numpy array."""
        vi, dt, dims = self.vars[name]
        rows = ctypes.c_int64()
        nbytes = ctypes.c_uint64()
        ptr = self._lib.gpk_sample_ptr(
            self._h, vi, sample, ctypes.byref(rows), ctypes.byref(nbytes)
        )
        if not ptr:
            raise IndexError(f"{name}[{sample}]")
        shape = (int(rows.value),) + dims[1:]
        view = np.ctypeslib.as_array(
            ctypes.cast(ptr, ctypes.POINTER(ctypes.c_uint8)),
            shape=(int(nbytes.value),),
        )
        # single copy out of the mmap, already writeable for downstream use
        return view.view(_NP_DTYPES[dt]).reshape(shape).copy()

    def sample_rows(self, name: str, sample: int) -> int:
        """Row count of one sample WITHOUT copying its payload (index-only
        lookup) — lets size scans over huge stores skip the data reads."""
        vi, _dt, _dims = self.vars[name]
        rows = ctypes.c_int64()
        nbytes = ctypes.c_uint64()
        ptr = self._lib.gpk_sample_ptr(
            self._h, vi, sample, ctypes.byref(rows), ctypes.byref(nbytes)
        )
        if not ptr:
            raise IndexError(f"{name}[{sample}]")
        return int(rows.value)

    def read_all(self, name: str) -> np.ndarray:
        """The whole concatenated blob, zero-copy view into the mmap."""
        vi, dt, dims = self.vars[name]
        nbytes = ctypes.c_uint64()
        ptr = self._lib.gpk_var_ptr(self._h, vi, ctypes.byref(nbytes))
        arr = np.ctypeslib.as_array(
            ctypes.cast(ptr, ctypes.POINTER(ctypes.c_uint8)),
            shape=(int(nbytes.value),),
        ).view(_NP_DTYPES[dt])
        # variable-dim vars concatenate samples along dim 0; fixed-shape vars
        # store dims as the per-sample shape, so samples stack in front of it
        if dims and dims[0] == -1:
            arr = arr.reshape((-1,) + dims[1:])
        else:
            arr = arr.reshape((-1,) + dims)
        # NOTE: view into the mmap — valid only while this reader is open;
        # the dataset layer holds the reader for its lifetime.
        arr.flags.writeable = False
        return arr

    def counts(self, name: str) -> Optional[np.ndarray]:
        vi, dt, dims = self.vars[name]
        if dims[0] != -1:
            return None
        self._lib.gpk_var_index.restype = ctypes.POINTER(ctypes.c_int64)
        self._lib.gpk_var_index.argtypes = [ctypes.c_void_p, ctypes.c_uint32]
        ptr = self._lib.gpk_var_index(self._h, vi)
        return np.ctypeslib.as_array(ptr, shape=(self.num_samples,)).copy()

    def close(self):
        if self._h:
            self._lib.gpk_close(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
