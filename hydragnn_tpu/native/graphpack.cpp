// GraphPack — memory-mapped packed-tensor shard format (C++ core).
//
// TPU-native replacement for the reference's ADIOS2 ".bp" data plane
// (hydragnn/utils/adiosdataset.py:77-789): every variable is stored as one
// contiguous blob concatenated along its variable dimension, with per-sample
// count/offset index arrays — the same variable_count/variable_offset design
// the reference builds with MPI-collective DefineVariable/Put calls
// (adiosdataset.py:207-270), but as a flat mmap-able file per writer process.
//
// Why mmap instead of a reader stack: file-backed MAP_SHARED pages are
// shared in the host page cache, so every trainer process on a TPU-VM host
// reads the SAME physical memory — the reference's node-local SharedMemory
// mode (adiosdataset.py:458-506) falls out for free, with zero copies and no
// local-rank-0 election protocol.
//
// File layout (little-endian):
//   magic "GPK1" | u32 version | u64 num_samples | u32 num_vars
//   num_vars x var descriptor:
//     u32 name_len | name bytes
//     u32 dtype (0=f32 1=f64 2=i32 3=i64 4=u8)
//     u32 ndim | i64 dims[ndim]     (dims[0] == -1 -> variable first dim)
//     u64 index_offset              (0 if fixed-shape)
//     u64 data_offset | u64 data_bytes
//   per variable-dim var: i64 count[num_samples] | i64 offset[num_samples]
//   raw blobs (64-byte aligned)

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <cstdlib>
#include <string>
#include <vector>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

constexpr uint32_t kVersion = 1;
constexpr char kMagic[4] = {'G', 'P', 'K', '1'};
constexpr uint64_t kAlign = 64;

size_t dtype_size(uint32_t dt) {
  switch (dt) {
    case 0: return 4;   // f32
    case 1: return 8;   // f64
    case 2: return 4;   // i32
    case 3: return 8;   // i64
    case 4: return 1;   // u8
    default: return 0;
  }
}

struct VarDesc {
  std::string name;
  uint32_t dtype = 0;
  std::vector<int64_t> dims;       // dims[0] == -1 => variable first dim
  std::vector<int64_t> count;      // per-sample extent of the variable dim
  std::vector<int64_t> offset;     // prefix sum of count
  uint64_t index_offset = 0;
  uint64_t data_offset = 0;
  uint64_t data_bytes = 0;
  const void* data = nullptr;      // writer only

  bool variable() const { return !dims.empty() && dims[0] < 0; }
  size_t row_bytes() const {
    size_t b = dtype_size(dtype);
    for (size_t i = 1; i < dims.size(); ++i) b *= (size_t)dims[i];
    return b;
  }
};

struct Writer {
  std::string path;
  uint64_t num_samples = 0;
  std::vector<VarDesc> vars;
};

struct Reader {
  int fd = -1;
  uint8_t* base = nullptr;
  size_t length = 0;
  bool owned_copy = false;         // preload mode: base is malloc'd
  uint64_t num_samples = 0;
  std::vector<VarDesc> vars;
};

uint64_t align_up(uint64_t v) { return (v + kAlign - 1) / kAlign * kAlign; }

template <typename T>
void put(std::string& buf, T v) {
  buf.append(reinterpret_cast<const char*>(&v), sizeof(T));
}

template <typename T>
T take(const uint8_t*& p) {
  T v;
  memcpy(&v, p, sizeof(T));
  p += sizeof(T);
  return v;
}

}  // namespace

extern "C" {

void gpk_close(void* rp);

// ---------------- writer ----------------

void* gpk_writer_create(const char* path, uint64_t num_samples) {
  Writer* w = new Writer();
  w->path = path;
  w->num_samples = num_samples;
  return w;
}

// counts: per-sample extent along dims[0] when dims[0] < 0, else NULL.
// data: the fully concatenated blob (caller keeps it alive until finish).
int gpk_writer_add_var(void* wp, const char* name, uint32_t dtype,
                       uint32_t ndim, const int64_t* dims,
                       const int64_t* counts, const void* data,
                       uint64_t data_bytes) {
  Writer* w = static_cast<Writer*>(wp);
  if (dtype_size(dtype) == 0 || ndim == 0) return -1;
  VarDesc v;
  v.name = name;
  v.dtype = dtype;
  v.dims.assign(dims, dims + ndim);
  if (v.variable()) {
    if (!counts) return -2;
    v.count.assign(counts, counts + w->num_samples);
    v.offset.resize(w->num_samples);
    int64_t off = 0;
    for (uint64_t i = 0; i < w->num_samples; ++i) {
      v.offset[i] = off;
      off += v.count[i];
    }
    if ((uint64_t)off * v.row_bytes() != data_bytes) return -3;
  } else {
    uint64_t expect = v.row_bytes() * (uint64_t)v.dims[0] * w->num_samples;
    // fixed-shape vars store [num_samples, dims...]
    if (expect != data_bytes) return -3;
  }
  v.data = data;
  v.data_bytes = data_bytes;
  w->vars.push_back(std::move(v));
  return 0;
}

int gpk_writer_finish(void* wp) {
  Writer* w = static_cast<Writer*>(wp);
  // serialize header to compute offsets
  std::string header;
  header.append(kMagic, 4);
  put<uint32_t>(header, kVersion);
  put<uint64_t>(header, w->num_samples);
  put<uint32_t>(header, (uint32_t)w->vars.size());
  size_t desc_start = header.size();
  for (auto& v : w->vars) {
    put<uint32_t>(header, (uint32_t)v.name.size());
    header.append(v.name);
    put<uint32_t>(header, v.dtype);
    put<uint32_t>(header, (uint32_t)v.dims.size());
    for (int64_t d : v.dims) put<int64_t>(header, d);
    put<uint64_t>(header, 0);  // index_offset placeholder
    put<uint64_t>(header, 0);  // data_offset placeholder
    put<uint64_t>(header, v.data_bytes);
  }
  // index arrays follow the header
  uint64_t cursor = header.size();
  for (auto& v : w->vars) {
    if (v.variable()) {
      v.index_offset = cursor;
      cursor += 2 * sizeof(int64_t) * w->num_samples;
    }
  }
  // blobs, aligned
  for (auto& v : w->vars) {
    cursor = align_up(cursor);
    v.data_offset = cursor;
    cursor += v.data_bytes;
  }
  // patch placeholders
  size_t p = desc_start;
  for (auto& v : w->vars) {
    p += 4 + v.name.size() + 4 + 4 + 8 * v.dims.size();
    memcpy(&header[p], &v.index_offset, 8);
    memcpy(&header[p + 8], &v.data_offset, 8);
    p += 24;
  }

  FILE* f = fopen(w->path.c_str(), "wb");
  if (!f) {
    delete w;
    return -1;
  }
  int rc = 0;
  if (fwrite(header.data(), 1, header.size(), f) != header.size()) rc = -2;
  uint64_t written = header.size();
  for (auto& v : w->vars) {
    if (!v.variable()) continue;
    if (fwrite(v.count.data(), sizeof(int64_t), v.count.size(), f) !=
        v.count.size())
      rc = -2;
    if (fwrite(v.offset.data(), sizeof(int64_t), v.offset.size(), f) !=
        v.offset.size())
      rc = -2;
    written += 2 * sizeof(int64_t) * w->num_samples;
  }
  for (auto& v : w->vars) {
    uint64_t pad = align_up(written) - written;
    static const char zeros[kAlign] = {0};
    if (pad && fwrite(zeros, 1, pad, f) != pad) rc = -2;
    written += pad;
    if (fwrite(v.data, 1, v.data_bytes, f) != v.data_bytes) rc = -2;
    written += v.data_bytes;
  }
  // stdio buffering can defer a write failure (e.g. ENOSPC) to the final
  // flush — a corrupt shard must not report success and get published.
  if (fclose(f) != 0) rc = -2;
  delete w;
  return rc;
}

void gpk_writer_abort(void* wp) { delete static_cast<Writer*>(wp); }

// ---------------- reader ----------------

// preload: 0 = pure mmap (page-cache shared across host processes),
//          1 = copy whole file into private RAM (for slow/remote filesystems)
void* gpk_open(const char* path, int preload) {
  int fd = open(path, O_RDONLY);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0) { close(fd); return nullptr; }
  size_t len = (size_t)st.st_size;
  void* base = mmap(nullptr, len, PROT_READ, MAP_SHARED, fd, 0);
  if (base == MAP_FAILED) { close(fd); return nullptr; }

  Reader* r = new Reader();
  r->length = len;
  if (preload) {
    uint8_t* copy = (uint8_t*)malloc(len);
    if (!copy) { munmap(base, len); close(fd); delete r; return nullptr; }
    memcpy(copy, base, len);
    munmap(base, len);
    close(fd);
    r->base = copy;
    r->owned_copy = true;
    r->fd = -1;
  } else {
    madvise(base, len, MADV_WILLNEED);
    r->base = (uint8_t*)base;
    r->fd = fd;
  }

  const uint8_t* p = r->base;
  if (len < 20 || memcmp(p, kMagic, 4) != 0) { gpk_close(r); return nullptr; }
  p += 4;
  uint32_t version = take<uint32_t>(p);
  if (version != kVersion) { gpk_close(r); return nullptr; }
  r->num_samples = take<uint64_t>(p);
  uint32_t nvars = take<uint32_t>(p);
  r->vars.resize(nvars);
  for (auto& v : r->vars) {
    uint32_t nl = take<uint32_t>(p);
    v.name.assign((const char*)p, nl);
    p += nl;
    v.dtype = take<uint32_t>(p);
    uint32_t nd = take<uint32_t>(p);
    v.dims.resize(nd);
    for (auto& d : v.dims) d = take<int64_t>(p);
    v.index_offset = take<uint64_t>(p);
    v.data_offset = take<uint64_t>(p);
    v.data_bytes = take<uint64_t>(p);
  }
  return r;
}

void gpk_close(void* rp) {
  Reader* r = static_cast<Reader*>(rp);
  if (!r) return;
  if (r->owned_copy) {
    free(r->base);
  } else if (r->base) {
    munmap(r->base, r->length);
  }
  if (r->fd >= 0) close(r->fd);
  delete r;
}

uint64_t gpk_num_samples(void* rp) {
  return static_cast<Reader*>(rp)->num_samples;
}
uint32_t gpk_num_vars(void* rp) {
  return (uint32_t)static_cast<Reader*>(rp)->vars.size();
}
const char* gpk_var_name(void* rp, uint32_t i) {
  return static_cast<Reader*>(rp)->vars[i].name.c_str();
}
uint32_t gpk_var_dtype(void* rp, uint32_t i) {
  return static_cast<Reader*>(rp)->vars[i].dtype;
}
uint32_t gpk_var_ndim(void* rp, uint32_t i) {
  return (uint32_t)static_cast<Reader*>(rp)->vars[i].dims.size();
}
void gpk_var_dims(void* rp, uint32_t i, int64_t* out) {
  const auto& d = static_cast<Reader*>(rp)->vars[i].dims;
  memcpy(out, d.data(), d.size() * sizeof(int64_t));
}

// Zero-copy pointer to one sample's slice of variable `vi`; writes the
// sample's first-dim extent to *rows and byte length to *nbytes.
const void* gpk_sample_ptr(void* rp, uint32_t vi, uint64_t sample,
                           int64_t* rows, uint64_t* nbytes) {
  Reader* r = static_cast<Reader*>(rp);
  if (vi >= r->vars.size() || sample >= r->num_samples) return nullptr;
  const VarDesc& v = r->vars[vi];
  size_t rb = v.row_bytes();
  if (v.variable()) {
    const int64_t* count =
        (const int64_t*)(r->base + v.index_offset);
    const int64_t* offset = count + r->num_samples;
    *rows = count[sample];
    *nbytes = (uint64_t)count[sample] * rb;
    return r->base + v.data_offset + (uint64_t)offset[sample] * rb;
  }
  *rows = v.dims[0];
  *nbytes = (uint64_t)v.dims[0] * rb;
  return r->base + v.data_offset + sample * (*nbytes);
}

// Bulk pointer to a variable's whole blob (for preloading into numpy).
const void* gpk_var_ptr(void* rp, uint32_t vi, uint64_t* nbytes) {
  Reader* r = static_cast<Reader*>(rp);
  if (vi >= r->vars.size()) return nullptr;
  *nbytes = r->vars[vi].data_bytes;
  return r->base + r->vars[vi].data_offset;
}

const int64_t* gpk_var_index(void* rp, uint32_t vi) {
  Reader* r = static_cast<Reader*>(rp);
  if (vi >= r->vars.size() || !r->vars[vi].variable()) return nullptr;
  return (const int64_t*)(r->base + r->vars[vi].index_offset);
}

}  // extern "C"
