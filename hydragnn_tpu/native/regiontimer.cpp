// RegionTimer — nested HPC region timers (C++ core).
//
// Native replacement for GPTL (`gptl4py`, used by hydragnn/utils/tracer.py:
// 39-59 with per-rank `gp.pr_file` / `pr_summary_file` dumps): nested
// start/stop regions accumulate into a call-tree keyed by the full region
// path ("train/forward"), with count/total/min/max per node, plus an
// in-memory event ring that exports chrome://tracing JSON (the modern
// equivalent of GPTL's text timing files — loadable in perfetto).

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <ctime>
#include <map>
#include <mutex>
#include <string>
#include <vector>

namespace {

double now_s() {
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return (double)ts.tv_sec + 1e-9 * (double)ts.tv_nsec;
}

struct Stat {
  uint64_t count = 0;
  double total = 0, mn = 1e300, mx = 0;
};

struct Event {
  std::string path;
  double t0, t1;
};

struct Timer {
  std::mutex mu;
  std::vector<std::pair<std::string, double>> stack;  // (name, t_start)
  std::map<std::string, Stat> stats;                  // keyed by full path
  std::vector<Event> events;
  size_t max_events = 1 << 20;
  double epoch = now_s();
};

}  // namespace

extern "C" {

void* rt_create() { return new Timer(); }
void rt_destroy(void* h) { delete static_cast<Timer*>(h); }

void rt_start(void* h, const char* name) {
  Timer* t = static_cast<Timer*>(h);
  std::lock_guard<std::mutex> lk(t->mu);
  t->stack.emplace_back(name, now_s());
}

void rt_stop(void* h, const char* name) {
  Timer* t = static_cast<Timer*>(h);
  std::lock_guard<std::mutex> lk(t->mu);
  // unwind to the matching frame (tolerates missed stops, like GPTL)
  for (size_t i = t->stack.size(); i > 0; --i) {
    if (t->stack[i - 1].first == name) {
      double t1 = now_s();
      double t0 = t->stack[i - 1].second;
      std::string path;
      for (size_t j = 0; j < i; ++j) {
        path += t->stack[j].first;
        if (j + 1 < i) path += '/';
      }
      Stat& s = t->stats[path];
      double dt = t1 - t0;
      s.count++;
      s.total += dt;
      if (dt < s.mn) s.mn = dt;
      if (dt > s.mx) s.mx = dt;
      if (t->events.size() < t->max_events)
        t->events.push_back({path, t0, t1});
      t->stack.resize(i - 1);
      return;
    }
  }
}

void rt_reset(void* h) {
  Timer* t = static_cast<Timer*>(h);
  std::lock_guard<std::mutex> lk(t->mu);
  t->stack.clear();
  t->stats.clear();
  t->events.clear();
  t->epoch = now_s();
}

// GPTL-style per-rank text summary: call-tree indented by path depth.
int rt_print(void* h, const char* filename) {
  Timer* t = static_cast<Timer*>(h);
  std::lock_guard<std::mutex> lk(t->mu);
  FILE* f = fopen(filename, "w");
  if (!f) return -1;
  fprintf(f, "%-44s %10s %14s %12s %12s %12s\n", "region", "calls",
          "total_s", "avg_ms", "min_ms", "max_ms");
  for (auto& kv : t->stats) {
    const std::string& path = kv.first;
    int depth = 0;
    for (char c : path)
      if (c == '/') depth++;
    std::string label(2 * depth, ' ');
    size_t slash = path.rfind('/');
    label += (slash == std::string::npos) ? path : path.substr(slash + 1);
    const Stat& s = kv.second;
    fprintf(f, "%-44s %10llu %14.4f %12.3f %12.3f %12.3f\n", label.c_str(),
            (unsigned long long)s.count, s.total,
            1e3 * s.total / (double)(s.count ? s.count : 1), 1e3 * s.mn,
            1e3 * s.mx);
  }
  fclose(f);
  return 0;
}

// Region names are arbitrary caller strings: escape them for JSON.
std::string json_escape(const std::string& in) {
  std::string out;
  out.reserve(in.size());
  for (unsigned char c : in) {
    if (c == '"' || c == '\\') {
      out += '\\';
      out += (char)c;
    } else if (c < 0x20) {
      char buf[8];
      snprintf(buf, sizeof(buf), "\\u%04x", c);
      out += buf;
    } else {
      out += (char)c;
    }
  }
  return out;
}

// chrome://tracing / perfetto JSON ("X" complete events).
int rt_chrome(void* h, const char* filename, int pid) {
  Timer* t = static_cast<Timer*>(h);
  std::lock_guard<std::mutex> lk(t->mu);
  FILE* f = fopen(filename, "w");
  if (!f) return -1;
  fprintf(f, "[\n");
  bool first = true;
  for (auto& e : t->events) {
    if (!first) fprintf(f, ",\n");
    first = false;
    fprintf(f,
            "{\"name\":\"%s\",\"ph\":\"X\",\"pid\":%d,\"tid\":0,"
            "\"ts\":%.3f,\"dur\":%.3f}",
            json_escape(e.path).c_str(), pid, 1e6 * (e.t0 - t->epoch),
            1e6 * (e.t1 - e.t0));
  }
  fprintf(f, "\n]\n");
  fclose(f);
  return 0;
}

// Accessors for tests / summaries.
// Newline-separated "path<TAB>total_seconds" dump of every region — the
// host-side consumer is the telemetry layer's region-totals forwarding
// (utils/tracer.py totals()). Returns bytes written, or -(bytes needed)
// when the buffer is too small so the caller can retry sized right.
int rt_totals(void* h, char* buf, int cap) {
  Timer* t = static_cast<Timer*>(h);
  std::lock_guard<std::mutex> lk(t->mu);
  std::string out;
  char line[512];
  for (auto& kv : t->stats) {
    snprintf(line, sizeof line, "%s\t%.9f\n", kv.first.c_str(),
             kv.second.total);
    out += line;
  }
  if ((int)out.size() + 1 > cap) return -(int)(out.size() + 1);
  memcpy(buf, out.c_str(), out.size() + 1);
  return (int)out.size();
}

uint64_t rt_count(void* h, const char* path) {
  Timer* t = static_cast<Timer*>(h);
  std::lock_guard<std::mutex> lk(t->mu);
  auto it = t->stats.find(path);
  return it == t->stats.end() ? 0 : it->second.count;
}

double rt_total(void* h, const char* path) {
  Timer* t = static_cast<Timer*>(h);
  std::lock_guard<std::mutex> lk(t->mu);
  auto it = t->stats.find(path);
  return it == t->stats.end() ? 0.0 : it->second.total;
}

}  // extern "C"
