// DistStore — distributed in-memory sample store (C++ core).
//
// TPU-native replacement for DDStore (`pyddstore`, used at
// hydragnn/utils/distdataset.py:22-183 and adiosdataset.py:507-545): the
// global sample index space is partitioned contiguously across processes;
// each process holds its partition in RAM and serves it to peers. The
// reference exposes add()/get(name, buf, offset)/epoch_begin()/epoch_end()
// over MPI one-sided windows; here the transport is plain TCP between
// TPU-VM hosts (DCN) — epoch_begin starts the serving thread, epoch_end
// drains and stops it, get() on a non-local sample fetches from the owner.
//
// Wire protocol (little-endian):
//   request:  u32 var_id | u64 global_sample_index
//   response: i64 rows | u64 nbytes | payload
//
// On-host sharing needs no RPC at all (GraphPack mmap shards cover it);
// DistStore exists for datasets larger than one host's RAM spread across
// hosts — SURVEY.md §2.4.

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <sys/socket.h>
#include <unistd.h>

#include <algorithm>
#include <atomic>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <map>
#include <memory>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace {

struct Var {
  std::string name;
  size_t row_bytes = 0;
  std::vector<int64_t> count;    // per LOCAL sample
  std::vector<int64_t> offset;   // prefix sum (rows)
  std::vector<uint8_t> data;     // owned copy of the local partition
};

struct Store {
  int rank = 0;
  int world = 1;
  std::vector<std::string> host;   // per-rank "ip"
  std::vector<int> port;           // per-rank port
  std::vector<int64_t> part_start; // first global sample of each rank
  std::vector<int64_t> part_count; // samples held by each rank
  std::vector<Var> vars;

  int listen_fd = -1;
  std::thread server;
  std::atomic<bool> running{false};
  // cached client connections; peer_fd[r] is only touched under peer_mu[r]
  std::vector<int> peer_fd;
  std::vector<std::unique_ptr<std::mutex>> peer_mu;
};

bool read_full(int fd, void* buf, size_t n) {
  uint8_t* p = (uint8_t*)buf;
  while (n) {
    ssize_t r = recv(fd, p, n, 0);
    if (r <= 0) return false;
    p += r;
    n -= (size_t)r;
  }
  return true;
}

bool write_full(int fd, const void* buf, size_t n) {
  const uint8_t* p = (const uint8_t*)buf;
  while (n) {
    ssize_t r = send(fd, p, n, MSG_NOSIGNAL);
    if (r <= 0) return false;
    p += r;
    n -= (size_t)r;
  }
  return true;
}

int owner_of(Store* s, int64_t idx) {
  for (int r = 0; r < s->world; ++r)
    if (idx >= s->part_start[r] && idx < s->part_start[r] + s->part_count[r])
      return r;
  return -1;
}

// local lookup: returns pointer into the var blob
const uint8_t* local_sample(Store* s, uint32_t vi, int64_t local_idx,
                            int64_t* rows, uint64_t* nbytes) {
  Var& v = s->vars[vi];
  *rows = v.count[local_idx];
  *nbytes = (uint64_t)(*rows) * v.row_bytes;
  return v.data.data() + (uint64_t)v.offset[local_idx] * v.row_bytes;
}

void serve_conn(Store* s, int fd) {
  for (;;) {
    // poll so shutdown (running=false) isn't blocked by an idle connection
    struct pollfd pf{fd, POLLIN, 0};
    int rc = poll(&pf, 1, 100 /*ms*/);
    if (!s->running.load()) break;
    if (rc <= 0) continue;
    uint32_t vi;
    uint64_t gidx;
    if (!read_full(fd, &vi, 4) || !read_full(fd, &gidx, 8)) break;
    if (vi >= s->vars.size()) break;
    int64_t local = (int64_t)gidx - s->part_start[s->rank];
    if (local < 0 || local >= s->part_count[s->rank]) break;
    int64_t rows;
    uint64_t nbytes;
    const uint8_t* p = local_sample(s, vi, local, &rows, &nbytes);
    if (!write_full(fd, &rows, 8) || !write_full(fd, &nbytes, 8) ||
        !write_full(fd, p, nbytes))
      break;
  }
  close(fd);
}

void server_loop(Store* s) {
  std::vector<std::thread> workers;
  while (s->running.load()) {
    struct pollfd pf{s->listen_fd, POLLIN, 0};
    int rc = poll(&pf, 1, 100 /*ms*/);
    if (rc <= 0) continue;
    int fd = accept(s->listen_fd, nullptr, nullptr);
    if (fd < 0) continue;
    int one = 1;
    setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    workers.emplace_back(serve_conn, s, fd);
  }
  for (auto& w : workers)
    if (w.joinable()) w.join();
}

// caller must hold peer_mu[rank]
int connect_peer(Store* s, int rank) {
  if (s->peer_fd[rank] >= 0) return s->peer_fd[rank];
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons((uint16_t)s->port[rank]);
  inet_pton(AF_INET, s->host[rank].c_str(), &addr.sin_addr);
  // the peer's epoch_begin may lag ours: retry briefly. A TCP socket is
  // unusable after a failed connect(), so each attempt gets a fresh one.
  for (int attempt = 0; attempt < 100; ++attempt) {
    int fd = socket(AF_INET, SOCK_STREAM, 0);
    if (fd < 0) return -1;
    if (connect(fd, (sockaddr*)&addr, sizeof(addr)) == 0) {
      int one = 1;
      setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
      s->peer_fd[rank] = fd;
      return fd;
    }
    close(fd);
    usleep(50 * 1000);
  }
  return -1;
}

// Drop a cached peer connection after a protocol failure so the next get()
// reconnects instead of reading a desynchronized stream. Caller must hold
// peer_mu[rank].
void invalidate_peer(Store* s, int rank, int fd) {
  if (s->peer_fd[rank] == fd) s->peer_fd[rank] = -1;
  close(fd);
}

// Read and discard n bytes (keeps the stream in sync when the caller's
// buffer was too small). Returns false on socket error.
bool drain(int fd, uint64_t n) {
  uint8_t scratch[4096];
  while (n) {
    size_t chunk = n < sizeof(scratch) ? (size_t)n : sizeof(scratch);
    if (!read_full(fd, scratch, chunk)) return false;
    n -= chunk;
  }
  return true;
}

}  // namespace

extern "C" {

// hosts: "ip:port,ip:port,..." — one entry per rank.
void* dds_create(int rank, int world, const char* hosts) {
  Store* s = new Store();
  s->rank = rank;
  s->world = world;
  std::string h(hosts);
  size_t pos = 0;
  while (pos < h.size()) {
    size_t comma = h.find(',', pos);
    if (comma == std::string::npos) comma = h.size();
    std::string entry = h.substr(pos, comma - pos);
    size_t colon = entry.rfind(':');
    s->host.push_back(entry.substr(0, colon));
    s->port.push_back(atoi(entry.c_str() + colon + 1));
    pos = comma + 1;
  }
  if ((int)s->host.size() != world) {
    delete s;
    return nullptr;
  }
  s->peer_fd.assign(world, -1);
  for (int i = 0; i < world; ++i)
    s->peer_mu.emplace_back(new std::mutex());
  return s;
}

// samples_per_rank: how many samples each rank holds (contiguous partition).
int dds_set_partition(void* sp, const int64_t* samples_per_rank) {
  Store* s = static_cast<Store*>(sp);
  s->part_start.resize(s->world);
  s->part_count.assign(samples_per_rank, samples_per_rank + s->world);
  int64_t off = 0;
  for (int r = 0; r < s->world; ++r) {
    s->part_start[r] = off;
    off += s->part_count[r];
  }
  return 0;
}

// Adds the LOCAL partition of one variable; data/counts are copied in.
int dds_add_var(void* sp, const char* name, uint64_t row_bytes,
                const int64_t* counts, const void* data,
                uint64_t data_bytes) {
  Store* s = static_cast<Store*>(sp);
  Var v;
  v.name = name;
  v.row_bytes = row_bytes;
  int64_t n = s->part_count[s->rank];
  v.count.assign(counts, counts + n);
  v.offset.resize(n);
  int64_t off = 0;
  for (int64_t i = 0; i < n; ++i) {
    v.offset[i] = off;
    off += v.count[i];
  }
  if ((uint64_t)off * row_bytes != data_bytes) return -1;
  v.data.assign((const uint8_t*)data, (const uint8_t*)data + data_bytes);
  s->vars.push_back(std::move(v));
  return (int)s->vars.size() - 1;
}

int dds_epoch_begin(void* sp) {
  Store* s = static_cast<Store*>(sp);
  if (s->running.load()) return 0;
  s->listen_fd = socket(AF_INET, SOCK_STREAM, 0);
  if (s->listen_fd < 0) return -1;
  int one = 1;
  setsockopt(s->listen_fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = INADDR_ANY;
  addr.sin_port = htons((uint16_t)s->port[s->rank]);
  if (bind(s->listen_fd, (sockaddr*)&addr, sizeof(addr)) != 0) {
    close(s->listen_fd);
    s->listen_fd = -1;
    return -2;
  }
  if (listen(s->listen_fd, 64) != 0) {
    close(s->listen_fd);
    s->listen_fd = -1;
    return -3;
  }
  s->running.store(true);
  s->server = std::thread(server_loop, s);
  return 0;
}

int dds_epoch_end(void* sp) {
  Store* s = static_cast<Store*>(sp);
  if (!s->running.load()) return 0;
  s->running.store(false);
  if (s->server.joinable()) s->server.join();
  close(s->listen_fd);
  s->listen_fd = -1;
  for (int r = 0; r < s->world; ++r) {
    std::lock_guard<std::mutex> lk(*s->peer_mu[r]);
    if (s->peer_fd[r] >= 0) close(s->peer_fd[r]);
    s->peer_fd[r] = -1;
  }
  return 0;
}

// Fetch sample `gidx` of var `vi` into out (capacity out_cap bytes).
// Returns rows (>=0) or negative error; *nbytes gets the payload size.
int64_t dds_get(void* sp, uint32_t vi, uint64_t gidx, void* out,
                uint64_t out_cap, uint64_t* nbytes) {
  Store* s = static_cast<Store*>(sp);
  int owner = owner_of(s, (int64_t)gidx);
  if (owner < 0 || vi >= s->vars.size()) return -1;
  if (owner == s->rank) {
    int64_t rows;
    const uint8_t* p = local_sample(
        s, vi, (int64_t)gidx - s->part_start[s->rank], &rows, nbytes);
    if (*nbytes > out_cap) return -2;
    memcpy(out, p, *nbytes);
    return rows;
  }
  // the lock spans connect -> request -> response -> (maybe) invalidate, so
  // the fd cannot be closed/reused by a concurrent get to the same owner
  std::lock_guard<std::mutex> lk(*s->peer_mu[owner]);
  int fd = connect_peer(s, owner);
  if (fd < 0) return -3;
  int64_t rows;
  if (!write_full(fd, &vi, 4) || !write_full(fd, &gidx, 8) ||
      !read_full(fd, &rows, 8) || !read_full(fd, nbytes, 8)) {
    invalidate_peer(s, owner, fd);
    return -4;
  }
  if (*nbytes > out_cap) {
    // consume the payload so the cached connection stays usable
    if (!drain(fd, *nbytes)) invalidate_peer(s, owner, fd);
    return -2;
  }
  if (!read_full(fd, out, *nbytes)) {
    invalidate_peer(s, owner, fd);
    return -4;
  }
  return rows;
}

int64_t dds_total_samples(void* sp) {
  Store* s = static_cast<Store*>(sp);
  int64_t t = 0;
  for (auto c : s->part_count) t += c;
  return t;
}

// Max payload bytes of var vi over the LOCAL partition (callers allocate
// out buffers with a host-side allgather max of this).
uint64_t dds_local_max_bytes(void* sp, uint32_t vi) {
  Store* s = static_cast<Store*>(sp);
  if (vi >= s->vars.size()) return 0;
  Var& v = s->vars[vi];
  int64_t mx = 0;
  for (auto c : v.count) mx = std::max(mx, c);
  return (uint64_t)mx * v.row_bytes;
}

void dds_destroy(void* sp) {
  Store* s = static_cast<Store*>(sp);
  dds_epoch_end(sp);
  delete s;
}

}  // extern "C"
