"""ctypes binding for the native region timer (regiontimer.cpp) — the GPTL
analog behind the ``hydragnn_tpu.utils.tracer`` facade."""

import ctypes

from hydragnn_tpu.native.build import load_library

_lib = None


def _load():
    global _lib
    if _lib is not None:
        return _lib
    lib = load_library("regiontimer", ["regiontimer.cpp"])
    lib.rt_create.restype = ctypes.c_void_p
    lib.rt_destroy.argtypes = [ctypes.c_void_p]
    lib.rt_start.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.rt_stop.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.rt_reset.argtypes = [ctypes.c_void_p]
    lib.rt_print.restype = ctypes.c_int
    lib.rt_print.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.rt_chrome.restype = ctypes.c_int
    lib.rt_chrome.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int]
    lib.rt_count.restype = ctypes.c_uint64
    lib.rt_count.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.rt_totals.restype = ctypes.c_int
    lib.rt_totals.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int]
    lib.rt_total.restype = ctypes.c_double
    lib.rt_total.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    _lib = lib
    return lib


class NativeRegionTimer:
    """Nested region timer with call-tree stats and chrome-trace export."""

    def __init__(self):
        self._lib = _load()
        self._h = self._lib.rt_create()

    def start(self, name: str):
        self._lib.rt_start(self._h, name.encode())

    def stop(self, name: str):
        self._lib.rt_stop(self._h, name.encode())

    def reset(self):
        self._lib.rt_reset(self._h)

    def pr_file(self, filename: str):
        import os

        os.makedirs(os.path.dirname(filename) or ".", exist_ok=True)
        self._lib.rt_print(self._h, filename.encode())

    def chrome_trace(self, filename: str, pid: int = 0):
        import os

        os.makedirs(os.path.dirname(filename) or ".", exist_ok=True)
        self._lib.rt_chrome(self._h, filename.encode(), pid)

    def count(self, path: str) -> int:
        return int(self._lib.rt_count(self._h, path.encode()))

    def total(self, path: str) -> float:
        return float(self._lib.rt_total(self._h, path.encode()))

    def totals(self) -> dict:
        """{region path: accumulated seconds} for every region — the
        telemetry layer's end-of-run region forwarding reads this."""
        cap = 1 << 16
        while True:
            buf = ctypes.create_string_buffer(cap)
            n = self._lib.rt_totals(self._h, buf, cap)
            if n >= 0:
                break
            cap = -n
        out = {}
        for line in buf.value.decode().splitlines():
            if "\t" in line:
                path, tot = line.rsplit("\t", 1)
                out[path] = float(tot)
        return out

    def __del__(self):
        try:
            if self._h:
                self._lib.rt_destroy(self._h)
                self._h = None
        except Exception:
            pass
