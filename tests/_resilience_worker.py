"""Worker for the kill-and-resume resilience e2e test (NOT a pytest module).

Runs a small deterministic training through the REAL epoch driver
(``train_validate_test``) with per-epoch resumable checkpoints, under
whatever ``HYDRAGNN_FAULT_*`` injection the parent test set. Three modes:

    python _resilience_worker.py <workdir> run      # fresh run
    python _resilience_worker.py <workdir> resume   # Training.continue path

The worker chdirs into ``workdir`` so checkpoints land under
``<workdir>/logs/``; at clean exit it dumps ``result.json`` with the
run's observable trajectory so the parent can compare killed+resumed
against uninterrupted. A run killed by ``HYDRAGNN_FAULT_KILL_AT_STEP``
exits hard (os._exit) and leaves no result.json — only the fsync'd
checkpoints.
"""

import json
import os
import sys

# the container pins JAX_PLATFORMS at interpreter startup; force CPU the
# same way conftest.py does
os.environ.setdefault(
    "XLA_FLAGS",
    (os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=1").strip(),
)
os.environ["JAX_PLATFORMS"] = "cpu"

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np

NUM_EPOCH = 5
LOG_NAME = "resil"


def make_samples(num=24, seed=11):
    from hydragnn_tpu.data.dataobj import GraphData

    rng = np.random.default_rng(seed)
    out = []
    for _ in range(num):
        n = 6
        g = GraphData()
        g.x = rng.random((n, 1)).astype(np.float32)
        g.pos = rng.random((n, 3)).astype(np.float32)
        src = np.arange(n)
        dst = (src + 1) % n
        g.edge_index = np.stack(
            [np.concatenate([src, dst]), np.concatenate([dst, src])]
        ).astype(np.int64)
        g.edge_attr = None
        # closed-form targets: graph sum + identity node head
        g.targets = [np.array([g.x.sum()], np.float32), g.x.copy()]
        g.target_types = ["graph", "node"]
        out.append(g)
    return out


def build():
    from hydragnn_tpu.data.loaders import GraphLoader, compute_layout
    from hydragnn_tpu.models.create import create_model_config
    from hydragnn_tpu.train.trainer import Trainer

    arch = {
        "model_type": "GIN",
        "input_dim": 1,
        "hidden_dim": 8,
        "num_conv_layers": 2,
        "output_dim": [1, 1],
        "output_type": ["graph", "node"],
        "output_heads": {
            "graph": {
                "num_sharedlayers": 1,
                "dim_sharedlayers": 8,
                "num_headlayers": 1,
                "dim_headlayers": [8],
            },
            "node": {
                "num_headlayers": 1,
                "dim_headlayers": [8],
                "type": "mlp",
            },
        },
        "task_weights": [1.0, 1.0],
    }
    training = {
        "num_epoch": NUM_EPOCH,
        "perc_train": 0.7,
        "Optimizer": {"type": "AdamW", "learning_rate": 1e-2},
        "resume_every": 1,
        "checkpoint_keep_last": 4,
    }
    samples = make_samples()
    layout = compute_layout([samples], batch_size=4, need_triplets=False)
    train_loader = GraphLoader(samples[:16], 4, layout, shuffle=True, seed=7)
    val_loader = GraphLoader(samples[16:20], 4, layout, shuffle=False)
    test_loader = GraphLoader(samples[20:], 4, layout, shuffle=False)
    model = create_model_config(arch)
    trainer = Trainer(model, training)
    state = trainer.init_state(next(iter(train_loader)), seed=0)
    return trainer, state, (train_loader, val_loader, test_loader), training


def main():
    workdir, mode = sys.argv[1], sys.argv[2]
    os.chdir(workdir)

    from hydragnn_tpu.train.checkpoint import (
        checkpoint_exists,
        load_state_dict,
        pop_train_meta,
        restore_into,
    )
    from hydragnn_tpu.train.epoch_driver import train_validate_test

    trainer, state, loaders, training = build()

    resume_meta = None
    if mode == "resume":
        if not checkpoint_exists(LOG_NAME):
            raise FileNotFoundError("resume requested but no checkpoint")
        restored = load_state_dict(LOG_NAME)
        resume_meta = pop_train_meta(restored)
        state = trainer.place_state(restore_into(state, restored))

    # count the epochs THIS process actually trains (the resumed run must
    # run only the remaining ones)
    epochs_run = []
    orig = trainer.train_epoch

    def counting_train_epoch(state, loader, rng):
        epochs_run.append(loader.epoch)
        return orig(state, loader, rng)

    trainer.train_epoch = counting_train_epoch

    config_nn = {
        "Training": training,
        "Variables_of_interest": {"output_names": ["sum", "x"]},
    }
    state = train_validate_test(
        trainer, state, *loaders, config_nn, LOG_NAME, verbosity=0,
        resume_meta=resume_meta,
    )

    from hydragnn_tpu.train.optimizer import get_learning_rate

    final = {
        "mode": mode,
        "resumed_from_epoch": (
            None if resume_meta is None else int(resume_meta["epoch"]) + 1
        ),
        "epochs_run": epochs_run,
        "final_lr": get_learning_rate(state.opt_state),
        "final_params_digest": [
            float(np.asarray(leaf, np.float64).sum())
            for leaf in jax.tree_util.tree_leaves(
                jax.device_get(state.params)
            )
        ],
    }
    with open("result.json", "w") as f:
        json.dump(final, f)


if __name__ == "__main__":
    main()
