"""CI multi-tenant serving smoke (standalone, NOT a pytest module).

The ISSUE 17 e2e gate: a 2-replica spec-driven fleet HBM-packing two
tenants onto two models, behind a response-caching router and a
predictive autoscaler —

1. steady state: both tenants served, every response computed by the
   TENANT'S model (zero cross-tenant responses, weight-verified),
2. response cache: resubmitting the same structures drives the router
   hit-ratio up, with hits bitwise-equal to the fresh answers,
3. tenant flood: 'acme' hammers far past its quota from 8 concurrent
   clients while 'beta' runs its baseline loop — only the offender is
   shed, beta finishes 100% ok,
4. autoscale spike: shed pressure grows the fleet 2 -> 3 via
   ``ServingFleet.resize``; the quiet tail shrinks it 3 -> 2,
5. the whole event stream validates against the documented schema
   (``tenant_admitted`` + ``cache_stats`` + ``fleet_scaled`` included).

Usage: python tests/_multitenant_smoke.py <workdir>
"""

import copy
import json
import os
import pickle
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from _fleet_smoke import ARCH, make_graphs  # noqa: E402

REQUEST_DEADLINE_S = 30.0
ACME_QUOTA = 2
FLOOD_CLIENTS = 8


def build_artifacts(workdir):
    """Two checkpoints (base + weight-bumped aux), plan samples, and a
    TENANTED fleet spec with the response cache enabled."""
    import jax

    from hydragnn_tpu.models.create import create_model_config
    from hydragnn_tpu.serve.buckets import plan_from_samples
    from hydragnn_tpu.train.checkpoint import save_model
    from hydragnn_tpu.train.trainer import Trainer

    samples = make_graphs(32, seed=17)
    plan = plan_from_samples(samples, max_batch_graphs=4, num_buckets=2)
    model = create_model_config(dict(ARCH))
    trainer = Trainer(
        model, {"Optimizer": {"type": "AdamW", "learning_rate": 1e-3}}
    )
    init_batch, _ = plan.pack([samples[0]], 0)
    state = trainer.init_state(init_batch, seed=0)
    ckdir = os.path.join(workdir, "ck")
    save_model(state, "base", path=ckdir)
    bumped = state.replace(
        params=jax.tree_util.tree_map(lambda x: x + 0.05, state.params)
    )
    save_model(bumped, "aux", path=ckdir)
    samples_path = os.path.join(workdir, "samples.pkl")
    with open(samples_path, "wb") as f:
        pickle.dump(samples, f)
    spec = {
        "checkpoint": {"name": "base", "path": ckdir},
        "arch": ARCH,
        "model_name": "m",
        "samples": samples_path,
        "plan": {"max_batch_graphs": 4, "num_buckets": 2},
        "server": {"max_wait_s": 0.003, "queue_capacity": 256},
        "tenants": [
            {"name": "acme", "model": "m", "quota": ACME_QUOTA},
            {"name": "beta", "model": "aux", "quota": 32,
             "checkpoint": {"name": "aux", "path": ckdir,
                            "arch": ARCH}},
        ],
        "cache": {"enabled": True},
    }
    spec_path = os.path.join(workdir, "spec.json")
    with open(spec_path, "w") as f:
        json.dump(spec, f)
    return spec_path, samples


def main(workdir):
    os.makedirs(workdir, exist_ok=True)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    import numpy as np

    from hydragnn_tpu.obs.events import validate_events
    from hydragnn_tpu.serve import (
        AutoscalePolicy,
        FleetAutoscaler,
        FleetRouter,
        ResponseCache,
        ServerOverloaded,
        ServingFleet,
    )

    spec_path, samples = build_artifacts(workdir)
    coord_dir = os.path.join(workdir, "coord")
    log_dir = os.path.join(workdir, "log")
    fleet = ServingFleet(
        coord_dir, 2, spec_path=spec_path, heartbeat_s=0.1,
        lease_s=0.75, poll_s=0.05, log_dir=log_dir,
    )
    t_boot = time.monotonic()
    fleet.start(wait_serving=True, timeout=300)
    boot_s = time.monotonic() - t_boot
    assert fleet.health()["live"] == 2, fleet.health()

    router = FleetRouter(
        coord_dir, lease_s=0.75, scan_interval_s=0.1, max_attempts=6,
        retry_base_delay_s=0.05,
        cache=ResponseCache(capacity=256, max_bytes=16 << 20),
    )

    # ---- phase 1: steady state + zero cross-tenant responses ----------
    per_tenant_model = {"acme": "m", "beta": "aux"}
    fresh = {}
    for tenant in ("acme", "beta"):
        raw = router.route(
            samples[0], tenant=tenant, deadline_s=REQUEST_DEADLINE_S,
            raw=True,
        )
        assert raw["model"] == per_tenant_model[tenant], raw
        fresh[tenant] = [np.asarray(h) for h in raw["heads"]]
    # different weights -> different numbers: a cross-tenant mixup would
    # be numerically visible, not just label-visible
    assert not np.allclose(fresh["acme"][0], fresh["beta"][0])
    rng = np.random.default_rng(3)
    for _ in range(20):
        tenant = ("acme", "beta")[int(rng.integers(2))]
        g = samples[int(rng.integers(len(samples)))]
        raw = router.route(
            g, tenant=tenant, deadline_s=REQUEST_DEADLINE_S, raw=True
        )
        assert raw["model"] == per_tenant_model[tenant], (
            f"CROSS-TENANT response: {tenant} got {raw['model']}"
        )

    # ---- phase 2: response-cache hit ratio climbs ---------------------
    snap0 = router.metrics.snapshot()
    repeats = 12
    for _ in range(repeats):
        heads = router.route(
            samples[0], tenant="beta", deadline_s=REQUEST_DEADLINE_S
        )
        for a, b in zip(heads, fresh["beta"]):
            assert np.array_equal(np.asarray(a), b), (
                "cache hit is not bitwise-equal to the fresh response"
            )
    snap1 = router.metrics.snapshot()
    new_hits = snap1["cache_hits_total"] - snap0["cache_hits_total"]
    assert new_hits >= repeats - 1, (snap0, snap1)
    ratio0 = snap0["cache_hits_total"] / max(
        snap0["cache_hits_total"] + snap0["cache_misses_total"], 1
    )
    ratio1 = snap1["cache_hits_total"] / max(
        snap1["cache_hits_total"] + snap1["cache_misses_total"], 1
    )
    assert ratio1 > ratio0, (ratio0, ratio1)

    # ---- phase 3: flood sheds ONLY the offender -----------------------
    stop = threading.Event()
    acme = {"ok": 0, "shed": 0, "failed": 0}
    lock = threading.Lock()

    def flood(seed):
        # every flooded graph gets a unique position jitter: a flood of
        # REPEATED graphs is absorbed by the response cache without ever
        # touching a replica (nice for the cache, useless for proving
        # quota isolation)
        frng = np.random.default_rng(seed)
        while not stop.is_set():
            g = copy.deepcopy(samples[int(frng.integers(len(samples)))])
            g.pos = (
                g.pos + frng.normal(scale=1e-3, size=g.pos.shape)
            ).astype(np.float32)
            try:
                router.route(g, tenant="acme",
                             deadline_s=REQUEST_DEADLINE_S)
                out = "ok"
            except ServerOverloaded:
                out = "shed"
            except Exception:
                out = "failed"
            with lock:
                acme[out] += 1

    floods = [
        threading.Thread(target=flood, args=(50 + i,), daemon=True)
        for i in range(FLOOD_CLIENTS)
    ]
    for t in floods:
        t.start()
    time.sleep(0.5)  # flood established
    beta_ok = beta_total = 0
    for _ in range(20):
        g = samples[int(rng.integers(len(samples)))]
        beta_total += 1
        raw = router.route(
            g, tenant="beta", deadline_s=REQUEST_DEADLINE_S, raw=True
        )
        assert raw["model"] == "aux", raw
        beta_ok += 1
    flood_window_shed = dict(acme)

    # ---- phase 4: shed pressure scales 2 -> 3, quiet shrinks 3 -> 2 ---
    scaler = FleetAutoscaler(
        fleet,
        signals=router.autoscale_signals,
        policy=AutoscalePolicy(
            min_replicas=2, max_replicas=3, capacity_rps=1e9,
            slo_budget=0.05, up_cooldown_s=0.0, down_cooldown_s=0.0,
            period_s=60.0, n_phases=6,
        ),
        interval_s=0.5,
    )
    scaler.tick()  # prime the counter baseline
    time.sleep(0.5)  # flood keeps shedding acme into the delta window
    decision = scaler.tick()
    assert decision is not None and decision["reason"] == "slo_pressure", (
        decision
    )
    assert fleet.target == 3, (decision, fleet.target)
    stop.set()
    for t in floods:
        t.join(timeout=60)
    assert acme["failed"] == 0, f"{acme['failed']} acme requests FAILED"
    assert flood_window_shed["shed"] > 0, flood_window_shed
    assert beta_ok == beta_total, (beta_ok, beta_total)
    tenant_shed = router.fleet_metrics.snapshot()["tenant_shed_total"]
    assert tenant_shed.get("tenant=acme", 0) > 0, tenant_shed
    assert "tenant=beta" not in tenant_shed, tenant_shed

    fleet.wait_serving(timeout=300)  # replica 2 boots + warms
    assert fleet.health()["live"] == 3, fleet.health()
    raw = router.route(
        samples[1], tenant="beta", deadline_s=REQUEST_DEADLINE_S, raw=True
    )
    assert raw["model"] == "aux", raw

    # quiet tail: zero-delta ticks decay the forecast to nothing and the
    # healthy fleet walks back down to min_replicas
    deadline = time.monotonic() + 120
    while time.monotonic() < deadline and fleet.target != 2:
        scaler.tick()
        time.sleep(0.3)
    assert fleet.target == 2, fleet.target
    deadline = time.monotonic() + 120
    while time.monotonic() < deadline and fleet.health()["live"] != 2:
        time.sleep(0.2)
    assert fleet.health()["live"] == 2, fleet.health()
    # the survivors still serve both tenants
    for tenant in ("acme", "beta"):
        raw = router.route(
            samples[2], tenant=tenant, deadline_s=REQUEST_DEADLINE_S,
            raw=True,
        )
        assert raw["model"] == per_tenant_model[tenant], raw

    # the load generator appends its cache ledger to the fleet stream
    # (the fleet_report pattern) so ops can replay hit-ratio history
    cs = router.cache.stats()
    fleet.emit(
        "cache_stats", hits=cs["hits"], misses=cs["misses"],
        evictions=cs["evictions"], bytes=cs["bytes"],
    )
    fleet.stop()

    # ---- phase 5: the event stream is schema-valid --------------------
    recs = validate_events(
        os.path.join(log_dir, "events.jsonl"),
        require=["tenant_admitted", "cache_stats", "fleet_scaled"],
    )
    admitted = {
        r["tenant"]: r for r in recs if r["event"] == "tenant_admitted"
    }
    assert set(admitted) == {"acme", "beta"}, admitted
    assert admitted["acme"]["quota"] == ACME_QUOTA, admitted
    assert admitted["beta"]["model"] == "aux", admitted
    scaled = [r for r in recs if r["event"] == "fleet_scaled"]
    transitions = [(r["old_target"], r["new_target"]) for r in scaled]
    assert (2, 3) in transitions and (3, 2) in transitions, transitions

    cache = router.metrics.snapshot()
    print(
        "multitenant smoke OK: boot {:.1f}s, {} acme flood attempts "
        "({} shed, 0 cross-tenant), beta {}/{} ok under flood, cache "
        "hit-ratio {:.2f}, scaled 2->3->2".format(
            boot_s, sum(acme.values()), acme["shed"], beta_ok,
            beta_total,
            cache["cache_hits_total"]
            / max(cache["cache_hits_total"] + cache["cache_misses_total"],
                  1),
        )
    )


if __name__ == "__main__":
    main(sys.argv[1])
