"""Worker + orchestration for the elastic kill-and-rejoin e2e (NOT a
pytest module — ``tests/test_elastic.py`` and the CI smoke drive it).

Three entry points:

    python _elastic_worker.py worker <workdir>
        The training payload one :class:`ElasticAgent` supervises: a small
        deterministic run through the REAL epoch driver with per-epoch
        resumable checkpoints and async checkpointing, heartbeat lease +
        peer watchdog from ``HYDRAGNN_ELASTIC_*`` env (set by the agent).
        Resumes from the rolling checkpoint whenever one exists — which is
        exactly what a respawn at a new world size does. Rank 0 activates
        run telemetry, so ``<workdir>/logs/elastic/events.jsonl`` carries
        the ``host_lost``/``world_resize`` record across generations, and
        writes ``result.json`` at clean completion.

    python _elastic_worker.py agent <workdir> <host> <n_hosts> <base_port>
        One per-host supervisor (``hydragnn_tpu.train.elastic.ElasticAgent``)
        wrapping the worker above.

    run_elastic(workdir, n_hosts, ...)
        Test-side helper: spawn the N agents, wait for all, return exit
        codes. Fault injection (e.g. ``HYDRAGNN_FAULT_LOSE_HOST_AT_STEP``)
        rides in via ``extra_env``.
"""

import json
import os
import subprocess
import sys

NUM_EPOCH = 8
LOG_NAME = "elastic"
# aggressive lease tuning: detection must outrun the (deliberately
# slowed) survivor finishing the whole run before the re-mesh happens
HEARTBEAT_S = "0.1"
LEASE_S = "0.75"


def _repo_root():
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---- training payload ------------------------------------------------------


def make_samples(num=24, seed=11):
    import numpy as np

    from hydragnn_tpu.data.dataobj import GraphData

    rng = np.random.default_rng(seed)
    out = []
    for _ in range(num):
        n = 6
        g = GraphData()
        g.x = rng.random((n, 1)).astype(np.float32)
        g.pos = rng.random((n, 3)).astype(np.float32)
        src = np.arange(n)
        dst = (src + 1) % n
        g.edge_index = np.stack(
            [np.concatenate([src, dst]), np.concatenate([dst, src])]
        ).astype(np.int64)
        g.edge_attr = None
        g.targets = [np.array([g.x.sum()], np.float32), g.x.copy()]
        g.target_types = ["graph", "node"]
        out.append(g)
    return out


def worker_main(workdir):
    # ONE virtual CPU device per process; must happen before backend init
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=1"
    ).strip()
    os.environ["JAX_PLATFORMS"] = "cpu"

    import jax

    jax.config.update("jax_platforms", "cpu")

    sys.path.insert(0, _repo_root())
    os.chdir(workdir)

    import numpy as np

    from hydragnn_tpu.obs import runtime as obs
    from hydragnn_tpu.parallel.distributed import setup_distributed
    from hydragnn_tpu.train import elastic
    from hydragnn_tpu.train.checkpoint import (
        checkpoint_exists,
        drain_async,
        load_state_dict,
        pop_train_meta,
        restore_into,
        rolling_checkpoints,
    )
    from hydragnn_tpu.train.epoch_driver import train_validate_test

    world, rank = setup_distributed()
    # the lease must exist before the (slow) build/compile below — a
    # compiling peer is not a dead peer
    rt = elastic.maybe_elastic()

    from hydragnn_tpu.data.loaders import GraphLoader, compute_layout
    from hydragnn_tpu.models.create import create_model_config
    from hydragnn_tpu.train.trainer import Trainer

    arch = {
        "model_type": "GIN",
        "input_dim": 1,
        "hidden_dim": 8,
        "num_conv_layers": 2,
        "output_dim": [1, 1],
        "output_type": ["graph", "node"],
        "output_heads": {
            "graph": {
                "num_sharedlayers": 1,
                "dim_sharedlayers": 8,
                "num_headlayers": 1,
                "dim_headlayers": [8],
            },
            "node": {
                "num_headlayers": 1,
                "dim_headlayers": [8],
                "type": "mlp",
            },
        },
        "task_weights": [1.0, 1.0],
    }
    training = {
        "num_epoch": NUM_EPOCH,
        "Optimizer": {"type": "AdamW", "learning_rate": 1e-2},
        "resume_every": 1,
        # retain every epoch: the e2e compares against the exact rolling
        # checkpoint the resized world resumed from
        "checkpoint_keep_last": NUM_EPOCH + 2,
        "async_checkpoint": True,
    }
    samples = make_samples()
    layout = compute_layout([samples], batch_size=4, need_triplets=False)
    # per-process batch shards rebalance by (rank, world) — the loaders'
    # DistributedSampler semantics; a re-meshed world re-derives them
    train_loader = GraphLoader(samples[:16], 4, layout, shuffle=True, seed=7)
    val_loader = GraphLoader(samples[16:20], 4, layout, shuffle=False)
    test_loader = GraphLoader(samples[20:], 4, layout, shuffle=False)
    model = create_model_config(arch)
    # mesh=None: each process trains its local shard on its own device.
    # The CPU PJRT backend has no cross-process XLA collectives
    # ("Multiprocess computations aren't implemented on the CPU backend"
    # — the same limitation tests/test_multiprocess.py documents), and
    # the elasticity machinery under test — jax.distributed bootstrap,
    # heartbeat lease, watchdog, agent re-mesh, checkpoint resume, shard
    # rebalance — is identical either way; on TPU the worker would hand
    # the Trainer the global mesh exactly as the driver does.
    trainer = Trainer(model, training, mesh=None)
    state = trainer.init_state(next(iter(train_loader)), seed=0)

    # all ranks: rank 0 gets the full events.jsonl stream, the other
    # hosts get per-host events-host<k>.jsonl streams (elastic mode) so
    # the fleet rollup sees every host's record
    telemetry = obs.init_run_telemetry(
        {"NeuralNetwork": {"Training": training}}, LOG_NAME
    )

    # start-aligned epoch 0: the coordination-service barrier (plain RPC,
    # no XLA collective — works on every backend) removes the multi-second
    # process-startup skew, so a fault at rank K's step N lands while the
    # other ranks are near step N too. On real accelerators the first
    # cross-host collective provides this alignment for free.
    if world > 1:
        try:
            from jax._src import distributed as _dist

            if _dist.global_state.client is not None:
                _dist.global_state.client.wait_at_barrier(
                    "hydragnn_elastic_start", 120_000
                )
        except Exception:
            pass

    # resume whenever a checkpoint (or an intact rolling fallback) exists:
    # gen 0 restarts and post-resize respawns share this one path
    resume_meta = None
    if checkpoint_exists(LOG_NAME) or rolling_checkpoints(LOG_NAME):
        restored = load_state_dict(LOG_NAME)
        resume_meta = pop_train_meta(restored)
        state = trainer.place_state(restore_into(state, restored))

    epochs_run = []
    orig = trainer.train_epoch

    def counting_train_epoch(state, loader, rng):
        epochs_run.append(loader.epoch)
        return orig(state, loader, rng)

    trainer.train_epoch = counting_train_epoch

    config_nn = {
        "Training": training,
        "Variables_of_interest": {"output_names": ["sum", "x"]},
    }
    try:
        state = train_validate_test(
            trainer, state, train_loader, val_loader, test_loader,
            config_nn, LOG_NAME, verbosity=0, resume_meta=resume_meta,
        )
        drain_async()
    finally:
        if rt is not None:
            rt.stop()

    if rank == 0:
        from hydragnn_tpu.train.optimizer import get_learning_rate

        result = {
            "world": world,
            "rank": rank,
            "gen": int(os.getenv("HYDRAGNN_ELASTIC_GEN", "0")),
            "resumed_from_epoch": (
                None if resume_meta is None else int(resume_meta["epoch"]) + 1
            ),
            "epochs_run": epochs_run,
            "final_lr": get_learning_rate(state.opt_state),
            "final_params_digest": [
                float(np.asarray(leaf, np.float64).sum())
                for leaf in jax.tree_util.tree_leaves(
                    jax.device_get(state.params)
                )
            ],
        }
        with open("result.json", "w") as f:
            json.dump(result, f)
    if telemetry is not None:
        obs.deactivate(status="complete")


# ---- agent + orchestration -------------------------------------------------


def agent_main(workdir, host, n_hosts, base_port):
    sys.path.insert(0, _repo_root())

    from hydragnn_tpu.train.elastic import ElasticAgent

    agent = ElasticAgent(
        [sys.executable, os.path.abspath(__file__), "worker", workdir],
        coord_dir=os.path.join(workdir, "elastic-coord"),
        host=int(host),
        n_hosts=int(n_hosts),
        base_port=int(base_port),
        heartbeat_s=float(os.getenv("HYDRAGNN_ELASTIC_HEARTBEAT_S",
                                    HEARTBEAT_S)),
        lease_s=float(os.getenv("HYDRAGNN_ELASTIC_LEASE_S", LEASE_S)),
    )
    return agent.run()


def run_elastic(workdir, n_hosts=2, base_port=None, extra_env=None,
                timeout=360):
    """Spawn ``n_hosts`` agents over one shared workdir; wait for all.

    Returns ``{host: returncode}``. The training run's artifacts land in
    ``<workdir>/logs/elastic/`` (checkpoints, events.jsonl, result.json
    at ``<workdir>/result.json``)."""
    import socket

    if base_port is None:
        # a port whose gen-indexed successors are also free enough in
        # practice; bind port 0 once to land in the ephemeral range
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            base_port = s.getsockname()[1]
    env = {
        k: v
        for k, v in os.environ.items()
        if not k.startswith(("HYDRAGNN_FAULT_", "HYDRAGNN_ELASTIC_",
                             "HYDRAGNN_TPU_", "HYDRAGNN_RESUME",
                             "HYDRAGNN_CKPT_", "HYDRAGNN_ASYNC"))
    }
    env.update(
        HYDRAGNN_ELASTIC_HEARTBEAT_S=HEARTBEAT_S,
        HYDRAGNN_ELASTIC_LEASE_S=LEASE_S,
    )
    env.update(extra_env or {})
    procs = {}
    for host in range(n_hosts):
        procs[host] = subprocess.Popen(
            [
                sys.executable, os.path.abspath(__file__), "agent",
                workdir, str(host), str(n_hosts), str(base_port),
            ],
            env=env,
        )
    rcs = {}
    try:
        for host, p in procs.items():
            rcs[host] = p.wait(timeout=timeout)
    finally:
        for p in procs.values():
            if p.poll() is None:
                p.kill()
    return rcs


if __name__ == "__main__":
    mode = sys.argv[1]
    if mode == "worker":
        worker_main(sys.argv[2])
    elif mode == "agent":
        raise SystemExit(
            agent_main(sys.argv[2], sys.argv[3], sys.argv[4], sys.argv[5])
        )
    else:
        raise SystemExit(f"unknown mode {mode!r}")
