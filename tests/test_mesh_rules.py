"""Partition-rule engine, 2-D mesh derivation, and collective accounting.

Units for ``parallel/rules.py`` (regex -> placement, fail-loud unmatched,
divisibility fallback, ZeRO overlay), ``parallel/mesh.py`` (best-fit
(d, m) factorization — the elastic re-mesh rule — and the
``shard_over_data_axis`` shim fix), and ``parallel/collectives.py`` (HLO
collective bytes attributed to mesh axes).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from hydragnn_tpu.parallel import rules as prules
from hydragnn_tpu.parallel.mesh import (
    best_mesh_shape,
    data_axis_multiple,
    make_mesh,
    make_mesh2d,
    mesh_shape_list,
    set_active_mesh,
    shard_over_data_axis,
)


def _mesh2d(d=4, m=2):
    return make_mesh2d(d, m)


# ---- rule matching --------------------------------------------------------


def pytest_rules_kernel_cols_bias_replicated():
    mesh = _mesh2d()
    tree = {
        "lin": {"kernel": np.zeros((16, 8), np.float32),
                "bias": np.zeros((8,), np.float32)},
        "bn": {"scale": np.zeros((16,), np.float32),
               "mean": np.zeros((16,), np.float32)},
    }
    sh = prules.match_partition_rules(tree, mesh)
    assert tuple(sh["lin"]["kernel"].spec) == (None, "model")
    assert tuple(sh["lin"]["bias"].spec) == ()
    assert tuple(sh["bn"]["scale"].spec) == ()
    assert tuple(sh["bn"]["mean"].spec) == ()


def pytest_rules_rank3_kernel_shards_last_dim():
    """MLPNode stacked heads: kernel_0 is [1, in, out] — the cols action
    must land on the LAST dim regardless of rank."""
    mesh = _mesh2d()
    tree = {"head": {"kernel_0": np.zeros((1, 16, 8), np.float32),
                     "bias_0": np.zeros((1, 8), np.float32)}}
    sh = prules.match_partition_rules(tree, mesh)
    assert tuple(sh["head"]["kernel_0"].spec) == (None, None, "model")
    assert tuple(sh["head"]["bias_0"].spec) == ()


def pytest_rules_divisibility_fallback():
    """A matched kernel whose output dim does not divide the model axis
    replicates instead of erroring (uneven device_put is a hard error in
    jax) — the fallback is visible in the summary."""
    mesh = _mesh2d(4, 2)
    tree = {"pre_nn": {"kernel": np.zeros((6, 3), np.float32)}}
    sh = prules.match_partition_rules(tree, mesh)
    assert tuple(sh["pre_nn"]["kernel"].spec) == ()


def pytest_rules_unmatched_fails_loudly():
    mesh = _mesh2d()
    tree = {"mystery_weight": np.zeros((16, 8), np.float32)}
    with pytest.raises(ValueError, match="mystery_weight"):
        prules.match_partition_rules(tree, mesh)
    # non-strict: replicates instead
    sh = prules.match_partition_rules(tree, mesh, strict=False)
    assert tuple(sh["mystery_weight"].spec) == ()


def pytest_state_shardings_lenient_on_data_only_mesh():
    """Strictness is load-bearing only where placement has a choice: an
    unknown param name on a pure 1-D data mesh replicates (a working
    config must not break), while the same state on a model-axis mesh
    raises."""
    from flax import struct

    class FakeState(struct.PyTreeNode):
        params: dict
        batch_stats: dict
        opt_state: dict
        step: jnp.ndarray

    state = FakeState(
        params={"mystery_weight": np.zeros((16, 8), np.float32)},
        batch_stats={}, opt_state={}, step=jnp.zeros((), jnp.int32),
    )
    sh = prules.state_shardings(state, make_mesh(), zero_stage=0)
    assert tuple(sh.params["mystery_weight"].spec) == ()
    with pytest.raises(ValueError, match="mystery_weight"):
        prules.state_shardings(state, _mesh2d(), zero_stage=0)


def pytest_rules_scalars_skip_matching():
    """Scalars/size-1 leaves never consult the rules (so GIN's eps and
    optax's count need no entry)."""
    mesh = _mesh2d()
    tree = {"eps": np.zeros((), np.float32),
            "count": np.zeros((1,), np.int32)}
    sh = prules.match_partition_rules(tree, mesh)
    assert tuple(sh["eps"].spec) == ()
    assert tuple(sh["count"].spec) == ()


def pytest_rules_explicit_spec_exceeding_rank_replicates():
    """An explicit PartitionSpec rule longer than a matched leaf's rank
    falls back to replication (the 'matched leaves never error'
    contract) instead of raising out of place_state."""
    mesh = _mesh2d()
    tree = {"att": np.zeros((128,), np.float32),
            "w": np.zeros((16, 8), np.float32)}
    table = ((r"(^|/)(att|w)$", P(None, "model")),)
    sh = prules.match_partition_rules(tree, mesh, rules=table)
    assert tuple(sh["att"].spec) == ()          # rank 1 < spec rank 2
    assert tuple(sh["w"].spec) == (None, "model")


def pytest_rules_config_override_precedes_defaults():
    mesh = _mesh2d()
    tree = {"lin": {"kernel": np.zeros((16, 8), np.float32)}}
    table = prules.resolve_rules(
        {"partition_rules": [[r"(^|/)kernel$", "replicate"]]}
    )
    sh = prules.match_partition_rules(tree, mesh, rules=table)
    assert tuple(sh["lin"]["kernel"].spec) == ()
    with pytest.raises(ValueError, match="unknown action"):
        prules.resolve_rules({"partition_rules": [["x", "diagonal"]]})


def pytest_rules_zero_overlay_composes_with_model_axis():
    """ZeRO's data overlay lands on dim 0 ON TOP of the model spec:
    P('data', 'model') for a divisible kernel moment."""
    from flax import struct

    class FakeState(struct.PyTreeNode):
        params: dict
        batch_stats: dict
        opt_state: dict
        step: jnp.ndarray

    mesh = _mesh2d(4, 2)
    state = FakeState(
        params={"lin": {"kernel": np.zeros((16, 8), np.float32),
                        "bias": np.zeros((8,), np.float32)}},
        batch_stats={},
        opt_state={"mu": {"lin": {"kernel": np.zeros((16, 8), np.float32),
                                  "bias": np.zeros((8,), np.float32)}}},
        step=jnp.zeros((), jnp.int32),
    )
    sh = prules.state_shardings(state, mesh, zero_stage=1)
    assert tuple(sh.opt_state["mu"]["lin"]["kernel"].spec) == ("data", "model")
    assert tuple(sh.opt_state["mu"]["lin"]["bias"].spec) == ()
    assert tuple(sh.params["lin"]["kernel"].spec) == (None, "model")
    sh3 = prules.state_shardings(state, mesh, zero_stage=3)
    assert tuple(sh3.params["lin"]["kernel"].spec) == ("data", "model")


def pytest_summarize_shardings_counts_bytes():
    mesh = _mesh2d()
    tree = {"lin": {"kernel": np.zeros((16, 8), np.float32),
                    "bias": np.zeros((8,), np.float32)}}
    sh = prules.match_partition_rules(tree, mesh)
    s = prules.summarize_shardings(tree, sh)
    assert s["total_leaves"] == 2
    assert s["sharded"] == 1 and s["replicated"] == 1
    assert s["sharded_bytes"] == 16 * 8 * 4
    assert s["replicated_bytes"] == 8 * 4
    assert s["axis_bytes"] == {"model": 16 * 8 * 4}


@pytest.mark.slow
def pytest_rules_cover_entire_model_zoo():
    """Strict matching over EVERY stack's full TrainState: a parameter
    name outside the rule table raises at place_state, so this test is
    the tripwire that keeps the table complete as models grow.
    slow-marked (9 model inits); tier-1 still exercises strict matching
    through every mesh-trainer test and the driver e2e runs."""
    import optax

    from hydragnn_tpu.models.create import (
        create_model_config,
        init_model_params,
    )
    from hydragnn_tpu.train.common import TrainState
    from test_models_forward import arch_config, make_batch

    mesh = _mesh2d(4, 2)
    for model_type in (
        "PNA", "GIN", "SAGE", "MFC", "CGCNN", "GAT", "SchNet", "EGNN",
        "DimeNet",
    ):
        batch = make_batch(with_triplets=model_type == "DimeNet")
        model = create_model_config(arch_config(model_type))
        variables = init_model_params(model, batch, seed=0)
        tx = optax.adamw(1e-3)
        state = TrainState(
            params=variables["params"],
            batch_stats=variables.get("batch_stats", {}),
            opt_state=tx.init(variables["params"]),
            step=jnp.zeros((), jnp.int32),
        )
        # strict=True: raises listing offenders if the table has a hole
        sh = prules.state_shardings(state, mesh, zero_stage=0)
        assert jax.tree_util.tree_structure(sh) == (
            jax.tree_util.tree_structure(
                state,
            )
        ), model_type


# ---- shard_over_data_axis shim fix ---------------------------------------


def pytest_shim_divisible_bias_no_longer_shards():
    """THE satellite fix: a size-8 bias on an 8-way data mesh used to
    shard silently (dim 0 divides the axis); the rule-engine route
    replicates it while kernels still shard."""
    mesh = make_mesh()  # 1-D ("data",) over all 8 devices
    tree = {"lin": {"kernel": np.ones((16, 4), np.float32),
                    "bias": np.ones((8,), np.float32)}}
    placed = shard_over_data_axis(tree, mesh)
    assert tuple(placed["lin"]["kernel"].sharding.spec) == ("data",)
    assert tuple(placed["lin"]["bias"].sharding.spec) == ()
    # and values are untouched
    np.testing.assert_array_equal(
        np.asarray(placed["lin"]["kernel"]), tree["lin"]["kernel"]
    )


def pytest_shim_respects_replicate_rule_names():
    mesh = make_mesh()
    # a 2-D leaf with a replicate-rule NAME (batch-norm scale stacked
    # per-layer) stays replicated even though dim 0 divides
    tree = {"bn": {"scale": np.ones((8, 16), np.float32)}}
    placed = shard_over_data_axis(tree, mesh)
    assert tuple(placed["bn"]["scale"].sharding.spec) == ()


# ---- best-fit mesh derivation (the elastic re-mesh rule) ------------------


def pytest_best_mesh_shape_table():
    assert best_mesh_shape(8, 1) == (8, 1)
    assert best_mesh_shape(8, 2) == (4, 2)
    assert best_mesh_shape(8, 4) == (2, 4)
    assert best_mesh_shape(8, 8) == (1, 8)
    # a shrunken world KEEPS the model width and drops data replicas
    assert best_mesh_shape(7, 2) == (3, 2)
    assert best_mesh_shape(5, 4) == (1, 4)
    # degenerate corners
    assert best_mesh_shape(1, 8) == (1, 1)
    assert best_mesh_shape(3, 0) == (3, 1)


def pytest_mesh_shape_list_and_active_multiple():
    mesh = _mesh2d(4, 2)
    assert mesh_shape_list(mesh) == [4, 2]
    assert mesh_shape_list(None) is None
    try:
        set_active_mesh(mesh)
        assert data_axis_multiple() == 4
        set_active_mesh(None)
        assert data_axis_multiple() == jax.device_count()
    finally:
        set_active_mesh(None)


def pytest_requested_mesh_env_and_config(monkeypatch):
    from hydragnn_tpu.parallel.mesh import requested_mesh

    monkeypatch.delenv("HYDRAGNN_MESH", raising=False)
    assert requested_mesh({"model_parallel": 2}) == (None, 2)
    assert requested_mesh({"mesh_shape": [4, 2]}) == (4, 2)
    assert requested_mesh({}) == (None, 1)
    monkeypatch.setenv("HYDRAGNN_MESH", "2,4")
    assert requested_mesh({"model_parallel": 8}) == (2, 4)  # env wins
    monkeypatch.setenv("HYDRAGNN_MESH", "4")
    assert requested_mesh(None) == (None, 4)
    monkeypatch.setenv("HYDRAGNN_MESH", "banana")
    with pytest.raises(ValueError, match="HYDRAGNN_MESH"):
        requested_mesh(None)
    monkeypatch.delenv("HYDRAGNN_MESH")
    with pytest.raises(ValueError, match="mesh_shape"):
        requested_mesh({"mesh_shape": [8]})  # [d, m] typo'd to one entry


def pytest_env_mesh_names_the_variable(monkeypatch):
    """HYDRAGNN_MESH parsing routes through utils/envparse.env_mesh: a
    malformed value errors naming the VARIABLE and the offending text,
    never a bare int() ValueError from inside resolve_mesh."""
    from hydragnn_tpu.parallel.mesh import requested_mesh
    from hydragnn_tpu.utils.envparse import env_mesh

    monkeypatch.delenv("HYDRAGNN_MESH", raising=False)
    assert env_mesh("HYDRAGNN_MESH") is None
    monkeypatch.setenv("HYDRAGNN_MESH", "  ")
    assert env_mesh("HYDRAGNN_MESH") is None
    monkeypatch.setenv("HYDRAGNN_MESH", "4,2")
    assert env_mesh("HYDRAGNN_MESH") == (4, 2)
    monkeypatch.setenv("HYDRAGNN_MESH", " 2 ")
    assert env_mesh("HYDRAGNN_MESH") == (None, 2)
    for bad in ("4x2", "4,2,1", "4,", "a,b", "0,2", "-1"):
        monkeypatch.setenv("HYDRAGNN_MESH", bad)
        with pytest.raises(ValueError) as e:
            requested_mesh(None)
        # names the variable AND the offending text
        assert "HYDRAGNN_MESH" in str(e.value) and bad in str(e.value)


def pytest_resolve_mesh_re_derives_oversized_request(monkeypatch):
    """A requested shape that no longer fits the visible devices (the
    elastic-shrink scenario) re-derives via best_mesh_shape instead of
    failing — on this 8-device host, 16,2 -> (4, 2)."""
    from hydragnn_tpu.parallel.mesh import resolve_mesh

    monkeypatch.setenv("HYDRAGNN_MESH", "16,2")
    try:
        mesh = resolve_mesh({})
        assert mesh_shape_list(mesh) == [4, 2]
    finally:
        set_active_mesh(None)


# ---- collective-bytes HLO accounting -------------------------------------


def pytest_collective_bytes_attributed_per_axis():
    from hydragnn_tpu.parallel.collectives import collective_bytes_by_axis

    mesh = _mesh2d(4, 2)
    x_sh = jax.sharding.NamedSharding(mesh, P("data"))
    w_sh = jax.sharding.NamedSharding(mesh, P(None, "model"))
    rep = jax.sharding.NamedSharding(mesh, P())

    def f(x, w):
        loss = ((x @ w) ** 2).mean()
        g = jax.grad(lambda w: ((x @ w) ** 2).mean())(w)
        return loss, g

    jf = jax.jit(f, in_shardings=(x_sh, w_sh), out_shardings=(rep, w_sh))
    x = jax.device_put(jnp.ones((16, 8)), x_sh)
    w = jax.device_put(jnp.ones((8, 4)), w_sh)
    compiled = jf.lower(x, w).compile()
    out = collective_bytes_by_axis(compiled.as_text(), ("data", "model"), (4, 2))
    # the dW contraction all-reduces over data; the mean over model —
    # both axes must carry bytes, and nothing lands in "other"
    assert out.get("data", 0) > 0, out
    assert out.get("model", 0) > 0, out
    assert "other" not in out, out


def pytest_collective_bytes_group_formats():
    from hydragnn_tpu.parallel.collectives import (
        classify_groups,
        collective_bytes_by_axis,
    )

    # explicit groups, stride-m = data axis on a (4, 2) mesh
    assert classify_groups(
        [(0, 2, 4, 6), (1, 3, 5, 7)], ("data", "model"), (4, 2)
    ) == "data"
    # consecutive runs of m = model axis
    assert classify_groups(
        [(0, 1), (2, 3), (4, 5), (6, 7)], ("data", "model"), (4, 2)
    ) == "model"
    # one full-mesh group on a genuinely 2-D mesh is a global reduce
    assert classify_groups(
        [tuple(range(8))], ("data", "model"), (4, 2)
    ) == "global"
    # ... but IS the data axis when model is degenerate
    assert classify_groups(
        [tuple(range(8))], ("data", "model"), (8, 1)
    ) == "data"
    # iota spelling, bytes summed from the result type
    hlo = (
        "%ar = f32[2,8]{1,0} all-reduce(f32[2,8]{1,0} %dot), channel_id=3,"
        " replica_groups=[4,2]<=[8], use_global_device_ids=true"
    )
    out = collective_bytes_by_axis(hlo, ("data", "model"), (4, 2))
    assert out == {"model": 2 * 8 * 4}
    # transposed iota = data axis
    hlo_t = (
        "%ar = bf16[4]{0} all-reduce(bf16[4]{0} %v), channel_id=1,"
        " replica_groups=[2,4]<=[4,2]T(1,0), use_global_device_ids=true"
    )
    out = collective_bytes_by_axis(hlo_t, ("data", "model"), (4, 2))
    assert out == {"data": 4 * 2}
    # -done lines of async pairs are not double counted
    hlo_async = (
        "%s = f32[4]{0} all-reduce-start(f32[4]{0} %v),"
        " replica_groups=[4,2]<=[8]\n"
        "%d = f32[4]{0} all-reduce-done(f32[4]{0} %s)"
    )
    out = collective_bytes_by_axis(hlo_async, ("data", "model"), (4, 2))
    assert out == {"model": 16}
    # async TUPLE type (operand, result): only the result half counts —
    # else async vs sync spellings of the same collective diverge
    hlo_tuple = (
        "%ag = (f32[8,4]{1,0}, f32[16,4]{1,0}) all-gather-start("
        "f32[8,4]{1,0} %v), replica_groups=[4,2]<=[8], dimensions={0}"
    )
    out = collective_bytes_by_axis(hlo_tuple, ("data", "model"), (4, 2))
    assert out == {"model": 16 * 4 * 4}


def pytest_resolve_mesh_honors_explicit_1d_width(monkeypatch):
    """HYDRAGNN_MESH='4,1' pins a 4-device 1-D mesh — an explicit narrow
    layout must not silently widen to every device."""
    from hydragnn_tpu.parallel.mesh import resolve_mesh

    monkeypatch.setenv("HYDRAGNN_MESH", "4,1")
    try:
        mesh = resolve_mesh({})
        assert tuple(mesh.axis_names) == ("data",)
        assert mesh.shape["data"] == 4
    finally:
        set_active_mesh(None)
