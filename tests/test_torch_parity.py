"""Seeded-trajectory numerics parity vs eager PyTorch (round-4 verdict
item 9): the strongest real-data-free numerics evidence available in this
container.

A tiny SchNet energy+forces multi-head model (north-star config 2's shape:
graph energy head + 3-dim node forces head) is trained for a few hundred
AdamW steps TWICE from the SAME weights on the SAME batch — once through
this framework's jitted train step, once through an eager-PyTorch
re-implementation of the identical math (reference execution style:
per-op dispatch, index_add_ scatters — ``hydragnn/models/SCFStack.py``,
``train/train_validate_test.py``). Weights are copied jax -> torch, so any
divergence is numerics, not initialization. Losses must agree per step to
float32 tolerance, with only slow drift from differing contraction orders.
"""

import math

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax

from hydragnn_tpu.graph import collate_graphs, pad_sizes_for
from hydragnn_tpu.models import create_model_config, init_model_params
from hydragnn_tpu.train.optimizer import select_optimizer

HIDDEN = 16
FWIDTH = 16  # filters == gaussians (sidesteps the reference's positional swap)
CUTOFF = 2.0
STEPS = 200


def _arch():
    return {
        "model_type": "SchNet",
        "input_dim": 1,
        "hidden_dim": HIDDEN,
        "output_dim": [1, 3],
        "output_type": ["graph", "node"],
        "output_heads": {
            "graph": {
                "num_sharedlayers": 2,
                "dim_sharedlayers": 8,
                "num_headlayers": 2,
                "dim_headlayers": [8, 8],
            },
            "node": {
                "num_headlayers": 2,
                "dim_headlayers": [8, 8],
                "type": "mlp",
            },
        },
        "task_weights": [1.0, 1.0],
        "num_conv_layers": 2,
        "num_nodes": 8,
        "edge_dim": None,
        "num_gaussians": FWIDTH,
        "num_filters": FWIDTH,
        "radius": CUTOFF,
        "equivariance": False,
        "max_neighbours": 10,
    }


def _samples(num=6):
    rng = np.random.default_rng(11)

    class S:
        pass

    out = []
    for _ in range(num):
        n = int(rng.integers(4, 9))
        s = S()
        s.x = rng.random((n, 1)).astype(np.float32)
        s.pos = (rng.random((n, 3)) * 1.2).astype(np.float32)
        src = np.repeat(np.arange(n), 2)
        dst = (src + rng.integers(1, n, src.shape[0])) % n
        s.edge_index = np.stack(
            [np.concatenate([src, dst]), np.concatenate([dst, src])]
        ).astype(np.int64)
        s.edge_attr = None
        # energy: sum of features; forces: smooth function of geometry
        center = s.pos - s.pos.mean(0)
        s.targets = [
            np.array([s.x.sum()], np.float32),
            (0.3 * center * s.x).astype(np.float32),
        ]
        out.append(s)
    return out


def _jax_losses(samples, steps):
    batch = collate_graphs(
        samples,
        *pad_sizes_for(8, 32, len(samples)),
        head_types=("graph", "node"),
        head_dims=(1, 3),
    )
    batch = jax.tree_util.tree_map(jnp.asarray, batch)
    model = create_model_config(_arch())
    variables = init_model_params(model, batch)
    params = variables["params"]
    opt = select_optimizer(
        {"Optimizer": {"type": "AdamW", "learning_rate": 1e-3}}
    )
    opt_state = opt.init(params)

    @jax.jit
    def step(params, opt_state):
        def loss_fn(p):
            outputs = model.apply({"params": p}, batch, train=False)
            tot, _ = model.loss(outputs, batch)
            return tot

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = opt.update(grads, opt_state, params)
        return loss, optax.apply_updates(params, updates), opt_state

    losses = []
    for _ in range(steps):
        loss, params, opt_state = step(params, opt_state)
        losses.append(float(loss))
    return variables, np.asarray(losses)


def _torch_losses(variables, samples, steps):
    import torch

    p = jax.tree_util.tree_map(
        lambda a: torch.tensor(np.asarray(a)), variables["params"]
    )
    xs, eis, gids, y_g, y_n, poss = [], [], [], [], [], []
    off = 0
    for g, s in enumerate(samples):
        xs.append(s.x)
        poss.append(s.pos)
        eis.append(s.edge_index + off)
        gids.append(np.full(s.x.shape[0], g))
        y_g.append(s.targets[0])
        y_n.append(s.targets[1])
        off += s.x.shape[0]
    x0 = torch.tensor(np.concatenate(xs))
    pos = torch.tensor(np.concatenate(poss))
    ei = torch.tensor(np.concatenate(eis, axis=1))
    gid = torch.tensor(np.concatenate(gids), dtype=torch.long)
    yg = torch.tensor(np.stack(y_g))
    yn = torch.tensor(np.concatenate(y_n))
    N, G = x0.shape[0], len(samples)
    send, recv = ei[0], ei[1]

    offset = torch.linspace(0.0, CUTOFF, FWIDTH)
    coeff = -0.5 / float(offset[1] - offset[0]) ** 2

    leaves = []

    def P(a):
        t = a.clone().detach().requires_grad_(True)
        leaves.append(t)
        return t

    convs = []
    for i in range(2):
        c = {k: v for k, v in p[f"encoder_conv_{i}"].items()}
        convs.append(
            {
                "f0k": P(c["filter_0"]["kernel"]),
                "f0b": P(c["filter_0"]["bias"]),
                "f1k": P(c["filter_1"]["kernel"]),
                "f1b": P(c["filter_1"]["bias"]),
                "lin1": P(c["lin1"]),
                "lin2": P(c["lin2"]),
                "bias2": P(c["bias2"]),
            }
        )
    gs = [
        (P(p["graph_shared"][f"TorchLinear_{i}"]["kernel"]),
         P(p["graph_shared"][f"TorchLinear_{i}"]["bias"]))
        for i in range(2)
    ]
    hg = [
        (P(p["head_0_graph"][f"TorchLinear_{i}"]["kernel"]),
         P(p["head_0_graph"][f"TorchLinear_{i}"]["bias"]))
        for i in range(3)
    ]
    hn = [
        (P(p["head_1_node"][f"kernel_{i}"][0]),
         P(p["head_1_node"][f"bias_{i}"][0]))
        for i in range(3)
    ]

    def ssp(v):
        return torch.nn.functional.softplus(v) - math.log(2.0)

    def forward():
        h = x0
        for c in convs:
            d = pos[send] - pos[recv]
            ew = d.pow(2).sum(-1).sqrt()
            ea = torch.exp(coeff * (ew[:, None] - offset) ** 2)
            w = ssp(ea @ c["f0k"] + c["f0b"]) @ c["f1k"] + c["f1b"]
            w = w * (0.5 * (torch.cos(ew * math.pi / CUTOFF) + 1.0))[:, None]
            hh = h @ c["lin1"]
            aggr = torch.zeros(N, w.shape[1]).index_add_(
                0, recv, hh[send] * w
            )
            h = torch.relu(aggr @ c["lin2"] + c["bias2"])
        cnt = torch.zeros(G).index_add_(0, gid, torch.ones(N))
        pooled = torch.zeros(G, HIDDEN).index_add_(0, gid, h) / cnt[:, None]
        sg = pooled
        for k, b in gs:
            sg = torch.relu(sg @ k + b)
        og = sg
        for i, (k, b) in enumerate(hg):
            og = og @ k + b
            if i < 2:
                og = torch.relu(og)
        on = h
        for i, (k, b) in enumerate(hn):
            on = on @ k + b
            if i < 2:
                on = torch.relu(on)
        return og, on

    opt = torch.optim.AdamW(leaves, lr=1e-3, eps=1e-8, weight_decay=0.01)
    losses = []
    for _ in range(steps):
        opt.zero_grad()
        og, on = forward()
        loss = 0.5 * torch.nn.functional.mse_loss(og, yg) + \
            0.5 * torch.nn.functional.mse_loss(on, yn)
        loss.backward()
        opt.step()
        losses.append(float(loss))
    return np.asarray(losses)


def pytest_schnet_seeded_trajectory_matches_torch():
    samples = _samples()
    variables, ours = _jax_losses(samples, STEPS)
    theirs = _torch_losses(variables, samples, STEPS)
    # identical math, different contraction order: tight at the start,
    # bounded slow drift over hundreds of steps
    rel = np.abs(ours - theirs) / np.maximum(np.abs(theirs), 1e-8)
    assert rel[:20].max() < 1e-4, f"early divergence: {rel[:20].max()}"
    assert rel.max() < 5e-3, f"trajectory drift: {rel.max()} at {rel.argmax()}"
    # and the trajectory actually trains (not a frozen fixed point)
    assert ours[-1] < 0.5 * ours[0]


# ---- EGNN (north-star config 4's model: equivariant coord channel) ------

EG_IN = 4  # [z-like, centered coords] — the MPtrj feature layout


def _egnn_arch():
    return {
        "model_type": "EGNN",
        "input_dim": EG_IN,
        "hidden_dim": HIDDEN,
        "output_dim": [1, 3],
        "output_type": ["graph", "node"],
        "output_heads": {
            "graph": {
                "num_sharedlayers": 2,
                "dim_sharedlayers": 8,
                "num_headlayers": 2,
                "dim_headlayers": [8, 8],
            },
            "node": {
                "num_headlayers": 2,
                "dim_headlayers": [8, 8],
                "type": "mlp",
            },
        },
        "task_weights": [1.0, 1.0],
        "num_conv_layers": 2,
        "num_nodes": 8,
        "edge_dim": None,
        "radius": CUTOFF,
        "equivariance": True,
        "max_neighbours": 10,
    }


def _egnn_samples(num=6):
    rng = np.random.default_rng(23)

    class S:
        pass

    out = []
    for _ in range(num):
        n = int(rng.integers(4, 9))
        s = S()
        pos = (rng.random((n, 3)) * 1.2).astype(np.float32)
        center = pos - pos.mean(0)
        s.pos = pos
        s.x = np.concatenate(
            [rng.random((n, 1)).astype(np.float32), center], axis=1
        )
        src = np.repeat(np.arange(n), 2)
        dst = (src + rng.integers(1, n, src.shape[0])) % n
        s.edge_index = np.stack(
            [np.concatenate([src, dst]), np.concatenate([dst, src])]
        ).astype(np.int64)
        s.edge_attr = None
        s.targets = [
            np.array([s.x[:, 0].sum()], np.float32),
            (0.3 * center * s.x[:, :1]).astype(np.float32),
        ]
        out.append(s)
    return out


def _egnn_jax_losses(samples, steps):
    batch = collate_graphs(
        samples,
        *pad_sizes_for(8, 32, len(samples)),
        head_types=("graph", "node"),
        head_dims=(1, 3),
    )
    batch = jax.tree_util.tree_map(jnp.asarray, batch)
    model = create_model_config(_egnn_arch())
    variables = init_model_params(model, batch)
    params = variables["params"]
    opt = select_optimizer(
        {"Optimizer": {"type": "AdamW", "learning_rate": 1e-3}}
    )
    opt_state = opt.init(params)

    @jax.jit
    def step(params, opt_state):
        def loss_fn(p):
            outputs = model.apply({"params": p}, batch, train=False)
            tot, _ = model.loss(outputs, batch)
            return tot

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = opt.update(grads, opt_state, params)
        return loss, optax.apply_updates(params, updates), opt_state

    losses = []
    for _ in range(steps):
        loss, params, opt_state = step(params, opt_state)
        losses.append(float(loss))
    return variables, np.asarray(losses)


def _egnn_torch_losses(variables, samples, steps):
    """Eager re-implementation of the E_GCL math in the reference's
    execution style (per-op dispatch, index_add_ scatters at the SENDER
    index — ``hydragnn/models/EGCLStack.py:116-236``): edge MLP on
    [h_row, h_col, ||dx||^2] as ONE concat matmul (the framework's
    SplitLinear is parameter-identical to it), tanh-bounded coord update
    with mean-by-count, coord channel gated off on the last layer."""
    import torch

    p = jax.tree_util.tree_map(
        lambda a: torch.tensor(np.asarray(a)), variables["params"]
    )
    xs, eis, gids, y_g, y_n, poss = [], [], [], [], [], []
    off = 0
    for g, s in enumerate(samples):
        xs.append(s.x)
        poss.append(s.pos)
        eis.append(s.edge_index + off)
        gids.append(np.full(s.x.shape[0], g))
        y_g.append(s.targets[0])
        y_n.append(s.targets[1])
        off += s.x.shape[0]
    x0 = torch.tensor(np.concatenate(xs))
    pos0 = torch.tensor(np.concatenate(poss))
    ei = torch.tensor(np.concatenate(eis, axis=1))
    gid = torch.tensor(np.concatenate(gids), dtype=torch.long)
    yg = torch.tensor(np.stack(y_g))
    yn = torch.tensor(np.concatenate(y_n))
    N, G = x0.shape[0], len(samples)
    row, col = ei[0], ei[1]  # sender, receiver (aggregation at row)

    leaves = []

    def P(a):
        t = a.clone().detach().requires_grad_(True)
        leaves.append(t)
        return t

    convs = []
    for i in range(2):
        c = p[f"encoder_conv_{i}"]
        convs.append(
            {
                "e0k": P(c["edge_mlp_0"]["kernel"]),
                "e0b": P(c["edge_mlp_0"]["bias"]),
                "e1k": P(c["edge_mlp_1"]["kernel"]),
                "e1b": P(c["edge_mlp_1"]["bias"]),
                "c0k": P(c["coord_mlp_0"]["kernel"]) if "coord_mlp_0" in c else None,
                "c0b": P(c["coord_mlp_0"]["bias"]) if "coord_mlp_0" in c else None,
                "c1": P(c["coord_mlp_1"]) if "coord_mlp_1" in c else None,
                "n0k": P(c["node_mlp_0"]["kernel"]),
                "n0b": P(c["node_mlp_0"]["bias"]),
                "n1k": P(c["node_mlp_1"]["kernel"]),
                "n1b": P(c["node_mlp_1"]["bias"]),
            }
        )
    gs = [
        (P(p["graph_shared"][f"TorchLinear_{i}"]["kernel"]),
         P(p["graph_shared"][f"TorchLinear_{i}"]["bias"]))
        for i in range(2)
    ]
    hg = [
        (P(p["head_0_graph"][f"TorchLinear_{i}"]["kernel"]),
         P(p["head_0_graph"][f"TorchLinear_{i}"]["bias"]))
        for i in range(3)
    ]
    hn = [
        (P(p["head_1_node"][f"kernel_{i}"][0]),
         P(p["head_1_node"][f"bias_{i}"][0]))
        for i in range(3)
    ]

    def forward():
        h, pos = x0, pos0
        for li, c in enumerate(convs):
            d = pos[row] - pos[col]
            radial = d.pow(2).sum(-1, keepdim=True)
            unit = d / (radial.sqrt() + 1.0)  # norm_diff=True
            e = torch.cat([h[row], h[col], radial], dim=-1) @ c["e0k"] + c["e0b"]
            e = torch.relu(e)
            e = torch.relu(e @ c["e1k"] + c["e1b"])
            equivariant = li < len(convs) - 1
            if equivariant:
                cw = torch.relu(e @ c["c0k"] + c["c0b"]) @ c["c1"]
                trans = torch.clamp(unit * torch.tanh(cw), -100.0, 100.0)
                coord_agg = torch.zeros(N, 3).index_add_(0, row, trans)
                cnt = torch.zeros(N).index_add_(
                    0, row, torch.ones(row.shape[0])
                )
                pos = pos + coord_agg / torch.clamp(cnt, min=1.0)[:, None]
            agg = torch.zeros(N, e.shape[1]).index_add_(0, row, e)
            hcat = torch.cat([h, agg], dim=-1)
            h = torch.relu(hcat @ c["n0k"] + c["n0b"]) @ c["n1k"] + c["n1b"]
            # the stack relu's every conv output (Base.py:289-302 parity;
            # base.py `x = act(c)` — EGNN skips BatchNorm, not activation)
            h = torch.relu(h)
        cnt = torch.zeros(G).index_add_(0, gid, torch.ones(N))
        pooled = torch.zeros(G, HIDDEN).index_add_(0, gid, h) / cnt[:, None]
        sg = pooled
        for k, b in gs:
            sg = torch.relu(sg @ k + b)
        og = sg
        for i, (k, b) in enumerate(hg):
            og = og @ k + b
            if i < 2:
                og = torch.relu(og)
        on = h
        for i, (k, b) in enumerate(hn):
            on = on @ k + b
            if i < 2:
                on = torch.relu(on)
        return og, on

    opt = torch.optim.AdamW(
        [t for t in leaves if t is not None],
        lr=1e-3, eps=1e-8, weight_decay=0.01,
    )
    losses = []
    for _ in range(steps):
        opt.zero_grad()
        og, on = forward()
        loss = 0.5 * torch.nn.functional.mse_loss(og, yg) + \
            0.5 * torch.nn.functional.mse_loss(on, yn)
        loss.backward()
        opt.step()
        losses.append(float(loss))
    return np.asarray(losses)


def pytest_egnn_seeded_trajectory_matches_torch():
    """Second parity anchor: the EQUIVARIANT stack (coord updates feed the
    next layer's geometry, so any divergence compounds through pos)."""
    samples = _egnn_samples()
    variables, ours = _egnn_jax_losses(samples, STEPS)
    theirs = _egnn_torch_losses(variables, samples, STEPS)
    rel = np.abs(ours - theirs) / np.maximum(np.abs(theirs), 1e-8)
    assert rel[:20].max() < 1e-4, f"early divergence: {rel[:20].max()}"
    assert rel.max() < 5e-3, f"trajectory drift: {rel.max()} at {rel.argmax()}"
    assert ours[-1] < 0.5 * ours[0]
