"""Online inference serving (hydragnn_tpu/serve): micro-batched,
bucket-compiled, observable predict server.

Acceptance (ISSUE 2): an in-process server under concurrent mixed-size
traffic must return predictions matching the offline
``PredictMixin.predict`` path for the same graphs, and after warmup the
compile counter must stay flat across >= 100 further requests (zero
steady-state recompiles). Plus the degradation contract: queue-full
shedding with a retry-after hint, per-request deadlines, next-larger-
bucket fallback for over-dense graphs, and the /healthz + /metrics
endpoint pair.
"""

import json
import urllib.request
from concurrent.futures import ThreadPoolExecutor

import numpy as np
import pytest

from hydragnn_tpu.data.dataobj import GraphData
from hydragnn_tpu.data.loaders import GraphLoader, compute_layout
from hydragnn_tpu.models import create_model_config
from hydragnn_tpu.serve import (
    DeadlineExceeded,
    GraphTooLarge,
    InferenceServer,
    LatencyHistogram,
    ModelRegistry,
    ServerOverloaded,
    plan_from_samples,
)
from hydragnn_tpu.train.trainer import Trainer

from test_models_forward import arch_config


def _graph(n, rng, degree=4, with_targets=True):
    d = GraphData(
        x=rng.random((n, 1)).astype(np.float32),
        pos=rng.random((n, 3)).astype(np.float32),
    )
    src = np.repeat(np.arange(n), max(degree // 2, 1))
    dst = (src + rng.integers(1, n, src.shape[0])) % n
    d.edge_index = np.stack(
        [np.concatenate([src, dst]), np.concatenate([dst, src])]
    ).astype(np.int64)
    if with_targets:
        d.targets = [np.asarray([d.x.sum()], np.float32), d.x.copy()]
        d.target_types = ["graph", "node"]
    return d


_HARNESS = {}


def _harness():
    """One (samples, model, state, registry, plan) per module — jit
    warmup is the expensive part; every test reuses it."""
    if _HARNESS:
        return _HARNESS
    rng = np.random.default_rng(42)
    samples = [_graph(int(n), rng) for n in rng.integers(4, 40, 60)]
    model = create_model_config(arch_config("SAGE"))
    trainer = Trainer(
        model, {"Optimizer": {"type": "AdamW", "learning_rate": 1e-3}}
    )
    plan = plan_from_samples(samples, max_batch_graphs=4, num_buckets=3)
    init_batch, _ = plan.pack([samples[0]], 0)
    state = trainer.init_state(init_batch)
    registry = ModelRegistry()
    registry.register(
        "sage", model, state.params, state.batch_stats
    )
    _HARNESS.update(
        samples=samples,
        model=model,
        trainer=trainer,
        state=state,
        registry=registry,
        plan=plan,
    )
    return _HARNESS


def pytest_serve_smoke_one_request_per_bucket():
    """CI smoke: start in-process, serve one request per bucket, shut
    down cleanly — the ci.yml serve gate."""
    h = _harness()
    plan, rng = h["plan"], np.random.default_rng(0)
    with InferenceServer(h["registry"], plan, max_wait_s=0.002) as server:
        assert server.is_warm()
        for cap in plan.capacities:
            g = _graph(cap.max_nodes, rng, with_targets=False)
            heads = server.predict(g, timeout=30)
            assert heads[0].shape == (1,)
            assert heads[1].shape == (cap.max_nodes, 1)
            assert all(np.isfinite(o).all() for o in heads)
    # clean shutdown: batcher gone, late submits fail fast instead of
    # queueing into a server that will never answer
    assert server.health()["status"] == "stopped"
    with pytest.raises(RuntimeError, match="stopped"):
        server.submit(_graph(8, rng, with_targets=False))


def pytest_serve_matches_offline_predict_under_concurrency():
    """The acceptance e2e: concurrent mixed-size requests == offline
    PredictMixin.predict, and zero steady-state recompiles."""
    h = _harness()
    samples, trainer, state = h["samples"], h["trainer"], h["state"]

    # offline reference: single max-sized layout, dataset order
    layout = compute_layout([samples], batch_size=4)
    loader = GraphLoader(
        samples, 4, layout, shuffle=False, num_shards=1, shard_id=0
    )
    _, _, _, offline = trainer.predict(state, loader)

    server = InferenceServer(
        h["registry"], h["plan"], max_wait_s=0.005, queue_capacity=512
    )
    with server:
        compiles_after_warmup = server.metrics.compiles_total
        assert compiles_after_warmup == h["plan"].num_buckets

        with ThreadPoolExecutor(max_workers=8) as pool:
            futs = [
                pool.submit(server.predict, g, None, 60) for g in samples
            ]
            results = [f.result() for f in futs]

        # same graphs, same weights: per-head rows must match the offline
        # sweep (reshaped to its flattened [rows, 1] collection format)
        for ihead in range(2):
            served = np.concatenate(
                [np.asarray(r[ihead]).reshape(-1, 1) for r in results]
            )
            np.testing.assert_allclose(
                served, offline[ihead], rtol=1e-5, atol=1e-5
            )

        # steady state: >= 100 further requests, compile counter flat
        rng = np.random.default_rng(7)
        futs = [
            server.submit(_graph(int(n), rng, with_targets=False))
            for n in rng.integers(4, 40, 110)
        ]
        for f in futs:
            f.result(60)
        assert server.metrics.compiles_total == compiles_after_warmup
    snap = server.metrics.snapshot()
    assert snap["responses_total"] >= len(samples) + 110
    assert snap["errors_total"] == 0
    assert 0.0 <= snap["padding_waste_ratio"] < 1.0
    assert snap["request_latency"]["p99"] >= snap["request_latency"]["p50"]


def pytest_serve_queue_full_sheds_with_retry_hint():
    h = _harness()
    server = InferenceServer(
        h["registry"], h["plan"], max_wait_s=0.01, queue_capacity=3
    )
    # batcher NOT started: the queue fills deterministically
    g = _graph(10, np.random.default_rng(1), with_targets=False)
    futs = [server.submit(g) for _ in range(3)]
    with pytest.raises(ServerOverloaded) as exc:
        server.submit(g)
    assert exc.value.retry_after_s > 0
    assert server.metrics.shed_total == 1
    assert server.metrics.requests_total == 3  # shed work never counted
    # stop() sweeps the never-started queue: accepted work fails loudly
    # and lands in errors_total (the metrics lifecycle invariant)
    server.stop()
    for f in futs:
        with pytest.raises(RuntimeError, match="stopped"):
            f.result(5)
    assert server.metrics.errors_total == 3


def pytest_serve_deadline_expires_in_queue():
    h = _harness()
    with InferenceServer(
        h["registry"], h["plan"], max_wait_s=0.02
    ) as server:
        g = _graph(10, np.random.default_rng(2), with_targets=False)
        fut = server.submit(g, deadline_s=0.0)  # already expired
        with pytest.raises(DeadlineExceeded):
            fut.result(30)
        assert server.metrics.timeouts_total >= 1
        # SLO accounting: the in-queue expiry counts as a missed deadline
        assert server.metrics.snapshot()["deadline_missed_total"] >= 1
        # ... and a request answered within its (generous) deadline as met
        ok = server.submit(g, deadline_s=60.0)
        ok.result(30)
        snap = server.metrics.snapshot()
        assert snap["deadline_met_total"] >= 1
        assert 0.0 < snap["slo_miss_ratio"] < 1.0


def pytest_serve_dense_graph_falls_back_to_larger_bucket():
    """A graph whose NODE count fits the smallest bucket but whose edge
    count overflows it must ride a larger bucket, not fail."""
    h = _harness()
    plan = h["plan"]
    cap0 = plan.capacities[0]
    n = cap0.max_nodes
    # dense enough to overflow sparse bucket 0, small enough for the top
    rng = np.random.default_rng(3)
    half = cap0.max_edges // (2 * n) + 1
    g = _graph(n, rng, degree=2 * half, with_targets=False)
    assert g.num_edges > cap0.max_edges
    assert g.num_edges <= plan.capacities[-1].max_edges
    b = plan.select(g)
    assert b > 0
    with InferenceServer(h["registry"], plan, max_wait_s=0.002) as server:
        heads = server.predict(g, timeout=30)
        assert heads[1].shape == (n, 1)
        assert server.metrics.bucket_fallbacks >= 1

    # and nothing admits a graph beyond the largest bucket
    with pytest.raises(GraphTooLarge):
        plan.select(_graph(10_000, rng, with_targets=False))


def pytest_serve_registry_versions_and_checkpoint_load(tmp_path):
    """Registry: versioned re-registration; checkpoint load uses the
    STRICT v2 loader (corruption refuses — never a silent rolling
    fallback for serving)."""
    from hydragnn_tpu.train.checkpoint import save_model

    h = _harness()
    registry = ModelRegistry()
    e1 = registry.register(
        "m", h["model"], h["state"].params, h["state"].batch_stats
    )
    e2 = registry.register(
        "m", h["model"], h["state"].params, h["state"].batch_stats
    )
    assert (e1.version, e2.version) == (1, 2)
    assert registry.get("m").version == 2
    assert registry.get("m", version=1) is e1

    save_model(h["state"], "served", path=str(tmp_path))
    entry = registry.load_checkpoint(
        "served", arch_config=arch_config("SAGE"), path=str(tmp_path)
    )
    assert entry.name == "served" and entry.version == 1
    assert entry.output_type == ("graph", "node")
    # restored weights serve identically to the in-memory registration
    plan = h["plan"]
    g = h["samples"][0]
    with InferenceServer(registry, plan, default_model="served",
                         max_wait_s=0.002) as server:
        ref = server.predict(g, model="m", timeout=30)
        out = server.predict(g, timeout=30)  # default_model path
    for a, b in zip(ref, out):
        np.testing.assert_allclose(a, b, atol=1e-6)

    # strict loader: flip a payload byte -> serving load refuses
    fname = tmp_path / "served" / "served.pk"
    raw = bytearray(fname.read_bytes())
    raw[len(raw) // 2] ^= 0xFF
    fname.write_bytes(bytes(raw))
    with pytest.raises(ValueError, match="corrupt"):
        registry.load_checkpoint(
            "served", arch_config=arch_config("SAGE"), path=str(tmp_path)
        )


def pytest_serve_observability_endpoints():
    h = _harness()
    with InferenceServer(
        h["registry"], h["plan"], max_wait_s=0.002, observability_port=0
    ) as server:
        server.predict(
            _graph(12, np.random.default_rng(4), with_targets=False),
            timeout=30,
        )
        host, port = server.observability_address
        health = json.load(
            urllib.request.urlopen(f"http://{host}:{port}/healthz")
        )
        assert health["status"] == "ok" and health["warm"] is True
        assert "sage" in health["models"]
        assert len(health["buckets"]) == h["plan"].num_buckets

        text = (
            urllib.request.urlopen(f"http://{host}:{port}/metrics")
            .read()
            .decode()
        )
        assert "hydragnn_serve_requests_total" in text
        assert "hydragnn_serve_compiles_total" in text
        assert 'quantile="0.99"' in text

        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(f"http://{host}:{port}/nope")
        assert exc.value.code == 404
    assert server.observability_address is None  # listener torn down


def pytest_plan_from_training_layout_serves():
    """Adopting a training-time bucketed layout as the serving plan:
    shapes match training's compiled family and requests still serve."""
    from hydragnn_tpu.serve import plan_from_layout

    h = _harness()
    samples = h["samples"]
    layout = compute_layout([samples], batch_size=4, num_buckets=3)
    smallest = min(samples, key=lambda s: s.num_nodes)
    plan = plan_from_layout(layout, warmup_sample=smallest)
    assert plan.num_buckets == len(layout.layouts)
    assert [l.n_pad for l in plan.layouts] == [
        l.n_pad for l in layout.layouts
    ]
    with InferenceServer(h["registry"], plan, max_wait_s=0.002) as server:
        for g in samples[:6]:
            heads = server.predict(g, timeout=30)
            assert heads[1].shape == (g.num_nodes, 1)


def pytest_latency_histogram_quantiles():
    hist = LatencyHistogram(bounds=(0.001, 0.01, 0.1))
    for _ in range(98):
        hist.observe(0.005)
    hist.observe(0.05)
    hist.observe(0.05)
    assert 0.001 < hist.quantile(0.5) <= 0.01
    assert 0.01 < hist.quantile(0.99) <= 0.1
    assert hist.state()["count"] == 100


# ---- satellite: run_prediction(use_devices) ------------------------------


def pytest_run_prediction_use_devices_is_a_loud_error():
    """The facades accepted use_devices and silently ignored it; now
    both refuse with guidance instead of pretending to honor it."""
    from hydragnn_tpu import run_prediction, run_training

    with pytest.raises(TypeError, match="use_devices"):
        run_prediction({}, use_devices=[0, 1])
    with pytest.raises(TypeError, match="use_devices"):
        run_training({}, use_devices=[0, 1])


# ---- satellite: configurable predict staging budget ----------------------


def pytest_predict_stage_budget_precedence(monkeypatch):
    """env > training config > 8 GiB class default, and the budget is
    what _stack_for_predict enforces."""
    h = _harness()
    trainer = h["trainer"]
    monkeypatch.delenv("HYDRAGNN_PREDICT_STAGE_BUDGET", raising=False)
    assert trainer._predict_stage_budget() == 8 * 1024**3

    cfg_trainer = Trainer(
        h["model"],
        {
            "Optimizer": {"type": "AdamW", "learning_rate": 1e-3},
            "predict_stage_budget_bytes": 12345,
        },
    )
    assert cfg_trainer._predict_stage_budget() == 12345
    monkeypatch.setenv("HYDRAGNN_PREDICT_STAGE_BUDGET", "4e9")
    assert cfg_trainer._predict_stage_budget() == 4_000_000_000
    monkeypatch.setenv("HYDRAGNN_PREDICT_STAGE_BUDGET", "lots")
    with pytest.raises(ValueError, match="byte count"):
        cfg_trainer._predict_stage_budget()

    # a tiny budget pushes the staged path to its documented MemoryError
    monkeypatch.setenv("HYDRAGNN_PREDICT_STAGE_BUDGET", "1")
    layout = compute_layout([h["samples"]], batch_size=4)
    loader = GraphLoader(
        h["samples"], 4, layout, shuffle=False, num_shards=1, shard_id=0
    )
    batch = next(iter(loader))
    with pytest.raises(MemoryError, match="budget"):
        trainer._stack_for_predict([batch])

    # through the REAL predict path a malformed override must fail
    # loudly, not be swallowed by the ragged-shape/over-budget fallback
    monkeypatch.setenv("HYDRAGNN_PREDICT_DEVICE_RESIDENT", "1")
    monkeypatch.setenv("HYDRAGNN_PREDICT_STAGE_BUDGET", "lots")
    with pytest.raises(ValueError, match="byte count"):
        trainer.predict(h["state"], loader)
