"""Canonical-graph response cache (hydragnn_tpu/serve/cache.py).

Acceptance (ISSUE 17): the canonical key must be PERMUTATION-STABLE
(property-tested: relabeling nodes and shuffling edge columns never
changes it) yet collision-distinct for physically perturbed inputs (one
ULP on one coordinate, one species flip, one rewired edge). Cached
responses must be bitwise-equal to fresh dispatches for the same
(tenant, model, version), and a promote/rollback must make every stale
hit impossible by construction — the version lives in the key.
"""

import numpy as np
import pytest

from hydragnn_tpu.data.dataobj import GraphData
from hydragnn_tpu.serve import (
    InferenceServer,
    ResponseCache,
    canonical_graph_key,
)

from test_serve import _graph, _harness


def _permuted(g, perm):
    """The same physical graph under node relabeling ``perm`` (new node
    j is old node perm[j]) plus a random shuffle of edge columns."""
    inv = np.argsort(perm)
    out = GraphData(
        x=np.asarray(g.x)[perm].copy(),
        pos=None if g.pos is None else np.asarray(g.pos)[perm].copy(),
    )
    ei = inv[np.asarray(g.edge_index)]
    shuffle = np.random.default_rng(int(perm[0])).permutation(ei.shape[1])
    out.edge_index = np.ascontiguousarray(ei[:, shuffle])
    if getattr(g, "edge_attr", None) is not None:
        out.edge_attr = np.asarray(g.edge_attr)[shuffle].copy()
    return out


# -- the permutation-invariance property --------------------------------------

def pytest_cache_key_is_permutation_invariant():
    """Property test: 25 random graphs x 4 random relabelings each —
    every relabeling (plus an edge-column shuffle) hashes identically."""
    rng = np.random.default_rng(11)
    for trial in range(25):
        n = int(rng.integers(3, 30))
        g = _graph(n, rng, with_targets=False)
        key = canonical_graph_key(g)
        for _ in range(4):
            perm = rng.permutation(n)
            assert canonical_graph_key(_permuted(g, perm)) == key


def pytest_cache_key_permutation_invariant_with_edge_attr():
    rng = np.random.default_rng(12)
    for _ in range(10):
        n = int(rng.integers(4, 20))
        g = _graph(n, rng, with_targets=False)
        g.edge_attr = rng.random(
            (g.edge_index.shape[1], 3)
        ).astype(np.float32)
        key = canonical_graph_key(g)
        perm = rng.permutation(n)
        assert canonical_graph_key(_permuted(g, perm)) == key


# -- collision distinctness ---------------------------------------------------

def pytest_cache_key_distinct_for_perturbed_inputs():
    """One ULP on one coordinate, one species value flip, one rewired
    edge, one edge_attr tweak: each must produce a fresh key."""
    rng = np.random.default_rng(13)
    g = _graph(12, rng, with_targets=False)
    g.edge_attr = rng.random((g.edge_index.shape[1], 2)).astype(np.float32)
    key = canonical_graph_key(g)
    seen = {key}

    bumped = _permuted(g, np.arange(12))  # deep copy via identity perm
    bumped.pos = bumped.pos.copy()
    bumped.pos[3, 1] = np.nextafter(
        bumped.pos[3, 1], np.float32(np.inf), dtype=np.float32
    )
    k = canonical_graph_key(bumped)
    assert k not in seen
    seen.add(k)

    flipped = _permuted(g, np.arange(12))
    flipped.x = flipped.x.copy()
    flipped.x[5, 0] += 1.0  # a different species/feature value
    k = canonical_graph_key(flipped)
    assert k not in seen
    seen.add(k)

    rewired = _permuted(g, np.arange(12))
    ei = rewired.edge_index.copy()
    ei[1, 0] = (ei[1, 0] + 1) % 12  # move one edge's destination
    if ei[1, 0] == ei[0, 0]:
        ei[1, 0] = (ei[1, 0] + 1) % 12
    rewired.edge_index = ei
    k = canonical_graph_key(rewired)
    assert k not in seen
    seen.add(k)

    attr = _permuted(g, np.arange(12))
    attr.edge_attr = attr.edge_attr.copy()
    attr.edge_attr[0, 0] += np.float32(1e-3)
    assert canonical_graph_key(attr) not in seen


def pytest_cache_key_separates_identical_atoms_different_wiring():
    """Four identical nodes as a path vs a star: pure content hashing
    would collide; the WL refinement round must not."""
    def mk(edges):
        g = GraphData(
            x=np.ones((4, 1), np.float32),
            pos=np.zeros((4, 3), np.float32),
        )
        e = np.asarray(edges, np.int64).T
        g.edge_index = np.concatenate([e, e[::-1]], axis=1)
        return g

    path = mk([(0, 1), (1, 2), (2, 3)])
    star = mk([(0, 1), (0, 2), (0, 3)])
    assert canonical_graph_key(path) != canonical_graph_key(star)


def pytest_cache_key_is_direction_sensitive():
    g = GraphData(
        x=np.arange(6, dtype=np.float32).reshape(3, 2),
        pos=np.zeros((3, 3), np.float32),
    )
    g.edge_index = np.asarray([[0, 1], [1, 2]], np.int64)
    fwd = canonical_graph_key(g)
    g.edge_index = np.asarray([[1, 2], [0, 1]], np.int64)
    assert canonical_graph_key(g) != fwd


# -- LRU mechanics ------------------------------------------------------------

def _heads(rng, rows=4):
    return [rng.random((1,)).astype(np.float64),
            rng.random((rows, 1)).astype(np.float64)]


def pytest_response_cache_lru_eviction_and_bounds():
    rng = np.random.default_rng(21)
    cache = ResponseCache(capacity=3, max_bytes=1 << 20)
    keys = [ResponseCache.key(f"g{i}", "m", 1) for i in range(4)]
    payloads = [_heads(rng) for _ in range(4)]
    for k, p in zip(keys[:3], payloads[:3]):
        cache.put(k, p)
    # touch keys[0] so keys[1] is the LRU tail
    assert cache.get(keys[0]) is not None
    cache.put(keys[3], payloads[3])
    assert len(cache) == 3
    assert cache.get(keys[1]) is None  # evicted
    assert cache.evictions == 1
    hit = cache.get(keys[0])
    np.testing.assert_array_equal(hit[1], payloads[0][1])
    # returned arrays are copies: mutating a hit cannot poison the cache
    hit[1][:] = -1.0
    np.testing.assert_array_equal(cache.get(keys[0])[1], payloads[0][1])


def pytest_response_cache_byte_bound_and_oversize_skip():
    rng = np.random.default_rng(22)
    small = _heads(rng, rows=4)
    per_entry = sum(h.nbytes for h in small)
    cache = ResponseCache(capacity=100, max_bytes=per_entry * 2)
    for i in range(3):
        cache.put(ResponseCache.key(f"g{i}", "m", 1), small)
    assert len(cache) == 2  # byte bound bit before capacity did
    assert cache.bytes <= per_entry * 2
    # one oversized answer is skipped, not allowed to wipe the cache
    cache.put(
        ResponseCache.key("huge", "m", 1),
        [rng.random((10_000, 8))],
    )
    assert len(cache) == 2
    assert cache.get(ResponseCache.key("huge", "m", 1)) is None


def pytest_response_cache_invalidate_filters():
    rng = np.random.default_rng(23)
    cache = ResponseCache(capacity=16, max_bytes=1 << 20)
    for tenant in ("a", "b"):
        for version in (1, 2):
            cache.put(
                ResponseCache.key("g", "m", version, tenant=tenant),
                _heads(rng),
            )
    assert cache.invalidate(tenant="a") == 2
    assert len(cache) == 2
    assert cache.invalidate(model="m", version=1) == 1
    assert cache.get(ResponseCache.key("g", "m", 2, tenant="b")) is not None
    assert cache.invalidate() == 1
    assert len(cache) == 0 and cache.bytes == 0


def pytest_response_cache_from_env_knobs(monkeypatch):
    monkeypatch.setenv("HYDRAGNN_CACHE", "0")
    assert ResponseCache.from_env({"enabled": True}) is None
    monkeypatch.setenv("HYDRAGNN_CACHE", "1")
    monkeypatch.setenv("HYDRAGNN_CACHE_CAPACITY", "7")
    monkeypatch.setenv("HYDRAGNN_CACHE_MAX_BYTES", "4096")
    cache = ResponseCache.from_env()
    assert cache.capacity == 7 and cache.max_bytes == 4096
    monkeypatch.setenv("HYDRAGNN_CACHE_CAPACITY", "0")
    with pytest.raises(ValueError):
        ResponseCache.from_env()
    monkeypatch.delenv("HYDRAGNN_CACHE")
    monkeypatch.delenv("HYDRAGNN_CACHE_CAPACITY")
    assert ResponseCache.from_env() is None  # no spec, no env: disabled


# -- server integration: bitwise equality + promote fencing -------------------

def pytest_server_cache_hit_is_bitwise_equal_and_promote_invalidates():
    h = _harness()
    registry, plan = h["registry"], h["plan"]
    cache = ResponseCache(capacity=64, max_bytes=8 << 20)
    rng = np.random.default_rng(31)
    g = _graph(10, rng, with_targets=False)
    with InferenceServer(
        registry, plan, max_wait_s=0.002, cache=cache
    ) as server:
        v1 = registry.get("sage").version
        fresh = server.predict(g, timeout=30)
        assert cache.misses >= 1 and len(cache) == 1
        hit = server.predict(g, timeout=30)
        assert cache.hits == 1
        for a, b in zip(fresh, hit):
            assert a.dtype == b.dtype and a.shape == b.shape
            assert np.array_equal(a, b)  # bitwise, not allclose
        # a permuted resubmission of the same structure also hits
        perm = rng.permutation(10)
        server.predict(_permuted(g, perm), timeout=30)
        assert cache.hits == 2

        # register a NEW version: it becomes implicitly active with no
        # activation event at all — the case where invalidation never
        # runs. The fence must still hold: lookups key on the new active
        # version, so the v1 entry is unreachable, not stale-served.
        registry.register(
            "sage", h["model"], h["state"].params,
            h["state"].batch_stats,
        )
        v2 = registry.get("sage").version
        assert v2 != v1
        assert len(cache) == 1  # v1 entry still resident...
        hits_before = cache.hits
        server.predict(g, timeout=30)
        assert cache.hits == hits_before  # ...but a miss by construction
        assert len(cache) == 2
        assert {k[2] for k in cache._entries} == {v1, v2}

        # an EFFECTIVE promote (activating the non-latest version) fires
        # the activation listener, which reclaims the model's entries
        registry.promote("sage", v1)
        assert len(cache) == 0
        server.predict(g, timeout=30)
        ((_, _, cached_version, _),) = list(cache._entries.keys())
        assert cached_version == v1

        # rollback fences the same way, back to the v2 channel
        registry.rollback("sage")
        assert registry.get("sage").version == v2
        assert len(cache) == 0
        server.predict(g, timeout=30)
        ((_, _, cached_version, _),) = list(cache._entries.keys())
        assert cached_version == v2
    stats = cache.stats()
    assert stats["hits"] == 2 and stats["hit_ratio"] > 0
