"""Pallas aggregation kernels vs XLA segment ops (interpret mode on CPU).

The kernels replace torch_scatter's role in the reference (SURVEY.md §2.4);
correctness is defined by ``jax.ops.segment_sum``. Values AND gradients must
match, including out-of-range padded ids contributing nothing.
"""

import jax
import jax.numpy as jnp
import numpy as np

from hydragnn_tpu.ops import segment_moments, segment_sum_onehot


def _case(seed=0, e=700, n=96, d=24):
    rng = np.random.default_rng(seed)
    data = jnp.asarray(rng.standard_normal((e, d)), jnp.float32)
    ids = jnp.asarray(rng.integers(0, n, e), jnp.int32)
    return data, ids, n


def pytest_segment_sum_matches_xla():
    data, ids, n = _case()
    ours = segment_sum_onehot(data, ids, n, True)
    ref = jax.ops.segment_sum(data, ids, num_segments=n)
    np.testing.assert_allclose(ours, ref, rtol=1e-5, atol=1e-5)


def pytest_segment_sum_empty_segments():
    data, ids, _ = _case(e=40, n=16)
    # leave segments 10.. empty
    ids = jnp.minimum(ids, 9)
    ours = segment_sum_onehot(data, ids, 16, True)
    assert np.allclose(np.asarray(ours[10:]), 0.0)


def pytest_segment_sum_grad():
    data, ids, n = _case(e=120, n=32, d=8)

    def loss_ours(x):
        return jnp.sum(segment_sum_onehot(x, ids, n, True) ** 2)

    def loss_ref(x):
        return jnp.sum(jax.ops.segment_sum(x, ids, num_segments=n) ** 2)

    g_ours = jax.grad(loss_ours)(data)
    g_ref = jax.grad(loss_ref)(data)
    np.testing.assert_allclose(g_ours, g_ref, rtol=1e-5, atol=1e-5)


def pytest_segment_moments_matches_xla():
    data, ids, n = _case(seed=3)
    s, c, sq = segment_moments(data, ids, n, True)
    ref_s = jax.ops.segment_sum(data, ids, num_segments=n)
    ref_c = jax.ops.segment_sum(jnp.ones(data.shape[0]), ids, num_segments=n)
    ref_sq = jax.ops.segment_sum(data * data, ids, num_segments=n)
    np.testing.assert_allclose(s, ref_s, rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(c[:, 0], ref_c, rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(sq, ref_sq, rtol=1e-4, atol=1e-5)


def pytest_segment_moments_grad():
    data, ids, n = _case(seed=4, e=96, n=24, d=8)

    def loss_ours(x):
        s, c, sq = segment_moments(x, ids, n, True)
        mean = s / jnp.maximum(c, 1.0)
        var = jax.nn.relu(sq / jnp.maximum(c, 1.0) - mean**2)
        return jnp.sum(mean**2) + jnp.sum(jnp.sqrt(var + 1e-5))

    def loss_ref(x):
        s = jax.ops.segment_sum(x, ids, num_segments=n)
        c = jax.ops.segment_sum(
            jnp.ones(x.shape[0]), ids, num_segments=n
        ).reshape(-1, 1)
        sq = jax.ops.segment_sum(x * x, ids, num_segments=n)
        mean = s / jnp.maximum(c, 1.0)
        var = jax.nn.relu(sq / jnp.maximum(c, 1.0) - mean**2)
        return jnp.sum(mean**2) + jnp.sum(jnp.sqrt(var + 1e-5))

    g_ours = jax.grad(loss_ours)(data)
    g_ref = jax.grad(loss_ref)(data)
    np.testing.assert_allclose(g_ours, g_ref, rtol=1e-4, atol=1e-5)


def pytest_nonmultiple_edge_count_padding():
    # edge count not a multiple of the kernel block: padded ids must not
    # contribute anywhere
    data, ids, n = _case(seed=5, e=301, n=40, d=5)
    ours = segment_sum_onehot(data, ids, n, True)
    ref = jax.ops.segment_sum(data, ids, num_segments=n)
    np.testing.assert_allclose(ours, ref, rtol=1e-5, atol=1e-5)
