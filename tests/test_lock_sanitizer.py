"""Runtime half of the threadlint concurrency suite (ISSUE 6).

The static pass (``tests/test_threadlint.py``) proves what the SOURCE
nests; these tests prove what execution composes: ``lock_sanitizer()``
catches an injected lock-order inversion the first time two threads
establish opposite orders (not the unlucky run that deadlocks), the
watchdog dumps all thread stacks + held locks and emits a
``deadlock_suspect`` event when an acquisition blocks past threshold,
and the shutdown paths this PR hardened actually terminate: server
stop-under-load drains within its timeout, the obs listener's stop is
idempotent and race-free, the prefetch worker joins on generator close,
and ``MetricsRegistry`` survives concurrent registration + scrape.
"""

import threading
import time
import urllib.request

import numpy as np
import pytest

from hydragnn_tpu.analysis.guards import (
    LockOrderViolation,
    lock_sanitizer,
)
from hydragnn_tpu.data.loaders import prefetch_iter
from hydragnn_tpu.obs.events import RunEventLog, validate_events
from hydragnn_tpu.obs.http import ObservabilityServer
from hydragnn_tpu.obs.metrics import MetricsRegistry


def _run_threads(*targets):
    errors = []

    def wrap(fn):
        def run():
            try:
                fn()
            except BaseException as e:  # surface on the test thread
                errors.append(e)

        return run

    threads = [threading.Thread(target=wrap(t)) for t in targets]
    for t in threads:
        t.start()
    for t in threads:
        t.join(30.0)
        assert not t.is_alive(), "test thread wedged"
    if errors:
        raise errors[0]
    return errors


# ---- order-inversion detection -------------------------------------------


def pytest_sanitizer_catches_injected_inversion():
    """The acceptance case: thread 1 nests A->B, thread 2 nests B->A.
    Neither run deadlocks (the threads run back-to-back), but the
    interleaving COULD — the sanitizer flags it from the order graph
    alone, and the harness raises on exit."""
    with pytest.raises(LockOrderViolation, match="reverse order"):
        with lock_sanitizer() as san:
            a = san.lock("a")
            b = san.lock("b")

            def forward():
                with a:
                    with b:
                        pass

            def backward():
                with b:
                    with a:
                        pass

            t1 = threading.Thread(target=forward)
            t1.start()
            t1.join()
            t2 = threading.Thread(target=backward)
            t2.start()
            t2.join()
            assert san.violations, "inversion not recorded"
            v = san.violations[0]
            assert v["holding"] == "b" and v["acquiring"] == "a"
            assert "a -> b" in v["reverse_chain"]


def pytest_sanitizer_consistent_order_is_clean():
    with lock_sanitizer() as san:
        a = san.lock("a")
        b = san.lock("b")

        def worker():
            for _ in range(50):
                with a:
                    with b:
                        pass

        _run_threads(worker, worker, worker)
        assert not san.violations


def pytest_sanitizer_transitive_inversion_across_threads():
    """a->b and b->c on two threads, then c->a on a third: a 3-cycle no
    single pair of nested withs exhibits."""
    with lock_sanitizer(check_on_exit=False) as san:
        a, b, c = san.lock("a"), san.lock("b"), san.lock("c")
        for outer, inner in ((a, b), (b, c), (c, a)):
            t = threading.Thread(
                target=lambda o=outer, i=inner: o.acquire()
                and i.acquire() and (i.release(), o.release())
            )
            t.start()
            t.join()
    assert san.violations
    assert san.violations[0]["reverse_chain"] == "a -> b -> c"
    with pytest.raises(LockOrderViolation):
        san.assert_clean()


def pytest_sanitizer_trylock_idiom_is_not_an_inversion():
    """`acquire(blocking=False)` against the established order is the
    STANDARD deadlock-avoidance idiom — it never waits, so it can never
    close a deadlock cycle and must not be flagged."""
    with lock_sanitizer() as san:
        a = san.lock("a")
        b = san.lock("b")

        def forward():
            with a:
                with b:
                    pass

        t = threading.Thread(target=forward)
        t.start()
        t.join()
        with b:
            assert a.acquire(blocking=False)
            a.release()
        assert not san.violations


def pytest_sanitizer_failed_timed_acquire_leaves_no_phantom_edge():
    """A timed-out acquire under a held lock established nothing — the
    reverse nesting later must stay clean."""
    with lock_sanitizer() as san:
        x = san.lock("x")
        y = san.lock("y")
        gate = threading.Lock()
        gate.acquire()
        x_wrapped_holder = threading.Event()

        def holder():  # keeps x busy so the timed acquire times out
            with x:
                x_wrapped_holder.set()
                gate.acquire()

        t = threading.Thread(target=holder)
        t.start()
        assert x_wrapped_holder.wait(5.0)
        with y:
            assert x.acquire(timeout=0.05) is False  # y->x NOT recorded
        gate.release()
        t.join(5.0)
        with x:  # the reverse order — clean, no phantom y->x edge
            with y:
                pass
        assert not san.violations


def pytest_sanitizer_reentrant_rlock_and_lock_surface():
    with lock_sanitizer() as san:
        r = san.rlock("r")
        with r:
            with r:  # reentrant re-acquire is not a new ordering
                pass
        assert not san.violations

        l = san.lock("plain")
        assert l.acquire()
        assert l.locked()
        l.release()
        assert not l.locked()

        # a timed-out acquire must not corrupt the held-set
        other = threading.Lock()
        wrapped = san.wrap("contended", other)
        other.acquire()
        t0 = time.monotonic()
        assert wrapped.acquire(timeout=0.05) is False
        assert time.monotonic() - t0 < 5.0
        other.release()
        with wrapped:  # now it acquires fine
            pass


# ---- watchdog -------------------------------------------------------------


def pytest_watchdog_dumps_threads_and_emits_event(tmp_path):
    """An acquisition blocked past watchdog_s dumps every thread's held
    locks + stack and emits a schema-valid ``deadlock_suspect`` event —
    then still completes once the holder releases (the watchdog
    REPORTS, it does not convert waits into failures)."""
    events = str(tmp_path / "events.jsonl")
    log = RunEventLog(events)
    with lock_sanitizer(watchdog_s=0.05, event_log=log) as san:
        lock = san.lock("hot")
        holding = threading.Event()

        def holder():
            with lock:
                holding.set()
                time.sleep(0.4)

        t = threading.Thread(target=holder, name="holder-thread")
        t.start()
        assert holding.wait(5.0)
        with lock:  # blocks ~0.4s > 0.05s watchdog
            pass
        t.join(5.0)

    assert len(san.deadlock_suspects) == 1
    suspect = san.deadlock_suspects[0]
    assert suspect["lock"] == "hot"
    assert suspect["waited_s"] >= 0.05
    by_name = {rec["name"]: rec for rec in suspect["threads"]}
    assert by_name["holder-thread"]["held_locks"] == ["hot"]
    assert any("holder" in line for line in by_name["holder-thread"]["stack"])

    log.close()
    records = validate_events(events, require=["deadlock_suspect"])
    (rec,) = [r for r in records if r["event"] == "deadlock_suspect"]
    assert rec["lock"] == "hot" and rec["threads"]


def pytest_watchdog_quiet_for_timeouts_below_threshold():
    """A caller timeout shorter than watchdog_s is ordinary control
    flow (the trylock-with-deadline idiom) — timing out there must not
    produce a deadlock_suspect."""
    with lock_sanitizer(watchdog_s=5.0) as san:
        lock = san.lock("busy")
        ready = threading.Event()
        release = threading.Event()

        def holder():
            with lock:
                ready.set()
                release.wait(10.0)

        t = threading.Thread(target=holder)
        t.start()
        assert ready.wait(5.0)
        assert lock.acquire(timeout=0.05) is False  # 0.05 << 5.0
        release.set()
        t.join(5.0)
    assert not san.deadlock_suspects


def pytest_watchdog_quiet_when_uncontended(tmp_path):
    log = RunEventLog(str(tmp_path / "events.jsonl"))
    with lock_sanitizer(watchdog_s=0.05, event_log=log) as san:
        lock = san.lock("calm")
        for _ in range(20):
            with lock:
                pass
    assert not san.deadlock_suspects
    log.close()
    records = validate_events(str(tmp_path / "events.jsonl"))
    assert not [r for r in records if r["event"] == "deadlock_suspect"]


# ---- metrics export -------------------------------------------------------


def pytest_sanitizer_exports_wait_hold_histograms():
    registry = MetricsRegistry("hydragnn_test")
    with lock_sanitizer(registry=registry) as san:
        lock = san.lock("pending queue")  # name gets metric-sanitized
        with lock:
            time.sleep(0.01)
        with lock:
            pass
    snap = registry.snapshot()
    wait = snap["lock_wait_seconds_pending_queue"]
    hold = snap["lock_hold_seconds_pending_queue"]
    assert wait["count"] == 2 and hold["count"] == 2
    assert hold["sum"] >= 0.009  # the sleep is inside the hold
    text = registry.render_prometheus()
    assert "lock_hold_seconds_pending_queue" in text
    assert "lock_wait_seconds_pending_queue" in text


def pytest_sanitizer_reentrant_hold_measures_outermost():
    """A nested re-acquire must not reset the hold clock — the
    histogram answers 'how long was this lock unavailable'."""
    registry = MetricsRegistry("hydragnn_test")
    with lock_sanitizer(registry=registry) as san:
        r = san.rlock("re")
        with r:
            time.sleep(0.03)
            with r:  # inner re-acquire, immediately released
                pass
            time.sleep(0.03)
    hold = registry.snapshot()["lock_hold_seconds_re"]
    assert hold["count"] == 1  # one OUTER hold, not two
    assert hold["sum"] >= 0.055


def pytest_metrics_registry_concurrent_registration_and_scrape():
    """The satellite stress test: writers declaring + recording NEW
    metrics while scrapers render — no torn exposition, no lost
    metrics, no 'dict changed size during iteration'."""
    registry = MetricsRegistry("stress")
    writers, per_writer = 6, 25
    done = threading.Event()

    def writer(wid):
        def run():
            for i in range(per_writer):
                name = f"w{wid}_m{i}"
                registry.counter(name)
                registry.inc(name, wid + 1)
        return run

    def scraper():
        while not done.is_set():
            text = registry.render_prometheus()
            assert text.endswith("\n")
            registry.snapshot()

    scrapers = [threading.Thread(target=scraper) for _ in range(2)]
    for s in scrapers:
        s.start()
    try:
        _run_threads(*[writer(w) for w in range(writers)])
    finally:
        done.set()
        for s in scrapers:
            s.join(10.0)
            assert not s.is_alive()
    snap = registry.snapshot()
    for w in range(writers):
        for i in range(per_writer):
            assert snap[f"w{w}_m{i}"] == w + 1


# ---- obs listener lifecycle ----------------------------------------------


class _Provider:
    def __init__(self):
        self.metrics = MetricsRegistry("probe")
        self.metrics.counter("up")
        self.metrics.inc("up")

    def health(self):
        return {"status": "ok"}


def pytest_obs_server_port0_idempotent_start_and_racing_stops():
    srv = ObservabilityServer(_Provider(), port=0)
    assert srv.address is None  # not started yet
    srv.start()
    host, port = srv.address
    assert port != 0
    assert srv.start() is srv  # idempotent, same listener
    assert srv.address == (host, port)
    with urllib.request.urlopen(
        f"http://{host}:{port}/healthz", timeout=10
    ) as resp:
        assert resp.status == 200

    # concurrent stops race safely: exactly one closes, the rest no-op
    _run_threads(*(srv.stop for _ in range(4)))
    assert srv.address is None
    srv.stop()  # stop-after-stop is a no-op too

    # SO_REUSEADDR: rebinding the just-closed port must not fail even
    # while the old socket lingers in TIME_WAIT
    srv2 = ObservabilityServer(_Provider(), host=host, port=port).start()
    try:
        assert srv2.address == (host, port)
    finally:
        srv2.stop()


# ---- prefetch worker shutdown --------------------------------------------


def pytest_prefetch_close_joins_worker_and_closes_source():
    """An interrupted epoch (generator close after one batch) must reap
    the worker thread AND run the source generator's finally blocks, so
    nothing keeps referencing a collated/device-resident batch."""
    state = {"closed": False, "produced": 0}

    def source():
        try:
            for i in range(1000):
                state["produced"] += 1
                yield i
        finally:
            state["closed"] = True

    it = prefetch_iter(source(), depth=2, name="pf-close-test")
    assert next(it) == 0
    it.close()  # the early `break` / exception path
    assert state["closed"], "source generator finally did not run"
    assert state["produced"] < 1000
    deadline = time.monotonic() + 10.0
    while time.monotonic() < deadline:
        if not any(
            t.name == "pf-close-test" for t in threading.enumerate()
        ):
            break
        time.sleep(0.01)
    else:
        raise AssertionError("prefetch worker leaked past close()")


# ---- server stop-under-load ----------------------------------------------


def pytest_serve_stop_under_load_drains_within_timeout():
    """stop(drain=True) under concurrent submit pressure: terminates
    within its timeout, resolves EVERY accepted future (result or
    shutdown error — no stranded waiter), joins the batcher, and stays
    idempotent."""
    from test_serve import _graph, _harness
    from hydragnn_tpu.serve import InferenceServer

    h = _harness()
    rng = np.random.default_rng(7)
    graphs = [
        _graph(int(n), rng, with_targets=False)
        for n in rng.integers(4, 30, 36)
    ]
    server = InferenceServer(
        h["registry"], h["plan"], max_wait_s=0.002, queue_capacity=256
    )
    server.start()
    futures = []
    fut_lock = threading.Lock()

    def submitter(chunk):
        def run():
            for g in chunk:
                f = server.submit(g)
                with fut_lock:
                    futures.append(f)
        return run

    _run_threads(*(submitter(graphs[i::3]) for i in range(3)))

    t0 = time.monotonic()
    server.stop(drain=True, timeout=30.0)
    assert time.monotonic() - t0 < 30.0
    assert server._thread is None, "batcher not joined"

    resolved = 0
    for f in futures:
        try:
            heads = f.result(timeout=5.0)
            assert all(np.isfinite(o).all() for o in heads)
        except RuntimeError:
            pass  # failed-at-shutdown is a deterministic outcome too
        resolved += 1
    assert resolved == len(futures) == len(graphs)

    # every accepted request ended in exactly one terminal counter
    snap = server.metrics.snapshot()
    assert snap["requests_total"] == (
        snap["responses_total"]
        + snap["timeouts_total"]
        + snap["errors_total"]
    )

    # a burst of concurrent stop() calls must all no-op cleanly (the
    # handle handoff under _submit_lock gives teardown to exactly one)
    _run_threads(*(server.stop for _ in range(6)))
    with pytest.raises(RuntimeError, match="stopped"):
        server.submit(graphs[0])
