"""Aggregation autotuner (ops/autotune.py): decision order, cache
determinism, env overrides, and schema-valid observability."""

import json
import os

import numpy as np

from hydragnn_tpu.ops import autotune as at


def _fresh(tmp_path, monkeypatch, name="cache.json"):
    path = str(tmp_path / name)
    monkeypatch.setenv("HYDRAGNN_AUTOTUNE_CACHE", path)
    at.reset_cache_state()
    return path


def pytest_static_policy_matches_promoted_tables():
    # the tables moved from data/loaders.py — the historical import
    # surface must agree with the promoted policy
    from hydragnn_tpu.data.loaders import auto_dense_aggregation

    assert auto_dense_aggregation is at.auto_dense_aggregation
    assert at.static_aggregation_choice(
        {"model_type": "PNA", "hidden_dim": 256}
    ) == "dense"
    assert at.static_aggregation_choice(
        {"model_type": "PNA", "hidden_dim": 64}
    ) == "segment"
    assert at.static_aggregation_choice(
        {"model_type": "SchNet", "hidden_dim": 2048}
    ) == "segment"
    assert at.static_aggregation_choice(
        {"model_type": "CGCNN", "hidden_dim": 64, "input_dim": 4}
    ) == "dense"
    assert at.static_aggregation_choice(
        {"model_type": "CGCNN", "hidden_dim": 64, "input_dim": 256}
    ) == "segment"


def pytest_measure_candidates_times_all_three(tmp_path, monkeypatch):
    _fresh(tmp_path, monkeypatch)
    t = at.measure_candidates(
        48, 160, 8, ("segment", "dense", "fused"), iters=2
    )
    assert set(t) == {"segment", "dense", "fused"}
    assert all(v > 0 for v in t.values())


def pytest_fused_candidate_excluded_off_tpu_unless_interpret(
    tmp_path, monkeypatch
):
    # off-TPU the fused probe would time the Pallas INTERPRETER —
    # meaningless for the compiled kernel, so autotune_bucket keeps it
    # out of the cache unless interpreter mode is explicitly requested
    path = _fresh(tmp_path, monkeypatch)
    at.autotune_bucket("GIN", 48, 160, 8, ("segment", "fused"), iters=2)
    sig = at.bucket_signature("GIN", 48, 160, 8)
    rec = json.load(open(path))["devices"][at.device_kind()][sig]
    assert "fused" not in rec["timings_ms"]
    at.reset_cache_state()
    _fresh(tmp_path, monkeypatch, name="cache2.json")
    at.autotune_bucket(
        "GIN", 48, 160, 8, ("segment", "fused"), iters=2, interpret=True
    )
    rec = json.load(open(at.cache_path()))["devices"][at.device_kind()][sig]
    assert "fused" in rec["timings_ms"]


def pytest_autotune_bucket_caches_and_is_deterministic(tmp_path, monkeypatch):
    path = _fresh(tmp_path, monkeypatch)
    choice = at.autotune_bucket("GIN", 48, 160, 8, iters=2)
    assert choice in at.CHOICES
    data = json.load(open(path))
    sig = at.bucket_signature("GIN", 48, 160, 8)
    assert data["devices"][at.device_kind()][sig]["choice"] == choice
    # a fresh process (singleton dropped) reads the SAME decision without
    # re-timing: poison the timings so a re-measure would be detectable
    data["devices"][at.device_kind()][sig]["choice"] = "dense"
    json.dump(data, open(path, "w"))
    at.reset_cache_state()
    assert at.autotune_bucket("GIN", 48, 160, 8, iters=2) == "dense"
    # and use_fused consumes the cached decision too
    assert not at.use_fused("GIN", 48, 160, 8, 8)
    data["devices"][at.device_kind()][sig]["choice"] = "fused"
    json.dump(data, open(path, "w"))
    at.reset_cache_state()
    assert at.use_fused("GIN", 48, 160, 8, 8)


def pytest_cached_choice_transfers_across_site_widths(tmp_path, monkeypatch):
    # the warmup tunes ONE representative width (hidden_dim); model sites
    # look up their own table widths (layer-0 input dim, EGNN's hidden+3)
    # — the decision must transfer within the same (model, N, E) bucket
    _fresh(tmp_path, monkeypatch)
    at.record_choice(at.bucket_signature("EGNN", 48, 160, 16), "fused", {})
    assert at.use_fused("EGNN", 48, 160, 19, 20, table_dim_b=19)
    assert not at.use_fused("EGNN", 64, 160, 19, 20)  # different bucket


def pytest_cached_dense_enacted_by_loader_not_trace_sites(
    tmp_path, monkeypatch
):
    # a measured "dense" win is a LAYOUT decision: the loader consults
    # the cache (any bucket of the model, most recent wins), while a
    # segment-laid batch reaching a trace-time site reports segment —
    # the gauge must show what actually ran
    from hydragnn_tpu.data.loaders import needs_dense_neighbors

    _fresh(tmp_path, monkeypatch)
    timed_all = {"segment": 2.0, "dense": 1.0, "fused": 3.0}
    arch = {"model_type": "SchNet", "hidden_dim": 64}  # policy: segment
    assert not needs_dense_neighbors(arch)
    at.record_choice(
        at.bucket_signature("SchNet", 48, 160, 64), "dense", timed_all
    )
    assert needs_dense_neighbors(arch)
    assert not at.use_fused("SchNet", 48, 160, 64, 64)
    # explicit config always beats the cache
    assert not needs_dense_neighbors(dict(arch, dense_aggregation=False))
    at.record_choice(
        at.bucket_signature("SchNet", 48, 160, 64), "segment", timed_all
    )
    at.reset_cache_state()
    assert not needs_dense_neighbors(arch)
    # a record that never TIMED dense says nothing about the layout: it
    # must not preempt the measured static crossover tables (PNA h256 is
    # dense by policy; a segment-vs-fused-only probe must not flip it)
    pna = {"model_type": "PNA", "hidden_dim": 256}
    assert needs_dense_neighbors(pna)
    at.record_choice(
        at.bucket_signature("PNA", 6144, 69120, 256), "segment",
        {"segment": 1.0, "fused": 2.0},
    )
    assert needs_dense_neighbors(pna)
    # ...and the crossover is WIDTH-dependent: a dense win measured at
    # one width must not flip configs at another (CGCNN's inverse
    # input-width crossover is the sharp case)
    at.record_choice(
        at.bucket_signature("CGCNN", 48, 160, 4), "dense", timed_all
    )
    assert needs_dense_neighbors({"model_type": "CGCNN", "input_dim": 4})
    assert not needs_dense_neighbors(
        {"model_type": "CGCNN", "input_dim": 256}
    )


def pytest_choice_events_re_emitted_per_telemetry_run(tmp_path, monkeypatch):
    # the dedup is scoped to the active RunTelemetry: a second run in the
    # same process must get its own agg_choice records
    from hydragnn_tpu.obs import runtime as obs_rt
    from hydragnn_tpu.obs.events import validate_events

    _fresh(tmp_path, monkeypatch)
    sig = at.bucket_signature("GIN", 48, 160, 8)
    at.record_choice(sig, "fused", {})
    for run in ("one", "two"):
        outdir = str(tmp_path / run)
        obs_rt.activate(obs_rt.RunTelemetry(run, outdir))
        try:
            assert at.use_fused("GIN", 48, 160, 8, 8)
        finally:
            obs_rt.deactivate()
        validate_events(
            os.path.join(outdir, "events.jsonl"), require=["agg_choice"]
        )


def pytest_env_overrides_beat_cache(tmp_path, monkeypatch):
    path = _fresh(tmp_path, monkeypatch)
    sig = at.bucket_signature("GIN", 48, 160, 8)
    at.record_choice(sig, "segment", {})
    monkeypatch.setenv("HYDRAGNN_AGG", "fused")
    assert at.use_fused("GIN", 48, 160, 8, 8)
    assert at.autotune_bucket("GIN", 48, 160, 8) == "fused"
    # the kill switch beats everything, including the force
    monkeypatch.setenv("HYDRAGNN_FUSED_MP", "0")
    assert not at.use_fused("GIN", 48, 160, 8, 8)
    monkeypatch.delenv("HYDRAGNN_AGG")
    monkeypatch.setenv("HYDRAGNN_FUSED_MP", "1")
    assert at.use_fused("GIN", 48, 160, 8, 8)


def pytest_fused_choice_respects_vmem_guard(tmp_path, monkeypatch):
    _fresh(tmp_path, monkeypatch)
    monkeypatch.setenv("HYDRAGNN_FUSED_MP", "1")
    # far past the VMEM budget: the force must fall back to segment
    assert not at.use_fused("GIN", 500_000, 2_000_000, 64, 64)
    # cached 'fused' for an oversized bucket falls back too
    monkeypatch.delenv("HYDRAGNN_FUSED_MP")
    sig = at.bucket_signature("GIN", 500_000, 2_000_000, 64)
    at.record_choice(sig, "fused", {})
    assert not at.use_fused("GIN", 500_000, 2_000_000, 64, 64)


def pytest_choices_emitted_as_schema_valid_events(tmp_path, monkeypatch):
    from hydragnn_tpu.obs import runtime as obs_rt
    from hydragnn_tpu.obs.events import validate_events

    _fresh(tmp_path, monkeypatch)
    outdir = str(tmp_path / "obs")
    telem = obs_rt.activate(obs_rt.RunTelemetry("at-test", outdir))
    try:
        at.autotune_bucket("GIN", 48, 160, 8, iters=2)
        at.reset_cache_state()
        at.autotune_bucket("GIN", 48, 160, 8)  # cache-sourced second read
    finally:
        obs_rt.deactivate()
    recs = validate_events(
        os.path.join(outdir, "events.jsonl"), require=["agg_choice"]
    )
    ev = [r for r in recs if r["event"] == "agg_choice"]
    sig = at.bucket_signature("GIN", 48, 160, 8)
    assert any(
        r["bucket"] == sig and r["source"] == "measured"
        and "timings_ms" in r
        for r in ev
    )
    assert any(r["bucket"] == sig and r["source"] == "cache" for r in ev)
    # ...and the labeled gauge carries the same (bucket, choice)
    choice = ev[0]["choice"]
    snap = telem.metrics.registry.get("aggregation_kernel")
    assert any(
        f"bucket={sig}" in k and f"choice={choice}" in k for k in snap
    )


def pytest_failed_probe_disqualifies_not_raises(monkeypatch, tmp_path):
    _fresh(tmp_path, monkeypatch)

    def boom(*a, **k):
        raise RuntimeError("probe broken")

    import hydragnn_tpu.ops.fused_mp as fm

    monkeypatch.setattr(fm, "fused_gather_sum", boom)
    t = at.measure_candidates(48, 160, 8, ("segment", "fused"), iters=2)
    assert "segment" in t and "fused" not in t


def pytest_trainer_warmup_hook(tmp_path, monkeypatch):
    # maybe_autotune: off by default, tunes the example bucket when the
    # env asks, and skips dense-layout batches
    _fresh(tmp_path, monkeypatch)

    class _Model:
        hidden_dim = 8
        partition_axis = None

    class _Batch:
        extras = None

        def __init__(self):
            self.x = np.zeros((48, 8), np.float32)
            self.senders = np.zeros((160,), np.int32)

    assert at.maybe_autotune(_Model(), _Batch(), {}) is None
    monkeypatch.setenv("HYDRAGNN_AUTOTUNE", "1")
    choice = at.maybe_autotune(_Model(), _Batch(), {})
    assert choice in at.CHOICES
    dense_batch = _Batch()
    dense_batch.extras = {"nbr_idx": np.zeros((48, 4), np.int32)}
    assert at.maybe_autotune(_Model(), dense_batch, {}) is None


def pytest_resolve_precision_policy():
    # the param-precision policy (models/create.py): env > explicit >
    # auto width table > conservative default
    from hydragnn_tpu.models.create import resolve_precision
    from hydragnn_tpu.models.pna import PNAStack

    wide = PNAStack(hidden_dim=256, deg=(0, 1))
    narrow = PNAStack(hidden_dim=64, deg=(0, 1))
    assert resolve_precision(wide, {}) == {
        "mixed": False, "source": "default"
    }
    assert resolve_precision(wide, {"mixed_precision": "auto"})["mixed"]
    assert not resolve_precision(narrow, {"mixed_precision": "auto"})["mixed"]
    assert resolve_precision(narrow, {"mixed_precision": True}) == {
        "mixed": True, "source": "explicit"
    }
    os.environ["HYDRAGNN_MIXED_PRECISION"] = "0"
    try:
        assert resolve_precision(wide, {"mixed_precision": True}) == {
            "mixed": False, "source": "env"
        }
    finally:
        del os.environ["HYDRAGNN_MIXED_PRECISION"]
    # DimeNet stays f32 under auto by policy
    from hydragnn_tpu.models.create import BF16_AUTO_MIN_HIDDEN

    assert "DimeNet" not in BF16_AUTO_MIN_HIDDEN
