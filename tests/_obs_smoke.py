"""CI observability smoke driver: a tiny live CPU training with the
introspection surface exercised end to end.

Usage: ``python tests/_obs_smoke.py <outdir>``

Trains 2 epochs with telemetry active and the /healthz+/metrics+/profile
endpoint live, hits ``/profile?steps=1`` from a mid-run hook, then
asserts the run left behind: compile events with non-empty cost/memory
analysis, a completed profile capture with a loadable trace dir, and a
schema-valid ``events.jsonl`` at ``<outdir>/events.jsonl`` — which the CI
step then feeds to ``python -m hydragnn_tpu.obs report --check-budget
.perf-baseline.json``. Exits non-zero on any missing piece.

(Underscore-prefixed: a driver script, not a collected test file. The
pytest twin is tests/test_xla_introspect.py's e2e.)
"""

import json
import os
import sys
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

from _resilience_worker import make_samples  # noqa: E402


class _ProfileOnEpochWriter:
    def __init__(self, url):
        self.url = url
        self.response = None

    def add_scalar(self, tag, value, step):
        # arm at the FIRST epoch's scalar: the remaining epoch's steps
        # drive the capture to completion before the run ends
        if self.response is None and step >= 0:
            self.response = json.loads(
                urllib.request.urlopen(self.url, timeout=30).read()
            )

    def close(self):
        pass


def main(outdir: str) -> int:
    from hydragnn_tpu.data.loaders import GraphLoader, compute_layout
    from hydragnn_tpu.models.create import create_model_config
    from hydragnn_tpu.obs import runtime as obs_rt
    from hydragnn_tpu.obs.events import validate_events
    from hydragnn_tpu.train.epoch_driver import train_validate_test
    from hydragnn_tpu.train.trainer import Trainer

    arch = {
        "model_type": "GIN",
        "input_dim": 1,
        "hidden_dim": 8,
        "num_conv_layers": 2,
        "output_dim": [1, 1],
        "output_type": ["graph", "node"],
        "output_heads": {
            "graph": {
                "num_sharedlayers": 1,
                "dim_sharedlayers": 8,
                "num_headlayers": 1,
                "dim_headlayers": [8],
            },
            "node": {"num_headlayers": 1, "dim_headlayers": [8],
                     "type": "mlp"},
        },
        "task_weights": [1.0, 1.0],
    }
    training = {
        "num_epoch": 2,
        "Optimizer": {"type": "AdamW", "learning_rate": 1e-2},
        "resume_every": 0,
    }
    samples = make_samples()
    layout = compute_layout([samples], batch_size=4)
    loaders = (
        GraphLoader(samples[:16], 4, layout, shuffle=True, seed=7),
        GraphLoader(samples[16:20], 4, layout, shuffle=False),
        GraphLoader(samples[20:], 4, layout, shuffle=False),
    )
    model = create_model_config(arch)
    trainer = Trainer(model, training)
    state = trainer.init_state(next(iter(loaders[0])), seed=0)

    telem = obs_rt.activate(
        obs_rt.RunTelemetry("obs-smoke", outdir, port=0)
    )
    try:
        telem.emit_manifest(
            {"NeuralNetwork": {"Training": training}}, "obs-smoke"
        )
        host, port = telem.address
        writer = _ProfileOnEpochWriter(
            f"http://{host}:{port}/profile?steps=1"
        )
        config_nn = {
            "Training": training,
            "Variables_of_interest": {"output_names": ["sum", "x"]},
        }
        train_validate_test(
            trainer, state, *loaders, config_nn, "obs-smoke",
            verbosity=0, writer=writer,
        )
        assert writer.response is not None, "mid-run /profile never hit"
        assert writer.response["status"] == "armed", writer.response
    finally:
        obs_rt.deactivate()

    recs = validate_events(
        os.path.join(outdir, "events.jsonl"),
        require=["run_manifest", "compile", "profile", "epoch", "run_end"],
    )
    compiles = [r for r in recs if r["event"] == "compile"]
    bad = [
        r for r in compiles
        if not (r["cost"].get("flops") and r["memory"].get("peak_bytes"))
    ]
    assert compiles and not bad, (
        f"compile events missing cost/memory analysis: {bad or 'none'}"
    )
    done = [
        r for r in recs
        if r["event"] == "profile" and r.get("status") == "done"
    ]
    assert done, "profile capture never completed"
    trace_dir = done[-1]["trace_dir"]
    trace_files = [
        f
        for _, _, files in os.walk(trace_dir)
        for f in files
    ]
    assert any(f.endswith(".xplane.pb") for f in trace_files), (
        f"no loadable trace under {trace_dir}: {trace_files}"
    )
    print(
        f"obs smoke ok: {len(compiles)} compile event(s), trace in "
        f"{trace_dir}, events at {os.path.join(outdir, 'events.jsonl')}"
    )
    return 0


if __name__ == "__main__":
    if len(sys.argv) != 2:
        print("usage: python tests/_obs_smoke.py <outdir>", file=sys.stderr)
        sys.exit(2)
    sys.exit(main(sys.argv[1]))
