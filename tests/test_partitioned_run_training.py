"""Giant-graph (partition) mode through the PUBLIC training API.

``Architecture.partition_axis`` routes ``run_training`` to the partitioned
trainer: every sample becomes one graph sharded node-wise over all 8 virtual
devices. Numerics match the unpartitioned model exactly, so the SAME
accuracy ceilings as ``tests/test_graphs.py`` must hold.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from test_graphs import unittest_train_model


def pytest_partitioned_run_training_pna():
    unittest_train_model(
        "PNA",
        "ci.json",
        False,
        overwrite_config={
            "NeuralNetwork": {"Architecture": {"partition_axis": "graph"}}
        },
        num_samples_tot=300,
    )
