"""Giant-graph (partition) mode through the PUBLIC training API.

``Architecture.partition_axis`` routes ``run_training`` to the partitioned
trainer: every sample becomes one graph sharded node-wise over all 8 virtual
devices. Numerics match the unpartitioned model exactly, so the SAME
accuracy ceilings as ``tests/test_graphs.py`` must hold.
"""

import os
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from test_graphs import FULL, unittest_train_model

_OVERWRITE = {"NeuralNetwork": {"Architecture": {"partition_axis": "graph"}}}


def pytest_partitioned_run_training_pna():
    unittest_train_model(
        "PNA", "ci.json", False, overwrite_config=_OVERWRITE,
        num_samples_tot=300,
    )


@pytest.mark.skipif(not FULL, reason="HYDRAGNN_FULL_TEST=1 for the long matrix")
@pytest.mark.parametrize("model_type", ["EGNN", "DimeNet"])
def pytest_partitioned_run_training_hard_paths(model_type):
    """The two hardest partition paths through the public API: EGNN's
    sender-side equivariant aggregation (halo_reduce) and DimeNet's
    2-hop/edge-state halos (triplet tables)."""
    ci = "ci_equivariant.json" if model_type == "EGNN" else "ci.json"
    unittest_train_model(
        model_type, ci, False, overwrite_config=_OVERWRITE,
        num_samples_tot=300,
    )
