"""CI request-tracing + tenant-cost smoke (standalone, NOT a pytest module).

The ISSUE 18 e2e gate: 2 tenants on a 2-replica spec-driven fleet (one
replica slowed by fault injection) behind a tracing FleetRouter —

1. steady state at ``HYDRAGNN_TRACE_SAMPLE=1.0``: every request flushes
   ONE schema-valid span tree (route -> admit -> cache_lookup ->
   attempt -> queue_wait/batch_form/dispatch/readback) whose segment
   durations sum to the end-to-end latency,
2. SIGKILL failover mid-load: a retried request across TWO replicas
   lands in ONE trace — two attempt spans with distinct replica ids,
   the final one 200 with the replica's queue/dispatch spans merged,
3. tail capture at ``HYDRAGNN_TRACE_SAMPLE=0.01``: 100% of SLO-missed
   requests flush a complete trace (the head sample would keep ~1%),
4. ``python -m hydragnn_tpu.obs trace`` reconstructs the trees and
   names queue_wait the dominant segment (the spec's wait cap IS the
   dominant cost under sporadic load),
5. per-tenant device-time bills scraped live from ``/healthz`` merge
   into a fleet bill whose tenant + idle seconds sum to the integrated
   replica-seconds within 1%,
6. cost->quota feedback: the SAME flood run twice — feedback off, then
   ``HYDRAGNN_TENANT_COST_QUOTAS=1`` — shaves the flooding tenant's
   quota (schema-valid ``quota_adjusted`` in the replica streams, down
   to the floor) and the quiet tenant's SLO-miss ratio does not get
   worse (strictly improves whenever the baseline had misses),
7. every event stream validates against the documented schema.

Usage: python tests/_trace_smoke.py <workdir>
"""

import contextlib
import copy
import io
import json
import os
import pickle
import signal
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from _fleet_smoke import ARCH, make_graphs  # noqa: E402

REQUEST_DEADLINE_S = 30.0
# fleet A (tracing): a sizeable wait cap makes queue_wait the dominant
# segment of every sporadic request — exactly what the anatomy table
# must surface. The SLO phase runs FIRST: replica 1's first 10 requests
# are slowed PAST the deadline but still answer 200, so an SLO-missed
# request flushes a COMPLETE tree (replica queue/dispatch spans on
# board) rather than a router-side timeout stub
TRACE_MAX_WAIT_S = 0.3
SLO_DEADLINE_S = 0.6
SLOW_REPLICA_FAULT = "1:0:10@0.4"  # replica 1: +0.4s, first 10 requests
STEADY_REQUESTS = 12
FAILOVER_REQUESTS = 16
SLO_REQUESTS = 24

# fleet B (feedback): tiny wait cap, one flooding tenant, shave fast
FEEDBACK_MAX_WAIT_S = 0.01
FLOOD_CLIENTS = 32
FEEDBACK_ENV = {
    "HYDRAGNN_TENANT_COST_QUOTAS": "1",
    "HYDRAGNN_TENANT_COST_WINDOW_S": "0.4",
    "HYDRAGNN_TENANT_COST_PATIENCE": "2",
    "HYDRAGNN_TENANT_COST_SHAVE": "0.25",
    "HYDRAGNN_TENANT_COST_FLOOR": "0.0625",
}
TENANT_QUOTA = 64
QUOTA_FLOOR = 4  # ceil(64 * 0.0625)
FLOOD_WARMUP_S = 3.0
BETA_PROBES = 14


def build_artifacts(workdir):
    """One checkpoint, plan samples, and two fleet specs sharing them:
    a tracing spec (large wait cap) and a feedback spec (small cap)."""
    from hydragnn_tpu.models.create import create_model_config
    from hydragnn_tpu.serve.buckets import plan_from_samples
    from hydragnn_tpu.train.checkpoint import save_model
    from hydragnn_tpu.train.trainer import Trainer

    samples = make_graphs(32, seed=23)
    plan = plan_from_samples(samples, max_batch_graphs=4, num_buckets=2)
    model = create_model_config(dict(ARCH))
    trainer = Trainer(
        model, {"Optimizer": {"type": "AdamW", "learning_rate": 1e-3}}
    )
    init_batch, _ = plan.pack([samples[0]], 0)
    state = trainer.init_state(init_batch, seed=0)
    ckdir = os.path.join(workdir, "ck")
    save_model(state, "base", path=ckdir)
    samples_path = os.path.join(workdir, "samples.pkl")
    with open(samples_path, "wb") as f:
        pickle.dump(samples, f)

    def write_spec(path, max_wait_s):
        spec = {
            "checkpoint": {"name": "base", "path": ckdir},
            "arch": ARCH,
            "model_name": "m",
            "samples": samples_path,
            "plan": {"max_batch_graphs": 4, "num_buckets": 2},
            "server": {"max_wait_s": max_wait_s, "queue_capacity": 256},
            "tenants": [
                {"name": "acme", "model": "m", "quota": TENANT_QUOTA},
                {"name": "beta", "model": "m", "quota": TENANT_QUOTA},
            ],
            "cache": {"enabled": True},
        }
        with open(path, "w") as f:
            json.dump(spec, f)
        return path

    trace_spec = write_spec(
        os.path.join(workdir, "spec-trace.json"), TRACE_MAX_WAIT_S
    )
    feedback_spec = write_spec(
        os.path.join(workdir, "spec-feedback.json"), FEEDBACK_MAX_WAIT_S
    )
    return samples, trace_spec, feedback_spec


def _jitter(rng, samples):
    """A unique graph per request: repeated structures are absorbed by
    the response cache without ever reaching a replica."""
    import numpy as np

    g = copy.deepcopy(samples[int(rng.integers(len(samples)))])
    g.pos = (
        g.pos + rng.normal(scale=1e-3, size=g.pos.shape)
    ).astype(np.float32)
    return g


def _scrape_fleet_bill(router):
    """Live per-replica cost bills from ``/healthz``, fleet-merged."""
    import urllib.request

    from hydragnn_tpu.serve.costs import merge_bills

    bills = []
    for _rid, port in router.live_replicas():
        try:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz", timeout=5
            ) as resp:
                body = json.loads(resp.read().decode())
        except Exception:
            continue
        if isinstance(body.get("costs"), dict):
            bills.append(body["costs"])
    return merge_bills(bills)


def _assert_linked_tree(trace):
    """Every span's parent resolves inside the trace (or is the explicit
    root marker) and the root route span exists."""
    assert trace["root"] is not None, trace["spans"]
    ids = {s["span"] for s in trace["spans"]}
    for s in trace["spans"]:
        assert s["parent"] == "" or s["parent"] in ids, (
            "orphan span",
            s,
        )


def tracing_fleet(workdir, samples, spec_path):
    """Fleet A: the tracing phases. Returns the measured facts the
    final assertions consume."""
    import numpy as np

    from hydragnn_tpu.obs import trace as trace_mod
    from hydragnn_tpu.obs.__main__ import main as obs_main
    from hydragnn_tpu.obs.events import validate_events
    from hydragnn_tpu.obs.trace import Tracer
    from hydragnn_tpu.serve import (
        DeadlineExceeded,
        FleetRouter,
        ResponseCache,
        ServingFleet,
    )

    coord_dir = os.path.join(workdir, "trace-coord")
    log_dir = os.path.join(workdir, "trace-log")
    os.environ["HYDRAGNN_FAULT_SLOW_REPLICA"] = SLOW_REPLICA_FAULT
    fleet = ServingFleet(
        coord_dir, 2, spec_path=spec_path, heartbeat_s=0.1,
        lease_s=0.75, poll_s=0.05, log_dir=log_dir,
    )
    t_boot = time.monotonic()
    fleet.start(wait_serving=True, timeout=300)
    boot_s = time.monotonic() - t_boot
    assert fleet.health()["live"] == 2, fleet.health()
    router = FleetRouter(
        coord_dir, lease_s=0.75, scan_interval_s=0.1, max_attempts=6,
        retry_base_delay_s=0.05,
        cache=ResponseCache(capacity=256, max_bytes=16 << 20),
    )
    rng = np.random.default_rng(7)
    try:
        # ---- phase 1: tail capture at a 1% head rate -------------------
        # round-robin sends every other request to the slowed replica:
        # those SUCCEED past their deadline (slo_missed on a 200), so
        # the tail rule must flush them — queue/dispatch spans included
        # — while the ~50% fast successes stay at the 1% head rate
        os.environ["HYDRAGNN_TRACE_SAMPLE"] = "0.01"
        tail_tracer = Tracer.from_env(fleet.emit)
        router.tracer = tail_tracer
        client_misses = 0
        for _ in range(SLO_REQUESTS):
            t0 = time.monotonic()
            try:
                router.route(
                    _jitter(rng, samples), tenant="beta",
                    deadline_s=SLO_DEADLINE_S,
                )
            except DeadlineExceeded:
                client_misses += 1
                continue
            if time.monotonic() - t0 > SLO_DEADLINE_S:
                client_misses += 1
        tail_snap = tail_tracer.metrics.snapshot()

        # ---- phase 2: steady state, every trace flushed ----------------
        os.environ["HYDRAGNN_TRACE_SAMPLE"] = "1.0"
        router.tracer = Tracer.from_env(fleet.emit)
        for i in range(STEADY_REQUESTS):
            tenant = ("acme", "beta")[i % 2]
            raw = router.route(
                _jitter(rng, samples), tenant=tenant,
                deadline_s=REQUEST_DEADLINE_S, raw=True,
            )
            assert raw["trace"], "response body must echo the trace id"

        # ---- phase 3: SIGKILL replica 0 -> failover in ONE trace -------
        os.kill(fleet.replica_pid(0), signal.SIGKILL)
        for i in range(FAILOVER_REQUESTS):
            tenant = ("acme", "beta")[i % 2]
            router.route(
                _jitter(rng, samples), tenant=tenant,
                deadline_s=REQUEST_DEADLINE_S,
            )
        fleet.wait_serving(timeout=300)  # the supervisor heals 1 -> 2
        assert fleet.health()["live"] == 2, fleet.health()

        # ---- per-tenant device-time bills sum to replica-seconds ------
        bill = _scrape_fleet_bill(router)
        assert bill, "no cost bills scraped from /healthz"
        busy = sum(t["device_s"] for t in bill["tenants"].values())
        assert abs(busy + bill["idle_s"] - bill["replica_s"]) <= (
            0.01 * bill["replica_s"] + 1e-6
        ), bill
        for tenant in ("acme", "beta"):
            row = bill["tenants"][tenant]
            assert row["requests"] > 0 and row["device_s"] > 0, bill
        # the load generator appends the fleet bill to the event stream
        # (the serve_bench pattern) so `obs report` can print the bill
        for name, row in bill["tenants"].items():
            fleet.emit(
                "tenant_cost", tenant=name,
                device_s=round(row["device_s"], 6),
                flops=row.get("flops", 0.0),
                requests=row.get("requests", 0),
                replica_s=round(bill["replica_s"], 6),
            )
    finally:
        fleet.stop()
        os.environ.pop("HYDRAGNN_FAULT_SLOW_REPLICA", None)
        os.environ.pop("HYDRAGNN_TRACE_SAMPLE", None)

    # ---- the flushed stream is schema-valid and reconstructs ----------
    recs = validate_events(
        os.path.join(log_dir, "events.jsonl"),
        require=["span", "tenant_cost"],
    )
    spans = [r for r in recs if r["event"] == "span"]
    traces = trace_mod.build_traces(spans)
    for t in traces.values():
        _assert_linked_tree(t)

    slo_traces = [
        t for t in traces.values()
        if (t["root"]["attrs"] or {}).get("slo_missed")
    ]
    ok_traces = [
        t for t in traces.values()
        if (t["root"]["attrs"] or {}).get("status") == "ok"
        and not (t["root"]["attrs"] or {}).get("slo_missed")
    ]
    # phases 2+3 ran at sample=1.0: every ok request flushed. Phase 1's
    # fast successes ran at the 1% head rate — at most a couple extra
    n_full = STEADY_REQUESTS + FAILOVER_REQUESTS
    assert n_full <= len(ok_traces) <= n_full + 4, len(ok_traces)
    # 100% tail capture: one flushed SLO-missed trace per client miss
    assert client_misses >= 6, client_misses
    assert len(slo_traces) == client_misses, (
        len(slo_traces), client_misses,
    )
    assert tail_snap["trace_tail_total"] >= client_misses, tail_snap

    dominant_ok = 0
    for t in ok_traces:
        names = {s["name"] for s in t["spans"]}
        # the full anatomy: router spans + the replica spans that rode
        # the response body back
        for required in (
            "route", "admit", "cache_lookup", "attempt",
            "queue_wait", "batch_form", "dispatch", "readback",
        ):
            assert required in names, (required, sorted(names))
        segs = trace_mod.segment_durations(t)
        total = sum(segs.values())
        root_dur = float(t["root"]["dur_s"])
        assert abs(total - root_dur) <= max(0.1 * root_dur, 0.05), (
            "segments must sum to the end-to-end latency",
            segs, root_dur,
        )
        if trace_mod.dominant_segment(t) == "queue_wait":
            dominant_ok += 1
    assert dominant_ok >= 0.8 * len(ok_traces), (
        dominant_ok, len(ok_traces),
    )
    # an SLO-missed trace is complete too: the replica-side expiry 504
    # carries its queue_wait span home before the router gives up
    for t in slo_traces:
        names = {s["name"] for s in t["spans"]}
        assert {"route", "admit", "attempt"} <= names, sorted(names)
    with_queue = sum(
        1 for t in slo_traces
        if any(s["name"] == "queue_wait" for s in t["spans"])
    )
    assert with_queue >= 0.8 * len(slo_traces), (
        with_queue, len(slo_traces),
    )

    # the failover proof: ONE trace, two attempts, two replicas, final
    # 200 with the winning replica's spans merged under its attempt
    failover = None
    for t in traces.values():
        attempts = [s for s in t["spans"] if s["name"] == "attempt"]
        replicas = {s["attrs"].get("replica") for s in attempts}
        statuses = {s["attrs"].get("status") for s in attempts}
        if len(attempts) >= 2 and len(replicas) >= 2 and 200 in statuses:
            failover = t
            break
    assert failover is not None, "no failover trace crossed two replicas"
    names = {s["name"] for s in failover["spans"]}
    assert {"queue_wait", "dispatch"} <= names, sorted(names)

    # the CLI reconstructs the same anatomy and flags the dominant
    # segment per slow trace
    out = io.StringIO()
    with contextlib.redirect_stdout(out):
        rc = obs_main(["trace", log_dir, "--slow", "40"])
    text = out.getvalue()
    assert rc == 0, text
    assert "queue_wait" in text, text
    # the wait cap dominates healthy requests; the slowed replica's
    # SLO-missed traces are flagged transport-dominant — both anatomies
    # must be named in the slow-trace listing
    assert "dominant=queue_wait" in text, text
    assert "dominant=transport" in text, text
    assert "SLO-MISSED" in text, text
    anat = trace_mod.anatomy(traces)
    totals = {
        name: seg["total_s"]
        for name, seg in anat["segments"].items()
        if name != "other"
    }
    assert max(totals, key=totals.get) == "queue_wait", totals

    return {
        "boot_s": boot_s,
        "traces": len(traces),
        "ok_traces": len(ok_traces),
        "slo_traces": len(slo_traces),
        "client_misses": client_misses,
        "bill": bill,
    }


def feedback_fleet(workdir, samples, spec_path, feedback_on):
    """Fleet B, booted twice with identical load: acme floods from
    FLOOD_CLIENTS threads while beta probes sequentially. Returns
    (solo_p50, beta latencies, quota_adjusted records)."""
    import numpy as np

    from hydragnn_tpu.obs.events import validate_events
    from hydragnn_tpu.serve import (
        FleetRouter,
        ServerOverloaded,
        ServingFleet,
    )

    tag = "on" if feedback_on else "off"
    coord_dir = os.path.join(workdir, f"feedback-{tag}-coord")
    log_dir = os.path.join(workdir, f"feedback-{tag}-log")
    for key in FEEDBACK_ENV:
        os.environ.pop(key, None)
    if feedback_on:
        os.environ.update(FEEDBACK_ENV)
    fleet = ServingFleet(
        coord_dir, 2, spec_path=spec_path, heartbeat_s=0.1,
        lease_s=0.75, poll_s=0.05, log_dir=log_dir,
    )
    fleet.start(wait_serving=True, timeout=300)
    router = FleetRouter(
        coord_dir, lease_s=0.75, scan_interval_s=0.1, max_attempts=6,
        retry_base_delay_s=0.05,
    )
    rng = np.random.default_rng(11)
    try:
        # quiet-tenant calibration: unloaded p50 anchors the SLO
        solo = []
        for _ in range(8):
            t0 = time.monotonic()
            router.route(
                _jitter(rng, samples), tenant="beta",
                deadline_s=REQUEST_DEADLINE_S,
            )
            solo.append(time.monotonic() - t0)
        solo_p50 = sorted(solo)[len(solo) // 2]

        stop = threading.Event()
        acme = {"ok": 0, "shed": 0, "failed": 0}
        lock = threading.Lock()

        def flood(seed):
            frng = np.random.default_rng(seed)
            while not stop.is_set():
                g = _jitter(frng, samples)
                try:
                    router.route(
                        g, tenant="acme", deadline_s=REQUEST_DEADLINE_S
                    )
                    out = "ok"
                except ServerOverloaded:
                    out = "shed"
                except Exception:
                    out = "failed"
                with lock:
                    acme[out] += 1

        floods = [
            threading.Thread(target=flood, args=(100 + i,), daemon=True)
            for i in range(FLOOD_CLIENTS)
        ]
        for t in floods:
            t.start()
        # feedback-on: the shave cascade (64 -> 16 -> 4) completes well
        # inside the warmup at WINDOW_S=0.4 / PATIENCE=2 / SHAVE=0.25
        time.sleep(FLOOD_WARMUP_S)
        beta_lat = []
        for _ in range(BETA_PROBES):
            t0 = time.monotonic()
            router.route(
                _jitter(rng, samples), tenant="beta",
                deadline_s=REQUEST_DEADLINE_S,
            )
            beta_lat.append(time.monotonic() - t0)
        stop.set()
        for t in floods:
            t.join(timeout=60)
        assert acme["failed"] == 0, acme
    finally:
        fleet.stop()
        for key in FEEDBACK_ENV:
            os.environ.pop(key, None)

    # replica cost streams: schema-valid, quota_adjusted only when the
    # feedback loop is armed
    adjustments = []
    for fn in sorted(os.listdir(coord_dir)):
        if not (fn.startswith("events-replica") and fn.endswith(".jsonl")):
            continue
        recs = validate_events(os.path.join(coord_dir, fn))
        adjustments.extend(
            r for r in recs if r["event"] == "quota_adjusted"
        )
    return solo_p50, beta_lat, adjustments, dict(acme)


def main(workdir):
    os.makedirs(workdir, exist_ok=True)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    # replicas are separate processes: a shared compilation cache keeps
    # the later boots from re-compiling the same bucket programs
    os.environ.setdefault(
        "JAX_COMPILATION_CACHE_DIR", os.path.join(workdir, "jaxcache")
    )

    samples, trace_spec, feedback_spec = build_artifacts(workdir)

    trace_facts = tracing_fleet(workdir, samples, trace_spec)

    solo_p50, lat_off, adj_off, acme_off = feedback_fleet(
        workdir, samples, feedback_spec, feedback_on=False
    )
    slo_s = max(3.0 * solo_p50, 0.08)
    _solo_on, lat_on, adj_on, acme_on = feedback_fleet(
        workdir, samples, feedback_spec, feedback_on=True
    )
    assert adj_off == [], adj_off  # feedback is OFF by default
    assert adj_on, "no quota_adjusted event with feedback armed"
    shaves = [a for a in adj_on if a["reason"] == "over_cost"]
    assert shaves and all(a["tenant"] == "acme" for a in shaves), adj_on
    assert all(a["new_quota"] < a["old_quota"] for a in shaves), shaves
    assert min(a["new_quota"] for a in shaves) == QUOTA_FLOOR, shaves

    miss_off = sum(1 for v in lat_off if v > slo_s) / len(lat_off)
    miss_on = sum(1 for v in lat_on if v > slo_s) / len(lat_on)
    # shaving the flooder must not hurt the quiet tenant — and must
    # strictly help whenever the baseline actually missed
    assert miss_on < miss_off or miss_on == 0.0, (
        miss_off, miss_on, slo_s,
    )

    print(
        "trace smoke OK: boot {:.1f}s, {} traces flushed ({} ok, {} "
        "SLO-missed = {} client misses, queue_wait dominant), fleet "
        "bill {:.2f}s device / {:.2f}s replica; feedback: acme quota "
        "64 -> {} over {} shave(s), beta SLO-miss {:.0%} -> {:.0%} "
        "(SLO {:.0f}ms, flood ok/shed {}/{} -> {}/{})".format(
            trace_facts["boot_s"], trace_facts["traces"],
            trace_facts["ok_traces"], trace_facts["slo_traces"],
            trace_facts["client_misses"],
            sum(
                t["device_s"]
                for t in trace_facts["bill"]["tenants"].values()
            ),
            trace_facts["bill"]["replica_s"],
            min(a["new_quota"] for a in shaves), len(shaves),
            miss_off, miss_on, slo_s * 1000,
            acme_off["ok"], acme_off["shed"],
            acme_on["ok"], acme_on["shed"],
        )
    )


if __name__ == "__main__":
    main(sys.argv[1])
