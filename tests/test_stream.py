"""Streaming data plane (hydragnn_tpu/data/stream/): shard-granular
sources, deterministic weighted mixing with checkpointable cursors,
distributed window shuffle, the auto-tuned bucket planner, and the
kill->resume + RAM-bound acceptance e2e."""

import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from _resilience_worker import make_samples  # noqa: E402
from test_bucketed_layouts import _oc20_shaped  # noqa: E402

from hydragnn_tpu.data.loaders import (  # noqa: E402
    BucketedLayout,
    GraphLoader,
    compute_layout,
)
from hydragnn_tpu.data.stream import (  # noqa: E402
    BucketPlanner,
    ExtxyzSource,
    ListSource,
    MPTrjSource,
    QM9RawSource,
    ShardStoreSource,
    StreamLoader,
    WeightedMix,
    sample_nbytes,
)


def _mix(seed=7, world=1, rank=0, window=2, weights=(2.0, 1.0), n=(40, 60),
         samples_per_epoch=None):
    a = ListSource(make_samples(n[0], seed=1), shard_size=8, name="a")
    b = ListSource(make_samples(n[1], seed=2), shard_size=8, name="b")
    return WeightedMix(
        [a, b], list(weights), seed=seed, num_shards=world, shard_id=rank,
        window=window, samples_per_epoch=samples_per_epoch,
    )


def _stream_loader(**kw):
    mix = _mix(**kw)
    planner = BucketPlanner(mix.sources, batch_size=4, num_buckets=2)
    return StreamLoader(mix, 4, planner.plan(emit=False))


# ---- sources --------------------------------------------------------------


def pytest_shard_store_source_matches_shard_dataset(tmp_path):
    """Lazy shard reads decode byte-identically to the materialized
    ShardDataset path (shared read_pack_sample), and the index-only size
    scan matches real sample sizes."""
    from hydragnn_tpu.data.shard_store import ShardDataset, ShardWriter

    samples = make_samples(20, seed=3)
    label = str(tmp_path / "store")
    w0 = ShardWriter(label, rank=0)
    w0.add(samples[:12])
    w0.save()
    w1 = ShardWriter(label, rank=1)
    w1.add(samples[12:])
    w1.save()

    src = ShardStoreSource(label)
    ds = ShardDataset(label)
    assert src.num_shards() == 2
    assert src.num_samples() == 20 == len(ds)
    got = src.read_shard(0) + src.read_shard(1)
    for d_stream, d_mat in zip(got, ds):
        np.testing.assert_array_equal(d_stream.x, d_mat.x)
        np.testing.assert_array_equal(d_stream.edge_index, d_mat.edge_index)
        for t1, t2 in zip(d_stream.targets, d_mat.targets):
            np.testing.assert_array_equal(t1, t2)
    nodes, edges = src.size_scan()
    np.testing.assert_array_equal(
        nodes, [d.num_nodes for d in samples]
    )
    np.testing.assert_array_equal(
        edges, [d.num_edges for d in samples]
    )
    ds.close()


def _periodic_frames(num, seed=0):
    """Small periodic cells (some spanning the boundary) with energies +
    forces — extxyz round-trippable."""
    rng = np.random.default_rng(seed)
    frames = []
    for _ in range(num):
        n = int(rng.integers(4, 9))
        # cell > 2 x cutoff on every axis: the PBC builder's duplicate-
        # image guard must stay quiet while boundary pairs still connect
        cell = np.diag(rng.uniform(6.5, 8.0, 3))
        pos = rng.uniform(0, 1, (n, 3)) @ cell
        frames.append(
            {
                "z": np.full(n, 6, np.int64),
                "pos": pos,
                "cell": cell,
                "pbc": np.array([True, True, True]),
                "info": {"energy": float(rng.normal())},
                "arrays": {"forces": rng.normal(size=(n, 3))},
            }
        )
    return frames


def pytest_extxyz_stream_pbc_matches_materialized(tmp_path):
    """Satellite: a periodic cell spanning two STREAMED shards produces
    the same neighbor lists as the materialized path — on-the-fly PBC
    radius graphs in the builder stage are bit-equal to
    ``frame_to_graph``'s."""
    from hydragnn_tpu.data.extxyz import load_extxyz_dir, write_extxyz

    frames = _periodic_frames(8, seed=5)
    d = tmp_path / "xyz"
    d.mkdir()
    # the dataset splits across two shard FILES mid-trajectory
    write_extxyz(str(d / "a.extxyz"), frames[:4])
    write_extxyz(str(d / "b.extxyz"), frames[4:])

    materialized = load_extxyz_dir(str(d), radius=3.0, max_neighbours=12)
    src = ExtxyzSource(dirpath=str(d), radius=3.0, max_neighbours=12)
    streamed = []
    for i in range(src.num_shards()):
        for s in src.read_shard(i):
            streamed.append(src.graph_builder(s))
    assert len(streamed) == len(materialized) == 8
    for s, m in zip(streamed, materialized):
        np.testing.assert_array_equal(s.edge_index, m.edge_index)
        np.testing.assert_allclose(s.edge_attr, m.edge_attr, rtol=0, atol=0)
        np.testing.assert_array_equal(s.x, m.x)
        for t1, t2 in zip(s.targets, m.targets):
            np.testing.assert_array_equal(t1, t2)
        assert s.edge_index.shape[1] > 0  # PBC edges actually formed


def pytest_mptrj_source_matches_load_mptrj(tmp_path):
    from hydragnn_tpu.data.mptrj import load_mptrj, write_mptrj_json

    rng = np.random.default_rng(11)
    records = []
    for i in range(6):
        n = int(rng.integers(3, 7))
        records.append(
            {
                "mp_id": f"mp-{i}",
                "frame_id": f"{i}_0_{i}",
                "z": rng.integers(1, 30, n),
                "pos": rng.uniform(0, 4, (n, 3)),
                "lattice": np.eye(3) * 8.0,
                "energy": float(rng.normal()),
                "forces": rng.normal(size=(n, 3)),
            }
        )
    path = str(tmp_path / "mptrj.json")
    write_mptrj_json(path, records)

    materialized = load_mptrj(path, radius=3.0, max_neighbours=10)
    src = MPTrjSource(path, entries_per_shard=2, radius=3.0, max_neighbours=10)
    assert src.num_shards() == 3
    streamed = []
    for i in range(3):
        for s in src.read_shard(i):
            streamed.append(src.graph_builder(s))
    assert len(streamed) == len(materialized)
    for s, m in zip(streamed, materialized):
        np.testing.assert_array_equal(s.edge_index, m.edge_index)
        np.testing.assert_allclose(s.x, m.x, rtol=0, atol=0)
        for t1, t2 in zip(s.targets, m.targets):
            np.testing.assert_array_equal(t1, t2)


def pytest_qm9_source_matches_dataset(tmp_path):
    from hydragnn_tpu.data.qm9_raw import QM9RawDataset, write_qm9_sdf

    rng = np.random.default_rng(4)
    mols = []
    for _ in range(10):
        n = int(rng.integers(3, 6))
        syms = ["C"] * n
        mols.append((syms, rng.uniform(0, 3, (n, 3))))
    targets = rng.normal(size=(10, 19))
    write_qm9_sdf(str(tmp_path), mols, targets, skips=[2])

    materialized = QM9RawDataset(str(tmp_path), radius=3.0, max_neighbours=4)
    src = QM9RawSource(
        str(tmp_path), molecules_per_shard=4, radius=3.0, max_neighbours=4
    )
    assert src.num_shards() == 3
    assert src.num_samples() == 9  # one skipped
    streamed = []
    for i in range(3):
        for s in src.read_shard(i):
            streamed.append(src.graph_builder(s))
    assert len(streamed) == len(materialized) == 9
    for s, m in zip(streamed, materialized):
        np.testing.assert_allclose(s.x, m.x)
        np.testing.assert_array_equal(s.edge_index, m.edge_index)
        np.testing.assert_allclose(s.targets[0], m.targets[0])


# ---- mix determinism / weights / distribution -----------------------------


def pytest_mix_deterministic_and_weighted():
    seq1 = [(k, d.x.tobytes()) for k, d in _mix(seed=9)]
    seq2 = [(k, d.x.tobytes()) for k, d in _mix(seed=9)]
    assert seq1 == seq2  # same seed -> bitwise-identical draw sequence
    seq3 = [(k, d.x.tobytes()) for k, d in _mix(seed=10)]
    assert seq1 != seq3
    draws = np.bincount([k for k, _ in seq1], minlength=2)
    frac = draws / draws.sum()
    assert abs(frac[0] - 2 / 3) < 0.15, frac  # ~2:1 weighting


def pytest_mix_epochs_advance_cursors():
    """Sources cycle ACROSS epochs: two epochs of a 2:1 mix draw more
    unique source-a samples than one epoch can cover of source b."""
    mix = _mix(seed=3, samples_per_epoch=30)
    seen_epoch0 = {d.x.tobytes() for _, d in mix}
    mix.set_epoch(1)
    seen_epoch1 = {d.x.tobytes() for _, d in mix}
    # a fresh epoch continues the streams, it does not replay epoch 0
    assert seen_epoch0 != seen_epoch1


def pytest_mix_rank_partition():
    """World-of-2 ranks draw equal counts from disjoint shard windows
    (per-pass), and both derive the plan with no communication."""
    r0 = [(k, d.x.tobytes()) for k, d in _mix(world=2, rank=0)]
    r1 = [(k, d.x.tobytes()) for k, d in _mix(world=2, rank=1)]
    assert len(r0) == len(r1) == 50  # ceil(100 / 2)
    # within the first pass the two ranks' sample sets are disjoint
    first0 = {x for _, x in r0[:20]}
    first1 = {x for _, x in r1[:20]}
    assert not (first0 & first1)


def pytest_mix_weight_validation():
    a = ListSource(make_samples(8, seed=1), shard_size=4, name="a")
    with pytest.raises(ValueError, match="weights"):
        WeightedMix([a], [0.0], num_shards=1, shard_id=0)
    with pytest.raises(ValueError, match="weights"):
        WeightedMix([a], [1.0, 2.0], num_shards=1, shard_id=0)


def pytest_mix_schema_mismatch_raises():
    a = ListSource(make_samples(8, seed=1), shard_size=4, name="a")
    bad = make_samples(8, seed=2)
    for d in bad:
        d.targets = [d.targets[0]]
        d.target_types = ["graph"]  # drops the node head
    b = ListSource(bad, shard_size=4, name="b")
    mix = WeightedMix([a, b], seed=1, num_shards=1, shard_id=0)
    with pytest.raises(ValueError, match="head schema"):
        for _ in mix:
            pass


# ---- cursor resume --------------------------------------------------------


def pytest_cursor_resume_replays_bitwise():
    """Restoring the epoch-boundary cursor into a FRESH pipeline replays
    the next epoch's batch stream bitwise — the resume contract the
    checkpoint meta relies on."""
    L1 = _stream_loader(seed=7)
    L1.set_epoch(0)
    for _ in L1:
        pass
    cursor = L1.state_dict()
    L1.set_epoch(1)
    ep1 = [b.x.copy() for b in L1]

    L2 = _stream_loader(seed=7)
    L2.load_state_dict(cursor)
    L2.set_epoch(1)
    ep1b = [b.x.copy() for b in L2]
    assert len(ep1) == len(ep1b)
    for x, y in zip(ep1, ep1b):
        np.testing.assert_array_equal(x, y)


def pytest_cursor_seed_mismatch_refused():
    L1 = _stream_loader(seed=7)
    sd = L1.state_dict()
    L2 = _stream_loader(seed=8)
    with pytest.raises(ValueError, match="seed"):
        L2.load_state_dict(sd)


def pytest_cursor_window_mismatch_refused():
    """A changed shard window silently changes the data order — refused
    like a seed mismatch."""
    m1 = _mix(seed=7, window=2)
    sd = m1.state_dict()
    m2 = _mix(seed=7, window=3)
    with pytest.raises(ValueError, match="window"):
        m2.load_state_dict(sd)


def pytest_cursor_world_resize_rederives():
    """Elastic world resize: the cursor's rank partition no longer
    exists — per-source positions re-derive (fresh), epoch is kept, and
    no error blocks the recovery (PR 8 shard semantics)."""
    m2 = _mix(seed=7, world=2, rank=0)
    for _ in m2:
        pass
    sd = m2.state_dict()
    assert any(
        s["ptr"] or s["offset"] or s["passno"]
        for s in sd["sources"].values()
    )
    m1 = _mix(seed=7, world=1, rank=0)
    with pytest.warns(UserWarning, match="world"):
        m1.load_state_dict(sd)
    assert m1.epoch == m2.epoch
    fresh = _mix(seed=7, world=1, rank=0)
    assert m1.state_dict()["sources"] == fresh.state_dict()["sources"]


def pytest_cursor_msgpack_roundtrip(tmp_path):
    """The cursor survives the checkpoint's msgpack train_meta format
    (ints and string keys only)."""
    from flax import serialization

    L = _stream_loader(seed=7)
    L.set_epoch(0)
    for _ in L:
        pass
    sd = L.state_dict()
    blob = serialization.msgpack_serialize(
        serialization.to_state_dict(sd)
    )
    restored = serialization.msgpack_restore(blob)
    L2 = _stream_loader(seed=7)
    L2.load_state_dict(restored)
    L.set_epoch(1)
    L2.set_epoch(1)
    for x, y in zip(L, L2):
        np.testing.assert_array_equal(x.x, y.x)


# ---- RAM residency bound --------------------------------------------------


def pytest_window_bounds_host_residency():
    """The acceptance RAM bound, asserted: the pipeline's peak buffered
    bytes stay within the shard window's capacity — per source, window x
    its largest shard — while the dataset is far larger."""
    window = 2
    mix = _mix(seed=13, window=window, n=(160, 240))
    planner = BucketPlanner(mix.sources, batch_size=4, num_buckets=2)
    loader = StreamLoader(mix, 4, planner.plan(emit=False))
    loader.set_epoch(0)
    for _ in loader:
        pass
    res = mix.residency_stats()
    assert res["open_shards_peak"] <= window

    def shard_bytes(src):
        return max(
            sum(sample_nbytes(d) for d in src.read_shard(i))
            for i in range(src.num_shards())
        )

    capacity = sum(window * shard_bytes(s) for s in mix.sources)
    total = sum(
        sample_nbytes(d) for s in mix.sources for d in s.samples
    )
    assert res["resident_bytes_peak"] <= capacity
    # the bound is meaningful: the whole dataset would not have fit it
    assert total > capacity


# ---- planner --------------------------------------------------------------


def _hand_table(samples, batch_size, num_buckets):
    """A plausible hand-written bucket table: equal-width node-count
    bounds (what an operator eyeballing the histogram writes down)."""
    from hydragnn_tpu.data.loaders import budget_bucket_layout, _lcm

    nodes = np.array([d.num_nodes for d in samples])
    edges = np.array([d.num_edges for d in samples])
    lo, hi = int(nodes.min()), int(nodes.max())
    step = max((hi - lo) // num_buckets, 1)
    bounds = [min(lo + step * (i + 1), hi) for i in range(num_buckets - 1)]
    bounds.append(hi)
    bounds = sorted(set(bounds))
    head_types = tuple(samples[0].target_types)
    head_dims = tuple(
        t.shape[-1] if t.ndim > 1 else t.shape[0]
        for t in samples[0].targets
    )
    import jax

    mult = _lcm(8, jax.device_count())
    layouts, kept, prev = [], [], 0
    for b in bounds:
        mask = (nodes > prev) & (nodes <= b)
        prev = b
        if not mask.any():
            continue
        kept.append(b)
        layouts.append(
            budget_bucket_layout(
                nodes[mask], edges[mask], np.zeros(int(mask.sum())),
                batch_size, mult, jax.device_count(), head_types, head_dims,
            )
        )
    return BucketedLayout(layouts=layouts, node_bounds=kept)


def pytest_auto_plan_beats_hand_table_on_oc20_mix():
    """Acceptance: on an OC20-shaped synthetic mix the auto-tuned plan's
    padding waste (via the existing epoch_padding_stats accounting) is
    <= both a hand-written equal-width bucket table and the single
    max-sized layout."""
    samples = _oc20_shaped(400, seed=21)
    batch_size = 16

    def measured_waste(layout):
        loader = GraphLoader(
            samples, batch_size, layout, shuffle=False, num_shards=1,
            shard_id=0,
        )
        real, padded = loader.epoch_padding_stats()
        return 1.0 - real / padded

    src = ListSource(samples, shard_size=32, name="oc20")
    planner = BucketPlanner([src], batch_size, num_buckets=4)
    auto = planner.plan(emit=False)
    assert isinstance(auto, BucketedLayout)

    hand = _hand_table(samples, batch_size, num_buckets=4)
    single = compute_layout([samples], batch_size)

    w_auto = measured_waste(auto)
    w_hand = measured_waste(hand)
    w_single = measured_waste(single)
    assert w_auto <= w_hand + 1e-9, (w_auto, w_hand)
    assert w_auto < w_single, (w_auto, w_single)
    # the planner's own estimate tracks the measured integrals
    est = planner.estimate_waste(auto)
    assert abs(est - w_auto) < 0.1, (est, w_auto)


def pytest_bucket_plan_event_schema(tmp_path):
    from hydragnn_tpu.obs import runtime as obs_rt
    from hydragnn_tpu.obs.events import validate_events

    src = ListSource(_oc20_shaped(60, seed=2), shard_size=16, name="oc20")
    telem = obs_rt.activate(
        obs_rt.RunTelemetry("plan", str(tmp_path / "logs"))
    )
    try:
        BucketPlanner([src], batch_size=8, num_buckets=3).plan()
    finally:
        obs_rt.deactivate()
    recs = validate_events(
        str(tmp_path / "logs" / "events.jsonl"), require=["bucket_plan"]
    )
    plan = [r for r in recs if r["event"] == "bucket_plan"][0]
    assert plan["num_buckets"] == len(plan["bounds"])
    assert plan["samples_scanned"] == 60
    assert 0.0 <= plan["est_waste"] < 1.0
    assert plan["per_source"] == {"oc20": 60}


def pytest_planner_size_scan_cap():
    src = ListSource(_oc20_shaped(64, seed=3), shard_size=8, name="s")
    planner = BucketPlanner([src], batch_size=8, num_buckets=2,
                            plan_shards=2)
    assert planner.scan()["nodes"].size == 16  # 2 shards x 8


# ---- stream loader mechanics ----------------------------------------------


def pytest_oversize_samples_dropped_warned_and_counted(tmp_path):
    from hydragnn_tpu.obs import runtime as obs_rt

    samples = make_samples(24, seed=5)
    big = make_samples(1, seed=6)[0]
    big.x = np.random.default_rng(0).random((4000, 1)).astype(np.float32)
    big.edge_index = np.zeros((2, 1), np.int64)
    big.targets = [np.array([1.0], np.float32), big.x.copy()]
    big.target_types = ["graph", "node"]
    src = ListSource(samples + [big], shard_size=8, name="a")
    mix = WeightedMix([src], seed=1, num_shards=1, shard_id=0)
    planner = BucketPlanner([src], batch_size=4, num_buckets=1,
                            plan_shards=3)  # the scan never sees `big`
    loader = StreamLoader(mix, 4, planner.plan(emit=False))
    loader.set_epoch(0)
    telem = obs_rt.activate(
        obs_rt.RunTelemetry("ovs", str(tmp_path / "logs"), events=False)
    )
    try:
        with pytest.warns(UserWarning, match="fit no bucket"):
            n = sum(1 for _ in loader)
        assert n > 0
        assert loader._epoch_stats["oversize_dropped"] >= 1
        # size-biased data loss is a visible series, not a private dict
        assert telem.metrics.snapshot()[
            "stream_oversize_dropped_total"
        ] >= 1
    finally:
        obs_rt.deactivate()


def pytest_plan_covers_eval_splits():
    """An eval graph LARGER than any train graph still gets a bucket:
    the assembled plan folds the materialized splits' sizes into the
    histogram, so evaluation cannot hit the collator's overflow."""
    from hydragnn_tpu.data.stream import assemble_stream_loaders

    train = make_samples(24, seed=1)  # all 6-node graphs
    big_eval = _oc20_shaped(8, seed=2)  # 20-250 nodes
    src = ListSource(train, shard_size=8, name="a")
    _, val_loader, _, _ = assemble_stream_loaders(
        [src], None, 4, {"num_buckets": 2, "seed": 3},
        big_eval, make_samples(4, seed=4),
    )
    batches = list(val_loader)  # collates without overflow
    assert sum(int(b.graph_mask.sum()) for b in batches) == len(big_eval)


def pytest_prefetch_path_identical_to_inline():
    inline = _stream_loader(seed=17)
    inline.prefetch = 0
    inline.set_epoch(0)
    seq_inline = [b.x.copy() for b in inline]
    threaded = _stream_loader(seed=17)
    threaded.prefetch = 3
    threaded.set_epoch(0)
    seq_threaded = [b.x.copy() for b in threaded]
    assert len(seq_inline) == len(seq_threaded)
    for x, y in zip(seq_inline, seq_threaded):
        np.testing.assert_array_equal(x, y)


def pytest_stream_gauges_populated(tmp_path):
    from hydragnn_tpu.obs import runtime as obs_rt

    telem = obs_rt.activate(
        obs_rt.RunTelemetry("gauges", str(tmp_path / "logs"))
    )
    try:
        loader = _stream_loader(seed=19)
        loader.set_epoch(0)
        for _ in loader:
            pass
        snap = telem.metrics.snapshot()
        assert snap["stream_samples_total"] == 100
        assert snap["stream_open_shards_peak"] >= 1
        assert snap["stream_resident_bytes_peak"] > 0
        rendered = telem.metrics.render_prometheus()
        assert "hydragnn_train_stream_source_fraction" in rendered
    finally:
        obs_rt.deactivate()


def pytest_example_batch_does_not_advance_cursor():
    loader = _stream_loader(seed=23)
    before = loader.state_dict()
    loader.example_batch()
    assert loader.state_dict() == before


# ---- env knob validation --------------------------------------------------


def pytest_env_knob_validation(monkeypatch):
    """Satellite: numeric env knobs fail with the VARIABLE named, not a
    bare int() ValueError."""
    from hydragnn_tpu.utils.envparse import env_int

    monkeypatch.setenv("HYDRAGNN_PREFETCH", "two")
    with pytest.raises(ValueError, match="HYDRAGNN_PREFETCH"):
        GraphLoader(
            make_samples(8, seed=1), 4,
            compute_layout([make_samples(8, seed=1)], 4),
            num_shards=1, shard_id=0,
        )
    monkeypatch.setenv("HYDRAGNN_PREFETCH", "-3")
    with pytest.raises(ValueError, match="HYDRAGNN_PREFETCH"):
        GraphLoader(
            make_samples(8, seed=1), 4,
            compute_layout([make_samples(8, seed=1)], 4),
            num_shards=1, shard_id=0,
        )
    monkeypatch.delenv("HYDRAGNN_PREFETCH")

    monkeypatch.setenv("HYDRAGNN_STREAM_WINDOW", "0")
    with pytest.raises(ValueError, match="HYDRAGNN_STREAM_WINDOW"):
        _mix(window=None)
    monkeypatch.setenv("HYDRAGNN_STREAM_WINDOW", "x")
    with pytest.raises(ValueError, match="HYDRAGNN_STREAM_WINDOW"):
        _mix(window=None)
    monkeypatch.delenv("HYDRAGNN_STREAM_WINDOW")

    monkeypatch.setenv("HYDRAGNN_STREAM_QUEUE", "1.5")
    mix = _mix()
    layout = BucketPlanner(mix.sources, 4, num_buckets=1).plan(emit=False)
    with pytest.raises(ValueError, match="HYDRAGNN_STREAM_QUEUE"):
        StreamLoader(mix, 4, layout)
    monkeypatch.delenv("HYDRAGNN_STREAM_QUEUE")

    assert env_int("HYDRAGNN_NOT_SET_ANYWHERE", 5) == 5


# ---- train e2e: weighted mix + kill->resume bitwise -----------------------


def _build_stream_training(num_epoch, seed=7):
    from hydragnn_tpu.models.create import create_model_config
    from hydragnn_tpu.train.trainer import Trainer

    arch = {
        "model_type": "GIN",
        "input_dim": 1,
        "hidden_dim": 8,
        "num_conv_layers": 2,
        "output_dim": [1, 1],
        "output_type": ["graph", "node"],
        "output_heads": {
            "graph": {
                "num_sharedlayers": 1,
                "dim_sharedlayers": 8,
                "num_headlayers": 1,
                "dim_headlayers": [8],
            },
            "node": {"num_headlayers": 1, "dim_headlayers": [8],
                     "type": "mlp"},
        },
        "task_weights": [1.0, 1.0],
    }
    training = {
        "num_epoch": num_epoch,
        "Optimizer": {"type": "AdamW", "learning_rate": 1e-2},
        "resume_every": 1,
        "checkpoint_keep_last": 3,
    }
    mix = _mix(seed=seed, samples_per_epoch=32)
    planner = BucketPlanner(mix.sources, batch_size=4, num_buckets=2)
    layout = planner.plan(emit=False)
    train_loader = StreamLoader(mix, 4, layout)
    evals = make_samples(8, seed=30)
    val_loader = GraphLoader(evals[:4], 4, layout, shuffle=False,
                             num_shards=1, shard_id=0)
    test_loader = GraphLoader(evals[4:], 4, layout, shuffle=False,
                              num_shards=1, shard_id=0)
    model = create_model_config(arch)
    trainer = Trainer(model, training)
    state = trainer.init_state(train_loader.example_batch(), seed=0)
    return trainer, state, (train_loader, val_loader, test_loader), training


def _leaves(state):
    import jax

    return [
        np.asarray(x)
        for x in jax.tree_util.tree_leaves(jax.device_get(state.params))
    ]


def pytest_stream_train_resume_bitwise(tmp_path, monkeypatch):
    """Acceptance e2e: a two-source weighted mix trains through the real
    epoch driver; a run stopped at epoch 1 and resumed through the
    checkpoint's train_meta (stream cursor included) reaches the SAME
    final parameters, bitwise, as the uninterrupted run."""
    from hydragnn_tpu.train.checkpoint import (
        load_state_dict,
        pop_train_meta,
        restore_into,
    )
    from hydragnn_tpu.train.epoch_driver import train_validate_test

    config_vars = {"output_names": ["sum", "x"]}

    # uninterrupted 4-epoch reference
    monkeypatch.chdir(tmp_path)
    os.makedirs("full", exist_ok=True)
    monkeypatch.chdir(tmp_path / "full")
    trainer, state, loaders, training = _build_stream_training(4)
    state_full = train_validate_test(
        trainer, state, *loaders, {"Training": training,
                                   "Variables_of_interest": config_vars},
        "streamrun", verbosity=0,
    )

    # stopped-at-2 run, then resume 2->4 with a FRESH pipeline
    monkeypatch.chdir(tmp_path)
    os.makedirs("killed", exist_ok=True)
    monkeypatch.chdir(tmp_path / "killed")
    trainer, state, loaders, training = _build_stream_training(2)
    train_validate_test(
        trainer, state, *loaders, {"Training": training,
                                   "Variables_of_interest": config_vars},
        "streamrun", verbosity=0,
    )
    trainer2, state2, loaders2, training2 = _build_stream_training(4)
    restored = load_state_dict("streamrun")
    meta = pop_train_meta(restored)
    assert meta is not None and meta.get("stream") is not None
    # cursor equality with the reference run's post-epoch-1 position
    state2 = trainer2.place_state(restore_into(state2, restored))
    state_resumed = train_validate_test(
        trainer2, state2, *loaders2, {"Training": training2,
                                      "Variables_of_interest": config_vars},
        "streamrun", verbosity=0, resume_meta=meta,
    )

    for a, b in zip(_leaves(state_full), _leaves(state_resumed)):
        np.testing.assert_array_equal(a, b)


# ---- driver path: Dataset.streaming config --------------------------------


def pytest_driver_streaming_config_e2e(tmp_path, monkeypatch):
    """``Dataset.streaming`` routes run_training through the stream
    builders: config derivation over the probe window, auto bucket plan,
    training + checkpoint, and the cursor landing in train_meta."""
    import hydragnn_tpu
    from hydragnn_tpu.data.shard_store import ShardWriter
    from hydragnn_tpu.train.checkpoint import (
        load_state_dict,
        pop_train_meta,
    )

    monkeypatch.chdir(tmp_path)
    for fam, seed in (("fam_a", 1), ("fam_b", 2)):
        samples = make_samples(24, seed=seed)
        for split, chunk in (
            ("trainset", samples[:16]),
            ("valset", samples[16:20]),
            ("testset", samples[20:]),
        ):
            w = ShardWriter(f"dataset/{fam}_{split}", rank=0)
            w.add(chunk)
            w.save()

    config = {
        "Verbosity": {"level": 0},
        "Dataset": {
            "name": "streamdrv",
            "streaming": {
                "sources": [
                    {
                        "format": "shard_store",
                        "train": f"dataset/{fam}_trainset",
                        "validate": f"dataset/{fam}_valset",
                        "test": f"dataset/{fam}_testset",
                        "weight": wgt,
                    }
                    for fam, wgt in (("fam_a", 2.0), ("fam_b", 1.0))
                ],
                "window_shards": 2,
                "num_buckets": 2,
                "samples_per_epoch": 16,
                "seed": 5,
            },
        },
        "NeuralNetwork": {
            "Architecture": {
                "model_type": "GIN",
                "radius": 2.0,
                "max_neighbours": 10,
                "periodic_boundary_conditions": False,
                "hidden_dim": 8,
                "num_conv_layers": 2,
                "output_heads": {
                    "graph": {
                        "num_sharedlayers": 1,
                        "dim_sharedlayers": 8,
                        "num_headlayers": 1,
                        "dim_headlayers": [8],
                    },
                },
                "task_weights": [1.0],
            },
            "Variables_of_interest": {
                "input_node_features": [0],
                "output_names": ["sum"],
                "output_index": [0],
                "type": ["graph"],
                "denormalize_output": False,
            },
            "Training": {
                "num_epoch": 2,
                "perc_train": 0.7,
                "loss_function_type": "mse",
                "batch_size": 4,
                "resume_every": 1,
                "Optimizer": {"type": "AdamW", "learning_rate": 1e-2},
            },
        },
        "Visualization": {"create_plots": False},
    }
    hydragnn_tpu.run_training(config)
    log_name = [d for d in os.listdir("logs") if "streamdrv" in d]
    assert log_name, os.listdir("logs")
    meta = pop_train_meta(load_state_dict(log_name[0]))
    assert meta is not None and meta.get("stream") is not None
    assert int(np.asarray(meta["epoch"])) == 1
    # the plan record lands in the run's event stream even though the
    # loaders were built before telemetry activated
    from hydragnn_tpu.obs.events import validate_events

    validate_events(
        os.path.join("logs", log_name[0], "events.jsonl"),
        require=["bucket_plan", "epoch", "run_manifest"],
    )
