"""HPO engine: samplers converge, pruner prunes, launcher parses.

Mirrors the role the reference's DeepHyper/Optuna drivers play
(``examples/qm9_hpo``, ``examples/multidataset_hpo``) with the native
implementation in ``hydragnn_tpu/hpo``.
"""

import os
import sys
import textwrap

import numpy as np

from hydragnn_tpu.hpo import TrialLauncher, TrialPruned, create_study, parse_val_loss


def pytest_random_search_quadratic():
    study = create_study(sampler="random", seed=1)

    def objective(trial):
        x = trial.suggest_float("x", -5.0, 5.0)
        return (x - 2.0) ** 2

    study.optimize(objective, n_trials=60)
    assert abs(study.best_params["x"] - 2.0) < 1.0
    assert study.best_value < 1.0


def pytest_tpe_beats_pure_chance():
    # TPE should concentrate samples near the optimum after startup
    study = create_study(sampler="tpe", seed=3, n_startup=10)

    def objective(trial):
        x = trial.suggest_float("x", 0.0, 10.0)
        y = trial.suggest_float("y", 1e-3, 10.0, log=True)
        return (x - 7.0) ** 2 + (np.log(y) - np.log(0.1)) ** 2

    study.optimize(objective, n_trials=80)
    assert study.best_value < 0.5
    late = [t.params["x"] for t in study.completed[40:]]
    assert abs(np.median(late) - 7.0) < 2.0  # concentrated, not uniform


def pytest_categorical_and_int_spaces():
    study = create_study(sampler="tpe", seed=0, n_startup=8)

    def objective(trial):
        m = trial.suggest_categorical("model", ["PNA", "GIN", "SAGE"])
        h = trial.suggest_int("hidden", 16, 256)
        base = {"PNA": 0.0, "GIN": 1.0, "SAGE": 2.0}[m]
        return base + abs(h - 64) / 64.0

    study.optimize(objective, n_trials=50)
    assert study.best_params["model"] == "PNA"
    assert isinstance(study.best_params["hidden"], int)
    assert abs(study.best_params["hidden"] - 64) < 48


def pytest_redefining_param_space_rejected():
    study = create_study(seed=0)
    t = study.ask()
    t.suggest_float("x", 0.0, 1.0)
    t2 = study.ask()
    try:
        t2.suggest_float("x", 0.0, 2.0)
        raise AssertionError("expected ValueError for redefined space")
    except ValueError:
        pass


def pytest_median_pruner():
    study = create_study(sampler="random", seed=0, pruner_warmup_trials=3)

    def objective(trial):
        x = trial.suggest_float("x", 0.0, 1.0)
        for step in range(1, 4):
            trial.report(x * step, step)
            if trial.should_prune():
                raise TrialPruned()
        return x

    study.optimize(objective, n_trials=30)
    pruned = [t for t in study.trials if t.state == "pruned"]
    completed = study.completed
    assert pruned, "median pruner never fired"
    # pruned trials must be the worse half at their final reported step
    assert np.median([t.params["x"] for t in pruned]) > np.median(
        [t.params["x"] for t in completed]
    )


def pytest_launcher_parses_and_runs(tmp_path, monkeypatch):
    assert parse_val_loss("Epoch 1\nVal Loss: 0.5\nVal Loss: 1.25e-2\n") == 0.0125
    assert parse_val_loss("no metric here") is None

    script = tmp_path / "fake_train.py"
    script.write_text(
        textwrap.dedent(
            """
            import sys
            args = dict(a.split("=", 1) for a in sys.argv[1:] if "=" in a)
            x = float(args["--x"])
            print(f"Val Loss: {(x - 3.0) ** 2}")
            """
        )
    )
    monkeypatch.delenv("SLURM_JOB_ID", raising=False)
    launcher = TrialLauncher(str(script), log_dir=str(tmp_path / "logs"))
    study = create_study(sampler="random", seed=0)

    def objective(trial):
        trial.suggest_float("x", 0.0, 6.0)
        return launcher.run(trial)

    study.optimize(objective, n_trials=8)
    assert study.best_value < 4.0
    # per-trial output files land in the log dir
    assert (tmp_path / "logs" / "output_0.txt").exists()


def pytest_launcher_failure_is_inf(tmp_path):
    script = tmp_path / "crash.py"
    script.write_text("raise SystemExit(1)\n")
    launcher = TrialLauncher(str(script), log_dir=str(tmp_path / "logs"))
    study = create_study(sampler="random", seed=0)
    t = study.ask()
    t.suggest_float("x", 0.0, 1.0)
    assert launcher.run(t) == float("inf")


def _trial_events(log_dir):
    from hydragnn_tpu.obs.events import validate_events

    return [
        r
        for r in validate_events(
            os.path.join(str(log_dir), "trials.jsonl"), require=["hpo_trial"]
        )
        if r["event"] == "hpo_trial"
    ]


def pytest_garbled_output_is_failed_with_structured_event(tmp_path,
                                                          monkeypatch):
    """A trial that exits 0 but prints no parseable metric must be marked
    FAILED by a schema-valid ``hpo_trial`` event (reason: garbled_output)
    and score +inf — never be silently treated as a score."""
    monkeypatch.delenv("SLURM_JOB_ID", raising=False)
    script = tmp_path / "garbled.py"
    script.write_text("print('Vol Less: 0.3 (typo, not a metric)')\n")
    logs = tmp_path / "logs"
    launcher = TrialLauncher(str(script), log_dir=str(logs))
    study = create_study(sampler="random", seed=0)
    t = study.ask()
    t.suggest_float("x", 0.0, 1.0)
    assert launcher.run(t) == float("inf")
    evs = _trial_events(logs)
    assert evs[-1]["status"] == "failed"
    assert evs[-1]["reason"] == "garbled_output"
    assert evs[-1]["trial"] == t.number
    # ...and through the concurrent driver the trial is TOLD as failed,
    # releasing its node block for the next trial
    launcher2 = TrialLauncher(str(script), log_dir=str(logs))
    study2 = create_study(sampler="random", seed=0)
    optimize_concurrent_kwargs = dict(
        n_trials=2, max_concurrent=1, nodes=["nodeA"],
    )
    from hydragnn_tpu.hpo import optimize_concurrent

    try:
        optimize_concurrent(
            study2, launcher2, lambda tr: tr.suggest_float("x", 0, 1),
            **optimize_concurrent_kwargs,
        )
    except Exception:
        pass  # every trial failed -> best_trial may not exist
    assert sum(1 for tr in study2.trials if tr.state == "failed") == 2
    assert len(_trial_events(logs)) >= 3


def pytest_completed_trial_emits_event_with_score(tmp_path, monkeypatch):
    monkeypatch.delenv("SLURM_JOB_ID", raising=False)
    script = tmp_path / "ok.py"
    script.write_text("print('Val Loss: 0.125')\n")
    logs = tmp_path / "logs"
    launcher = TrialLauncher(str(script), log_dir=str(logs))
    study = create_study(sampler="random", seed=0)
    t = study.ask()
    t.suggest_float("x", 0.0, 1.0)
    assert launcher.run(t, nodelist=["n1", "n2"]) == 0.125
    ev = _trial_events(logs)[-1]
    assert ev["status"] == "completed"
    assert ev["val_loss"] == 0.125
    assert ev["nodes"] == ["n1", "n2"]


def pytest_heartbeat_stale_trial_is_early_killed(tmp_path, monkeypatch):
    """The elastic early-kill signal: a trial whose heartbeat lease goes
    stale (hung collective / wedged host) is killed well before the hard
    timeout, marked ``killed:heartbeat_timeout``, and scored +inf."""
    import textwrap
    import time

    monkeypatch.delenv("SLURM_JOB_ID", raising=False)
    script = tmp_path / "hung.py"
    script.write_text(
        textwrap.dedent(
            """
            import json, os, time
            # one heartbeat, then the 'collective' wedges forever
            with open(os.environ["HYDRAGNN_HEARTBEAT_FILE"], "w") as f:
                json.dump({"ts": time.time(), "step": 1}, f)
            time.sleep(600)
            print("Val Loss: 0.0")
            """
        )
    )
    logs = tmp_path / "logs"
    launcher = TrialLauncher(
        str(script), log_dir=str(logs), timeout=120, heartbeat_timeout=1.0
    )
    study = create_study(sampler="random", seed=0)
    t = study.ask()
    t.suggest_float("x", 0.0, 1.0)
    t0 = time.time()
    assert launcher.run(t) == float("inf")
    assert time.time() - t0 < 60  # killed by the lease, not the timeout
    ev = _trial_events(logs)[-1]
    assert ev["status"] == "killed"
    assert ev["reason"] == "heartbeat_timeout"


def pytest_diverging_trial_is_early_killed(tmp_path, monkeypatch):
    """The divergence-guard early kill: a trial whose heartbeat reports
    guard restores past the budget is killed and marked
    ``killed:divergence`` — freeing its nodes instead of burning the
    remaining epochs on a diverging config."""
    import textwrap

    monkeypatch.delenv("SLURM_JOB_ID", raising=False)
    script = tmp_path / "diverge.py"
    script.write_text(
        textwrap.dedent(
            """
            import json, os, time
            # keep the lease FRESH while reporting ever-more restores:
            # only the divergence budget can kill this trial
            path = os.environ["HYDRAGNN_HEARTBEAT_FILE"]
            for i in range(600):
                with open(path, "w") as f:
                    json.dump({"ts": time.time(), "step": i,
                               "guard_restores": i}, f)
                time.sleep(0.1)
            print("Val Loss: 0.0")
            """
        )
    )
    logs = tmp_path / "logs"
    launcher = TrialLauncher(
        str(script), log_dir=str(logs), timeout=120,
        heartbeat_timeout=30.0, max_guard_restores=3,
    )
    study = create_study(sampler="random", seed=0)
    t = study.ask()
    t.suggest_float("x", 0.0, 1.0)
    assert launcher.run(t) == float("inf")
    ev = _trial_events(logs)[-1]
    assert ev["status"] == "killed"
    assert ev["reason"] == "divergence"


def pytest_hung_collective_detected_through_fresh_lease(tmp_path):
    """The real wiring's hang shape: the lease DAEMON keeps stamping
    ``ts`` while the training thread is wedged, so ``progress_ts`` (only
    advanced by real optimizer steps) is the staleness signal — a fresh
    ``ts`` with stale ``progress_ts`` must still kill; a fresh lease with
    NO progress yet (compile/data load) must not."""
    import json
    import time

    launcher = TrialLauncher(
        "unused", log_dir=str(tmp_path / "logs"), heartbeat_timeout=5.0
    )
    hb = tmp_path / "hb.json"
    now = time.time()
    # wedged training thread, live daemon: stale progress, fresh ts
    hb.write_text(json.dumps({"ts": now, "progress_ts": now - 100}))
    assert launcher._kill_reason(str(hb), started=now) == "heartbeat_timeout"
    # compiling trial: fresh ts, no progress reported yet -> alive
    hb.write_text(json.dumps({"ts": now, "progress_ts": 0.0}))
    assert launcher._kill_reason(str(hb), started=now) is None
    # wedged HOST: everything stale -> killed via the ts fallback
    hb.write_text(json.dumps({"ts": now - 100}))
    assert launcher._kill_reason(str(hb), started=now) == "heartbeat_timeout"


def pytest_launcher_early_kill_knobs_from_env(tmp_path, monkeypatch):
    monkeypatch.setenv("HPO_HEARTBEAT_TIMEOUT_S", "7.5")
    monkeypatch.setenv("HPO_MAX_GUARD_RESTORES", "4")
    launcher = TrialLauncher("unused", log_dir=str(tmp_path / "logs"))
    assert launcher.heartbeat_timeout == 7.5
    assert launcher.max_guard_restores == 4
    # explicit args beat the env
    launcher2 = TrialLauncher(
        "unused", log_dir=str(tmp_path / "logs"),
        heartbeat_timeout=1.0, max_guard_restores=1,
    )
    assert launcher2.heartbeat_timeout == 1.0
    assert launcher2.max_guard_restores == 1


def pytest_concurrent_trials_overlap(tmp_path, monkeypatch):
    """optimize_concurrent keeps N trials in flight (the reference's
    DeepHyper multi-node scheduler shape): with 4-way concurrency the
    observed in-flight count must actually reach 4, every trial
    completes, and the sampler still finds the optimum region. (Wall-time
    assertions with real subprocesses are unusable here — interpreter
    startup is CPU-bound and the CI host has one core — so the launcher's
    run is stubbed with a sleeper.)"""
    import threading
    import time

    from hydragnn_tpu.hpo import optimize_concurrent

    monkeypatch.delenv("SLURM_JOB_ID", raising=False)
    monkeypatch.delenv("HPO_NODELIST", raising=False)
    monkeypatch.delenv("HPO_MAX_CONCURRENT", raising=False)
    launcher = TrialLauncher("unused", log_dir=str(tmp_path / "logs"))
    lock = threading.Lock()
    live = {"now": 0, "peak": 0}

    def fake_run(trial, nodelist=None):
        with lock:
            live["now"] += 1
            live["peak"] = max(live["peak"], live["now"])
        time.sleep(0.2)
        with lock:
            live["now"] -= 1
        return (trial.params["x"] - 3.0) ** 2

    launcher.run = fake_run
    study = create_study(sampler="random", seed=0)
    best = optimize_concurrent(
        study, launcher, lambda t: t.suggest_float("x", 0.0, 6.0),
        n_trials=8, max_concurrent=4,
    )
    assert len(study.completed) == 8
    assert best is not None and best.value < 4.0
    assert live["peak"] == 4, f"peak concurrency {live['peak']}, wanted 4"


def pytest_concurrent_node_blocks_disjoint(tmp_path, monkeypatch):
    """Concurrent trials must be pinned to DISJOINT node blocks while in
    flight (reference: one srun --nodelist block per trial)."""
    from hydragnn_tpu.hpo import NodePool, optimize_concurrent

    monkeypatch.delenv("SLURM_JOB_ID", raising=False)
    pool_nodes = [f"node{i}" for i in range(4)]
    launcher = TrialLauncher("unused", log_dir=str(tmp_path / "logs"))
    launcher.nnodes = 2

    inflight, overlaps, seen = [], [], []

    def fake_run(trial, nodelist=None):
        import time

        assert nodelist is not None and len(nodelist) == 2
        for other in list(inflight):
            if set(other) & set(nodelist):
                overlaps.append((other, nodelist))
        inflight.append(nodelist)
        seen.append(tuple(nodelist))
        time.sleep(0.1)
        inflight.remove(nodelist)
        return float(trial.number)

    launcher.run = fake_run
    study = create_study(sampler="random", seed=0)
    best = optimize_concurrent(
        study,
        launcher,
        lambda t: t.suggest_float("x", 0.0, 1.0),
        n_trials=6,
        nodes=pool_nodes,
    )
    assert not overlaps, overlaps
    assert len(seen) == 6
    assert best.value == 0.0  # trial 0 returned 0.0

    # pool exhaustion is a loud error, not a silent shared block
    pool = NodePool(["a", "b"])
    pool.acquire(2)
    import pytest as _pytest

    with _pytest.raises(RuntimeError):
        pool.acquire(1)


def pytest_concurrent_failures_marked_failed(tmp_path, monkeypatch):
    """+inf results are told as failed: the sampler must not learn from
    crashed trials and best_trial must ignore them."""
    from hydragnn_tpu.hpo import optimize_concurrent

    monkeypatch.delenv("SLURM_JOB_ID", raising=False)
    launcher = TrialLauncher("unused", log_dir=str(tmp_path / "logs"))
    launcher.run = lambda trial, nodelist=None: (
        float("inf") if trial.number % 2 else float(trial.number + 1)
    )
    study = create_study(sampler="random", seed=0)
    best = optimize_concurrent(
        study, launcher, lambda t: t.suggest_float("x", 0.0, 1.0),
        n_trials=6, max_concurrent=2,
    )
    assert len(study.completed) == 3
    assert sum(1 for t in study.trials if t.state == "failed") == 3
    assert best.value == 1.0
