"""Worker for the 2-D mesh CI smoke (NOT a pytest module).

One small deterministic training through the REAL epoch driver on
whatever mesh the environment resolves (``HYDRAGNN_MESH`` /
``Training.model_parallel`` via ``MESH_SMOKE_MODEL_PARALLEL``), with live
telemetry so the parent can schema-validate the ``mesh_shape`` /
``param_sharding`` / ``world_resize`` events. Modes::

    python _mesh_worker.py <workdir> run      # fresh run
    python _mesh_worker.py <workdir> resume   # Training.continue path

``MESH_SMOKE_DEVICES`` sets the forced host-platform device count (the
parent shrinks it to 7 for the elastic re-derivation phase). The worker
asserts the per-epoch compile count stays FLAT after the first epoch and
dumps ``result.json`` with the loss trajectory. A run killed by
``HYDRAGNN_FAULT_KILL_AT_STEP`` exits hard and leaves only checkpoints.
"""

import json
import os
import sys
import time

os.environ["XLA_FLAGS"] = (
    os.environ.get("MESH_SMOKE_BASE_XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count="
    + os.environ.get("MESH_SMOKE_DEVICES", "8")
).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np

NUM_EPOCH = int(os.environ.get("MESH_SMOKE_EPOCHS", "2"))
LOG_NAME = "mesh-smoke"


def make_samples(num=24, seed=11):
    from hydragnn_tpu.data.dataobj import GraphData

    rng = np.random.default_rng(seed)
    out = []
    for _ in range(num):
        n = 6
        g = GraphData()
        g.x = rng.random((n, 1)).astype(np.float32)
        g.pos = rng.random((n, 3)).astype(np.float32)
        src = np.arange(n)
        dst = (src + 1) % n
        g.edge_index = np.stack(
            [np.concatenate([src, dst]), np.concatenate([dst, src])]
        ).astype(np.int64)
        g.edge_attr = None
        g.targets = [np.array([g.x.sum()], np.float32), g.x.copy()]
        g.target_types = ["graph", "node"]
        out.append(g)
    return out


def build():
    from hydragnn_tpu.data.loaders import GraphLoader, compute_layout
    from hydragnn_tpu.models.create import create_model_config
    from hydragnn_tpu.parallel.mesh import resolve_mesh
    from hydragnn_tpu.train.trainer import Trainer

    arch = {
        "model_type": "GIN",
        "input_dim": 1,
        "hidden_dim": 8,
        "num_conv_layers": 2,
        "output_dim": [1, 1],
        "output_type": ["graph", "node"],
        "output_heads": {
            "graph": {
                "num_sharedlayers": 1,
                "dim_sharedlayers": 8,
                "num_headlayers": 1,
                "dim_headlayers": [8],
            },
            "node": {
                "num_headlayers": 1,
                "dim_headlayers": [8],
                "type": "mlp",
            },
        },
        "task_weights": [1.0, 1.0],
    }
    training = {
        "num_epoch": NUM_EPOCH,
        "perc_train": 0.7,
        "Optimizer": {"type": "AdamW", "learning_rate": 1e-2},
        "resume_every": 1,
        "checkpoint_keep_last": 4,
    }
    mp = os.environ.get("MESH_SMOKE_MODEL_PARALLEL")
    if mp:
        training["model_parallel"] = int(mp)
    # the driver's order: resolve the mesh BEFORE layouts so padding
    # divides the data axis (on 7 devices at m=2 the count itself would
    # not divide anything)
    mesh = resolve_mesh(training)
    samples = make_samples()
    layout = compute_layout([samples], batch_size=4, need_triplets=False)
    train_loader = GraphLoader(samples[:16], 4, layout, shuffle=True, seed=7)
    val_loader = GraphLoader(samples[16:20], 4, layout, shuffle=False)
    test_loader = GraphLoader(samples[20:], 4, layout, shuffle=False)
    model = create_model_config(arch)
    trainer = Trainer(model, training, mesh=mesh)
    state = trainer.init_state(next(iter(train_loader)), seed=0)
    return trainer, state, (train_loader, val_loader, test_loader), training


def main():
    workdir, mode = sys.argv[1], sys.argv[2]
    os.chdir(workdir)
    started = time.monotonic()

    from hydragnn_tpu.obs import runtime as obs
    from hydragnn_tpu.parallel.mesh import announce_mesh, mesh_shape_list
    from hydragnn_tpu.train.checkpoint import (
        checkpoint_exists,
        load_state_dict,
        pop_train_meta,
        restore_into,
        rolling_checkpoints,
    )
    from hydragnn_tpu.train.epoch_driver import train_validate_test

    trainer, state, loaders, training = build()

    resume_meta = None
    if mode == "resume":
        if not (checkpoint_exists(LOG_NAME) or rolling_checkpoints(LOG_NAME)):
            raise FileNotFoundError("resume requested but no checkpoint")
        restored = load_state_dict(LOG_NAME)
        resume_meta = pop_train_meta(restored)
        state = trainer.place_state(restore_into(state, restored))

    config = {"NeuralNetwork": {"Training": training}}
    telemetry = obs.init_run_telemetry(config, LOG_NAME, path="./logs/")
    # the driver's announce: mesh_shape + param_sharding events, and the
    # re-derive world_resize when the checkpoint recorded another mesh
    announce_mesh(
        trainer.mesh, trainer=trainer, resume_meta=resume_meta,
        started_ts=started,
    )

    # per-epoch compile-count record: flat after the warmup epoch
    compile_sizes = []
    epoch_losses = []
    orig = trainer.train_epoch

    def counting_train_epoch(st, loader, rng):
        st, rng, loss, tasks = orig(st, loader, rng)
        compile_sizes.append(int(trainer._train_step._cache_size()))
        epoch_losses.append(float(loss))
        return st, rng, loss, tasks

    trainer.train_epoch = counting_train_epoch

    config_nn = {
        "Training": training,
        "Variables_of_interest": {"output_names": ["sum", "x"]},
    }
    try:
        state = train_validate_test(
            trainer, state, *loaders, config_nn, LOG_NAME, verbosity=0,
            resume_meta=resume_meta,
        )
    except BaseException:
        obs.deactivate(status="failed")
        raise
    obs.deactivate(status="complete")

    # uniform batch shapes: every signature compiles inside epoch 1, so
    # the cache size must be FLAT across epochs (recompile = regression)
    if len(compile_sizes) >= 2:
        assert all(c == compile_sizes[0] for c in compile_sizes), (
            "compile count grew across epochs: " + repr(compile_sizes)
        )

    with open("result.json", "w") as f:
        json.dump(
            {
                "mode": mode,
                "mesh": mesh_shape_list(trainer.mesh),
                "devices": len(jax.devices()),
                "epoch_losses": epoch_losses,
                "compile_sizes": compile_sizes,
                "resumed_from_epoch": (
                    None
                    if resume_meta is None
                    else int(resume_meta["epoch"]) + 1
                ),
            },
            f,
        )


if __name__ == "__main__":
    main()
