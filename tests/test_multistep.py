"""Scan-based multi-step dispatch parity.

One `lax.scan` dispatch over K stacked microbatches must produce the same
training trajectory as K plain per-batch steps — exact epoch metrics, same
final parameters. This is the dispatch-amortization path
(`Trainer.steps_per_dispatch` / `HYDRAGNN_STEPS_PER_DISPATCH`); the
reference has no counterpart (its hot loop is eager per-batch,
`train/train_validate_test.py:463-520`).
"""

import numpy as np
import pytest

import jax

from hydragnn_tpu.graph import collate_graphs, pad_sizes_for, stack_batches
from hydragnn_tpu.models import create_model_config
from hydragnn_tpu.parallel.mesh import make_mesh
from hydragnn_tpu.train.trainer import Trainer

from test_models_forward import FakeData


def _arch(model_type="PNA", max_n=6):
    return {
        "model_type": model_type,
        "input_dim": 1,
        "hidden_dim": 16,
        "output_dim": [1, 1],
        "output_type": ["graph", "node"],
        "output_heads": {
            "graph": {
                "num_sharedlayers": 1,
                "dim_sharedlayers": 8,
                "num_headlayers": 1,
                "dim_headlayers": [8],
            },
            "node": {"num_headlayers": 1, "dim_headlayers": [8], "type": "mlp"},
        },
        "task_weights": [1.0, 1.0],
        "num_conv_layers": 2,
        "num_nodes": max_n,
        "edge_dim": None,
        "pna_deg": [0, 2, 4, 2],
        "equivariance": False,
    }


def _batches(num_batches, num_graphs=8, max_n=6, seed=0):
    rng = np.random.default_rng(seed)
    n_pad, e_pad, g_pad = pad_sizes_for(
        max_n, 2 * max_n, num_graphs, graph_multiple=8
    )
    out = []
    for _ in range(num_batches):
        samples = [
            FakeData(rng, int(rng.integers(3, max_n + 1)))
            for _ in range(num_graphs)
        ]
        out.append(
            collate_graphs(
                samples, n_pad, e_pad, g_pad,
                head_types=("graph", "node"), head_dims=(1, 1),
            )
        )
    return out


class ListLoader:
    def __init__(self, batches):
        self.batches = batches

    def __len__(self):
        return len(self.batches)

    def __iter__(self):
        return iter(self.batches)

    def set_epoch(self, epoch):
        pass


def _run(batches, steps_per_dispatch, mesh=None):
    model = create_model_config(_arch())
    trainer = Trainer(
        model,
        training_config={
            "Optimizer": {"type": "AdamW", "learning_rate": 1e-2},
            "steps_per_dispatch": steps_per_dispatch,
        },
        mesh=mesh,
    )
    state = trainer.init_state(batches[0])
    state, _rng, loss, tasks = trainer.train_epoch(
        state, ListLoader(batches), jax.random.PRNGKey(0)
    )
    return state, loss, tasks


def pytest_multistep_matches_single_step():
    batches = _batches(5)  # K=2 -> two stacked dispatches + one trailing single
    s1, loss1, tasks1 = _run(batches, 1)
    s2, loss2, tasks2 = _run(batches, 2)
    assert np.isclose(loss1, loss2, rtol=1e-5), (loss1, loss2)
    np.testing.assert_allclose(tasks1, tasks2, rtol=1e-5)
    flat1 = jax.tree_util.tree_leaves(s1.params)
    flat2 = jax.tree_util.tree_leaves(s2.params)
    for a, b in zip(flat1, flat2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)
    flat1 = jax.tree_util.tree_leaves(s1.batch_stats)
    flat2 = jax.tree_util.tree_leaves(s2.batch_stats)
    for a, b in zip(flat1, flat2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


def pytest_multistep_evaluate_matches_single_step():
    """Streaming evaluation under steps_per_dispatch (stacked eval scan)
    must produce EXACTLY the per-batch path's averaged metrics — eval has
    no optimizer state, so the only difference allowed is dispatch
    count."""
    batches = _batches(5)  # K=2 -> two stacked groups + one trailing single
    model = create_model_config(_arch())
    results = {}
    for k in (1, 2):
        trainer = Trainer(
            model,
            training_config={
                "Optimizer": {"type": "AdamW", "learning_rate": 1e-2},
                "steps_per_dispatch": k,
            },
        )
        state = trainer.init_state(batches[0])
        results[k] = trainer.evaluate(state, ListLoader(batches))
    loss1, tasks1 = results[1]
    loss2, tasks2 = results[2]
    assert np.isclose(loss1, loss2, rtol=1e-6), (loss1, loss2)
    np.testing.assert_allclose(tasks1, tasks2, rtol=1e-6)


@pytest.mark.parametrize("spd", [1, 2])
def pytest_device_prefetch_matches_sync(spd):
    """The double-buffered device-prefetch streaming path (transfers
    issued ahead from a background thread) must reproduce the strict
    alternate-transfer-and-step trajectory exactly — both for per-batch
    dispatch and COMPOSED with multi-step stacking (spd=2: the round-5
    production configuration, prefetching stacked groups)."""
    batches = _batches(5)

    def run(depth):
        model = create_model_config(_arch())
        trainer = Trainer(
            model,
            training_config={
                "Optimizer": {"type": "AdamW", "learning_rate": 1e-2},
                "device_prefetch": depth,
                "steps_per_dispatch": spd,
            },
        )
        state = trainer.init_state(batches[0])
        state, _rng, loss, tasks = trainer.train_epoch(
            state, ListLoader(batches), jax.random.PRNGKey(0)
        )
        loss_v, tasks_v = trainer.evaluate(state, ListLoader(batches))
        return state, loss, tasks, loss_v, tasks_v

    s1, loss1, tasks1, lv1, tv1 = run(0)
    s2, loss2, tasks2, lv2, tv2 = run(3)
    assert np.isclose(loss1, loss2, rtol=1e-6), (loss1, loss2)
    assert np.isclose(lv1, lv2, rtol=1e-6), (lv1, lv2)
    np.testing.assert_allclose(tasks1, tasks2, rtol=1e-6)
    np.testing.assert_allclose(tv1, tv2, rtol=1e-6)
    for a, b in zip(
        jax.tree_util.tree_leaves(s1.params),
        jax.tree_util.tree_leaves(s2.params),
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)


def pytest_multistep_sharded_mesh():
    mesh = make_mesh(8)
    batches = _batches(4)
    s1, loss1, _ = _run(batches, 1, mesh=mesh)
    s2, loss2, _ = _run(batches, 4, mesh=mesh)
    assert np.isclose(loss1, loss2, rtol=1e-5), (loss1, loss2)
    for a, b in zip(
        jax.tree_util.tree_leaves(s1.params), jax.tree_util.tree_leaves(s2.params)
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


def pytest_staged_epoch_matches_streaming():
    batches = _batches(4)
    model = create_model_config(_arch())
    cfg = {"Optimizer": {"type": "AdamW", "learning_rate": 1e-2}}
    t1 = Trainer(model, training_config=cfg)
    s1 = t1.init_state(batches[0])
    s1, _, loss1, tasks1 = t1.train_epoch(
        s1, ListLoader(batches), jax.random.PRNGKey(0)
    )
    t2 = Trainer(model, training_config=cfg)
    s2 = t2.init_state(batches[0])
    staged = t2.stage_batches(batches)
    s2, _, loss2, tasks2 = t2.train_epoch_staged(
        s2, staged, jax.random.PRNGKey(0), shuffle=False
    )
    assert np.isclose(loss1, loss2, rtol=1e-5), (loss1, loss2)
    np.testing.assert_allclose(tasks1, tasks2, rtol=1e-5)
    for a, b in zip(
        jax.tree_util.tree_leaves(s1.params), jax.tree_util.tree_leaves(s2.params)
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


def pytest_staged_epoch_shuffles_batch_order():
    batches = _batches(4)
    model = create_model_config(_arch())
    cfg = {"Optimizer": {"type": "AdamW", "learning_rate": 1e-2}}
    t = Trainer(model, training_config=cfg)
    s = t.init_state(batches[0])
    staged = t.stage_batches(batches)
    # two epochs with shuffle: runs, stays finite, and the rng advances
    rng = jax.random.PRNGKey(0)
    s, rng1, loss_a, _ = t.train_epoch_staged(s, staged, rng)
    s, rng2, loss_b, _ = t.train_epoch_staged(s, staged, rng1)
    assert np.isfinite(loss_a) and np.isfinite(loss_b)
    assert not np.array_equal(np.asarray(rng1), np.asarray(rng2))


def pytest_fit_staged_matches_per_epoch_loop():
    """One whole-training dispatch == N per-epoch dispatches (no shuffle,
    plateau never fires in 3 epochs)."""
    batches = _batches(3)
    model = create_model_config(_arch())
    cfg = {"Optimizer": {"type": "AdamW", "learning_rate": 1e-2}}

    t1 = Trainer(model, training_config=cfg)
    s1 = t1.init_state(batches[0])
    staged1 = t1.stage_batches(batches)
    rng = jax.random.PRNGKey(0)
    losses = []
    for _ in range(3):
        s1, rng, loss, _ = t1.train_epoch_staged(s1, staged1, rng, shuffle=False)
        losses.append(loss)

    t2 = Trainer(model, training_config=cfg)
    s2 = t2.init_state(batches[0])
    staged2 = t2.stage_batches(batches)
    s2, best2, sched2, _, series = t2.fit_staged(
        s2, staged2, 3, jax.random.PRNGKey(0), shuffle=False
    )
    np.testing.assert_allclose(series["train_loss"], losses, rtol=1e-5)
    for a, b in zip(
        jax.tree_util.tree_leaves(s1.params), jax.tree_util.tree_leaves(s2.params)
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)
    # train improves -> best_state tracks the last (lowest-val) epoch
    assert float(sched2.best_val) <= series["val_loss"][0]
    assert int(sched2.epoch) == 3
    assert not series["stopped"].any()


def pytest_fit_staged_chunked_carry():
    """Two 2-epoch dispatches with carried sched/best == one 4-epoch
    dispatch (models have no dropout, so rng streams don't affect math)."""
    batches = _batches(3)
    model = create_model_config(_arch())
    cfg = {"Optimizer": {"type": "AdamW", "learning_rate": 1e-2}}

    ta = Trainer(model, training_config=cfg)
    sa = ta.init_state(batches[0])
    sta = ta.stage_batches(batches)
    sa, besta, scheda, rnga, ser_a = ta.fit_staged(
        sa, sta, 2, jax.random.PRNGKey(7), shuffle=False
    )
    sa, besta, scheda, rnga, ser_a2 = ta.fit_staged(
        sa, sta, 2, rnga, shuffle=False, sched=scheda, best_state=besta
    )

    tb = Trainer(model, training_config=cfg)
    sb = tb.init_state(batches[0])
    stb = tb.stage_batches(batches)
    sb, bestb, schedb, _, ser_b = tb.fit_staged(
        sb, stb, 4, jax.random.PRNGKey(7), shuffle=False
    )
    np.testing.assert_allclose(
        np.concatenate([ser_a["train_loss"], ser_a2["train_loss"]]),
        ser_b["train_loss"],
        rtol=1e-5,
    )
    assert int(scheda.epoch) == int(schedb.epoch) == 4
    for a, b in zip(
        jax.tree_util.tree_leaves(sa.params), jax.tree_util.tree_leaves(sb.params)
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


def pytest_fit_staged_early_stop_and_val():
    """With a huge LR the loss diverges; early stopping with patience 1
    must fire and later epochs come back as NaN-marked skips."""
    batches = _batches(2)
    model = create_model_config(_arch())
    cfg = {
        "Optimizer": {"type": "SGD", "learning_rate": 1e6},
        "EarlyStopping": True,
        "patience": 1,
    }
    t = Trainer(model, training_config=cfg)
    s = t.init_state(batches[0])
    staged = t.stage_batches(batches)
    val = t.stage_batches(batches[:1])
    s, best, sched, _, series = t.fit_staged(
        s, staged, 8, jax.random.PRNGKey(0), staged_val=val, shuffle=False
    )
    assert series["stopped"].any()
    first_stop = int(np.argmax(series["stopped"]))
    # every epoch after the stop is a NaN skip row
    if first_stop + 1 < len(series["train_loss"]):
        assert np.isnan(series["train_loss"][first_stop + 1 :]).all()
    assert bool(sched.stopped)


def pytest_fit_staged_pad_to_inert():
    """pad_to-padded epochs must be inert: fit(3, pad_to=5) == fit(3), with
    padded series rows trimmed away."""
    batches = _batches(3)
    model = create_model_config(_arch())
    cfg = {"Optimizer": {"type": "AdamW", "learning_rate": 1e-2}}
    ta = Trainer(model, training_config=cfg)
    sa = ta.init_state(batches[0])
    sta = ta.stage_batches(batches)
    sa, _, scheda, _, ser_a = ta.fit_staged(
        sa, sta, 3, jax.random.PRNGKey(3), shuffle=False, pad_to=5
    )
    tb = Trainer(model, training_config=cfg)
    sb = tb.init_state(batches[0])
    stb = tb.stage_batches(batches)
    sb, _, schedb, _, ser_b = tb.fit_staged(
        sb, stb, 3, jax.random.PRNGKey(3), shuffle=False
    )
    assert ser_a["train_loss"].shape == (3,)
    np.testing.assert_allclose(ser_a["train_loss"], ser_b["train_loss"], rtol=1e-5)
    assert int(scheda.epoch) == int(schedb.epoch) == 3
    assert not ser_a["stopped"].any()
    for a, b in zip(
        jax.tree_util.tree_leaves(sa.params), jax.tree_util.tree_leaves(sb.params)
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


def pytest_predict_staged_matches_streaming():
    """The device-resident predict fast path (one scan + one readback) must
    produce identical metrics and per-head value arrays."""
    batches = _batches(4)
    model = create_model_config(_arch())
    loader = ListLoader(batches)

    t1 = Trainer(
        model, training_config={"Optimizer": {"type": "AdamW", "learning_rate": 1e-2}}
    )
    s1 = t1.init_state(batches[0])
    e1, te1, tv1, pv1 = t1.predict(s1, loader)

    t2 = Trainer(
        model,
        training_config={
            "Optimizer": {"type": "AdamW", "learning_rate": 1e-2},
            "device_resident_dataset": True,
        },
    )
    s2 = t2.init_state(batches[0])
    # spy: the fast path must actually run (a silent fallback to streaming
    # would make this parity test vacuous)
    calls = []
    orig_scan = t2._predict_scan
    t2._predict_scan = lambda *a, **k: (calls.append(1), orig_scan(*a, **k))[1]
    # same init seed -> same params; compare outputs directly
    e2, te2, tv2, pv2 = t2.predict(s2, loader)
    assert calls, "device-resident predict path did not execute"
    assert np.isclose(e1, e2, rtol=1e-6), (e1, e2)
    np.testing.assert_allclose(te1, te2, rtol=1e-6)
    for a, b in zip(tv1, tv2):
        np.testing.assert_allclose(a, b, rtol=1e-6)
    for a, b in zip(pv1, pv2):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)


def pytest_stack_batches_shapes():
    batches = _batches(3)
    stacked = stack_batches(batches)
    assert stacked.x.shape == (3,) + batches[0].x.shape
    assert stacked.senders.shape == (3,) + batches[0].senders.shape
    assert len(stacked.targets) == 2
