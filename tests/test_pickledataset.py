"""Per-sample pickle dataset + dataset-class inheritance round-trip +
config-schema checks.

Analogs of the reference's ``tests/test_datasetclass_inheritance.py:95-120``
(raw dataset -> writer -> reader -> loaders) and ``tests/test_config.py:15-40``
(required config sections present in shipped example configs).
"""

import json
import os
import glob

import numpy as np
import pytest

from hydragnn_tpu.data import (
    GraphData,
    SimplePickleDataset,
    SimplePickleWriter,
    create_dataloaders,
    split_dataset,
)
from hydragnn_tpu.data.lsms import LSMSDataset
from synthetic import deterministic_graph_data

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _samples(n=7, seed=0):
    rng = np.random.default_rng(seed)
    out = []
    for i in range(n):
        k = int(rng.integers(3, 7))
        d = GraphData(
            x=rng.normal(size=(k, 2)).astype(np.float32),
            pos=rng.normal(size=(k, 3)).astype(np.float32),
        )
        d.edge_index = np.stack(
            [np.arange(k, dtype=np.int64), (np.arange(k) + 1) % k]
        )
        d.targets = [np.asarray([float(i)], np.float32)]
        d.target_types = ["graph"]
        out.append(d)
    return out


def pytest_pickle_roundtrip(tmp_path):
    samples = _samples()
    SimplePickleWriter(samples, str(tmp_path), "trainset")
    ds = SimplePickleDataset(str(tmp_path), "trainset")
    assert len(ds) == len(samples)
    for a, b in zip(samples, ds):
        np.testing.assert_allclose(a.x, b.x)
        np.testing.assert_allclose(a.pos, b.pos)
        np.testing.assert_array_equal(a.edge_index, b.edge_index)
        np.testing.assert_allclose(a.targets[0], b.targets[0])


def pytest_pickle_subdir_bucketing(tmp_path):
    """Subdir layout: sample k lives in <basedir>/<k // nmax>/ — the
    reference's filesystem-friendly bucketing (pickledataset.py:78-90)."""
    samples = _samples(9)
    SimplePickleWriter(
        samples, str(tmp_path), "total", use_subdir=True, nmax_persubdir=4
    )
    # files 0-3 in "0/", 4-7 in "1/", 8 in "2/"
    assert os.path.exists(tmp_path / "0" / "total-0.pkl")
    assert os.path.exists(tmp_path / "1" / "total-4.pkl")
    assert os.path.exists(tmp_path / "2" / "total-8.pkl")
    ds = SimplePickleDataset(str(tmp_path), "total")
    assert len(ds) == 9
    np.testing.assert_allclose(ds[8].targets[0], [8.0])


def pytest_pickle_subset_and_preload(tmp_path):
    samples = _samples(6)
    SimplePickleWriter(samples, str(tmp_path), "total")
    ds = SimplePickleDataset(str(tmp_path), "total", subset=[4, 1])
    assert len(ds) == 2
    np.testing.assert_allclose(ds[0].targets[0], [4.0])
    ds.setsubset([2])
    np.testing.assert_allclose(ds[0].targets[0], [2.0])
    pre = SimplePickleDataset(str(tmp_path), "total", preload=True)
    np.testing.assert_allclose(pre[5].targets[0], [5.0])


def pytest_pickle_var_config_on_read(tmp_path):
    """var_config applies target extraction + input column selection on
    read (update_data_object analog)."""
    rng = np.random.default_rng(1)
    d = GraphData(
        x=rng.normal(size=(4, 3)).astype(np.float32),
        pos=rng.normal(size=(4, 3)).astype(np.float32),
        y=np.asarray([3.25], np.float32),
    )
    d.edge_index = np.stack([np.arange(4, dtype=np.int64), (np.arange(4) + 1) % 4])
    SimplePickleWriter([d], str(tmp_path), "total")
    var_config = {
        "type": ["graph", "node"],
        "output_index": [0, 1],
        "graph_feature_dims": [1],
        "node_feature_dims": [1, 2],
        "input_node_features": [0],
    }
    ds = SimplePickleDataset(str(tmp_path), "total", var_config=var_config)
    out = ds[0]
    assert out.target_types == ["graph", "node"]
    np.testing.assert_allclose(out.targets[0], [3.25])
    assert out.targets[1].shape == (4, 2)  # node head = x columns 1:3
    assert out.x.shape == (4, 1)  # input selection applied after


def pytest_pickle_meta_version_guard(tmp_path):
    with open(tmp_path / "total-meta.pkl", "wb") as f:
        import pickle

        pickle.dump([1, 2, 3], f)  # not a manifest dict
    with pytest.raises(ValueError, match="manifest"):
        SimplePickleDataset(str(tmp_path), "total")


def pytest_datasetclass_inheritance_roundtrip(tmp_path, monkeypatch):
    """Raw LSMS dataset -> per-sample pickle write -> read -> loaders:
    the reference's dataset-class inheritance round-trip
    (test_datasetclass_inheritance.py:95-120), through AbstractRawDataset
    machinery and the pickle dataset."""
    monkeypatch.chdir(tmp_path)
    raw_dir = str(tmp_path / "raw")
    deterministic_graph_data(raw_dir, number_configurations=24)
    ds_config = {
        "name": "unit_test",
        "format": "LSMS",
        "path": {"total": raw_dir},
        "node_features": {
            "name": ["num_of_protons", "charge_density", "magnetic_moment"],
            "dim": [1, 1, 1],
            "column_index": [0, 5, 6],
        },
        "graph_features": {
            "name": ["free_energy"],
            "dim": [1],
            "column_index": [0],
        },
    }
    total = LSMSDataset(ds_config)
    assert len(total) == 24
    trainset, valset, testset = split_dataset(list(total), 0.8, False)
    base = str(tmp_path / "pkl")
    SimplePickleWriter(list(trainset), base, "trainset")
    SimplePickleWriter(list(valset), base, "valset")
    SimplePickleWriter(list(testset), base, "testset")
    # read back with on-read target extraction (update_data_object analog)
    var_config = {
        "type": ["graph"],
        "output_index": [0],
        "graph_feature_dims": [1],
        "node_feature_dims": [1, 1, 1],
        "input_node_features": [0],
    }
    tr = SimplePickleDataset(base, "trainset", var_config=var_config)
    va = SimplePickleDataset(base, "valset", var_config=var_config)
    te = SimplePickleDataset(base, "testset", var_config=var_config)
    assert len(tr) + len(va) + len(te) == 24
    # raw sample content survives the round trip bit-for-bit
    np.testing.assert_allclose(trainset[0].x[:, :1], tr[0].x)
    np.testing.assert_allclose(trainset[0].pos, tr[0].pos)

    # and the reloaded datasets feed the standard loader path
    from hydragnn_tpu.data import radius_graph

    def _prep(ds):
        out = []
        for i in range(len(ds)):
            d = ds[i]
            d.edge_index = radius_graph(d.pos, 7.0, 10)
            out.append(d)
        return out

    train_loader, _, _ = create_dataloaders(
        _prep(tr), _prep(va), _prep(te), batch_size=8
    )
    batch = next(iter(train_loader))
    assert batch.node_mask.sum() > 0


_REQUIRED = {
    "Dataset": ["name", "format", "path", "node_features", "graph_features"],
    "NeuralNetwork": ["Architecture", "Variables_of_interest", "Training"],
}


@pytest.mark.parametrize("config_file", ["lsms/lsms.json"])
def pytest_config_schema(config_file):
    """Required sections/keys present in shipped example configs
    (reference tests/test_config.py:15-40 — and actually check the keys,
    which the reference's loop only pretends to)."""
    with open(os.path.join(_REPO, "examples", config_file)) as f:
        config = json.load(f)
    for category, keys in _REQUIRED.items():
        assert category in config, f"missing {category}"
        for key in keys:
            assert key in config[category], f"missing {category}.{key}"


def pytest_config_schema_all_examples():
    """Every shipped example config parses and has the NeuralNetwork core
    sections (Dataset sections only apply to raw-data configs)."""
    configs = glob.glob(os.path.join(_REPO, "examples", "*", "*.json"))
    assert configs
    for path in configs:
        with open(path) as f:
            config = json.load(f)
        if "NeuralNetwork" not in config:
            continue  # auxiliary json (e.g. HPO space definitions)
        for key in _REQUIRED["NeuralNetwork"]:
            assert key in config["NeuralNetwork"], f"{path}: missing {key}"
