"""Fused message-passing kernels (ops/fused_mp.py) vs XLA references.

Interpret mode on CPU — the same kernel code compiles on TPU. Values AND
gradients must match the unfused gather -> edge-op -> segment-sum
composition, including masked (padded) edges, empty segments, and edge
counts that are not a multiple of the kernel block.
"""

import jax
import jax.numpy as jnp
import numpy as np

from hydragnn_tpu.ops import (
    fused_egnn_edge_phase,
    fused_gather_mean,
    fused_gather_moments,
    fused_gather_sum,
    fused_gather_weighted_sum,
    fused_mp_enabled,
)


def _case(seed=0, e=301, n=40, d=12, mask_p=0.2):
    """e=301 is deliberately NOT a multiple of the 256 edge block."""
    rng = np.random.default_rng(seed)
    x = jnp.asarray(rng.standard_normal((n, d)), jnp.float32)
    snd = jnp.asarray(rng.integers(0, n, e), jnp.int32)
    rcv = jnp.asarray(rng.integers(0, n, e), jnp.int32)
    mask = jnp.asarray(rng.random(e) > mask_p)
    return x, snd, rcv, mask, n


def _ref_sum(x, snd, rcv, mask, n):
    msg = jnp.where(mask[:, None], x[snd], 0.0)
    return jax.ops.segment_sum(msg, rcv, num_segments=n)


def pytest_fused_gather_sum_matches_xla():
    x, snd, rcv, mask, n = _case()
    out = fused_gather_sum(x, snd, rcv, n, mask, True)
    np.testing.assert_allclose(
        out, _ref_sum(x, snd, rcv, mask, n), rtol=1e-5, atol=1e-5
    )


def pytest_fused_gather_sum_grad():
    x, snd, rcv, mask, n = _case(seed=1, e=120, n=24, d=8)

    def ours(x):
        return jnp.sum(fused_gather_sum(x, snd, rcv, n, mask, True) ** 2)

    def ref(x):
        return jnp.sum(_ref_sum(x, snd, rcv, mask, n) ** 2)

    np.testing.assert_allclose(
        jax.grad(ours)(x), jax.grad(ref)(x), rtol=1e-4, atol=1e-5
    )


def pytest_fused_gather_sum_empty_segments():
    x, snd, rcv, mask, n = _case(seed=2, e=60, n=32)
    rcv = jnp.minimum(rcv, 9)  # segments 10.. empty
    out = fused_gather_sum(x, snd, rcv, n, mask, True)
    assert np.allclose(np.asarray(out[10:]), 0.0)


def pytest_fused_gather_mean_matches_xla():
    x, snd, rcv, mask, n = _case(seed=3)
    mean, deg = fused_gather_mean(x, snd, rcv, n, mask, True)
    cnt = jax.ops.segment_sum(mask.astype(jnp.float32), rcv, num_segments=n)
    ref = _ref_sum(x, snd, rcv, mask, n) / jnp.maximum(cnt, 1.0)[:, None]
    np.testing.assert_allclose(mean, ref, rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(deg[:, 0], cnt, rtol=1e-6, atol=0)


def pytest_fused_gather_weighted_sum_matches_xla():
    x, snd, rcv, mask, n = _case(seed=4)
    rng = np.random.default_rng(14)
    w = jnp.asarray(rng.standard_normal(x[snd].shape), jnp.float32)
    w = w * mask[:, None]
    out = fused_gather_weighted_sum(x, w, snd, rcv, n, True)
    ref = jax.ops.segment_sum(x[snd] * w, rcv, num_segments=n)
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5)

    def ours(x, w):
        return jnp.sum(fused_gather_weighted_sum(x, w, snd, rcv, n, True) ** 2)

    def refl(x, w):
        return jnp.sum(jax.ops.segment_sum(x[snd] * w, rcv, num_segments=n) ** 2)

    ga = jax.grad(ours, argnums=(0, 1))(x, w)
    gb = jax.grad(refl, argnums=(0, 1))(x, w)
    for a, b in zip(ga, gb):
        np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-5)


def pytest_fused_gather_moments_matches_xla():
    x, snd, rcv, mask, n = _case(seed=5)
    rng = np.random.default_rng(15)
    ze = jnp.asarray(rng.standard_normal(x[snd].shape), jnp.float32)
    s, c, sq, z = fused_gather_moments(x, snd, rcv, n, mask, ze, True)
    z_ref = jnp.where(mask[:, None], x[snd] + ze, 0.0)
    np.testing.assert_allclose(z, z_ref, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(
        s, jax.ops.segment_sum(z_ref, rcv, num_segments=n),
        rtol=1e-5, atol=1e-5,
    )
    np.testing.assert_allclose(
        c[:, 0],
        jax.ops.segment_sum(mask.astype(jnp.float32), rcv, num_segments=n),
        rtol=1e-6, atol=0,
    )
    np.testing.assert_allclose(
        sq, jax.ops.segment_sum(z_ref * z_ref, rcv, num_segments=n),
        rtol=1e-4, atol=1e-5,
    )


def pytest_fused_gather_moments_grad_through_all_outputs():
    # gradient flows through the reduced stats AND the per-edge z output
    x, snd, rcv, mask, n = _case(seed=6, e=96, n=24, d=6)
    rng = np.random.default_rng(16)
    ze = jnp.asarray(rng.standard_normal((96, 6)), jnp.float32)

    def ours(x, ze):
        s, c, sq, z = fused_gather_moments(x, snd, rcv, n, mask, ze, True)
        mean = s / jnp.maximum(c, 1.0)
        return jnp.sum(mean**2) + jnp.sum(sq) + jnp.sum(z**3)

    def ref(x, ze):
        z = jnp.where(mask[:, None], x[snd] + ze, 0.0)
        s = jax.ops.segment_sum(z, rcv, num_segments=n)
        c = jax.ops.segment_sum(
            mask.astype(jnp.float32), rcv, num_segments=n
        )[:, None]
        sq = jax.ops.segment_sum(z * z, rcv, num_segments=n)
        mean = s / jnp.maximum(c, 1.0)
        return jnp.sum(mean**2) + jnp.sum(sq) + jnp.sum(z**3)

    ga = jax.grad(ours, argnums=(0, 1))(x, ze)
    gb = jax.grad(ref, argnums=(0, 1))(x, ze)
    for a, b in zip(ga, gb):
        np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-5)


def _egnn_setup(equivariant, seed=7, e=90, n=20, h=8):
    rng = np.random.default_rng(seed)
    snd = jnp.asarray(rng.integers(0, n, e), jnp.int32)
    rcv = jnp.asarray(rng.integers(0, n, e), jnp.int32)
    mask = jnp.asarray(rng.random(e) > 0.25)
    ys = jnp.asarray(rng.standard_normal((n, h)), jnp.float32)
    yr = jnp.asarray(rng.standard_normal((n, h)), jnp.float32)
    pos = jnp.asarray(rng.standard_normal((n, 3)), jnp.float32)
    params = [
        jnp.asarray(rng.standard_normal((h,)), jnp.float32),  # w_rad
        jnp.asarray(rng.standard_normal((h, h)) * 0.3, jnp.float32),
        jnp.asarray(rng.standard_normal((h,)) * 0.1, jnp.float32),
    ]
    if equivariant:
        params += [
            jnp.asarray(rng.standard_normal((h, h)) * 0.3, jnp.float32),
            jnp.zeros((h,), jnp.float32),
            jnp.asarray(rng.standard_normal((h, 1)) * 0.1, jnp.float32),
        ]
    return ys, yr, pos, tuple(params), snd, rcv, mask, n, h


def _egnn_ref(ys, yr, pos, params, snd, rcv, mask, n):
    w_rad, W2, b2 = params[:3]
    cd = pos[snd] - pos[rcv]
    radial = (cd * cd).sum(-1, keepdims=True)
    nz = radial > 0
    norm = jnp.where(nz, jnp.sqrt(jnp.where(nz, radial, 1.0)), 0.0)
    cd = cd / (norm + 1.0)
    pre = ys[snd] + yr[rcv] + radial * w_rad
    e = jax.nn.relu(pre)
    e = jax.nn.relu(e @ W2 + b2)
    e = jnp.where(mask[:, None], e, 0.0)
    if len(params) > 3:
        Wc0, bc0, Wc1 = params[3:]
        cw = jax.nn.relu(e @ Wc0 + bc0)
        cw = jnp.tanh(cw @ Wc1)
        trans = jnp.clip(cd * cw, -100.0, 100.0)
        trans = jnp.where(mask[:, None], trans, 0.0)
        packed = jnp.concatenate(
            [e, trans, mask.astype(jnp.float32)[:, None]], -1
        )
    else:
        packed = jnp.concatenate([e, mask.astype(jnp.float32)[:, None]], -1)
    return jax.ops.segment_sum(packed, snd, num_segments=n)


def pytest_fused_egnn_edge_phase_matches_xla():
    for equivariant in (False, True):
        ys, yr, pos, params, snd, rcv, mask, n, h = _egnn_setup(equivariant)
        out = fused_egnn_edge_phase(
            ys, yr, pos, params, snd, rcv, n, mask, None, True
        )
        ref = _egnn_ref(ys, yr, pos, params, snd, rcv, mask, n)
        np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-4)


def pytest_fused_egnn_edge_phase_grad():
    ys, yr, pos, params, snd, rcv, mask, n, h = _egnn_setup(True)

    def ours(ys, yr, pos, params):
        return jnp.sum(
            fused_egnn_edge_phase(
                ys, yr, pos, params, snd, rcv, n, mask, None, True
            )
            ** 2
        )

    def ref(ys, yr, pos, params):
        return jnp.sum(_egnn_ref(ys, yr, pos, params, snd, rcv, mask, n) ** 2)

    ga = jax.tree_util.tree_leaves(
        jax.grad(ours, argnums=(0, 1, 2, 3))(ys, yr, pos, params)
    )
    gb = jax.tree_util.tree_leaves(
        jax.grad(ref, argnums=(0, 1, 2, 3))(ys, yr, pos, params)
    )
    for a, b in zip(ga, gb):
        np.testing.assert_allclose(
            a, b, rtol=1e-3, atol=np.abs(np.asarray(b)).max() * 1e-4 + 1e-5
        )


def pytest_fused_backward_zeroes_out_of_range_ids():
    # the VJP honors the forward kernel's padding contract: edges whose
    # GATHER id is out of range linearize around a ZERO gather (not a
    # clamp-gather of the last row), and out-of-range REDUCE ids get a
    # zero cotangent
    x, snd, rcv, mask, n = _case(seed=8, e=60, n=16, d=4, mask_p=0.0)
    big = jnp.iinfo(jnp.int32).max
    snd = snd.at[-5:].set(big)
    rng = np.random.default_rng(18)
    ze = jnp.asarray(rng.standard_normal((60, 4)), jnp.float32)

    def loss(x, ze):
        s, c, sq, z = fused_gather_moments(x, snd, rcv, n, mask, ze, True)
        return jnp.sum(s**2) + jnp.sum(z**3)

    def ref(x, ze):
        real = snd < n
        safe = jnp.where(real, snd, 0)
        z = jnp.where(real[:, None], x[safe], 0.0) + ze  # mask all-true
        s = jax.ops.segment_sum(z, rcv, num_segments=n)
        return jnp.sum(s**2) + jnp.sum(z**3)

    ga = jax.grad(loss, argnums=(0, 1))(x, ze)
    gb = jax.grad(ref, argnums=(0, 1))(x, ze)
    for a, b in zip(ga, gb):
        np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-5)
    # and reduce-side: out-of-range receivers drop from the reduction
    rcv2 = rcv.at[-5:].set(big)

    def loss2(x):
        return jnp.sum(fused_gather_sum(x, snd, rcv2, n, mask, True) ** 2)

    def ref2(x):
        real = (snd < n) & (rcv2 < n)
        safe_s = jnp.where(snd < n, snd, 0)
        z = jnp.where(real[:, None], x[safe_s], 0.0)
        safe_r = jnp.where(rcv2 < n, rcv2, n)
        return jnp.sum(
            jax.ops.segment_sum(z, safe_r, num_segments=n + 1)[:n] ** 2
        )

    np.testing.assert_allclose(
        jax.grad(loss2)(x), jax.grad(ref2)(x), rtol=1e-4, atol=1e-5
    )


def pytest_fused_mp_vmem_guard():
    # small configs fit; a node table alone past the budget does not
    assert fused_mp_enabled(1024, 1024, 64, 64)
    assert not fused_mp_enabled(200_000, 200_000, 64, 64)
    # the one-hot indicators count too: huge N at tiny dim must not pass
    assert not fused_mp_enabled(2_000_000, 2_000_000, 1, 1)
