"""Dense neighbor-list aggregation: numerical parity with the segment
path (forward AND gradients — the custom VJP routes the backward pass
through reverse neighbor lists) plus host-side list construction."""

import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from hydragnn_tpu.graph import collate_graphs, pad_sizes_for
from hydragnn_tpu.models import create_model_config, init_model_params
from hydragnn_tpu.ops.dense_agg import (
    build_neighbor_lists,
    dense_minmax,
    dense_moments,
    dense_sum,
    gather_neighbors,
    max_degree,
)

from test_models_forward import arch_config, make_batch


from hydragnn_tpu.ops.dense_agg import attach_neighbor_lists as _with_neighbors


def pytest_neighbor_list_construction():
    senders = np.array([0, 2, 1, 0, 3])
    receivers = np.array([1, 1, 0, 3, 3])
    mask = np.array([True, True, True, True, False])  # last edge is padding
    k_in, k_out = max_degree(senders, receivers, mask)
    assert (k_in, k_out) == (2, 2)
    ex = build_neighbor_lists(senders, receivers, mask, 4, k_in, k_out)
    # node 1 receives from 0 and 2, in edge order
    assert ex["nbr_idx"][1].tolist() == [0, 2]
    assert ex["nbr_mask"][1].tolist() == [True, True]
    assert ex["nbr_edge"][1].tolist() == [0, 1]
    # node 2 receives nothing
    assert ex["nbr_mask"][2].tolist() == [False, False]
    # padding edge 4 excluded: node 3 receives only edge 3 (from node 0)
    assert ex["nbr_mask"][3].tolist() == [True, False]
    assert ex["nbr_idx"][3, 0] == 0
    # reverse list: node 0 sends edges 0 (slot 0 of node 1) and 3 (slot 0
    # of node 3) -> flat positions 1*2+0 and 3*2+0
    assert sorted(ex["rev_idx"][0][ex["rev_mask"][0]].tolist()) == [2, 6]


def pytest_gather_neighbors_vjp_matches_autodiff():
    """The reverse-list backward equals the scatter-add the plain gather
    would produce."""
    rng = np.random.default_rng(0)
    n, d = 40, 8
    senders = rng.integers(0, n, 160)
    receivers = rng.integers(0, n, 160)
    mask = np.ones(160, bool)
    k_in, k_out = max_degree(senders, receivers, mask)
    ex = build_neighbor_lists(senders, receivers, mask, n, k_in, k_out)
    x = jnp.asarray(rng.standard_normal((n, d)), jnp.float32)
    nbr = jnp.asarray(ex["nbr_idx"])
    nmask = jnp.asarray(ex["nbr_mask"])
    rev = jnp.asarray(ex["rev_idx"])
    rmask = jnp.asarray(ex["rev_mask"])

    def f_custom(x):
        g = gather_neighbors(x, nbr, rev, rmask)
        return (jnp.where(nmask[..., None], g, 0.0) ** 2).sum()

    def f_plain(x):
        g = x[nbr]
        return (jnp.where(nmask[..., None], g, 0.0) ** 2).sum()

    g_custom = jax.grad(f_custom)(x)
    g_plain = jax.grad(f_plain)(x)
    np.testing.assert_allclose(
        np.asarray(g_custom), np.asarray(g_plain), rtol=1e-5, atol=1e-5
    )


def pytest_dense_reductions_match_segment():
    rng = np.random.default_rng(1)
    n, e, d = 30, 120, 16
    senders = rng.integers(0, n, e)
    receivers = rng.integers(0, n - 5, e)  # leave some empty receivers
    mask = rng.random(e) < 0.8
    # the collate contract: padding edges target the padding node slot, so
    # their zeroed data never reaches a real receiver's min/max
    senders[~mask] = n - 1
    receivers[~mask] = n - 1
    k_in, k_out = max_degree(senders, receivers, mask)
    ex = build_neighbor_lists(senders, receivers, mask, n, k_in, k_out)
    h_edges = rng.standard_normal((e, d)).astype(np.float32)

    from hydragnn_tpu.graph import segment_minmax_fused, segment_moments_fused

    hm = jnp.where(jnp.asarray(mask)[:, None], jnp.asarray(h_edges), 0.0)
    s, cnt, sq = segment_moments_fused(
        hm, jnp.asarray(receivers), n, weights=jnp.asarray(mask)
    )
    deg_ref = jnp.maximum(cnt, 1.0)
    mean_ref = s / deg_ref
    std_ref = jnp.sqrt(jnp.maximum(sq / deg_ref - mean_ref**2, 0.0) + 1e-5)
    mn_ref, mx_ref = segment_minmax_fused(
        hm, jnp.asarray(receivers), n, has=cnt > 0
    )

    # dense path: messages arranged [N, K, D] via nbr_edge
    h_dense = jnp.asarray(h_edges)[jnp.asarray(ex["nbr_edge"])]
    nmask = jnp.asarray(ex["nbr_mask"])
    mean_d, std_d, deg_d, has_d = dense_moments(h_dense, nmask)
    mn_d, mx_d = dense_minmax(h_dense, nmask, has_d)

    np.testing.assert_allclose(mean_d, mean_ref, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(std_d, std_ref, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(mn_d, mn_ref, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(mx_d, mx_ref, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(
        dense_sum(h_dense, nmask), s, rtol=1e-5, atol=1e-6
    )


# default tier: one combo per aggregation STRUCTURE (multi-aggregator,
# plain receiver-sum, edge-conditioned, sender-side equivariant x2);
# HYDRAGNN_FULL_TEST=1 runs the whole matrix
_COMBOS = [
    ("PNA", "edges"),
    ("GAT", "plain"),
    ("DimeNet", "plain"),
    ("GIN", "plain"),
    ("SchNet", "equivariant"),
    ("EGNN", "equivariant"),
]
if int(os.getenv("HYDRAGNN_FULL_TEST", "0")) == 1:
    _COMBOS += [
        ("PNA", "plain"),
        ("SAGE", "plain"),
        ("MFC", "plain"),
        ("CGCNN", "edges"),
        ("SchNet", "plain"),
        ("EGNN", "plain"),
    ]


@pytest.mark.parametrize("model_type,variant", _COMBOS)
def pytest_dense_path_parity(model_type, variant):
    """Full stacks: identical outputs and parameter gradients through the
    dense and segment paths (receiver-side AND sender-side aggregations,
    equivariant coordinate updates included)."""
    batch = make_batch(with_triplets=(model_type == "DimeNet"))
    cfg = arch_config(model_type)
    if variant == "edges":
        cfg["edge_dim"] = 1
    if variant == "equivariant":
        cfg["equivariance"] = True
    model = create_model_config(cfg)
    params = init_model_params(model, batch)
    dense_batch = _with_neighbors(batch)

    def loss(p, b):
        outputs = model.apply(p, b, train=False)
        return sum(jnp.sum(o**2) for o in outputs)

    l_seg, g_seg = jax.value_and_grad(loss)(params, batch)
    l_den, g_den = jax.value_and_grad(loss)(params, dense_batch)
    np.testing.assert_allclose(float(l_seg), float(l_den), rtol=1e-4)
    flat_seg = jax.tree_util.tree_leaves(g_seg)
    flat_den = jax.tree_util.tree_leaves(g_den)
    for a, b in zip(flat_seg, flat_den):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-5
        )
