"""CI streaming smoke driver (NOT a pytest module).

Usage: ``python tests/_stream_smoke.py <outdir>``

Exercises the streaming data plane end to end in subprocesses:

1. an UNINTERRUPTED 4-epoch run of a two-source weighted mix through the
   real epoch driver, telemetry active — records per-epoch stream
   cursors, final params digest, and leaves a schema-checked
   ``events.jsonl`` carrying the auto-tuned ``bucket_plan`` event;
2. the same run HARD-KILLED mid-epoch-2 (``HYDRAGNN_FAULT_KILL_AT_STEP``),
   leaving only the fsync'd checkpoint with the stream cursor in its
   ``train_meta``;
3. a resume from that checkpoint — the orchestrator asserts the saved
   cursor equals the uninterrupted run's post-epoch-1 cursor (cursor
   equality) and the resumed final params match the uninterrupted run's
   BITWISE (trajectory equality).

(Underscore-prefixed: a driver script; the pytest twin with the
in-process variants is tests/test_stream.py.)
"""

import json
import os
import subprocess
import sys

os.environ.setdefault(
    "XLA_FLAGS",
    (os.environ.get("XLA_FLAGS", "")
     + " --xla_force_host_platform_device_count=1").strip(),
)
os.environ["JAX_PLATFORMS"] = "cpu"

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "tests"))

NUM_EPOCH = 4
LOG_NAME = "streamsmoke"
KILL_STEP = 20  # ~8 batches/epoch at 32 samples, bs 4 -> mid-epoch-2


def make_varied(num, seed, n_lo=4, n_hi=20):
    """make_samples with VARIABLE graph sizes — the two sources must
    spread the size histogram or the bucket planner degenerates to one
    bucket and the smoke stops exercising mixed-shape streaming."""
    import numpy as np

    from hydragnn_tpu.data.dataobj import GraphData

    rng = np.random.default_rng(seed)
    out = []
    for _ in range(num):
        n = int(rng.integers(n_lo, n_hi + 1))
        g = GraphData()
        g.x = rng.random((n, 1)).astype(np.float32)
        g.pos = rng.random((n, 3)).astype(np.float32)
        src = np.arange(n)
        dst = (src + 1) % n
        g.edge_index = np.stack(
            [np.concatenate([src, dst]), np.concatenate([dst, src])]
        ).astype(np.int64)
        g.targets = [np.array([g.x.sum()], np.float32), g.x.copy()]
        g.target_types = ["graph", "node"]
        out.append(g)
    return out


def build(num_epoch):
    from _resilience_worker import make_samples

    from hydragnn_tpu.data.loaders import GraphLoader
    from hydragnn_tpu.data.stream import (
        BucketPlanner,
        ListSource,
        StreamLoader,
        WeightedMix,
    )
    from hydragnn_tpu.models.create import create_model_config
    from hydragnn_tpu.train.trainer import Trainer

    arch = {
        "model_type": "GIN",
        "input_dim": 1,
        "hidden_dim": 8,
        "num_conv_layers": 2,
        "output_dim": [1, 1],
        "output_type": ["graph", "node"],
        "output_heads": {
            "graph": {
                "num_sharedlayers": 1,
                "dim_sharedlayers": 8,
                "num_headlayers": 1,
                "dim_headlayers": [8],
            },
            "node": {"num_headlayers": 1, "dim_headlayers": [8],
                     "type": "mlp"},
        },
        "task_weights": [1.0, 1.0],
    }
    training = {
        "num_epoch": num_epoch,
        "Optimizer": {"type": "AdamW", "learning_rate": 1e-2},
        "resume_every": 1,
        "checkpoint_keep_last": 3,
    }
    src_a = ListSource(make_samples(40, seed=1), shard_size=8, name="qm9ish")
    src_b = ListSource(
        make_varied(60, seed=2, n_lo=8, n_hi=24), shard_size=8,
        name="oc20ish",
    )
    mix = WeightedMix(
        [src_a, src_b], [2.0, 1.0], seed=7, samples_per_epoch=32,
        num_shards=1, shard_id=0, window=2,
    )
    layout = BucketPlanner(mix.sources, batch_size=4, num_buckets=2).plan()
    train_loader = StreamLoader(mix, 4, layout)
    evals = make_samples(8, seed=30)
    val_loader = GraphLoader(evals[:4], 4, layout, shuffle=False,
                             num_shards=1, shard_id=0)
    test_loader = GraphLoader(evals[4:], 4, layout, shuffle=False,
                              num_shards=1, shard_id=0)
    model = create_model_config(arch)
    trainer = Trainer(model, training)
    state = trainer.init_state(train_loader.example_batch(), seed=0)
    return trainer, state, (train_loader, val_loader, test_loader), training


def worker(workdir, mode):
    os.chdir(workdir)
    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    from hydragnn_tpu.obs import runtime as obs_rt
    from hydragnn_tpu.train.checkpoint import (
        checkpoint_exists,
        load_state_dict,
        pop_train_meta,
        restore_into,
    )
    from hydragnn_tpu.train.epoch_driver import train_validate_test

    telem = obs_rt.activate(
        obs_rt.RunTelemetry(LOG_NAME, os.path.join("logs", LOG_NAME))
    )
    trainer, state, loaders, training = build(NUM_EPOCH)
    train_loader = loaders[0]

    resume_meta = None
    if mode == "resume":
        if not checkpoint_exists(LOG_NAME):
            raise FileNotFoundError("resume requested but no checkpoint")
        restored = load_state_dict(LOG_NAME)
        resume_meta = pop_train_meta(restored)
        state = trainer.place_state(restore_into(state, restored))

    # capture the stream cursor after every trained epoch (the full run's
    # trace is the killed run's cursor-equality reference)
    cursors = []
    orig = trainer.train_epoch

    def tracing_train_epoch(state, loader, rng):
        out = orig(state, loader, rng)
        cursors.append({"epoch": loader.epoch,
                        "cursor": loader.state_dict()})
        return out

    trainer.train_epoch = tracing_train_epoch

    config_nn = {
        "Training": training,
        "Variables_of_interest": {"output_names": ["sum", "x"]},
    }
    state = train_validate_test(
        trainer, state, *loaders, config_nn, LOG_NAME, verbosity=0,
        resume_meta=resume_meta,
    )
    obs_rt.deactivate()

    result = {
        "mode": mode,
        "cursors": cursors,
        "padding": train_loader.epoch_padding_stats(),
        "residency": train_loader.mix.residency_stats(),
        "final_params": [
            np.asarray(leaf, np.float64).tolist()
            for leaf in jax.tree_util.tree_leaves(
                jax.device_get(state.params)
            )
        ],
    }
    with open("result.json", "w") as f:
        json.dump(result, f)


def _run_worker(workdir, mode, extra_env=None):
    os.makedirs(workdir, exist_ok=True)
    env = dict(os.environ)
    env.update(extra_env or {})
    return subprocess.run(
        [sys.executable, os.path.abspath(__file__), "worker",
         os.path.abspath(workdir), mode],
        env=env, capture_output=True, text=True, timeout=300,
    )


def main(outdir):
    os.makedirs(outdir, exist_ok=True)

    # phase 1: uninterrupted reference (telemetry + bucket_plan event)
    full = _run_worker(os.path.join(outdir, "full"), "run")
    assert full.returncode == 0, full.stderr[-3000:]
    ref = json.load(open(os.path.join(outdir, "full", "result.json")))
    assert len(ref["cursors"]) == NUM_EPOCH

    from hydragnn_tpu.obs.events import validate_events

    events_path = os.path.join(
        outdir, "full", "logs", LOG_NAME, "events.jsonl"
    )
    recs = validate_events(events_path, require=["bucket_plan", "epoch"])
    plan = [r for r in recs if r["event"] == "bucket_plan"][0]
    assert plan["num_buckets"] >= 1 and plan["samples_scanned"] > 0
    print(f"bucket_plan event schema-valid: {plan['num_buckets']} buckets, "
          f"est_waste {plan['est_waste']}")

    # the RAM bound, asserted on the reference run's own accounting
    res = ref["residency"]
    assert res["open_shards_peak"] <= 2, res
    print(f"residency bounded by window: {res}")

    # phase 2: hard kill mid-epoch-2
    killdir = os.path.join(outdir, "kill")
    killed = _run_worker(
        killdir, "run", {"HYDRAGNN_FAULT_KILL_AT_STEP": str(KILL_STEP)}
    )
    from hydragnn_tpu.utils import faults

    assert killed.returncode == faults.KILL_EXIT_CODE, (
        killed.returncode, killed.stderr[-3000:],
    )
    assert not os.path.exists(os.path.join(killdir, "result.json"))

    # cursor equality: the killed run's checkpointed cursor == the
    # uninterrupted run's post-epoch-1 cursor
    from hydragnn_tpu.train.checkpoint import load_state_dict, pop_train_meta

    restored = load_state_dict(
        LOG_NAME, path=os.path.join(killdir, "logs")
    )
    meta = pop_train_meta(restored)
    assert meta is not None and meta.get("stream") is not None

    def canon(x):
        if isinstance(x, dict):
            return {k: canon(v) for k, v in x.items()}
        try:
            return int(x)
        except (TypeError, ValueError):
            return x

    saved_epoch = int(meta["epoch"])
    want = canon(ref["cursors"][saved_epoch]["cursor"])
    got = canon(meta["stream"])
    assert got == want, f"cursor mismatch:\n saved {got}\n ref   {want}"
    print(f"kill->checkpoint cursor equals reference post-epoch-{saved_epoch}"
          " cursor")

    # phase 3: resume -> bitwise-identical final params
    resumed = _run_worker(killdir, "resume")
    assert resumed.returncode == 0, resumed.stderr[-3000:]
    res_out = json.load(open(os.path.join(killdir, "result.json")))
    assert res_out["final_params"] == ref["final_params"], (
        "resumed trajectory diverged from uninterrupted run"
    )
    print("kill->resume final params bitwise-identical to uninterrupted run")
    return 0


if __name__ == "__main__":
    if len(sys.argv) >= 2 and sys.argv[1] == "worker":
        worker(sys.argv[2], sys.argv[3])
    else:
        sys.exit(main(sys.argv[1]))
