"""Self-healing serving fleet (serve/fleet.py + serve/router.py).

Acceptance (ISSUE 15): N InferenceServer replicas behind one router,
coordinated through the shared-dir lease/tombstone protocol extracted
from elastic training into ``hydragnn_tpu.coord``; replica death and
wedge detected + healed by the supervisor; zero-downtime registry-driven
hot-swap with CRC-bad candidates rolling back loudly; deadline-aware
budgeted retry and priority-lane load shedding at the router.

The subprocess kill-and-heal + promote e2e lives in
``tests/_fleet_smoke.py`` (the CI gate) with a ``slow``-marked pytest
wrapper here; everything in-process below reuses the test_serve harness
so the tier-1 cost stays one jit warmup.
"""

import json
import os
import threading
import time
import urllib.request

import numpy as np
import pytest

from hydragnn_tpu import coord
from hydragnn_tpu.serve import (
    DeadlineExceeded,
    FleetRouter,
    InferenceServer,
    ModelRegistry,
    ReplicaServer,
    RetryBudget,
    ServerOverloaded,
)
from hydragnn_tpu.serve.fleet import REPLICA, ServingFleet
from hydragnn_tpu.serve.router import NoLiveReplica
from hydragnn_tpu.utils import faults
from hydragnn_tpu.utils.retry import backoff_delay

from test_models_forward import arch_config
from test_serve import _graph, _harness


# ---- coord extraction ------------------------------------------------------


def pytest_coord_replica_prefix_paths_and_dead_members(tmp_path):
    """The extracted core speaks replica leases as fluently as host
    leases: kind/prefix generalization + tombstone lifecycle."""
    d = str(tmp_path)
    assert coord.hb_path(d, REPLICA, 3, prefix=REPLICA).endswith(
        "replicas/replica-3.json"
    )
    now = time.time()
    coord.write_json(
        coord.hb_path(d, REPLICA, 0, prefix=REPLICA), {"ts": now}
    )
    coord.write_json(
        coord.hb_path(d, REPLICA, 1, prefix=REPLICA), {"ts": now - 60}
    )
    dead = coord.dead_members(
        d, [0, 1, 2], lease_s=5.0, kind=REPLICA, prefix=REPLICA
    )
    assert dead == {1: pytest.approx(now, abs=5.0)}
    # tombstone + clear (the respawn path lifts the sentence)
    coord.write_tombstone(d, 0, reason="wedged", by=-1, prefix=REPLICA)
    assert coord.read_tombstone(d, 0, prefix=REPLICA)["reason"] == "wedged"
    assert 0 in coord.dead_members(
        d, [0], lease_s=5.0, kind=REPLICA, prefix=REPLICA
    )
    coord.clear_tombstone(d, 0, prefix=REPLICA)
    assert coord.read_tombstone(d, 0, prefix=REPLICA) is None
    assert 0 not in coord.dead_members(
        d, [0], lease_s=5.0, kind=REPLICA, prefix=REPLICA
    )
    # elastic still re-exports the same implementation (one core, two
    # consumers — the satellite's whole point)
    from hydragnn_tpu.train import elastic

    assert elastic.Heartbeat is coord.Heartbeat
    assert elastic.dead_members is coord.dead_members
    assert issubclass(elastic.PeerWatchdog, coord.PeerWatchdog)


# ---- registry promote / rollback -------------------------------------------


def pytest_registry_promote_rollback_and_idempotence():
    h = _harness()
    registry = ModelRegistry()
    e1 = registry.register("m", h["model"], h["state"].params,
                           h["state"].batch_stats)
    e2 = registry.register("m", h["model"], h["state"].params,
                           h["state"].batch_stats)
    # never promoted: latest registered serves (historical behavior)
    assert registry.get("m") is e2
    assert registry.promote("m", 1) is e1
    assert registry.get("m") is e1
    assert registry.describe()["m"]["version"] == 1
    assert registry.describe()["m"]["latest"] == 2
    # double-promote of the active version is an idempotent no-op: the
    # later rollback still reverts to the GENUINE previous version
    assert registry.promote("m", 1) is e1
    assert registry.rollback("m") is e2
    assert registry.get("m") is e2
    with pytest.raises(ValueError, match="roll back"):
        registry.rollback("m")
    with pytest.raises(KeyError):
        registry.promote("m", 99)
    with pytest.raises(KeyError):
        registry.promote("nope")


def pytest_registry_promote_checkpoint_rejects_corrupt_atomically(tmp_path):
    """A candidate failing CRC/strict load is rejected with NO registry
    mutation: no half-registered version, active version untouched."""
    from hydragnn_tpu.train.checkpoint import save_model

    h = _harness()
    save_model(h["state"], "base", path=str(tmp_path))
    save_model(h["state"], "cand", path=str(tmp_path))
    registry = ModelRegistry()
    registry.load_checkpoint(
        "base", arch_config=arch_config("SAGE"), path=str(tmp_path),
        name="m",
    )
    assert registry.get("m").version == 1

    # flip a payload byte: the strict v2 loader must refuse
    fname = tmp_path / "cand" / "cand.pk"
    raw = bytearray(fname.read_bytes())
    raw[len(raw) // 2] ^= 0xFF
    fname.write_bytes(bytes(raw))
    with pytest.raises(ValueError, match="corrupt"):
        registry.promote_checkpoint(
            "cand", arch_config=arch_config("SAGE"), path=str(tmp_path),
            name="m",
        )
    # atomicity: nothing registered, nothing promoted
    desc = registry.describe()["m"]
    assert desc["versions"] == 1 and desc["version"] == 1
    assert registry.get("m").version == 1

    # the intact candidate promotes in one step
    save_model(h["state"], "cand", path=str(tmp_path))
    entry = registry.promote_checkpoint(
        "cand", arch_config=arch_config("SAGE"), path=str(tmp_path),
        name="m",
    )
    assert entry.version == 2 and registry.get("m").version == 2
    assert registry.rollback("m").version == 1


def pytest_registry_promote_checkpoint_pins_active_not_latest(tmp_path):
    """promote_checkpoint in the rolled-back state (active v1 while the
    rejected candidate v2 is still registered) must pin the ACTIVE
    version: the rejected candidate never serves during the load window,
    and rollback after the fixed promote returns to the genuine
    pre-promote version, not the rejected one."""
    from hydragnn_tpu.train.checkpoint import save_model

    h = _harness()
    for ck in ("base", "bad", "fixed"):
        save_model(h["state"], ck, path=str(tmp_path))
    registry = ModelRegistry()
    registry.load_checkpoint(
        "base", arch_config=arch_config("SAGE"), path=str(tmp_path),
        name="m",
    )
    registry.promote_checkpoint(
        "bad", arch_config=arch_config("SAGE"), path=str(tmp_path),
        name="m",
    )
    assert registry.get("m").version == 2
    registry.rollback("m")
    assert registry.get("m").version == 1  # bad candidate benched
    entry = registry.promote_checkpoint(
        "fixed", arch_config=arch_config("SAGE"), path=str(tmp_path),
        name="m",
    )
    assert entry.version == 3 and registry.get("m").version == 3
    # the rollback stack never picked the benched v2 back up
    assert registry.rollback("m").version == 1


def pytest_respawn_skips_history_and_rolls_back_to_booted_base(tmp_path):
    """The respawn path's two subtle contracts: (a) promote commands
    already on disk are NEVER replayed at boot (a failed promote's
    candidate must not be re-warmed, its ack not overwritten); (b) a
    replica respawned after a resolved promote adopts the candidate but
    keeps the version it BOOTED with as the cmd-0 base, so a fleet-wide
    rollback() reverts it to the true base instead of the candidate."""
    from hydragnn_tpu.train.checkpoint import save_model

    h = _harness()
    ckdir = tmp_path / "ck"
    save_model(h["state"], "base", path=str(ckdir))
    save_model(h["state"], "cand", path=str(ckdir))

    def boot(coord_dir):
        registry = ModelRegistry()
        registry.load_checkpoint(
            "base", arch_config=arch_config("SAGE"), path=str(ckdir),
            name="m",
        )
        server = InferenceServer(
            registry, h["plan"], default_model="m", max_wait_s=0.002
        )
        rep = ReplicaServer(
            server, coord_dir, 0, heartbeat_s=0.05, model_name="m",
            arch_config=arch_config("SAGE"), poll_s=0.02,
        )
        return registry, rep

    # (a) a failed promote's cmd file with NO published active version
    d1 = str(tmp_path / "c1")
    os.makedirs(os.path.join(d1, "promote"))
    coord.write_json(
        os.path.join(d1, "promote", "cmd-000001.json"),
        {"cmd_id": 1, "checkpoint": "cand", "path": str(ckdir)},
    )
    registry1, rep1 = boot(d1)
    rep1.start()
    try:
        time.sleep(0.2)  # several watcher ticks
        assert rep1._last_cmd_handled == 1
        assert registry1.describe()["m"]["versions"] == 1  # no replay
        assert not os.path.exists(
            os.path.join(d1, "promote", "ack-000001-r0.json")
        )
    finally:
        rep1.shutdown()

    # (b) respawn after the promote RESOLVED: adopt, then roll back
    d2 = str(tmp_path / "c2")
    os.makedirs(os.path.join(d2, "promote"))
    coord.write_json(
        os.path.join(d2, "promote", "cmd-000001.json"),
        {"cmd_id": 1, "checkpoint": "cand", "path": str(ckdir)},
    )
    coord.write_json(
        os.path.join(d2, "promote", "active.json"),
        {"seq": 1, "cmd_id": 1, "latest_cmd": 1},
    )
    registry2, rep2 = boot(d2)
    rep2.start()
    try:
        assert registry2.get("m").version == 2  # serving the candidate
        assert rep2._warmed[0] == ("m", 1)  # base = the BOOTED version
        coord.write_json(
            os.path.join(d2, "promote", "active.json"),
            {"seq": 2, "cmd_id": 0, "latest_cmd": 1},
        )
        deadline = time.monotonic() + 20
        while (
            time.monotonic() < deadline
            and registry2.get("m").version != 1
        ):
            time.sleep(0.02)
        assert registry2.get("m").version == 1  # true base, no split
    finally:
        rep2.shutdown()


# ---- fault-injection knobs (each fires exactly once at its trigger,
# inert when unset — the PR 8 fault-unit pattern) ---------------------------


def pytest_fault_kill_replica_fires_once_at_trigger(monkeypatch):
    exits = []
    monkeypatch.setattr(os, "_exit", exits.append)
    faults.reset()
    # inert when unset
    monkeypatch.delenv("HYDRAGNN_FAULT_KILL_REPLICA_AT_REQUEST",
                       raising=False)
    for _ in range(3):
        faults.kill_replica_at_request()
    assert exits == []
    # inert for a different replica id even at the matching ordinal
    monkeypatch.setenv("HYDRAGNN_FLEET_REPLICA", "0")
    monkeypatch.setenv("HYDRAGNN_FAULT_KILL_REPLICA_AT_REQUEST", "1:1")
    faults.kill_replica_at_request()
    assert exits == []
    # fires exactly once, at the configured (replica, ordinal)
    faults.reset()
    monkeypatch.setenv("HYDRAGNN_FAULT_KILL_REPLICA_AT_REQUEST", "0:2")
    faults.kill_replica_at_request()
    assert exits == []  # ordinal 1 != 2
    faults.kill_replica_at_request()
    assert exits == [faults.KILL_EXIT_CODE]  # ordinal 2: fire
    faults.kill_replica_at_request()
    assert exits == [faults.KILL_EXIT_CODE]  # ordinal 3: once only
    faults.reset()


def pytest_fault_slow_replica_spec(monkeypatch):
    sleeps = []
    monkeypatch.setattr(time, "sleep", sleeps.append)
    monkeypatch.delenv("HYDRAGNN_FAULT_SLOW_REPLICA", raising=False)
    faults.slow_replica(0)
    assert sleeps == []  # inert when unset
    monkeypatch.setenv("HYDRAGNN_FLEET_REPLICA", "1")
    monkeypatch.setenv("HYDRAGNN_FAULT_SLOW_REPLICA", "1:3@0.2")
    for i in range(6):
        faults.slow_replica(i)
    assert sleeps == [0.2]  # exactly once, at request ordinal 3
    monkeypatch.setenv("HYDRAGNN_FAULT_SLOW_REPLICA", "0:3@0.2")
    faults.slow_replica(3)
    assert sleeps == [0.2]  # other replica targeted: inert here
    # bare colon-free spec targets replica 0, default 0.25 s
    monkeypatch.setenv("HYDRAGNN_FLEET_REPLICA", "0")
    monkeypatch.setenv("HYDRAGNN_FAULT_SLOW_REPLICA", "5")
    faults.slow_replica(5)
    assert sleeps == [0.2, 0.25]


def pytest_fault_corrupt_candidate_fires_once(tmp_path, monkeypatch):
    blob = bytes(range(64))
    src = tmp_path / "cand.pk"
    src.write_bytes(blob)
    faults.reset()
    monkeypatch.delenv("HYDRAGNN_FAULT_CORRUPT_CANDIDATE", raising=False)
    assert faults.corrupt_candidate(str(src)) == str(src)  # inert unset
    monkeypatch.setenv("HYDRAGNN_FAULT_CORRUPT_CANDIDATE", "2")
    faults.reset()
    assert faults.corrupt_candidate(str(src)) == str(src)  # load 1: no
    out = faults.corrupt_candidate(str(src))  # load 2: fires
    assert out != str(src)
    corrupted = open(out, "rb").read()
    assert corrupted != blob and len(corrupted) == len(blob)
    assert corrupted[len(blob) // 2] == blob[len(blob) // 2] ^ 0xFF
    assert src.read_bytes() == blob  # the shared original is untouched
    assert faults.corrupt_candidate(str(src)) == str(src)  # once only
    faults.reset()


# ---- retry budget + backoff ------------------------------------------------


def pytest_retry_budget_token_bucket():
    b = RetryBudget(ratio=0.5, reserve=2.0)
    assert b.try_acquire() and b.try_acquire()
    assert not b.try_acquire()  # reserve exhausted: a storm dies here
    for _ in range(2):
        b.on_success()
    assert b.tokens == 1.0
    assert b.try_acquire() and not b.try_acquire()
    for _ in range(100):
        b.on_success()
    assert b.tokens == 2.0  # earned tokens cap at the reserve
    with pytest.raises(ValueError):
        RetryBudget(ratio=-1)


def pytest_backoff_delay_shared_curve():
    for attempt in range(4):
        lo = 0.05 * 2.0 ** attempt
        for _ in range(20):
            d = backoff_delay(attempt, 0.05)
            assert lo <= d <= lo * 1.5 + 1e-12


# ---- router admission / shedding (no live replicas needed) ----------------


def pytest_router_sheds_with_retry_after_when_fleet_empty(tmp_path):
    router = FleetRouter(str(tmp_path), target_replicas=2,
                         scan_interval_s=0.0)
    g = _graph(8, np.random.default_rng(0), with_targets=False)
    with pytest.raises(ServerOverloaded) as exc:
        router.route(g)
    assert exc.value.retry_after_s > 0  # the queue-full contract, fleet-wide
    assert router.metrics.shed_total == 1
    with pytest.raises(ValueError, match="unknown lane"):
        router.route(g, lane="nope")


def pytest_router_degraded_sheds_low_priority_lane_only(tmp_path):
    d = str(tmp_path)
    # one live lease of a target-2 fleet: degraded
    coord.write_json(
        coord.hb_path(d, REPLICA, 0, prefix=REPLICA),
        {"ts": time.time(), "state": "serving", "port": 1,
         "replica": 0},
    )
    coord.write_json(
        os.path.join(d, "fleet.json"),
        {"live": 1, "target": 2, "degraded": True, "ts": time.time()},
    )
    router = FleetRouter(
        d, lanes={"interactive": 0, "batch": 1},
        shed_priority_when_degraded=1, scan_interval_s=0.0,
        max_attempts=2, retry_base_delay_s=0.001,
    )
    g = _graph(8, np.random.default_rng(1), with_targets=False)
    # the batch lane sheds at admission, with a retry-after hint and the
    # per-lane gauge moving
    with pytest.raises(ServerOverloaded) as exc:
        router.route(g, lane="batch")
    assert exc.value.retry_after_s > 0
    snap = router.fleet_metrics.snapshot()
    assert snap["lane_shed_total"] == {"lane=batch": 1}
    # the interactive lane is still admitted — port 1 answers nothing, so
    # it burns its attempts against connection failures and fails LOUDLY
    with pytest.raises(NoLiveReplica):
        router.route(g, lane="interactive")
    assert router.fleet_metrics.snapshot()["replica_errors_total"] >= 1


# ---- in-process replica: routing, stop-under-load, hot-swap ---------------


def _fresh_server(**kw):
    """A fresh registry + InferenceServer over the shared harness model
    (promote state must not leak into the module harness)."""
    h = _harness()
    registry = ModelRegistry()
    registry.register("sage", h["model"], h["state"].params,
                      h["state"].batch_stats)
    kw.setdefault("max_wait_s", 0.002)
    return InferenceServer(registry, h["plan"], default_model="sage", **kw)


def pytest_replica_roundtrip_and_router_parity(tmp_path):
    """Route through lease discovery + HTTP and get the same numbers the
    in-process server returns; raw mode carries version/batch/replica."""
    server = _fresh_server()
    rep = ReplicaServer(server, str(tmp_path), 0, heartbeat_s=0.05)
    rep.start()
    try:
        router = FleetRouter(str(tmp_path), target_replicas=1,
                             lease_s=2.0, scan_interval_s=0.05)
        g = _graph(12, np.random.default_rng(2), with_targets=False)
        heads = router.route(g, deadline_s=30.0)
        direct = server.predict(g, timeout=30)
        for a, b in zip(heads, direct):
            np.testing.assert_allclose(a, b, atol=1e-6)
        raw = router.route(g, deadline_s=30.0, raw=True)
        assert raw["replica"] == 0 and raw["version"] == 1
        assert raw["batch_seq"] >= 1
        # an unknown model name is the REQUEST's fault: 400, propagated
        # immediately — never burned against the retry budget
        with pytest.raises(RuntimeError, match="answered 400"):
            router.route(g, model="nope", deadline_s=10.0)
        # the deadline series counted the met deadlines end to end
        assert router.metrics.snapshot()["deadline_met_total"] == 2
        # /healthz over the replica port carries replica identity
        host, port = rep.address
        health = json.load(
            urllib.request.urlopen(f"http://{host}:{port}/healthz")
        )
        assert health["replica"] == 0 and health["state"] == "serving"
        assert "hydragnn_serve_requests_total" in (
            urllib.request.urlopen(f"http://{host}:{port}/metrics")
            .read().decode()
        )
    finally:
        rep.shutdown()
    # a drained replica releases a done-marked lease: not dead, not live
    lease = coord.read_json(
        coord.hb_path(str(tmp_path), REPLICA, 0, prefix=REPLICA)
    )
    assert lease["done"] and lease["state"] == "stopped"
    assert coord.dead_members(
        str(tmp_path), [0], lease_s=0.0, kind=REPLICA, prefix=REPLICA
    ) == {}


def pytest_replica_stop_under_load_terminal_outcomes(tmp_path):
    """The PR 6 stop-under-load contract extended to the respawn path:
    a fleet-orchestrated replica teardown resolves EVERY accepted
    request with a terminal outcome — a result, or an explicit shed
    whose retry-after matches the queue-full contract. No hangs, no
    silent drops."""
    server = _fresh_server(queue_capacity=64)
    rep = ReplicaServer(server, str(tmp_path), 0, heartbeat_s=0.05)
    rep.start()
    router = FleetRouter(str(tmp_path), target_replicas=1,
                         scan_interval_s=0.05, max_attempts=1)
    rng = np.random.default_rng(3)
    graphs = [
        _graph(int(n), rng, with_targets=False)
        for n in rng.integers(4, 30, 40)
    ]
    outcomes = []
    lock = threading.Lock()

    def client(chunk):
        for g in chunk:
            try:
                router.route(g, deadline_s=20.0)
                out = "ok"
            except ServerOverloaded as e:
                assert e.retry_after_s > 0
                out = "shed"
            except (NoLiveReplica, DeadlineExceeded):
                out = "unreachable"
            with lock:
                outcomes.append(out)

    threads = [
        threading.Thread(target=client, args=(graphs[i::4],))
        for i in range(4)
    ]
    for t in threads:
        t.start()
    time.sleep(0.05)  # load in flight
    rep.shutdown(drain=True, timeout=20.0)
    for t in threads:
        t.join(timeout=30.0)
        assert not t.is_alive(), "client thread hung past shutdown"
    assert len(outcomes) == len(graphs)  # every request terminal
    assert outcomes.count("ok") >= 1
    # shutting down mid-burst: the tail was answered, shed with a hint,
    # or found the lease already released — never silently dropped
    assert all(o in ("ok", "shed", "unreachable") for o in outcomes)
    # the replica-side metrics lifecycle invariant survived the teardown
    snap = server.metrics.snapshot()
    assert snap["requests_total"] == (
        snap["responses_total"] + snap["timeouts_total"]
        + snap["errors_total"]
    )


def pytest_hot_swap_promote_and_corrupt_rollback_in_process(tmp_path):
    """The hot-swap e2e, replica-side: a candidate checkpoint is loaded
    + warmed through the LIVE batcher (compile-counter verified) and
    atomically promoted under load with zero failed requests and no
    micro-batch mixing versions; a corrupt candidate acks failed and the
    old version never stops serving."""
    from hydragnn_tpu.train.checkpoint import save_model

    h = _harness()
    ckdir = tmp_path / "ck"
    save_model(h["state"], "base", path=str(ckdir))
    bumped = h["state"].replace(
        params=__import__("jax").tree_util.tree_map(
            lambda x: x + 0.05, h["state"].params
        )
    )
    save_model(bumped, "cand", path=str(ckdir))

    coord_dir = str(tmp_path / "coord")
    registry = ModelRegistry()
    registry.load_checkpoint(
        "base", arch_config=arch_config("SAGE"), path=str(ckdir), name="m"
    )
    server = InferenceServer(
        registry, h["plan"], default_model="m", max_wait_s=0.002,
        queue_capacity=256,
    )
    rep = ReplicaServer(
        server, coord_dir, 0, heartbeat_s=0.05,
        model_name="m", arch_config=arch_config("SAGE"),
    )
    rep.start()
    try:
        router = FleetRouter(coord_dir, target_replicas=1,
                             scan_interval_s=0.05)
        g = _graph(10, np.random.default_rng(4), with_targets=False)
        before = router.route(g, deadline_s=30.0, raw=True)
        assert before["version"] == 1

        # closed-loop load through the whole swap
        stop = threading.Event()
        responses = []
        failures = []
        lock = threading.Lock()

        def pump():
            rng = np.random.default_rng(5)
            while not stop.is_set():
                gg = _graph(int(rng.integers(4, 30)), rng,
                            with_targets=False)
                try:
                    raw = router.route(gg, deadline_s=30.0, raw=True)
                    with lock:
                        responses.append(
                            (raw["batch_seq"], raw["version"])
                        )
                except Exception as e:  # any failure breaks the promise
                    with lock:
                        failures.append(repr(e))

        pumps = [threading.Thread(target=pump) for _ in range(2)]
        for t in pumps:
            t.start()
        try:
            # supervisor-side command, replica-side execution
            pdir = os.path.join(coord_dir, "promote")
            coord.write_json(
                os.path.join(pdir, "cmd-000001.json"),
                {"cmd_id": 1, "checkpoint": "cand", "path": str(ckdir)},
            )
            deadline = time.monotonic() + 60
            ack = None
            while time.monotonic() < deadline and ack is None:
                ack = coord.read_json(
                    os.path.join(pdir, "ack-000001-r0.json")
                )
                time.sleep(0.05)
            assert ack is not None, "promote never acked"
            assert ack["status"] == "warmed", ack
            assert ack["version"] == 2
            # per-bucket warm, compile-counter verified, old version
            # still the active one until the publish
            assert ack["compiles"] == h["plan"].num_buckets
            assert registry.get("m").version == 1
            coord.write_json(
                os.path.join(pdir, "active.json"),
                {"seq": 1, "cmd_id": 1, "latest_cmd": 1},
            )
            deadline = time.monotonic() + 30
            while (
                time.monotonic() < deadline
                and registry.get("m").version != 2
            ):
                time.sleep(0.02)
            assert registry.get("m").version == 2
            after = router.route(g, deadline_s=30.0, raw=True)
            assert after["version"] == 2
            # v2 really is the candidate's weights
            np.testing.assert_allclose(
                np.asarray(after["heads"][0]),
                np.asarray(
                    server.predict(g, model="m", timeout=30)[0]
                ),
                atol=1e-6,
            )
            assert not np.allclose(
                np.asarray(after["heads"][0]),
                np.asarray(before["heads"][0]),
            )

            # corrupt candidate: strict load refuses, ack says failed,
            # active version keeps serving every request
            raw2 = bytearray((ckdir / "cand" / "cand.pk").read_bytes())
            raw2[len(raw2) // 2] ^= 0xFF
            (ckdir / "broken" / "broken.pk").parent.mkdir(parents=True)
            (ckdir / "broken" / "broken.pk").write_bytes(bytes(raw2))
            coord.write_json(
                os.path.join(pdir, "cmd-000002.json"),
                {"cmd_id": 2, "checkpoint": "broken", "path": str(ckdir)},
            )
            deadline = time.monotonic() + 60
            ack2 = None
            while time.monotonic() < deadline and ack2 is None:
                ack2 = coord.read_json(
                    os.path.join(pdir, "ack-000002-r0.json")
                )
                time.sleep(0.05)
            assert ack2 is not None and ack2["status"] == "failed", ack2
            assert "corrupt" in ack2["error"]
            assert registry.get("m").version == 2  # untouched
            assert router.route(g, deadline_s=30.0, raw=True)[
                "version"
            ] == 2
        finally:
            stop.set()
            for t in pumps:
                t.join(timeout=30.0)
        # zero failed requests through kill-free swap + rejected promote
        assert failures == []
        assert len(responses) > 0
        # no micro-batch mixed versions: every batch_seq maps to ONE
        # version (in-flight batches kept their packed entry)
        by_batch = {}
        for seq, version in responses:
            by_batch.setdefault(seq, set()).add(version)
        assert all(len(v) == 1 for v in by_batch.values()), by_batch
        assert {v for s in by_batch.values() for v in s} <= {1, 2}
    finally:
        rep.shutdown()


# ---- supervisor logic (in-process, fake processes) ------------------------


class _FakeProc:
    def __init__(self, rc=None):
        self.rc = rc
        self.pid = 4242
        self.killed = False

    def poll(self):
        return self.rc

    def kill(self):
        self.killed = True
        self.rc = -9

    def wait(self, timeout=None):
        return self.rc


def pytest_supervisor_heals_exit_and_wedge_and_prices_events(tmp_path):
    """ServingFleet._tick against fake replica processes: death by exit
    and by stale lease both tombstone-for-the-record, respawn at the
    next incarnation, and price the transitions as schema-valid events
    + gauges; the respawned replica's serving lease closes the loop with
    replica_respawned + downtime."""
    from hydragnn_tpu.obs.events import validate_events

    d = str(tmp_path / "coord")
    fleet = ServingFleet(
        d, 2, worker_cmd=["true"], lease_s=0.5, poll_s=0.05,
        log_dir=str(tmp_path / "log"),
    )
    for sub in (f"{REPLICA}s", "dead", "promote"):
        os.makedirs(os.path.join(d, sub), exist_ok=True)
    spawned = []
    fleet._spawn = lambda h: (  # no real processes in this unit
        spawned.append((h.rid, h.incarnation)),
        setattr(h, "proc", _FakeProc()),
        setattr(h, "spawned_ts", time.time()),
        setattr(h, "was_serving", False),
    )
    h0, h1 = fleet._replicas[0], fleet._replicas[1]
    h0.proc, h1.proc = _FakeProc(), _FakeProc()
    now = time.time()
    for rid in (0, 1):
        coord.write_json(
            coord.hb_path(d, REPLICA, rid, prefix=REPLICA),
            {"ts": now, "gen": 0, "state": "serving", "port": 1000 + rid},
        )
    fleet._tick(now)
    assert fleet.metrics.snapshot()["live_replicas"] == 2.0
    assert fleet.metrics.snapshot()["availability"] == 1.0

    # replica 0 exits; replica 1 wedges (stale lease, process alive)
    h0.proc.rc = -9
    coord.write_json(
        coord.hb_path(d, REPLICA, 1, prefix=REPLICA),
        {"ts": now - 60, "gen": 0, "state": "serving", "port": 1001},
    )
    fleet._tick(now + 1.0)
    assert [s[0] for s in spawned] == [0, 1]  # both respawned
    assert h0.incarnation == 1 and h1.incarnation == 1
    assert h1.proc.killed or spawned  # the wedged one was killed first
    snap = fleet.metrics.snapshot()
    assert snap["replica_losses_total"] == 2
    assert snap["degraded"] == 1.0 and snap["live_replicas"] == 0.0
    # tombstones were lifted for the respawn
    assert coord.read_tombstone(d, 0, prefix=REPLICA) is None
    # a stale lease from the OLD incarnation reads as booting, not dead
    fleet._tick(now + 1.5)
    assert fleet.metrics.snapshot()["replica_losses_total"] == 2

    # the respawned replicas report serving at the new incarnation
    for rid in (0, 1):
        coord.write_json(
            coord.hb_path(d, REPLICA, rid, prefix=REPLICA),
            {"ts": now + 2.0, "gen": 1, "state": "serving",
             "port": 2000 + rid},
        )
    fleet._tick(now + 2.0)
    snap = fleet.metrics.snapshot()
    assert snap["replica_respawns_total"] == 2
    assert snap["live_replicas"] == 2.0 and snap["degraded"] == 0.0
    assert snap["last_recovery_seconds"] > 0
    fleet.events.close()
    recs = validate_events(
        str(tmp_path / "log" / "events.jsonl"),
        require=["replica_lost", "replica_respawned", "fleet_degraded"],
    )
    lost = [r for r in recs if r["event"] == "replica_lost"]
    assert {r["reason"] for r in lost} == {"exit_-9", "lease_expired"}
    respawned = [r for r in recs if r["event"] == "replica_respawned"]
    assert all(r["downtime_s"] > 0 for r in respawned)


# ---- subprocess e2e (the CI smoke, wrapped) -------------------------------


@pytest.mark.slow  # 2 replica processes x jax import + warmup
def pytest_fleet_smoke_e2e(tmp_path):
    import _fleet_smoke

    _fleet_smoke.main(str(tmp_path / "smoke"))
