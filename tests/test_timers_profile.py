"""utils/timers.py + utils/profile.py — previously untested.

Timers: re-registration accumulation semantics (a new ``Timer(name)``
inherits the accumulated elapsed of its predecessor) and the
``print_timers`` cross-host min/max/avg reduction, proven against a FAKE
world (monkeypatched rank/world + host_allreduce) rather than hope.

Profiler: the wait/warmup/active step schedule and the target-epoch gate,
against a recording fake of ``jax.profiler``.
"""

import time

import numpy as np
import pytest

from hydragnn_tpu.utils.profile import Profiler
from hydragnn_tpu.utils.timers import Timer, print_timers, reset_timers


# ---- Timer accumulation --------------------------------------------------


def pytest_timer_reregistration_accumulates():
    reset_timers()
    a = Timer("phase")
    a.start()
    time.sleep(0.01)
    a.stop()
    first = a.elapsed
    assert first > 0
    # a NEW Timer of the same name picks up the accumulated total — the
    # class-level aggregation the reference's time_utils relies on
    b = Timer("phase")
    assert b.elapsed == first
    b.start()
    time.sleep(0.01)
    b.stop()
    assert b.elapsed > first
    # a different name starts from zero
    assert Timer("other").elapsed == 0.0
    reset_timers()
    assert Timer("phase").elapsed == 0.0
    reset_timers()


def pytest_timer_stop_without_start_is_noop():
    reset_timers()
    t = Timer("idle")
    t.stop()  # must not raise or accumulate
    assert t.elapsed == 0.0
    reset_timers()


# ---- print_timers cross-host reduction -----------------------------------


class _FakeWorld:
    """Two hosts: rank 0 measured ``base``, rank 1 measured ``base + skew``
    per timer — so min/max/avg have known closed forms."""

    def __init__(self, world=2, rank=0, skew=2.0):
        self.world = world
        self.rank = rank
        self.skew = skew

    def get_comm_size_and_rank(self):
        return self.world, self.rank

    def host_allreduce(self, values, op="sum"):
        values = np.asarray(values, np.float64)
        others = [values + self.skew * r for r in range(1, self.world)]
        stack = np.stack([values] + others)
        return {
            "min": stack.min(axis=0),
            "max": stack.max(axis=0),
            "sum": stack.sum(axis=0),
        }[op]


def _patch_world(monkeypatch, fake):
    import hydragnn_tpu.parallel.distributed as dist

    monkeypatch.setattr(
        dist, "get_comm_size_and_rank", fake.get_comm_size_and_rank
    )
    monkeypatch.setattr(dist, "host_allreduce", fake.host_allreduce)


def pytest_print_timers_reduces_across_fake_world(monkeypatch, capsys):
    reset_timers()
    t = Timer("epoch")
    t.elapsed = 10.0
    u = Timer("load")
    u.elapsed = 4.0
    _patch_world(monkeypatch, _FakeWorld(world=2, rank=0, skew=2.0))
    print_timers(verbosity=0)
    out = capsys.readouterr().out
    lines = [ln.split() for ln in out.strip().splitlines()]
    assert lines[0] == ["timer", "min_s", "max_s", "avg_s"]
    # sorted by name: epoch then load; rank1 = rank0 + 2.0
    assert lines[1] == ["epoch", "10.0000", "12.0000", "11.0000"]
    assert lines[2] == ["load", "4.0000", "6.0000", "5.0000"]
    reset_timers()


def pytest_print_timers_silent_off_rank_zero(monkeypatch, capsys):
    reset_timers()
    Timer("epoch").elapsed = 1.0
    _patch_world(monkeypatch, _FakeWorld(world=2, rank=1))
    print_timers(verbosity=0)
    assert capsys.readouterr().out == ""
    reset_timers()


def pytest_print_timers_no_timers_is_noop(capsys):
    reset_timers()
    print_timers(verbosity=0)
    assert capsys.readouterr().out == ""


# ---- Profiler schedule ---------------------------------------------------


class _FakeJaxProfiler:
    def __init__(self):
        self.calls = []

    def start_trace(self, trace_dir):
        self.calls.append(("start", trace_dir))

    def stop_trace(self):
        self.calls.append(("stop",))


@pytest.fixture
def fake_profiler(monkeypatch, tmp_path):
    import jax.profiler

    fake = _FakeJaxProfiler()
    monkeypatch.setattr(jax.profiler, "start_trace", fake.start_trace)
    monkeypatch.setattr(jax.profiler, "stop_trace", fake.stop_trace)
    return fake


def pytest_profiler_wait_warmup_active_schedule(fake_profiler, tmp_path):
    prof = Profiler(
        str(tmp_path / "trace"), wait=2, warmup=1, active=2, target_epoch=1
    )
    prof.setup({"enable": 1})
    prof.set_current_epoch(1)
    with prof:
        for step in range(1, 8):
            prof.step()
            if step <= 2:  # wait window: nothing traced yet
                assert fake_profiler.calls == []
            elif step < 5:  # warmup+active: tracing
                assert fake_profiler.calls == [
                    ("start", str(tmp_path / "trace"))
                ]
    # stopped exactly once, at wait+warmup+active+1 (step 6), not at exit
    assert fake_profiler.calls == [
        ("start", str(tmp_path / "trace")), ("stop",)
    ]


def pytest_profiler_target_epoch_gates(fake_profiler, tmp_path):
    prof = Profiler(str(tmp_path / "t"), wait=0, warmup=1, active=1,
                    target_epoch=3)
    prof.setup({"enable": 1})
    prof.set_current_epoch(2)  # wrong epoch: schedule must not arm
    with prof:
        for _ in range(5):
            prof.step()
    assert fake_profiler.calls == []
    prof.set_current_epoch(3)
    with prof:
        for _ in range(3):
            prof.step()
    assert fake_profiler.calls == [("start", str(tmp_path / "t")), ("stop",)]


def pytest_profiler_disabled_never_traces(fake_profiler, tmp_path):
    prof = Profiler(str(tmp_path / "t"), wait=0, warmup=0, active=1)
    prof.setup({})  # no config -> stays disabled
    assert not prof.enabled
    prof.set_current_epoch(1)
    with prof:
        for _ in range(4):
            prof.step()
    assert fake_profiler.calls == []


def pytest_profiler_exit_stops_open_trace(fake_profiler, tmp_path):
    # active window still open when the epoch ends: __exit__ must close it
    prof = Profiler(str(tmp_path / "t"), wait=0, warmup=2, active=10,
                    target_epoch=None)
    prof.setup({"enable": 1, "wait": 0, "warmup": 2, "active": 10})
    prof.set_current_epoch(0)
    with prof:
        for _ in range(3):
            prof.step()
    assert fake_profiler.calls == [("start", str(tmp_path / "t")), ("stop",)]


def pytest_profiler_setup_reads_config(tmp_path):
    prof = Profiler(str(tmp_path / "default"))
    prof.setup(
        {"enable": 1, "trace_dir": str(tmp_path / "cfg"), "wait": 7,
         "warmup": 2, "active": 4, "target_epoch": 5}
    )
    assert prof.enabled
    assert prof.trace_dir == str(tmp_path / "cfg")
    assert (prof.wait, prof.warmup, prof.active) == (7, 2, 4)
    assert prof.target_epoch == 5
