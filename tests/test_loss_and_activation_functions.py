"""Interface smoke tests: every loss x activation combination runs a few
training steps without error (reference
``tests/test_loss_and_activation_functions.py`` — 'does not assert
anything' beyond completing)."""

import os

import numpy as np
import pytest

import jax

from hydragnn_tpu.models import create_model_config, init_model_params
from hydragnn_tpu.train.trainer import Trainer

from test_models_forward import arch_config, make_batch

LOSSES = ["mse", "mae", "rmse", "smooth_l1"]
ACTIVATIONS = [
    "relu",
    "selu",
    "prelu",
    "elu",
    "lrelu_01",
    "lrelu_025",
    "lrelu_05",
    "sigmoid",
]


# Default CI covers every loss (with one activation) and every activation
# (with one loss) — 11 compiles instead of the 28-combo cross product;
# HYDRAGNN_FULL_TEST=1 restores the full matrix. SAGE backbone: the
# simplest conv, so each combo's (cached) compile is cheapest — the combo
# under test is the loss/activation plumbing, not the conv.
FULL = int(os.getenv("HYDRAGNN_FULL_TEST", "0")) == 1
if FULL:
    COMBOS = [(l, a) for l in LOSSES for a in ACTIVATIONS]
else:
    COMBOS = [(l, "relu") for l in LOSSES] + [
        ("mse", a) for a in ACTIVATIONS if a != "relu"
    ]


@pytest.mark.parametrize("loss_name,activation", COMBOS)
def pytest_loss_activation(loss_name, activation):
    batch = make_batch()
    cfg = arch_config("SAGE")
    cfg["activation_function"] = activation
    cfg["loss_function_type"] = loss_name
    model = create_model_config(cfg)
    trainer = Trainer(model, {"Optimizer": {"type": "AdamW", "learning_rate": 1e-3}})
    state = trainer.init_state(batch)
    rng = jax.random.PRNGKey(0)
    for _ in range(2):
        rng, sub = jax.random.split(rng)
        state, metrics = trainer._train_step(state, trainer.put_batch(batch), sub)
    assert np.isfinite(float(metrics["loss"]))
