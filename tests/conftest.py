"""Test harness: run everything on an 8-device virtual CPU mesh.

Mirrors the reference CI strategy (SURVEY.md §4): their "fake cluster" is
gloo-on-CPU under mpirun; ours is XLA's host-platform device partitioning —
the same sharded code paths compile and run with N=8 logical devices on one
host, no mocks.

Note: this container pre-imports jax and pins JAX_PLATFORMS to the TPU plugin
at interpreter startup, so plain env vars in conftest are too late — we
override through jax.config before any backend is initialized.
"""

import os
import sys

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# persistent XLA compile cache: recompiles of the jitted train/eval
# programs dominate CI wall-clock on this 1-core host; with the cache warm
# the full default suite drops by minutes (driver paths already enable it,
# this covers direct-Trainer unit tests too)
from hydragnn_tpu.utils.compile_cache import enable_compile_cache

enable_compile_cache()

# ---- CI tiers -------------------------------------------------------------
# HYDRAGNN_FAST_TEST=1: skip the end-to-end/subprocess-heavy files — the
# ~6-minute smoke tier on the 1-core CI host (BASELINE.md "CI economics").
# HYDRAGNN_FULL_TEST=1 (read inside the files) widens matrices instead.
if int(os.getenv("HYDRAGNN_FAST_TEST", "0")) == 1:
    collect_ignore = [
        "test_graphs.py",  # e2e accuracy trainings
        "test_examples.py",  # example subprocesses
        "test_multiprocess.py",  # two-process distributed runs
        "test_partitioned_run_training.py",  # partitioned e2e trainings
        "test_model_loadpred.py",  # train+reload e2e runs
        "test_hpo.py",  # HPO trial loops
    ]
