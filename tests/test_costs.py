"""Tenant cost ledger (serve/costs.py): attribution, billing, feedback.

Deterministic unit coverage with an injected clock: per-batch
attribution sums, the bill's exact-sum invariant (tenant device-seconds
+ idle == replica-seconds), fleet bill merging and per-million pricing,
and the cost->quota feedback loop against a REAL TenantManager — shave
under persistent over-cost, the starvation floor, restore on sustained
under-cost, and the schema shape of every ``quota_adjusted`` event.
"""

import pytest

from hydragnn_tpu.obs.events import EVENT_FIELDS
from hydragnn_tpu.serve.costs import (
    UNTENANTED,
    CostLedger,
    merge_bills,
    price_per_million,
)
from hydragnn_tpu.serve.tenants import TenantManager, TenantSpec


class _Clock:
    def __init__(self, now=0.0):
        self.now = now

    def __call__(self):
        return self.now

    def advance(self, dt):
        self.now += dt


class _Sink:
    def __init__(self):
        self.events = []

    def __call__(self, event, **fields):
        self.events.append((event, fields))


def _manager(**quotas):
    specs = [
        TenantSpec(name=name, model="m", quota=q, weight=1.0)
        for name, q in quotas.items()
    ]
    return TenantManager(specs, default_quota=64)


# ---- attribution + billing -------------------------------------------------


def pytest_note_batch_accumulates():
    clock = _Clock()
    ledger = CostLedger(clock=clock)
    ledger.note_batch("acme", 0, 4, 0.2, flops=100.0)
    ledger.note_batch("acme", 1, 2, 0.3, flops=50.0)
    ledger.note_batch("beta", 0, 1, 0.5)
    ledger.note_batch(None, 0, 1, 0.1)
    clock.advance(2.0)
    bill = ledger.bill()
    acme = bill["tenants"]["acme"]
    assert acme["device_s"] == pytest.approx(0.5)
    assert acme["flops"] == pytest.approx(150.0)
    assert acme["requests"] == 6
    assert acme["batches"] == 2
    assert bill["tenants"][UNTENANTED]["device_s"] == pytest.approx(0.1)
    assert acme["cost_share"] == pytest.approx(0.5 / 1.1, abs=1e-5)


def pytest_bill_sums_exactly_to_replica_seconds():
    clock = _Clock()
    ledger = CostLedger(clock=clock)
    ledger.note_batch("acme", 0, 3, 0.7)
    ledger.note_batch("beta", 0, 3, 0.4)
    clock.advance(10.0)
    bill = ledger.bill()
    assert bill["replica_s"] == pytest.approx(10.0)
    total = (
        sum(t["device_s"] for t in bill["tenants"].values())
        + bill["idle_s"]
    )
    assert total == pytest.approx(bill["replica_s"], rel=1e-9)
    # skew clamp: busy beyond the lifetime never goes negative-idle
    ledger2 = CostLedger(clock=_Clock())
    ledger2.note_batch("acme", 0, 1, 5.0)
    assert ledger2.bill()["idle_s"] == 0.0


def pytest_merge_bills_and_price_per_million(monkeypatch):
    clock_a, clock_b = _Clock(), _Clock()
    a, b = CostLedger(clock=clock_a), CostLedger(clock=clock_b)
    a.note_batch("acme", 0, 10, 1.0, flops=10.0)
    b.note_batch("acme", 0, 10, 3.0, flops=30.0)
    b.note_batch("beta", 0, 5, 1.0)
    clock_a.advance(5.0)
    clock_b.advance(7.0)
    merged = merge_bills([a.bill(), b.bill(), {}])
    assert merged["replica_s"] == pytest.approx(12.0)
    assert merged["tenants"]["acme"]["device_s"] == pytest.approx(4.0)
    assert merged["tenants"]["acme"]["requests"] == 20
    assert merged["tenants"]["acme"]["cost_share"] == pytest.approx(0.8)
    monkeypatch.setenv("HYDRAGNN_COST_PER_REPLICA_HOUR", "3.6")
    price = price_per_million(merged, succeeded=24)
    assert price["replica_s_per_million"] == pytest.approx(5e5)
    assert price["cost_per_million"] == pytest.approx(5e5 / 3600 * 3.6)
    assert price_per_million(merged, 0)["cost_per_million"] == float("inf")


def pytest_prometheus_families_render():
    ledger = CostLedger(clock=_Clock())
    ledger.note_batch("acme", 0, 1, 0.5)
    text = ledger.render_prometheus()
    assert 'hydragnn_tenant_cost_device_seconds{tenant="acme"}' in text
    assert "hydragnn_tenant_cost_replica_seconds" in text
    assert "hydragnn_tenant_cost_idle_seconds" in text


# ---- quota feedback --------------------------------------------------------


def _feedback_ledger(monkeypatch, sink, clock, **env):
    monkeypatch.setenv("HYDRAGNN_TENANT_COST_QUOTAS", "1")
    monkeypatch.setenv("HYDRAGNN_TENANT_COST_WINDOW_S", "1.0")
    monkeypatch.setenv("HYDRAGNN_TENANT_COST_PATIENCE", "2")
    monkeypatch.setenv("HYDRAGNN_TENANT_COST_SHAVE", "0.5")
    monkeypatch.setenv("HYDRAGNN_TENANT_COST_FLOOR", "0.125")
    for k, v in env.items():
        monkeypatch.setenv(k, v)
    return CostLedger(emit=sink, clock=clock)


def _window(ledger, clock, tenants, loads):
    """One cost window: attribute `loads` (tenant -> seconds), advance
    past the window, tick the feedback."""
    for name, secs in loads.items():
        ledger.note_batch(name, 0, 1, secs)
    clock.advance(ledger.window_s + 0.01)
    return ledger.maybe_adjust_quotas(tenants)


def pytest_feedback_disabled_by_default(monkeypatch):
    monkeypatch.delenv("HYDRAGNN_TENANT_COST_QUOTAS", raising=False)
    clock = _Clock()
    ledger = CostLedger(clock=clock)
    tenants = _manager(acme=32, beta=32)
    assert _window(ledger, clock, tenants, {"acme": 1.0}) == []
    assert tenants.quota_for("acme") == 32


def pytest_feedback_shaves_after_patience(monkeypatch):
    sink = _Sink()
    clock = _Clock()
    ledger = _feedback_ledger(monkeypatch, sink, clock)
    tenants = _manager(acme=32, beta=32)
    # window 1: over tolerance but patience=2 -> no action yet
    assert _window(
        ledger, clock, tenants, {"acme": 0.9, "beta": 0.1}
    ) == []
    assert tenants.quota_for("acme") == 32
    # window 2: still over -> shave to half
    adj = _window(ledger, clock, tenants, {"acme": 0.9, "beta": 0.1})
    assert len(adj) == 1
    assert adj[0]["tenant"] == "acme"
    assert adj[0]["reason"] == "over_cost"
    assert tenants.quota_for("acme") == 16
    assert tenants.quota_override("acme") == 16
    # the quiet tenant is untouched
    assert tenants.quota_for("beta") == 32
    # emitted record carries exactly the schema's required fields
    event, fields = sink.events[0]
    assert event == "quota_adjusted"
    assert set(EVENT_FIELDS["quota_adjusted"]) <= set(fields)
    assert fields["old_quota"] == 32 and fields["new_quota"] == 16


def pytest_feedback_floor_prevents_starvation(monkeypatch):
    clock = _Clock()
    ledger = _feedback_ledger(monkeypatch, _Sink(), clock)
    tenants = _manager(acme=32, beta=32)
    for _ in range(20):  # keep flooding: repeated shaves bottom out
        _window(ledger, clock, tenants, {"acme": 1.0, "beta": 0.01})
    # floor = ceil(32 * 0.125) = 4, never lower, never zero
    assert tenants.quota_for("acme") == 4


def pytest_feedback_restores_after_sustained_under(monkeypatch):
    sink = _Sink()
    clock = _Clock()
    ledger = _feedback_ledger(monkeypatch, sink, clock)
    tenants = _manager(acme=32, beta=32)
    _window(ledger, clock, tenants, {"acme": 0.9, "beta": 0.1})
    _window(ledger, clock, tenants, {"acme": 0.9, "beta": 0.1})
    assert tenants.quota_for("acme") == 16
    # balanced load for `patience` windows -> override clears
    _window(ledger, clock, tenants, {"acme": 0.5, "beta": 0.5})
    adj = _window(ledger, clock, tenants, {"acme": 0.5, "beta": 0.5})
    assert any(a["reason"] == "restored" for a in adj)
    assert tenants.quota_override("acme") is None
    assert tenants.quota_for("acme") == 32


def pytest_feedback_no_tick_within_window(monkeypatch):
    clock = _Clock()
    ledger = _feedback_ledger(monkeypatch, _Sink(), clock)
    tenants = _manager(acme=32)
    ledger.note_batch("acme", 0, 1, 1.0)
    clock.advance(ledger.window_s / 2)  # window not yet elapsed
    assert ledger.maybe_adjust_quotas(tenants) == []


def pytest_quota_override_clamped_and_validated():
    tenants = _manager(acme=8)
    tenants.set_quota_override("acme", 100)  # above base: clamped at read
    assert tenants.quota_for("acme") == 8
    tenants.set_quota_override("acme", 2)
    assert tenants.quota_for("acme") == 2
    assert tenants.describe()["acme"]["quota"] == 2
    assert tenants.describe()["acme"]["quota_override"] == 2
    with pytest.raises(ValueError):
        tenants.set_quota_override("acme", 0)
    with pytest.raises(KeyError):
        tenants.set_quota_override("ghost", 4)
    tenants.set_quota_override("acme", None)
    assert tenants.quota_for("acme") == 8
