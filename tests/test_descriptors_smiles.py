"""Atomic descriptors + SMILES featurization.

Mirrors ``tests/test_atomicdescriptors.py`` in the reference plus structural
checks of the SMILES graph builder (``hydragnn/utils/smiles_utils.py``) on
molecules with known composition.
"""

import numpy as np

from hydragnn_tpu.utils.atomicdescriptors import atomicdescriptors
from hydragnn_tpu.utils.smiles import (
    generate_graphdata_from_smilestr,
    get_node_attribute_name,
)

TYPES = {"C": 0, "H": 1, "O": 2, "N": 3, "F": 4, "S": 5}


def _counts(data):
    z = data.x[:, len(TYPES)].astype(int)
    return {el: int((z == n).sum()) for el, n in
            [("H", 1), ("C", 6), ("N", 7), ("O", 8)]}


def pytest_atomicdescriptors(tmp_path):
    desc = atomicdescriptors(
        str(tmp_path / "emb.json"), element_types=["C", "H", "S"]
    )
    f = desc.get_atom_features("C")
    # 3 type one-hot + 10 scalar properties + 4 block one-hot
    assert f.shape == (17,)
    assert np.isfinite(f).all()
    assert desc.get_atom_features(16).shape == (17,)  # lookup by Z

    # cached file is reused verbatim when not overwritten
    desc2 = atomicdescriptors(str(tmp_path / "emb.json"), overwritten=False)
    assert np.allclose(desc2.get_atom_features("H"), desc.get_atom_features("H"))


def pytest_atomicdescriptors_onehot(tmp_path):
    desc = atomicdescriptors(
        str(tmp_path / "emb1h.json"), element_types=["C", "H", "S"], one_hot=True
    )
    f = desc.get_atom_features("S")
    assert set(np.unique(f)).issubset({0.0, 1.0})


def pytest_node_attribute_names():
    names, dims = get_node_attribute_name(TYPES)
    assert names[: len(TYPES)] == ["atomC", "atomH", "atomO", "atomN", "atomF",
                                   "atomS"]
    assert names[-1] == "Hprop"
    assert dims == [1] * (len(TYPES) + 6)


def pytest_smiles_methane():
    data = generate_graphdata_from_smilestr("C", [0.5], TYPES)
    assert data.num_nodes == 5  # C + 4 explicit H
    assert data.num_edges == 8  # 4 bonds, both directions
    c = _counts(data)
    assert c["C"] == 1 and c["H"] == 4
    off = len(TYPES)
    carbon = data.x[data.x[:, off] == 6][0]
    assert carbon[off + 4] == 1.0  # SP3
    assert carbon[off + 5] == 4.0  # bonded hydrogens


def pytest_smiles_ethene_bonds():
    data = generate_graphdata_from_smilestr("C=C", [1.0], TYPES)
    c = _counts(data)
    assert c["C"] == 2 and c["H"] == 4
    off = len(TYPES)
    carbons = data.x[data.x[:, off] == 6]
    assert (carbons[:, off + 3] == 1.0).all()  # SP2
    # one double bond -> exactly 2 directed edges one-hot at slot "double"
    assert int(data.edge_attr[:, 1].sum()) == 2


def pytest_smiles_benzene_aromatic():
    data = generate_graphdata_from_smilestr("c1ccccc1", [0.0], TYPES)
    c = _counts(data)
    assert c["C"] == 6 and c["H"] == 6
    off = len(TYPES)
    carbons = data.x[data.x[:, off] == 6]
    assert (carbons[:, off + 1] == 1.0).all()  # aromatic flag
    assert (carbons[:, off + 5] == 1.0).all()  # 1 H each
    assert int(data.edge_attr[:, 3].sum()) == 12  # 6 aromatic ring bonds


def pytest_smiles_pyrrole_bracket_h():
    data = generate_graphdata_from_smilestr("c1cc[nH]c1", [0.0], TYPES)
    c = _counts(data)
    assert c["C"] == 4 and c["N"] == 1 and c["H"] == 5


def pytest_smiles_branches_rings():
    # acetic acid: branch + double bond + hydroxyl
    data = generate_graphdata_from_smilestr("CC(=O)O", [0.0], TYPES)
    c = _counts(data)
    assert c["C"] == 2 and c["O"] == 2 and c["H"] == 4
    # biphenyl: the inter-ring default bond between aromatic atoms must be
    # SINGLE (not on an aromatic cycle)
    data = generate_graphdata_from_smilestr("c1ccc(c2ccccc2)cc1", [0.0], TYPES)
    assert int(data.edge_attr[:, 3].sum()) == 24  # 12 ring bonds
    assert _counts(data)["H"] == 10


def pytest_smiles_var_config_targets():
    var_config = {
        "type": ["graph"],
        "output_index": [0],
        "graph_feature_dims": [1],
        "input_node_feature_dims": [1] * (len(TYPES) + 6),
    }
    data = generate_graphdata_from_smilestr("CCO", [2.5], TYPES, var_config)
    assert len(data.targets) == 1
    assert np.allclose(data.targets[0], [2.5])
