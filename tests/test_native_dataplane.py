"""Native (C++) components: GraphPack shard store round-trip, DistStore
remote fetch over TCP, region-timer call-tree (reference analogs: ADIOS2
AdiosWriter/AdiosDataset, pyddstore DistDataset, gptl4py tracer —
SURVEY.md §2.4)."""

import json
import os
import tempfile
import time

import numpy as np

from hydragnn_tpu.data.dataobj import GraphData


def _mk(rng, n):
    d = GraphData()
    d.x = rng.random((n, 2)).astype(np.float32)
    d.pos = rng.random((n, 3)).astype(np.float32)
    e = 2 * n
    d.edge_index = rng.integers(0, n, (2, e)).astype(np.int64)
    d.edge_attr = rng.random((e, 1)).astype(np.float32)
    d.y = rng.random(4).astype(np.float32)
    d.supercell_size = np.eye(3, dtype=np.float32)
    d.targets = [
        rng.random(2).astype(np.float32),
        rng.random((n, 1)).astype(np.float32),
    ]
    d.target_types = ["graph", "node"]
    return d


def _assert_same(a, b):
    assert np.allclose(a.x, b.x)
    assert np.allclose(a.pos, b.pos)
    assert np.array_equal(a.edge_index, b.edge_index)
    assert np.allclose(a.edge_attr, b.edge_attr)
    assert np.allclose(a.y, b.y)
    assert b.target_types == ["graph", "node"]
    assert np.allclose(a.targets[0], b.targets[0])
    assert np.allclose(a.targets[1], b.targets[1])


def pytest_graphpack_roundtrip():
    from hydragnn_tpu.data.shard_store import ShardDataset, ShardWriter

    rng = np.random.default_rng(0)
    samples = [_mk(rng, int(rng.integers(3, 9))) for _ in range(40)]
    with tempfile.TemporaryDirectory() as tmp:
        label = os.path.join(tmp, "trainset")
        w0 = ShardWriter(label, rank=0)
        w0.add(samples[:25])
        w0.add_global("pna_deg", np.array([1, 2, 3]))
        w0.save()
        w1 = ShardWriter(label, rank=1)
        w1.add(samples[25:])
        w1.save()

        for preload in (False, True):
            ds = ShardDataset(label, preload=preload)
            assert len(ds) == 40
            assert ds.meta["pna_deg"] == [1, 2, 3]
            for i in (0, 13, 24, 25, 39):
                _assert_same(samples[i], ds.get(i))
            assert np.allclose(
                ds.get(7).supercell_size, samples[7].supercell_size
            )
            ds.close()


def pytest_graphpack_bulk_view():
    from hydragnn_tpu.data.shard_store import ShardDataset, ShardWriter

    rng = np.random.default_rng(1)
    samples = [_mk(rng, 5) for _ in range(8)]
    with tempfile.TemporaryDirectory() as tmp:
        label = os.path.join(tmp, "set")
        w = ShardWriter(label, rank=0)
        w.add(samples)
        w.save()
        ds = ShardDataset(label)
        xs = ds.readers[0].read_all("x")
        assert xs.shape == (40, 2)
        assert not xs.flags.writeable  # zero-copy mmap view
        assert np.allclose(xs[:5], samples[0].x)
        counts = ds.readers[0].counts("x")
        assert counts.tolist() == [5] * 8
        ds.close()


def pytest_graphpack_empty_shard():
    """A rank with zero local samples still writes a valid (empty) shard."""
    from hydragnn_tpu.data.shard_store import ShardDataset, ShardWriter

    rng = np.random.default_rng(3)
    with tempfile.TemporaryDirectory() as tmp:
        label = os.path.join(tmp, "s")
        w1 = ShardWriter(label, rank=1)
        w1.add([])
        w1.save()
        w0 = ShardWriter(label, rank=0)
        w0.add([_mk(rng, 4)])
        w0.save()
        ds = ShardDataset(label)
        assert len(ds) == 1
        assert ds.get(0).num_nodes == 4
        ds.close()


def pytest_graphpack_subset_view():
    """Subset views expose only the chosen global indices through len/[i]
    (AdiosDataset subset parity, ``utils/adiosdataset.py:610-636``)."""
    from hydragnn_tpu.data.shard_store import ShardDataset, ShardWriter

    rng = np.random.default_rng(7)
    with tempfile.TemporaryDirectory() as tmp:
        label = os.path.join(tmp, "s")
        w = ShardWriter(label, rank=0)
        samples = [_mk(rng, 3 + i) for i in range(6)]
        w.add(samples)
        w.save()
        ds = ShardDataset(label, subset=[4, 1, 5])
        assert len(ds) == 3
        assert ds.num_samples_total() == 6
        assert ds[0].num_nodes == samples[4].x.shape[0]
        assert ds[1].num_nodes == samples[1].x.shape[0]
        # get() still addresses the GLOBAL index space
        assert ds.get(0).num_nodes == samples[0].x.shape[0]
        # iteration follows the subset view
        assert [d.num_nodes for d in ds] == [
            samples[i].x.shape[0] for i in (4, 1, 5)
        ]
        ds.close()


def pytest_diststore_remote_fetch():
    from hydragnn_tpu.data.distdataset import DistDataset

    rng = np.random.default_rng(2)
    all_samples = [_mk(rng, int(rng.integers(3, 9))) for _ in range(30)]
    # single-process twin-store test: the host-side allgather of per-rank
    # maxima can't run (one jax process), so pass the global maxima directly
    mc = {"nodes": 8, "edges": 16}
    ds0 = DistDataset(
        all_samples[:20], rank=0, world=2, samples_per_rank=[20, 10],
        base_port=23810, max_counts=mc,
    )
    ds1 = DistDataset(
        all_samples[20:], rank=1, world=2, samples_per_rank=[20, 10],
        base_port=23810, max_counts=mc,
    )
    try:
        assert len(ds0) == 30 and len(ds1) == 30
        ds0.epoch_begin()
        ds1.epoch_begin()
        for idx in (0, 19, 20, 29):  # local + remote both directions
            _assert_same(all_samples[idx], ds0.get(idx))
        _assert_same(all_samples[5], ds1.get(5))
        ds0.epoch_end()
        ds1.epoch_end()
        # window reopens
        ds0.epoch_begin()
        ds1.epoch_begin()
        _assert_same(all_samples[25], ds0.get(25))
        ds0.epoch_end()
        ds1.epoch_end()
    finally:
        ds0.close()
        ds1.close()


def pytest_region_timer_calltree():
    from hydragnn_tpu.native.regiontimer import NativeRegionTimer

    t = NativeRegionTimer()
    for _ in range(2):
        t.start("train")
        t.start("forward")
        time.sleep(0.002)
        t.stop("forward")
        t.stop("train")
    assert t.count("train") == 2
    assert t.count("train/forward") == 2
    assert t.total("train") >= t.total("train/forward") > 0
    with tempfile.TemporaryDirectory() as tmp:
        t.pr_file(os.path.join(tmp, "trace.0"))
        text = open(os.path.join(tmp, "trace.0")).read()
        assert "forward" in text and "train" in text
        t.chrome_trace(os.path.join(tmp, "trace.json"))
        events = json.load(open(os.path.join(tmp, "trace.json")))
        assert len(events) == 4
        assert all(e["ph"] == "X" for e in events)


def pytest_tracer_facade_native_backend():
    from hydragnn_tpu.utils import tracer as tr

    tr.initialize(("native",))
    tr.start("epoch")
    tr.stop("epoch")
    with tempfile.TemporaryDirectory() as tmp:
        tr.save(os.path.join(tmp, "t"))
        assert os.path.exists(os.path.join(tmp, "t.0"))
        assert os.path.exists(os.path.join(tmp, "t.0.trace.json"))
    tr.reset()
