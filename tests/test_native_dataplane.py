"""Native (C++) components: GraphPack shard store round-trip, DistStore
remote fetch over TCP, region-timer call-tree (reference analogs: ADIOS2
AdiosWriter/AdiosDataset, pyddstore DistDataset, gptl4py tracer —
SURVEY.md §2.4)."""

import json
import os
import tempfile
import time

import numpy as np
import pytest

from hydragnn_tpu.data.dataobj import GraphData


def _mk(rng, n):
    d = GraphData()
    d.x = rng.random((n, 2)).astype(np.float32)
    d.pos = rng.random((n, 3)).astype(np.float32)
    e = 2 * n
    d.edge_index = rng.integers(0, n, (2, e)).astype(np.int64)
    d.edge_attr = rng.random((e, 1)).astype(np.float32)
    d.y = rng.random(4).astype(np.float32)
    d.supercell_size = np.eye(3, dtype=np.float32)
    d.targets = [
        rng.random(2).astype(np.float32),
        rng.random((n, 1)).astype(np.float32),
    ]
    d.target_types = ["graph", "node"]
    return d


def _assert_same(a, b):
    assert np.allclose(a.x, b.x)
    assert np.allclose(a.pos, b.pos)
    assert np.array_equal(a.edge_index, b.edge_index)
    assert np.allclose(a.edge_attr, b.edge_attr)
    assert np.allclose(a.y, b.y)
    assert b.target_types == ["graph", "node"]
    assert np.allclose(a.targets[0], b.targets[0])
    assert np.allclose(a.targets[1], b.targets[1])


def pytest_graphpack_roundtrip():
    from hydragnn_tpu.data.shard_store import ShardDataset, ShardWriter

    rng = np.random.default_rng(0)
    samples = [_mk(rng, int(rng.integers(3, 9))) for _ in range(40)]
    with tempfile.TemporaryDirectory() as tmp:
        label = os.path.join(tmp, "trainset")
        w0 = ShardWriter(label, rank=0)
        w0.add(samples[:25])
        w0.add_global("pna_deg", np.array([1, 2, 3]))
        w0.save()
        w1 = ShardWriter(label, rank=1)
        w1.add(samples[25:])
        w1.save()

        for preload in (False, True):
            ds = ShardDataset(label, preload=preload)
            assert len(ds) == 40
            assert ds.meta["pna_deg"] == [1, 2, 3]
            for i in (0, 13, 24, 25, 39):
                _assert_same(samples[i], ds.get(i))
            assert np.allclose(
                ds.get(7).supercell_size, samples[7].supercell_size
            )
            ds.close()


def pytest_graphpack_bulk_view():
    from hydragnn_tpu.data.shard_store import ShardDataset, ShardWriter

    rng = np.random.default_rng(1)
    samples = [_mk(rng, 5) for _ in range(8)]
    with tempfile.TemporaryDirectory() as tmp:
        label = os.path.join(tmp, "set")
        w = ShardWriter(label, rank=0)
        w.add(samples)
        w.save()
        ds = ShardDataset(label)
        xs = ds.readers[0].read_all("x")
        assert xs.shape == (40, 2)
        assert not xs.flags.writeable  # zero-copy mmap view
        assert np.allclose(xs[:5], samples[0].x)
        counts = ds.readers[0].counts("x")
        assert counts.tolist() == [5] * 8
        ds.close()


def pytest_graphpack_empty_shard():
    """A rank with zero local samples still writes a valid (empty) shard."""
    from hydragnn_tpu.data.shard_store import ShardDataset, ShardWriter

    rng = np.random.default_rng(3)
    with tempfile.TemporaryDirectory() as tmp:
        label = os.path.join(tmp, "s")
        w1 = ShardWriter(label, rank=1)
        w1.add([])
        w1.save()
        w0 = ShardWriter(label, rank=0)
        w0.add([_mk(rng, 4)])
        w0.save()
        ds = ShardDataset(label)
        assert len(ds) == 1
        assert ds.get(0).num_nodes == 4
        ds.close()


def pytest_graphpack_subset_view():
    """Subset views expose only the chosen global indices through len/[i]
    (AdiosDataset subset parity, ``utils/adiosdataset.py:610-636``)."""
    from hydragnn_tpu.data.shard_store import ShardDataset, ShardWriter

    rng = np.random.default_rng(7)
    with tempfile.TemporaryDirectory() as tmp:
        label = os.path.join(tmp, "s")
        w = ShardWriter(label, rank=0)
        samples = [_mk(rng, 3 + i) for i in range(6)]
        w.add(samples)
        w.save()
        ds = ShardDataset(label, subset=[4, 1, 5])
        assert len(ds) == 3
        assert ds.num_samples_total() == 6
        assert ds[0].num_nodes == samples[4].x.shape[0]
        assert ds[1].num_nodes == samples[1].x.shape[0]
        # get() still addresses the GLOBAL index space
        assert ds.get(0).num_nodes == samples[0].x.shape[0]
        # iteration follows the subset view
        assert [d.num_nodes for d in ds] == [
            samples[i].x.shape[0] for i in (4, 1, 5)
        ]
        ds.close()


def pytest_diststore_remote_fetch():
    from hydragnn_tpu.data.distdataset import DistDataset

    rng = np.random.default_rng(2)
    all_samples = [_mk(rng, int(rng.integers(3, 9))) for _ in range(30)]
    # single-process twin-store test: the host-side allgather of per-rank
    # maxima can't run (one jax process), so pass the global maxima directly
    mc = {"nodes": 8, "edges": 16}
    ds0 = DistDataset(
        all_samples[:20], rank=0, world=2, samples_per_rank=[20, 10],
        base_port=23810, max_counts=mc,
    )
    ds1 = DistDataset(
        all_samples[20:], rank=1, world=2, samples_per_rank=[20, 10],
        base_port=23810, max_counts=mc,
    )
    try:
        assert len(ds0) == 30 and len(ds1) == 30
        ds0.epoch_begin()
        ds1.epoch_begin()
        for idx in (0, 19, 20, 29):  # local + remote both directions
            _assert_same(all_samples[idx], ds0.get(idx))
        _assert_same(all_samples[5], ds1.get(5))
        ds0.epoch_end()
        ds1.epoch_end()
        # window reopens
        ds0.epoch_begin()
        ds1.epoch_begin()
        _assert_same(all_samples[25], ds0.get(25))
        ds0.epoch_end()
        ds1.epoch_end()
    finally:
        ds0.close()
        ds1.close()


def pytest_diststore_subgroup_replication():
    """ddstore_width analog: with subgroup_width the world splits into
    blocks that each hold a FULL replica, and every get() resolves inside
    the caller's block. Out-of-block ranks get dead addresses here, so any
    cross-subgroup fetch would error — the sweep passing proves locality."""
    from hydragnn_tpu.data.distdataset import (
        DistDataset,
        subgroup_local_indices,
        subgroup_of,
    )

    # split arithmetic incl. the smaller trailing group
    assert subgroup_of(0, 4, 2) == (0, 0, 2, 0)
    assert subgroup_of(3, 4, 2) == (1, 1, 2, 2)
    assert subgroup_of(3, 4, 3) == (1, 0, 1, 3)  # trailing group of one
    assert subgroup_of(2, 4, None) == (0, 2, 4, 0)
    assert list(subgroup_local_indices(5, 3, 4, 3)) == [0, 1, 2, 3, 4]
    cover = [list(subgroup_local_indices(7, r, 4, 2)) for r in range(4)]
    assert cover[0] + cover[1] == list(range(7))  # group 0 = full replica
    assert cover[2] + cover[3] == list(range(7))  # group 1 = full replica

    rng = np.random.default_rng(7)
    all_samples = [_mk(rng, int(rng.integers(3, 9))) for _ in range(30)]
    mc = {"nodes": 8, "edges": 16}
    dead = "127.0.0.1:9"  # nothing listens there — contact would fail

    def shard(rank):
        return [all_samples[i] for i in subgroup_local_indices(30, rank, 4, 2)]

    def spr(rank):
        return [
            len(subgroup_local_indices(30, r, 4, 2))
            for r in range(*{0: (0, 2), 1: (2, 4)}[rank // 2])
        ]

    # group 0 (ranks 0,1) with ranks 2,3 unreachable
    addrs0 = ["127.0.0.1:23870", "127.0.0.1:23871", dead, dead]
    ds0 = DistDataset(shard(0), rank=0, world=4, addresses=addrs0,
                      samples_per_rank=spr(0), max_counts=mc,
                      subgroup_width=2)
    ds1 = DistDataset(shard(1), rank=1, world=4, addresses=addrs0,
                      samples_per_rank=spr(1), max_counts=mc,
                      subgroup_width=2)
    # group 1 (ranks 2,3) with ranks 0,1 unreachable — independent replica
    addrs1 = [dead, dead, "127.0.0.1:23872", "127.0.0.1:23873"]
    ds2 = DistDataset(shard(2), rank=2, world=4, addresses=addrs1,
                      samples_per_rank=spr(2), max_counts=mc,
                      subgroup_width=2)
    ds3 = DistDataset(shard(3), rank=3, world=4, addresses=addrs1,
                      samples_per_rank=spr(3), max_counts=mc,
                      subgroup_width=2)
    try:
        assert ds0.store.group_index == 0 and ds3.store.group_index == 1
        assert ds0.store.world == 2  # the subgroup IS the store's world
        for ds in (ds0, ds1, ds2, ds3):
            assert len(ds) == 30  # global index space in every block
            ds.epoch_begin()
        for idx in range(30):  # full sweep: local + intra-block remote
            _assert_same(all_samples[idx], ds0.get(idx))
            _assert_same(all_samples[idx], ds3.get(idx))
        _assert_same(all_samples[0], ds1.get(0))
        _assert_same(all_samples[29], ds2.get(29))
        for ds in (ds0, ds1, ds2, ds3):
            ds.epoch_end()
    finally:
        for ds in (ds0, ds1, ds2, ds3):
            ds.close()


def _subgroup_worker(rank, base_port, results, barrier):
    """One REAL process of a 4-rank world with subgroup_width=2: builds its
    subgroup shard, serves it, sweeps the full global index space, and
    reports per-index node counts for cross-process verification. Ranks
    outside the block get dead addresses, so any cross-subgroup fetch
    would error instead of silently succeeding."""
    try:
        import numpy as _np

        from hydragnn_tpu.data.distdataset import (
            DistDataset,
            subgroup_local_indices,
        )

        rng = _np.random.default_rng(11)
        all_samples = [_mk(rng, int(rng.integers(3, 9))) for _ in range(20)]
        dead = "127.0.0.1:9"
        group = rank // 2
        addrs = [
            f"127.0.0.1:{base_port + r}" if r // 2 == group else dead
            for r in range(4)
        ]
        mine = subgroup_local_indices(20, rank, 4, 2)
        ds = DistDataset(
            [all_samples[i] for i in mine],
            rank=rank,
            world=4,
            addresses=addrs,
            samples_per_rank=[
                len(subgroup_local_indices(20, group * 2 + p, 4, 2))
                for p in range(2)
            ],
            max_counts={"nodes": 8, "edges": 16},
            subgroup_width=2,
        )
        try:
            ds.epoch_begin()
            counts = [ds.get(i).num_nodes for i in range(20)]
            # every fetch resolved inside the subgroup; verify content
            expected = [s.num_nodes for s in all_samples]
            assert counts == expected, (rank, counts, expected)
            # barrier: no rank tears its server down while a subgroup
            # peer may still be mid-sweep (a sleep would be skew-flaky)
            barrier.wait(timeout=90)
            ds.epoch_end()
        finally:
            ds.close()
        # "ok" only after teardown so epoch_end/close failures surface
        results.put((rank, "ok"))
    except Exception as e:  # surface on the parent
        results.put((rank, f"{type(e).__name__}: {e}"))


@pytest.mark.skipif(
    int(os.getenv("HYDRAGNN_FAST_TEST", "0")) == 1,
    reason="spawns 4 real processes: default tier",
)
def pytest_diststore_subgroup_multiprocess():
    """4 REAL processes, subgroup_width=2: both blocks independently serve
    a full replica and every get() resolves within the caller's block
    (out-of-block ranks are unreachable by construction)."""
    import multiprocessing as mp

    ctx = mp.get_context("spawn")
    results = ctx.Queue()
    barrier = ctx.Barrier(4)
    base_port = 23960
    procs = [
        ctx.Process(
            target=_subgroup_worker, args=(r, base_port, results, barrier)
        )
        for r in range(4)
    ]
    for p in procs:
        p.start()
    outcomes = {}
    try:
        for _ in range(4):
            rank, status = results.get(timeout=120)
            outcomes[rank] = status
    finally:
        for p in procs:
            p.join(timeout=30)
            if p.is_alive():
                p.terminate()
    assert outcomes == {r: "ok" for r in range(4)}, outcomes


def pytest_region_timer_calltree():
    from hydragnn_tpu.native.regiontimer import NativeRegionTimer

    t = NativeRegionTimer()
    for _ in range(2):
        t.start("train")
        t.start("forward")
        time.sleep(0.002)
        t.stop("forward")
        t.stop("train")
    assert t.count("train") == 2
    assert t.count("train/forward") == 2
    assert t.total("train") >= t.total("train/forward") > 0
    with tempfile.TemporaryDirectory() as tmp:
        t.pr_file(os.path.join(tmp, "trace.0"))
        text = open(os.path.join(tmp, "trace.0")).read()
        assert "forward" in text and "train" in text
        t.chrome_trace(os.path.join(tmp, "trace.json"))
        events = json.load(open(os.path.join(tmp, "trace.json")))
        assert len(events) == 4
        assert all(e["ph"] == "X" for e in events)


def pytest_tracer_facade_native_backend():
    from hydragnn_tpu.utils import tracer as tr

    tr.initialize(("native",))
    tr.start("epoch")
    tr.stop("epoch")
    with tempfile.TemporaryDirectory() as tmp:
        tr.save(os.path.join(tmp, "t"))
        assert os.path.exists(os.path.join(tmp, "t.0"))
        assert os.path.exists(os.path.join(tmp, "t.0.trace.json"))
    tr.reset()
