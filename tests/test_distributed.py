"""Direct units for ``parallel/distributed.py`` (212 LoC that were only
exercised incidentally): scheduler env detection and process-count/rank
derivation, SLURM nodelist/timeleft parsing, the nearly-even local-shard
split, host collectives' single-process identities, and the
``make_array_from_process_local_data`` layout round-trip on the forced
8-device mesh."""

import os

import numpy as np
import pytest

import jax

from hydragnn_tpu.parallel import distributed as dist


# ---- process-count / rank derivation --------------------------------------


def pytest_setup_distributed_single_process(monkeypatch):
    """No cluster env -> (1, 0) with no jax.distributed.initialize."""
    for var in (
        "HYDRAGNN_TPU_COORDINATOR", "HYDRAGNN_TPU_NUM_PROCESSES",
        "HYDRAGNN_TPU_PROCESS_ID", "SLURM_NTASKS", "OMPI_COMM_WORLD_SIZE",
    ):
        monkeypatch.delenv(var, raising=False)
    called = {}
    monkeypatch.setattr(
        jax.distributed, "initialize",
        lambda **kw: called.setdefault("kw", kw),
    )
    world, rank = dist.setup_distributed()
    assert (world, rank) == (1, 0)
    assert "kw" not in called


def pytest_setup_distributed_slurm_derivation(monkeypatch):
    """SLURM env -> coordinator from the nodelist head + configured port,
    process count/id from SLURM_NTASKS/SLURM_PROCID."""
    monkeypatch.setattr(dist, "_initialized", False)
    for var in ("HYDRAGNN_TPU_COORDINATOR", "OMPI_COMM_WORLD_SIZE"):
        monkeypatch.delenv(var, raising=False)
    monkeypatch.setenv("SLURM_NTASKS", "4")
    monkeypatch.setenv("SLURM_PROCID", "2")
    monkeypatch.setenv("SLURM_NODELIST", "frontier[00007-00010]")
    monkeypatch.setenv("HYDRAGNN_TPU_PORT", "23456")
    called = {}
    monkeypatch.setattr(
        jax.distributed, "initialize", lambda **kw: called.update(kw)
    )
    dist.setup_distributed()
    assert called["coordinator_address"] == "frontier00007:23456"
    assert called["num_processes"] == 4
    assert called["process_id"] == 2
    monkeypatch.setattr(dist, "_initialized", False)


def pytest_setup_distributed_openmpi_derivation(monkeypatch):
    monkeypatch.setattr(dist, "_initialized", False)
    for var in ("HYDRAGNN_TPU_COORDINATOR", "SLURM_NTASKS"):
        monkeypatch.delenv(var, raising=False)
    monkeypatch.setenv("OMPI_COMM_WORLD_SIZE", "2")
    monkeypatch.setenv("OMPI_COMM_WORLD_RANK", "1")
    called = {}
    monkeypatch.setattr(
        jax.distributed, "initialize", lambda **kw: called.update(kw)
    )
    dist.setup_distributed()
    assert called["num_processes"] == 2
    assert called["process_id"] == 1
    monkeypatch.setattr(dist, "_initialized", False)


def pytest_get_comm_size_and_rank_single():
    assert dist.get_comm_size_and_rank() == (1, 0)


# ---- local-shard math ------------------------------------------------------


def pytest_nsplit_nearly_even():
    chunks = [list(c) for c in dist.nsplit(list(range(10)), 3)]
    assert chunks == [[0, 1, 2, 3], [4, 5, 6], [7, 8, 9]]
    # every element exactly once, sizes differ by at most one
    sizes = [len(c) for c in chunks]
    assert max(sizes) - min(sizes) <= 1
    assert sorted(sum(chunks, [])) == list(range(10))
    # more shards than items: trailing shards are empty, nothing is lost
    chunks = [list(c) for c in dist.nsplit(list(range(2)), 4)]
    assert sorted(sum(chunks, [])) == [0, 1]
    assert len(chunks) == 4


def pytest_parse_slurm_nodelist_forms():
    assert dist.parse_slurm_nodelist("node1,node2") == ["node1", "node2"]
    assert dist.parse_slurm_nodelist("frontier[00001-00003,00007]") == [
        "frontier00001", "frontier00002", "frontier00003", "frontier00007",
    ]


def pytest_parse_slurm_timeleft_forms():
    assert dist._parse_slurm_timeleft("1-02:03:04") == (
        ((1 * 24 + 2) * 60 + 3) * 60 + 4
    )
    assert dist._parse_slurm_timeleft("02:03:04") == (2 * 60 + 3) * 60 + 4
    assert dist._parse_slurm_timeleft("03:04") == 3 * 60 + 4
    assert dist._parse_slurm_timeleft("59") == 59
    assert dist._parse_slurm_timeleft("INVALID") is None
    assert dist._parse_slurm_timeleft("") is None


def pytest_check_remaining_non_slurm(monkeypatch):
    monkeypatch.delenv("SLURM_JOB_ID", raising=False)
    assert dist.check_remaining(1e9) is True


# ---- host collectives (single-process identities) --------------------------


def pytest_host_allreduce_single_process_identity():
    """On one process every op is the identity (the multi-process branch
    needs real peers; test_multiprocess covers it)."""
    arr = np.arange(6, dtype=np.float64).reshape(2, 3)
    for op in ("sum", "max", "min"):
        np.testing.assert_array_equal(dist.host_allreduce(arr, op), arr)


def pytest_host_allgather_int_single():
    assert dist.host_allgather_int(7) == [7]


# ---- make_array_from_process_local_data layout round-trip ------------------


def pytest_process_local_data_round_trip_1d():
    """The multi-host batch-assembly primitive, on the forced 8-device
    mesh: a P('data')-sharded assembly reads back bitwise, and each
    device holds exactly its contiguous row block."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from hydragnn_tpu.parallel.mesh import make_mesh

    mesh = make_mesh()
    sharding = NamedSharding(mesh, P("data"))
    host = np.arange(16 * 3, dtype=np.float32).reshape(16, 3)
    arr = jax.make_array_from_process_local_data(sharding, host)
    np.testing.assert_array_equal(np.asarray(arr), host)
    rows = 16 // mesh.shape["data"]
    for shard in arr.addressable_shards:
        lo = shard.index[0].start or 0
        np.testing.assert_array_equal(
            np.asarray(shard.data), host[lo : lo + rows]
        )


def pytest_process_local_data_round_trip_2d():
    """Same primitive on the 2-D mesh: P('data') shards rows over the
    data axis only — every model-group replica of a row block is
    identical (the layout put_batch relies on)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from hydragnn_tpu.parallel.mesh import make_mesh2d

    mesh = make_mesh2d(4, 2)
    sharding = NamedSharding(mesh, P("data"))
    host = np.arange(8 * 2, dtype=np.float32).reshape(8, 2)
    arr = jax.make_array_from_process_local_data(sharding, host)
    np.testing.assert_array_equal(np.asarray(arr), host)
    # 4-way row split, each block present on BOTH model devices
    seen = {}
    for shard in arr.addressable_shards:
        lo = shard.index[0].start or 0
        seen.setdefault(lo, []).append(np.asarray(shard.data))
    assert len(seen) == 4
    for lo, copies in seen.items():
        assert len(copies) == 2
        np.testing.assert_array_equal(copies[0], copies[1])
        np.testing.assert_array_equal(copies[0], host[lo : lo + 2])
