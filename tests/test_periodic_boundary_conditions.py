"""PBC neighbor-count checks against analytic expectations.

Mirrors the reference strategy (``tests/test_periodic_boundary_conditions.py:
25-123``): build small crystals with known coordination and assert exact edge
counts with and without periodic images.
"""

import numpy as np

from hydragnn_tpu.data.radius_graph import radius_graph, radius_graph_pbc


def _bcc_supercell(n):
    """n x n x n BCC supercell with lattice constant 1."""
    pts = []
    for x in range(n):
        for y in range(n):
            for z in range(n):
                pts.append([x, y, z])
                pts.append([x + 0.5, y + 0.5, z + 0.5])
    return np.asarray(pts, dtype=np.float64), float(n) * np.eye(3)


def pytest_bcc_coordination():
    # BCC first neighbor shell: 8 at distance sqrt(3)/2 ~ 0.866. Use a 2x2x2
    # supercell so each neighbor is a distinct atom (a 1-cell would connect
    # the same pair through several images, which — like the reference's
    # duplicate-edge assert — is rejected).
    pos, cell = _bcc_supercell(2)
    edge_index, lengths = radius_graph_pbc(pos, cell, radius=0.9, max_neighbors=100)
    assert edge_index.shape[1] == 8 * pos.shape[0]
    assert np.allclose(lengths, np.sqrt(3) / 2, atol=1e-6)
    # without PBC the corner atom at the origin keeps only its in-cell shell
    ei = radius_graph(pos, radius=0.9, max_neighbors=100)
    assert ei.shape[1] < 8 * pos.shape[0]


def pytest_bcc_second_shell():
    # radius 1.05 adds the 6 second-shell neighbors at distance 1.0
    # (3x3x3 supercell keeps +x / -x neighbors distinct atoms)
    pos, cell = _bcc_supercell(3)
    edge_index, lengths = radius_graph_pbc(pos, cell, radius=1.05, max_neighbors=100)
    per_atom = edge_index.shape[1] / pos.shape[0]
    assert per_atom == 8 + 6
    n_first = int(np.sum(np.isclose(lengths, np.sqrt(3) / 2, atol=1e-6)))
    n_second = int(np.sum(np.isclose(lengths, 1.0, atol=1e-6)))
    assert n_first == 8 * pos.shape[0]
    assert n_second == 6 * pos.shape[0]


def pytest_dimer_in_vacuum_cell():
    # a dimer in a large cell: PBC must not add any extra neighbors
    pos = np.array([[0.0, 0.0, 0.0], [0.74, 0.0, 0.0]])
    cell = 20.0 * np.eye(3)
    edge_index, lengths = radius_graph_pbc(pos, cell, radius=1.0, max_neighbors=10)
    assert edge_index.shape[1] == 2
    assert np.allclose(lengths, 0.74, atol=1e-6)


def pytest_pbc_edge_lengths_cross_boundary():
    # atom pair split across the boundary: minimum image distance applies
    pos = np.array([[0.05, 0.5, 0.5], [0.95, 0.5, 0.5]])
    cell = np.eye(3)
    edge_index, lengths = radius_graph_pbc(pos, cell, radius=0.2, max_neighbors=10)
    assert edge_index.shape[1] == 2
    assert np.allclose(lengths, 0.1, atol=1e-6)
