"""2-D ("data", "model") mesh: trajectory parity + explicit shardings.

The tentpole contract (docs/parallelism.md): on the forced 8-device CPU
mesh, the 2-D loss trajectory matches the single-device run to float32
tolerance for EVERY shape in {8x1, 4x2, 2x4, 1x8}; the step programs
declare explicit in/out shardings (params actually sharded over
``model``, donation intact); ZeRO composes (data overlay on moments);
and graph-partition mode runs on the ``model`` axis of the same mesh.
"""

import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from hydragnn_tpu.graph.batch import collate_graphs, pad_sizes_for
from hydragnn_tpu.models.create import create_model_config, init_model_params
from hydragnn_tpu.parallel.mesh import make_mesh2d
from hydragnn_tpu.train.trainer import Trainer

MESH_SHAPES = [(8, 1), (4, 2), (2, 4), (1, 8)]


class _S:
    pass


def _samples(k, seed):
    r = np.random.default_rng(seed)
    out = []
    for _ in range(k):
        n = 12
        s = _S()
        s.x = r.random((n, 3)).astype(np.float32)
        s.pos = r.random((n, 3)).astype(np.float32)
        src = np.repeat(np.arange(n), 2)
        dst = (src + r.integers(1, n, src.shape[0])) % n
        s.edge_index = np.stack(
            [np.concatenate([src, dst]), np.concatenate([dst, src])]
        ).astype(np.int64)
        s.edge_attr = None
        s.targets = [np.array([s.x.sum()], np.float32),
                     s.x[:, :1].astype(np.float32)]
        out.append(s)
    return out


def _batches(n_batches=3):
    n_pad, e_pad, g_pad = pad_sizes_for(12, 48, 8, graph_multiple=8)
    return [
        collate_graphs(
            _samples(8, seed=i), n_pad, e_pad, g_pad,
            head_types=("graph", "node"), head_dims=(1, 1),
        )
        for i in range(n_batches)
    ]


def _arch(hidden=16):
    return {
        "model_type": "PNA",
        "input_dim": 3,
        "hidden_dim": hidden,
        "output_dim": [1, 1],
        "output_type": ["graph", "node"],
        "output_heads": {
            "graph": {"num_sharedlayers": 1, "dim_sharedlayers": 8,
                      "num_headlayers": 1, "dim_headlayers": [8]},
            "node": {"num_headlayers": 1, "dim_headlayers": [8],
                     "type": "mlp"},
        },
        "task_weights": [1.0, 1.0],
        "num_conv_layers": 2,
        "max_neighbours": 10,
        "pna_deg": [0, 10, 20, 10, 5, 2, 1, 1, 1, 1],
    }


def _train_losses(mesh, batches, nsteps=6, training=None):
    model = create_model_config(_arch())
    trainer = Trainer(
        model,
        dict(training or {"Optimizer": {"type": "AdamW",
                                        "learning_rate": 1e-3}}),
        mesh=mesh,
    )
    state = trainer.init_state(batches[0], seed=0)
    rng = jax.random.PRNGKey(0)
    losses = []
    for i in range(nsteps):
        rng, sub = jax.random.split(rng)
        state, m = trainer._train_step(
            state, trainer.put_batch(batches[i % len(batches)]), sub
        )
        losses.append(float(np.asarray(m["loss"])))
    return losses, state, trainer


@pytest.mark.slow
def pytest_mesh2d_trajectory_parity_all_shapes():
    """Every {8x1, 4x2, 2x4, 1x8} trajectory == the single-device run to
    f32 tolerance — sharding is placement, not arithmetic. slow-marked
    (5 trainer compiles); the CI mesh smoke (tests/_mesh_smoke.py) runs
    the same matrix as a dedicated gate, and tier-1 keeps the 4x2 fit
    parity + partitioned parity below."""
    batches = _batches()
    ref, _, _ = _train_losses(None, batches)
    for d, m in MESH_SHAPES:
        got, state, _ = _train_losses(make_mesh2d(d, m), batches)
        np.testing.assert_allclose(
            got, ref, rtol=2e-4, atol=2e-5,
            err_msg=f"mesh {d}x{m} diverged from single-device",
        )
        sharded = [
            leaf
            for leaf in jax.tree_util.tree_leaves(state.params)
            if any(a is not None for a in tuple(leaf.sharding.spec))
        ]
        if m > 1:
            # params are REALLY split over model (hidden 16 divides all m)
            assert sharded, f"mesh {d}x{m}: no param sharded over model"
        else:
            assert not sharded


def pytest_mesh2d_explicit_shardings_and_donation():
    """The compiled step declares the rule-engine state sharding on its
    outputs, and donation still holds (the donated input's buffers are
    consumed)."""
    batches = _batches(1)
    _, state, trainer = _train_losses(make_mesh2d(4, 2), batches, nsteps=1)
    prev = state
    rng = jax.random.PRNGKey(7)
    new_state, _ = trainer._train_step(
        prev, trainer.put_batch(batches[0]), rng
    )
    # out shardings match the rule engine's placement
    want = jax.tree_util.tree_map(
        lambda s: tuple(s.spec), trainer._state_shardings.params
    )
    got = jax.tree_util.tree_map(
        lambda l: tuple(l.sharding.spec), new_state.params
    )
    assert want == got
    assert any(
        ("model",) == spec[-1:] or "model" in spec
        for spec in jax.tree_util.tree_leaves(
            got, is_leaf=lambda x: isinstance(x, tuple)
        )
    )
    # donation: the input state's buffers were consumed by the step
    assert all(
        leaf.is_deleted()
        for leaf in jax.tree_util.tree_leaves(prev.params)
    ), "donated state buffers survived — donation regressed"


def pytest_mesh2d_fit_staged_parity():
    """The whole-training fit path (staged data, on-device scheduler)
    produces the same loss series on 4x2 as unmeshed — the tier-1
    trajectory-parity anchor (the full {8x1, 4x2, 2x4, 1x8} matrix runs
    slow-marked above and in the CI mesh smoke)."""
    batches = _batches(2)
    training = {"Optimizer": {"type": "AdamW", "learning_rate": 1e-3}}

    def fit(mesh):
        model = create_model_config(_arch())
        trainer = Trainer(model, dict(training), mesh=mesh)
        state = trainer.init_state(batches[0], seed=0)
        staged = trainer.stage_batches(batches)
        state, _best, _sched, _rng, series = trainer.fit_staged(
            state, staged, 3, jax.random.PRNGKey(3), shuffle=False
        )
        return series["train_loss"]

    ref = fit(None)
    got = fit(make_mesh2d(4, 2))
    np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-5)


def pytest_mesh2d_zero_overlay_on_moments():
    """ZeRO stage 1 on the 2-D mesh: moment kernels carry
    P('data', 'model') — both axes at once."""
    batches = _batches(1)
    model = create_model_config(_arch())
    trainer = Trainer(
        model,
        {"Optimizer": {"type": "AdamW", "learning_rate": 1e-3,
                       "zero_stage": 1}},
        mesh=make_mesh2d(4, 2),
    )
    state = trainer.init_state(batches[0], seed=0)
    specs = {
        tuple(leaf.sharding.spec)
        for leaf in jax.tree_util.tree_leaves(state.opt_state)
        if hasattr(leaf, "sharding")
    }
    assert ("data", "model") in specs, specs
    state, metrics = trainer._train_step(
        state, trainer.put_batch(batches[0]), jax.random.PRNGKey(0)
    )
    assert np.isfinite(float(np.asarray(metrics["loss"])))


def pytest_mesh2d_partitioned_on_model_axis():
    """Graph-partition mode on the 2-D mesh: node/edge ownership on the
    ``model`` axis (data axis replicated), forward + train parity vs the
    unpartitioned single-device model."""
    import optax

    from test_graph_partition import (  # noqa: F401
        HEAD_DIMS,
        HEAD_TYPES,
        _arch as _part_arch,
        _giant_graph,
        _single_batch,
    )
    from hydragnn_tpu.parallel.graph_partition import (
        make_partitioned_apply,
        make_partitioned_train_step,
        partition_graph,
        put_partitioned_batch,
    )
    from hydragnn_tpu.train.trainer import TrainState

    sample = _giant_graph(seed=3)
    cfg = _part_arch("PNA")
    ref_model = create_model_config(dict(cfg))
    cfg_p = dict(cfg)
    cfg_p["partition_axis"] = "model"
    part_model = create_model_config(cfg_p)
    single = _single_batch(sample)
    variables = init_model_params(ref_model, single, seed=0)
    ref_out = ref_model.apply(variables, single, train=False)

    mesh = make_mesh2d(2, 4)
    batch, info = partition_graph(
        sample, 4, HEAD_TYPES, HEAD_DIMS, order="morton"
    )
    pbatch = put_partitioned_batch(batch, mesh, "model")
    part_out = make_partitioned_apply(part_model, mesh, "model")(
        variables, pbatch
    )
    g_ref = np.asarray(ref_out[0])[0]
    g_part = np.asarray(part_out[0]).reshape(4, 2, -1)
    for p in range(4):
        np.testing.assert_allclose(g_part[p, 0], g_ref, rtol=2e-4, atol=2e-5)
    n = sample.x.shape[0]
    node_part = info.gather_nodes(np.asarray(part_out[1]))
    np.testing.assert_allclose(
        node_part, np.asarray(ref_out[1])[:n], rtol=2e-4, atol=2e-5
    )

    tx = optax.sgd(1e-2)
    state = TrainState(
        params=variables["params"],
        batch_stats=variables.get("batch_stats", {}),
        opt_state=tx.init(variables["params"]),
        step=jnp.zeros((), jnp.int32),
    )
    step = make_partitioned_train_step(part_model, tx, mesh, "model")
    state, metrics = step(state, pbatch, jax.random.PRNGKey(5))
    assert np.isfinite(float(metrics["loss"]))


def pytest_mesh2d_announce_events(tmp_path):
    """announce_mesh lands schema-valid mesh_shape + param_sharding
    events, and — when the resumed meta recorded a different mesh — the
    re-derive world_resize with the NEW mesh shape."""
    from hydragnn_tpu.obs import runtime as obs_rt
    from hydragnn_tpu.obs.events import validate_events
    from hydragnn_tpu.parallel.mesh import announce_mesh

    class _FakeTrainer:
        def sharding_summary(self):
            return {
                "total_leaves": 4, "sharded": 2, "replicated": 2,
                "sharded_bytes": 1024, "replicated_bytes": 64,
                "axis_bytes": {"model": 1024},
            }

    telemetry = obs_rt.RunTelemetry("mesh-ev", str(tmp_path))
    obs_rt.activate(telemetry)
    try:
        mesh = make_mesh2d(3, 2)
        announce_mesh(
            mesh, trainer=_FakeTrainer(),
            resume_meta={"mesh": [4, 2]}, started_ts=None,
        )
    finally:
        obs_rt.deactivate()
    recs = validate_events(
        str(tmp_path / "events.jsonl"),
        require=["mesh_shape", "param_sharding", "world_resize"],
    )
    by_type = {}
    for r in recs:
        by_type.setdefault(r["event"], r)
    assert by_type["mesh_shape"]["shape"] == [3, 2]
    assert by_type["mesh_shape"]["axes"] == ["data", "model"]
    wr = by_type["world_resize"]
    assert wr["old_world"] == 8 and wr["new_world"] == 6
    assert wr["mesh_shape"] == [3, 2]
    assert wr["source"] == "re-derive"
