"""Real-dataset format parsers: QM9 sdf/csv + dsgdb9nsd xyz, OC20 extxyz,
MPtrj JSON.

Fixture data uses the first molecules of the actual QM9 distribution
(methane / ammonia / water: real published geometries and property rows) in
the exact gdb9 file layout, so the parser is tested against the real
format, not a convenient imitation. The datasets themselves cannot be
downloaded in this environment (no network egress); dropping the real
``gdb9.sdf`` next to these fixtures exercises the identical code path.
"""

import json
import os
import sys

import numpy as np
import pytest

from hydragnn_tpu.data.extxyz import (
    frame_to_graph,
    iter_extxyz,
    load_extxyz_dir,
    write_extxyz,
)
from hydragnn_tpu.data.mptrj import (
    iter_mptrj,
    load_mptrj,
    structure_from_dict,
    write_mptrj_json,
)
from hydragnn_tpu.data.qm9_raw import (
    HAR2EV,
    QM9RawDataset,
    parse_dsgdb9nsd_xyz,
    parse_sdf_v2000,
    read_gdb9_csv,
    read_uncharacterized,
)

# --- real QM9 rows (gdb_1 methane, gdb_2 ammonia, gdb_3 water) -------------

_GDB9_SDF = """gdb_1
  -OEChem-03231823243D

  5  4  0  0  0  0  0  0  0  0999 V2000
   -0.0127    1.0858    0.0080 C   0  0  0  0  0  0  0  0  0  0  0  0
    0.0022   -0.0060    0.0020 H   0  0  0  0  0  0  0  0  0  0  0  0
    1.0117    1.4638    0.0003 H   0  0  0  0  0  0  0  0  0  0  0  0
   -0.5408    1.4475   -0.8766 H   0  0  0  0  0  0  0  0  0  0  0  0
   -0.5238    1.4379    0.9064 H   0  0  0  0  0  0  0  0  0  0  0  0
  1  2  1  0  0  0  0
  1  3  1  0  0  0  0
  1  4  1  0  0  0  0
  1  5  1  0  0  0  0
M  END
$$$$
gdb_2
  -OEChem-03231823243D

  4  3  0  0  0  0  0  0  0  0999 V2000
   -0.0404    1.0241    0.0626 N   0  0  0  0  0  0  0  0  0  0  0  0
    0.0172    0.0125    0.0042 H   0  0  0  0  0  0  0  0  0  0  0  0
    0.9158    1.3587   -0.0086 H   0  0  0  0  0  0  0  0  0  0  0  0
   -0.5203    1.3435   -0.7755 H   0  0  0  0  0  0  0  0  0  0  0  0
  1  2  1  0  0  0  0
  1  3  1  0  0  0  0
  1  4  1  0  0  0  0
M  END
$$$$
gdb_3
  -OEChem-03231823243D

  3  2  0  0  0  0  0  0  0  0999 V2000
   -0.0343    0.9775    0.0076 O   0  0  0  0  0  0  0  0  0  0  0  0
    0.0647    0.0205    0.0015 H   0  0  0  0  0  0  0  0  0  0  0  0
    0.8717    1.3008    0.0006 H   0  0  0  0  0  0  0  0  0  0  0  0
  1  2  1  0  0  0  0
  1  3  1  0  0  0  0
M  END
$$$$
"""

_GDB9_CSV = """mol_id,A,B,C,mu,alpha,homo,lumo,gap,r2,zpve,u0,u298,h298,g298,cv,u0_atom,u298_atom,h298_atom,g298_atom
gdb_1,157.7118,157.70997,157.70699,0.0,13.21,-0.3877,0.1171,0.5048,35.3641,0.044749,-40.47893,-40.476062,-40.475117,-40.498597,6.469,-395.999595,-398.64329,-401.014647,-372.471772
gdb_2,293.60975,293.54111,191.39397,1.6256,9.46,-0.257,0.0829,0.3399,26.1563,0.034358,-56.525887,-56.523026,-56.522082,-56.544961,6.316,-276.861363,-278.620271,-280.399259,-259.338802
gdb_3,799.58812,437.90386,282.94545,1.8511,6.31,-0.2928,0.0687,0.3615,19.0002,0.021375,-76.404702,-76.401867,-76.400922,-76.422349,6.002,-213.087624,-213.974294,-215.159658,-201.407171
"""


@pytest.fixture()
def qm9_root(tmp_path):
    root = tmp_path / "qm9raw"
    root.mkdir()
    (root / "gdb9.sdf").write_text(_GDB9_SDF)
    (root / "gdb9.sdf.csv").write_text(_GDB9_CSV)
    # real-file shape: 9 banner lines, "  index  name ..." rows, count tail
    (root / "uncharacterized.txt").write_text(
        "\n" * 9 + "  2  gdb_2 fails\n" + "1 compounds\n"
    )
    return str(root)


def pytest_qm9_sdf_parser():
    mols = parse_sdf_v2000(_GDB9_SDF)
    assert len(mols) == 3
    syms, pos, bonds = mols[0]
    assert syms == ["C", "H", "H", "H", "H"]
    assert pos.shape == (5, 3) and bonds.shape == (4, 2)
    assert bonds[0].tolist() == [0, 1]  # 0-based
    # C-H bond length ~1.09 A in the real geometry
    d = np.linalg.norm(pos[0] - pos[1], axis=-1)
    assert 1.05 < d < 1.15


def pytest_qm9_csv_pyg_ordering():
    import tempfile

    with tempfile.NamedTemporaryFile("w", suffix=".csv", delete=False) as f:
        f.write(_GDB9_CSV)
        path = f.name
    y = read_gdb9_csv(path)
    os.unlink(path)
    assert y.shape == (3, 19)
    # PyG order: index 0 = mu (Debye, unconverted), 10 = g298 (Ha -> eV),
    # 16 = A (GHz, unconverted)
    assert y[1, 0] == pytest.approx(1.6256)
    assert y[0, 10] == pytest.approx(-40.498597 * HAR2EV)
    assert y[0, 16] == pytest.approx(157.7118)


def pytest_qm9_raw_dataset(qm9_root):
    ds = QM9RawDataset(qm9_root, target_index=10, per_atom=True)
    # gdb_2 is uncharacterized -> skipped
    assert len(ds) == 2
    d = ds[0]
    assert d.x.shape == (5, 1) and d.x[0, 0] == 6.0  # carbon
    assert d.target_types == ["graph"]
    assert d.targets[0][0] == pytest.approx(-40.498597 * HAR2EV / 5, rel=1e-6)
    assert d.edge_index.shape[0] == 2 and d.num_edges > 0
    # bond-edge mode: methane has 4 bonds -> 8 directed edges
    ds_b = QM9RawDataset(qm9_root, edges="bonds")
    assert ds_b[0].num_edges == 8


def pytest_qm9_dsgdb9nsd_xyz(tmp_path):
    # original-layout file for water with '*^' Fortran exponents
    (tmp_path / "dsgdb9nsd_000003.xyz").write_text(
        "3\n"
        "gdb 3\t799.58812\t437.90386\t282.94545\t1.8511\t6.31\t-0.2928\t"
        "0.0687\t0.3615\t19.0002\t2.1375*^-2\t-76.404702\t-76.401867\t"
        "-76.400922\t-76.422349\t6.002\n"
        "O\t-0.0343\t0.9775\t0.0076\t-0.3872\n"
        "H\t0.0647\t0.0205\t0.0015\t0.1936\n"
        "H\t0.8717\t1.3008\t0.0006\t0.1936\n"
        "1341.307\t1341.307\t2591.043\n"
    )
    syms, pos, y = parse_dsgdb9nsd_xyz(str(tmp_path / "dsgdb9nsd_000003.xyz"))
    assert syms == ["O", "H", "H"]
    assert y[0] == pytest.approx(1.8511)  # mu
    assert y[6] == pytest.approx(0.021375 * HAR2EV)  # zpve, *^ exponent
    assert y[10] == pytest.approx(-76.422349 * HAR2EV)
    assert np.isnan(y[12])  # atomization energies absent in this layout
    ds = QM9RawDataset(str(tmp_path))
    assert len(ds) == 1 and ds[0].x[0, 0] == 8.0


def pytest_extxyz_roundtrip(tmp_path):
    cell = np.diag([7.2, 7.2, 18.6])
    frames = [
        {
            "z": np.array([29, 29, 1]),
            "pos": np.array([[0.0, 0, 0], [1.8, 1.8, 0], [1.8, 1.8, 2.1]]),
            "cell": cell,
            "info": {"energy": -12.345678},
            "arrays": {"forces": np.array([[0.0, 0, 0.1], [0, 0, -0.2], [0, 0, 0.1]])},
        }
    ]
    path = str(tmp_path / "s0.extxyz")
    write_extxyz(path, frames)
    back = list(iter_extxyz(path))
    assert len(back) == 1
    fr = back[0]
    assert fr["symbols"] == ["Cu", "Cu", "H"]
    assert fr["z"].tolist() == [29, 29, 1]
    np.testing.assert_allclose(fr["pos"], frames[0]["pos"], atol=1e-6)
    np.testing.assert_allclose(fr["cell"], cell, atol=1e-6)
    assert fr["pbc"].all()
    assert fr["info"]["energy"] == pytest.approx(-12.345678)
    np.testing.assert_allclose(
        fr["arrays"]["forces"], frames[0]["arrays"]["forces"], atol=1e-6
    )

    g = frame_to_graph(fr, radius=4.0, max_neighbours=12)
    assert g.target_types == ["graph", "node"]
    assert g.targets[0][0] == pytest.approx(-12.345678 / 3)
    assert g.targets[1].shape == (3, 3)
    assert g.edge_attr is not None and g.edge_attr.shape[1] == 1
    # PBC: corner Cu sees the other Cu through the cell boundary too
    assert g.num_edges >= 4


def pytest_extxyz_dir_force_filter(tmp_path):
    ok = {
        "z": np.array([1, 1]),
        "pos": np.array([[0.0, 0, 0], [0, 0, 0.9]]),
        "info": {"energy": -1.0},
        "arrays": {"forces": np.zeros((2, 3))},
    }
    bad = dict(ok)
    bad = {
        **ok,
        "arrays": {"forces": np.array([[0.0, 0, 500.0], [0, 0, 0]])},
    }
    write_extxyz(str(tmp_path / "a.extxyz"), [ok, bad])
    graphs = load_extxyz_dir(str(tmp_path), radius=2.0)
    assert len(graphs) == 1  # 500 eV/A frame dropped


def pytest_mptrj_roundtrip(tmp_path):
    lattice = np.diag([4.0, 4.0, 4.0])
    rec = {
        "mp_id": "mp-1",
        "frame_id": "mp-1-0-0",
        "z": np.array([26, 8]),
        "pos": np.array([[0.0, 0, 0], [2.0, 2.0, 2.0]]),
        "lattice": lattice,
        "energy": -6.5,  # per atom
        "forces": np.array([[0.0, 0, 0.3], [0, 0, -0.3]]),
        "stress": np.eye(3) * 0.1,
        "magmom": np.array([2.2, 0.1]),
    }
    path = str(tmp_path / "MPtrj_tiny.json")
    write_mptrj_json(path, [rec])
    # the written file is genuine MPtrj schema: nested dicts + pymatgen sites
    with open(path) as f:
        nested = json.load(f)
    site0 = nested["mp-1"]["mp-1-0-0"]["structure"]["sites"][0]
    assert site0["species"][0]["element"] == "Fe"
    z, pos, lat = structure_from_dict(nested["mp-1"]["mp-1-0-0"]["structure"])
    assert z.tolist() == [26, 8]
    np.testing.assert_allclose(pos, rec["pos"], atol=1e-8)

    graphs = load_mptrj(path, radius=4.5)
    assert len(graphs) == 1
    g = graphs[0]
    assert g.target_types == ["graph", "node"]
    assert g.targets[0][0] == pytest.approx(-6.5)
    assert g.extras["mp_id"] == "mp-1"
    assert "magmom" in g.extras and "stress" in g.extras
    # node features are [z, centered cartesian coords] — the reference's
    # MPtrj feature layout (train.py:143 with input_node_features [0,1,2,3]);
    # without coordinates the invariant MLP force head cannot learn forces
    assert g.x.shape == (2, 4)
    np.testing.assert_allclose(g.x[:, 0], [26, 8])
    np.testing.assert_allclose(g.x[:, 1:].mean(axis=0), 0.0, atol=1e-6)
    np.testing.assert_allclose(
        g.x[:, 1:], g.pos - g.pos.mean(axis=0, keepdims=True), atol=1e-6
    )


def pytest_pair_potential_forces_are_exact_gradient():
    sys.path.insert(
        0, os.path.join(os.path.dirname(os.path.dirname(__file__)), "examples")
    )
    from common import pair_potential_forces

    rng = np.random.default_rng(7)
    z = rng.choice([3, 14, 26, 8], size=9).astype(np.float64)
    pos = rng.normal(0.0, 1.5, (9, 3)).astype(np.float64)
    e0, f = pair_potential_forces(z, pos)
    assert np.isfinite(e0) and np.isfinite(f).all()
    assert np.abs(f).max() > 0  # nontrivial field
    eps = 1e-7
    g = np.zeros_like(pos)
    for i in range(9):
        for d in range(3):
            p = pos.copy()
            p[i, d] += eps
            e1, _ = pair_potential_forces(z, p)
            g[i, d] = -(e1 - e0) / eps
    np.testing.assert_allclose(g, f, atol=1e-5)


def pytest_pbc_pair_energy_matches_brute_force_images():
    """Minimum-image energy == explicit sum over periodic images (valid
    while cutoff < min period / 2, the OC20 slab regime)."""
    sys.path.insert(
        0, os.path.join(os.path.dirname(os.path.dirname(__file__)), "examples")
    )
    from common import pbc_pair_energy

    rng = np.random.default_rng(11)
    cell = np.diag([7.2, 7.2, 18.6])
    z = rng.choice([29, 78, 47, 8], size=9).astype(np.float64)
    pos = rng.uniform(0.0, 7.2, (9, 3))
    cutoff, r0, w_scale = 3.5, 2.0, 0.05

    def brute(z, pos):
        e = 0.0
        period = np.diag(cell)
        for i in range(len(z)):
            for j in range(len(z)):
                for sx in (-1, 0, 1):
                    for sy in (-1, 0, 1):
                        for sz in (-1, 0, 1):
                            if i == j and sx == sy == sz == 0:
                                continue
                            d = pos[i] - pos[j] + np.array([sx, sy, sz]) * period
                            r = np.linalg.norm(d)
                            if r < cutoff:
                                w = w_scale * np.sqrt(z[i] * z[j])
                                s = 0.5 * (1 + np.cos(np.pi * r / cutoff))
                                e += w * (r - r0) ** 2 * s
        return e / 2.0

    got = pbc_pair_energy(z, pos, cell, cutoff=cutoff, r0=r0, w_scale=w_scale)
    np.testing.assert_allclose(got, brute(z, pos), rtol=1e-10)
    assert got > 0  # nontrivial


def pytest_mptrj_fractional_sites():
    s = {
        "lattice": {"matrix": [[2.0, 0, 0], [0, 2.0, 0], [0, 0, 2.0]]},
        "sites": [
            {"species": [{"element": "Li", "occu": 1.0}], "abc": [0.5, 0.5, 0.5]}
        ],
    }
    z, pos, lat = structure_from_dict(s)
    assert z.tolist() == [3]
    np.testing.assert_allclose(pos[0], [1.0, 1.0, 1.0])


def pytest_qm9_raw_trains_end_to_end(qm9_root, tmp_path, monkeypatch):
    """Real-format QM9 -> loaders -> PNA training steps through the public
    pipeline (tiny but complete: proves the ingestion path feeds the
    framework)."""
    monkeypatch.chdir(tmp_path)
    import jax

    from hydragnn_tpu.data import create_dataloaders
    from hydragnn_tpu.models import create_model_config
    from hydragnn_tpu.train import Trainer
    from hydragnn_tpu.utils.config import update_config

    ds = QM9RawDataset(qm9_root, radius=7.0, max_neighbours=5)
    samples = [ds[i % len(ds)].clone() for i in range(12)]
    config = {
        "NeuralNetwork": {
            "Architecture": {
                "model_type": "PNA",
                "hidden_dim": 8,
                "num_conv_layers": 2,
                "output_heads": {
                    "graph": {
                        "num_sharedlayers": 1,
                        "dim_sharedlayers": 8,
                        "num_headlayers": 1,
                        "dim_headlayers": [8],
                    }
                },
                "task_weights": [1.0],
            },
            "Training": {"batch_size": 4, "num_epoch": 1,
                          "Optimizer": {"learning_rate": 1e-3}},
            "Variables_of_interest": {
                "input_node_features": [0],
                "output_names": ["free_energy"],
                "output_index": [0],
                "output_dim": [1],
                "type": ["graph"],
                "denormalize_output": False,
            },
        }
    }
    tr, va, te = samples[:8], samples[8:10], samples[10:]
    train_loader, val_loader, test_loader = create_dataloaders(tr, va, te, 4)
    config = update_config(config, train_loader, val_loader, test_loader)
    arch = dict(config["NeuralNetwork"]["Architecture"])
    arch["loss_function_type"] = "mse"
    model = create_model_config(arch, 0)
    trainer = Trainer(model, config["NeuralNetwork"]["Training"], verbosity=0)
    batch = next(iter(train_loader))
    state = trainer.init_state(batch, seed=0)
    rng = jax.random.PRNGKey(0)
    for _ in range(2):
        rng, sub = jax.random.split(rng)
        state, metrics = trainer._train_step(state, trainer.put_batch(batch), sub)
    assert np.isfinite(float(metrics["loss"]))


def pytest_mptrj_streaming_parser(tmp_path):
    """iter_mptrj_entries streams top-level entries without json.load-ing
    the whole file (the real MPtrj json is tens of GB) — robust to
    pretty-printed whitespace and chunk boundaries."""
    from hydragnn_tpu.data.mptrj import iter_mptrj_entries

    recs = []
    for t in range(5):
        recs.append(
            {
                "mp_id": f"mp-{t}",
                "frame_id": f"mp-{t}-0-0",
                "z": np.array([26, 8]),
                "pos": np.array([[0.0, 0, 0], [2.0, 2.0, 2.0]]),
                "lattice": np.diag([4.0, 4.0, 4.0]),
                "energy": -6.5 - t,
                "forces": np.zeros((2, 3)),
                "magmom": np.array([1.0, 0.0]),
            }
        )
    compact = str(tmp_path / "MPtrj_c.json")
    write_mptrj_json(compact, recs)
    pretty = str(tmp_path / "MPtrj_p.json")
    with open(pretty, "w") as f:
        json.dump(json.load(open(compact)), f, indent=2)
    for p in (compact, pretty):
        # chunk=64 forces many refills: keys/values straddle boundaries
        for chunk in (64, 1 << 22):
            keys = [k for k, _ in iter_mptrj_entries(p, chunk=chunk)]
            assert keys == [f"mp-{t}" for t in range(5)]
        graphs = load_mptrj(p, radius=4.5)
        assert len(graphs) == 5
        assert graphs[3].targets[0][0] == pytest.approx(-9.5)

    # a truncated download must raise LOUDLY, not silently yield a partial
    # dataset (ValueError at EOF mid-value, or JSONDecodeError when the
    # cut lands mid-literal and reads as a syntax error)
    raw = open(compact).read()
    cut = str(tmp_path / "MPtrj_cut.json")
    with open(cut, "w") as f:
        f.write(raw[: int(len(raw) * 0.6)])
    with pytest.raises((ValueError, json.JSONDecodeError)):
        list(iter_mptrj_entries(cut, chunk=64))
    nobrace = str(tmp_path / "MPtrj_nobrace.json")
    with open(nobrace, "w") as f:
        f.write(raw.rstrip()[:-1])  # drop only the closing brace
    with pytest.raises(ValueError, match="closing brace"):
        list(iter_mptrj_entries(nobrace, chunk=64))


# --- round-3 advisor-hardening regressions ---------------------------------


def pytest_extxyz_partial_pbc_slab(tmp_path):
    """A pbc=\"T T F\" slab must not form edges through the vacuum axis
    (advisor round 2): two atoms 2.0 apart along z in a cell with only 3.0
    of z extent are within a 1.5 cutoff ONLY via the z image shift."""
    frames = [
        {
            "z": np.array([1, 1]),
            "pos": np.array([[0.5, 0.5, 0.5], [0.5, 0.5, 2.5]]),
            "cell": np.diag([8.0, 8.0, 3.0]),
            "pbc": np.array([True, True, False]),
            "info": {"energy": -1.0},
            "arrays": {},
        }
    ]
    path = str(tmp_path / "slab.extxyz")
    write_extxyz(path, frames)
    fr = list(iter_extxyz(path))[0]
    assert fr["pbc"].tolist() == [True, True, False]  # round-trips
    g = frame_to_graph(fr, radius=1.5, max_neighbours=8)
    assert g.num_edges == 0  # no edge across the non-periodic axis
    # fully periodic: same geometry DOES connect through the z image
    fr_full = {**fr, "pbc": np.array([True, True, True])}
    g_full = frame_to_graph(fr_full, radius=1.5, max_neighbours=8)
    assert g_full.num_edges == 2


def pytest_extxyz_truncated_frame_reports_context(tmp_path):
    path = str(tmp_path / "trunc.extxyz")
    with open(path, "w") as f:
        f.write('3\nProperties=species:S:1:pos:R:3 energy=-1.0\n')
        f.write("H 0.0 0.0 0.0\n")  # file ends after 1 of 3 atoms
    with pytest.raises(ValueError, match="trunc.extxyz.*frame 0"):
        list(iter_extxyz(path))


def pytest_extxyz_short_atom_line_reports_context(tmp_path):
    path = str(tmp_path / "short.extxyz")
    with open(path, "w") as f:
        f.write('1\nProperties=species:S:1:pos:R:3 energy=-1.0\n')
        f.write("H 0.0 0.0\n")  # missing a pos column
    with pytest.raises(ValueError, match="columns"):
        list(iter_extxyz(path))


def pytest_extxyz_string_extra_column(tmp_path):
    path = str(tmp_path / "tags.extxyz")
    with open(path, "w") as f:
        f.write('2\nProperties=species:S:1:pos:R:3:tag:S:1 energy=-1.0\n')
        f.write("H 0.0 0.0 0.0 surface\n")
        f.write("H 0.0 0.0 0.9 adsorbate\n")
    frames = list(iter_extxyz(path))
    assert frames[0]["arrays"]["tag"].tolist() == ["surface", "adsorbate"]


def pytest_mptrj_missing_energy_raises(tmp_path):
    nested = {
        "mp-1": {
            "mp-1-0-0": {
                "structure": {
                    "lattice": {"matrix": np.diag([4.0, 4.0, 4.0]).tolist()},
                    "sites": [
                        {
                            "species": [{"element": "Fe", "occu": 1.0}],
                            "abc": [0.0, 0.0, 0.0],
                        }
                    ],
                },
                "force": [[0.0, 0.0, 0.0]],
            }
        }
    }
    path = str(tmp_path / "noenergy.json")
    with open(path, "w") as f:
        json.dump(nested, f)
    with pytest.raises(KeyError):
        list(iter_mptrj(path, energy_per_atom=False))
    with pytest.raises(KeyError):
        list(iter_mptrj(path, energy_per_atom=True))


def pytest_qm9_csv_bad_header_raises(tmp_path):
    path = str(tmp_path / "gdb9.sdf.csv")
    with open(path, "w") as f:
        f.write("wrong,header,row\n")
    with pytest.raises(ValueError, match="header"):
        read_gdb9_csv(path)
