"""CI model-quality observatory smoke (standalone, NOT a pytest module).

The drift-detection twin of ``tests/_fleet_smoke.py``: a two-replica
:class:`ServingFleet` with the full quality stack armed — K-sample
uncertainty scoring (``HYDRAGNN_UNC_SAMPLES``), streaming drift
detection against a version-pinned reference (``HYDRAGNN_DRIFT_*``) and
the labeled-on-demand feedback sink (``HYDRAGNN_FEEDBACK_*``) — under
closed-loop two-tenant load:

1. quiet phase: bounded request count, every response must carry a
   finite per-head ``uncertainty`` vector, and the detector must close
   at least one SCORED window with ZERO alerts (no flapping — the
   thresholds sit above the measured finite-window noise floor),
2. shift phase: ``HYDRAGNN_FAULT_SHIFT_INPUTS`` scales every request
   graph 6x once a replica's request ordinal crosses the spec, and the
   smoke hammers until a schema-valid ``drift_alert`` raises — on a
   shift-affected feature only (an alert on ``num_nodes`` /
   ``num_edges`` / ``unc`` would be a false positive),
3. the compile counter scraped from every replica's ``/metrics`` must
   not move between quiet steady state and the end of the run (the
   scoring program is warmed like every bucket program — a drifted
   input is a VALUE change, never a shape change),
4. after shutdown the feedback queue must hold deduped packs of the
   SHIFTED graphs (admission here is drifted-only: ``MIN_UNC`` is set
   above GIN's honest zero dropout variance), each bitwise identical to
   a client-side reconstruction and readable back through
   ``ShardStoreSource`` into a ``WeightedMix``,
5. every per-replica event stream validates against the documented
   schema and ``python -m hydragnn_tpu.obs drift`` renders the run.

Usage: python tests/_drift_smoke.py <workdir>
"""

import glob
import json
import math
import os
import sys
import threading
import time
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

TENANTS = ("acme", "beta")
NUM_WORKERS = 4
REQUEST_DEADLINE_S = 30.0

# the quiet phase is a BOUNDED request count kept strictly below the
# fault spec's ordinal, so no quiet request can be shifted even if the
# router sent every single one to the same replica
QUIET_REQUESTS = 1400
SHIFT_AT = 2000
SHIFT_SCALE = 6.0

# detector window 256 with two tenants -> ~128 samples per (tenant,
# feature) key per window; the measured worst-case same-distribution
# noise over a fixed 32-graph pool at that count is PSI ~0.40 / KS
# ~0.23, while the 6x input scale scores PSI > 3 / KS > 0.8 — the
# thresholds sit between with >2x margin on both sides
KNOBS = {
    "HYDRAGNN_UNC_SAMPLES": "3",
    "HYDRAGNN_DRIFT_WINDOW": "256",
    "HYDRAGNN_DRIFT_PSI": "0.9",
    "HYDRAGNN_DRIFT_KS": "0.5",
    "HYDRAGNN_DRIFT_RAISE": "2",
    "HYDRAGNN_DRIFT_CLEAR": "2",
    "HYDRAGNN_FEEDBACK_MAX_GRAPHS": "8",
    "HYDRAGNN_FEEDBACK_MAX_PACKS": "4",
    # above the GIN stack's honest zero dropout variance: the sink may
    # admit through the DRIFTED path only, so it must stay empty until
    # an alert is active and then fill with shifted graphs exclusively
    "HYDRAGNN_FEEDBACK_MIN_UNC": "0.5",
    "HYDRAGNN_FAULT_SHIFT_INPUTS": f"{SHIFT_AT}:@{SHIFT_SCALE}",
}

DETECT_DEADLINE_S = 300.0
HAMMER_CAP = 16000
POST_DETECT_REQUESTS = 600

# the only feature streams the 6x input scale moves — species is x[:, 0],
# edge_len follows pos, pred follows the model outputs; num_nodes /
# num_edges / unc are shift-invariant so an alert there is flapping
SHIFTED_FEATURES = {"species", "edge_len", "pred"}


def blast(router, samples, n, seed0):
    """Send ``n`` requests from ``NUM_WORKERS`` closed-loop clients,
    tenants interleaved; returns (ok, failed, bad_uncertainty)."""
    import numpy as np

    counts = [n // NUM_WORKERS] * NUM_WORKERS
    for i in range(n % NUM_WORKERS):
        counts[i] += 1
    ok = [0] * NUM_WORKERS
    failed = [0] * NUM_WORKERS
    bad_unc = [0] * NUM_WORKERS

    def worker(w):
        rng = np.random.default_rng(seed0 + w)
        for j in range(counts[w]):
            g = samples[int(rng.integers(0, len(samples)))]
            tenant = TENANTS[(w + j) % len(TENANTS)]
            try:
                body = router.route(
                    g, deadline_s=REQUEST_DEADLINE_S, raw=True,
                    tenant=tenant,
                )
            except Exception:
                failed[w] += 1
                continue
            unc = body.get("uncertainty")
            if (
                isinstance(unc, list)
                and len(unc) == 2
                and all(
                    v is not None
                    and math.isfinite(float(v))
                    and float(v) >= 0.0
                    for v in unc
                )
            ):
                ok[w] += 1
            else:
                bad_unc[w] += 1

    threads = [
        threading.Thread(target=worker, args=(w,), daemon=True)
        for w in range(NUM_WORKERS)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return sum(ok), sum(failed), sum(bad_unc)


def quality_events(coord_dir):
    from hydragnn_tpu.obs.drift import load_quality_events

    return load_quality_events(coord_dir)


def raised_alerts(records, since=None):
    out = []
    for r in records:
        if r.get("event") != "drift_alert" or r.get("status") != "raised":
            continue
        if since is not None and float(r.get("ts") or 0.0) < since:
            continue
        out.append(r)
    return out


def scrape_compiles(coord_dir):
    """``hydragnn_serve_compiles_total`` per live replica, scraped off
    each replica's ``/metrics`` (port from its heartbeat lease)."""
    out = {}
    for lease in sorted(
        glob.glob(os.path.join(coord_dir, "replicas", "replica-*.json"))
    ):
        try:
            with open(lease) as f:
                info = json.load(f)
            port = int(info["port"])
            text = (
                urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/metrics", timeout=10
                )
                .read()
                .decode()
            )
        except Exception:
            continue
        value = None
        for line in text.splitlines():
            if line.startswith("hydragnn_serve_compiles_total "):
                value = float(line.split()[-1])
        out[os.path.basename(lease)] = (value, text)
    return out


def shifted_lookup(samples):
    """canonical key -> the exact shifted graph every replica-side
    ``shift_inputs`` call must have produced (same numpy, same op, same
    float32 inputs after the JSON round-trip -> bitwise identical)."""
    from hydragnn_tpu.serve.cache import canonical_graph_key

    out = {}
    for g in samples:
        s = g.clone()
        s.x = s.x * SHIFT_SCALE
        s.pos = s.pos * SHIFT_SCALE
        out[canonical_graph_key(s)] = s
    return out


def main(workdir):
    os.makedirs(workdir, exist_ok=True)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    feedback_dir = os.path.join(workdir, "feedback")
    knobs = dict(KNOBS, HYDRAGNN_FEEDBACK_DIR=feedback_dir)
    saved = {k: os.environ.get(k) for k in knobs}
    os.environ.update(knobs)
    try:
        run(workdir, feedback_dir)
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def run(workdir, feedback_dir):
    from _fleet_smoke import build_artifacts

    from hydragnn_tpu.data.stream.mix import WeightedMix
    from hydragnn_tpu.data.stream.source import ShardStoreSource
    from hydragnn_tpu.obs.__main__ import main as obs_main
    from hydragnn_tpu.obs.events import validate_events
    from hydragnn_tpu.serve import FleetRouter
    from hydragnn_tpu.serve.cache import canonical_graph_key
    from hydragnn_tpu.serve.fleet import ServingFleet

    spec_path, ckdir, samples = build_artifacts(workdir)
    # declare the two tenants (sharing the default model) — a tenant
    # label on a request is rejected unless the server has a
    # TenantManager, and the drift keys are per-tenant
    with open(spec_path) as f:
        spec = json.load(f)
    spec["tenants"] = [{"name": t, "model": "m"} for t in TENANTS]
    with open(spec_path, "w") as f:
        json.dump(spec, f)
    coord_dir = os.path.join(workdir, "coord")
    fleet = ServingFleet(
        coord_dir,
        2,
        spec_path=spec_path,
        heartbeat_s=0.1,
        lease_s=0.75,
        poll_s=0.05,
        log_dir=os.path.join(workdir, "log"),
    )
    fleet.start(wait_serving=True, timeout=300)
    detect_s = None
    hammer_sent = 0
    try:
        assert fleet.health()["live"] == 2, fleet.health()
        router = FleetRouter(
            coord_dir,
            lease_s=0.75,
            scan_interval_s=0.1,
            max_attempts=6,
            retry_base_delay_s=0.05,
        )

        # ---- phase 1: quiet two-tenant traffic --------------------------
        sent = QUIET_REQUESTS
        ok, failed, bad_unc = blast(router, samples, QUIET_REQUESTS, 100)
        assert bad_unc == 0, (
            f"{bad_unc} responses lacked a finite 2-head uncertainty"
        )
        assert ok >= 0.8 * QUIET_REQUESTS, (ok, failed)
        # a vacuously alert-free quiet phase proves nothing: require at
        # least one SCORED (non-bootstrap) window before the shift,
        # topping up in small bounded bites if routing skew delayed it
        def scored_windows():
            return sum(
                1
                for r in quality_events(coord_dir)
                if r.get("event") == "drift_window" and r.get("scores")
            )

        while scored_windows() == 0 and sent + 100 <= SHIFT_AT - 50:
            ok2, failed2, bad2 = blast(router, samples, 100, 7000 + sent)
            assert bad2 == 0
            sent += 100
        assert scored_windows() >= 1, (
            f"no scored drift window after {sent} quiet requests"
        )
        t_mark = time.time()
        assert not raised_alerts(quality_events(coord_dir)), (
            "drift alert raised on QUIET traffic (flapping): "
            f"{raised_alerts(quality_events(coord_dir))}"
        )

        # quiet steady state reached: the compile counter must be flat
        # from here to the end of the run, shift included
        base = scrape_compiles(coord_dir)
        assert len(base) == 2, f"scraped {sorted(base)} of 2 replicas"
        for name, (value, _) in sorted(base.items()):
            assert value is not None and value > 0, (name, value)

        # ---- phase 2: hammer across the fault-injected shift ------------
        t_hammer = time.monotonic()
        detected = None
        seed = 9000
        while detected is None:
            if time.monotonic() - t_hammer > DETECT_DEADLINE_S:
                break
            if hammer_sent >= HAMMER_CAP:
                break
            ok3, failed3, bad3 = blast(router, samples, 240, seed)
            assert bad3 == 0
            hammer_sent += 240
            seed += NUM_WORKERS
            hits = raised_alerts(quality_events(coord_dir), since=t_mark)
            if hits:
                detected = hits[0]
        assert detected is not None, (
            f"no drift_alert raised within {hammer_sent} shifted-phase "
            f"requests / {DETECT_DEADLINE_S}s"
        )
        detect_s = time.monotonic() - t_hammer
        # keep serving shifted traffic so the (drifted-only) sink
        # accumulates past the alert on both tenants
        ok4, failed4, bad4 = blast(
            router, samples, POST_DETECT_REQUESTS, 31000
        )
        assert bad4 == 0

        end = scrape_compiles(coord_dir)
        assert sorted(end) == sorted(base), (sorted(base), sorted(end))
        for name, (value, text) in sorted(end.items()):
            assert value == base[name][0], (
                f"{name}: compiles moved {base[name][0]} -> {value} "
                "after warmup (steady state must be recompile-free, "
                "shift included)"
            )
            assert "hydragnn_drift_score" in text, name
            assert "hydragnn_uncertainty" in text, name
    finally:
        fleet.stop()

    # ---- post-mortem: events, alerts, sink, CLI -------------------------
    streams = sorted(
        glob.glob(os.path.join(coord_dir, "events-replica*.jsonl"))
    )
    assert streams, coord_dir
    names = set()
    for stream in streams:
        records = validate_events(stream)
        names.update(r["event"] for r in records)
    for required in ("drift_window", "drift_alert", "feedback_sink"):
        assert required in names, (required, sorted(names))

    records = quality_events(coord_dir)
    early = raised_alerts(records, since=None)
    assert all(float(r.get("ts") or 0.0) >= t_mark for r in early), (
        f"alert(s) raised on quiet traffic: "
        f"{[r for r in early if float(r.get('ts') or 0.0) < t_mark]}"
    )
    raised = raised_alerts(records, since=t_mark)
    assert raised
    for r in raised:
        assert r.get("feature") in SHIFTED_FEATURES, (
            f"alert on a shift-invariant feature (false positive): {r}"
        )
    windows = sum(1 for r in records if r.get("event") == "drift_window")

    # the sink persisted SHIFTED graphs only, deduped per replica, and
    # every pack reads back bitwise through ShardStoreSource/WeightedMix
    expect = shifted_lookup(samples)
    sink_dirs = [
        d
        for d in sorted(glob.glob(os.path.join(feedback_dir, "replica*")))
        if glob.glob(os.path.join(d, "shard.*.gpk"))
    ]
    assert sink_dirs, f"no feedback packs under {feedback_dir}"
    total_graphs = 0
    for d in sink_dirs:
        seen = set()
        mix = WeightedMix([ShardStoreSource(d)], seed=3)
        for _, g in mix:
            key = canonical_graph_key(g)
            assert key not in seen, f"duplicate graph in {d}"
            seen.add(key)
            s = expect.get(key)
            assert s is not None, (
                f"sink graph in {d} is not one of the shifted inputs"
            )
            assert g.x.tobytes() == s.x.tobytes()
            assert g.pos.tobytes() == s.pos.tobytes()
            assert g.edge_index.tobytes() == s.edge_index.tobytes()
            total_graphs += 1
        packs = len(glob.glob(os.path.join(d, "shard.*.gpk")))
        assert packs <= int(KNOBS["HYDRAGNN_FEEDBACK_MAX_PACKS"]), d
    assert total_graphs >= 1

    # the run renders through the CLI in both formats
    assert obs_main(["drift", coord_dir]) == 0
    assert obs_main(["drift", coord_dir, "--format", "json"]) == 0

    print(
        f"drift smoke OK: windows={windows} "
        f"alerts_raised={len(raised)} "
        f"first_alert={detected.get('tenant')}|{detected.get('feature')}"
        f"|{detected.get('head')} ({detected.get('kind')}="
        f"{detected.get('score')}) "
        f"detect_s={detect_s:.1f} hammer_requests={hammer_sent} "
        f"sink_graphs={total_graphs} sink_dirs={len(sink_dirs)}"
    )


if __name__ == "__main__":
    if len(sys.argv) != 2:
        print(__doc__)
        sys.exit(2)
    main(sys.argv[1])
