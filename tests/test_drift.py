"""Model-quality observatory (obs/drift.py + serve/quality.py).

Acceptance: P² quantile estimates stay within a rank-error bound on
adversarial streams; StreamingHistogram merges are associative (fleet
rollups and reference snapshots must not depend on merge order); the
sketch-vs-sketch PSI/KS scores agree with an exact scipy-free reference
on raw data; the feedback sink dedups permuted duplicates via
``canonical_graph_key`` and its queue dir round-trips bitwise through
``ShardStoreSource`` into a ``WeightedMix``; the drift detector's
hysteresis raises/clears on consecutive windows and pins its reference
per version (promote snapshots, rollback reloads — never overwrites);
every ``HYDRAGNN_DRIFT_*``/``HYDRAGNN_UNC_*``/``HYDRAGNN_FEEDBACK_*``
knob is unit-locked; and the opt-in uncertainty scorer keeps the
zero-steady-state-recompiles contract (compile-counter-verified).
"""

import contextlib
import json
import math
import os

import numpy as np
import pytest

from hydragnn_tpu.models import create_model_config
from hydragnn_tpu.obs.drift import (
    DriftDetector,
    P2Quantile,
    StreamingHistogram,
    build_drift_report,
    graph_features,
    ks,
    load_quality_events,
    psi,
    render_drift_text,
)
from hydragnn_tpu.obs.events import RunEventLog, validate_events
from hydragnn_tpu.serve import (
    FeedbackSink,
    InferenceServer,
    ModelRegistry,
    UncertaintyScorer,
    canonical_graph_key,
    plan_from_samples,
)
from hydragnn_tpu.serve.canary import (
    CanaryGates,
    _CandidateStats,
    evaluate_gates,
)
from hydragnn_tpu.train.trainer import Trainer

from test_models_forward import arch_config
from test_serve import _graph


@contextlib.contextmanager
def _env(**kv):
    saved = {k: os.environ.get(k) for k in kv}
    try:
        for k, v in kv.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        yield
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


# ---- sketches ------------------------------------------------------------


def _p2_estimate(data, q):
    sk = P2Quantile(q)
    for v in data:
        sk.add(float(v))
    return sk.value()


def pytest_p2_quantile_rank_error_bound_on_adversarial_streams():
    """P² stays within a ±0.12 rank-error band (plus a 2%-of-range
    slack) even on the classic adversarial orderings: sorted ascending /
    descending (markers chase a moving front), alternating extremes
    (bimodal), and a heavy-tailed draw."""
    rng = np.random.default_rng(11)
    streams = {
        "ascending": np.arange(2000, dtype=np.float64),
        "descending": np.arange(2000, dtype=np.float64)[::-1],
        "alternating": np.tile([0.0, 100.0], 1000),
        "heavy_tail": rng.pareto(1.5, 2000),
        "gaussian": rng.normal(3.0, 2.0, 2000),
    }
    for name, data in streams.items():
        span = float(np.max(data) - np.min(data))
        for q in (0.5, 0.9):
            est = _p2_estimate(data, q)
            lo = float(np.quantile(data, max(q - 0.12, 0.0)))
            hi = float(np.quantile(data, min(q + 0.12, 1.0)))
            assert lo - 0.02 * span <= est <= hi + 0.02 * span, (
                f"{name} q={q}: estimate {est} outside rank band "
                f"[{lo}, {hi}]"
            )


def pytest_p2_quantile_exact_below_five_samples():
    sk = P2Quantile(0.5)
    assert sk.value() is None
    for v in (5.0, 1.0, 3.0):
        sk.add(v)
    assert sk.value() == 3.0  # nearest-rank median of {1, 3, 5}


def _hist_of(data, max_bins=48):
    h = StreamingHistogram(max_bins)
    for v in data:
        h.add(float(v))
    return h


def pytest_streaming_histogram_merge_associativity():
    """(A ⊎ B) ⊎ C and A ⊎ (B ⊎ C) agree: exact same total mass, and
    quantiles within the sketch's own approximation error of each other
    AND of the exact concatenated stream — the property that lets fleet
    rollups and reference snapshots merge in any order."""
    rng = np.random.default_rng(5)
    a = rng.normal(0.0, 1.0, 1500)
    b = rng.normal(5.0, 2.0, 1500)
    c = rng.exponential(2.0, 1500)
    concat = np.concatenate([a, b, c])
    spread = float(np.quantile(concat, 0.99) - np.quantile(concat, 0.01))

    left = _hist_of(a)
    left.merge(_hist_of(b))
    left.merge(_hist_of(c))
    bc = _hist_of(b)
    bc.merge(_hist_of(c))
    right = _hist_of(a)
    right.merge(bc)

    assert left.total == right.total == float(concat.size)
    assert left.min == right.min == float(np.min(concat))
    assert left.max == right.max == float(np.max(concat))
    for q in (0.1, 0.25, 0.5, 0.75, 0.9):
        ql, qr = left.quantile(q), right.quantile(q)
        exact = float(np.quantile(concat, q))
        assert abs(ql - qr) <= 0.05 * spread, (q, ql, qr)
        assert abs(ql - exact) <= 0.08 * spread, (q, ql, exact)


def pytest_streaming_histogram_serialization_roundtrip():
    rng = np.random.default_rng(6)
    h = _hist_of(rng.normal(0.0, 1.0, 400), max_bins=16)
    h2 = StreamingHistogram.from_dict(
        json.loads(json.dumps(h.to_dict()))
    )
    assert h2.total == h.total and h2.bins == h.bins
    assert h2.quantile(0.5) == h.quantile(0.5)


# ---- PSI / KS vs an exact scipy-free reference ---------------------------


def _exact_psi(ref, live, bins=10, eps=1e-4):
    edges = np.quantile(ref, [i / bins for i in range(1, bins)])
    edges = np.concatenate([[-np.inf], edges, [np.inf]])
    p = np.histogram(ref, edges)[0] / ref.size
    q = np.histogram(live, edges)[0] / live.size
    p = np.maximum(p, eps)
    q = np.maximum(q, eps)
    return float(np.sum((p - q) * np.log(p / q)))


def _exact_ks(x, y):
    pts = np.concatenate([x, y])
    fx = np.searchsorted(np.sort(x), pts, side="right") / x.size
    fy = np.searchsorted(np.sort(y), pts, side="right") / y.size
    return float(np.max(np.abs(fx - fy)))


def pytest_psi_ks_agree_with_exact_reference():
    """Sketch-vs-sketch scores track the exact raw-data scores: near
    zero for same-distribution streams (below the alert thresholds),
    and matching the exact values for a 1.5-sigma shift (well above)."""
    rng = np.random.default_rng(17)
    ref = rng.normal(0.0, 1.0, 4000)
    same = rng.normal(0.0, 1.0, 4000)
    shift = rng.normal(1.5, 1.0, 4000)
    h_ref, h_same, h_shift = _hist_of(ref, 64), _hist_of(same, 64), \
        _hist_of(shift, 64)

    assert psi(h_ref, h_same) < 0.1   # "stable" rule-of-thumb band
    assert ks(h_ref, h_same) < 0.08
    e_psi, e_ks = _exact_psi(ref, shift), _exact_ks(ref, shift)
    s_psi, s_ks = psi(h_ref, h_shift), ks(h_ref, h_shift)
    assert abs(s_ks - e_ks) <= 0.05, (s_ks, e_ks)
    assert abs(s_psi - e_psi) <= 0.05 + 0.25 * e_psi, (s_psi, e_psi)
    # and both sides agree the shift clears the default thresholds
    assert s_psi > 0.25 and e_psi > 0.25
    assert s_ks > 0.35 and e_ks > 0.35


def pytest_psi_ks_empty_and_identical_sketches():
    empty = StreamingHistogram(8)
    h = _hist_of([1.0, 2.0, 3.0], 8)
    assert psi(empty, h) == 0.0 and ks(h, empty) == 0.0
    assert psi(h, h) == pytest.approx(0.0, abs=1e-9)
    assert ks(h, h) == 0.0


# ---- drift detector: hysteresis + version-pinned reference ---------------


def _feed(det, values, tenant="acme"):
    active = False
    for v in values:
        active = det.observe(tenant, heads=[np.asarray([v], np.float64)])
    return active


def pytest_drift_detector_hysteresis_raise_and_clear(tmp_path):
    """Bootstrap window becomes the reference; two consecutive shifted
    windows raise (not one — no flapping), two clean windows clear.
    Events land schema-valid in the stream."""
    log = RunEventLog(str(tmp_path / "events.jsonl"))
    det = DriftDetector(
        str(tmp_path), window=64, raise_after=2, clear_after=2,
        emit=log.emit,
    )
    det.on_activate(1)  # nothing to snapshot yet: ref arrives at window 1
    rng = np.random.default_rng(23)
    base = rng.normal(0.0, 1.0, 64)  # SAME values every clean window:
    # identical sketches score exactly 0, so "clean" cannot flake

    assert _feed(det, base) is False          # window 1: bootstrap
    assert os.path.exists(str(tmp_path / "drift-ref-v1.json"))
    assert _feed(det, base + 8.0) is False    # window 2: over, 1 < raise_after
    assert _feed(det, base + 8.0) is True     # window 3: raised
    assert det.alert_active("acme") and det.alert_active()
    assert _feed(det, base) is True           # window 4: 1 clean, still active
    assert _feed(det, base) is False          # window 5: cleared
    assert not det.alert_active()
    st = det.stats()
    assert st["windows_evaluated"] == 5
    assert st["alerts_raised"] == 1 and st["alerts_cleared"] == 1

    records = validate_events(
        log.path, require=["drift_window", "drift_alert"]
    )
    alerts = [r for r in records if r["event"] == "drift_alert"]
    assert [a["status"] for a in alerts] == ["raised", "cleared"]
    assert alerts[0]["tenant"] == "acme" and alerts[0]["version"] == 1
    # the CLI report folds the same stream back into an empty active set
    report = build_drift_report(load_quality_events(log.path))
    assert report["windows"] == 5 and report["alerts_active"] == []
    assert "model-quality" in render_drift_text(report)


def pytest_drift_reference_pinned_per_version(tmp_path):
    """Promote snapshots a NEW per-version file; rollback to an earlier
    version RELOADS its frozen file byte-identically — baselines never
    alias across versions."""
    det = DriftDetector(str(tmp_path), window=32)
    det.on_activate(1)
    rng = np.random.default_rng(29)
    _feed(det, rng.normal(0.0, 1.0, 32))  # bootstrap ref for v1
    v1_path = str(tmp_path / "drift-ref-v1.json")
    v1_bytes = open(v1_path, "rb").read()

    _feed(det, rng.normal(9.0, 1.0, 32))  # candidate-era traffic
    det.on_activate(2)                    # promote: snapshot fresh traffic
    v2_path = str(tmp_path / "drift-ref-v2.json")
    assert os.path.exists(v2_path)
    assert det.stats()["reference_version"] == 2
    assert open(v1_path, "rb").read() == v1_bytes  # v1 untouched

    det.on_activate(1)                    # rollback: reload, never re-snapshot
    assert det.stats()["reference_version"] == 1
    assert open(v1_path, "rb").read() == v1_bytes
    ref = json.load(open(v1_path))
    assert ref["version"] == 1 and ref["sketches"]


def pytest_graph_features_shapes():
    g = _graph(10, np.random.default_rng(3), with_targets=False)
    feats = graph_features(g)
    assert feats["num_nodes"] == [10.0]
    assert feats["num_edges"] == [float(g.num_edges)]
    assert len(feats["species"]) == 10
    assert feats["edge_len"] and all(v >= 0.0 for v in feats["edge_len"])


# ---- knob unit locks -----------------------------------------------------


def pytest_drift_knob_unit_locks(tmp_path):
    d = str(tmp_path)
    with _env(HYDRAGNN_DRIFT_WINDOW="0"):
        assert DriftDetector.from_env(d) is None  # 0 = detection off
    with _env(HYDRAGNN_DRIFT_WINDOW="banana"):
        with pytest.raises(ValueError, match="HYDRAGNN_DRIFT_WINDOW"):
            DriftDetector.from_env(d)
    with _env(HYDRAGNN_DRIFT_WINDOW="16", HYDRAGNN_DRIFT_RAISE="0"):
        with pytest.raises(ValueError, match="HYDRAGNN_DRIFT_RAISE"):
            DriftDetector.from_env(d)
    with _env(HYDRAGNN_DRIFT_WINDOW="16", HYDRAGNN_DRIFT_PSI="nan"):
        with pytest.raises(ValueError, match="HYDRAGNN_DRIFT_PSI"):
            DriftDetector.from_env(d)
    with _env(HYDRAGNN_DRIFT_WINDOW="16", HYDRAGNN_DRIFT_BINS="4"):
        with pytest.raises(ValueError, match="HYDRAGNN_DRIFT_BINS"):
            DriftDetector.from_env(d)
    with _env(
        HYDRAGNN_DRIFT_WINDOW="16", HYDRAGNN_DRIFT_PSI="0.1",
        HYDRAGNN_DRIFT_KS="0.2", HYDRAGNN_DRIFT_RAISE="3",
        HYDRAGNN_DRIFT_CLEAR="4", HYDRAGNN_DRIFT_BINS="32",
    ):
        det = DriftDetector.from_env(d)
        assert (det.window, det.psi_threshold, det.ks_threshold,
                det.raise_after, det.clear_after, det.max_bins) == (
            16, 0.1, 0.2, 3, 4, 32)


def pytest_uncertainty_knob_unit_locks():
    with _env(HYDRAGNN_UNC_SAMPLES=None):
        assert UncertaintyScorer.from_env() is None  # unset = off
    with _env(HYDRAGNN_UNC_SAMPLES="0"):
        assert UncertaintyScorer.from_env() is None
    with _env(HYDRAGNN_UNC_SAMPLES="1"):
        with pytest.raises(ValueError, match="HYDRAGNN_UNC_SAMPLES"):
            UncertaintyScorer.from_env()
    with _env(HYDRAGNN_UNC_SAMPLES="two"):
        with pytest.raises(ValueError, match="HYDRAGNN_UNC_SAMPLES"):
            UncertaintyScorer.from_env()
    with _env(HYDRAGNN_UNC_SAMPLES="3", HYDRAGNN_UNC_MODE="bayes"):
        with pytest.raises(ValueError, match="HYDRAGNN_UNC_MODE"):
            UncertaintyScorer.from_env()
    with _env(HYDRAGNN_UNC_SAMPLES="3", HYDRAGNN_UNC_MODE="ensemble",
              HYDRAGNN_UNC_SEED="9"):
        sc = UncertaintyScorer.from_env()
        assert (sc.mode, sc.samples, sc.seed) == ("ensemble", 3, 9)


def pytest_feedback_knob_unit_locks(tmp_path):
    with _env(HYDRAGNN_FEEDBACK_DIR=None):
        assert FeedbackSink.from_env() is None  # unset = sink off
    d = str(tmp_path / "queue")
    with _env(HYDRAGNN_FEEDBACK_DIR=d, HYDRAGNN_FEEDBACK_MAX_GRAPHS="0"):
        with pytest.raises(
            ValueError, match="HYDRAGNN_FEEDBACK_MAX_GRAPHS"
        ):
            FeedbackSink.from_env()
    with _env(HYDRAGNN_FEEDBACK_DIR=d, HYDRAGNN_FEEDBACK_MIN_UNC="nan"):
        with pytest.raises(ValueError, match="HYDRAGNN_FEEDBACK_MIN_UNC"):
            FeedbackSink.from_env()
    with _env(HYDRAGNN_FEEDBACK_DIR=d, HYDRAGNN_FEEDBACK_MAX_GRAPHS="7",
              HYDRAGNN_FEEDBACK_MAX_PACKS="2",
              HYDRAGNN_FEEDBACK_MIN_UNC="0.5"):
        sink = FeedbackSink.from_env()
        assert (sink.queue_dir, sink.max_graphs, sink.max_packs,
                sink.min_unc) == (d, 7, 2, 0.5)


def pytest_canary_unc_ratio_knob_unit_lock():
    with _env(HYDRAGNN_CANARY_MAX_UNC_RATIO=None):
        assert CanaryGates.from_env().max_unc_ratio is None  # gate off
    with _env(HYDRAGNN_CANARY_MAX_UNC_RATIO="-1"):
        with pytest.raises(
            ValueError, match="HYDRAGNN_CANARY_MAX_UNC_RATIO"
        ):
            CanaryGates.from_env()
    with _env(HYDRAGNN_CANARY_MAX_UNC_RATIO="2.5"):
        assert CanaryGates.from_env().max_unc_ratio == 2.5


# ---- canary uncertainty veto ---------------------------------------------


def _stats_with_unc(live_unc, canary_unc, n=6):
    stats = _CandidateStats()
    heads = [np.ones((4,), np.float32)]
    for _ in range(n):
        assert stats.add_sample(
            heads, heads, bucket=0, live_latency_s=0.01,
            canary_latency_s=0.01, live_unc=live_unc,
            canary_unc=canary_unc,
        )
    return stats.snapshot()


def pytest_canary_uncertainty_veto():
    gates = CanaryGates(
        min_samples=6, min_bucket_samples=4, max_unc_ratio=2.0
    )
    # canary 5x noisier than live: reject, and the failure names the gate
    snap = _stats_with_unc([0.01], [0.05])
    verdict = evaluate_gates(snap, gates)
    assert verdict["verdict"] == "reject"
    assert any("uncertainty" in f for f in verdict["failures"])
    # within the ratio: promote
    assert evaluate_gates(
        _stats_with_unc([0.01], [0.015]), gates
    )["verdict"] == "promote"
    # gate off (max_unc_ratio None) ignores the same evidence
    off = CanaryGates(min_samples=6, min_bucket_samples=4)
    assert evaluate_gates(snap, off)["verdict"] == "promote"
    # no uncertainty evidence (scorer not running): gate skips
    assert evaluate_gates(
        _stats_with_unc(None, None), gates
    )["verdict"] == "promote"
    # an old snapshot dict without the "uncertainty" key: no KeyError
    legacy = dict(snap)
    del legacy["uncertainty"]
    assert evaluate_gates(legacy, gates)["verdict"] == "promote"
    # below the per-side sample floor: not enough evidence to veto
    small = _stats_with_unc([0.01], [0.05], n=3)
    assert evaluate_gates(
        small, CanaryGates(min_samples=2, min_bucket_samples=4,
                           max_unc_ratio=2.0)
    )["verdict"] == "promote"


# ---- feedback sink -------------------------------------------------------


def _permuted_copy(g, rng):
    """Same graph, relabeled nodes + shuffled edge columns — the
    canonical key must not move."""
    n = g.x.shape[0]
    perm = rng.permutation(n)  # perm[old] = new label
    h = g.clone()
    inv = np.empty(n, np.int64)
    inv[perm] = np.arange(n)
    h.x = g.x[inv]
    h.pos = g.pos[inv]
    ei = perm[g.edge_index]
    cols = rng.permutation(ei.shape[1])
    h.edge_index = ei[:, cols]
    return h


def pytest_canonical_key_invariant_under_permutation():
    rng = np.random.default_rng(31)
    g = _graph(14, rng, with_targets=False)
    assert canonical_graph_key(_permuted_copy(g, rng)) == \
        canonical_graph_key(g)
    other = _graph(14, np.random.default_rng(32), with_targets=False)
    assert canonical_graph_key(other) != canonical_graph_key(g)


def pytest_feedback_sink_dedups_permuted_duplicates(tmp_path):
    rng = np.random.default_rng(37)
    sink = FeedbackSink(str(tmp_path / "queue"), max_graphs=64)
    g = _graph(12, rng, with_targets=False)
    assert sink.offer(g, drifted=True) is True
    assert sink.offer(_permuted_copy(g, rng), drifted=True) is False
    assert sink.offer(_permuted_copy(g, rng), drifted=True) is False
    assert sink.offer(
        _graph(12, np.random.default_rng(38), with_targets=False),
        drifted=True,
    ) is True
    st = sink.stats()
    assert st["accepted"] == 2 and st["deduped"] == 2
    # admission policy: neither drifted nor above min_unc = not buffered
    quiet = FeedbackSink(str(tmp_path / "q2"), min_unc=0.5)
    assert quiet.offer(g, uncertainty=[0.1]) is False
    assert quiet.offer(g, uncertainty=[0.9]) is True
    assert quiet.offer(g, uncertainty=[float("nan")]) is False


def pytest_feedback_sink_roundtrips_through_shardstore_mix(tmp_path):
    """The queue dir is a REAL StreamSource input: flushed packs read
    back through ShardStoreSource into a WeightedMix with every array
    bitwise intact."""
    from hydragnn_tpu.data.stream.mix import WeightedMix
    from hydragnn_tpu.data.stream.source import ShardStoreSource

    rng = np.random.default_rng(41)
    qdir = str(tmp_path / "queue")
    sink = FeedbackSink(qdir, max_graphs=3, max_packs=4)
    originals = {}
    for seed in range(5):
        g = _graph(
            int(rng.integers(6, 16)), np.random.default_rng(100 + seed),
            with_targets=False,
        )
        assert sink.offer(g, drifted=True)
        originals[canonical_graph_key(g)] = g
    sink.close()  # flush the partial tail pack
    st = sink.stats()
    assert st["graphs"] == 5 and st["packs"] == 2 and st["buffered"] == 0

    src = ShardStoreSource(qdir)
    mix = WeightedMix([src], seed=1)
    got = [d for _, d in mix]
    assert len(got) == 5
    for d in got:
        g = originals.pop(canonical_graph_key(d))
        assert d.x.tobytes() == g.x.tobytes()
        assert d.pos.tobytes() == g.pos.tobytes()
        assert d.edge_index.tobytes() == g.edge_index.tobytes()
    assert not originals  # every offered graph came back exactly once


def pytest_feedback_sink_bounded_packs(tmp_path):
    sink = FeedbackSink(str(tmp_path / "q"), max_graphs=1, max_packs=2)
    for seed in range(4):
        sink.offer(
            _graph(8, np.random.default_rng(200 + seed),
                   with_targets=False),
            drifted=True,
        )
    st = sink.stats()
    assert st["packs"] == 2 and st["dropped"] == 2  # disk stays bounded
    assert sink.offer(None, drifted=True) is False  # never raises


# ---- uncertainty scorer (compile-counter-verified) -----------------------

_GAT = {}


def _gat_harness():
    """GAT is the dropout-bearing stack (attention dropout 0.25), so MC
    dropout produces genuinely nonzero variance."""
    if _GAT:
        return _GAT
    rng = np.random.default_rng(7)
    samples = [_graph(int(n), rng) for n in rng.integers(4, 24, 24)]
    samples.append(_graph(24, rng))  # pin the top bucket's capacity
    model = create_model_config(arch_config("GAT"))
    trainer = Trainer(
        model, {"Optimizer": {"type": "AdamW", "learning_rate": 1e-3}}
    )
    plan = plan_from_samples(samples, max_batch_graphs=4, num_buckets=2)
    init_batch, _ = plan.pack([samples[0]], 0)
    state = trainer.init_state(init_batch)
    registry = ModelRegistry()
    registry.register("gat", model, state.params, state.batch_stats)
    _GAT.update(
        samples=samples, model=model, state=state, registry=registry,
        plan=plan,
    )
    return _GAT


@pytest.mark.slow
def pytest_scorer_zero_steady_state_recompiles():
    """The tentpole compile contract: with dropout scoring on, warmup
    compiles exactly 2 programs per bucket (predict + score) and the
    counter stays FLAT across mixed traffic; every response carries
    per-head variance, nonzero for a dropout-bearing model."""
    h = _gat_harness()
    scorer = UncertaintyScorer(mode="dropout", samples=3, seed=0)
    with InferenceServer(
        h["registry"], h["plan"], max_wait_s=0.002, scorer=scorer
    ) as server:
        warm = server.metrics.compiles_total
        assert warm == h["plan"].num_buckets * 2
        rng = np.random.default_rng(3)
        futs = [
            server.submit(_graph(int(n), rng, with_targets=False))
            for n in rng.integers(4, 24, 40)
        ]
        for f in futs:
            heads = f.result(120)
            assert all(np.isfinite(o).all() for o in heads)
        assert server.metrics.compiles_total == warm  # zero recompiles
        uncs = [f.uncertainty for f in futs]
        assert all(u is not None and len(u) == 2 for u in uncs)
        assert all(
            v is None or (math.isfinite(v) and v >= 0.0)
            for u in uncs for v in u
        )
        assert any(v and v > 0.0 for u in uncs for v in u)
        q = server.health()["quality"]
        assert q["mode"] == "dropout" and q["scored"] >= 40
        assert q["quantiles"]  # per-(tenant,bucket,head) sketches filled


@pytest.mark.slow
def pytest_scorer_ensemble_variance_across_versions():
    """Ensemble mode: two registered versions with different weights
    disagree, so the stacked-member variance is nonzero; the scoring
    signature tracks the member set (recompile only at promote)."""
    import jax

    h = _gat_harness()
    reg = ModelRegistry()
    reg.register("gat", h["model"], h["state"].params,
                 h["state"].batch_stats)
    bumped = jax.tree_util.tree_map(
        lambda a: np.asarray(a) * 1.05 + 0.02, h["state"].params
    )
    reg.register("gat", h["model"], bumped, h["state"].batch_stats)
    scorer = UncertaintyScorer(mode="ensemble", samples=2, registry=reg)
    e1, e2 = reg.get("gat", 1), reg.get("gat", 2)
    assert scorer.signature(e1) != scorer.signature(e2)

    g = _graph(8, np.random.default_rng(9), with_targets=False)
    batch, _ = h["plan"].pack([g], 0)
    batch = jax.tree_util.tree_map(np.asarray, batch)
    variances = [np.asarray(v) for v in jax.device_get(
        list(scorer.dispatch(e2, batch))
    )]
    assert len(variances) == 2
    assert all(np.isfinite(v).all() and (v >= 0.0).all()
               for v in variances)
    assert any(float(np.max(v)) > 0.0 for v in variances)


# ---- report / ledger tolerate pre-quality streams ------------------------


def pytest_reports_tolerate_streams_without_quality_events(tmp_path):
    from hydragnn_tpu.obs import ledger as ledger_mod
    from hydragnn_tpu.obs import report as report_mod
    from hydragnn_tpu.obs.__main__ import main as obs_main

    log = RunEventLog(str(tmp_path / "events.jsonl"))
    log.emit("epoch", epoch=0, train_loss=1.0, val_loss=1.1,
             test_loss=1.2, mode="f32")
    report = report_mod.build_report(report_mod.load_events(log.path))
    assert report["quality"] is None  # old stream: section omitted
    for render in (report_mod.render_text, report_mod.render_markdown):
        assert "model quality" not in render(report).lower()

    fleet = ledger_mod.build_fleet_report(str(tmp_path))
    assert fleet["quality"] is None
    ledger_mod.render_fleet_text(fleet)
    ledger_mod.render_fleet_markdown(fleet)
    # `obs drift` on a quality-free dir: usage exit (2), not a crash
    assert obs_main(["drift", str(tmp_path)]) == 2


def pytest_reports_surface_quality_section(tmp_path):
    from hydragnn_tpu.obs import ledger as ledger_mod
    from hydragnn_tpu.obs import report as report_mod
    from hydragnn_tpu.obs.__main__ import main as obs_main

    log = RunEventLog(str(tmp_path / "events.jsonl"))
    det = DriftDetector(
        str(tmp_path), window=32, raise_after=1, emit=log.emit
    )
    det.on_activate(1)
    rng = np.random.default_rng(43)
    base = rng.normal(0.0, 1.0, 32)
    for vals in (base, base + 9.0):  # bootstrap, then one raising window
        for v in vals:
            det.observe("acme", heads=[np.asarray([v])],
                        uncertainty=[abs(float(v)) * 0.01])
    assert det.alert_active("acme")

    report = report_mod.build_report(report_mod.load_events(log.path))
    assert report["quality"] and report["quality"]["alerts_active"]
    assert "model quality" in report_mod.render_text(report)
    assert "ACTIVE ALERT" in report_mod.render_text(report)
    fleet = ledger_mod.build_fleet_report(str(tmp_path))
    assert fleet["quality"]["alerts_active"]
    assert "model quality" in ledger_mod.render_fleet_text(fleet)
    assert obs_main(["drift", str(tmp_path)]) == 0
    assert obs_main(["drift", str(tmp_path), "--format", "json"]) == 0
    # prometheus families present for scrapes
    assert "hydragnn_drift_score" in det.render_prometheus()
