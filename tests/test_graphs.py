"""End-to-end accuracy tests through the full public API.

Mirrors the reference's core test strategy (``tests/test_graphs.py:25-189``):
train each model on the deterministic synthetic dataset via
``hydragnn_tpu.run_training``, reload + predict via ``run_prediction``, and
assert per-head RMSE and sample MAE against per-model ceilings.
"""

import json
import os
import sys
import tempfile

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import hydragnn_tpu
from hydragnn_tpu.utils.config import merge_config
from synthetic import deterministic_graph_data

# same ceilings as the reference CI (tests/test_graphs.py:139-156)
THRESHOLDS = {
    "SAGE": [0.20, 0.20],
    "PNA": [0.20, 0.20],
    "MFC": [0.20, 0.20],
    "GIN": [0.25, 0.20],
    "GAT": [0.60, 0.70],
    "CGCNN": [0.50, 0.40],
    "SchNet": [0.20, 0.20],
    "DimeNet": [0.50, 0.50],
    "EGNN": [0.20, 0.20],
}

_WORKDIR = None


def _workdir():
    global _WORKDIR
    if _WORKDIR is None:
        _WORKDIR = tempfile.mkdtemp(prefix="hydragnn_tpu_ci_")
    return _WORKDIR


def unittest_train_model(
    model_type, ci_input, use_lengths, overwrite_config=None, num_samples_tot=500
):
    workdir = _workdir()
    os.environ["SERIALIZED_DATA_PATH"] = workdir
    cwd = os.getcwd()
    os.chdir(workdir)
    try:
        config_file = os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "inputs", ci_input
        )
        with open(config_file, "r") as f:
            config = json.load(f)
        config["NeuralNetwork"]["Architecture"]["model_type"] = model_type
        if overwrite_config:
            config = merge_config(config, overwrite_config)
        if use_lengths:
            config["NeuralNetwork"]["Architecture"]["edge_features"] = ["lengths"]
        # MFC favors graph-level over node-level heads in the multihead CI run
        if model_type == "MFC" and ci_input == "ci_multihead.json":
            config["NeuralNetwork"]["Architecture"]["task_weights"][0] = 2

        perc_train = config["NeuralNetwork"]["Training"]["perc_train"]
        for name, rel in config["Dataset"]["path"].items():
            if name == "total":
                num = num_samples_tot
            elif name == "train":
                num = int(num_samples_tot * perc_train)
            else:
                num = int(num_samples_tot * (1 - perc_train) * 0.5)
            # key the cached dataset dir by its size: tests with different
            # num_samples_tot must not silently share (and therefore train
            # on whichever size generated first)
            data_path = os.path.join(workdir, f"{rel}_{num}")
            config["Dataset"]["path"][name] = data_path
            if not os.path.exists(data_path) or not os.listdir(data_path):
                deterministic_graph_data(data_path, number_configurations=num)

        import copy

        hydragnn_tpu.run_training(copy.deepcopy(config))
        error, error_rmse_task, true_values, predicted_values = (
            hydragnn_tpu.run_prediction(copy.deepcopy(config))
        )

        thresholds = dict(THRESHOLDS)
        if use_lengths and "vector" not in ci_input:
            thresholds["CGCNN"] = [0.175, 0.175]
            thresholds["PNA"] = [0.10, 0.10]
        if use_lengths and "vector" in ci_input:
            thresholds["PNA"] = [0.2, 0.15]
        if ci_input == "ci_conv_head.json":
            thresholds["GIN"] = [0.25, 0.40]

        for ihead in range(len(true_values)):
            assert (
                error_rmse_task[ihead] < thresholds[model_type][0]
            ), f"head {ihead} RMSE {error_rmse_task[ihead]} for {model_type}"
            mae = float(
                np.abs(
                    np.asarray(true_values[ihead])
                    - np.asarray(predicted_values[ihead])
                ).mean()
            )
            assert (
                mae < thresholds[model_type][1]
            ), f"head {ihead} sample MAE {mae} for {model_type}"
        assert error < thresholds[model_type][0], f"total error {error}"
    finally:
        os.chdir(cwd)


ALL_MODELS = ["SAGE", "GIN", "GAT", "MFC", "PNA", "CGCNN", "SchNet", "DimeNet", "EGNN"]
FULL = int(os.getenv("HYDRAGNN_FULL_TEST", "0")) == 1

# Default CI keeps one run per feature axis + the fast models; set
# HYDRAGNN_FULL_TEST=1 for the reference's full 33-run matrix
# (tests/test_graphs.py:193-224).
# Default-tier e2e coverage: every model trains to the accuracy ceilings
# at least once — PNA+SchNet (singlehead), PNA+GAT (multihead), CGCNN
# (lengths), EGNN (equivariant), GIN+MFC (conv head), SAGE+DimeNet
# (singlehead additions below).
_DEFAULT_SINGLEHEAD = ["PNA", "SchNet", "SAGE", "DimeNet"]
_DEFAULT_MULTIHEAD = ["PNA", "GAT"]


@pytest.mark.parametrize(
    "model_type", ALL_MODELS if FULL else _DEFAULT_SINGLEHEAD
)
def pytest_train_model(model_type):
    unittest_train_model(model_type, "ci.json", False)


@pytest.mark.parametrize(
    "model_type", ALL_MODELS if FULL else _DEFAULT_MULTIHEAD
)
def pytest_train_model_multihead(model_type):
    unittest_train_model(model_type, "ci_multihead.json", False)


@pytest.mark.parametrize(
    "model_type",
    ["PNA", "CGCNN", "SchNet", "EGNN"] if FULL else ["PNA", "CGCNN"],
)
def pytest_train_model_lengths(model_type):
    unittest_train_model(model_type, "ci.json", True)


@pytest.mark.parametrize("model_type", ["EGNN", "SchNet"] if FULL else ["EGNN"])
def pytest_train_equivariant_model(model_type):
    unittest_train_model(model_type, "ci_equivariant.json", False)


@pytest.mark.parametrize("model_type", ["PNA"])
def pytest_train_model_vectoroutput(model_type):
    unittest_train_model(model_type, "ci_vectoroutput.json", True)


@pytest.mark.parametrize(
    "model_type",
    ["SAGE", "GIN", "GAT", "MFC", "PNA", "SchNet", "DimeNet", "EGNN"]
    if FULL
    else ["GIN", "MFC"],
)
def pytest_train_model_conv_head(model_type):
    unittest_train_model(model_type, "ci_conv_head.json", False)


@pytest.mark.parametrize("model_type", ["PNA"])
def pytest_train_model_multistep_dispatch(model_type):
    """steps_per_dispatch (scan multi-step) through the public API must hit
    the same accuracy ceilings as the per-batch streaming path."""
    unittest_train_model(
        model_type,
        "ci.json",
        False,
        overwrite_config={
            "NeuralNetwork": {"Training": {"steps_per_dispatch": 4}}
        },
        num_samples_tot=300,
    )


@pytest.mark.parametrize("model_type", ["PNA", "DimeNet"])
def pytest_train_model_dense_aggregation(model_type):
    """Scatter-free dense neighbor-list aggregation (dense_aggregation:
    true) through the public API must hit the same accuracy ceilings as
    the segment path — it is the performance mode for MXU-scale configs
    (ops/dense_agg.py). DimeNet's dense mode is the bmm-triplet path
    (models/dimenet.py): no T axis, no host-side compute_triplets."""
    unittest_train_model(
        model_type,
        "ci.json",
        False,
        overwrite_config={
            "NeuralNetwork": {"Architecture": {"dense_aggregation": True}}
        },
        num_samples_tot=300,
    )


@pytest.mark.skipif(not FULL, reason="auto-dense e2e: FULL tier")
def pytest_train_model_auto_dense_no_flag():
    """At MXU widths the aggregation path is chosen AUTOMATICALLY (no
    dense_aggregation key anywhere): the measured-crossover policy must
    route this hidden-96 MFC run onto the dense path and still hit the
    reference ceilings through the public API."""
    unittest_train_model(
        "MFC",
        "ci.json",
        False,
        overwrite_config={
            "NeuralNetwork": {"Architecture": {"hidden_dim": 96}}
        },
        num_samples_tot=300,
    )


@pytest.mark.parametrize("model_type", ["PNA"])
def pytest_train_model_nll_loss(model_type):
    """Uncertainty-weighted NLL multi-task loss (the mode the reference
    leaves unfinished): heads grow a log-variance channel, training through
    the public API still hits the reference accuracy ceilings."""
    unittest_train_model(
        model_type,
        "ci.json",
        False,
        overwrite_config={
            "NeuralNetwork": {"Architecture": {"ilossweights_nll": 1}}
        },
        num_samples_tot=300,
    )


@pytest.mark.parametrize("model_type", ["PNA"])
def pytest_train_model_whole_training_dispatch(model_type):
    """Device-resident + chunked whole-training dispatch (fit_staged) must
    hit the same accuracy ceilings through the public run_training API."""
    unittest_train_model(
        model_type,
        "ci.json",
        False,
        overwrite_config={
            "NeuralNetwork": {
                "Training": {
                    "device_resident_dataset": True,
                    "fit_chunk_epochs": 10,
                }
            }
        },
        num_samples_tot=300,
    )


@pytest.mark.skipif(not FULL, reason="cross-mode matrix: FULL tier")
@pytest.mark.parametrize(
    "training_overwrite",
    [
        {"device_resident_dataset": True, "fit_chunk_epochs": 10},
        {"steps_per_dispatch": 4},
    ],
    ids=["whole_training", "multistep"],
)
def pytest_train_model_dense_cross_modes(training_overwrite):
    """dense_aggregation composes with the whole-training and multi-step
    dispatch modes (the extras ride stage_batches/stack_batches): same
    reference ceilings through the public API."""
    unittest_train_model(
        "PNA",
        "ci.json",
        False,
        overwrite_config={
            "NeuralNetwork": {
                "Architecture": {"dense_aggregation": True},
                "Training": training_overwrite,
            }
        },
        num_samples_tot=300,
    )
