"""numlint (analysis --suite=numerics): the numerics & kernel-safety suite.

Per rule: a bad snippet that must flag and a good snippet that must not,
plus the numlint suppression tag (and its one-line scope), the
``--list-rules`` catalog for the fourth suite, the baseline ratchet, and
the acceptance regressions — the merged tree runs clean against the
committed (empty) ``.numlint-baseline.json``, and reintroducing an
unguarded exp or an unmasked gather fails the gate.

Everything here is pure-AST: no jax execution. The compiled-memory half
of numlint (``analysis/mem.py``) is covered by
``tests/test_numlint_mem.py`` and the CI ratchet smoke; the runtime half
(``nan_sentinel``) by the sentinel tests in the same file.
"""

import json
import os
import textwrap

from hydragnn_tpu.analysis import analyze_paths
from hydragnn_tpu.analysis.__main__ import main as lint_main
from hydragnn_tpu.analysis.core import rules_in_suite

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

NUMERICS_RULES = {
    "low-precision-accum",
    "precision-policy-bypass",
    "unguarded-exp-log-div",
    "nan-unsafe-where",
    "unmasked-gather-id",
    "pallas-vmem-unbounded",
}


def _lint(tmp_path, files, select=None):
    for rel, src in files.items():
        path = tmp_path / rel
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(textwrap.dedent(src))
    return analyze_paths(
        [str(tmp_path)],
        root=str(tmp_path),
        select=select or rules_in_suite("numerics"),
    ).findings


def _rules_of(findings):
    return sorted({f.rule for f in findings})


def pytest_numerics_suite_registry():
    assert rules_in_suite("numerics") == NUMERICS_RULES


# ---- low-precision-accum --------------------------------------------------

_ACCUM_BAD = """
    import jax
    import jax.numpy as jnp

    def dense_sum(h, nbr_mask):
        hm = jnp.where(nbr_mask[..., None], h, 0.0)
        return hm.sum(axis=1)

    def scatter(x, gid, n):
        return jax.ops.segment_sum(x, gid, num_segments=n)

    def prefix(w):
        return jnp.cumsum(w)

    def contract(a, b):
        return jnp.matmul(a, b)
"""

_ACCUM_GOOD = """
    import jax
    import jax.numpy as jnp
    import numpy as np

    def dense_sum(h, nbr_mask):
        hm = jnp.where(nbr_mask[..., None], h, 0.0).astype(jnp.float32)
        return hm.sum(axis=1).astype(h.dtype)

    def scatter(x, gid, n):
        return jax.ops.segment_sum(
            x.astype(jnp.float32), gid, num_segments=n
        )

    def prefix(w):
        return jnp.cumsum(w, dtype=jnp.float32)

    def offsets(batch, deg):
        # integer count prefix sums and host numpy never run bf16
        a = jnp.cumsum(batch.n_node)
        b = np.cumsum(deg)
        return a, b

    def degree(nbr_mask):
        return nbr_mask.sum(axis=1)  # bool mask -> int accumulation

    def contract(a, b):
        return jnp.matmul(a, b, preferred_element_type=jnp.float32)

    def agg_kernel(h_ref, o_ref):
        # kernel bodies see pre-masked f32 refs by the wrapper contract
        o_ref[...] = h_ref[...].sum(axis=1)
"""


def pytest_low_precision_accum(tmp_path):
    bad = _lint(
        tmp_path, {"ops/bad_agg.py": _ACCUM_BAD},
        select={"low-precision-accum"},
    )
    assert len(bad) == 4, [(f.line, f.message) for f in bad]
    assert _rules_of(bad) == ["low-precision-accum"]
    good = _lint(
        tmp_path, {"ops/good_agg.py": _ACCUM_GOOD},
        select={"low-precision-accum"},
    )
    assert [f for f in good if f.path.endswith("good_agg.py")] == []


def pytest_accum_scoped_to_numeric_dirs(tmp_path):
    # the same accumulation in serve/ (host orchestration) is exempt
    found = _lint(
        tmp_path, {"serve/router.py": _ACCUM_BAD},
        select={"low-precision-accum"},
    )
    assert found == []


# ---- precision-policy-bypass ----------------------------------------------

_BYPASS_BAD = """
    import jax.numpy as jnp

    def pack(x):
        return x.astype(jnp.bfloat16)

    def alloc(n):
        return jnp.zeros((n,), dtype=jnp.float16)
"""


def pytest_precision_policy_bypass(tmp_path):
    bad = _lint(
        tmp_path, {"serve/pack.py": _BYPASS_BAD},
        select={"precision-policy-bypass"},
    )
    assert len(bad) == 2, [(f.line, f.message) for f in bad]
    # the sanctioned application site is exempt: steps.py casts per the
    # resolve_precision policy
    good = _lint(
        tmp_path, {"train/steps.py": _BYPASS_BAD},
        select={"precision-policy-bypass"},
    )
    assert [f for f in good if f.path.endswith("steps.py")] == []


# ---- unguarded-exp-log-div ------------------------------------------------

_EXPLOG_BAD = """
    import jax.numpy as jnp

    def f(x, h):
        e = jnp.exp(x)
        l = jnp.log(x)
        d = x - h
        r = jnp.sqrt(d)
        return e + l + r + x / h.sum(1)
"""

_EXPLOG_GOOD = """
    import jax.numpy as jnp

    def f(x, h, eps):
        e = jnp.exp(jnp.minimum(x, 0.0))
        l = jnp.log(x + 1e-9)
        d = x - h
        r = jnp.sqrt(d + eps)
        w = jnp.sqrt(x)  # plain width/fan-in: never triggers
        s = jnp.exp(x - x.max())  # max-shifted softmax idiom
        return e + l + r + w + s + x / jnp.maximum(h.sum(1), 1.0)
"""


def pytest_unguarded_exp_log_div(tmp_path):
    bad = _lint(
        tmp_path, {"models/act.py": _EXPLOG_BAD},
        select={"unguarded-exp-log-div"},
    )
    assert len(bad) == 4, [(f.line, f.message) for f in bad]
    good = _lint(
        tmp_path, {"models/act_ok.py": _EXPLOG_GOOD},
        select={"unguarded-exp-log-div"},
    )
    assert [f for f in good if f.path.endswith("act_ok.py")] == []


def pytest_div_by_builtin_sum_is_exempt(tmp_path):
    # host-side config math: the Python builtin sum() is not an array
    # reduction that can hit zero on padded slots
    found = _lint(
        tmp_path,
        {
            "models/weights.py": """
            def norm(ws):
                s = sum(abs(w) for w in ws)
                return [w / s for w in ws]
            """,
        },
        select={"unguarded-exp-log-div"},
    )
    assert found == []


# ---- nan-unsafe-where -----------------------------------------------------


def pytest_nan_unsafe_where(tmp_path):
    bad = _lint(
        tmp_path,
        {
            "models/safe.py": """
            import jax.numpy as jnp

            def f(x):
                return jnp.where(x > 0, jnp.sqrt(x), 0.0)
            """,
        },
        select={"nan-unsafe-where"},
    )
    assert len(bad) == 1
    good = _lint(
        tmp_path,
        {
            "models/safe_ok.py": """
            import jax.numpy as jnp

            def f(x):
                p = x > 0
                return jnp.where(p, jnp.sqrt(jnp.where(p, x, 1.0)), 0.0)
            """,
        },
        select={"nan-unsafe-where"},
    )
    assert [f for f in good if f.path.endswith("safe_ok.py")] == []


# ---- unmasked-gather-id ---------------------------------------------------

_GATHER_BAD = """
    import jax
    import jax.numpy as jnp

    def gather(x, nbr_idx):
        rows = x[nbr_idx]
        return rows

    def scatter(x, gid):
        return jax.ops.segment_sum(x, gid)
"""

_GATHER_GOOD = """
    import jax
    import jax.numpy as jnp

    def gather(x, nbr_idx, nbr_mask):
        rows = jnp.where(nbr_mask[..., None], x[nbr_idx], 0.0)
        return rows

    def clipped(x, raw_idx, n):
        idx = jnp.clip(raw_idx, 0, n - 1)
        return x[idx]

    def consumed(x, nbr_idx, nbr_mask):
        return dense_sum(x[nbr_idx], nbr_mask)

    def scatter(x, gid, n):
        return jax.ops.segment_sum(x, gid, num_segments=n)
"""


def pytest_unmasked_gather_id(tmp_path):
    bad = _lint(
        tmp_path, {"ops/gath.py": _GATHER_BAD},
        select={"unmasked-gather-id"},
    )
    assert len(bad) == 2, [(f.line, f.message) for f in bad]
    good = _lint(
        tmp_path, {"ops/gath_ok.py": _GATHER_GOOD},
        select={"unmasked-gather-id"},
    )
    assert [f for f in good if f.path.endswith("gath_ok.py")] == []


def pytest_gather_rule_scoped_to_ops(tmp_path):
    # models/ gathers go through the graph/segment wrappers; the raw-id
    # contract is an ops/-only discipline
    found = _lint(
        tmp_path, {"models/net.py": _GATHER_BAD},
        select={"unmasked-gather-id"},
    )
    assert _rules_of(found) == []


# ---- pallas-vmem-unbounded ------------------------------------------------

_PALLAS_BAD = """
    from jax.experimental import pallas as pl

    def run(x):
        return pl.pallas_call(_kern, out_shape=x)(x)
"""

_PALLAS_GOOD = """
    from jax.experimental import pallas as pl

    _VMEM_BUDGET = 64 * 1024 * 1024

    def run_enabled(working_set):
        return working_set < _VMEM_BUDGET

    def run(x):
        return pl.pallas_call(_kern, out_shape=x)(x)
"""


def pytest_pallas_vmem_unbounded(tmp_path):
    bad = _lint(
        tmp_path, {"ops/kern.py": _PALLAS_BAD},
        select={"pallas-vmem-unbounded"},
    )
    assert len(bad) == 1
    good = _lint(
        tmp_path, {"ops/kern_ok.py": _PALLAS_GOOD},
        select={"pallas-vmem-unbounded"},
    )
    assert [f for f in good if f.path.endswith("kern_ok.py")] == []


# ---- suppression ----------------------------------------------------------


def pytest_numlint_suppression_scope(tmp_path):
    # trailing on the flagged line and standalone directly above both
    # suppress; a directive two lines up does NOT leak downward
    found = _lint(
        tmp_path,
        {
            "models/sup.py": """
            import jax.numpy as jnp

            def f(x):
                a = jnp.exp(x)  # numlint: disable=unguarded-exp-log-div
                # numlint: disable=unguarded-exp-log-div
                b = jnp.exp(x)
                # numlint: disable=unguarded-exp-log-div
                pass
                c = jnp.exp(x)
                return a + b + c
            """,
        },
        select={"unguarded-exp-log-div"},
    )
    assert len(found) == 1 and found[0].line == 10


def pytest_suppressing_a_different_rule_does_not_cover(tmp_path):
    found = _lint(
        tmp_path,
        {
            "models/tag.py": """
            import jax.numpy as jnp

            def f(x):
                return jnp.exp(x)  # numlint: disable=nan-unsafe-where
            """,
        },
        select={"unguarded-exp-log-div"},
    )
    assert len(found) == 1


# ---- CLI: fourth suite, baseline ratchet ----------------------------------


def pytest_numerics_cli_gate_and_baseline(tmp_path, capsys):
    bad = tmp_path / "models" / "m.py"
    bad.parent.mkdir(parents=True)
    bad.write_text(
        "import jax.numpy as jnp\n\n"
        "def f(x):\n"
        "    return jnp.exp(x)\n"
    )
    # the suite gates on its findings
    assert lint_main([str(bad), "--suite=numerics"]) == 1
    capsys.readouterr()
    # a written baseline absorbs them...
    bl = tmp_path / "bl.json"
    assert (
        lint_main(
            [str(bad), "--suite=numerics", f"--write-baseline={bl}"]
        )
        == 0
    )
    assert (
        lint_main([str(bad), "--suite=numerics", f"--baseline={bl}"]) == 0
    )
    capsys.readouterr()
    # ...but a reintroduced NEW finding still fails the gate, named
    bad.write_text(
        bad.read_text() + "\n\ndef g(x):\n    return jnp.log(x)\n"
    )
    assert (
        lint_main(
            [
                str(bad), "--suite=numerics", f"--baseline={bl}",
                "--format=github",
            ]
        )
        == 1
    )
    out = capsys.readouterr().out
    assert "unguarded-exp-log-div" in out
    assert "log" in out


def pytest_list_rules_includes_numerics(capsys):
    assert lint_main(["--list-rules", "--suite=numerics"]) == 0
    listed = capsys.readouterr().out
    assert "suite numerics (numlint gate" in listed
    for name in NUMERICS_RULES:
        assert name in listed, name
    assert "suite jax" not in listed


# ---- acceptance -----------------------------------------------------------


def pytest_merged_tree_is_clean_for_numerics_suite():
    """`--suite=numerics` exits 0 on the committed tree: every true
    positive (unclamped exp in schnet, bare sqrt in dimenet/common,
    bf16-reachable accumulations in dense_agg/fused_mp) was FIXED, the
    two deliberate raw gathers carry justified suppressions, and the
    committed baseline is EMPTY."""
    paths = [
        os.path.join(REPO_ROOT, d)
        for d in ("hydragnn_tpu", "examples", "benchmarks")
    ]
    result = analyze_paths(
        paths, select=rules_in_suite("numerics"), root=REPO_ROOT
    )
    assert not result.findings, [
        f"{f.path}:{f.line}: {f.rule}" for f in result.findings
    ]
    bl = json.load(open(os.path.join(REPO_ROOT, ".numlint-baseline.json")))
    assert bl["findings"] == []
