"""numlint's compiled-memory ratchet (analysis/mem.py) + NaN sentinel.

Budget-level: fingerprint shape, save/load/version gate, the tolerance
semantics (growth past tolerance fails naming program + field + bytes,
a budgeted zero tolerates nothing, shrinkage and stale programs are
notes), and the injection regression — a synthetic HBM blow-up on one
program's temp/peak bytes MUST be caught.

Env knobs: ``HYDRAGNN_NUMLINT_MEM_TOLERANCE`` and
``HYDRAGNN_NAN_SENTINEL`` route through ``utils/envparse`` — a bad
value raises naming the variable, never a bare ``float()`` traceback.

Runtime: the :func:`~hydragnn_tpu.analysis.guards.nan_sentinel` harness
(origin localization to a named head/param subtree, raise vs report
modes, schema-gated ``nan_origin`` events) and one compiled e2e — two
real step programs' ``memory_analysis()`` fingerprinted, budgeted,
checked clean, then caught regressing.
"""

import json

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from hydragnn_tpu.analysis.mem import (
    BUDGET_VERSION,
    GATED_FIELDS,
    INJECTED_TEMP_BYTES,
    check_fingerprints,
    default_tolerance,
    fingerprint_memory,
    load_budget,
    prove_injection,
    save_budget,
)

_FP = {
    "argument_bytes": 1000,
    "output_bytes": 400,
    "temp_bytes": 2000,
    "alias_bytes": 0,
    "generated_code_bytes": 0,
    "peak_bytes": 3400,
}


def _programs():
    return {"train_step": dict(_FP), "eval_step": dict(_FP)}


# ---- budget roundtrip -----------------------------------------------------


def pytest_budget_roundtrip_and_version_gate(tmp_path):
    path = tmp_path / "mem.json"
    save_budget(str(path), _programs(), (4, 2), tolerance=0.25)
    budget = load_budget(str(path))
    assert budget["version"] == BUDGET_VERSION
    assert budget["mesh"]["shape"] == [4, 2]
    assert budget["tolerance"] == 0.25
    assert set(budget["programs"]) == {"train_step", "eval_step"}
    assert budget["programs"]["train_step"]["peak_bytes"] == 3400
    # a version-bumped budget must be regenerated, not reinterpreted
    doctored = dict(budget, version=BUDGET_VERSION + 1)
    path.write_text(json.dumps(doctored))
    with pytest.raises(ValueError, match="version"):
        load_budget(str(path))


# ---- tolerance semantics --------------------------------------------------


def pytest_check_semantics():
    budget = _programs()
    # identical fingerprints: clean
    v, n = check_fingerprints(_programs(), budget, tolerance=0.25)
    assert not v and not n
    # growth inside tolerance: clean
    ok = _programs()
    ok["train_step"]["temp_bytes"] = 2400  # +20% < 25%
    v, _ = check_fingerprints(ok, budget, tolerance=0.25)
    assert not v
    # growth past tolerance: violation naming program, field and bytes
    grown = _programs()
    grown["train_step"]["peak_bytes"] = 5000
    v, _ = check_fingerprints(grown, budget, tolerance=0.25)
    assert len(v) == 1
    assert "train_step" in v[0] and "peak_bytes" in v[0]
    assert "3400" in v[0] and "5000" in v[0]
    # a budgeted zero tolerates NOTHING: a program with no temp buffer
    # today cannot silently start materializing one
    zb = _programs()
    zb["eval_step"]["temp_bytes"] = 0
    zb["eval_step"]["peak_bytes"] = 1400
    now = _programs()
    now["eval_step"]["temp_bytes"] = 64
    now["eval_step"]["peak_bytes"] = 1400
    v, _ = check_fingerprints(now, zb, tolerance=0.25)
    assert any("eval_step" in x and "temp_bytes" in x for x in v)
    # shrinkage is a note (tighten the budget), not a violation
    small = _programs()
    small["train_step"]["temp_bytes"] = 100
    v, n = check_fingerprints(small, budget, tolerance=0.25)
    assert not v
    assert any("shrank" in x for x in n)
    # an unbudgeted program is a violation; a stale budgeted one a note
    v, n = check_fingerprints(
        {**_programs(), "fit_scan": dict(_FP)}, budget, tolerance=0.25
    )
    assert any("fit_scan" in x and "not in the memory budget" in x
               for x in v)
    v, n = check_fingerprints(
        {"train_step": dict(_FP)}, budget, tolerance=0.25
    )
    assert not v
    assert any("eval_step" in x and "stale" in x for x in n)


def pytest_injection_is_caught():
    assert prove_injection(_programs(), _programs(), tolerance=0.25)
    # a tolerance wide enough to swallow the synthetic blow-up means
    # the gate is NOT proving anything — the proof must say so
    huge = INJECTED_TEMP_BYTES * 10 / _FP["temp_bytes"]
    assert not prove_injection(_programs(), _programs(), tolerance=huge)


# ---- env knobs route through envparse -------------------------------------


def pytest_mem_tolerance_env_knob(monkeypatch):
    monkeypatch.delenv("HYDRAGNN_NUMLINT_MEM_TOLERANCE", raising=False)
    assert default_tolerance() == 0.25
    monkeypatch.setenv("HYDRAGNN_NUMLINT_MEM_TOLERANCE", "0.5")
    assert default_tolerance() == 0.5
    for bad in ("soon", "nan", "-0.5"):
        monkeypatch.setenv("HYDRAGNN_NUMLINT_MEM_TOLERANCE", bad)
        with pytest.raises(
            ValueError, match="HYDRAGNN_NUMLINT_MEM_TOLERANCE"
        ):
            default_tolerance()


def pytest_nan_sentinel_env_knob(monkeypatch):
    from hydragnn_tpu.utils.envparse import env_int

    monkeypatch.setenv("HYDRAGNN_NAN_SENTINEL", "yes")
    with pytest.raises(ValueError, match="HYDRAGNN_NAN_SENTINEL"):
        env_int("HYDRAGNN_NAN_SENTINEL", 0)
    monkeypatch.setenv("HYDRAGNN_NAN_SENTINEL", "1")
    assert env_int("HYDRAGNN_NAN_SENTINEL", 0) == 1


# ---- nan sentinel (the runtime half) --------------------------------------


def pytest_nonfinite_report_and_origin():
    from hydragnn_tpu.analysis.guards import nan_origin, nonfinite_report

    tree = {
        "params": {
            "head_energy": jnp.array([1.0, np.nan]),
            "head_forces": jnp.ones(3),
        },
        "loss": jnp.array(np.inf),
        "step": jnp.array(3),  # int leaves count as finite
    }
    bad = nonfinite_report(tree)
    assert [p for p, _ in bad] == ["['loss']", "['params']['head_energy']"]
    origin = nan_origin(tree, "train_step")
    assert origin == {
        "scope": "train_step",
        "origin": "['loss']",
        "subtree": "loss",
        "leaves": 2,
        "total": 4,
    }
    assert nan_origin({"x": jnp.ones(2)}, "s") is None


def pytest_nan_sentinel_raise_and_report_modes():
    from hydragnn_tpu.analysis.guards import NonFiniteError, nan_sentinel

    def step(x):
        return {"loss": jnp.log(x), "aux": x}

    wrapped = nan_sentinel(step, scope="train_step")
    out = wrapped(jnp.array(2.0))  # finite passes through untouched
    assert float(out["loss"]) == pytest.approx(np.log(2.0))
    with pytest.raises(NonFiniteError, match="train_step.*loss"):
        wrapped(jnp.array(-1.0))

    class Log:
        def __init__(self):
            self.recs = []

        def emit(self, event, **fields):
            self.recs.append((event, fields))

    log = Log()
    reporter = nan_sentinel(
        step, scope="canary:7", events=log, mode="report"
    )
    out = reporter(jnp.array(-1.0))  # report mode never raises
    assert not np.isfinite(float(out["loss"]))
    assert log.recs == [
        (
            "nan_origin",
            {
                "scope": "canary:7",
                "origin": "['loss']",
                "subtree": "loss",
                "leaves": 1,
                "total": 2,
            },
        )
    ]
    with pytest.raises(ValueError, match="mode"):
        nan_sentinel(step, scope="s", mode="maybe")


def pytest_nan_origin_event_is_schema_valid(tmp_path):
    from hydragnn_tpu.analysis.guards import nan_origin
    from hydragnn_tpu.obs.events import (
        EVENT_FIELDS,
        RunEventLog,
        validate_events,
    )

    assert EVENT_FIELDS["nan_origin"] == (
        "scope", "origin", "subtree", "leaves", "total",
    )
    log = RunEventLog(str(tmp_path / "events.jsonl"))
    payload = nan_origin({"loss": jnp.array(np.nan)}, "train_step")
    log.emit("nan_origin", **payload)
    log.close()
    # validate_events raises on any schema violation; requiring the
    # type proves the emit really landed
    records = validate_events(
        str(tmp_path / "events.jsonl"), require=["nan_origin"]
    )
    assert records[0]["subtree"] == "loss"


def pytest_canary_nan_veto_carries_origin():
    from hydragnn_tpu.serve.canary import (
        CanaryGates,
        _CandidateStats,
        evaluate_gates,
    )

    stats = _CandidateStats()
    live = [np.ones((2, 1), np.float32)]
    bad = [np.full((2, 1), np.nan, np.float32)]
    assert stats.add_sample(live, bad, bucket=0,
                            live_latency_s=0.01, canary_latency_s=0.01) \
        is False
    snap = stats.snapshot()
    assert snap["nans"] == 1
    assert snap["nan_origins"][0]["subtree"] == "head_0"
    decision = evaluate_gates(snap, CanaryGates(min_samples=1))
    assert decision["verdict"] == "reject"
    assert decision["reason"].startswith("nan_outputs")
    assert "head_0" in decision["reason"]


def pytest_nan_sentinel_wired_into_train_step(monkeypatch):
    """HYDRAGNN_NAN_SENTINEL=1 wraps the built train step: poisoned
    params fail the FIRST step with the offending subtree named,
    instead of an epochs-later NaN loss curve."""
    from test_models_forward import FakeData

    from hydragnn_tpu.analysis.guards import NonFiniteError
    from hydragnn_tpu.graph import collate_graphs, pad_sizes_for
    from hydragnn_tpu.models import create_model_config
    from hydragnn_tpu.train.trainer import Trainer

    monkeypatch.setenv("HYDRAGNN_NAN_SENTINEL", "1")
    rng = np.random.default_rng(0)
    n_pad, e_pad, g_pad = pad_sizes_for(6, 12, 4, graph_multiple=4)
    batch = collate_graphs(
        [FakeData(rng, 5) for _ in range(4)], n_pad, e_pad, g_pad,
        head_types=("graph",), head_dims=(1,),
    )
    model = create_model_config({
        "model_type": "GIN",
        "input_dim": 1,
        "hidden_dim": 8,
        "output_dim": [1],
        "output_type": ["graph"],
        "output_heads": {
            "graph": {
                "num_sharedlayers": 1, "dim_sharedlayers": 4,
                "num_headlayers": 1, "dim_headlayers": [4],
            },
        },
        "task_weights": [1.0],
        "num_conv_layers": 1,
        "num_nodes": 6,
        "edge_dim": None,
        "equivariance": False,
    })
    trainer = Trainer(model, training_config={
        "Optimizer": {"type": "AdamW", "learning_rate": 1e-2},
    })
    state = trainer.init_state(batch)
    poisoned = state.replace(
        params=jax.tree_util.tree_map(
            lambda p: jnp.full_like(p, jnp.nan), state.params
        )
    )
    with pytest.raises(NonFiniteError, match="train_step"):
        trainer._train_step(poisoned, batch, jax.random.PRNGKey(0))


# ---- compiled e2e (two real programs) -------------------------------------


def pytest_compiled_memory_fingerprint_is_stable_components():
    """fingerprint_memory on a real compiled program: integer bytes,
    and the gated peak is the alias-free component sum (XLA's alias
    accounting is not stable across compiles — the ratchet must not
    flap on it)."""
    fn = jax.jit(lambda x: (x @ x).sum())
    compiled = fn.lower(jnp.ones((16, 16), jnp.float32)).compile()
    fp = fingerprint_memory(compiled)
    for field in GATED_FIELDS:
        assert isinstance(fp[field], int)
    assert fp["peak_bytes"] == (
        fp["argument_bytes"] + fp["output_bytes"] + fp["temp_bytes"]
        + fp["generated_code_bytes"]
    )
    assert fp["argument_bytes"] >= 16 * 16 * 4


def pytest_compiled_programs_budget_and_ratchet(tmp_path):
    """Compile train_step + eval_step on a real 2x2 mesh, budget their
    memory fingerprints, check clean, then prove the synthetic HBM
    blow-up fails — the CI memory-ratchet smoke in miniature."""
    from hydragnn_tpu.analysis.hlo import compile_step_programs
    from hydragnn_tpu.analysis.mem import fingerprint_programs

    _texts, _axes, shape, context = compile_step_programs(
        (2, 2), programs=("train_step", "eval_step")
    )
    current = fingerprint_programs(context["compiled"])
    assert set(current) == {"train_step", "eval_step"}
    # a real train step moves real bytes
    assert current["train_step"]["peak_bytes"] > 0
    assert current["train_step"]["argument_bytes"] > 0

    path = tmp_path / "mem.json"
    save_budget(str(path), current, shape, tolerance=0.25)
    budget = load_budget(str(path))
    v, n = check_fingerprints(current, budget["programs"], tolerance=0.25)
    assert not v and not n
    assert prove_injection(current, budget["programs"], tolerance=0.25)
