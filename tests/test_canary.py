"""SLO-gated canary promotion (serve/canary.py + serve/registry.py
publication channel).

Acceptance (ISSUE 16): training publishes candidate snapshots into a
``CandidateChannel``; a ``CanaryController`` shadow-routes a fraction of
live traffic to a canary replica and promotes through statistical gates
(per-head MAE, per-bucket latency, NaN/error vetoes, min-sample floors)
or rejects loudly. Chaos locks: a crash-looping / NaN-emitting /
latency-regressing candidate can NEVER reach active; the shadow path
can never degrade live SLOs (canary invisible to the router's capacity
math, shadow shed before any priority lane).

The subprocess publish->shadow->promote e2e lives in
``tests/_canary_smoke.py`` (the CI gate) with a ``slow``-marked wrapper
here; everything in-process below reuses the test_serve harness so the
tier-1 cost stays one jit warmup.
"""

import json
import os
import pickle
import threading
import time

import numpy as np
import pytest

from hydragnn_tpu import coord
from hydragnn_tpu.serve import (
    CanaryController,
    CanaryGates,
    CandidateChannel,
    FleetRouter,
    InferenceServer,
    ModelRegistry,
    ReplicaServer,
    ServerOverloaded,
    publish_candidate,
)
from hydragnn_tpu.serve.buckets import plan_from_samples
from hydragnn_tpu.serve.canary import _CandidateStats, evaluate_gates
from hydragnn_tpu.serve.fleet import CANARY
from hydragnn_tpu.utils import faults

from test_models_forward import arch_config
from test_serve import _graph, _harness


# ---- publication channel ---------------------------------------------------


def pytest_candidate_channel_snapshot_pending_pins_gc(tmp_path):
    """publish() snapshots the checkpoint BEFORE committing the manifest
    (the training side's rolling saves overwrite in place), pending() is
    a committed-only oldest-first cursor, and GC keeps last-K plus the
    active/rollback-base pins."""
    src = tmp_path / "ck" / "m"
    src.mkdir(parents=True)
    (src / "m.pk").write_bytes(b"weights-v1")
    ch = CandidateChannel(str(tmp_path / "chan"))
    assert ch.latest_seq() == 0 and ch.pending() == []
    man1 = ch.publish("m", str(tmp_path / "ck"), epoch=0)
    assert man1["seq"] == 1 and man1["epoch"] == 0
    snap1 = os.path.join(man1["path"], "m", "m.pk")
    assert open(snap1, "rb").read() == b"weights-v1"
    # the publisher overwrites its live file; the committed snapshot
    # must not move under the consumer
    (src / "m.pk").write_bytes(b"weights-v2")
    man2 = ch.publish("m", str(tmp_path / "ck"))
    assert open(snap1, "rb").read() == b"weights-v1"
    assert open(
        os.path.join(man2["path"], "m", "m.pk"), "rb"
    ).read() == b"weights-v2"
    assert [m["seq"] for m in ch.pending()] == [1, 2]
    assert [m["seq"] for m in ch.pending(after_seq=1)] == [2]
    # a torn manifest is invisible to consumers (commit point honored)
    with open(ch.manifest_path(3), "w") as f:
        f.write('{"seq": 3, "torn')
    assert [m["seq"] for m in ch.pending()] == [1, 2]
    os.remove(ch.manifest_path(3))
    for _ in (3, 4):
        ch.publish("m", str(tmp_path / "ck"))
    # promotion pins: the new active + the previous active (rollback base)
    ch.record_promotion(2)
    assert ch.pinned() == {2}
    ch.record_promotion(4)
    assert ch.pinned() == {2, 4}
    removed = ch.gc(keep_last=1)
    assert removed == [1, 3]  # 4 = last-K, {2, 4} = pins
    assert ch.read(1) is None and not os.path.isdir(ch.version_dir(3))
    assert [m["seq"] for m in ch.pending()] == [2, 4]
    with pytest.raises(ValueError, match="keep_last"):
        ch.gc(0)
    # the one-shot training-side convenience: publish + retention
    publish_candidate(str(tmp_path / "chan"), "m", str(tmp_path / "ck"),
                      keep_last=1)
    assert [m["seq"] for m in ch.pending()] == [2, 4, 5]
    with pytest.raises(FileNotFoundError):
        ch.publish("ghost", str(tmp_path / "ck"))


# ---- fault-injection knobs (inert unset, exact fire point) -----------------


def pytest_fault_nan_and_slow_candidate_unit(monkeypatch):
    monkeypatch.delenv("HYDRAGNN_FAULT_NAN_CANDIDATE", raising=False)
    monkeypatch.delenv("HYDRAGNN_FAULT_SLOW_CANDIDATE", raising=False)
    sleeps = []
    monkeypatch.setattr(time, "sleep", sleeps.append)
    for i in range(4):  # both knobs inert when unset
        assert faults.nan_candidate(i + 1) is False
        faults.slow_candidate(i)
    assert sleeps == []
    # NaN: the configured 1-based ordinal only, or every request
    monkeypatch.setenv("HYDRAGNN_FAULT_NAN_CANDIDATE", "2")
    assert [faults.nan_candidate(k) for k in (1, 2, 3)] == [
        False, True, False,
    ]
    monkeypatch.setenv("HYDRAGNN_FAULT_NAN_CANDIDATE", "all")
    assert all(faults.nan_candidate(k) for k in (1, 2, 9))
    # slow: fires exactly once at the configured 0-based ordinal
    monkeypatch.setenv("HYDRAGNN_FAULT_SLOW_CANDIDATE", "3@0.1")
    for i in range(6):
        faults.slow_candidate(i)
    assert sleeps == [0.1]
    # range spec (NAN_AT_STEP grammar) + the 0.25 s default
    monkeypatch.setenv("HYDRAGNN_FAULT_SLOW_CANDIDATE", "0:2@0.2")
    for i in range(4):
        faults.slow_candidate(i)
    assert sleeps == [0.1, 0.2, 0.2]
    monkeypatch.setenv("HYDRAGNN_FAULT_SLOW_CANDIDATE", "5")
    faults.slow_candidate(5)
    assert sleeps == [0.1, 0.2, 0.2, 0.25]


# ---- gates: pure decision table --------------------------------------------


def _stats(**over):
    base = {
        "samples": 10, "errors": 0, "nans": 0,
        "head_mae": {0: 1e-4, 1: 1e-4},
        "head_live_mag": {0: 1.0, 1: 1.0},
        "buckets": {0: {"n": 5, "live_mean_s": 0.010,
                        "canary_mean_s": 0.012}},
    }
    base.update(over)
    return base


def pytest_evaluate_gates_decision_table():
    gates = CanaryGates(
        min_samples=4, min_bucket_samples=2, head_mae_tol=1e-3,
        head_mae_rel_tol=0.1, latency_ratio_tol=2.0, latency_slack_s=0.0,
        max_shadow_errors=0,
    )
    assert evaluate_gates(_stats(), gates)["verdict"] == "promote"
    # vetoes precede everything — one NaN rejects even below the floor
    d = evaluate_gates(_stats(samples=0, nans=1), gates)
    assert d["verdict"] == "reject" and d["reason"].startswith("nan_outputs")
    d = evaluate_gates(_stats(errors=1), gates)
    assert d["verdict"] == "reject"
    assert d["reason"].startswith("shadow_errors")
    # below the floor: wait, never promote on thin evidence
    assert evaluate_gates(_stats(samples=3), gates)["verdict"] == "wait"
    # head MAE vs max(abs tol, rel tol x live magnitude)
    d = evaluate_gates(_stats(head_mae={0: 0.2, 1: 1e-4}), gates)
    assert d["verdict"] == "reject" and "head_mae: head 0" in d["reason"]
    assert evaluate_gates(  # 0.05 <= 0.1 * |live|: rel tol admits it
        _stats(head_mae={0: 0.05, 1: 1e-4}), gates
    )["verdict"] == "promote"
    # per-bucket latency: mean canary > live x ratio + slack rejects,
    # but a bucket under min_bucket_samples carries no verdict weight
    slow = {0: {"n": 5, "live_mean_s": 0.010, "canary_mean_s": 0.030}}
    d = evaluate_gates(_stats(buckets=slow), gates)
    assert d["verdict"] == "reject" and "latency: bucket 0" in d["reason"]
    thin = {0: {"n": 1, "live_mean_s": 0.010, "canary_mean_s": 9.0}}
    assert evaluate_gates(
        _stats(buckets=thin), gates
    )["verdict"] == "promote"
    # every failed gate is named in the reason, not just the first
    d = evaluate_gates(
        _stats(head_mae={0: 0.2, 1: 1e-4}, buckets=slow), gates
    )
    assert "head_mae" in d["reason"] and "latency" in d["reason"]


def pytest_candidate_stats_nan_veto_and_accumulation():
    s = _CandidateStats()
    assert s.add_sample(
        [np.ones(4)], [np.full(4, 1.1)], bucket=0,
        live_latency_s=0.01, canary_latency_s=0.03,
    )
    # a non-finite canary answer is a veto, never a sample
    assert not s.add_sample(
        [np.ones(4)], [np.array([1.0, np.nan, 1.0, 1.0])], bucket=0,
        live_latency_s=0.01, canary_latency_s=0.03,
    )
    snap = s.snapshot()
    assert snap["samples"] == 1 and snap["nans"] == 1
    assert snap["head_mae"][0] == pytest.approx(0.1)
    assert snap["head_live_mag"][0] == pytest.approx(1.0)
    assert snap["buckets"][0]["n"] == 1
    assert snap["buckets"][0]["canary_mean_s"] == pytest.approx(0.03)


# ---- controller harness ----------------------------------------------------


class _StubFleet:
    """The supervisor surface the controller needs, promotion recorded
    instead of executed."""

    def __init__(self, coord_dir, spec_path=None, promote_result=None):
        self.coord_dir = coord_dir
        self.spec_path = spec_path
        self.lease_s = 2.0
        self.events = []
        self.promotes = []
        self._result = promote_result or {
            "status": "promoted", "cmd_id": 1, "versions": {0: 2, 1: 2},
            "propagated": True, "acks": {},
        }

    def emit(self, event, **fields):
        self.events.append((event, fields))

    def promote(self, checkpoint, path=None, arch_config=None, name=None,
                timeout=None):
        self.promotes.append({"checkpoint": checkpoint, "path": path,
                              "name": name})
        return dict(self._result)


def _write_spec(tmp_path, **extra):
    spec = {"model_name": "m", "checkpoint": {"name": "x", "path": "y"}}
    spec.update(extra)
    path = str(tmp_path / "spec.json")
    with open(path, "w") as f:
        json.dump(spec, f)
    return path


# ---- shadow tap: shed-first contract ---------------------------------------


def pytest_shadow_tap_sheds_degraded_then_queue_full(tmp_path):
    """The tap never blocks and never queues work a degraded fleet (or a
    full queue) cannot afford: degraded sheds FIRST, queue-full sheds
    next, and a disarmed tap is a no-op — all counted."""
    d = str(tmp_path / "coord")
    os.makedirs(d)
    stub = _StubFleet(d, _write_spec(tmp_path))
    c = CanaryController(
        stub, str(tmp_path / "chan"), fraction=0.5, queue_capacity=4,
        heartbeat_s=0.0,  # degraded cache: always re-read
    )
    g = object()  # the tap never inspects the graph
    c.shadow_tap(g, {"heads": [[1.0]]}, 0.01)  # disarmed: ignored
    assert c._q.qsize() == 0
    c._armed.set()
    for _ in range(8):  # stride 2: ordinals 0,2,4,6 eligible -> queue 4
        c.shadow_tap(g, {"heads": [[1.0]]}, 0.01)
    snap = c.metrics.snapshot()
    assert c._q.qsize() == 4 and snap["shadow_shed_total"] == 0
    for _ in range(2):  # ordinal 8 eligible, queue full -> shed
        c.shadow_tap(g, {"heads": [[1.0]]}, 0.01)
    assert c.metrics.snapshot()["shadow_shed_total"] == 1
    # a degraded fleet sheds shadow work before anything else
    time.sleep(0.01)
    coord.write_json(
        os.path.join(d, "fleet.json"),
        {"live": 1, "target": 2, "degraded": True, "ts": time.time()},
    )
    for _ in range(2):
        c.shadow_tap(g, {"heads": [[1.0]]}, 0.01)
    assert c.metrics.snapshot()["shadow_shed_total"] == 2
    assert c._q.qsize() == 4  # nothing slipped past the shed


# ---- crash loop / boot timeout / supersede (stub factory, no serving) ------


def pytest_canary_crash_loop_supersede_and_boot_timeout(tmp_path):
    src = tmp_path / "ck" / "c1"
    src.mkdir(parents=True)
    (src / "c1.pk").write_bytes(b"blob")
    root = str(tmp_path / "chan")
    ch = CandidateChannel(root)
    ch.publish("c1", str(tmp_path / "ck"))
    ch.publish("c1", str(tmp_path / "ck"))
    d = str(tmp_path / "coord")
    os.makedirs(d)

    class _DeadHandle:
        def alive(self):
            return False

        def stop(self):
            pass

    spawned = []

    def factory(spec_path, canary_id, incarnation):
        spawned.append((canary_id, incarnation))
        return _DeadHandle()

    stub = _StubFleet(d, _write_spec(tmp_path))
    gates = CanaryGates(max_crashes=1, min_samples=4)
    c = CanaryController(
        stub, root, poll_s=0.01, gates=gates, replica_factory=factory,
    )
    with c:
        # only the NEWEST pending candidate gets shadow budget; older
        # unevaluated ones are already-stale training states
        d1 = c.wait_decision(1, timeout=30)
        assert d1["verdict"] == "rejected"
        assert d1["reason"] == "superseded by seq 2"
        # death -> respawn once (the budget) -> death -> crash_loop
        d2 = c.wait_decision(2, timeout=30)
        assert d2["verdict"] == "rejected"
        assert d2["reason"].startswith("crash_loop: candidate died 2")
    assert spawned == [(2, 0), (2, 1)]  # same candidate, next incarnation
    assert stub.promotes == []  # a crash-looping candidate NEVER promotes
    snap = c.metrics.snapshot()
    assert snap["crashes_total"] == 2 and snap["rejects_total"] == 2
    assert [e for e, _ in stub.events].count("canary_rejected") == 2
    assert [e for e, _ in stub.events].count("canary_started") == 1

    # a candidate alive but never serving burns the boot timeout, not
    # the respawn budget — and is rejected as unproven, not promoted
    class _WedgedHandle(_DeadHandle):
        def alive(self):
            return True

    stub2 = _StubFleet(d, _write_spec(tmp_path))
    c2 = CanaryController(
        stub2, root, poll_s=0.01, boot_timeout_s=0.2, gates=gates,
        replica_factory=lambda *a: _WedgedHandle(),
    )
    ch.publish("c1", str(tmp_path / "ck"))
    with c2:
        d3 = c2.wait_decision(3, timeout=30)
    assert d3["verdict"] == "rejected"
    assert "never reached serving" in d3["reason"]
    assert stub2.promotes == []


# ---- router exclusion: canary invisible to live traffic --------------------


def _fresh_server(**kw):
    h = _harness()
    registry = ModelRegistry()
    registry.register("sage", h["model"], h["state"].params,
                      h["state"].batch_stats)
    kw.setdefault("max_wait_s", 0.002)
    return InferenceServer(registry, h["plan"], default_model="sage", **kw)


def pytest_router_excludes_canary_and_shadow_sheds_before_lanes(tmp_path):
    """A canary replica in flight is invisible to the router BY
    CONSTRUCTION (it leases under ``canarys/``, outside the discovery
    glob): zero live requests reach it, it never counts toward the
    degradation ladder's capacity math, and while the fleet is degraded
    the shadow tap sheds while the priority-0 lane is still admitted."""
    d = str(tmp_path / "coord")
    live = ReplicaServer(_fresh_server(), d, 0, heartbeat_s=0.05)
    live.start()
    canary = ReplicaServer(
        _fresh_server(), d, 9, heartbeat_s=0.05, role=CANARY,
    )
    canary.start()
    try:
        assert os.path.exists(
            os.path.join(d, "canarys", "canary-9.json")
        )
        lease = coord.read_json(
            coord.hb_path(d, CANARY, 9, prefix=CANARY)
        )
        assert lease["role"] == CANARY and lease["state"] == "serving"
        # the supervisor's capacity math says degraded: 1 live of 2 —
        # the serving canary must not paper over the missing replica
        coord.write_json(
            os.path.join(d, "fleet.json"),
            {"live": 1, "target": 2, "degraded": True, "ts": time.time()},
        )
        router = FleetRouter(
            d, lanes={"interactive": 0, "batch": 1},
            shed_priority_when_degraded=1, lease_s=2.0,
            scan_interval_s=0.0, max_attempts=2, retry_base_delay_s=0.001,
        )
        assert router.degraded()
        rng = np.random.default_rng(7)
        replicas_seen = set()
        for _ in range(6):
            raw = router.route(
                _graph(int(rng.integers(4, 30)), rng, with_targets=False),
                lane="interactive", deadline_s=30.0, raw=True,
            )
            replicas_seen.add(raw["replica"])
        assert replicas_seen == {0}  # the canary took ZERO live requests
        with canary._lock:
            assert canary._served == 0
        # degraded shed order: shadow tap first, batch lane second, the
        # interactive lane (above) still admitted
        stub = _StubFleet(d, _write_spec(tmp_path))
        c = CanaryController(stub, str(tmp_path / "chan"),
                             fraction=1.0, heartbeat_s=0.0)
        c._armed.set()
        c.shadow_tap(object(), {"heads": [[1.0]]}, 0.01)
        assert c.metrics.snapshot()["shadow_shed_total"] == 1
        assert c._q.qsize() == 0
        g = _graph(8, rng, with_targets=False)
        with pytest.raises(ServerOverloaded):
            router.route(g, lane="batch", deadline_s=30.0)
    finally:
        canary.shutdown()
        live.shutdown()


# ---- controller e2e: veto -> latency gate -> promote -----------------------


def pytest_canary_controller_vetoes_gates_then_promotes(
    tmp_path, monkeypatch
):
    """Three candidates through a REAL in-process canary replica: the
    NaN-emitting one is vetoed, the latency-regressing one fails its
    bucket gate, the healthy one promotes — recording the promotion pin
    and emitting the full event ladder. The fleet promote itself is
    stubbed (locked by test_fleet); this locks the decision plumbing."""
    from hydragnn_tpu.train.checkpoint import save_model

    h = _harness()
    ckdir = str(tmp_path / "ck")
    save_model(h["state"], "base", path=ckdir)
    rng = np.random.default_rng(21)
    samples = [_graph(int(n), rng) for n in rng.integers(4, 40, 24)]
    samples_path = str(tmp_path / "samples.pkl")
    with open(samples_path, "wb") as f:
        pickle.dump(samples, f)
    plan_kw = {"max_batch_graphs": 4, "num_buckets": 2}
    arch = arch_config("SAGE")
    spec_path = _write_spec(
        tmp_path, checkpoint={"name": "base", "path": ckdir},
        arch=arch, samples=samples_path, plan=plan_kw,
    )
    plan = plan_from_samples(samples, **plan_kw)
    coord_dir = str(tmp_path / "coord")
    os.makedirs(coord_dir)

    reps = []

    class _InProcHandle:
        def __init__(self, rep):
            self.rep = rep
            self._dead = False

        def alive(self):
            return not self._dead

        def stop(self):
            self._dead = True
            self.rep.shutdown()

    def factory(cand_spec_path, canary_id, incarnation):
        with open(cand_spec_path) as f:
            cand_spec = json.load(f)
        registry = ModelRegistry()
        registry.load_checkpoint(
            cand_spec["checkpoint"]["name"], arch_config=arch,
            path=cand_spec["checkpoint"]["path"], name="m",
        )
        rep = ReplicaServer(
            InferenceServer(registry, plan, default_model="m",
                            max_wait_s=0.002),
            coord_dir, canary_id, heartbeat_s=0.05,
            incarnation=incarnation, model_name="m", arch_config=arch,
            role=CANARY,
        )
        rep.start()
        reps.append(rep)
        return _InProcHandle(rep)

    # a live-side server over the same base weights: the shadow compare
    # target (identical params -> MAE 0 for the healthy candidate)
    live_reg = ModelRegistry()
    live_reg.load_checkpoint("base", arch_config=arch, path=ckdir,
                             name="m")
    live = InferenceServer(live_reg, plan, default_model="m",
                           max_wait_s=0.002)
    pairs = []
    with live:
        for g in samples[:4]:
            pairs.append((g, [np.asarray(o) for o in
                              live.predict(g, timeout=30)]))

    root = str(tmp_path / "chan")
    ch = CandidateChannel(root)
    stub = _StubFleet(coord_dir, spec_path)
    gates = CanaryGates(
        min_samples=4, min_bucket_samples=1, head_mae_tol=5e-3,
        latency_ratio_tol=2.0, latency_slack_s=0.2, max_crashes=1,
        decide_timeout_s=120.0,
    )
    c = CanaryController(
        stub, ch, spec_path, fraction=1.0, gates=gates, poll_s=0.02,
        boot_timeout_s=120.0,
    )
    c._factory = factory

    def feed_until_decided(seq, live_latency_s=0.05, timeout=120.0):
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with c._lock:
                if any(dec["seq"] == seq for dec in c.decisions):
                    break
            if c._armed.is_set():
                for g, heads in pairs:
                    c.shadow_tap(g, {"heads": heads}, live_latency_s)
            time.sleep(0.05)
        return c.wait_decision(seq, timeout=10.0)

    with c:
        # 1. NaN-emitting candidate: hard veto, loud rejection
        monkeypatch.setenv("HYDRAGNN_FAULT_NAN_CANDIDATE", "all")
        ch.publish("base", ckdir)
        d1 = feed_until_decided(1)
        assert d1["verdict"] == "rejected"
        assert d1["reason"].startswith("nan_outputs")
        monkeypatch.delenv("HYDRAGNN_FAULT_NAN_CANDIDATE")
        # 2. latency regression: every shadow request slowed past the
        #    bucket gate (live 0.05 s x 2.0 + 0.2 s slack < 0.5 s)
        monkeypatch.setenv("HYDRAGNN_FAULT_SLOW_CANDIDATE", "0:999@0.5")
        ch.publish("base", ckdir)
        d2 = feed_until_decided(2)
        assert d2["verdict"] == "rejected"
        assert "latency: bucket" in d2["reason"]
        assert d2["samples"] >= gates.min_samples
        monkeypatch.delenv("HYDRAGNN_FAULT_SLOW_CANDIDATE")
        assert stub.promotes == []  # neither bad candidate reached active
        # 3. healthy candidate: all gates pass -> the hot-swap fires
        ch.publish("base", ckdir)
        d3 = feed_until_decided(3)
        assert d3["verdict"] == "promoted"
        assert d3["samples"] >= gates.min_samples
        assert d3["gate_latency_s"] >= 0
    assert [p["checkpoint"] for p in stub.promotes] == ["base"]
    assert stub.promotes[0]["path"] == ch.read(3)["path"]  # the snapshot
    assert ch.pinned() == {3}  # promotion recorded for retention GC
    events = [e for e, _ in stub.events]
    assert events.count("canary_started") == 3
    assert events.count("canary_rejected") == 2
    assert events.count("canary_promoted") == 1
    rejected = [f for e, f in stub.events if e == "canary_rejected"]
    assert {f["candidate"] for f in rejected} == {1, 2}
    snap = c.metrics.snapshot()
    assert snap["promotes_total"] == 1 and snap["rejects_total"] == 2
    assert snap["nan_vetoes_total"] == 1
    assert snap["shadow_samples_total"] >= 2 * gates.min_samples
    # every canary replica the controller booted was torn down, and the
    # live side never routed to any of them
    assert all(r._state == "stopped" for r in reps)
    assert "hydragnn_canary_promotes_total 1" in (
        c.metrics.render_prometheus()
    )


def pytest_canary_promote_rollback_chains_reason(tmp_path):
    """When the quality gates pass but the mechanical hot-swap rolls
    back (strict load refused on a replica, ack timeout), the canary
    verdict is still a loud rejection with the fleet's reason chained —
    never a silent success."""
    d = str(tmp_path / "coord")
    os.makedirs(d)
    stub = _StubFleet(
        d, _write_spec(tmp_path),
        promote_result={"status": "rolled_back", "reason": "corrupt pk"},
    )
    c = CanaryController(stub, str(tmp_path / "chan"), fraction=1.0)
    manifest = {"seq": 5, "checkpoint": "cand", "path": "/x",
                "ts": time.time()}
    with c._lock:
        c._cand = manifest
    c._promote(manifest, {"samples": 30})
    d5 = c.wait_decision(5, timeout=5)
    assert d5["verdict"] == "rejected"
    assert d5["reason"] == "hot_swap_rolled_back: corrupt pk"
    assert c.metrics.snapshot()["rejects_total"] == 1


# ---- subprocess e2e (the CI smoke, wrapped) -------------------------------


@pytest.mark.slow  # replica + canary processes x jax import + warmup
def pytest_canary_smoke_e2e(tmp_path):
    import _canary_smoke

    _canary_smoke.main(str(tmp_path / "smoke"))
