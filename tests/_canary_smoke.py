"""CI canary-promotion smoke (standalone, NOT a pytest module).

The train->serve flywheel's last mile, end to end with real processes:
a 2-replica :class:`ServingFleet` under closed-loop client load, a
:class:`CanaryController` consuming a :class:`CandidateChannel`, and a
SUBPROCESS canary replica per candidate —

1. a POISONED candidate (``HYDRAGNN_FAULT_NAN_CANDIDATE=all``, the
   canary-only NaN injection) is shadow-evaluated and REJECTED with a
   schema-valid ``canary_rejected`` carrying the ``nan_outputs`` veto;
   the active version never blinks,
2. a good candidate accumulates shadow evidence from mirrored live
   traffic, passes every gate, and is PROMOTED through the all-acked
   hot-swap — with ZERO failed live requests across both phases and
   zero live requests ever routed to the canary.

Validates the whole event stream against the documented schema and
prints the shadow overhead (samples / shed / gate latency) the bench
tracks.

Usage: python tests/_canary_smoke.py <workdir>
"""

import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

NUM_CLIENTS = 2
REQUEST_DEADLINE_S = 30.0
DECISION_TIMEOUT_S = 300.0


def main(workdir):
    os.makedirs(workdir, exist_ok=True)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    import numpy as np

    import _fleet_smoke
    from hydragnn_tpu.obs.events import validate_events
    from hydragnn_tpu.serve import (
        CanaryController,
        CanaryGates,
        CandidateChannel,
        FleetRouter,
        ServerOverloaded,
    )
    from hydragnn_tpu.serve.fleet import ServingFleet

    spec_path, ckdir, samples = _fleet_smoke.build_artifacts(workdir)
    coord_dir = os.path.join(workdir, "coord")
    log_dir = os.path.join(workdir, "log")
    fleet = ServingFleet(
        coord_dir,
        2,
        spec_path=spec_path,
        heartbeat_s=0.1,
        lease_s=0.75,
        poll_s=0.05,
        log_dir=log_dir,
    )
    fleet.start(wait_serving=True, timeout=300)
    assert fleet.health()["live"] == 2, fleet.health()

    router = FleetRouter(
        coord_dir,
        lease_s=0.75,
        scan_interval_s=0.1,
        max_attempts=6,
        retry_base_delay_s=0.05,
    )

    channel = CandidateChannel(os.path.join(workdir, "chan"))
    # the bumped candidate legitimately disagrees with base (+0.05 on
    # every param), so the MAE tolerance is wide open here: this smoke
    # locks the PIPELINE (publish -> shadow -> gates -> swap), the gate
    # decision table itself is unit-locked in tests/test_canary.py
    gates = CanaryGates(
        min_samples=8,
        min_bucket_samples=1,
        head_mae_tol=100.0,
        head_mae_rel_tol=100.0,
        latency_ratio_tol=100.0,
        latency_slack_s=5.0,
        max_crashes=2,
        decide_timeout_s=DECISION_TIMEOUT_S,
    )
    controller = CanaryController(
        fleet,
        channel,
        spec_path,
        fraction=0.5,
        gates=gates,
        poll_s=0.05,
        boot_timeout_s=240.0,
        heartbeat_s=0.1,
    )
    controller.attach(router)  # mirror live 200s into the shadow queue
    controller.start()

    stop = threading.Event()
    lock = threading.Lock()
    results = []
    failures = []

    def client(seed):
        rng = np.random.default_rng(seed)
        while not stop.is_set():
            g = samples[int(rng.integers(0, len(samples)))]
            try:
                raw = router.route(
                    g, deadline_s=REQUEST_DEADLINE_S, raw=True
                )
                outcome = ("ok", raw["replica"], raw["version"])
            except ServerOverloaded:
                outcome = ("shed", None, None)
            except Exception as e:
                outcome = ("failed", None, None)
                with lock:
                    failures.append(repr(e))
            with lock:
                results.append(outcome)

    clients = [
        threading.Thread(target=client, args=(200 + i,), daemon=True)
        for i in range(NUM_CLIENTS)
    ]
    for t in clients:
        t.start()

    try:
        # phase 1: poisoned candidate -> NaN veto, never promoted
        os.environ["HYDRAGNN_FAULT_NAN_CANDIDATE"] = "all"
        t0 = time.monotonic()
        channel.publish("cand", ckdir, note="poisoned")
        dec1 = controller.wait_decision(1, timeout=DECISION_TIMEOUT_S)
        reject_s = time.monotonic() - t0
        assert dec1["verdict"] == "rejected", dec1
        assert dec1["reason"].startswith("nan_outputs"), dec1
        del os.environ["HYDRAGNN_FAULT_NAN_CANDIDATE"]
        raw = router.route(
            samples[0], deadline_s=REQUEST_DEADLINE_S, raw=True
        )
        assert raw["version"] == 1, raw  # active never blinked

        # phase 2: good candidate -> gates pass -> all-acked hot-swap
        t0 = time.monotonic()
        channel.publish("cand", ckdir, note="good")
        dec2 = controller.wait_decision(2, timeout=DECISION_TIMEOUT_S)
        promote_s = time.monotonic() - t0
        assert dec2["verdict"] == "promoted", dec2
        assert dec2["samples"] >= gates.min_samples, dec2
        seen = set()
        for _ in range(12):
            raw = router.route(
                samples[0], deadline_s=REQUEST_DEADLINE_S, raw=True
            )
            seen.add((raw["replica"], raw["version"]))
        assert all(v == 2 for _, v in seen), seen
        assert len({r for r, _ in seen}) == 2, seen
        time.sleep(0.5)
    finally:
        stop.set()
        for t in clients:
            t.join(timeout=60)
        controller.stop()
        fleet.stop()

    with lock:
        done = list(results)
        failed = list(failures)
    # ZERO failed live requests through both canary phases — the shadow
    # path and the swap never cost a client anything
    assert not failed, f"{len(failed)} failed live request(s): {failed[:5]}"
    assert all(r in (0, 1) for o, r, _ in done if o == "ok"), (
        "a live request reached a non-fleet replica"
    )
    n_ok = sum(1 for o, _, _ in done if o == "ok")
    assert n_ok > 0, "no live traffic served"

    recs = validate_events(
        os.path.join(log_dir, "events.jsonl"),
        require=[
            "canary_started", "canary_rejected", "canary_promoted",
            "model_promoted",
        ],
    )
    rejected = [r for r in recs if r["event"] == "canary_rejected"][0]
    assert rejected["candidate"] == 1, rejected
    assert rejected["reason"].startswith("nan_outputs"), rejected
    promoted = [r for r in recs if r["event"] == "canary_promoted"][0]
    assert promoted["candidate"] == 2, promoted
    assert promoted["samples"] >= gates.min_samples, promoted
    assert channel.pinned() == {2}

    snap = controller.metrics.snapshot()
    print(
        "canary smoke OK: poisoned rejected in {:.1f}s ({}), good promoted "
        "in {:.1f}s ({} shadow samples, {} shed, {} live requests, "
        "0 failed)".format(
            reject_s, rejected["reason"].split(":")[0], promote_s,
            int(snap["shadow_samples_total"]),
            int(snap["shadow_shed_total"]), n_ok,
        )
    )


if __name__ == "__main__":
    main(sys.argv[1])
