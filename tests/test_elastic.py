"""Elastic self-healing training: heartbeat leases, peer watchdog,
agent re-mesh, and the kill-and-rejoin e2e — all driven with injected
host loss (``HYDRAGNN_FAULT_LOSE_HOST_AT_STEP``), not hope.

The e2e starts N=2 single-device CPU processes under per-host
``ElasticAgent`` supervisors, fault-kills one mid-epoch, and asserts the
survivor re-meshes to world 1 WITHOUT operator action, finishes training,
emits a schema-valid ``world_resize`` event with the measured recovery
time, and lands on exactly the trajectory of a clean 1-process restart
from the same rolling checkpoint.
"""

import json
import os
import subprocess
import sys
import textwrap
import time

import numpy as np
import pytest

from hydragnn_tpu.train import elastic
from hydragnn_tpu.utils import faults

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import _elastic_worker  # noqa: E402

FAST = int(os.getenv("HYDRAGNN_FAST_TEST", "0")) == 1


# ---- coordination primitives ----------------------------------------------


def pytest_heartbeat_writes_and_refreshes_lease(tmp_path):
    path = str(tmp_path / "workers" / "host-0.json")
    hb = elastic.Heartbeat(path, lambda: {"step": 7}, interval_s=0.05)
    hb.start()
    try:
        first = json.load(open(path))
        assert first["step"] == 7 and first["ts"] > 0
        time.sleep(0.2)
        second = json.load(open(path))
        assert second["ts"] > first["ts"]  # the lease refreshes
    finally:
        hb.stop()
    assert not hb._thread.is_alive()


def pytest_dead_members_lease_and_tombstone(tmp_path):
    d = str(tmp_path)
    now = time.time()
    elastic._write_json(elastic._hb_path(d, "worker", 0), {"ts": now})
    elastic._write_json(elastic._hb_path(d, "worker", 1), {"ts": now - 60})
    elastic.write_tombstone(d, 2, reason="preempted", by=2)
    # host 3 never heartbeat: still bootstrapping, NOT dead
    dead = elastic.dead_members(d, [0, 1, 2, 3], lease_s=5.0, kind="worker")
    assert 0 not in dead and 3 not in dead
    assert 1 in dead and 2 in dead
    # tombstones are first-write-wins: the detection ts must not move
    ts = elastic.read_tombstone(d, 2)["ts"]
    elastic.write_tombstone(d, 2, reason="other", by=0)
    assert elastic.read_tombstone(d, 2)["ts"] == ts
    # a CLEANLY finished member (final lease marked done=True) is never
    # dead no matter how stale — end of run, not a loss; rank 0's
    # post-training tail must not be watchdog-killed by finished peers
    elastic._write_json(
        elastic._hb_path(d, "worker", 4), {"ts": now - 3600, "done": True}
    )
    dead = elastic.dead_members(d, [4], lease_s=5.0, kind="worker")
    assert dead == {}
    # a stale lease from an EARLIER generation reads as "respawned worker
    # still booting", not dead (leases persist at one path across
    # re-meshes); the same stale lease IS dead once it names the current
    # generation, and a lease with no gen field counts as current
    elastic._write_json(
        elastic._hb_path(d, "worker", 5), {"ts": now - 60, "gen": 0}
    )
    assert elastic.dead_members(
        d, [5], lease_s=5.0, kind="worker", current_gen=1
    ) == {}
    assert 5 in elastic.dead_members(
        d, [5], lease_s=5.0, kind="worker", current_gen=0
    )
    assert 1 in elastic.dead_members(
        d, [1], lease_s=5.0, kind="worker", current_gen=3
    )  # host 1's lease above has no gen field -> judged as current


def pytest_watchdog_detects_stale_peer_and_self_eviction(tmp_path):
    d = str(tmp_path)
    now = time.time()
    elastic._write_json(elastic._hb_path(d, "worker", 1), {"ts": now - 60})
    losses, evictions = [], []
    wd = elastic.PeerWatchdog(
        d, host=0, members=[0, 1], lease_s=1.0, interval_s=0.05,
        on_loss=losses.append, on_evicted=lambda: evictions.append(1),
    )
    wd.start()
    try:
        deadline = time.time() + 5
        while not losses and time.time() < deadline:
            time.sleep(0.02)
    finally:
        wd.stop()
    assert losses and 1 in losses[0]

    # a host finding its OWN tombstone evicts itself (no split brain)
    elastic.write_tombstone(d, 5, reason="lease_expired", by=0)
    wd2 = elastic.PeerWatchdog(
        d, host=5, members=[5, 6], lease_s=30.0, interval_s=0.05,
        on_loss=losses.append, on_evicted=lambda: evictions.append(1),
    )
    wd2.start()
    try:
        deadline = time.time() + 5
        while not evictions and time.time() < deadline:
            time.sleep(0.02)
    finally:
        wd2.stop()
    assert evictions


def pytest_world_resize_event_and_gauges(tmp_path):
    from hydragnn_tpu.obs import runtime as obs
    from hydragnn_tpu.obs.events import validate_events

    t = obs.RunTelemetry("t", str(tmp_path))
    obs.activate(t)
    try:
        obs.world_resized(old_world=4, new_world=3, gen=2, recovery_s=1.25)
        snap = t.metrics.snapshot()
        assert snap["world_size"] == 3.0
        assert snap["last_recovery_seconds"] == 1.25
    finally:
        obs.deactivate()
    recs = validate_events(
        str(tmp_path / "events.jsonl"), require=["world_resize"]
    )
    ev = [r for r in recs if r["event"] == "world_resize"][0]
    assert ev["old_world"] == 4 and ev["new_world"] == 3
    assert ev["gen"] == 2 and ev["recovery_s"] == 1.25


# ---- fault injection -------------------------------------------------------


def pytest_slow_step_spec(monkeypatch):
    sleeps = []
    monkeypatch.setattr(time, "sleep", sleeps.append)
    monkeypatch.setenv("HYDRAGNN_FAULT_SLOW_STEP", "4:6@0.3")
    for s in range(8):
        faults.slow_step(s)
    assert sleeps == [0.3, 0.3]  # steps 4 and 5 only
    monkeypatch.setenv("HYDRAGNN_FAULT_SLOW_STEP", "2")  # default delay
    faults.slow_step(2)
    assert sleeps[-1] == 0.25


def pytest_lose_host_targets_one_rank_only(monkeypatch):
    # this process is rank 0; a spec naming rank 3 must be a no-op even
    # at the matching step (otherwise the test would have died here)
    monkeypatch.setenv("HYDRAGNN_FAULT_LOSE_HOST_AT_STEP", "3:0")
    faults.lose_host_at_step(0)
    # non-matching step on the matching rank: also a no-op
    monkeypatch.setenv("HYDRAGNN_FAULT_LOSE_HOST_AT_STEP", "0:99")
    faults.lose_host_at_step(0)


@pytest.mark.slow  # subprocess + jax import (~10 s) for one exit code
def pytest_lose_host_kills_targeted_rank():
    code = textwrap.dedent(
        """
        import os
        os.environ["HYDRAGNN_FAULT_LOSE_HOST_AT_STEP"] = "0:2"
        from hydragnn_tpu.utils import faults
        faults.lose_host_at_step(1)
        faults.lose_host_at_step(2)  # exits 113 here
        raise SystemExit(0)
        """
    )
    proc = subprocess.run(
        [sys.executable, "-c", code],
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
        timeout=120,
    )
    assert proc.returncode == faults.KILL_EXIT_CODE


def pytest_straggler_shows_in_flight_recorder():
    from hydragnn_tpu.obs.runtime import FlightRecorder

    fr = FlightRecorder(capacity=16, stall_factor=4.0, min_fill=4)
    stalls = []
    for i in range(12):
        t0 = time.perf_counter()
        faults.slow_step(i)  # no env set: free
        dt = time.perf_counter() - t0 + 0.01
        if i == 10:
            dt += 0.5  # the injected straggler's extra wall time
        s = fr.record(dt)
        if s:
            stalls.append(s)
    assert len(stalls) == 1 and stalls[0]["step"] == 10


# ---- agent re-mesh without jax (stub workers) ------------------------------


_STUB_WORKER = textwrap.dedent(
    """
    import json, os, sys, time

    sys.path.insert(0, {root!r})
    from hydragnn_tpu.train import elastic

    coord = os.environ["HYDRAGNN_ELASTIC_DIR"]
    host = int(os.environ["HYDRAGNN_ELASTIC_HOST"])
    gen = int(os.environ["HYDRAGNN_ELASTIC_GEN"])
    members = [int(m) for m in os.environ["HYDRAGNN_ELASTIC_MEMBERS"].split(",")]
    out = os.environ["STUB_OUT"]

    rec = dict(host=host, gen=gen, members=members,
               rank=members.index(host), world=len(members),
               coordinator=os.environ["HYDRAGNN_TPU_COORDINATOR"],
               num=os.environ["HYDRAGNN_TPU_NUM_PROCESSES"],
               pid=os.environ["HYDRAGNN_TPU_PROCESS_ID"],
               detect=os.environ.get("HYDRAGNN_ELASTIC_DETECT_TS"),
               prev=os.environ.get("HYDRAGNN_ELASTIC_PREV_WORLD"))
    with open(os.path.join(out, f"gen{{gen}}-host{{host}}.json"), "w") as f:
        json.dump(rec, f)

    if gen == 0 and host == 2:
        raise SystemExit(113)  # preempted (faults.KILL_EXIT_CODE)
    if gen == 0:
        # survivors: wait for the dying host's tombstone, then exit for
        # re-mesh exactly as the real watchdog would
        deadline = time.time() + 30
        while time.time() < deadline:
            if elastic.read_tombstone(coord, 2) is not None:
                raise SystemExit(elastic.EXIT_RESHAPE)
            time.sleep(0.05)
        raise SystemExit(7)
    raise SystemExit(0)  # gen 1: done
    """
)


@pytest.mark.slow  # subprocess agents; the CI elastic smoke covers 2->1
@pytest.mark.skipif(FAST, reason="subprocess agents — full tier only")
def pytest_agents_remesh_3_to_2_with_stub_workers(tmp_path):
    """Three agents, host 2's worker 'preempted' at gen 0: the survivors
    must re-form as a 2-member gen-1 world with ranks reassigned, the new
    coordinator port, and the detection timestamp carried over — all via
    the shared directory, no agent-to-agent channel."""
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    stub = tmp_path / "stub_worker.py"
    stub.write_text(_STUB_WORKER.format(root=root))
    out = tmp_path / "out"
    out.mkdir()
    coord = str(tmp_path / "coord")

    env = {**os.environ, "STUB_OUT": str(out)}
    procs = [
        subprocess.Popen(
            [
                sys.executable, "-m", "hydragnn_tpu.train.elastic",
                "--dir", coord, "--host", str(h), "--hosts", "3",
                "--base-port", "23001", "--heartbeat", "0.1",
                "--lease", "1.0",
                "--", sys.executable, str(stub),
            ],
            env=env, cwd=root,
        )
        for h in range(3)
    ]
    rcs = [p.wait(timeout=120) for p in procs]
    assert rcs[2] == faults.KILL_EXIT_CODE  # the preempted host's agent
    assert rcs[0] == 0 and rcs[1] == 0  # survivors finished gen 1

    g0h0 = json.load(open(out / "gen0-host0.json"))
    assert g0h0["members"] == [0, 1, 2] and g0h0["world"] == 3
    assert g0h0["coordinator"].endswith(":23001")
    g1h0 = json.load(open(out / "gen1-host0.json"))
    g1h1 = json.load(open(out / "gen1-host1.json"))
    # ranks reassigned over the survivors, fresh coordinator port, and
    # the resize context (detection ts + previous world) passed through
    assert g1h0["members"] == [0, 1] and g1h1["members"] == [0, 1]
    assert (g1h0["rank"], g1h1["rank"]) == (0, 1)
    assert (g1h0["num"], g1h1["num"]) == ("2", "2")
    assert g1h0["coordinator"].endswith(":23002")
    assert g1h0["detect"] is not None and g1h0["prev"] == "3"
    # the gen-1 file records the transition
    gen, info = elastic.latest_gen(coord)
    assert gen == 1
    assert info["members"] == [0, 1]
    assert info["prev_members"] == [0, 1, 2]
    assert info["detect_ts"] is not None


# ---- kill-and-rejoin e2e ---------------------------------------------------


def _meta_of(path_pk):
    from hydragnn_tpu.train import checkpoint as ck

    return ck.pop_train_meta(
        ck._parse_checkpoint_bytes(open(path_pk, "rb").read(), path_pk)
    )


@pytest.mark.slow  # ~90 s multi-process e2e; tier-1's wall budget is
# protected by the dedicated CI "Elastic kill-and-rejoin smoke" step,
# which runs the same scenario (tests/_elastic_smoke.py) before tier-1
@pytest.mark.skipif(FAST, reason="multi-process e2e — full tier only")
def pytest_elastic_kill_and_rejoin_matches_clean_restart(tmp_path):
    """The acceptance e2e: 2 processes, one fault-killed mid-epoch-2. The
    survivor re-meshes to world 1 and finishes all epochs without any
    operator action; a schema-valid ``world_resize`` event records the
    recovery time; the post-resize trajectory is bitwise-identical to a
    clean 1-process restart from the same rolling checkpoint."""
    from hydragnn_tpu.obs.events import validate_events
    from hydragnn_tpu.train.checkpoint import rolling_checkpoints

    workdir = str(tmp_path / "elastic")
    os.makedirs(workdir)
    num_epoch = _elastic_worker.NUM_EPOCH
    # 2 steps/epoch/rank at world 2: rank 1's step 3 is mid-epoch-1. The
    # survivor keeps training (slowed to 0.3 s/step so the lease watchdog
    # always wins the race against run completion) until its watchdog
    # declares the loss; the exact epoch it then resumes from depends on
    # detection latency, so the assertions pin the INVARIANTS: resumed
    # strictly after the first checkpoint, strictly before the end, and
    # ran exactly the remaining epochs.
    rcs = _elastic_worker.run_elastic(
        workdir, n_hosts=2,
        extra_env={
            "HYDRAGNN_FAULT_LOSE_HOST_AT_STEP": "1:3",
            "HYDRAGNN_FAULT_SLOW_STEP": "0:@0.3",
        },
    )
    assert rcs[1] == faults.KILL_EXIT_CODE, rcs
    assert rcs[0] == 0, rcs

    got = json.load(open(os.path.join(workdir, "result.json")))
    assert got["world"] == 1 and got["gen"] >= 1
    resumed = got["resumed_from_epoch"]
    assert resumed is not None and 1 <= resumed < num_epoch, got
    assert got["epochs_run"] == list(range(resumed, num_epoch)), got

    # the event stream (appended across generations) is schema-valid and
    # records the loss + the resize with a real recovery time
    recs = validate_events(
        os.path.join(workdir, "logs", "elastic", "events.jsonl"),
        require=["host_lost", "world_resize", "checkpoint_saved"],
    )
    resize = [r for r in recs if r["event"] == "world_resize"][-1]
    assert resize["old_world"] == 2 and resize["new_world"] == 1
    assert resize["gen"] == got["gen"]
    assert 0.0 < resize["recovery_s"] < 300.0
    lost = [r for r in recs if r["event"] == "host_lost"][0]
    assert lost["host"] == 1
    # async checkpointing was live: saves carry the overlap split
    async_saves = [
        r for r in recs
        if r["event"] == "checkpoint_saved" and r.get("async")
    ]
    assert async_saves, "no async checkpoint_saved events"
    assert all(
        "snapshot_s" in r and "write_s" in r for r in async_saves
    )

    # trajectory check: a CLEAN 1-process restart from the very rolling
    # checkpoint the resized world resumed from must land on the
    # identical final state
    logs = os.path.join(workdir, "logs")
    roll_by_epoch = {
        int(_meta_of(p)["epoch"]): p
        for p in rolling_checkpoints("elastic", path=logs)
    }
    refdir = str(tmp_path / "ref")
    ref_ck = os.path.join(refdir, "logs", "elastic")
    os.makedirs(ref_ck)
    with open(roll_by_epoch[resumed - 1], "rb") as src, open(
        os.path.join(ref_ck, "elastic.pk"), "wb"
    ) as dst:
        dst.write(src.read())
    env = {
        k: v
        for k, v in os.environ.items()
        if not k.startswith(("HYDRAGNN_FAULT_", "HYDRAGNN_ELASTIC_",
                             "HYDRAGNN_TPU_"))
    }
    worker = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "_elastic_worker.py"
    )
    ref = subprocess.run(
        [sys.executable, worker, "worker", refdir],
        env=env, capture_output=True, text=True, timeout=420,
    )
    assert ref.returncode == 0, ref.stderr[-2000:]
    ref_res = json.load(open(os.path.join(refdir, "result.json")))
    assert ref_res["resumed_from_epoch"] == resumed
    assert ref_res["epochs_run"] == got["epochs_run"]
    assert ref_res["final_lr"] == got["final_lr"]
    np.testing.assert_allclose(
        got["final_params_digest"],
        ref_res["final_params_digest"],
        rtol=0,
        atol=0,
    )
