"""Unified telemetry (hydragnn_tpu/obs): shared metrics core parity with
serving, structured run events + schema validation, ScalarWriter fan-out,
live training /metrics endpoint, padding-waste accounting, honest tracer
sync — and the acceptance e2e: a tiny training with telemetry enabled,
scraped WHILE it runs, leaving a schema-valid events.jsonl behind.
"""

import json
import os
import sys
import time
import urllib.request
import warnings

import numpy as np
import pytest

import jax

from hydragnn_tpu import obs
from hydragnn_tpu.obs import runtime as obs_rt
from hydragnn_tpu.obs.events import RunEventLog, validate_events
from hydragnn_tpu.obs.metrics import MetricsRegistry
from hydragnn_tpu.obs.scalars import (
    CsvScalarBackend,
    JsonlScalarBackend,
    ScalarWriter,
)

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from _resilience_worker import make_samples  # noqa: E402

# ---- shared-core parity with serving -------------------------------------

# render_prometheus() of the PRE-REFACTOR hydragnn_tpu/serve/metrics.py for
# exactly the traffic _drive_serve_traffic() generates — the shared-core
# promotion must keep the serving exposition byte-identical. DELIBERATE
# extension (goodput/SLO PR): the deadline-outcome + SLO-miss series are
# appended AFTER the historical lines, so every pre-existing consumer's
# byte offsets are untouched and the golden grew by exactly that tail.
# DELIBERATE extension (multi-tenant PR): the response-cache series are
# appended after the SLO tail under the same rule.
_GOLDEN_SERVE = """\
# HELP hydragnn_serve_requests_total Accepted requests
# TYPE hydragnn_serve_requests_total counter
hydragnn_serve_requests_total 5
# HELP hydragnn_serve_responses_total Completed requests
# TYPE hydragnn_serve_responses_total counter
hydragnn_serve_responses_total 5
# HELP hydragnn_serve_shed_total Queue-full rejections
# TYPE hydragnn_serve_shed_total counter
hydragnn_serve_shed_total 1
# HELP hydragnn_serve_timeouts_total Deadline expiries
# TYPE hydragnn_serve_timeouts_total counter
hydragnn_serve_timeouts_total 1
# HELP hydragnn_serve_errors_total Failed requests
# TYPE hydragnn_serve_errors_total counter
hydragnn_serve_errors_total 2
# HELP hydragnn_serve_batches_total Dispatched micro-batches
# TYPE hydragnn_serve_batches_total counter
hydragnn_serve_batches_total 2
# HELP hydragnn_serve_compiles_total Novel-shape compiles
# TYPE hydragnn_serve_compiles_total counter
hydragnn_serve_compiles_total 1
# HELP hydragnn_serve_bucket_fallbacks_total Requests served by a larger bucket than their node count
# TYPE hydragnn_serve_bucket_fallbacks_total counter
hydragnn_serve_bucket_fallbacks_total 1
# HELP hydragnn_serve_queue_depth Requests waiting
# TYPE hydragnn_serve_queue_depth gauge
hydragnn_serve_queue_depth 3
# HELP hydragnn_serve_padding_waste_ratio Padded node rows carrying no real node
# TYPE hydragnn_serve_padding_waste_ratio gauge
hydragnn_serve_padding_waste_ratio 0.241071
hydragnn_serve_bucket_hits_total{bucket="32"} 3
hydragnn_serve_bucket_hits_total{bucket="64"} 2
# TYPE hydragnn_serve_request_latency_seconds summary
hydragnn_serve_request_latency_seconds{quantile="0.5"} 0.0375
hydragnn_serve_request_latency_seconds{quantile="0.99"} 2.455
hydragnn_serve_request_latency_seconds_sum 1.732
hydragnn_serve_request_latency_seconds_count 3
# TYPE hydragnn_serve_batch_latency_seconds summary
hydragnn_serve_batch_latency_seconds{quantile="0.5"} 0.025
hydragnn_serve_batch_latency_seconds{quantile="0.99"} 0.495
hydragnn_serve_batch_latency_seconds_sum 0.412
hydragnn_serve_batch_latency_seconds_count 2
# HELP hydragnn_serve_slo_misses_total Deadline-carrying requests that missed their deadline
# TYPE hydragnn_serve_slo_misses_total counter
hydragnn_serve_slo_misses_total 2
hydragnn_serve_deadline_outcomes_total{outcome="met"} 2
hydragnn_serve_deadline_outcomes_total{outcome="missed"} 2
# HELP hydragnn_serve_slo_miss_ratio Fraction of deadline-carrying requests that missed
# TYPE hydragnn_serve_slo_miss_ratio gauge
hydragnn_serve_slo_miss_ratio 0.5
# HELP hydragnn_serve_cache_hits_total Requests answered from the response cache
# TYPE hydragnn_serve_cache_hits_total counter
hydragnn_serve_cache_hits_total 2
# HELP hydragnn_serve_cache_misses_total Cache lookups that fell through to dispatch
# TYPE hydragnn_serve_cache_misses_total counter
hydragnn_serve_cache_misses_total 3
# HELP hydragnn_serve_cache_evictions_total Entries evicted by the LRU bounds
# TYPE hydragnn_serve_cache_evictions_total counter
hydragnn_serve_cache_evictions_total 1
# HELP hydragnn_serve_cache_bytes Resident response-cache payload bytes
# TYPE hydragnn_serve_cache_bytes gauge
hydragnn_serve_cache_bytes 4096
"""


def _drive_serve_traffic(m):
    for _ in range(5):
        m.on_submit()
    m.on_shed()
    m.on_timeout()  # in-queue expiry: also a missed deadline
    m.on_error(2)
    m.on_compile()
    m.set_queue_depth(3)
    m.on_batch(bucket=32, num_requests=3, real_nodes=70, padded_nodes=96,
               batch_seconds=0.012, fallbacks=1)
    m.on_batch(bucket=64, num_requests=2, real_nodes=100, padded_nodes=128,
               batch_seconds=0.4)
    for s in (0.002, 0.03, 1.7):
        m.on_response_latency(s)
    # per-request deadline outcomes (SLO accounting): 2 met, 1 delivered
    # late -> with the timeout above, 2 met / 2 missed, miss ratio 0.5
    m.on_deadline(True)
    m.on_deadline(True)
    m.on_deadline(False)
    # response-cache traffic (multi-tenant PR): 2 hits, 3 misses, one
    # LRU eviction, 4 KiB resident
    m.on_cache_hit(2)
    m.on_cache_miss(3)
    m.on_cache_evict()
    m.set_cache_bytes(4096)
    return m


def pytest_serve_metrics_prometheus_byte_parity():
    from hydragnn_tpu.serve.metrics import ServeMetrics

    m = _drive_serve_traffic(ServeMetrics())
    assert m.render_prometheus() == _GOLDEN_SERVE


def pytest_serve_reexports_shared_core():
    import hydragnn_tpu.serve.http as serve_http
    import hydragnn_tpu.serve.metrics as serve_metrics

    assert serve_metrics.ServeMetrics is obs.ServeMetrics
    assert serve_metrics.LatencyHistogram is obs.LatencyHistogram
    assert serve_http.ObservabilityServer is obs.ObservabilityServer
    # the serve package facade too
    from hydragnn_tpu.serve import ObservabilityServer, ServeMetrics

    assert ServeMetrics is obs.ServeMetrics
    assert ObservabilityServer is obs.ObservabilityServer


# ---- metrics registry ----------------------------------------------------


def pytest_metrics_registry_declare_record_render():
    r = MetricsRegistry("t")
    r.counter("a_total", "help a")
    r.gauge("g", "a gauge")
    r.histogram("lat_seconds", "a histogram")
    r.inc("a_total", 3)
    r.set("g", 0.25)
    r.observe("lat_seconds", 0.01)
    r.observe("lat_seconds", 0.02)
    snap = r.snapshot()
    assert snap["a_total"] == 3
    assert snap["g"] == 0.25
    assert snap["lat_seconds"]["count"] == 2
    text = r.render_prometheus()
    assert "# TYPE t_a_total counter\nt_a_total 3" in text
    assert "# TYPE t_g gauge\nt_g 0.25" in text
    assert 't_lat_seconds{quantile="0.5"}' in text
    assert "t_lat_seconds_count 2" in text
    # declaration order is exposition order
    assert text.index("t_a_total") < text.index("t_g") < text.index(
        "t_lat_seconds"
    )
    with pytest.raises(ValueError):
        r.counter("a_total")


# ---- run-event stream ----------------------------------------------------


def pytest_event_log_roundtrip_and_validation(tmp_path):
    path = str(tmp_path / "events.jsonl")
    log = RunEventLog(path)
    log.emit("run_manifest", schema_version=1, run="r", config_hash="c",
             git_rev="g", world_size=1, device_kind="cpu", device_count=1,
             num_epoch=2)
    log.emit("epoch", epoch=0, train_loss=np.float32(0.5), val_loss=0.6,
             test_loss=0.7, mode="stream", wall_time_s=0.1)
    log.emit("custom_future_event", anything=True)  # unknown types are legal
    # a diverged epoch's NaN losses must yield STRICT JSON (null, not a
    # bare NaN token jq/JS consumers reject)
    log.emit("epoch", epoch=1, train_loss=float("nan"),
             val_loss=np.float32("inf"), test_loss=0.1, mode="stream")
    log.emit("run_end", status="complete")
    log.close()

    def _no_constants(name):
        raise ValueError(f"non-standard JSON constant {name}")

    for line in open(path):
        json.loads(line, parse_constant=_no_constants)  # strict parse
    recs = validate_events(path, require=["run_manifest", "epoch", "run_end"])
    assert [r["seq"] for r in recs] == [0, 1, 2, 3, 4]
    assert recs[1]["train_loss"] == 0.5  # numpy scalar serialized as float
    assert recs[3]["train_loss"] is None  # NaN -> null
    assert recs[3]["val_loss"] is None  # inf -> null
    assert recs[3]["test_loss"] == pytest.approx(0.1)

    with pytest.raises(ValueError, match="never emitted"):
        validate_events(path, require=["guard_restore"])

    # a known type missing a required field is a violation
    bad = str(tmp_path / "bad.jsonl")
    b = RunEventLog(bad)
    b.emit("epoch", epoch=0)
    b.close()
    with pytest.raises(ValueError, match="missing required fields"):
        validate_events(bad)

    # a torn/interleaved stream (seq gap) is a violation
    torn = str(tmp_path / "torn.jsonl")
    with open(torn, "w") as f:
        f.write('{"event": "x", "ts": 1.0, "seq": 0}\n')
        f.write('{"event": "x", "ts": 2.0, "seq": 2}\n')
    with pytest.raises(ValueError, match="seq"):
        validate_events(torn)


def pytest_event_log_append_resumes_seq_and_repairs_torn_tail(tmp_path):
    """A rerun/resume of the same run name continues the stream: seq picks
    up where the previous process stopped, and a hard-kill's partial final
    line (no newline) is truncated away instead of merging with the first
    resumed event."""
    path = str(tmp_path / "events.jsonl")
    log = RunEventLog(path)
    log.emit("run_manifest", schema_version=1, run="r", config_hash="c",
             git_rev="g", world_size=1, device_kind="cpu", device_count=1,
             num_epoch=2)
    log.emit("epoch", epoch=0, train_loss=0.5, val_loss=0.6, test_loss=0.7,
             mode="stream")
    log.close()
    # simulate a SIGKILL mid-write: a partial line with no newline
    with open(path, "a") as f:
        f.write('{"event": "epoch", "ts": 3.0, "se')
    resumed = RunEventLog(path)
    resumed.emit("resume", start_epoch=1)
    resumed.emit("run_end", status="complete")
    resumed.close()
    recs = validate_events(path, require=["resume", "run_end"])
    assert [r["seq"] for r in recs] == [0, 1, 2, 3]
    assert recs[2]["event"] == "resume"  # the torn partial line is gone


# ---- ScalarWriter fan-out ------------------------------------------------


def pytest_scalar_writer_jsonl_roundtrip(tmp_path):
    path = str(tmp_path / "scalars.jsonl")
    w = ScalarWriter([JsonlScalarBackend(path)])
    w.add_scalar("train error", 0.5, 0)
    w.add_scalar("train error", 0.25, 1)
    w.add_regions({"train": 1.5, "dataload": 0.5}, step=2)
    w.close()
    recs = [json.loads(line) for line in open(path)]
    assert [(r["tag"], r["value"], r["step"]) for r in recs] == [
        ("train error", 0.5, 0),
        ("train error", 0.25, 1),
        ("tracer/dataload_seconds", 0.5, 2),
        ("tracer/train_seconds", 1.5, 2),
    ]
    assert all("ts" in r for r in recs)


def pytest_scalar_writer_csv_backend(tmp_path):
    path = str(tmp_path / "scalars.csv")
    w = ScalarWriter([CsvScalarBackend(path)])
    w.add_scalar("loss", 1.25, 3)
    w.close()
    lines = open(path).read().strip().splitlines()
    assert lines[0] == "tag,value,step,ts"
    assert lines[1].startswith("loss,1.25,3,")


def pytest_scalar_writer_for_run_warns_once_without_tensorboard(
    tmp_path, monkeypatch
):
    from hydragnn_tpu.obs import scalars as sc

    monkeypatch.setattr(sc, "_tb_warned", False)

    def _boom(self, log_dir):
        raise ImportError("no torch here")

    monkeypatch.setattr(sc.TensorBoardScalarBackend, "__init__", _boom)
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        w1 = ScalarWriter.for_run("runA", path=str(tmp_path))
        w2 = ScalarWriter.for_run("runB", path=str(tmp_path))
    tb_warnings = [c for c in caught if "TensorBoard" in str(c.message)]
    assert len(tb_warnings) == 1  # exactly once per process
    # the always-on file backend still records
    w1.add_scalar("x", 1.0, 0)
    w1.close()
    w2.close()
    assert os.path.exists(tmp_path / "runA" / "scalars.jsonl")


# ---- no-op fast path -----------------------------------------------------


def pytest_hooks_are_noops_when_inactive():
    obs_rt.deactivate()
    assert obs_rt.active() is None
    n = 100_000
    t0 = time.perf_counter()
    for _ in range(n):
        obs_rt.emit("epoch", epoch=1)
        obs_rt.epoch_complete(1, 0.5, 0.5, 0.5)
        obs_rt.guard_skip("step", 1)
        obs_rt.checkpoint_saved("x", kind="primary")
    dt = time.perf_counter() - t0
    # 400k inactive hook calls; a disabled epoch loop makes a handful per
    # epoch, so even this very lenient bound (~6µs/call) proves the
    # telemetry-off wall time is baseline within noise
    assert dt < 2.5, f"no-op hooks too slow: {dt:.3f}s for {4 * n} calls"


# ---- padding-waste accounting in the loader ------------------------------


def _sized_samples(sizes, seed=3):
    from hydragnn_tpu.data.dataobj import GraphData

    rng = np.random.default_rng(seed)
    out = []
    for n in sizes:
        g = GraphData()
        g.x = rng.random((n, 1)).astype(np.float32)
        g.pos = rng.random((n, 3)).astype(np.float32)
        src = np.arange(n)
        dst = (src + 1) % n
        g.edge_index = np.stack(
            [np.concatenate([src, dst]), np.concatenate([dst, src])]
        ).astype(np.int64)
        g.edge_attr = None
        g.targets = [np.array([g.x.sum()], np.float32), g.x.copy()]
        g.target_types = ["graph", "node"]
        out.append(g)
    return out


def pytest_epoch_padding_stats_plain_and_bucketed():
    from hydragnn_tpu.data.loaders import GraphLoader, compute_layout

    sizes = [4, 6, 8, 12, 5, 9, 11, 4, 7, 10, 6, 8]
    samples = _sized_samples(sizes)
    layout = compute_layout([samples], batch_size=4)
    loader = GraphLoader(
        samples, 4, layout, shuffle=False, num_shards=1, shard_id=0
    )
    real, padded = loader.epoch_padding_stats()
    assert real == sum(sizes)
    assert padded == len(loader) * layout.n_pad
    assert 0.0 < 1.0 - real / padded < 1.0

    bucketed = compute_layout([samples], batch_size=4, num_buckets=2)
    bloader = GraphLoader(
        samples, 4, bucketed, shuffle=False, num_shards=1, shard_id=0
    )
    breal, bpadded = bloader.epoch_padding_stats()
    assert breal == sum(sizes)
    assert bpadded == sum(
        bucketed.layouts[b].n_pad for b, _ in bloader._batch_plan()
    )
    # bucketing exists to cut padding waste — same data, less padding
    assert bpadded <= padded


# ---- honest tracer sync (HYDRAGNN_TRACE_LEVEL=1) -------------------------


def pytest_tracer_sync_absorbs_async_dispatch(monkeypatch):
    import jax.numpy as jnp

    from hydragnn_tpu.utils import tracer as tr

    n = 1800
    x = jnp.ones((n, n))
    f = jax.jit(lambda a: a @ a @ a @ a)
    f(x).block_until_ready()  # compile outside the measurement
    t0 = time.perf_counter()
    f(x).block_until_ready()
    true_t = time.perf_counter() - t0

    monkeypatch.setattr(tr, "_tracers", {"timer": tr.TimerTracer()})
    monkeypatch.setattr(tr, "_enabled", True)

    # without the sync, stop() returns while the compute is still in
    # flight — the region absorbs ~none of it
    monkeypatch.delenv("HYDRAGNN_TRACE_LEVEL", raising=False)
    tr.start("nosync")
    y = f(x)
    tr.stop("nosync")
    y.block_until_ready()
    no_sync = tr._tracers["timer"].acc["nosync"]
    if no_sync > 0.5 * true_t:
        pytest.skip("backend dispatch is synchronous here; nothing to test")

    monkeypatch.setenv("HYDRAGNN_TRACE_LEVEL", "1")
    tr.start("synced")
    y = f(x)
    tr.stop("synced")  # must block until the dispatched matmuls finish
    synced = tr._tracers["timer"].acc["synced"]
    assert synced >= 0.5 * true_t, (
        f"traced region absorbed {synced:.4f}s of a {true_t:.4f}s "
        "async computation — trace level 1 is not device-syncing"
    )


# ---- env/config knobs ----------------------------------------------------


def pytest_init_run_telemetry_knobs(tmp_path, monkeypatch):
    cfg = {"NeuralNetwork": {"Training": {"num_epoch": 3}}}

    monkeypatch.setenv("HYDRAGNN_TELEMETRY", "0")
    assert obs_rt.init_run_telemetry(cfg, "off", path=str(tmp_path)) is None
    assert obs_rt.active() is None

    monkeypatch.delenv("HYDRAGNN_TELEMETRY")
    monkeypatch.setenv("HYDRAGNN_OBS_PORT", "0")
    telem = obs_rt.init_run_telemetry(cfg, "on", path=str(tmp_path))
    try:
        assert telem is not None and obs_rt.active() is telem
        host, port = telem.address
        health = json.loads(
            urllib.request.urlopen(
                f"http://{host}:{port}/healthz", timeout=10
            ).read()
        )
        assert health["status"] == "ok" and health["run"] == "on"
    finally:
        obs_rt.deactivate()
    recs = validate_events(
        str(tmp_path / "on" / "events.jsonl"),
        require=["run_manifest", "run_end"],
    )
    man = recs[0]
    assert man["num_epoch"] == 3
    assert man["device_kind"] == "cpu"
    assert man["world_size"] == 1
    assert len(man["config_hash"]) == 12


# ---- the acceptance e2e --------------------------------------------------


def _build_tiny_training(num_epoch):
    from hydragnn_tpu.data.loaders import GraphLoader, compute_layout
    from hydragnn_tpu.models.create import create_model_config
    from hydragnn_tpu.train.trainer import Trainer

    arch = {
        "model_type": "GIN",
        "input_dim": 1,
        "hidden_dim": 8,
        "num_conv_layers": 2,
        "output_dim": [1, 1],
        "output_type": ["graph", "node"],
        "output_heads": {
            "graph": {
                "num_sharedlayers": 1,
                "dim_sharedlayers": 8,
                "num_headlayers": 1,
                "dim_headlayers": [8],
            },
            "node": {"num_headlayers": 1, "dim_headlayers": [8],
                     "type": "mlp"},
        },
        "task_weights": [1.0, 1.0],
    }
    training = {
        "num_epoch": num_epoch,
        "Optimizer": {"type": "AdamW", "learning_rate": 1e-2},
        "resume_every": 1,
        "divergence_guard": True,
    }
    samples = make_samples()
    layout = compute_layout([samples], batch_size=4)
    loaders = (
        GraphLoader(samples[:16], 4, layout, shuffle=True, seed=7),
        GraphLoader(samples[16:20], 4, layout, shuffle=False),
        GraphLoader(samples[20:], 4, layout, shuffle=False),
    )
    model = create_model_config(arch)
    trainer = Trainer(model, training)
    state = trainer.init_state(next(iter(loaders[0])), seed=0)
    return trainer, state, loaders, training


class _ScrapeOnEpochWriter:
    """writer= hook that scrapes the live endpoint DURING the run (at the
    first epoch>=1 scalar) — the 'concurrent /metrics' acceptance leg."""

    def __init__(self, url):
        self.url = url
        self.scraped = None

    def add_scalar(self, tag, value, step):
        if self.scraped is None and step >= 1:
            self.scraped = urllib.request.urlopen(
                self.url, timeout=10
            ).read().decode()

    def close(self):
        pass


def pytest_training_telemetry_e2e(tmp_path, monkeypatch):
    from hydragnn_tpu.train.epoch_driver import train_validate_test

    monkeypatch.chdir(tmp_path)
    # one poisoned step so the guard path emits into the same stream
    monkeypatch.setenv("HYDRAGNN_FAULT_NAN_AT_STEP", "2")
    num_epoch = 3
    trainer, state, loaders, training = _build_tiny_training(num_epoch)
    assert trainer.guard is not None

    telem = obs_rt.activate(
        obs_rt.RunTelemetry(
            "obs-e2e", str(tmp_path / "logs" / "obs-e2e"), port=0
        )
    )
    try:
        telem.emit_manifest(
            {"NeuralNetwork": {"Training": training}}, "obs-e2e"
        )
        host, port = telem.address
        writer = _ScrapeOnEpochWriter(f"http://{host}:{port}/metrics")
        config_nn = {
            "Training": training,
            "Variables_of_interest": {"output_names": ["sum", "x"]},
        }
        train_validate_test(
            trainer, state, *loaders, config_nn, "obs-e2e", verbosity=0,
            writer=writer,
        )

        # -- concurrent scrape returned live epoch/throughput/guard series
        assert writer.scraped is not None, "mid-run scrape never happened"
        mid = writer.scraped
        assert "hydragnn_train_epochs_total" in mid
        assert "hydragnn_train_graphs_per_second" in mid
        assert "hydragnn_train_guard_skips_total 1" in mid
        assert "hydragnn_train_heartbeat_age_seconds" in mid

        # -- end-of-run metrics state
        final = urllib.request.urlopen(
            f"http://{host}:{port}/metrics", timeout=10
        ).read().decode()
        snap = telem.metrics.snapshot()
        assert snap["epochs_total"] == num_epoch
        assert snap["guard_skips_total"] == 1
        assert snap["checkpoints_saved_total"] >= num_epoch
        assert snap["steps_total"] == num_epoch * 4  # 16 samples / bs 4
        assert snap["epoch_seconds"]["count"] == num_epoch
        assert f"hydragnn_train_epoch {float(num_epoch - 1)}" in final

        health = json.loads(
            urllib.request.urlopen(
                f"http://{host}:{port}/healthz", timeout=10
            ).read()
        )
        assert health["status"] == "ok"
        assert health["epoch"] == num_epoch - 1
    finally:
        obs_rt.deactivate()

    # -- the event stream validates against the documented schema
    recs = validate_events(
        str(tmp_path / "logs" / "obs-e2e" / "events.jsonl"),
        require=[
            "run_manifest", "epoch", "checkpoint_saved", "guard_skip",
            "run_end",
        ],
    )
    epochs = [r for r in recs if r["event"] == "epoch"]
    assert [e["epoch"] for e in epochs] == list(range(num_epoch))
    assert all(e["wall_time_s"] > 0 for e in epochs)
    assert all(e["graphs_per_sec"] > 0 for e in epochs)
    assert all(0.0 <= e["padding_waste"] < 1.0 for e in epochs)
    assert all(e["mode"] == "stream" for e in epochs)
    ckpts = [r for r in recs if r["event"] == "checkpoint_saved"]
    assert all(c["kind"] == "primary" and c["resumable"] for c in ckpts)
    guard = [r for r in recs if r["event"] == "guard_skip"]
    assert len(guard) == 1 and guard[0]["scope"] == "step"
    assert recs[-1]["event"] == "run_end"
    assert recs[-1]["status"] == "complete"


def pytest_fit_staged_epochs_report_train_time(tmp_path, monkeypatch):
    """The fit-staged path used to log no train time/throughput at all;
    now each epoch carries chunk_time/n and the chunk emits fit_chunk."""
    from hydragnn_tpu.train.epoch_driver import train_validate_test

    monkeypatch.chdir(tmp_path)
    monkeypatch.setenv("HYDRAGNN_DEVICE_RESIDENT", "1")
    monkeypatch.setenv("HYDRAGNN_FIT_CHUNK", "2")
    num_epoch = 4
    trainer, state, loaders, training = _build_tiny_training(num_epoch)
    trainer.guard = None  # guard is epoch-granular on the fit path anyway

    telem = obs_rt.activate(
        obs_rt.RunTelemetry(
            "obs-fit", str(tmp_path / "logs" / "obs-fit"), port=None
        )
    )
    try:
        config_nn = {
            "Training": training,
            "Variables_of_interest": {"output_names": ["sum", "x"]},
        }
        train_validate_test(
            trainer, state, *loaders, config_nn, "obs-fit", verbosity=0,
        )
    finally:
        obs_rt.deactivate()
    recs = validate_events(
        str(tmp_path / "logs" / "obs-fit" / "events.jsonl"),
        require=["fit_chunk", "epoch", "staged"],
    )
    chunks = [r for r in recs if r["event"] == "fit_chunk"]
    assert [c["epoch_start"] for c in chunks] == [0, 2]
    assert all(c["epochs"] == 2 and c["wall_time_s"] > 0 for c in chunks)
    epochs = [r for r in recs if r["event"] == "epoch"]
    assert len(epochs) == num_epoch
    assert all(e["mode"] == "fit" for e in epochs)
    assert all(e["wall_time_s"] > 0 for e in epochs)
    assert all(e["graphs_per_sec"] > 0 for e in epochs)
