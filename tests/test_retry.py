"""Direct unit coverage for ``utils/retry.py`` — previously exercised
only through the resilience e2e: jittered-backoff bounds, the attempt
cap, non-retryable passthrough, and the env-knob defaults.
"""

import os

import pytest

from hydragnn_tpu.utils import retry
from hydragnn_tpu.utils.retry import retry_io


def _always_fail(record):
    def fn():
        record.append(1)
        raise OSError("transient")

    return fn


def pytest_backoff_delays_doubled_with_bounded_jitter(monkeypatch):
    """Delay i must be ``base * 2**i`` stretched by the uniform jitter
    factor in [1.0, 1.5) — never shorter (a stampede re-sync) and never
    past the +50% bound."""
    delays = []
    monkeypatch.setattr(retry.time, "sleep", delays.append)
    calls = []
    base = 0.05
    with pytest.raises(OSError):
        retry_io(_always_fail(calls), attempts=4, base_delay=base)
    assert len(calls) == 4
    assert len(delays) == 3  # no sleep after the final attempt
    for i, d in enumerate(delays):
        lo = base * (2.0 ** i)
        assert lo <= d <= lo * 1.5, (i, d)
    # the jitter draw actually varies (not a fixed multiplier)
    monkeypatch.setattr(
        retry.random, "uniform", lambda a, b: 0.5
    )
    delays2 = []
    monkeypatch.setattr(retry.time, "sleep", delays2.append)
    with pytest.raises(OSError):
        retry_io(_always_fail([]), attempts=3, base_delay=base)
    assert delays2 == [base * 1.5, base * 2 * 1.5]


def pytest_attempt_cap_is_exact(monkeypatch):
    monkeypatch.setattr(retry.time, "sleep", lambda s: None)
    for attempts in (1, 2, 5):
        calls = []
        with pytest.raises(OSError, match="transient"):
            retry_io(_always_fail(calls), attempts=attempts,
                     base_delay=0.001)
        assert len(calls) == attempts
    # nonsensical budgets clamp to one attempt, not zero (which would
    # re-raise a stale/None error)
    calls = []
    with pytest.raises(OSError):
        retry_io(_always_fail(calls), attempts=0, base_delay=0.001)
    assert len(calls) == 1


def pytest_success_after_transient_failures(monkeypatch):
    monkeypatch.setattr(retry.time, "sleep", lambda s: None)
    state = {"n": 0}

    def flaky():
        state["n"] += 1
        if state["n"] < 3:
            raise OSError("transient")
        return "data"

    assert retry_io(flaky, attempts=5, base_delay=0.001) == "data"
    assert state["n"] == 3


def pytest_non_retryable_exceptions_pass_through(monkeypatch):
    sleeps = []
    monkeypatch.setattr(retry.time, "sleep", sleeps.append)

    # FileNotFoundError: an OSError subclass, but a wrong path is not
    # transient — one attempt, zero sleeps
    calls = []

    def missing():
        calls.append(1)
        raise FileNotFoundError("gone")

    with pytest.raises(FileNotFoundError):
        retry_io(missing, attempts=5, base_delay=0.001)
    assert len(calls) == 1 and sleeps == []

    # non-OSError exceptions (bad data, logic bugs) propagate immediately
    calls = []

    def corrupt():
        calls.append(1)
        raise ValueError("bad payload")

    with pytest.raises(ValueError, match="bad payload"):
        retry_io(corrupt, attempts=5, base_delay=0.001)
    assert len(calls) == 1 and sleeps == []


def pytest_env_knobs_default_the_budget(monkeypatch):
    monkeypatch.setattr(retry.time, "sleep", lambda s: None)
    monkeypatch.setenv("HYDRAGNN_IO_RETRIES", "2")
    monkeypatch.setenv("HYDRAGNN_IO_RETRY_BASE_S", "0.001")
    calls = []
    with pytest.raises(OSError):
        retry_io(_always_fail(calls))  # attempts=None reads the env
    assert len(calls) == 2
    # explicit argument beats the env
    calls = []
    with pytest.raises(OSError):
        retry_io(_always_fail(calls), attempts=3, base_delay=0.001)
    assert len(calls) == 3
    assert os.getenv("HYDRAGNN_IO_RETRIES") == "2"
