"""Graph-partition parallelism: exact parity with the unpartitioned model.

One giant random graph is sharded node-wise over a 4-device mesh axis
(``parallel/graph_partition.py``); forward outputs, loss, and one full
training step must match the single-device model to float32 tolerance —
the collectives (halo all_to_all, BN/pool/loss psums, grad psum) are
numerically transparent by design.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from hydragnn_tpu.graph.batch import collate_graphs, pad_sizes_for
from hydragnn_tpu.models.create import create_model_config, init_model_params
from hydragnn_tpu.parallel.graph_partition import (
    make_partitioned_apply,
    make_partitioned_train_step,
    partition_graph,
    put_partitioned_batch,
)
from hydragnn_tpu.parallel.mesh import make_mesh


HEAD_TYPES = ("graph", "node")
HEAD_DIMS = (1, 1)
NUM_PARTS = 4


class _S:
    pass


def _giant_graph(n=70, seed=0, k=4):
    """Random geometric-ish graph: each node connects to k random others,
    symmetrized (the radius-graph shape all reference datasets use)."""
    rng = np.random.default_rng(seed)
    s = _S()
    s.x = rng.random((n, 3)).astype(np.float32)
    s.pos = rng.random((n, 3)).astype(np.float32)
    src = np.repeat(np.arange(n), k)
    dst = (src + rng.integers(1, n, src.shape[0])) % n
    se = np.concatenate([src, dst])
    re = np.concatenate([dst, src])
    # dedup directed pairs so halo slot bookkeeping sees a clean edge list
    pairs = np.unique(np.stack([se, re], 1), axis=0)
    pairs = pairs[pairs[:, 0] != pairs[:, 1]]
    s.edge_index = pairs.T.astype(np.int64)
    s.edge_attr = None
    s.targets = [
        np.array([s.x.sum() / n], np.float32),
        (s.x[:, :1] * 2.0).astype(np.float32),
    ]
    return s


def _arch(model_type, extra=None):
    cfg = {
        "model_type": model_type,
        "input_dim": 3,
        "hidden_dim": 16,
        "output_dim": list(HEAD_DIMS),
        "output_type": list(HEAD_TYPES),
        "output_heads": {
            "graph": {
                "num_sharedlayers": 1,
                "dim_sharedlayers": 8,
                "num_headlayers": 1,
                "dim_headlayers": [8],
            },
            "node": {
                "num_headlayers": 1,
                "dim_headlayers": [8],
                "type": "mlp",
            },
        },
        "task_weights": [1.0, 1.0],
        "num_conv_layers": 2,
        "max_neighbours": 10,
        "num_gaussians": 10,
        "num_filters": 8,
        "radius": 2.0,
        "basis_emb_size": 4,
        "envelope_exponent": 5,
        "int_emb_size": 8,
        "out_emb_size": 8,
        "num_after_skip": 1,
        "num_before_skip": 1,
        "num_radial": 3,
        "num_spherical": 2,
        "pna_deg": [0, 10, 20, 10, 5, 2, 1, 1, 1, 1],
    }
    if extra:
        cfg.update(extra)
    return cfg


def _single_batch(sample, need_triplets=False):
    if need_triplets:
        # go through the PRODUCTION collation path so the triplet padding
        # contract is exercised, not re-implemented
        from hydragnn_tpu.data.dataobj import GraphData
        from hydragnn_tpu.data.loaders import GraphLoader, compute_layout

        g = GraphData(
            x=sample.x,
            pos=sample.pos,
            edge_index=sample.edge_index,
            edge_attr=sample.edge_attr,
        )
        g.targets = list(sample.targets)
        g.target_types = list(HEAD_TYPES)
        layout = compute_layout([[g]], batch_size=1, need_triplets=True)
        (batch,) = list(
            GraphLoader([g], 1, layout, shuffle=False, num_shards=1, shard_id=0)
        )
        return jax.tree_util.tree_map(jnp.asarray, batch)
    n = sample.x.shape[0]
    e = sample.edge_index.shape[1]
    n_pad, e_pad, g_pad = pad_sizes_for(n, e, 1)
    return collate_graphs(
        [sample], n_pad, e_pad, g_pad, HEAD_TYPES, HEAD_DIMS, to_device=True
    )


def _partitioned(sample, mesh, need_triplets=False):
    batch, info = partition_graph(
        sample,
        NUM_PARTS,
        HEAD_TYPES,
        HEAD_DIMS,
        order="morton",
        need_triplets=need_triplets,
    )
    return put_partitioned_batch(batch, mesh, "graph"), info


def _models(model_type, extra=None):
    cfg = _arch(model_type, extra)
    ref = create_model_config(dict(cfg))
    cfg_p = dict(cfg)
    cfg_p["partition_axis"] = "graph"
    part = create_model_config(cfg_p)
    return ref, part


def pytest_partitioner_covers_graph():
    sample = _giant_graph()
    batch, info = partition_graph(sample, NUM_PARTS, HEAD_TYPES, HEAD_DIMS)
    n = sample.x.shape[0]
    # every real node exactly once, features preserved
    x_back = info.gather_nodes(np.asarray(batch.x))
    np.testing.assert_allclose(x_back, sample.x, rtol=0, atol=0)
    # edges conserved
    assert int(np.asarray(batch.edge_mask).sum()) == sample.edge_index.shape[1]
    # n_node[0] of every part records the global real count
    n_node = np.asarray(batch.n_node).reshape(NUM_PARTS, 2)
    assert (n_node[:, 0] == n).all()


@pytest.mark.parametrize(
    "model_type",
    ["PNA", "GIN", "SAGE", "MFC", "CGCNN", "GAT", "SchNet", "EGNN", "DimeNet"],
)
def pytest_partitioned_forward_parity(model_type):
    sample = _giant_graph(seed=3)
    extra = (
        {"equivariance": True}
        if model_type in ("SchNet", "EGNN")
        else None
    )
    if model_type == "DimeNet":
        extra = {"hidden_dim": 8}  # DIMEStack: hidden = in_dim for in>1
    need_triplets = model_type == "DimeNet"
    ref_model, part_model = _models(model_type, extra)
    single = _single_batch(sample, need_triplets=need_triplets)
    variables = init_model_params(ref_model, single, seed=0)

    ref_out = ref_model.apply(variables, single, train=False)

    mesh = make_mesh(NUM_PARTS, "graph")
    pbatch, info = _partitioned(sample, mesh, need_triplets=need_triplets)
    part_out = make_partitioned_apply(part_model, mesh, "graph")(variables, pbatch)

    # graph head: replicated rows, every shard's row 0 equals the reference
    g_ref = np.asarray(ref_out[0])[0]
    g_part = np.asarray(part_out[0]).reshape(NUM_PARTS, 2, -1)
    for p in range(NUM_PARTS):
        np.testing.assert_allclose(g_part[p, 0], g_ref, rtol=2e-4, atol=2e-5)

    # node head: gather shard rows back to global order
    n = sample.x.shape[0]
    node_ref = np.asarray(ref_out[1])[:n]
    node_part = info.gather_nodes(np.asarray(part_out[1]))
    np.testing.assert_allclose(node_part, node_ref, rtol=2e-4, atol=2e-5)


def pytest_partitioned_nll_loss_parity():
    """Uncertainty-weighted NLL mode under graph partitioning: the psum'd
    masked NLL and the collected (log-variance-stripped) predictions match
    the unpartitioned model."""
    sample = _giant_graph(seed=5)
    ref_model, part_model = _models("PNA", {"ilossweights_nll": 1})
    single = _single_batch(sample)
    variables = init_model_params(ref_model, single, seed=0)
    ref_out = ref_model.apply(variables, single, train=False)
    ref_tot, ref_tasks = ref_model.loss(ref_out, single)

    mesh = make_mesh(NUM_PARTS, "graph")
    pbatch, info = _partitioned(sample, mesh)
    part_out = make_partitioned_apply(part_model, mesh, "graph")(
        variables, pbatch
    )
    # heads carry the extra log-variance channel in both layouts
    d = ref_model.output_dim[0]
    assert np.asarray(ref_out[0]).shape[-1] == d + 1
    g_ref = np.asarray(ref_out[0])[0]
    g_part = np.asarray(part_out[0]).reshape(NUM_PARTS, 2, -1)
    for p in range(NUM_PARTS):
        np.testing.assert_allclose(g_part[p, 0], g_ref, rtol=2e-4, atol=2e-5)
    # the partitioned psum'd loss equals the single-device loss
    from hydragnn_tpu.parallel.graph_partition import (
        make_partitioned_eval_step,
    )

    pmetrics = make_partitioned_eval_step(part_model, mesh, "graph")(
        variables["params"], variables.get("batch_stats", {}), pbatch
    )
    np.testing.assert_allclose(
        float(pmetrics["loss"]), float(ref_tot), rtol=2e-4, atol=1e-6
    )


def pytest_partitioned_train_step_parity():
    """One full training step (loss + grads + SGD update) matches."""
    import optax

    sample = _giant_graph(seed=7)
    ref_model, part_model = _models("PNA")
    single = _single_batch(sample)
    variables = init_model_params(ref_model, single, seed=0)
    params = variables["params"]
    batch_stats = variables.get("batch_stats", {})
    # SGD: parameter deltas are linear in the gradient, so the comparison
    # is well-conditioned (adamw's g/sqrt(g^2) amplifies near-zero-grad noise)
    tx = optax.sgd(1e-2)

    # reference step (single device)
    def ref_loss(p):
        vs = {"params": p, "batch_stats": batch_stats}
        out, mut = ref_model.apply(
            vs,
            single,
            train=True,
            mutable=["batch_stats"],
            rngs={"dropout": jax.random.PRNGKey(5)},
        )
        tot, _ = ref_model.loss(out, single)
        return tot, mut["batch_stats"]

    (ref_tot, ref_bs), ref_grads = jax.value_and_grad(ref_loss, has_aux=True)(
        params
    )

    # the reference optimizer step (before the donating partitioned step
    # consumes the param buffers)
    updates, _ = tx.update(ref_grads, tx.init(params), params)
    ref_new = optax.apply_updates(params, updates)
    ref_new = jax.tree_util.tree_map(np.asarray, ref_new)
    ref_bs = jax.tree_util.tree_map(np.asarray, ref_bs)
    ref_tot = float(ref_tot)

    mesh = make_mesh(NUM_PARTS, "graph")
    pbatch, _ = _partitioned(sample, mesh)

    from hydragnn_tpu.train.trainer import TrainState

    state = TrainState(
        params=params,
        batch_stats=batch_stats,
        opt_state=tx.init(params),
        step=jnp.zeros((), jnp.int32),
    )
    step = make_partitioned_train_step(part_model, tx, mesh, "graph")
    new_state, metrics = step(state, pbatch, jax.random.PRNGKey(5))

    np.testing.assert_allclose(
        float(metrics["loss"]), ref_tot, rtol=2e-4, atol=1e-6
    )
    flat_ref = jax.tree_util.tree_leaves(ref_new)
    flat_new = jax.tree_util.tree_leaves(new_state.params)
    for a, b in zip(flat_ref, flat_new):
        np.testing.assert_allclose(
            np.asarray(b), np.asarray(a), rtol=3e-4, atol=3e-6
        )

    # BN running stats psum'd across shards == single-device stats
    flat_ref_bs = jax.tree_util.tree_leaves(ref_bs)
    flat_new_bs = jax.tree_util.tree_leaves(new_state.batch_stats)
    for a, b in zip(flat_ref_bs, flat_new_bs):
        np.testing.assert_allclose(
            np.asarray(b), np.asarray(a), rtol=2e-4, atol=2e-5
        )


def _check_partitioned_dense_parity(model_type, extra, seed):
    """Shared parity contract: partitioned+dense forward must equal the
    unpartitioned segment model; returns pieces for extra checks."""
    sample = _giant_graph(seed=seed)
    ref_model, part_model = _models(model_type, extra)
    single = _single_batch(sample)
    variables = init_model_params(ref_model, single, seed=0)
    ref_out = ref_model.apply(variables, single, train=False)

    mesh = make_mesh(NUM_PARTS, "graph")
    pbatch, info = partition_graph(
        sample, NUM_PARTS, HEAD_TYPES, HEAD_DIMS, order="morton",
        need_neighbors=True,
    )
    assert "nbr_idx" in pbatch.extras and info.k_in > 0
    pbatch = put_partitioned_batch(pbatch, mesh, "graph")
    part_out = make_partitioned_apply(part_model, mesh, "graph")(
        variables, pbatch
    )
    g_ref = np.asarray(ref_out[0])[0]
    g_part = np.asarray(part_out[0]).reshape(NUM_PARTS, 2, -1)
    for p in range(NUM_PARTS):
        np.testing.assert_allclose(g_part[p, 0], g_ref, rtol=2e-4, atol=2e-5)
    n = sample.x.shape[0]
    node_ref = np.asarray(ref_out[1])[:n]
    node_part = info.gather_nodes(np.asarray(part_out[1]))
    np.testing.assert_allclose(node_part, node_ref, rtol=2e-4, atol=2e-5)
    return part_model, variables, pbatch, mesh


def pytest_partitioned_dense_aggregation_parity():
    """Dense neighbor lists under graph partitioning: per-shard lists over
    the extended (local+halo) node table, gather through halos, backward
    through reverse lists — outputs must equal the unpartitioned segment
    model exactly like the standard partitioned path does."""
    part_model, variables, pbatch, mesh = _check_partitioned_dense_parity(
        "PNA", None, seed=7
    )

    # and the partitioned TRAIN step runs with dense lists
    import optax

    from hydragnn_tpu.parallel.graph_partition import (
        make_partitioned_train_step,
    )
    from hydragnn_tpu.train.trainer import TrainState

    tx = optax.adamw(1e-3)
    state = TrainState(
        params=variables["params"],
        batch_stats=variables.get("batch_stats", {}),
        opt_state=tx.init(variables["params"]),
        step=jnp.zeros((), jnp.int32),
    )
    step = make_partitioned_train_step(part_model, tx, mesh, "graph")
    state, metrics = step(state, pbatch, jax.random.PRNGKey(0))
    assert np.isfinite(float(metrics["loss"]))


def pytest_partitioned_dense_egnn_sender_side():
    """EGNN under partition + dense lists: sender-side reverse-list
    aggregation composes with halo_reduce; forward parity vs the
    unpartitioned segment model."""
    _check_partitioned_dense_parity("EGNN", {"equivariance": True}, seed=9)
