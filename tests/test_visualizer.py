"""Visualizer smoke tests: every diagnostic renders and lands on disk
(catalog parity with ``hydragnn/postprocess/visualizer.py:24-742``)."""

import os

import numpy as np

from hydragnn_tpu.postprocess.visualizer import Visualizer


def pytest_visualizer_catalog(tmp_path):
    cwd = os.getcwd()
    os.chdir(tmp_path)
    try:
        rng = np.random.default_rng(0)
        num_nodes = 6
        graphs = 40
        viz = Visualizer(
            "vis_test",
            num_heads=2,
            head_dims=[1, 3],
            num_nodes_list=[num_nodes] * graphs,
        )
        t_g = rng.random((graphs, 1))
        p_g = t_g + 0.05 * rng.standard_normal((graphs, 1))
        t_n = rng.random((graphs * num_nodes, 3))
        p_n = t_n + 0.05 * rng.standard_normal(t_n.shape)
        tv = [t_g, t_n]
        pv = [p_g, p_n]

        viz.num_nodes_plot()
        viz.create_scatter_plots(tv, pv, output_names=["energy", "forces"])
        viz.create_error_histograms(tv, pv, output_names=["energy", "forces"])
        viz.create_plot_global(tv, pv, output_names=["energy", "forces"])
        viz.create_plot_global_analysis(tv, pv, output_names=["energy", "forces"])
        viz.create_parity_plot_vector(tv, pv, ihead=1, output_name="forces")
        viz.create_error_histogram_per_node(
            [t_g, t_n[:, :1]], [p_g, p_n[:, :1]], ihead=1, output_name="f0"
        )
        viz.create_parity_plot_and_error_histogram_scalar(
            tv, pv, ihead=0, output_name="energy"
        )
        viz.create_parity_plot_per_node_vector(
            tv, pv, ihead=1, output_name="forces"
        )
        viz.plot_history(
            np.geomspace(1, 0.1, 5), np.geomspace(1, 0.12, 5), np.geomspace(1, 0.13, 5)
        )
        # per-task panels + pickled series (reference visualizer.py:629-690)
        viz.plot_history(
            np.geomspace(1, 0.1, 5),
            np.geomspace(1, 0.12, 5),
            np.geomspace(1, 0.13, 5),
            task_loss_train=np.abs(rng.standard_normal((5, 2))) + 0.01,
            task_weights=[0.5, 0.5],
            task_names=["energy", "forces"],
        )

        out = os.path.join("logs", "vis_test")
        expected = [
            "num_nodes.png",
            "scatter_energy.png",
            "scatter_forces.png",
            "error_hist_energy.png",
            "parity_all_heads.png",
            "global_analysis.png",
            "parity_vector_forces.png",
            "error_hist_per_node_f0.png",
            "parity_and_hist_energy.png",
            "parity_per_node_vector_forces.png",
            "history_loss.png",
            "history_loss.pckl",
            # create_scatter_plots dispatch (reference :693-727): vector
            # head -> component parity; scalar head -> parity+hist panel
            "parity_vector_forces.png",
            "parity_and_hist_energy.png",
            # create_plot_global runs the per-head deep analysis too
            "energy_scatter_condm_err.png",
            "forces_scatter_condm_err.png",
        ]
        for f in expected:
            assert os.path.isfile(os.path.join(out, f)), f

        # conditional mean is flat-ish for homoscedastic noise
        centers, cm = Visualizer._err_condmean(t_g, p_g - t_g, bins=5)
        assert centers.shape == (5,) and np.all(cm >= 0)
    finally:
        os.chdir(cwd)
