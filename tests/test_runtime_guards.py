"""Runtime correctness guards (hydragnn_tpu/analysis/guards.py).

Acceptance (ISSUE 4):

- recompile sentinel: ``steps.train_step`` compiles exactly once per
  batch shape — the compile counter stays FLAT across 2 further epochs
  of varying (bucketed) batches, and across a 100-request serve burst.
- transfer guard: one train epoch and one serve dispatch run under
  ``jax.transfer_guard_device_to_host("disallow")`` — the hot paths'
  only fetches are explicit ``jax.device_get`` calls, so they pass; a
  reintroduced per-batch ``float()`` hard-errors (asserted where the
  backend actually guards transfers; the CPU backend is host-resident
  and has no transfer to guard, so enforcement is probed and skipped
  there rather than faked).

Kept deliberately small: tiny model, few batches — the sentinel logic is
about *counts*, not scale.
"""

import numpy as np
import pytest

import jax

from hydragnn_tpu.analysis.guards import (
    CompileSentinel,
    RecompileError,
    no_host_syncs,
    transfer_guard_available,
)
from hydragnn_tpu.graph import collate_graphs, pad_sizes_for
from hydragnn_tpu.models import create_model_config
from hydragnn_tpu.train.trainer import Trainer

from test_models_forward import FakeData, arch_config


def _batches(num_batches, num_graphs=4, max_n=6, seed=0):
    """Shape-uniform batches at one (max_n-derived) padded layout."""
    rng = np.random.default_rng(seed)
    n_pad, e_pad, g_pad = pad_sizes_for(
        max_n, 2 * max_n, num_graphs, graph_multiple=8
    )
    return [
        collate_graphs(
            [
                FakeData(rng, int(rng.integers(3, max_n + 1)))
                for _ in range(num_graphs)
            ],
            n_pad,
            e_pad,
            g_pad,
            head_types=("graph", "node"),
            head_dims=(1, 1),
        )
        for _ in range(num_batches)
    ]


class ListLoader:
    def __init__(self, batches):
        self.batches = batches

    def __len__(self):
        return len(self.batches)

    def __iter__(self):
        return iter(self.batches)

    def set_epoch(self, epoch):
        pass


_H = {}


def _trainer():
    """Module-shared trainer + two-bucket batch mix (compile once)."""
    if _H:
        return _H
    model = create_model_config(arch_config("SAGE"))
    trainer = Trainer(
        model, {"Optimizer": {"type": "AdamW", "learning_rate": 1e-3}}
    )
    # two distinct padded shapes = a bucketed epoch's compile surface
    batches = _batches(2, max_n=6, seed=0) + _batches(2, max_n=10, seed=1)
    state = trainer.init_state(batches[0])
    _H.update(trainer=trainer, state=state, batches=batches)
    return _H


# ---- recompile sentinel ---------------------------------------------------


def pytest_sentinel_detects_a_leaked_shape():
    """Negative control: the sentinel must actually trip on a novel
    shape (via the jit cache even when the persistent compile cache
    absorbs the backend compile)."""
    f = jax.jit(lambda x: x * 2.0)
    f(np.ones(4, np.float32))  # warm shape A
    with pytest.raises(RecompileError):
        with CompileSentinel(fns=[f]):
            f(np.ones(8, np.float32))  # novel shape B


def pytest_sentinel_flat_on_warm_shapes():
    f = jax.jit(lambda x: x * 2.0)
    f(np.ones(4, np.float32))
    with CompileSentinel(fns=[f]) as sentinel:
        for _ in range(10):
            f(np.ones(4, np.float32))
    sentinel.assert_flat("warm replay")


def pytest_train_step_compiles_once_across_two_epochs():
    """The acceptance run: warm one epoch over BOTH bucket shapes, then
    two further epochs must add zero compiles and zero jit-cache entries
    on the compiled step."""
    h = _trainer()
    trainer, state, batches = h["trainer"], h["state"], h["batches"]
    loader = ListLoader(batches)
    rng = jax.random.PRNGKey(0)
    # warmup epoch: compiles one executable per bucket shape (+ the
    # metric-accumulation programs)
    state, rng, loss, _ = trainer.train_epoch(state, loader, rng)
    assert np.isfinite(loss)
    with CompileSentinel(fns=[trainer._train_step]) as sentinel:
        for _ in range(2):
            state, rng, loss, _ = trainer.train_epoch(state, loader, rng)
            assert np.isfinite(loss)
    sentinel.assert_flat("2 bucketed epochs after warmup")
    _H["state"] = state  # step donates; keep the live one for other tests


# ---- transfer guard -------------------------------------------------------


def _guard_enforces() -> bool:
    """Does this backend actually error on implicit D2H transfers? The
    CPU platform stores arrays host-side — nothing to guard."""
    if not transfer_guard_available():
        return False
    x = jax.jit(lambda v: v + 1)(np.ones((), np.float32))
    try:
        with no_host_syncs():
            float(x)
        return False
    except Exception:
        return True


def pytest_transfer_guard_train_epoch_runs_clean():
    """One full streaming epoch under the guard: every put is H2D (out
    of scope), the epoch's ONE readback is an explicit device_get — so
    a guarded run completes and matches an unguarded one."""
    h = _trainer()
    trainer, state, batches = h["trainer"], h["state"], h["batches"]
    loader = ListLoader(batches)
    with no_host_syncs():
        state, _rng, loss, tasks = trainer.train_epoch(
            state, loader, jax.random.PRNGKey(7)
        )
    assert np.isfinite(loss) and np.all(np.isfinite(tasks))
    _H["state"] = state


def pytest_transfer_guard_catches_reintroduced_float():
    """The enforcement direction: a per-batch float() under the guard
    must hard-error. Probed and skipped on host-resident backends where
    jax defines no transfer to guard (the static jaxlint gate covers
    those environments)."""
    if not _guard_enforces():
        pytest.skip(
            "transfer guard is a no-op on this (host-resident) backend"
        )
    h = _trainer()
    trainer, state, batches = h["trainer"], h["state"], h["batches"]

    class HostileLoader(ListLoader):
        pass

    def hostile_acc(acc, metrics, multi=False):
        return (acc or 0.0) + float(metrics["loss"])  # the anti-pattern

    orig = trainer._acc_add
    trainer._acc_add = hostile_acc
    try:
        with pytest.raises(Exception, match="[Tt]ransfer"):
            with no_host_syncs():
                trainer.train_epoch(
                    state, HostileLoader(batches), jax.random.PRNGKey(9)
                )
    finally:
        trainer._acc_add = orig


# ---- serving --------------------------------------------------------------

_S = {}


def _server_harness():
    if _S:
        return _S
    from hydragnn_tpu.serve import (
        InferenceServer,
        ModelRegistry,
        plan_from_samples,
    )
    from test_serve import _graph

    rng = np.random.default_rng(3)
    samples = [_graph(int(n), rng) for n in rng.integers(4, 32, 40)]
    model = create_model_config(arch_config("SAGE"))
    trainer = Trainer(
        model, {"Optimizer": {"type": "AdamW", "learning_rate": 1e-3}}
    )
    plan = plan_from_samples(samples, max_batch_graphs=4, num_buckets=2)
    init_batch, _ = plan.pack([samples[0]], 0)
    state = trainer.init_state(init_batch)
    registry = ModelRegistry()
    registry.register("sage", model, state.params, state.batch_stats)
    server = InferenceServer(registry, plan, max_wait_s=0.002)
    _S.update(server=server, samples=samples, rng=rng)
    return _S


def pytest_serve_burst_100_requests_compile_flat():
    """Warm the server (one compile per bucket), then a 100-request
    burst of mixed sizes must add ZERO compiles — at the jax level (the
    sentinel) and at the serve-metrics level."""
    h = _server_harness()
    server, samples = h["server"], h["samples"]
    with server:  # start() warms every (model, bucket) executable
        compiles_warm = server.metrics.snapshot()["compiles_total"]
        with CompileSentinel() as sentinel:
            futures = [
                server.submit(samples[i % len(samples)])
                for i in range(100)
            ]
            for fut in futures:
                heads = fut.result(timeout=60)
                assert all(np.isfinite(np.asarray(o)).all() for o in heads)
        sentinel.assert_flat("100-request serve burst")
        assert (
            server.metrics.snapshot()["compiles_total"] == compiles_warm
        )


def pytest_transfer_guard_serve_dispatch():
    """One packed dispatch under the guard: inputs are host-packed, the
    output fetch is one explicit device_get — clean."""
    from hydragnn_tpu.serve.server import _Request

    h = _server_harness()
    server, samples = h["server"], h["samples"]
    if not server.is_warm():
        server.warmup()
    g = samples[0]
    entry = server.registry.get("sage")
    bucket, sizes = server.plan.admit(g)
    req = _Request(g, entry, bucket, sizes, deadline=None, fallback=False)
    with no_host_syncs():
        server._dispatch_batch([req], bucket, real_nodes=sizes[0])
    heads = req.future.result(timeout=30)
    assert heads[0].shape == (1,)
    assert all(np.isfinite(np.asarray(o)).all() for o in heads)
