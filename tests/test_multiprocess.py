"""Multi-process distributed CI — the reference's ``mpirun -n 2`` story.

The reference exercises its distributed paths for real with 2 MPI ranks on
CPU (gloo backend, SURVEY.md §4). Here: 2 OS processes, each with 2 virtual
CPU devices, bootstrapped through ``jax.distributed`` via the framework's
env-var detection — then a REAL cross-process data-parallel training step on
the 4-device global mesh with per-process local batch shards
(``tests/_multiprocess_worker.py``). No mocks.
"""

import os
import socket
import subprocess
import sys


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def pytest_two_process_training_step():
    import tempfile

    worker = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "_multiprocess_worker.py")
    port = _free_port()
    env = dict(os.environ)
    # the workers pin their own platform/devices; scrub the suite's settings
    env.pop("XLA_FLAGS", None)
    env.pop("JAX_PLATFORMS", None)
    env["HYDRAGNN_TPU_TEST_CKPT"] = tempfile.mkdtemp(prefix="mp_ckpt_")
    procs = [
        subprocess.Popen(
            [sys.executable, worker, str(rank), "2", str(port)],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            env=env,
        )
        for rank in range(2)
    ]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=540)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs.append(out)
    for rank, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {rank} failed:\n{out}"
        assert f"MPOK rank={rank} world=2" in out, out

    # both ranks computed the identical global loss
    losses = [
        line.split("loss=")[1]
        for out in outs
        for line in out.splitlines()
        if line.startswith("MPOK")
    ]
    assert len(losses) == 2 and losses[0] == losses[1], losses
