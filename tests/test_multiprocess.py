"""Multi-process distributed CI — the reference's ``mpirun -n 2`` story.

The reference exercises its distributed paths for real with 2 MPI ranks on
CPU (gloo backend, SURVEY.md §4). Here: 2 OS processes, each with 2 virtual
CPU devices, bootstrapped through ``jax.distributed`` via the framework's
env-var detection — then a REAL cross-process data-parallel training step on
the 4-device global mesh with per-process local batch shards
(``tests/_multiprocess_worker.py``). No mocks.
"""

import os
import socket
import subprocess
import sys

import pytest

FULL = int(os.getenv("HYDRAGNN_FULL_TEST", "0")) == 1


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def pytest_two_process_training_step():
    import tempfile

    worker = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "_multiprocess_worker.py")
    port = _free_port()
    env = dict(os.environ)
    # the workers pin their own platform/devices; scrub the suite's settings
    env.pop("XLA_FLAGS", None)
    env.pop("JAX_PLATFORMS", None)
    env["HYDRAGNN_TPU_TEST_CKPT"] = tempfile.mkdtemp(prefix="mp_ckpt_")
    procs = [
        subprocess.Popen(
            [sys.executable, worker, str(rank), "2", str(port)],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            env=env,
        )
        for rank in range(2)
    ]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=540)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs.append(out)
    for rank, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {rank} failed:\n{out}"
        assert f"MPOK rank={rank} world=2" in out, out

    # both ranks computed the identical global loss
    losses = [
        line.split("loss=")[1]
        for out in outs
        for line in out.splitlines()
        if line.startswith("MPOK")
    ]
    assert len(losses) == 2 and losses[0] == losses[1], losses

    # ...and it is the CORRECT global loss: equal to a single-process step
    # on the two shards assembled with global index offsets. (Round-2
    # regression guard: per-process local indices shipped unoffset once
    # made shard 1's gathers read shard 0's rows — finite, agreeing, and
    # wrong.)
    expected = _reference_global_loss()
    assert abs(float(losses[0]) - expected) < 5e-5, (losses[0], expected)


@pytest.mark.skipif(not FULL, reason="4-process composed run: FULL tier")
def pytest_four_process_composed_training():
    """Round-4 verdict item 7: bucketed layouts + ZeRO stage-3 + a
    diststore-fed streaming epoch COMPOSED in one real 4-process
    ``jax.distributed`` run — the subsystems previously proven only one
    process (or one pair) at a time. Asserts cross-process loss agreement
    AND first-step parity against a single-process reconstruction of the
    globally-assembled first batch."""
    worker = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "_composed_worker.py")
    port = _free_port()
    # the store binds one port PER RANK: verify each individually instead
    # of assuming base..base+3 are free (ephemeral-range collisions made
    # the single-port version flake)
    dds_addrs = ",".join(f"127.0.0.1:{_free_port()}" for _ in range(4))
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env.pop("JAX_PLATFORMS", None)
    procs = [
        subprocess.Popen(
            [sys.executable, worker, str(rank), "4", str(port), dds_addrs],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            env=env,
        )
        for rank in range(4)
    ]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=560)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs.append(out)
    for rank, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {rank} failed:\n{out[-4000:]}"
        assert f"CWOK rank={rank} world=4" in out, out[-2000:]
    first = [
        line.split("loss0=")[1].split()[0]
        for out in outs
        for line in out.splitlines()
        if line.startswith("CWOK")
    ]
    epochs = [
        line.split("epoch=")[1].split()[0]
        for out in outs
        for line in out.splitlines()
        if line.startswith("CWOK")
    ]
    assert len(set(first)) == 1 and len(set(epochs)) == 1, (first, epochs)
    expected = _composed_reference_first_loss()
    assert abs(float(first[0]) - expected) < 5e-5, (first[0], expected)


def _assemble_global_batch(shards):
    """Globally-assembled batch from per-shard collations with global
    index offsets — ONE implementation for every reference-loss
    reconstruction (a one-sided edit here would silently diverge the
    2-process and 4-process parity checks)."""
    import numpy as np

    from hydragnn_tpu.graph.batch import GraphBatch

    n_pad = shards[0].x.shape[0]
    g_pad = shards[0].n_node.shape[0]
    assert all(b.x.shape[0] == n_pad for b in shards), "shape lockstep"
    acc = {f: [] for f in ("x", "pos", "senders", "receivers", "node_graph",
                            "n_node", "n_edge", "node_mask", "edge_mask",
                            "graph_mask")}
    tgt = [[] for _ in shards[0].targets]
    for p, b in enumerate(shards):
        acc["x"].append(b.x); acc["pos"].append(b.pos)
        acc["senders"].append(np.asarray(b.senders) + p * n_pad)
        acc["receivers"].append(np.asarray(b.receivers) + p * n_pad)
        acc["node_graph"].append(np.asarray(b.node_graph) + p * g_pad)
        acc["n_node"].append(b.n_node); acc["n_edge"].append(b.n_edge)
        acc["node_mask"].append(b.node_mask)
        acc["edge_mask"].append(b.edge_mask)
        acc["graph_mask"].append(b.graph_mask)
        for i, t in enumerate(b.targets):
            tgt[i].append(t)
    return GraphBatch(
        x=np.concatenate(acc["x"]),
        pos=np.concatenate(acc["pos"]),
        senders=np.concatenate(acc["senders"]).astype(np.int32),
        receivers=np.concatenate(acc["receivers"]).astype(np.int32),
        edge_attr=None,
        node_graph=np.concatenate(acc["node_graph"]).astype(np.int32),
        n_node=np.concatenate(acc["n_node"]),
        n_edge=np.concatenate(acc["n_edge"]),
        node_mask=np.concatenate(acc["node_mask"]),
        edge_mask=np.concatenate(acc["edge_mask"]),
        graph_mask=np.concatenate(acc["graph_mask"]),
        targets=tuple(np.concatenate(t) for t in tgt),
    )


def _reference_step_loss(gbatch, arch):
    """One single-process (no-mesh) train step on the assembled batch."""
    import jax

    from hydragnn_tpu.models import create_model_config
    from hydragnn_tpu.train.trainer import Trainer

    model = create_model_config(arch)
    trainer = Trainer(
        model, training_config={"Optimizer": {"type": "AdamW",
                                               "learning_rate": 1e-3}}
    )
    state = trainer.init_state(gbatch)
    state, metrics = trainer._train_step(
        state, trainer.put_batch(gbatch), jax.random.PRNGKey(0)
    )
    return float(metrics["loss"])


def _composed_reference_first_loss():
    """Single-process reconstruction of the 4-process run's FIRST step:
    every shard's first planned bucketed batch, assembled with global
    index offsets, stepped once without a mesh."""
    from hydragnn_tpu.data.loaders import GraphLoader
    from _composed_worker import (
        composed_layout,
        make_sized_samples,
        worker_arch,
    )

    world = 4
    global_samples = [
        s for r in range(world) for s in make_sized_samples(r)
    ]
    layout = composed_layout(world)
    shards = []
    for r in range(world):
        loader = GraphLoader(
            global_samples, 4, layout, shuffle=True, seed=7,
            num_shards=world, shard_id=r, contiguous_buckets=True,
        )
        shards.append(next(iter(loader)))
    return _reference_step_loss(_assemble_global_batch(shards), worker_arch())


def _reference_global_loss():
    from hydragnn_tpu.graph import collate_graphs, pad_sizes_for
    from _multiprocess_worker import make_samples, worker_arch

    local_graphs = 4
    n_pad, e_pad, g_pad = pad_sizes_for(
        6, 12, local_graphs, node_multiple=8, edge_multiple=8, graph_multiple=8
    )
    shards = [
        collate_graphs(
            make_samples(local_graphs, seed=100 + rank),
            n_pad, e_pad, g_pad,
            head_types=("graph", "node"), head_dims=(1, 1),
        )
        for rank in range(2)
    ]
    return _reference_step_loss(_assemble_global_batch(shards), worker_arch())
