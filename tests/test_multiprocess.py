"""Multi-process distributed CI — the reference's ``mpirun -n 2`` story.

The reference exercises its distributed paths for real with 2 MPI ranks on
CPU (gloo backend, SURVEY.md §4). Here: 2 OS processes, each with 2 virtual
CPU devices, bootstrapped through ``jax.distributed`` via the framework's
env-var detection — then a REAL cross-process data-parallel training step on
the 4-device global mesh with per-process local batch shards
(``tests/_multiprocess_worker.py``). No mocks.
"""

import os
import socket
import subprocess
import sys


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def pytest_two_process_training_step():
    import tempfile

    worker = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "_multiprocess_worker.py")
    port = _free_port()
    env = dict(os.environ)
    # the workers pin their own platform/devices; scrub the suite's settings
    env.pop("XLA_FLAGS", None)
    env.pop("JAX_PLATFORMS", None)
    env["HYDRAGNN_TPU_TEST_CKPT"] = tempfile.mkdtemp(prefix="mp_ckpt_")
    procs = [
        subprocess.Popen(
            [sys.executable, worker, str(rank), "2", str(port)],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            env=env,
        )
        for rank in range(2)
    ]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=540)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs.append(out)
    for rank, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {rank} failed:\n{out}"
        assert f"MPOK rank={rank} world=2" in out, out

    # both ranks computed the identical global loss
    losses = [
        line.split("loss=")[1]
        for out in outs
        for line in out.splitlines()
        if line.startswith("MPOK")
    ]
    assert len(losses) == 2 and losses[0] == losses[1], losses

    # ...and it is the CORRECT global loss: equal to a single-process step
    # on the two shards assembled with global index offsets. (Round-2
    # regression guard: per-process local indices shipped unoffset once
    # made shard 1's gathers read shard 0's rows — finite, agreeing, and
    # wrong.)
    expected = _reference_global_loss()
    assert abs(float(losses[0]) - expected) < 5e-5, (losses[0], expected)


def _reference_global_loss():
    import numpy as np

    import jax

    from hydragnn_tpu.graph import collate_graphs, pad_sizes_for
    from hydragnn_tpu.graph.batch import GraphBatch
    from hydragnn_tpu.models import create_model_config
    from hydragnn_tpu.train.trainer import Trainer
    from _multiprocess_worker import make_samples, worker_arch

    local_graphs = 4
    n_pad, e_pad, g_pad = pad_sizes_for(
        6, 12, local_graphs, node_multiple=8, edge_multiple=8, graph_multiple=8
    )
    shards = [
        collate_graphs(
            make_samples(local_graphs, seed=100 + rank),
            n_pad, e_pad, g_pad,
            head_types=("graph", "node"), head_dims=(1, 1),
        )
        for rank in range(2)
    ]
    acc = {f: [] for f in ("x", "pos", "senders", "receivers", "node_graph",
                            "n_node", "n_edge", "node_mask", "edge_mask",
                            "graph_mask")}
    tgt = [[] for _ in shards[0].targets]
    for p, b in enumerate(shards):
        acc["x"].append(b.x); acc["pos"].append(b.pos)
        acc["senders"].append(np.asarray(b.senders) + p * n_pad)
        acc["receivers"].append(np.asarray(b.receivers) + p * n_pad)
        acc["node_graph"].append(np.asarray(b.node_graph) + p * g_pad)
        acc["n_node"].append(b.n_node); acc["n_edge"].append(b.n_edge)
        acc["node_mask"].append(b.node_mask)
        acc["edge_mask"].append(b.edge_mask)
        acc["graph_mask"].append(b.graph_mask)
        for i, t in enumerate(b.targets):
            tgt[i].append(t)
    gbatch = GraphBatch(
        x=np.concatenate(acc["x"]),
        pos=np.concatenate(acc["pos"]),
        senders=np.concatenate(acc["senders"]).astype(np.int32),
        receivers=np.concatenate(acc["receivers"]).astype(np.int32),
        edge_attr=None,
        node_graph=np.concatenate(acc["node_graph"]).astype(np.int32),
        n_node=np.concatenate(acc["n_node"]),
        n_edge=np.concatenate(acc["n_edge"]),
        node_mask=np.concatenate(acc["node_mask"]),
        edge_mask=np.concatenate(acc["edge_mask"]),
        graph_mask=np.concatenate(acc["graph_mask"]),
        targets=tuple(np.concatenate(t) for t in tgt),
    )
    model = create_model_config(worker_arch())
    trainer = Trainer(
        model, training_config={"Optimizer": {"type": "AdamW",
                                               "learning_rate": 1e-3}}
    )
    state = trainer.init_state(gbatch)
    state, metrics = trainer._train_step(
        state, trainer.put_batch(gbatch), jax.random.PRNGKey(0)
    )
    return float(metrics["loss"])
