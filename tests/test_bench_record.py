"""The round-recording contract of bench.py: the BENCH_EXTRA merge must
never lose measured history (round 2's headline was lost to exactly this
class of bug), and the headline line must stay small, last, and parseable."""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import bench  # noqa: E402


def _row(model, precision="f32", aggregation="segment", ms=1.0):
    return {
        "model": model,
        "hidden": 256,
        "graphs_per_batch": 64,
        "nodes_per_graph": 90,
        "avg_degree": 12,
        "layers": 3,
        "precision": precision,
        "aggregation": aggregation,
        "ms_per_step": ms,
    }


def pytest_merge_keeps_skipped_configs(tmp_path):
    out = str(tmp_path / "extra.json")
    # round 1: two configs measured
    bench.merge_extra_rows(out, [_row("PNA"), _row("GIN")])
    # round 2: only PNA re-measured (budget skipped GIN)
    rows = bench.merge_extra_rows(out, [_row("PNA", ms=2.0)])
    by_model = {r["model"]: r for r in rows}
    assert by_model["PNA"]["ms_per_step"] == 2.0
    assert "carried_over" not in by_model["PNA"]  # fresh
    assert by_model["GIN"]["ms_per_step"] == 1.0  # history preserved
    assert by_model["GIN"]["carried_over"] is True  # and marked stale
    # round 3: GIN re-measured again -> marker cleared
    rows = bench.merge_extra_rows(out, [_row("GIN", ms=3.0)])
    by_model = {r["model"]: r for r in rows}
    assert by_model["GIN"]["ms_per_step"] == 3.0
    assert "carried_over" not in by_model["GIN"]
    assert by_model["PNA"]["carried_over"] is True


def pytest_merge_tracks_staleness_age_and_cursor(tmp_path):
    """Round-4 verdict item 8: carried rows accumulate an ``age`` so
    cross-round A/Bs can see how stale they ride, and the rotation cursor
    persists so every config refreshes within ~2 budgeted runs."""
    out = str(tmp_path / "extra.json")
    bench.merge_extra_rows(out, [_row("PNA"), _row("GIN")], cursor=5)
    assert bench.read_refresh_cursor(out) == 5
    rows = bench.merge_extra_rows(out, [_row("PNA")], cursor=7)
    by_model = {r["model"]: r for r in rows}
    assert by_model["GIN"]["age"] == 1
    assert by_model["PNA"]["age"] == 0
    rows = bench.merge_extra_rows(out, [_row("PNA")], cursor=9)
    by_model = {r["model"]: r for r in rows}
    assert by_model["GIN"]["age"] == 2  # two runs stale now
    assert bench.read_refresh_cursor(out) == 9


def pytest_rotation_covers_all_configs():
    """The rotated window starting at the persisted cursor must enumerate
    every config exactly once per cycle."""
    configs = bench._extra_configs()
    n = len(configs)
    start = 7 % n
    rotated = configs[start:] + configs[:start]
    def key(c):
        return (c["model_type"], c["hidden"], c.get("dense", False),
                c.get("bf16", False), c["num_graphs"])
    assert sorted(map(str, map(key, rotated))) == sorted(
        map(str, map(key, configs))
    )


def pytest_merge_distinguishes_configs_not_models(tmp_path):
    out = str(tmp_path / "extra.json")
    rows = bench.merge_extra_rows(
        out,
        [_row("PNA", "f32", "segment"), _row("PNA", "bf16", "dense", ms=0.5)],
    )
    assert len(rows) == 2  # same model, different config identity


def pytest_merge_backs_up_corrupt_file(tmp_path, capsys):
    out = str(tmp_path / "extra.json")
    with open(out, "w") as f:
        f.write('{"rows": [{"model": "PN')  # truncated mid-dump
    rows = bench.merge_extra_rows(out, [_row("GIN")])
    assert [r["model"] for r in rows] == ["GIN"]
    assert os.path.exists(out + ".bak")  # history preserved for forensics
    assert "unreadable" in capsys.readouterr().err
    # the rewritten file parses cleanly
    assert json.load(open(out))["rows"][0]["model"] == "GIN"


def pytest_headline_shape():
    """The driver json-parses the LAST stdout line: keep it one compact
    object with the contracted keys — exercised through the REAL
    formatting helper at worst-case value widths."""
    line = bench.headline_line(
        123456.78, 1234.5678, 98765.43, 1234.5678, mfu_pct=12.34
    )
    parsed = json.loads(line)
    assert set(parsed) == {
        "metric",
        "value",
        "unit",
        "mfu_pct",
        "vs_baseline",
        "legacy_value",
        "legacy_vs_baseline",
    }
    assert parsed["mfu_pct"] == 12.34
    assert len(line) < 200  # tail-capture safe
    # every baseline may fail independently; Nones must not crash or widen
    assert json.loads(bench.headline_line(1.0, None, None, None))


def pytest_failed_attempt_annotates_without_losing_metrics(tmp_path):
    """A failed re-measure must keep the last good row's metrics (history
    is the point of the merge) while resetting attempt_age so the
    oldest-first refresh order moves past the failing config."""
    out = str(tmp_path / "extra.json")
    bench.merge_extra_rows(out, [_row("PNA", ms=7.0), _row("GIN", ms=2.0)])
    kw = dict(model_type="PNA", hidden=256, num_graphs=64, nodes=90,
              degree=12, layers=3)
    rows = bench.merge_extra_rows(out, [], failures=[(kw, "boom")])
    pna = next(r for r in rows if r["model"] == "PNA")
    gin = next(r for r in rows if r["model"] == "GIN")
    assert pna["ms_per_step"] == 7.0  # metrics preserved
    assert pna["failed"] == "boom"
    assert pna["attempt_age"] == 0 and pna["age"] == 1  # data is stale,
    assert gin["attempt_age"] == 1  # ...but the attempt is fresh
    ages = bench.read_row_ages(out)
    assert ages[bench._config_key(kw)] == 0
    # a failing NEVER-measured config gets a stub so it ages too
    kw2 = dict(kw, model_type="SAGE")
    rows = bench.merge_extra_rows(out, [], failures=[(kw2, "oom")])
    sage = next(r for r in rows if r["model"] == "SAGE")
    assert sage["failed"] == "oom" and "ms_per_step" not in sage
    # a later SUCCESS clears the failure annotation
    rows = bench.merge_extra_rows(out, [_row("PNA", ms=6.5)])
    pna = next(r for r in rows if r["model"] == "PNA")
    assert "failed" not in pna and pna["ms_per_step"] == 6.5
