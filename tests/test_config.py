"""Config-schema guard tests (round-3 verdict item 9).

Mirrors the reference's ``tests/test_config.py:15-40`` (required sections
present in the shipped example configs) and adds negative tests pinning
``update_config``'s validation/error paths so key drift in
``hydragnn_tpu/utils/config.py`` is caught directly, not incidentally.
"""

import copy
import json
import os

import numpy as np
import pytest

from hydragnn_tpu.utils.config import (
    check_output_dim_consistent,
    merge_config,
    update_config,
    update_config_edge_dim,
    update_config_equivariance,
    update_config_NN_outputs,
)

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_EXAMPLE_CONFIGS = [
    "lsms/lsms.json",
    "qm9/qm9.json",
    "md17/md17.json",
    "open_catalyst_2020/oc20.json",
    "mptrj/mptrj.json",
    "multidataset/gfm.json",
]


@pytest.mark.parametrize("config_file", _EXAMPLE_CONFIGS)
def pytest_example_config_schema(config_file):
    """Same contract as the reference test: every shipped example config
    carries the required categories and keys."""
    with open(os.path.join(_REPO, "examples", config_file)) as f:
        config = json.load(f)

    assert "NeuralNetwork" in config, "Missing required input category"
    for key in ("Architecture", "Variables_of_interest", "Training"):
        assert key in config["NeuralNetwork"], f"Missing NeuralNetwork.{key}"
    arch = config["NeuralNetwork"]["Architecture"]
    for key in ("model_type", "hidden_dim", "num_conv_layers", "output_heads",
                "task_weights"):
        assert key in arch, f"Missing Architecture.{key}"
    voi = config["NeuralNetwork"]["Variables_of_interest"]
    for key in ("input_node_features", "output_index", "type"):
        assert key in voi, f"Missing Variables_of_interest.{key}"
    training = config["NeuralNetwork"]["Training"]
    for key in ("batch_size", "num_epoch"):
        assert key in training, f"Missing Training.{key}"
    if "Dataset" in config:
        assert "name" in config["Dataset"], "Missing Dataset.name"
        # streaming-only Dataset sections (docs/data.md) name their
        # formats per source; `format` governs the raw->serialized path
        if "streaming" not in config["Dataset"]:
            assert "format" in config["Dataset"], "Missing Dataset.format"
        else:
            for src in config["Dataset"]["streaming"].get("sources", []):
                assert "train" in src, "streaming source missing train path"


class _Sample:
    def __init__(self, n=4, targets=None):
        self.num_nodes = n
        self.num_edges = 2 * n
        self.edge_index = np.stack(
            [np.arange(2 * n) % n, (np.arange(2 * n) + 1) % n]
        ).astype(np.int64)
        self.targets = targets or [np.ones((1,), np.float32),
                                   np.ones((n, 1), np.float32)]


class _Loader:
    def __init__(self, samples):
        self.dataset = samples


def _nn_config(node_head_type="mlp"):
    return {
        "Architecture": {
            "model_type": "GIN",
            "hidden_dim": 8,
            "num_conv_layers": 2,
            "output_heads": {
                "graph": {
                    "num_sharedlayers": 1,
                    "dim_sharedlayers": 8,
                    "num_headlayers": 1,
                    "dim_headlayers": [8],
                },
                "node": {
                    "num_headlayers": 1,
                    "dim_headlayers": [8],
                    "type": node_head_type,
                },
            },
            "task_weights": [1.0, 1.0],
        },
        "Training": {"batch_size": 2, "num_epoch": 1},
        "Variables_of_interest": {
            "input_node_features": [0],
            "output_index": [0, 0],
            "type": ["graph", "node"],
            "denormalize_output": False,
        },
    }


def pytest_update_config_derives_dims():
    samples = [_Sample(4), _Sample(4)]
    loaders = [_Loader(samples)] * 3
    config = update_config({"NeuralNetwork": _nn_config()}, *loaders)
    arch = config["NeuralNetwork"]["Architecture"]
    assert arch["output_dim"] == [1, 1]
    assert arch["output_type"] == ["graph", "node"]
    assert arch["num_nodes"] == 4
    assert arch["input_dim"] == 1
    assert arch["pna_deg"] is None  # GIN
    assert arch["equivariance"] is False
    assert arch["edge_dim"] is None
    assert config["NeuralNetwork"]["Training"]["loss_function_type"] == "mse"
    assert config["NeuralNetwork"]["Training"]["Optimizer"]["type"] == "AdamW"


def pytest_update_config_pna_degree_histogram():
    cfg = {"NeuralNetwork": _nn_config()}
    cfg["NeuralNetwork"]["Architecture"]["model_type"] = "PNA"
    loaders = [_Loader([_Sample(4)])] * 3
    config = update_config(copy.deepcopy(cfg), *loaders)
    arch = config["NeuralNetwork"]["Architecture"]
    # ring graph: every node has in-degree 2 -> histogram [0, 0, 4]
    assert arch["pna_deg"] == [0, 0, 4]
    assert arch["max_neighbours"] == 2


def pytest_auto_dense_aggregation_policy():
    """The measured-crossover policy (BASELINE.md): scatter-heavy models
    pick the dense path at MXU widths with NO config flag; SchNet/EGNN
    never do; an explicit flag and partition mode always win."""
    from hydragnn_tpu.data.loaders import needs_dense_neighbors

    for m in ("PNA", "GAT", "MFC", "DimeNet"):
        assert needs_dense_neighbors({"model_type": m, "hidden_dim": 256})
        assert needs_dense_neighbors({"model_type": m, "hidden_dim": 96})
        assert not needs_dense_neighbors({"model_type": m, "hidden_dim": 64})
    for m in ("GIN", "SAGE"):
        assert needs_dense_neighbors({"model_type": m, "hidden_dim": 256})
        assert not needs_dense_neighbors({"model_type": m, "hidden_dim": 128})
    # SchNet/EGNN: one fused scatter/layer — dense never wins. CGCNN runs
    # at input_dim width, so hidden_dim is not a crossover signal.
    for m in ("SchNet", "EGNN", "CGCNN"):
        assert not needs_dense_neighbors({"model_type": m, "hidden_dim": 512})
    # CGCNN's own rule keys on input_dim — its true conv width — and
    # INVERSELY: the dense frame's gather traffic grows with input width
    # while the scatter cost it removes stays flat (round-5 measured
    # crossover, BASELINE.md). Narrow inputs (the realistic case) go dense.
    assert needs_dense_neighbors(
        {"model_type": "CGCNN", "hidden_dim": 64, "input_dim": 4}
    )
    assert needs_dense_neighbors(
        {"model_type": "CGCNN", "hidden_dim": 512, "input_dim": 64}
    )
    assert not needs_dense_neighbors(
        {"model_type": "CGCNN", "hidden_dim": 64, "input_dim": 256}
    )
    # absent input_dim stays conservative (segment), whatever the hidden
    assert not needs_dense_neighbors({"model_type": "CGCNN", "hidden_dim": 512})
    # explicit override beats the policy in both directions
    assert not needs_dense_neighbors(
        {"model_type": "PNA", "hidden_dim": 256, "dense_aggregation": False}
    )
    assert needs_dense_neighbors(
        {"model_type": "EGNN", "hidden_dim": 64, "dense_aggregation": True}
    )
    # partition mode always builds its own per-shard lists
    assert not needs_dense_neighbors(
        {"model_type": "PNA", "hidden_dim": 256, "partition_axis": "data"}
    )


def pytest_update_config_records_auto_dense():
    """update_config writes the resolved AUTO decision into the arch so
    saved configs show which path ran."""
    cfg = {"NeuralNetwork": _nn_config()}
    cfg["NeuralNetwork"]["Architecture"]["model_type"] = "PNA"
    cfg["NeuralNetwork"]["Architecture"]["hidden_dim"] = 256
    loaders = [_Loader([_Sample(4)])] * 3
    config = update_config(copy.deepcopy(cfg), *loaders)
    assert config["NeuralNetwork"]["Architecture"]["dense_aggregation"] is True
    cfg["NeuralNetwork"]["Architecture"]["hidden_dim"] = 8
    config = update_config(copy.deepcopy(cfg), *loaders)
    assert config["NeuralNetwork"]["Architecture"]["dense_aggregation"] is False


def pytest_update_config_mfc_degree_bound():
    """MFC configs derive a dataset-wide static in-degree bound so the
    conv can slice dead banks from its one-hot degree matmul."""
    cfg = {"NeuralNetwork": _nn_config()}
    cfg["NeuralNetwork"]["Architecture"]["model_type"] = "MFC"
    cfg["NeuralNetwork"]["Architecture"]["max_neighbours"] = 50
    loaders = [_Loader([_Sample(4)])] * 3
    config = update_config(copy.deepcopy(cfg), *loaders)
    # ring graph: every node has in-degree exactly 2
    assert config["NeuralNetwork"]["Architecture"]["mfc_degree_bound"] == 2


def pytest_update_config_rejects_mlp_per_node_variable_size():
    """``mlp_per_node`` + variable graph size must raise
    (``config_utils.py:156-192`` analog)."""
    cfg = {"NeuralNetwork": _nn_config(node_head_type="mlp_per_node")}
    loaders = [_Loader([_Sample(4), _Sample(6)])] * 3
    with pytest.raises(ValueError, match="mlp_per_node"):
        update_config(cfg, *loaders)


def pytest_update_config_env_overrides_size_detection(monkeypatch):
    monkeypatch.setenv("HYDRAGNN_USE_VARIABLE_GRAPH_SIZE", "1")
    cfg = {"NeuralNetwork": _nn_config(node_head_type="mlp_per_node")}
    loaders = [_Loader([_Sample(4)])] * 3  # fixed size, but env says variable
    with pytest.raises(ValueError, match="mlp_per_node"):
        update_config(cfg, *loaders)


def pytest_update_config_unknown_output_type():
    nn = _nn_config()
    nn["Variables_of_interest"]["type"] = ["graph", "bogus"]
    with pytest.raises(ValueError, match="Unknown output type"):
        update_config_NN_outputs(nn, _Sample(4), False)


def pytest_equivariance_validation():
    assert update_config_equivariance({"model_type": "EGNN",
                                       "equivariance": True})["equivariance"]
    with pytest.raises(AssertionError, match="equivariance"):
        update_config_equivariance({"model_type": "GIN", "equivariance": True})
    # absent key defaults to False
    assert update_config_equivariance({"model_type": "GIN"})[
        "equivariance"] is False


def pytest_edge_dim_validation():
    arch = update_config_edge_dim({"model_type": "PNA",
                                   "edge_features": ["length"]})
    assert arch["edge_dim"] == 1
    with pytest.raises(AssertionError, match="[Ee]dge"):
        update_config_edge_dim({"model_type": "GIN",
                                "edge_features": ["length"]})
    # CGCNN requires constant width: edge_dim 0 when no features given
    assert update_config_edge_dim({"model_type": "CGCNN"})["edge_dim"] == 0
    assert update_config_edge_dim({"model_type": "GIN"})["edge_dim"] is None


def pytest_output_dim_consistency_check():
    config = {
        "Dataset": {
            "graph_features": {"dim": [1]},
            "node_features": {"dim": [1]},
        },
        "NeuralNetwork": {
            "Variables_of_interest": {
                "type": ["graph"],
                "output_index": [0],
            }
        },
    }
    check_output_dim_consistent(_Sample(4), config)  # consistent: no raise
    bad = copy.deepcopy(config)
    bad["Dataset"]["graph_features"]["dim"] = [7]
    with pytest.raises(AssertionError):
        check_output_dim_consistent(_Sample(4), bad)


def pytest_merge_config_deep():
    a = {"x": {"y": 1, "z": 2}, "w": 3}
    b = {"x": {"y": 10}, "v": 4}
    out = merge_config(a, b)
    assert out == {"x": {"y": 10, "z": 2}, "w": 3, "v": 4}
    assert a == {"x": {"y": 1, "z": 2}, "w": 3}  # inputs untouched
