"""Direct unit pins for checkpoint load-path promises PR 1 made but never
tested head-on: ``rolling_checkpoints`` ordering, ``pop_train_meta`` on
v1/legacy/odd inputs, and the rolling-copy fsync durability fix.
"""

import binascii
import os
import struct
import tempfile

import numpy as np

from hydragnn_tpu.train import checkpoint as ck
from hydragnn_tpu.train.checkpoint import (
    load_state_dict,
    pop_train_meta,
    restore_into,
    rolling_checkpoints,
    save_model,
)


def _state_dict_fixture(step=5):
    return {
        "params": {"w": np.arange(4, dtype=np.float32)},
        "batch_stats": {},
        "opt_state": {},
        "step": np.int32(step),
    }


# ---- rolling_checkpoints ordering -----------------------------------------


def pytest_rolling_order_is_numeric_not_lexicographic():
    """Sequence numbers must sort as integers: roll-10 is NEWER than
    roll-9 even though it sorts lower as a string — and out-of-pattern
    files in the directory are ignored, not mis-ordered."""
    with tempfile.TemporaryDirectory() as tmp:
        out_dir = os.path.join(tmp, "m")
        os.makedirs(out_dir)
        for seq in (2, 9, 10, 100):
            open(
                os.path.join(out_dir, f"m.roll-{seq:06d}.pk"), "wb"
            ).write(b"x")
        # same name with a hand-made UNPADDED seq (an operator cp) still
        # ranks by numeric value
        open(os.path.join(out_dir, "m.roll-42.pk"), "wb").write(b"x")
        # noise that must not be picked up
        open(os.path.join(out_dir, "m.roll-5.pk.tmp"), "wb").write(b"x")
        open(os.path.join(out_dir, "m.pk"), "wb").write(b"x")
        rolls = rolling_checkpoints("m", path=tmp)
        seqs = [
            int(os.path.basename(p).split("roll-")[1].split(".")[0])
            for p in rolls
        ]
        assert seqs == [100, 42, 10, 9, 2]


def pytest_rolling_sequence_continues_across_restarts():
    """A resumed run must append AFTER the existing history: seq picks up
    from the newest retained file, never recycling numbers (which would
    make pruning eat the wrong copies)."""
    with tempfile.TemporaryDirectory() as tmp:
        for ep in range(3):
            save_model(_state_dict_fixture(ep), "m", path=tmp,
                       train_meta={"epoch": ep}, keep_last=2)
        first = rolling_checkpoints("m", path=tmp)
        # keep_last=2: seqs 1 and 2 retained (0 pruned)
        assert [os.path.basename(p) for p in first] == [
            "m.roll-000002.pk", "m.roll-000001.pk",
        ]
        # "restart": a fresh process appends seq 3
        save_model(_state_dict_fixture(3), "m", path=tmp,
                   train_meta={"epoch": 3}, keep_last=2)
        after = rolling_checkpoints("m", path=tmp)
        assert [os.path.basename(p) for p in after] == [
            "m.roll-000003.pk", "m.roll-000002.pk",
        ]
        meta = pop_train_meta(
            ck._parse_checkpoint_bytes(open(after[0], "rb").read(), after[0])
        )
        assert int(meta["epoch"]) == 3


# ---- pop_train_meta on v1 / legacy / odd inputs ---------------------------


def pytest_pop_train_meta_v1_header_returns_none():
    with tempfile.TemporaryDirectory() as tmp:
        save_model(_state_dict_fixture(), "m", path=tmp)  # v2, no meta
        fname = os.path.join(tmp, "m", "m.pk")
        raw = open(fname, "rb").read()
        blob = raw[16:]
        v1 = ck._MAGIC + struct.pack(
            "<II", 1, binascii.crc32(blob) & 0xFFFFFFFF
        ) + blob
        open(fname, "wb").write(v1)
        restored = load_state_dict("m", path=tmp)
        assert pop_train_meta(restored) is None
        # and restore_into on the meta-less dict reconstructs the leaves
        rebuilt = restore_into(_state_dict_fixture(), restored)
        np.testing.assert_array_equal(rebuilt["params"]["w"],
                                      np.arange(4, dtype=np.float32))


def pytest_pop_train_meta_legacy_headerless_returns_none():
    with tempfile.TemporaryDirectory() as tmp:
        save_model(_state_dict_fixture(7), "m", path=tmp)
        fname = os.path.join(tmp, "m", "m.pk")
        blob = open(fname, "rb").read()[16:]
        open(fname, "wb").write(blob)  # pre-header era file
        restored = load_state_dict("m", path=tmp)
        assert pop_train_meta(restored) is None
        assert int(restored["step"]) == 7


def pytest_pop_train_meta_detaches_and_is_idempotent():
    with tempfile.TemporaryDirectory() as tmp:
        save_model(_state_dict_fixture(), "m", path=tmp,
                   train_meta={"epoch": 9})
        restored = load_state_dict("m", path=tmp)
        meta = pop_train_meta(restored)
        assert int(meta["epoch"]) == 9
        assert ck.TRAIN_META_KEY not in restored
        assert pop_train_meta(restored) is None  # second pop: nothing


def pytest_pop_train_meta_non_dict_input_returns_none():
    assert pop_train_meta(None) is None
    assert pop_train_meta([1, 2, 3]) is None


# ---- rolling-copy durability (the _retain_rolling fsync fix) --------------


def pytest_rolling_copy_is_fsynced_before_rename(monkeypatch):
    """The durability bug this PR fixes: the rolling tmp file must be
    flushed + fsync'd before ``os.replace`` — exactly like the primary —
    or a crash can leave EMPTY fallback copies, which are read precisely
    when the primary is already lost."""
    synced_then_renamed = []
    synced_fds = set()
    real_fsync = os.fsync
    real_replace = os.replace

    def spy_fsync(fd):
        synced_fds.add(True)
        return real_fsync(fd)

    def spy_replace(src, dst):
        if dst.endswith(".pk"):
            synced_then_renamed.append((dst, bool(synced_fds)))
            synced_fds.clear()
        return real_replace(src, dst)

    monkeypatch.setattr(os, "fsync", spy_fsync)
    monkeypatch.setattr(os, "replace", spy_replace)
    with tempfile.TemporaryDirectory() as tmp:
        save_model(_state_dict_fixture(), "m", path=tmp,
                   train_meta={"epoch": 0}, keep_last=2)
    # two renames (primary + rolling copy), EACH preceded by its own fsync
    assert len(synced_then_renamed) == 2
    assert all(synced for _, synced in synced_then_renamed), (
        synced_then_renamed
    )
    kinds = sorted(
        "roll" if ".roll-" in dst else "primary"
        for dst, _ in synced_then_renamed
    )
    assert kinds == ["primary", "roll"]
