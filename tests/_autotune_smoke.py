"""CI kernel-microbench smoke driver: the aggregation autotuner end to end
on one bucket, interpreter mode, with schema-validated observability.

Usage: ``python tests/_autotune_smoke.py <outdir>``

Runs the autotuner's measured pass over {segment, dense, fused} for one
small bucket (the fused candidate runs the Pallas interpreter on CPU),
asserts the decision lands in the on-disk cache AND that a second,
cache-state-dropped read returns the SAME choice without re-timing
(source=cache), exercises the env override, and validates the emitted
``agg_choice`` events against the documented schema. Exits non-zero on
any missing piece.

(Underscore-prefixed: a driver script, not a collected test file. The
pytest twin is tests/test_autotune.py.)
"""

import json
import os
import sys

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def main(outdir: str) -> int:
    os.makedirs(outdir, exist_ok=True)
    os.environ["HYDRAGNN_AUTOTUNE_CACHE"] = os.path.join(
        outdir, "autotune.json"
    )
    from hydragnn_tpu.obs import runtime as obs_rt
    from hydragnn_tpu.obs.events import validate_events
    from hydragnn_tpu.ops import autotune as at

    telem = obs_rt.activate(obs_rt.RunTelemetry("autotune-smoke", outdir))
    try:
        # interpret=True: explicitly time the interpreter so the fused
        # machinery is exercised on CPU CI (off-TPU, autotune_bucket
        # otherwise refuses to let emulation timings into the cache)
        choice = at.autotune_bucket(
            "GIN", 64, 256, 16, candidates=("segment", "dense", "fused"),
            iters=3, interpret=True,
        )
        assert choice in at.CHOICES, choice
        sig = at.bucket_signature("GIN", 64, 256, 16)
        cache = json.load(open(at.cache_path()))
        rec = cache["devices"][at.device_kind()][sig]
        assert rec["choice"] == choice, rec
        assert set(rec["timings_ms"]) == {"segment", "dense", "fused"}, rec

        # deterministic re-read: drop the in-process state, same answer,
        # sourced from the cache (no re-timing)
        at.reset_cache_state()
        assert at.autotune_bucket("GIN", 64, 256, 16) == choice

        # env override wins over the cached decision
        os.environ["HYDRAGNN_AGG"] = "segment"
        try:
            assert at.autotune_bucket("GIN", 64, 256, 16) == "segment"
            assert not at.use_fused("GIN", 64, 256, 16, 16)
        finally:
            del os.environ["HYDRAGNN_AGG"]
    finally:
        obs_rt.deactivate()

    recs = validate_events(
        os.path.join(outdir, "events.jsonl"), require=["agg_choice"]
    )
    ev = [r for r in recs if r["event"] == "agg_choice"]
    sources = {r["source"] for r in ev}
    assert {"measured", "cache", "env"} <= sources, sources
    measured = [r for r in ev if r["source"] == "measured"]
    assert measured and measured[0]["timings_ms"], measured
    print(
        f"autotune smoke ok: bucket {sig} -> {choice} "
        f"(timings {measured[0]['timings_ms']}), {len(ev)} agg_choice "
        f"event(s), cache at {at.cache_path()}"
    )
    return 0


if __name__ == "__main__":
    if len(sys.argv) != 2:
        print(
            "usage: python tests/_autotune_smoke.py <outdir>",
            file=sys.stderr,
        )
        sys.exit(2)
    sys.exit(main(sys.argv[1]))
