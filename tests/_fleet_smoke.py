"""CI fleet kill-and-heal + hot-swap smoke (standalone, NOT a pytest module).

The bounded-wall-time serving twin of ``tests/_elastic_smoke.py``: two
spec-driven replica processes behind a :class:`ServingFleet` supervisor
and a :class:`FleetRouter`, under closed-loop load from concurrent
clients, through the full fault schedule —

1. steady state (baseline latency),
2. SIGKILL replica 1 mid-load -> lease/process-exit detection, respawn,
   ``replica_lost`` + ``fleet_degraded`` + ``replica_respawned`` events
   with the measured downtime,
3. zero-downtime hot-swap promote of a candidate checkpoint (per-bucket
   warm on every replica, compile-counter verified, atomic publish),
4. promote of a CRC-corrupt candidate -> loud rollback with the good
   version still serving.

Asserts zero requests lost beyond the retry budget (every submitted
request reaches a terminal outcome; none fail), validates the whole
event stream against the documented schema, and emits a ``fleet_report``
with the measured availability.

Usage: python tests/_fleet_smoke.py <workdir>
"""

import json
import os
import pickle
import signal
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

NUM_CLIENTS = 3
REQUEST_DEADLINE_S = 30.0

ARCH = {
    "model_type": "GIN",
    "input_dim": 1,
    "hidden_dim": 8,
    "num_conv_layers": 2,
    "output_dim": [1, 1],
    "output_type": ["graph", "node"],
    "output_heads": {
        "graph": {
            "num_sharedlayers": 1,
            "dim_sharedlayers": 8,
            "num_headlayers": 1,
            "dim_headlayers": [8],
        },
        "node": {"num_headlayers": 1, "dim_headlayers": [8], "type": "mlp"},
    },
    "task_weights": [1.0, 1.0],
}


def make_graphs(num, seed):
    import numpy as np

    from hydragnn_tpu.data.dataobj import GraphData

    rng = np.random.default_rng(seed)
    out = []
    for _ in range(num):
        n = int(rng.integers(5, 14))
        g = GraphData(
            x=rng.random((n, 1)).astype(np.float32),
            pos=rng.random((n, 3)).astype(np.float32),
        )
        src = np.arange(n)
        dst = (src + 1) % n
        g.edge_index = np.stack(
            [np.concatenate([src, dst]), np.concatenate([dst, src])]
        ).astype(np.int64)
        out.append(g)
    return out


def build_artifacts(workdir, arch=None, samples=None, *, batch=4,
                    buckets=2, model_name="m", max_wait_s=0.003,
                    queue_capacity=256):
    """Base + bumped-candidate (+ CRC-corrupt) checkpoints, plan
    samples, and the fleet spec — THE fleet artifact recipe, shared
    with ``benchmarks/serve_bench.py --fleet`` (which passes its own
    arch + graph-size distribution)."""
    import jax

    from hydragnn_tpu.models.create import create_model_config
    from hydragnn_tpu.serve.buckets import plan_from_samples
    from hydragnn_tpu.train.checkpoint import save_model
    from hydragnn_tpu.train.trainer import Trainer

    arch = dict(ARCH) if arch is None else dict(arch)
    if samples is None:
        samples = make_graphs(32, seed=11)
    plan = plan_from_samples(
        samples, max_batch_graphs=batch, num_buckets=buckets
    )
    model = create_model_config(dict(arch))
    trainer = Trainer(
        model, {"Optimizer": {"type": "AdamW", "learning_rate": 1e-3}}
    )
    init_batch, _ = plan.pack([samples[0]], 0)
    state = trainer.init_state(init_batch, seed=0)
    ckdir = os.path.join(workdir, "ck")
    save_model(state, "base", path=ckdir)
    bumped = state.replace(
        params=jax.tree_util.tree_map(lambda x: x + 0.05, state.params)
    )
    save_model(bumped, "cand", path=ckdir)
    # the corrupt candidate: cand's bytes with one payload byte flipped —
    # the strict v2 CRC on every replica must refuse it
    cand_pk = os.path.join(ckdir, "cand", "cand.pk")
    blob = bytearray(open(cand_pk, "rb").read())
    blob[len(blob) // 2] ^= 0xFF
    os.makedirs(os.path.join(ckdir, "broken"), exist_ok=True)
    with open(os.path.join(ckdir, "broken", "broken.pk"), "wb") as f:
        f.write(bytes(blob))

    samples_path = os.path.join(workdir, "samples.pkl")
    with open(samples_path, "wb") as f:
        pickle.dump(samples, f)
    spec = {
        "checkpoint": {"name": "base", "path": ckdir},
        "arch": arch,
        "model_name": model_name,
        "samples": samples_path,
        "plan": {"max_batch_graphs": batch, "num_buckets": buckets},
        "server": {"max_wait_s": max_wait_s,
                   "queue_capacity": queue_capacity},
    }
    spec_path = os.path.join(workdir, "spec.json")
    with open(spec_path, "w") as f:
        json.dump(spec, f)
    return spec_path, ckdir, samples


def main(workdir):
    os.makedirs(workdir, exist_ok=True)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    import numpy as np

    from hydragnn_tpu.obs.events import validate_events
    from hydragnn_tpu.serve import FleetRouter, ServerOverloaded
    from hydragnn_tpu.serve.fleet import ServingFleet

    spec_path, ckdir, samples = build_artifacts(workdir)
    coord_dir = os.path.join(workdir, "coord")
    log_dir = os.path.join(workdir, "log")
    fleet = ServingFleet(
        coord_dir,
        2,
        spec_path=spec_path,
        heartbeat_s=0.1,
        lease_s=0.75,
        poll_s=0.05,
        log_dir=log_dir,
    )
    t_boot = time.monotonic()
    fleet.start(wait_serving=True, timeout=300)
    boot_s = time.monotonic() - t_boot
    assert fleet.health()["live"] == 2, fleet.health()

    router = FleetRouter(
        coord_dir,
        lease_s=0.75,
        scan_interval_s=0.1,
        max_attempts=6,
        retry_base_delay_s=0.05,
    )

    stop = threading.Event()
    lock = threading.Lock()
    results = []  # (t, latency_s, outcome)
    failures = []

    def client(seed):
        rng = np.random.default_rng(seed)
        while not stop.is_set():
            g = samples[int(rng.integers(0, len(samples)))]
            t0 = time.monotonic()
            try:
                router.route(g, deadline_s=REQUEST_DEADLINE_S)
                outcome = "ok"
            except ServerOverloaded:
                outcome = "shed"  # explicit, terminal, retry-after
            except Exception as e:
                outcome = "failed"
                with lock:
                    failures.append(repr(e))
            with lock:
                results.append(
                    (t0, time.monotonic() - t0, outcome)
                )

    clients = [
        threading.Thread(target=client, args=(100 + i,), daemon=True)
        for i in range(NUM_CLIENTS)
    ]
    for t in clients:
        t.start()

    try:
        # phase 1: steady state
        time.sleep(2.0)
        with lock:
            assert any(o == "ok" for _, _, o in results), "no traffic served"

        # phase 2: SIGKILL replica 1 mid-load -> detect + respawn
        pid = fleet.replica_pid(1)
        os.kill(pid, signal.SIGKILL)
        t_kill = time.monotonic()
        deadline = time.monotonic() + 240
        while time.monotonic() < deadline:
            snap = fleet.metrics.snapshot()
            if snap["replica_respawns_total"] >= 1:
                break
            time.sleep(0.1)
        heal_s = time.monotonic() - t_kill
        snap = fleet.metrics.snapshot()
        assert snap["replica_losses_total"] >= 1, snap
        assert snap["replica_respawns_total"] >= 1, (
            f"replica never respawned within 240s: {snap}"
        )
        assert snap["last_recovery_seconds"] > 0, snap

        # phase 3: hot-swap promote mid-load (both replicas warm + verify)
        res = fleet.promote("cand", path=ckdir, arch_config=ARCH,
                            name="m", timeout=240)
        assert res["status"] == "promoted", res
        assert res["propagated"], res  # every replica REPORTS v2 active
        assert all(
            a["status"] == "warmed" and a["compiles"] == 2
            for a in res["acks"].values()
        ), res
        # every response routed from here on computes on the candidate
        seen = set()
        for _ in range(12):
            raw = router.route(
                samples[0], deadline_s=REQUEST_DEADLINE_S, raw=True
            )
            seen.add((raw["replica"], raw["version"]))
        assert all(v == 2 for _, v in seen), seen
        assert len({r for r, _ in seen}) == 2, (
            f"expected both replicas serving, saw {seen}"
        )

        # phase 4: corrupt candidate -> loud rollback, v2 never blinks
        res2 = fleet.promote("broken", path=ckdir, arch_config=ARCH,
                             name="m", timeout=240)
        assert res2["status"] == "rolled_back", res2
        assert "corrupt" in res2["reason"], res2
        raw = router.route(
            samples[0], deadline_s=REQUEST_DEADLINE_S, raw=True
        )
        assert raw["version"] == 2, raw
        time.sleep(1.0)

        stop.set()
        for t in clients:
            t.join(timeout=60)
        with lock:
            done = list(results)
            failed = list(failures)
        # zero requests lost beyond the retry budget: every submitted
        # request reached a terminal outcome, and none FAILED — kills
        # were healed by retry, sheds (if any) answered with retry-after
        assert not failed, f"{len(failed)} lost request(s): {failed[:5]}"
        n_ok = sum(1 for _, _, o in done if o == "ok")
        n_shed = sum(1 for _, _, o in done if o == "shed")
        assert n_ok + n_shed == len(done)
        availability = n_ok / max(len(done), 1)
        lat = sorted(l for _, l, o in done if o == "ok")
        p50 = lat[len(lat) // 2]
        p99 = lat[min(int(len(lat) * 0.99), len(lat) - 1)]
        slo = router.metrics.snapshot()
        fleet.emit(
            "fleet_report",
            submitted=len(done),
            succeeded=n_ok,
            availability=round(availability, 6),
            shed=n_shed,
            p50_ms=round(p50 * 1e3, 3),
            p99_ms=round(p99 * 1e3, 3),
            slo_miss_ratio=slo["slo_miss_ratio"],
            kill_heal_s=round(heal_s, 3),
        )
        assert availability > 0.9, (
            f"availability {availability} with {n_shed} sheds"
        )
    finally:
        # ALWAYS tear the fleet down — a failed phase must not leave
        # orphaned replica processes holding CI's stdout open
        stop.set()
        for t in clients:
            t.join(timeout=60)
        fleet.stop()

    recs = validate_events(
        os.path.join(log_dir, "events.jsonl"),
        require=[
            "replica_lost", "replica_respawned", "fleet_degraded",
            "model_promoted", "model_rollback", "fleet_report",
        ],
    )
    lost = [r for r in recs if r["event"] == "replica_lost"][0]
    assert lost["replica"] == 1, lost
    respawned = [r for r in recs if r["event"] == "replica_respawned"][0]
    assert 0 < respawned["downtime_s"] < 240, respawned
    promoted = [r for r in recs if r["event"] == "model_promoted"][0]
    assert promoted["name"] == "m" and promoted["version"] == 2, promoted
    rolled = [r for r in recs if r["event"] == "model_rollback"]
    assert any("corrupt" in r["reason"] for r in rolled), rolled

    print(
        "fleet smoke OK: boot {:.1f}s, kill->heal {:.1f}s "
        "(downtime {:.1f}s), promote+rollback verified, {} requests "
        "({} shed), availability {:.4f}, p50 {:.0f}ms p99 {:.0f}ms".format(
            boot_s, heal_s, respawned["downtime_s"], len(done), n_shed,
            availability, p50 * 1e3, p99 * 1e3,
        )
    )


if __name__ == "__main__":
    main(sys.argv[1])
