"""XLA introspection (hydragnn_tpu/obs/introspect + report): compiled
cost/memory capture per bucket, the step-time flight recorder + stall
detector, on-demand /profile trace capture, the post-mortem report CLI in
all three formats, and the perf-budget ratchet — plus the acceptance e2e:
a CPU training run whose compile events carry non-empty cost/memory
analysis, a live /profile?steps=1 that writes a loadable trace dir, and a
--check-budget that exits non-zero on an exceeded figure.

(Named test_xla_* so it collects AFTER the established suite — the tier-1
budget on slow hosts reaches the legacy files first.)
"""

import json
import os
import sys
import urllib.request
import warnings

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from hydragnn_tpu.obs import introspect as it
from hydragnn_tpu.obs import report as rep
from hydragnn_tpu.obs import runtime as obs_rt
from hydragnn_tpu.obs.__main__ import main as obs_main
from hydragnn_tpu.obs.events import validate_events
from hydragnn_tpu.obs.runtime import FlightRecorder

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from _resilience_worker import make_samples  # noqa: E402


@pytest.fixture(autouse=True)
def _clean_introspection(monkeypatch):
    """Every test starts with no active telemetry, no forced
    introspection, and an empty capture store."""
    monkeypatch.delenv("HYDRAGNN_INTROSPECT", raising=False)
    obs_rt.deactivate()
    it.reset_captured()
    yield
    obs_rt.deactivate()
    it.reset_captured()


# ---- flight recorder -----------------------------------------------------


def pytest_flight_recorder_ring_wraparound():
    fr = FlightRecorder(capacity=4, stall_factor=100.0, min_fill=1)
    for i in range(10):
        fr.record(float(i))
    assert fr.count == 10
    assert fr.snapshot() == [6.0, 7.0, 8.0, 9.0]
    # before wrapping, snapshot is the partial prefix in order
    fr2 = FlightRecorder(capacity=8, stall_factor=100.0, min_fill=1)
    fr2.record(1.0)
    fr2.record(2.0)
    assert fr2.snapshot() == [1.0, 2.0]


def pytest_flight_recorder_stall_threshold_edge():
    fr = FlightRecorder(capacity=16, stall_factor=4.0, min_fill=4)
    for _ in range(8):
        assert fr.record(0.01) is None
    # EXACTLY at factor x median must NOT fire (strictly-greater contract)
    assert fr.record(0.04) is None
    # a hair beyond does, judged against the window BEFORE the stalled
    # step enters it
    stall = fr.record(0.0401)
    assert stall is not None
    assert stall["median"] == pytest.approx(0.01)
    assert stall["factor"] == 4.0
    assert stall["seconds"] == pytest.approx(0.0401)
    assert stall["step"] == 9


def pytest_flight_recorder_min_fill_clamped_to_capacity():
    # a 4-deep window with the default min_fill=8 must still detect —
    # min_fill clamps to capacity instead of silently disabling stalls
    fr = FlightRecorder(capacity=4, stall_factor=2.0)
    assert fr.min_fill == 4
    for _ in range(4):
        fr.record(0.01)
    assert fr.record(1.0) is not None


def pytest_flight_recorder_no_stall_during_warmup():
    # min_fill gates: even a 1000x step cannot stall before the window
    # has enough history — first-epoch compile/warmup steps never alert
    fr = FlightRecorder(capacity=16, stall_factor=2.0, min_fill=8)
    for _ in range(7):
        fr.record(0.01)
    assert fr.record(10.0) is None  # 8th record: only 7 buffered
    for _ in range(7):
        fr.record(0.01)
    assert fr.record(10.0) is not None  # window is live now


def pytest_on_step_skips_compile_steps(tmp_path, monkeypatch):
    """A step whose dispatch contained an XLA compile neither stalls nor
    enters the ring (its wall time is compile time)."""
    t = obs_rt.RunTelemetry("fr", str(tmp_path / "fr"), port=None)
    try:
        for _ in range(10):
            t.on_step(0.01)
        assert t.flight.count == 10
        # simulate a backend compile landing during the next dispatch
        monkeypatch.setattr(
            obs_rt, "_compile_events", obs_rt._compile_events + 1
        )
        t.on_step(5.0)  # would be a flagrant stall if recorded
        assert t.flight.count == 10  # skipped, not buffered
        assert t.metrics.snapshot()["stalls_total"] == 0
        # the NEXT non-compile slow step does stall
        t.on_step(5.0)
        assert t.metrics.snapshot()["stalls_total"] == 1
    finally:
        t.close()
    recs = validate_events(str(tmp_path / "fr" / "events.jsonl"))
    stalls = [r for r in recs if r["event"] == "stall"]
    assert len(stalls) == 1
    assert stalls[0]["median"] == pytest.approx(0.01)
    assert stalls[0]["factor"] == 8.0  # the documented default


def pytest_on_step_normalizes_multi_step_dispatches(tmp_path):
    """K-step scan dispatches are judged on PER-STEP time: a healthy
    multi dispatch among single-step dispatches must not read as a
    stall."""
    t = obs_rt.RunTelemetry("ms", str(tmp_path / "ms"), port=None)
    try:
        for _ in range(10):
            t.on_step(0.01)
        t.on_step(0.08, count=8)  # 10ms/step: healthy, 8x the wall time
        assert t.metrics.snapshot()["stalls_total"] == 0
        t.on_step(0.9, count=8)  # 112ms/step > 8 x 10ms median: stall
        assert t.metrics.snapshot()["stalls_total"] == 1
    finally:
        t.close()


# ---- instrumented jit ----------------------------------------------------


def pytest_instrument_passthrough_when_disabled():
    f = it.instrument("toy", jax.jit(lambda x: x * 2))
    np.testing.assert_allclose(np.asarray(f(jnp.ones(4))), 2.0)
    assert it.captured() == []


def pytest_instrument_captures_per_novel_shape(monkeypatch):
    monkeypatch.setenv("HYDRAGNN_INTROSPECT", "1")
    f = it.instrument("toy", jax.jit(lambda x: (x @ x).sum()))
    f(jnp.ones((8, 8)))
    f(jnp.ones((8, 8)))  # repeat shape: no second capture
    f(jnp.ones((16, 16)))
    recs = it.captured("toy")
    assert len(recs) == 2
    buckets = {r["bucket"] for r in recs}
    assert len(buckets) == 2
    for r in recs:
        assert r["bucket"].startswith("toy/")
        assert r["cost"].get("flops", 0) > 0
        assert r["memory"].get("peak_bytes", 0) > 0
        assert r["memory"].get("argument_bytes", 0) > 0
    # the bigger matmul costs more flops — the figures are real
    by_flops = sorted(r["cost"]["flops"] for r in recs)
    assert by_flops[1] > by_flops[0]


def pytest_instrument_forwards_attributes(monkeypatch):
    jitted = jax.jit(lambda x: x + 1)
    f = it.instrument("fw", jitted)
    x = jnp.ones(3)
    # the AOT surface benchmarks use, and the sentinel's cache probe
    assert f.lower(x).compile() is not None
    assert f._cache_size() == jitted._cache_size()
    # a non-jit callable degrades to pure passthrough even when enabled
    monkeypatch.setenv("HYDRAGNN_INTROSPECT", "1")
    g = it.instrument("plain", lambda x: x * 3)
    assert g(2) == 6
    assert it.captured("plain") == []


def pytest_instrument_bucket_label_stable():
    key = it.signature_key((jnp.ones((4, 2)),))
    assert it.bucket_label("p", key) == it.bucket_label("p", key)
    other = it.signature_key((jnp.ones((4, 3)),))
    assert it.bucket_label("p", key) != it.bucket_label("p", other)


# ---- trace capture -------------------------------------------------------


class _FakeJaxProfiler:
    def __init__(self):
        self.calls = []

    def start_trace(self, trace_dir):
        self.calls.append(("start", trace_dir))

    def stop_trace(self):
        self.calls.append(("stop",))


@pytest.fixture
def fake_profiler(monkeypatch):
    import jax.profiler

    fake = _FakeJaxProfiler()
    monkeypatch.setattr(jax.profiler, "start_trace", fake.start_trace)
    monkeypatch.setattr(jax.profiler, "stop_trace", fake.stop_trace)
    return fake


def pytest_trace_capture_lifecycle(fake_profiler, tmp_path):
    tc = it.TraceCapture(str(tmp_path / "tr"))
    assert tc.arm(0)["status"] == "error"
    assert tc.tick() is None  # idle: no-op
    assert tc.arm(2)["status"] == "armed"
    assert tc.arm(1)["status"] == "busy"  # one capture at a time
    started = tc.tick()
    assert started["status"] == "started" and started["steps"] == 2
    assert fake_profiler.calls == [("start", str(tmp_path / "tr"))]
    assert tc.tick() is None  # step 1 of 2
    done = tc.tick()  # step 2 of 2 -> stop
    assert done["status"] == "done"
    assert fake_profiler.calls[-1] == ("stop",)
    assert tc.tick() is None  # back to idle


def pytest_trace_capture_start_failure_does_not_wedge(
    monkeypatch, tmp_path
):
    """A profiler that refuses to start (another session active) must
    surface as an error payload, not an exception into the training
    loop — and the next arm must work."""
    import jax.profiler

    calls = []

    def _boom(trace_dir):
        if not calls:
            calls.append("boom")
            raise RuntimeError("profiler already active")
        calls.append(("start", trace_dir))

    monkeypatch.setattr(jax.profiler, "start_trace", _boom)
    monkeypatch.setattr(
        jax.profiler, "stop_trace", lambda: calls.append(("stop",))
    )
    tc = it.TraceCapture(str(tmp_path / "tr"))
    assert tc.arm(1)["status"] == "armed"
    err = tc.tick()
    assert err["status"] == "error"
    assert "already active" in err["error"]
    # not wedged: a fresh arm starts cleanly once the profiler recovers
    assert tc.arm(1)["status"] == "armed"
    assert tc.tick()["status"] == "started"
    assert tc.tick()["status"] == "done"


def pytest_fit_path_profile_ticks_at_chunk_boundaries(
    fake_profiler, tmp_path, monkeypatch
):
    """Whole-chunk dispatches have no per-step hook: /profile and
    HYDRAGNN_PROFILE_AT_STEP resolve at dispatch boundaries instead of
    wedging the endpoint in 'busy'."""
    t = obs_rt.RunTelemetry("fitp", str(tmp_path / "fitp"), port=None)
    obs_rt.activate(t)
    try:
        assert t.profile(1)["status"] == "armed"
        obs_rt.epoch_start(0)
        obs_rt.dispatch_boundary()  # chunk 1 done -> trace starts
        assert fake_profiler.calls == [("start", t.trace.trace_dir)]
        obs_rt.dispatch_boundary()  # chunk 2 done -> trace flushed
        assert fake_profiler.calls[-1] == ("stop",)
        assert t.profile(1)["status"] == "armed"  # endpoint not wedged
    finally:
        obs_rt.deactivate()


def pytest_staged_epoch_profile_ticks_per_dispatch(
    fake_profiler, tmp_path, monkeypatch
):
    """train_epoch_staged is ONE dispatch per epoch with no per-step
    hook: /profile must tick per staged epoch, not wedge in 'busy'."""
    monkeypatch.chdir(tmp_path)
    trainer, state, loaders, _ = _build_tiny_training(num_epoch=2)
    staged = trainer.stage_batches(list(loaders[0]))
    rng = jax.random.PRNGKey(0)
    t = obs_rt.activate(
        obs_rt.RunTelemetry("st", str(tmp_path / "st"), port=None)
    )
    try:
        state, rng, _, _ = trainer.train_epoch_staged(state, staged, rng)
        assert t.profile(1)["status"] == "armed"
        state, rng, _, _ = trainer.train_epoch_staged(state, staged, rng)
        assert fake_profiler.calls[0][0] == "start"
        state, rng, _, _ = trainer.train_epoch_staged(state, staged, rng)
        assert fake_profiler.calls[-1] == ("stop",)
        assert t.profile(1)["status"] == "armed"  # not wedged
    finally:
        obs_rt.deactivate()


def pytest_trace_capture_close_flushes_open_trace(fake_profiler, tmp_path):
    tc = it.TraceCapture(str(tmp_path / "tr"))
    tc.arm(10)
    tc.tick()
    assert tc.close()["status"] == "done"
    assert fake_profiler.calls[-1] == ("stop",)
    assert tc.close() is None  # idempotent


def pytest_parse_profile_at_step():
    assert it.parse_profile_at_step(None) is None
    assert it.parse_profile_at_step("") is None
    assert it.parse_profile_at_step("2:5") == (2, 5)
    assert it.parse_profile_at_step("7") == (0, 7)
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        assert it.parse_profile_at_step("nope") is None
    assert any("PROFILE_AT_STEP" in str(c.message) for c in caught)


def pytest_env_armed_profile_at_step(fake_profiler, tmp_path, monkeypatch):
    monkeypatch.setenv("HYDRAGNN_PROFILE_AT_STEP", "1:2")
    monkeypatch.setenv("HYDRAGNN_PROFILE_STEPS", "2")
    t = obs_rt.RunTelemetry("arm", str(tmp_path / "arm"), port=None)
    try:
        t.on_epoch_start(0)
        for _ in range(5):
            t.on_step(0.01)
        assert fake_profiler.calls == []  # wrong epoch: never armed
        t.on_epoch_start(1)
        t.on_step(0.01)
        assert fake_profiler.calls == []  # step 1 < target 2
        t.on_step(0.01)  # step 2: arms AND starts on the same tick
        assert fake_profiler.calls == [("start", t.trace.trace_dir)]
        t.on_step(0.01)
        t.on_step(0.01)
        assert fake_profiler.calls[-1] == ("stop",)
        # one-shot: later epochs do not re-arm
        t.on_epoch_start(1)
        for _ in range(5):
            t.on_step(0.01)
        assert len(fake_profiler.calls) == 2
    finally:
        t.close()


def pytest_http_profile_501_without_provider_support(tmp_path):
    from hydragnn_tpu.obs.http import ObservabilityServer
    from hydragnn_tpu.obs.metrics import MetricsRegistry

    class Dummy:
        metrics = MetricsRegistry("dummy")

        def health(self):
            return {"status": "ok"}

    srv = ObservabilityServer(Dummy(), port=0).start()
    try:
        host, port = srv.address
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(
                f"http://{host}:{port}/profile?steps=1", timeout=10
            )
        assert exc.value.code == 501
    finally:
        srv.stop()


# ---- deprecation shim ----------------------------------------------------


def pytest_utils_profile_shim_reexports_and_warns():
    import importlib

    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        import hydragnn_tpu.utils.profile as shim

        shim = importlib.reload(shim)  # module body re-runs: must warn
    assert any(
        issubclass(c.category, DeprecationWarning) for c in caught
    )
    assert shim.Profiler is it.Profiler
    assert shim.record_function is it.record_function


# ---- report + budget ratchet (unit) --------------------------------------


def _write_events(path, records):
    with open(path, "w") as f:
        for i, r in enumerate(records):
            f.write(json.dumps({"ts": 100.0 + i, "seq": i, **r}) + "\n")


_MANIFEST = {
    "event": "run_manifest", "schema_version": 1, "run": "r",
    "config_hash": "c", "git_rev": "g", "world_size": 1,
    "device_kind": "cpu", "device_count": 1, "num_epoch": 2,
}


def _synthetic_stream(tmp_path):
    path = str(tmp_path / "events.jsonl")
    _write_events(
        path,
        [
            _MANIFEST,
            {"event": "compile", "name": "train_step",
             "bucket": "train_step/aaaa1111",
             "cost": {"flops": 1000.0, "bytes_accessed": 500.0},
             "memory": {"peak_bytes": 2048.0, "argument_bytes": 1024.0}},
            {"event": "epoch", "epoch": 0, "train_loss": 0.5,
             "val_loss": 0.6, "test_loss": 0.7, "mode": "stream",
             "wall_time_s": 1.0, "graphs_per_sec": 100.0,
             "padding_waste": 0.25},
            {"event": "stall", "step": 9, "seconds": 1.0, "median": 0.1,
             "factor": 8.0},
            {"event": "epoch", "epoch": 1, "train_loss": None,
             "val_loss": None, "test_loss": None, "mode": "stream"},
            {"event": "run_end", "status": "complete"},
        ],
    )
    return path


def pytest_report_builds_and_renders_all_formats(tmp_path):
    path = _synthetic_stream(tmp_path)
    report = rep.build_report(rep.load_events(path))
    assert report["run"]["status"] == "complete"
    assert len(report["epochs"]) == 2
    assert report["epochs"][1]["train_loss"] is None  # nulled NaN survives
    assert report["throughput"]["best_graphs_per_sec"] == 100.0
    assert report["counts"]["stall"] == 1
    assert report["programs"]["train_step/aaaa1111"]["flops"] == 1000.0
    text = rep.render_text(report)
    assert "train_step" in text and "graphs/s" in text and "stall" in text
    md = rep.render_markdown(report)
    assert md.startswith("# Run report") and "| epoch |" in md
    parsed = json.loads(rep.render_json(report))
    assert parsed["run"]["status"] == "complete"


def pytest_report_tolerates_torn_streams(tmp_path):
    path = str(tmp_path / "events.jsonl")
    with open(path, "w") as f:
        f.write(json.dumps({"event": "epoch", "ts": 1.0, "seq": 0,
                            "epoch": 0, "train_loss": 0.1,
                            "val_loss": 0.1, "test_loss": 0.1,
                            "mode": "stream"}) + "\n")
        f.write('{"event": "epoch", "ts": 2.0, "se')  # torn tail
    report = rep.build_report(rep.load_events(path))
    assert len(report["epochs"]) == 1
    assert report["run"]["status"] == "incomplete"  # no run_end recorded


def pytest_budget_check_violations_and_notes(tmp_path):
    path = _synthetic_stream(tmp_path)
    report = rep.build_report(rep.load_events(path))
    budget = rep.budget_from_report(report, tolerance=0.10)
    assert budget["programs"]["train_step/aaaa1111"]["flops"] == 1000.0

    # within tolerance: clean
    assert rep.check_budget(report, budget) == ([], [], [])
    # baseline tightened under the measurement -> violation with the
    # offending metric named
    tight = json.loads(json.dumps(budget))
    tight["programs"]["train_step/aaaa1111"]["flops"] = 500.0
    violations, unbudgeted, stale = rep.check_budget(report, tight)
    assert [v["metric"] for v in violations] == ["flops"]
    assert violations[0]["current"] == 1000.0
    assert violations[0]["limit"] == pytest.approx(550.0)
    # inside an explicitly wider tolerance: clean again
    assert rep.check_budget(report, tight, tolerance=1.5)[0] == []
    # unknown buckets on either side are notes, not failures
    extra = json.loads(json.dumps(budget))
    extra["programs"]["gone/00000000"] = {"flops": 1.0}
    del extra["programs"]["train_step/aaaa1111"]
    violations, unbudgeted, stale = rep.check_budget(report, extra)
    assert violations == []
    assert unbudgeted == ["train_step/aaaa1111"]
    assert stale == ["gone/00000000"]


def pytest_report_cli_exit_codes(tmp_path, capsys):
    path = _synthetic_stream(tmp_path)
    budget_path = str(tmp_path / "budget.json")
    # usage error: no stream
    assert obs_main(["report", str(tmp_path / "nope")]) == 2
    # write the baseline from the run, then the check passes
    assert obs_main(["report", path, "--write-budget", budget_path]) == 0
    assert obs_main(["report", path, "--check-budget", budget_path]) == 0
    # exceed beyond tolerance -> exit 1
    budget = json.load(open(budget_path))
    budget["programs"]["train_step/aaaa1111"]["peak_bytes"] = 100.0
    json.dump(budget, open(budget_path, "w"))
    capsys.readouterr()
    assert obs_main(["report", path, "--check-budget", budget_path]) == 1
    assert "OVER BUDGET" in capsys.readouterr().err
    # malformed budget -> usage error
    json.dump({"not": "a budget"}, open(budget_path, "w"))
    assert obs_main(["report", path, "--check-budget", budget_path]) == 2


def pytest_report_cli_refuses_vacuous_budget_pass(tmp_path, capsys):
    """A stream with ZERO compile events cannot satisfy a non-empty
    budget — the gate must fail loudly, not pass having checked
    nothing (e.g. introspection silently off in CI)."""
    path = str(tmp_path / "events.jsonl")
    _write_events(
        path, [_MANIFEST, {"event": "run_end", "status": "complete"}]
    )
    budget_path = str(tmp_path / "budget.json")
    json.dump(
        {"version": 1, "tolerance": 0.1,
         "programs": {"train_step/aaaa1111": {"flops": 1.0}}},
        open(budget_path, "w"),
    )
    capsys.readouterr()
    assert obs_main(["report", path, "--check-budget", budget_path]) == 2
    assert "no compile events" in capsys.readouterr().err


# ---- the acceptance e2e --------------------------------------------------


def _build_tiny_training(num_epoch=2):
    from hydragnn_tpu.data.loaders import GraphLoader, compute_layout
    from hydragnn_tpu.models.create import create_model_config
    from hydragnn_tpu.train.trainer import Trainer

    arch = {
        "model_type": "GIN",
        "input_dim": 1,
        "hidden_dim": 8,
        "num_conv_layers": 2,
        "output_dim": [1, 1],
        "output_type": ["graph", "node"],
        "output_heads": {
            "graph": {
                "num_sharedlayers": 1,
                "dim_sharedlayers": 8,
                "num_headlayers": 1,
                "dim_headlayers": [8],
            },
            "node": {"num_headlayers": 1, "dim_headlayers": [8],
                     "type": "mlp"},
        },
        "task_weights": [1.0, 1.0],
    }
    training = {
        "num_epoch": num_epoch,
        "Optimizer": {"type": "AdamW", "learning_rate": 1e-2},
        "resume_every": 0,
    }
    samples = make_samples()
    layout = compute_layout([samples], batch_size=4)
    loaders = (
        GraphLoader(samples[:16], 4, layout, shuffle=True, seed=7),
        GraphLoader(samples[16:20], 4, layout, shuffle=False),
        GraphLoader(samples[20:], 4, layout, shuffle=False),
    )
    model = create_model_config(arch)
    trainer = Trainer(model, training)
    state = trainer.init_state(next(iter(loaders[0])), seed=0)
    return trainer, state, loaders, training


class _ProfileOnEpochWriter:
    """writer= hook that arms /profile?steps=1 DURING the run — the
    'on-demand capture on a live run' acceptance leg."""

    def __init__(self, url):
        self.url = url
        self.response = None

    def add_scalar(self, tag, value, step):
        if self.response is None and step >= 1:
            self.response = json.loads(
                urllib.request.urlopen(self.url, timeout=10).read()
            )

    def close(self):
        pass


def pytest_introspection_training_e2e(tmp_path, monkeypatch):
    from hydragnn_tpu.train.epoch_driver import train_validate_test

    monkeypatch.chdir(tmp_path)
    num_epoch = 3
    trainer, state, loaders, training = _build_tiny_training(num_epoch)
    log_dir = str(tmp_path / "logs" / "xla-e2e")
    telem = obs_rt.activate(obs_rt.RunTelemetry("xla-e2e", log_dir, port=0))
    try:
        telem.emit_manifest(
            {"NeuralNetwork": {"Training": training}}, "xla-e2e"
        )
        host, port = telem.address
        writer = _ProfileOnEpochWriter(
            f"http://{host}:{port}/profile?steps=1"
        )
        config_nn = {
            "Training": training,
            "Variables_of_interest": {"output_names": ["sum", "x"]},
        }
        train_validate_test(
            trainer, state, *loaders, config_nn, "xla-e2e", verbosity=0,
            writer=writer,
        )
        assert writer.response is not None, "mid-run /profile never hit"
        assert writer.response["status"] == "armed"
        # per-bucket compiled-cost gauges are live on /metrics
        metrics = urllib.request.urlopen(
            f"http://{host}:{port}/metrics", timeout=10
        ).read().decode()
        assert 'hydragnn_train_flops_per_step{bucket="train_step/' in metrics
        assert 'hydragnn_train_hbm_peak_bytes{bucket="train_step/' in metrics
    finally:
        obs_rt.deactivate()

    # -- compile events carry non-empty cost AND memory analysis
    recs = validate_events(
        os.path.join(log_dir, "events.jsonl"),
        require=["run_manifest", "compile", "profile", "epoch", "run_end"],
    )
    compiles = [r for r in recs if r["event"] == "compile"]
    names = {r["name"] for r in compiles}
    assert "train_step" in names and "eval_step" in names
    assert len({r["bucket"] for r in compiles}) == len(compiles)
    for r in compiles:
        assert r["cost"].get("flops", 0) > 0, r
        assert r["memory"].get("peak_bytes", 0) > 0, r
        assert r["memory"].get("argument_bytes", 0) > 0, r

    # -- the live-armed capture completed and left a loadable trace dir
    profile_events = [r for r in recs if r["event"] == "profile"]
    assert [p["status"] for p in profile_events][:3] == [
        "armed", "started", "done"
    ]
    trace_dir = profile_events[-1]["trace_dir"]
    trace_files = [
        os.path.join(root, f)
        for root, _, files in os.walk(trace_dir)
        for f in files
    ]
    assert any(f.endswith(".xplane.pb") for f in trace_files), trace_files

    # -- the report CLI renders all three formats from this run
    for fmt in ("text", "markdown", "json"):
        assert obs_main(["report", log_dir, "--format", fmt]) == 0

    # -- budget ratchet against THIS run: write, pass, then trip it
    budget_path = str(tmp_path / "perf-baseline.json")
    assert obs_main(["report", log_dir, "--write-budget", budget_path]) == 0
    assert obs_main(["report", log_dir, "--check-budget", budget_path]) == 0
    budget = json.load(open(budget_path))
    key = next(
        k for k in budget["programs"] if k.startswith("train_step/")
    )
    budget["programs"][key]["flops"] /= 10.0
    json.dump(budget, open(budget_path, "w"))
    assert obs_main(["report", log_dir, "--check-budget", budget_path]) == 1
