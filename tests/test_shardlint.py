"""shardlint (analysis --suite=sharding): the sharding-correctness suite.

Per rule: a bad snippet that must flag and a good snippet that must not,
plus the shardlint suppression tag, the per-suite ``--list-rules``
catalog, multi-suite ``--stats``/github output in ONE invocation, the
CLI exit-code contract, and the acceptance regressions — the merged tree
runs clean against the committed (empty) ``.shardlint-baseline.json``,
and reintroducing a hardcoded axis in a step builder or a contract-less
serve jit fails the gate.

Everything here is pure-AST: no jax execution. The compiled-HLO half of
shardlint (``analysis/hlo.py``) is covered by
``tests/test_shardlint_hlo.py`` and the CI ratchet smoke.
"""

import json
import os
import textwrap

from hydragnn_tpu.analysis import analyze_paths
from hydragnn_tpu.analysis.__main__ import main as lint_main
from hydragnn_tpu.analysis.core import all_rules, all_suites, rules_in_suite

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

SHARDING_RULES = {
    "hardcoded-mesh-axis",
    "jit-missing-shardings",
    "unknown-spec-axis",
    "device-put-without-sharding",
    "legacy-pmap-usage",
    "reshape-across-sharded-dim",
}


def _lint(tmp_path, files, **kw):
    for rel, src in files.items():
        path = tmp_path / rel
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(textwrap.dedent(src))
    return analyze_paths([str(tmp_path)], root=str(tmp_path), **kw).findings


def _rules_of(findings):
    return sorted({f.rule for f in findings})


def pytest_sharding_suite_registry():
    assert rules_in_suite("sharding") == SHARDING_RULES
    assert "sharding" in all_suites()


def pytest_axis_vocabulary_matches_parallel_constants():
    # the rule module's fallback vocabulary and the real constants must
    # agree — a renamed axis must change BOTH or the lint goes blind
    from hydragnn_tpu.analysis.rules_sharding import _known_axes
    from hydragnn_tpu.parallel.mesh import (
        DATA_AXIS,
        GRAPH_AXIS,
        KNOWN_AXES,
        MODEL_AXIS,
    )

    assert _known_axes() == frozenset(KNOWN_AXES)
    assert {DATA_AXIS, MODEL_AXIS, GRAPH_AXIS} == set(KNOWN_AXES)


# ---- hardcoded-mesh-axis --------------------------------------------------

_AXIS_BAD = """
    from jax.sharding import NamedSharding, PartitionSpec as P

    def plan(mesh):
        batch = NamedSharding(mesh, P("data"))
        stacked = NamedSharding(mesh, P(None, "model"))
        return batch, stacked
"""

_AXIS_GOOD = """
    from jax.sharding import NamedSharding, PartitionSpec as P

    from hydragnn_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS

    def plan(mesh):
        batch = NamedSharding(mesh, P(DATA_AXIS))
        stacked = NamedSharding(mesh, P(None, MODEL_AXIS))
        return batch, stacked
"""


def pytest_hardcoded_axis_flags_literals_outside_parallel(tmp_path):
    findings = _lint(tmp_path, {"train/steps.py": _AXIS_BAD})
    hits = [f for f in findings if f.rule == "hardcoded-mesh-axis"]
    assert len(hits) == 2, findings


def pytest_hardcoded_axis_clean_on_constants(tmp_path):
    findings = _lint(tmp_path, {"train/steps.py": _AXIS_GOOD})
    assert not [f for f in findings if f.rule == "hardcoded-mesh-axis"]


def pytest_hardcoded_axis_exempts_parallel_package(tmp_path):
    # parallel/ is where the strings are DEFINED — the constants module
    # and the mesh builders legitimately spell them
    findings = _lint(tmp_path, {"parallel/mesh.py": _AXIS_BAD})
    assert not [f for f in findings if f.rule == "hardcoded-mesh-axis"]


def pytest_hardcoded_axis_flags_collective_axis_names(tmp_path):
    src = """
        import jax

        def pooled(x):
            return jax.lax.psum(x, "model")

        def indexed(axis_name):
            return jax.lax.axis_index(axis_name)  # variable: fine
    """
    findings = _lint(tmp_path, {"models/common.py": src})
    hits = [f for f in findings if f.rule == "hardcoded-mesh-axis"]
    assert len(hits) == 1 and "'model'" in hits[0].message, findings


# ---- jit-missing-shardings ------------------------------------------------

_JIT_BAD = """
    import jax

    def make(model):
        def _apply(params, batch):
            return model.apply(params, batch)

        return jax.jit(_apply)
"""

_JIT_GOOD = """
    import jax

    from hydragnn_tpu.parallel.mesh import jit_replicated

    def make(model, plan):
        def _apply(params, batch):
            return model.apply(params, batch)

        def train_step(state, batch, rng):
            return state

        a = jit_replicated(_apply)
        b = jax.jit(train_step, **plan, donate_argnums=(0,))
        c = jax.jit(_apply, out_shardings=None)
        d = jax.jit(lambda t: t)  # utility copy: inherits deliberately
        return a, b, c, d
"""


def pytest_jit_missing_shardings_flags_bare_dispatch_jit(tmp_path):
    findings = _lint(tmp_path, {"serve/server.py": _JIT_BAD})
    hits = [f for f in findings if f.rule == "jit-missing-shardings"]
    assert len(hits) == 1 and "_apply" in hits[0].message, findings


def pytest_jit_missing_shardings_sanctioned_spellings(tmp_path):
    findings = _lint(tmp_path, {"train/steps.py": _JIT_GOOD})
    assert not [f for f in findings if f.rule == "jit-missing-shardings"]


def pytest_jit_missing_shardings_decorator_forms(tmp_path):
    src = """
        from functools import partial

        import jax

        @jax.jit
        def eval_step(params, batch):
            return params

        @jax.jit(donate_argnums=(0,))
        def train_step(state, batch, rng):
            return state

        @partial(jax.jit, donate_argnums=(0,))
        def update_step(state, batch):
            return state

        @partial(jax.jit, out_shardings=None)
        def predict_step(params, batch):
            return params  # declared contract (explicit inherit)

        @jax.jit
        def _copy_buffers(t):
            return t  # not a dispatching name: exempt
    """
    findings = _lint(tmp_path, {"serve/server.py": src})
    hits = [f for f in findings if f.rule == "jit-missing-shardings"]
    assert len(hits) == 3, findings
    flagged = {m.split("`")[1] for m in (h.message for h in hits) if "`" in m}
    assert flagged == {"eval_step", "train_step", "update_step"}, hits


def pytest_jit_missing_shardings_scoped_to_train_serve(tmp_path):
    # benches build ad-hoc jits against whatever placement they measure
    findings = _lint(tmp_path, {"benchmarks/bench.py": _JIT_BAD})
    assert not [f for f in findings if f.rule == "jit-missing-shardings"]


# ---- unknown-spec-axis ----------------------------------------------------


def pytest_unknown_spec_axis_flags_typo(tmp_path):
    src = """
        from jax.sharding import PartitionSpec as P

        from hydragnn_tpu.parallel.mesh import DATA_AXIS

        def specs():
            bad = P("dat")
            ok = P(DATA_AXIS)
            ok2 = P("data", "model")
            return bad, ok, ok2
    """
    # applies INSIDE parallel/ too — a typo there is just as fatal
    findings = _lint(tmp_path, {"parallel/rules.py": src})
    hits = [f for f in findings if f.rule == "unknown-spec-axis"]
    assert len(hits) == 1 and "'dat'" in hits[0].message, findings


def pytest_unknown_spec_axis_flags_collective_typo(tmp_path):
    src = """
        import jax

        def pooled(x):
            return jax.lax.psum(x, "graf")
    """
    findings = _lint(tmp_path, {"models/base.py": src})
    assert _rules_of(findings) == ["unknown-spec-axis"], findings


# ---- device-put-without-sharding ------------------------------------------


def pytest_device_put_without_sharding(tmp_path):
    src = """
        import jax
        import jax.numpy as jnp

        def place(batch, sharding):
            bad = jax.device_put(batch)
            good = jax.device_put(batch, sharding)
            kw = jax.device_put(batch, device=sharding)
            scalar = jax.device_put(0.0)
            return bad, good, kw, scalar
    """
    findings = _lint(tmp_path, {"train/trainer.py": src})
    hits = [f for f in findings if f.rule == "device-put-without-sharding"]
    assert len(hits) == 1 and hits[0].line == 6, findings


# ---- legacy-pmap-usage ----------------------------------------------------


def pytest_legacy_pmap_flags_calls_and_decorators(tmp_path):
    src = """
        import jax

        step = jax.pmap(lambda x: x)

        @jax.pmap
        def replicated(x):
            return x

        def mesh_way(fn, shardings):
            return jax.jit(fn, in_shardings=shardings)
    """
    findings = _lint(tmp_path, {"train/old.py": src})
    hits = [f for f in findings if f.rule == "legacy-pmap-usage"]
    assert len(hits) == 2, findings


# ---- reshape-across-sharded-dim -------------------------------------------

_RESHAPE_BAD = """
    import jax
    import jax.numpy as jnp

    def step(x, sharding):
        x = jax.lax.with_sharding_constraint(x, sharding)
        flat = x.reshape(-1, x.shape[-1])
        also = jnp.reshape(x, (-1, 4))
        return flat, also
"""

_RESHAPE_GOOD = """
    import jax
    import jax.numpy as jnp

    def step(x, sharding):
        x = jax.lax.with_sharding_constraint(x, sharding)
        keep = x.reshape(x.shape[0], -1)  # leading (sharded) dim kept
        return keep

    def host_side(a):
        return a.reshape(-1, 3)  # no sharding pinned in this function
"""


def pytest_reshape_across_sharded_dim_flags_leading_collapse(tmp_path):
    findings = _lint(tmp_path, {"train/steps.py": _RESHAPE_BAD})
    hits = [f for f in findings if f.rule == "reshape-across-sharded-dim"]
    assert len(hits) == 2, findings


def pytest_reshape_across_sharded_dim_good_patterns(tmp_path):
    findings = _lint(tmp_path, {"train/steps.py": _RESHAPE_GOOD})
    assert not [
        f for f in findings if f.rule == "reshape-across-sharded-dim"
    ], findings


# ---- suppression tag ------------------------------------------------------


def pytest_shardlint_suppression_tag(tmp_path):
    src = """
        from jax.sharding import PartitionSpec as P

        def specs(mesh):
            a = P("data")  # shardlint: disable=hardcoded-mesh-axis
            # justification: doc example rendered into --help output
            # shardlint: disable=hardcoded-mesh-axis
            b = P("data")
            c = P("data")
            return a, b, c
    """
    findings = _lint(tmp_path, {"train/x.py": src})
    hits = [f for f in findings if f.rule == "hardcoded-mesh-axis"]
    assert len(hits) == 1, findings  # only c survives


# ---- CLI: suite selection, list-rules, multi-suite output -----------------


def pytest_suite_cli_selects_sharding(tmp_path, capsys):
    bad = tmp_path / "train" / "t.py"
    bad.parent.mkdir(parents=True)
    bad.write_text(
        "import jax\n\n"
        "def f(x, acc=[]):\n"
        "    return jax.device_put(x)\n"
    )
    assert lint_main([str(bad), "--suite=sharding", "--format=json"]) == 1
    out = json.loads(capsys.readouterr().out)
    assert sorted({f["rule"] for f in out["new"]}) == [
        "device-put-without-sharding"
    ]
    # unknown suite is a usage error
    assert lint_main([str(bad), "--suite=shardzzz"]) == 2
    capsys.readouterr()


def pytest_list_rules_groups_by_suite(capsys):
    assert lint_main(["--list-rules"]) == 0
    listed = capsys.readouterr().out
    # one header per suite, naming its gate
    for header in (
        "suite jax (jaxlint gate",
        "suite concurrency (threadlint gate",
        "suite sharding (shardlint gate",
        "suite numerics (numlint gate",
    ):
        assert header in listed, listed
    # every registered rule appears with its one-line doc
    for name, rule in all_rules().items():
        assert f"{name}: " in listed, name
        assert rule.description.split("\n")[0][:40] in listed.replace(
            "\n", " "
        )
    # --suite filters the catalog
    assert lint_main(["--list-rules", "--suite=sharding"]) == 0
    listed = capsys.readouterr().out
    assert "suite sharding" in listed and "suite jax" not in listed
    for name in SHARDING_RULES:
        assert name in listed
    # unknown suite is a usage error even for --list-rules
    assert lint_main(["--list-rules", "--suite=nope"]) == 2
    capsys.readouterr()


def pytest_multi_suite_stats_and_github_in_one_invocation(tmp_path, capsys):
    """One invocation with NO --suite must report findings from all
    FOUR suites: github annotations for each, and a --stats table
    listing every suite's rules (satellite: report coverage across
    suites, previously only exercised per-suite)."""
    bad = tmp_path / "serve" / "s.py"
    bad.parent.mkdir(parents=True)
    bad.write_text(
        "import queue\n"
        "import jax\n"
        "import jax.numpy as jnp\n\n"
        "q = queue.Queue()\n\n"
        "def f(x, acc=[]):\n"
        "    return jax.device_put(x.astype(jnp.bfloat16))\n"
    )
    assert lint_main([str(bad), "--format=github", "--stats"]) == 1
    out = capsys.readouterr().out
    # one annotation per finding, each naming its rule
    for rule in (
        "queue-misuse",  # concurrency
        "mutable-default-arg",  # jax
        "device-put-without-sharding",  # sharding
        "precision-policy-bypass",  # numerics
    ):
        assert f"title=jaxlint {rule}" in out, out
    # the stats table covers all four suites' rules in one run
    for rule in ("queue-misuse", "mutable-default-arg",
                 "device-put-without-sharding", "hardcoded-mesh-axis",
                 "precision-policy-bypass"):
        assert rule in out.split("new finding(s)")[-1], out
    # and per-suite baselines compose in one gate each: the sharding
    # baseline absorbs the sharding finding, the others still fail
    bl = tmp_path / "bl.json"
    assert (
        lint_main(
            [str(bad), "--suite=sharding", f"--write-baseline={bl}"]
        )
        == 0
    )
    capsys.readouterr()
    assert (
        lint_main(
            [str(bad), "--suite=sharding", f"--baseline={bl}", "--stats"]
        )
        == 0
    )
    out = capsys.readouterr().out
    assert "device-put-without-sharding" in out  # baselined column
    assert lint_main([str(bad), f"--baseline={bl}"]) == 1
    capsys.readouterr()


# ---- acceptance -----------------------------------------------------------


def pytest_merged_tree_is_clean_for_sharding_suite():
    """`--suite=sharding` exits 0 on the committed tree: every true
    positive (hardcoded axes in steps/trainer/predict, the serve jit)
    was FIXED, and the committed baseline is EMPTY."""
    paths = [
        os.path.join(REPO_ROOT, d)
        for d in ("hydragnn_tpu", "examples", "benchmarks")
    ]
    result = analyze_paths(
        paths, select=rules_in_suite("sharding"), root=REPO_ROOT
    )
    assert not result.findings, [
        f"{f.path}:{f.line}: {f.rule}" for f in result.findings
    ]
    bl = json.load(open(os.path.join(REPO_ROOT, ".shardlint-baseline.json")))
    assert bl["findings"] == []


def pytest_reintroduction_fails_the_gate(tmp_path):
    """The two regressions the gate exists for: a hardcoded axis crept
    back into a step builder, and a serve-side jit added without its
    sharding contract."""
    steps = textwrap.dedent(
        """
        from jax.sharding import NamedSharding, PartitionSpec as P

        def _sharding_plan(mesh, st):
            return {"train_step": dict(
                in_shardings=(st, NamedSharding(mesh, P("data")), None),
            )}
        """
    )
    serve = textwrap.dedent(
        """
        import jax

        def build(model):
            def _predict(params, batch):
                return model.apply(params, batch)

            return jax.jit(_predict)
        """
    )
    findings = _lint(
        tmp_path,
        {"train/steps.py": steps, "serve/server.py": serve},
        select=rules_in_suite("sharding"),
    )
    assert _rules_of(findings) == [
        "hardcoded-mesh-axis",
        "jit-missing-shardings",
    ], findings
