"""CI smoke: 2-D mesh parity + elastic mesh re-derivation (NOT pytest).

Four phases, each a subprocess of ``_mesh_worker.py``:

1. **4x2 training** on the forced 8-device CPU backend (2 epochs, live
   telemetry): schema-valid ``mesh_shape`` (shape [4, 2]) +
   ``param_sharding`` events, per-epoch compile count flat (asserted in
   the worker).
2. **1x1 reference** (single forced device, no mesh): the 4x2 loss
   trajectory must match it to float32 tolerance — 2-D sharding is
   placement, not arithmetic.
3. **Kill**: same 4x2 config with ``HYDRAGNN_FAULT_KILL_AT_STEP`` mid
   epoch 2 — the worker dies hard (exit 113) leaving rolling
   checkpoints whose train meta records mesh [4, 2].
4. **Re-derive + resume** on SEVEN devices: ``resolve_mesh`` keeps the
   model width and drops a data replica — (3, 2) on 6 of 7 devices —
   the resumed run emits ``world_resize`` with the NEW mesh shape and
   completes.

Usage: python tests/_mesh_smoke.py <scratch-dir>
"""

import json
import os
import shutil
import subprocess
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(HERE))
WORKER = os.path.join(HERE, "_mesh_worker.py")
PHASE_TIMEOUT = 240


def run_worker(workdir, mode, devices, env_extra=None, expect_rc=0):
    os.makedirs(workdir, exist_ok=True)
    env = dict(os.environ)
    env.pop("HYDRAGNN_MESH", None)
    env.pop("XLA_FLAGS", None)
    env["MESH_SMOKE_DEVICES"] = str(devices)
    env.update(env_extra or {})
    proc = subprocess.run(
        [sys.executable, WORKER, workdir, mode],
        timeout=PHASE_TIMEOUT,
        env=env,
    )
    assert proc.returncode == expect_rc, (
        f"worker {mode} (devices={devices}) exited {proc.returncode}, "
        f"expected {expect_rc}"
    )
    result = os.path.join(workdir, "result.json")
    if expect_rc == 0:
        with open(result) as f:
            return json.load(f)
    return None


def load_events(workdir):
    from hydragnn_tpu.obs.events import validate_events

    return validate_events(
        os.path.join(workdir, "logs", "mesh-smoke", "events.jsonl")
    )


def main(scratch):
    shutil.rmtree(scratch, ignore_errors=True)
    os.makedirs(scratch)

    # ---- phase 1: every mesh shape on 8 devices ------------------------
    runs = {}
    for d, m in ((4, 2), (8, 1), (2, 4), (1, 8)):
        workdir = os.path.join(scratch, f"mesh{d}x{m}")
        r = run_worker(
            workdir, "run", devices=8,
            env_extra={"HYDRAGNN_MESH": f"{d},{m}"},
        )
        assert r["mesh"] == [d, m], r
        runs[(d, m)] = r
    # the event contract is checked on the 4x2 run (all shapes share it)
    events = load_events(os.path.join(scratch, "mesh4x2"))
    by_type = {}
    for rec in events:
        by_type.setdefault(rec["event"], rec)
    assert by_type["mesh_shape"]["shape"] == [4, 2], by_type.get("mesh_shape")
    assert by_type["mesh_shape"]["axes"] == ["data", "model"]
    ps = by_type["param_sharding"]
    assert ps["sharded"] > 0 and ps["sharded_bytes"] > 0, ps
    print(
        f"PHASE1 OK 4x2: losses={runs[(4, 2)]['epoch_losses']} "
        f"compile_sizes={runs[(4, 2)]['compile_sizes']} "
        f"sharded={ps['sharded']}/{ps['total_leaves']}"
    )

    # ---- phase 2: single-device reference, parity for EVERY shape ------
    d_ref = os.path.join(scratch, "single")
    r_ref = run_worker(d_ref, "run", devices=1)
    assert r_ref["mesh"] is None, r_ref
    b = r_ref["epoch_losses"]
    for (d, m), r in runs.items():
        a = r["epoch_losses"]
        assert len(a) == len(b) and len(a) >= 2, (a, b)
        for x, y in zip(a, b):
            assert abs(x - y) <= 5e-4 * max(abs(y), 1.0), (
                f"{d}x{m} trajectory diverged from single-device: "
                f"{a} vs {b}"
            )
    print(f"PHASE2 OK parity across {sorted(runs)}: 1x1 losses={b}")

    # ---- phase 3: kill mid-epoch-2 on 4x2 ------------------------------
    d_el = os.path.join(scratch, "elastic")
    run_worker(
        d_el, "run", devices=8,
        env_extra={
            "MESH_SMOKE_MODEL_PARALLEL": "2",
            "MESH_SMOKE_EPOCHS": "4",
            # 4 steps/epoch at batch 4 over 16 train samples: step 6 is
            # mid epoch 2 — after the epoch-1 resumable checkpoint
            "HYDRAGNN_FAULT_KILL_AT_STEP": "6",
        },
        expect_rc=113,
    )
    assert not os.path.exists(os.path.join(d_el, "result.json"))
    print("PHASE3 OK: killed at step 6 (exit 113), checkpoints on disk")

    # ---- phase 4: resume on 7 devices -> re-derived (3, 2) -------------
    r_res = run_worker(
        d_el, "resume", devices=7,
        env_extra={
            "MESH_SMOKE_MODEL_PARALLEL": "2",
            "MESH_SMOKE_EPOCHS": "4",
        },
    )
    assert r_res["mesh"] == [3, 2], r_res
    assert r_res["resumed_from_epoch"] is not None
    events = load_events(d_el)
    resizes = [e for e in events if e["event"] == "world_resize"]
    assert resizes, "no world_resize event after mesh re-derivation"
    wr = resizes[-1]
    assert wr["mesh_shape"] == [3, 2], wr
    assert wr["old_world"] == 8 and wr["new_world"] == 6, wr
    assert wr["recovery_s"] >= 0
    assert events[-1]["event"] == "run_end"
    statuses = [e["status"] for e in events if e["event"] == "run_end"]
    assert statuses[-1] == "complete", statuses
    print(
        f"PHASE4 OK re-derive: resumed at epoch "
        f"{r_res['resumed_from_epoch']} on mesh {r_res['mesh']}, "
        f"world_resize {wr['old_world']}->{wr['new_world']} "
        f"mesh_shape={wr['mesh_shape']}"
    )
    print("MESH SMOKE OK")


if __name__ == "__main__":
    main(sys.argv[1] if len(sys.argv) > 1 else "/tmp/mesh-smoke")
