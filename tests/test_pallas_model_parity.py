"""The pallas-fused PNA path must be numerically identical to the XLA path.

Flips ``HYDRAGNN_PALLAS`` and compares the full multihead forward, loss and
parameter gradients on the same batch and parameters.
"""

import os

import jax
import numpy as np

from hydragnn_tpu.graph import collate_graphs, pad_sizes_for
from hydragnn_tpu.models import create_model_config, init_model_params


def _arch():
    return {
        "model_type": "PNA",
        "input_dim": 1,
        "hidden_dim": 16,
        "output_dim": [1, 1],
        "output_type": ["graph", "node"],
        "output_heads": {
            "graph": {
                "num_sharedlayers": 1,
                "dim_sharedlayers": 8,
                "num_headlayers": 1,
                "dim_headlayers": [8],
            },
            "node": {"num_headlayers": 1, "dim_headlayers": [8], "type": "mlp"},
        },
        "task_weights": [1.0, 1.0],
        "num_conv_layers": 2,
        "num_nodes": 10,
        "edge_dim": None,
        "pna_deg": [0, 4, 8, 4],
        "equivariance": False,
    }


def _batch(seed=0):
    rng = np.random.default_rng(seed)

    class _S:
        pass

    samples = []
    for _ in range(6):
        n = int(rng.integers(4, 11))
        s = _S()
        s.x = rng.random((n, 1)).astype(np.float32)
        s.pos = rng.random((n, 3)).astype(np.float32)
        src = np.arange(n)
        dst = (src + 1) % n
        s.edge_index = np.stack(
            [np.concatenate([src, dst]), np.concatenate([dst, src])]
        ).astype(np.int64)
        s.edge_attr = None
        s.targets = [np.array([s.x.sum()], np.float32), s.x.astype(np.float32)]
        samples.append(s)
    n_pad, e_pad, g_pad = pad_sizes_for(10, 20, 6)
    return collate_graphs(
        samples, n_pad, e_pad, g_pad, head_types=("graph", "node"),
        head_dims=(1, 1),
    )


def _loss_and_grads(flag_value):
    os.environ["HYDRAGNN_PALLAS"] = flag_value
    try:
        batch = jax.tree_util.tree_map(jax.numpy.asarray, _batch())
        model = create_model_config(_arch())
        variables = init_model_params(model, batch)

        def loss_fn(params):
            outputs = model.apply(
                {**variables, "params": params}, batch, train=False
            )
            tot, _ = model.loss(outputs, batch)
            return tot

        loss, grads = jax.value_and_grad(loss_fn)(variables["params"])
        return float(loss), jax.tree_util.tree_map(np.asarray, grads)
    finally:
        os.environ.pop("HYDRAGNN_PALLAS", None)


def pytest_pna_pallas_matches_xla():
    loss_xla, grads_xla = _loss_and_grads("0")
    loss_pls, grads_pls = _loss_and_grads("1")
    assert np.isclose(loss_xla, loss_pls, rtol=1e-5), (loss_xla, loss_pls)
    flat_x, _ = jax.tree_util.tree_flatten(grads_xla)
    flat_p, _ = jax.tree_util.tree_flatten(grads_pls)
    for a, b in zip(flat_x, flat_p):
        np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-5)
