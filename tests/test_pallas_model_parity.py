"""Pallas kernel paths must be numerically identical to the XLA paths.

Three layers of parity, all on the CPU interpreter (the same kernel code
compiles on TPU):

- model-level, one-hot segment kernels: flip ``HYDRAGNN_PALLAS`` and
  compare the full multihead forward, loss and parameter gradients on the
  same batch and parameters (PNA — the stack that consumes
  ``segment_moments``);
- model-level, fused message-passing kernels (``ops/fused_mp.py``): flip
  ``HYDRAGNN_FUSED_MP`` and compare the same way for SchNet, EGNN
  (equivariant and not), PNA, GIN and SAGE;
- op-level backward: the custom VJPs of ``segment_sum_onehot`` and
  ``segment_moments`` against the reference ``jax.ops.segment_sum`` VJP,
  including padded-edge (out-of-range ids) and empty-segment cases.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np

from hydragnn_tpu.graph import collate_graphs, pad_sizes_for
from hydragnn_tpu.models import create_model_config, init_model_params
from hydragnn_tpu.ops import segment_moments, segment_sum_onehot


def _arch(model_type="PNA", equivariance=False):
    return {
        "model_type": model_type,
        "input_dim": 1,
        "hidden_dim": 16,
        "output_dim": [1, 1],
        "output_type": ["graph", "node"],
        "output_heads": {
            "graph": {
                "num_sharedlayers": 1,
                "dim_sharedlayers": 8,
                "num_headlayers": 1,
                "dim_headlayers": [8],
            },
            "node": {"num_headlayers": 1, "dim_headlayers": [8], "type": "mlp"},
        },
        "task_weights": [1.0, 1.0],
        "num_conv_layers": 2,
        "num_nodes": 10,
        "edge_dim": None,
        "pna_deg": [0, 4, 8, 4],
        "equivariance": equivariance,
        "num_gaussians": 8,
        "num_filters": 16,
        "radius": 3.0,
    }


def _batch(seed=0):
    rng = np.random.default_rng(seed)

    class _S:
        pass

    samples = []
    for _ in range(6):
        n = int(rng.integers(4, 11))
        s = _S()
        s.x = rng.random((n, 1)).astype(np.float32)
        s.pos = rng.random((n, 3)).astype(np.float32)
        src = np.arange(n)
        dst = (src + 1) % n
        s.edge_index = np.stack(
            [np.concatenate([src, dst]), np.concatenate([dst, src])]
        ).astype(np.int64)
        s.edge_attr = None
        s.targets = [np.array([s.x.sum()], np.float32), s.x.astype(np.float32)]
        samples.append(s)
    n_pad, e_pad, g_pad = pad_sizes_for(10, 20, 6)
    return collate_graphs(
        samples, n_pad, e_pad, g_pad, head_types=("graph", "node"),
        head_dims=(1, 1),
    )


def _loss_and_grads(env_name, flag_value, model_type="PNA",
                    equivariance=False):
    os.environ[env_name] = flag_value
    try:
        batch = jax.tree_util.tree_map(jax.numpy.asarray, _batch())
        model = create_model_config(_arch(model_type, equivariance))
        variables = init_model_params(model, batch)

        def loss_fn(params):
            outputs = model.apply(
                {**variables, "params": params}, batch, train=False
            )
            tot, _ = model.loss(outputs, batch)
            return tot

        loss, grads = jax.value_and_grad(loss_fn)(variables["params"])
        return float(loss), jax.tree_util.tree_map(np.asarray, grads)
    finally:
        os.environ.pop(env_name, None)


def _assert_model_parity(env_name, model_type, equivariance=False):
    loss_xla, grads_xla = _loss_and_grads(env_name, "0", model_type,
                                          equivariance)
    loss_pls, grads_pls = _loss_and_grads(env_name, "1", model_type,
                                          equivariance)
    assert np.isclose(loss_xla, loss_pls, rtol=1e-5), (loss_xla, loss_pls)
    flat_x, _ = jax.tree_util.tree_flatten(grads_xla)
    flat_p, _ = jax.tree_util.tree_flatten(grads_pls)
    for a, b in zip(flat_x, flat_p):
        np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-5)


def pytest_pna_pallas_matches_xla():
    _assert_model_parity("HYDRAGNN_PALLAS", "PNA")


# ---- fused message-passing kernels (ops/fused_mp.py) ---------------------
# the acceptance bar: forward AND gradient parity on the CPU interpreter
# for the stacks wired through the fused ops


def pytest_fused_mp_gin_matches_xla():
    _assert_model_parity("HYDRAGNN_FUSED_MP", "GIN")


def pytest_fused_mp_sage_matches_xla():
    _assert_model_parity("HYDRAGNN_FUSED_MP", "SAGE")


def pytest_fused_mp_schnet_matches_xla():
    _assert_model_parity("HYDRAGNN_FUSED_MP", "SchNet")


def pytest_fused_mp_pna_matches_xla():
    _assert_model_parity("HYDRAGNN_FUSED_MP", "PNA")


def pytest_fused_mp_egnn_matches_xla():
    _assert_model_parity("HYDRAGNN_FUSED_MP", "EGNN")


def pytest_fused_mp_egnn_equivariant_matches_xla():
    # the deepest fused op: radial + 2-layer edge MLP + tanh-bounded coord
    # update + packed sender reduction in one kernel
    _assert_model_parity("HYDRAGNN_FUSED_MP", "EGNN", equivariance=True)


# ---- op-level backward parity: pallas custom VJPs vs the reference
# jax.ops.segment_sum VJP, padded-edge and empty-segment cases included


def _grad_case(e=120, n=32, d=8, seed=0, pad_tail=0, empty_from=None):
    """Data + ids with optional out-of-range padded-edge tail (the kernels'
    padding contract: ids past num_segments contribute nothing) and an
    optional empty-segment band [empty_from, n)."""
    rng = np.random.default_rng(seed)
    data = jnp.asarray(rng.standard_normal((e, d)), jnp.float32)
    hi = n if empty_from is None else empty_from
    ids = rng.integers(0, hi, e)
    if pad_tail:
        ids[-pad_tail:] = np.iinfo(np.int32).max  # padded edges
    return data, jnp.asarray(ids, jnp.int32), n


def _sum_losses(ids, n):
    def ours(x):
        return jnp.sum(segment_sum_onehot(x, ids, n, True) ** 2)

    def ref(x):
        return jnp.sum(
            jax.ops.segment_sum(x, ids, num_segments=n) ** 2
        )

    return ours, ref


def pytest_segment_sum_backward_matches_reference_vjp():
    data, ids, n = _grad_case()
    ours, ref = _sum_losses(ids, n)
    np.testing.assert_allclose(
        jax.grad(ours)(data), jax.grad(ref)(data), rtol=1e-5, atol=1e-6
    )


def pytest_segment_sum_backward_padded_edges():
    # out-of-range padded ids: the reference segment_sum DROPS them
    # (mode-clip semantics differ), so compare against the masked
    # reference — padded rows must receive exactly zero gradient
    data, ids, n = _grad_case(e=100, n=24, d=6, pad_tail=17)
    real = ids < n

    def ours(x):
        return jnp.sum(segment_sum_onehot(x, ids, n, True) ** 2)

    def ref(x):
        xm = jnp.where(real[:, None], x, 0.0)
        safe = jnp.where(real, ids, n)  # route pads to the dropped bin
        return jnp.sum(
            jax.ops.segment_sum(xm, safe, num_segments=n + 1)[:n] ** 2
        )

    g_ours = np.asarray(jax.grad(ours)(data))
    g_ref = np.asarray(jax.grad(ref)(data))
    np.testing.assert_allclose(g_ours, g_ref, rtol=1e-5, atol=1e-6)
    assert np.all(g_ours[-17:] == 0.0), "padded edges must get zero grad"


def pytest_segment_sum_backward_empty_segments():
    data, ids, n = _grad_case(e=80, n=32, d=5, empty_from=20)
    ours, ref = _sum_losses(ids, n)
    fwd = segment_sum_onehot(data, ids, n, True)
    assert np.allclose(np.asarray(fwd[20:]), 0.0)
    np.testing.assert_allclose(
        jax.grad(ours)(data), jax.grad(ref)(data), rtol=1e-5, atol=1e-6
    )


def _moments_losses(ids, n):
    def ours(x):
        s, c, sq = segment_moments(x, ids, n, True)
        mean = s / jnp.maximum(c, 1.0)
        var = jax.nn.relu(sq / jnp.maximum(c, 1.0) - mean**2)
        return jnp.sum(mean**2) + jnp.sum(jnp.sqrt(var + 1e-5))

    def ref(x):
        s = jax.ops.segment_sum(x, ids, num_segments=n)
        c = jax.ops.segment_sum(
            jnp.ones(x.shape[0]), ids, num_segments=n
        ).reshape(-1, 1)
        sq = jax.ops.segment_sum(x * x, ids, num_segments=n)
        mean = s / jnp.maximum(c, 1.0)
        var = jax.nn.relu(sq / jnp.maximum(c, 1.0) - mean**2)
        return jnp.sum(mean**2) + jnp.sum(jnp.sqrt(var + 1e-5))

    return ours, ref


def pytest_segment_moments_backward_matches_reference_vjp():
    data, ids, n = _grad_case(e=96, n=24, d=8, seed=4)
    ours, ref = _moments_losses(ids, n)
    np.testing.assert_allclose(
        jax.grad(ours)(data), jax.grad(ref)(data), rtol=1e-4, atol=1e-5
    )


def pytest_segment_moments_backward_padded_and_empty():
    # padded-edge tail AND an empty-segment band in one case: pads get
    # zero gradient, empty segments produce the reduction identity and a
    # finite gradient (the sqrt(var+eps) epsilon keeps d/dx finite)
    data, ids, n = _grad_case(e=90, n=30, d=4, seed=5, pad_tail=13,
                              empty_from=18)
    real = np.asarray(ids) < n

    def ours(x):
        s, c, sq = segment_moments(x, ids, n, True)
        mean = s / jnp.maximum(c, 1.0)
        var = jax.nn.relu(sq / jnp.maximum(c, 1.0) - mean**2)
        return jnp.sum(mean**2) + jnp.sum(jnp.sqrt(var + 1e-5))

    g = np.asarray(jax.grad(ours)(data))
    assert np.isfinite(g).all()
    assert np.all(g[~real] == 0.0), "padded edges must get zero grad"
    s, c, sq = segment_moments(data, ids, n, True)
    assert np.allclose(np.asarray(s[18:]), 0.0)
    assert np.allclose(np.asarray(c[18:]), 0.0)
