"""Goodput & MFU ledger (obs/ledger.py) + fleet rollup tests.

Covers the PR's acceptance bar directly:

- on a CPU run with a known compiled-cost program,
  ``hydragnn_train_mfu{bucket=}`` equals the hand-computed
  ``flops_per_step x steps/sec / peak`` to 1e-6;
- goodput category fractions sum to 1.0 +- 1e-6 per epoch;
- the fleet rollup merges multiple hosts' streams, prices world_resize
  recovery as lost goodput, and flags the slow host as a straggler.
"""

import json
import os
import sys
import warnings

import numpy as np
import pytest

os.environ.setdefault("JAX_PLATFORMS", "cpu")

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from _resilience_worker import make_samples  # noqa: E402

from hydragnn_tpu.obs import ledger as led  # noqa: E402
from hydragnn_tpu.obs import runtime as obs_rt  # noqa: E402
from hydragnn_tpu.obs.events import validate_events  # noqa: E402


# ---- peak-FLOPs resolution -----------------------------------------------


def pytest_resolve_peak_flops_env_table_and_warn_once(monkeypatch):
    # env override beats everything (and is the only CPU-side source)
    monkeypatch.setenv("HYDRAGNN_PEAK_FLOPS", "1.5e12")
    assert led.resolve_peak_flops("anything") == 1.5e12
    monkeypatch.delenv("HYDRAGNN_PEAK_FLOPS")

    # table lookup is precision-aware
    assert led.resolve_peak_flops("TPU v4", mixed=True) == 275e12
    assert led.resolve_peak_flops("TPU v4", mixed=False) == 137.5e12
    # default precision follows note_precision
    led.note_precision(True, source="test")
    try:
        assert led.resolve_peak_flops("TPU v5") == 459e12
    finally:
        led.note_precision(False, source="test")
    assert led.resolve_peak_flops("TPU v5") == 229.5e12

    # unknown kinds warn exactly once per kind and return None
    monkeypatch.setattr(led, "_peak_warned", set())
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        assert led.resolve_peak_flops("weird-chip-9000") is None
        assert led.resolve_peak_flops("weird-chip-9000") is None
    hits = [c for c in caught if "weird-chip-9000" in str(c.message)]
    assert len(hits) == 1


# ---- ledger unit behavior ------------------------------------------------


class _Clock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t


def _collecting_ledger(clock, compile_seconds=lambda: 0.0):
    events = []

    def emit(event, **fields):
        events.append({"event": event, **fields})

    return led.GoodputLedger(
        emit=emit, compile_seconds=compile_seconds, clock=clock
    ), events


def pytest_ledger_fractions_sum_to_one_and_attribute():
    clock = _Clock()
    compile_box = {"s": 0.0}
    ledger, events = _collecting_ledger(clock, lambda: compile_box["s"])

    ledger.epoch_begin(0)
    # 4 steps of 0.5s, the first containing 0.3s of backend compile
    compile_box["s"] += 0.3
    ledger.on_step(0.5, 1, compile_s=0.3)
    for _ in range(3):
        ledger.on_step(0.5, 1)
    ledger.data_wait(0.4)
    ledger.checkpoint_cost(0.2)
    ledger.guard_cost(0.1)
    clock.t += 10.0  # the epoch took 10s of wall
    ledger.epoch_begin(1)  # closes window 0

    assert len(events) == 1 and events[0]["event"] == "goodput"
    g = events[0]
    assert g["epoch"] == 0
    assert abs(g["wall_s"] - 10.0) < 1e-6
    s = g["seconds"]
    # compute = step dispatch minus in-step compile
    assert abs(s["compute"] - (2.0 - 0.3)) < 1e-6
    assert abs(s["compile"] - 0.3) < 1e-6
    assert abs(s["data_stall"] - 0.4) < 1e-6
    assert abs(s["checkpoint"] - 0.2) < 1e-6
    assert abs(s["guard_recovery"] - 0.1) < 1e-6
    # other is the residual to the 10s wall
    assert abs(s["other"] - (10.0 - 2.7)) < 1e-6
    assert abs(sum(g["fractions"].values()) - 1.0) < 1e-6
    assert g["goodput_fraction"] == g["fractions"]["compute"]

    # a window whose components EXCEED wall (async overlap) still sums
    # to exactly 1 with other == 0
    ledger.on_step(5.0, 1)
    ledger.checkpoint_cost(5.0)
    clock.t += 1.0  # wall (1s) < known (10s)
    ledger.finalize()
    g1 = events[-1]
    assert g1["epoch"] == 1
    assert g1["seconds"]["other"] == 0.0
    assert abs(sum(g1["fractions"].values()) - 1.0) < 1e-6


def pytest_ledger_staged_compute_excludes_only_train_compile():
    """Eval-span compile is already kept out of the eval category; the
    staged-path compute deduction must not subtract it from the train
    wall a second time."""
    clock = _Clock()
    box = {"s": 0.0}
    ledger, events = _collecting_ledger(clock, lambda: box["s"])
    ledger.epoch_begin(0)
    box["s"] += 2.0  # train-side compile inside the staged dispatch
    ledger.note_train_wall(10.0)
    ledger.eval_begin()
    box["s"] += 3.0  # eval programs compiling inside the eval span
    ledger.eval_end()
    clock.t += 15.0
    ledger.finalize()
    g = events[-1]
    assert abs(g["seconds"]["compile"] - 5.0) < 1e-6
    # compute = train wall minus the TRAIN-side compile only: 10 - 2
    assert abs(g["seconds"]["compute"] - 8.0) < 1e-6
    assert abs(sum(g["fractions"].values()) - 1.0) < 1e-6


def pytest_ledger_whole_dispatch_epochs_use_train_wall():
    """Staged/fit epochs have no per-step hook: the driver's measured
    train wall is the compute signal."""
    clock = _Clock()
    ledger, events = _collecting_ledger(clock)
    ledger.epoch_begin(0)
    ledger.note_train_wall(3.0)
    clock.t += 4.0
    ledger.finalize()
    g = events[-1]
    assert abs(g["seconds"]["compute"] - 3.0) < 1e-6
    assert abs(g["seconds"]["other"] - 1.0) < 1e-6
    assert abs(sum(g["fractions"].values()) - 1.0) < 1e-6


def pytest_ledger_mfu_hand_computation(monkeypatch):
    monkeypatch.setenv("HYDRAGNN_PEAK_FLOPS", "2e9")
    clock = _Clock()
    ledger, events = _collecting_ledger(clock)
    ledger.note_program(
        {"name": "train_step", "bucket": "train_step/abc",
         "cost": {"flops": 1e6}}
    )
    # eval buckets never get an MFU
    ledger.note_program(
        {"name": "eval_step", "bucket": "eval_step/def",
         "cost": {"flops": 5e5}}
    )
    ledger.epoch_begin(0)
    for _ in range(10):
        ledger.on_step(0.01, 1)
    clock.t += 1.0
    ledger.finalize()
    g = events[-1]
    assert set(g["mfu"]) == {"train_step/abc"}
    m = g["mfu"]["train_step/abc"]
    # 10 steps over 0.1s of step time = 100 steps/s
    assert abs(m["steps_per_sec"] - 100.0) < 1e-6
    expected = 1e6 * m["steps_per_sec"] / 2e9
    assert abs(m["mfu"] - expected) < 1e-6
    assert m["peak_flops"] == 2e9


# ---- the CPU acceptance e2e ----------------------------------------------


def _build_tiny_training(num_epoch):
    from hydragnn_tpu.data.loaders import GraphLoader, compute_layout
    from hydragnn_tpu.models.create import create_model_config
    from hydragnn_tpu.train.trainer import Trainer

    arch = {
        "model_type": "GIN",
        "input_dim": 1,
        "hidden_dim": 8,
        "num_conv_layers": 2,
        "output_dim": [1, 1],
        "output_type": ["graph", "node"],
        "output_heads": {
            "graph": {
                "num_sharedlayers": 1,
                "dim_sharedlayers": 8,
                "num_headlayers": 1,
                "dim_headlayers": [8],
            },
            "node": {"num_headlayers": 1, "dim_headlayers": [8],
                     "type": "mlp"},
        },
        "task_weights": [1.0, 1.0],
    }
    training = {
        "num_epoch": num_epoch,
        "Optimizer": {"type": "AdamW", "learning_rate": 1e-2},
        "resume_every": 1,
    }
    samples = make_samples()
    layout = compute_layout([samples], batch_size=4)
    loaders = (
        GraphLoader(samples[:16], 4, layout, shuffle=True, seed=7),
        GraphLoader(samples[16:20], 4, layout, shuffle=False),
        GraphLoader(samples[20:], 4, layout, shuffle=False),
    )
    model = create_model_config(arch)
    trainer = Trainer(model, training)
    state = trainer.init_state(next(iter(loaders[0])), seed=0)
    return trainer, state, loaders, training


def pytest_goodput_mfu_acceptance_e2e(tmp_path, monkeypatch):
    """The PR's acceptance bar: a real CPU training with a configured
    peak — per-epoch goodput fractions sum to 1 +- 1e-6, and the MFU
    equals flops x steps/sec / peak to 1e-6, hand-recomputed from the
    event's own inputs AND cross-checked against the flops gauge."""
    from hydragnn_tpu.train.epoch_driver import train_validate_test

    monkeypatch.chdir(tmp_path)
    peak = 1e9
    monkeypatch.setenv("HYDRAGNN_PEAK_FLOPS", str(peak))
    num_epoch = 2
    trainer, state, loaders, training = _build_tiny_training(num_epoch)

    telem = obs_rt.activate(
        obs_rt.RunTelemetry(
            "goodput-e2e", str(tmp_path / "logs" / "goodput-e2e"),
            port=None,
        )
    )
    try:
        telem.emit_manifest(
            {"NeuralNetwork": {"Training": training}}, "goodput-e2e"
        )
        config_nn = {
            "Training": training,
            "Variables_of_interest": {"output_names": ["sum", "x"]},
        }
        train_validate_test(
            trainer, state, *loaders, config_nn, "goodput-e2e",
            verbosity=0,
        )
    finally:
        obs_rt.deactivate()
    # snapshot AFTER close: the final epoch's window publishes during
    # deactivate, and the gauges must mirror that last window
    snap = telem.metrics.snapshot()

    recs = validate_events(
        str(tmp_path / "logs" / "goodput-e2e" / "events.jsonl"),
        require=["goodput", "compile", "epoch", "run_end"],
    )
    goodput = [r for r in recs if r["event"] == "goodput"]
    assert [g["epoch"] for g in goodput] == list(range(num_epoch))
    for g in goodput:
        assert abs(sum(g["fractions"].values()) - 1.0) < 1e-6, g
        assert set(g["seconds"]) == set(led.CATEGORIES)
        assert g["wall_s"] > 0
        assert 0.0 <= g["goodput_fraction"] <= 1.0
    # the epoch after warmup has real compute attribution
    assert goodput[-1]["seconds"]["compute"] > 0
    assert goodput[-1]["steps"] == 4  # 16 samples / batch 4

    # MFU: hand-recompute from the event's own inputs, against the
    # configured peak, and against the introspection flops gauge
    mfu_events = [g for g in goodput if g.get("mfu")]
    assert mfu_events, "no MFU recorded despite HYDRAGNN_PEAK_FLOPS"
    flops_gauge = snap["flops_per_step"]
    mfu_gauge = snap["mfu"]
    for g in mfu_events:
        for bucket, m in g["mfu"].items():
            assert bucket.startswith(("train_step/", "train_multi/"))
            expected = m["flops"] * m["steps_per_sec"] / peak
            assert abs(m["mfu"] - expected) <= 1e-6 * max(expected, 1.0)
            assert m["peak_flops"] == peak
            assert flops_gauge[f"bucket={bucket}"] == m["flops"]
    # the live gauge carries the LAST window's value
    last = mfu_events[-1]
    for bucket, m in last["mfu"].items():
        assert abs(mfu_gauge[f"bucket={bucket}"] - m["mfu"]) < 1e-9
    # goodput fraction gauges mirror the last window too
    frac_gauge = snap["goodput_fraction"]
    for cat, frac in goodput[-1]["fractions"].items():
        assert abs(frac_gauge[f"category={cat}"] - frac) < 1e-9


# ---- straggler flagging ---------------------------------------------------


def pytest_flag_stragglers_leave_one_out():
    hosts = {
        "0": {"p50": 0.30, "count": 30},
        "1": {"p50": 0.001, "count": 30},
    }
    assert led.flag_stragglers(hosts, factor=2.0) == ["0"]
    # symmetric fleet: nobody flags
    even = {str(i): {"p50": 0.01, "count": 30} for i in range(4)}
    assert led.flag_stragglers(even, factor=2.0) == []
    # under-sampled hosts neither flag nor pollute the baseline
    hosts["2"] = {"p50": 9.9, "count": 1}
    assert led.flag_stragglers(hosts, factor=2.0, min_steps=3) == ["0"]
    # a single qualified host can never be judged
    assert led.flag_stragglers(
        {"0": {"p50": 1.0, "count": 30}}, factor=2.0
    ) == []


# ---- fleet rollup ---------------------------------------------------------


def _write_events(path, records):
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w") as f:
        for i, rec in enumerate(records):
            rec = dict(rec)
            rec.setdefault("seq", i)
            f.write(json.dumps(rec) + "\n")


def _fleet_fixture(root):
    run = os.path.join(root, "logs", "run")
    _write_events(
        os.path.join(run, "events.jsonl"),
        [
            {"event": "run_manifest", "ts": 100.0, "host": 0,
             "schema_version": 1, "run": "run", "config_hash": "c",
             "git_rev": "g", "world_size": 2, "device_kind": "cpu",
             "device_count": 1, "num_epoch": 4},
            {"event": "goodput", "ts": 110.0, "epoch": 0, "wall_s": 10.0,
             "seconds": {}, "fractions": {}, "goodput_fraction": 0.5,
             "steps": 4, "step_s": 1.2},
            {"event": "world_resize", "ts": 120.0, "old_world": 2,
             "new_world": 1, "gen": 1, "recovery_s": 2.5},
            {"event": "run_end", "ts": 150.0, "status": "complete"},
        ],
    )
    _write_events(
        os.path.join(run, "events-host1.jsonl"),
        [
            {"event": "run_manifest", "ts": 101.0, "host": 1,
             "schema_version": 1, "run": "run", "config_hash": "c",
             "git_rev": "g", "world_size": 2, "device_kind": "cpu",
             "device_count": 1, "num_epoch": 4},
            {"event": "stall", "ts": 105.0, "step": 7, "seconds": 2.0,
             "median": 0.1, "factor": 8.0},
        ],
    )
    workers = os.path.join(root, "elastic-coord", "workers")
    os.makedirs(workers, exist_ok=True)
    with open(os.path.join(workers, "host-0.json"), "w") as f:
        json.dump(
            {"host": 0, "ts": 149.0, "step": 30, "epoch": 3, "done": True,
             "step_digest": {"count": 30, "sum": 9.0, "p50": 0.30,
                             "p99": 0.32}},
            f,
        )
    with open(os.path.join(workers, "host-1.json"), "w") as f:
        json.dump(
            {"host": 1, "ts": 119.0, "step": 12, "epoch": 1,
             "step_digest": {"count": 12, "sum": 0.012, "p50": 0.001,
                             "p99": 0.002}},
            f,
        )
    return run


def pytest_fleet_rollup_merges_prices_and_flags(tmp_path):
    _fleet_fixture(str(tmp_path))
    report = led.build_fleet_report(str(tmp_path), straggler_factor=2.0)
    # both hosts' streams merged into one ts-ordered view
    assert set(report["streams"]) == {"events.jsonl", "events-host1.jsonl"}
    assert report["events"] == 6
    ts_order = [i["t"] for i in report["timeline"]]
    assert ts_order == sorted(ts_order)
    hosts_in_timeline = {i["host"] for i in report["timeline"]}
    assert {"0", "1"} <= hosts_in_timeline
    # heartbeat digests drive the per-host distributions
    assert report["hosts"]["0"]["p50"] == 0.30
    assert report["hosts"]["1"]["p50"] == 0.001
    assert report["hosts"]["0"]["source"] == "heartbeat"
    # the slow host is flagged
    assert report["stragglers"] == ["0"]
    # the world_resize recovery window is priced as lost goodput
    assert report["lost_goodput_s"] == 2.5
    assert report["lost_goodput_host_s"] == 2.5  # new_world == 1
    assert 0 < report["lost_goodput_fraction"] <= 1.0
    assert report["mean_goodput_fraction"] == 0.5
    # all three renderers produce output mentioning the straggler
    for fmt, render in led.FLEET_RENDERERS.items():
        out = render(report)
        assert "0" in out and out.endswith("\n"), fmt
    assert "STRAGGLER" in led.render_fleet_text(report)


def pytest_fleet_cli(tmp_path, capsys):
    from hydragnn_tpu.obs.__main__ import main

    _fleet_fixture(str(tmp_path))
    assert main(["fleet", str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert "STRAGGLER" in out and "fleet rollup" in out
    # json format parses
    assert main(["fleet", str(tmp_path), "--format", "json"]) == 0
    assert json.loads(capsys.readouterr().out)["stragglers"] == ["0"]
    # empty dir: usage error, not a crash
    empty = tmp_path / "empty"
    empty.mkdir()
    assert main(["fleet", str(empty)]) == 2
    assert main(["fleet", str(tmp_path / "missing")]) == 2


def _live_lease(workers, host, p50, count=30, ts=None, done=False):
    import time as _time

    os.makedirs(workers, exist_ok=True)
    with open(os.path.join(workers, f"host-{host}.json"), "w") as f:
        json.dump(
            {"host": host, "ts": _time.time() if ts is None else ts,
             "done": done,
             "step_digest": {"count": count, "sum": p50 * count,
                             "p50": p50, "p99": p50 * 1.1}},
            f,
        )


def pytest_poll_fleet_gauges_scrape_time(tmp_path):
    """The leader's /metrics scrape reads peer lease digests into the
    fleet gauges (obs/ledger.poll_fleet_gauges via extra_polls) — LIVE
    hosts only: done/stale/tombstoned leases drop out of the view."""
    from hydragnn_tpu.obs.runtime import TrainingMetrics

    coord = str(tmp_path / "coord")
    workers = os.path.join(coord, "workers")
    _live_lease(workers, 0, 0.3)
    _live_lease(workers, 1, 0.001)
    m = TrainingMetrics()
    m.extra_polls.append(
        lambda: led.poll_fleet_gauges(coord, m.registry)
    )
    text = m.render_prometheus()
    assert 'hydragnn_train_fleet_step_p50_seconds{host="0"} 0.3' in text
    assert 'hydragnn_train_fleet_step_p50_seconds{host="1"} 0.001' in text
    assert "hydragnn_train_fleet_straggler_hosts 1.0" in text

    # the straggler finishes cleanly (done=True): it must leave the live
    # view — both its p50 series and the straggler count
    _live_lease(workers, 0, 0.3, done=True)
    text = m.render_prometheus()
    assert 'fleet_step_p50_seconds{host="0"}' not in text
    assert "hydragnn_train_fleet_straggler_hosts 0.0" in text

    # ... same for a stale lease (the host died without a goodbye)
    _live_lease(workers, 0, 0.3, ts=100.0)
    assert 'host="0"' not in m.render_prometheus()
    # ... and for a tombstoned host
    _live_lease(workers, 0, 0.3)
    os.makedirs(os.path.join(coord, "dead"), exist_ok=True)
    with open(os.path.join(coord, "dead", "host-0.json"), "w") as f:
        json.dump({"host": 0, "ts": 1.0, "reason": "x", "by": 1}, f)
    assert 'host="0"' not in m.render_prometheus()

    # a missing coordination dir must not break the scrape
    m2 = TrainingMetrics()
    m2.extra_polls.append(
        lambda: led.poll_fleet_gauges(str(tmp_path / "gone"), m2.registry)
    )
    assert (
        "hydragnn_train_fleet_straggler_hosts 0" in m2.render_prometheus()
    )


def pytest_collective_estimate_opt_in(monkeypatch):
    """The collective category is 0 without HYDRAGNN_ICI_BYTES_PER_S and
    a labeled bandwidth-model estimate with it."""
    clock = _Clock()
    ledger, events = _collecting_ledger(clock)
    ledger.note_program(
        {"name": "train_step", "bucket": "train_step/aa",
         "cost": {"flops": 1.0},
         "collectives": {"data": 1e6, "model": 1e6}}
    )
    ledger.epoch_begin(0)
    for _ in range(10):
        ledger.on_step(0.1, 1)
    clock.t += 2.0
    ledger.epoch_begin(1)
    g = events[-1]
    assert g["seconds"]["collective"] == 0.0
    assert "collective_estimated" not in g

    monkeypatch.setenv("HYDRAGNN_ICI_BYTES_PER_S", "1e8")
    for _ in range(10):
        ledger.on_step(0.1, 1)
    clock.t += 2.0
    ledger.finalize()
    g = events[-1]
    # 10 steps x 2e6 bytes / 1e8 B/s = 0.2s, carved out of compute
    assert abs(g["seconds"]["collective"] - 0.2) < 1e-6
    assert abs(g["seconds"]["compute"] - 0.8) < 1e-6
    assert g["collective_estimated"] is True
    assert abs(sum(g["fractions"].values()) - 1.0) < 1e-6


# ---- events-without-leases fallback ---------------------------------------


def pytest_fleet_falls_back_to_goodput_events(tmp_path):
    run = os.path.join(str(tmp_path), "logs", "run")
    _write_events(
        os.path.join(run, "events-host0.jsonl"),
        [{"event": "goodput", "ts": 10.0, "epoch": 0, "wall_s": 5.0,
          "seconds": {}, "fractions": {}, "goodput_fraction": 0.9,
          "steps": 10, "step_s": 3.0}],
    )
    _write_events(
        os.path.join(run, "events-host1.jsonl"),
        [{"event": "goodput", "ts": 10.0, "epoch": 0, "wall_s": 5.0,
          "seconds": {}, "fractions": {}, "goodput_fraction": 0.9,
          "steps": 10, "step_s": 0.1}],
    )
    report = led.build_fleet_report(str(tmp_path), straggler_factor=2.0)
    assert report["hosts"]["0"]["source"] == "events"
    assert report["hosts"]["0"]["p50"] == pytest.approx(0.3)
    assert report["stragglers"] == ["0"]


# ---- budget MFU floor -----------------------------------------------------


def pytest_budget_mfu_floor_roundtrip_and_direction():
    from hydragnn_tpu.obs import report as report_mod

    report = {
        "programs": {
            "train_step/aa": {"flops": 100.0, "mfu": 0.08},
            "eval_step/bb": {"flops": 50.0},
        }
    }
    budget = report_mod.budget_from_report(report, tolerance=0.1)
    assert budget["programs"]["train_step/aa"]["mfu_floor"] == 0.08
    assert "mfu_floor" not in budget["programs"]["eval_step/bb"]

    # at/above floor: clean
    v, _, _ = report_mod.check_budget(report, budget)
    assert v == []
    # regression below floor x (1 - tol): violation
    worse = {
        "programs": {
            "train_step/aa": {"flops": 100.0, "mfu": 0.05},
            "eval_step/bb": {"flops": 50.0},
        }
    }
    v, _, _ = report_mod.check_budget(worse, budget)
    assert [x["metric"] for x in v] == ["mfu_floor"]
    assert v[0]["current"] == 0.05
    # a run that measured no MFU is NOT a violation (the CLI notes it)
    unmeasured = {
        "programs": {
            "train_step/aa": {"flops": 100.0},
            "eval_step/bb": {"flops": 50.0},
        }
    }
    v, _, _ = report_mod.check_budget(unmeasured, budget)
    assert v == []
    # the upper-bound metrics still ratchet the usual direction
    heavier = {
        "programs": {
            "train_step/aa": {"flops": 200.0, "mfu": 0.08},
            "eval_step/bb": {"flops": 50.0},
        }
    }
    v, _, _ = report_mod.check_budget(heavier, budget)
    assert [x["metric"] for x in v] == ["flops"]


# ---- report: mesh header, collectives, goodput sections -------------------


def pytest_report_carries_mesh_collectives_goodput(tmp_path):
    from hydragnn_tpu.obs import report as report_mod

    path = str(tmp_path / "events.jsonl")
    _write_events(
        path,
        [
            {"event": "run_manifest", "ts": 1.0, "schema_version": 1,
             "run": "r", "config_hash": "c", "git_rev": "g",
             "world_size": 1, "device_kind": "cpu", "device_count": 8,
             "num_epoch": 1},
            {"event": "mesh_shape", "ts": 1.5, "axes": ["data", "model"],
             "shape": [4, 2], "devices": 8},
            {"event": "compile", "ts": 2.0, "name": "train_step",
             "bucket": "train_step/aa", "cost": {"flops": 1000.0},
             "memory": {"peak_bytes": 64.0},
             "collectives": {"data": 512.0, "model": 128.0}},
            {"event": "compile", "ts": 2.5, "name": "eval_step",
             "bucket": "eval_step/bb", "cost": {"flops": 10.0},
             "memory": {}, "collectives": {"data": 256.0}},
            # a resumed run RE-REPORTS the same bucket: the per-axis
            # rollup must dedup (last capture wins), not double-count
            {"event": "compile", "ts": 2.7, "name": "train_step",
             "bucket": "train_step/aa", "cost": {"flops": 1000.0},
             "memory": {"peak_bytes": 64.0},
             "collectives": {"data": 512.0, "model": 128.0}},
            {"event": "goodput", "ts": 3.0, "epoch": 0, "wall_s": 2.0,
             "seconds": {"compute": 1.0, "other": 1.0},
             "fractions": {"compute": 0.5, "other": 0.5},
             "goodput_fraction": 0.5, "steps": 4, "step_s": 1.0,
             "mfu": {"train_step/aa": {"mfu": 0.07, "flops": 1000.0,
                                       "steps_per_sec": 4.0,
                                       "peak_flops": 1e5}}},
            {"event": "run_end", "ts": 4.0, "status": "complete"},
        ],
    )
    report = report_mod.build_report(report_mod.load_events(path))
    assert report["run"]["mesh_shape"] == [4, 2]
    assert report["collectives"] == {"data": 768.0, "model": 128.0}
    assert report["programs"]["train_step/aa"]["mfu"] == 0.07
    assert "mfu" not in report["programs"]["eval_step/bb"]
    assert report["goodput"][0]["goodput_fraction"] == 0.5

    text = report_mod.render_text(report)
    assert "mesh: 4x2 (data, model)" in text
    assert "collective bytes" in text
    assert "goodput" in text
    assert "7.00%" in text  # the program table's mfu column
    md = report_mod.render_markdown(report)
    assert "## Collective bytes (per mesh axis)" in md
    assert "## Goodput" in md
    json.loads(report_mod.render_json(report))


# ---- serve SLO accounting -------------------------------------------------


def pytest_serve_metrics_deadline_outcomes():
    from hydragnn_tpu.obs.metrics import ServeMetrics

    m = ServeMetrics()
    m.on_deadline(True)
    m.on_deadline(True)
    m.on_deadline(False)
    m.on_timeout(2)  # queue expiries are missed deadlines too
    s = m.snapshot()
    assert s["deadline_met_total"] == 2
    assert s["deadline_missed_total"] == 3
    assert s["slo_miss_ratio"] == 0.6
    text = m.render_prometheus()
    assert "hydragnn_serve_slo_misses_total 3" in text
    assert 'hydragnn_serve_deadline_outcomes_total{outcome="met"} 2' in text
    assert (
        'hydragnn_serve_deadline_outcomes_total{outcome="missed"} 3' in text
    )
    assert "hydragnn_serve_slo_miss_ratio 0.6" in text
    # no deadlines at all: ratio is 0, not a division error
    assert ServeMetrics().snapshot()["slo_miss_ratio"] == 0.0
