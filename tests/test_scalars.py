"""Direct ScalarWriter fan-out coverage (obs/scalars.py).

The fan-out was previously exercised mostly incidentally through the
observability e2e; these tests pin its contracts on their own: JSONL/CSV
backends record the SAME rows for the same calls, backend failures are
isolated (one broken backend must not eat the others' scalars or the
run), tracer totals forward through ``add_regions``, ``for_run`` honors
the format knob and the rank-0-only contract, and the missing-TensorBoard
warning fires exactly once per process.
"""

import csv
import json
import os
import sys
import warnings

os.environ.setdefault("JAX_PLATFORMS", "cpu")

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from hydragnn_tpu.obs.scalars import (  # noqa: E402
    CsvScalarBackend,
    JsonlScalarBackend,
    ScalarWriter,
)


def _drive(writer):
    writer.add_scalar("train error", 0.5, 0)
    writer.add_scalar("train error", 0.25, 1)
    writer.add_scalar("validate error", 0.75, 1)
    writer.add_regions({"train": 2.0, "dataload": 0.5}, step=2)
    writer.close()


def _jsonl_rows(path):
    return [
        (r["tag"], r["value"], r["step"])
        for r in (json.loads(line) for line in open(path))
    ]


def _csv_rows(path):
    with open(path, newline="") as f:
        rows = list(csv.DictReader(f))
    return [(r["tag"], float(r["value"]), int(r["step"])) for r in rows]


def pytest_jsonl_and_csv_backends_record_identical_rows(tmp_path):
    """Row PARITY: the two plain-file backends are interchangeable — the
    same call sequence produces the same (tag, value, step) rows."""
    jpath = str(tmp_path / "scalars.jsonl")
    cpath = str(tmp_path / "scalars.csv")
    _drive(ScalarWriter([JsonlScalarBackend(jpath)]))
    _drive(ScalarWriter([CsvScalarBackend(cpath)]))
    jrows, crows = _jsonl_rows(jpath), _csv_rows(cpath)
    assert jrows == crows
    assert ("tracer/train_seconds", 2.0, 2) in jrows
    assert ("tracer/dataload_seconds", 0.5, 2) in jrows
    # regions render in sorted name order (deterministic output)
    tracer_rows = [t for t, _, _ in jrows if t.startswith("tracer/")]
    assert tracer_rows == sorted(tracer_rows)


def pytest_fanout_writes_every_backend_and_isolates_failures(tmp_path):
    jpath = str(tmp_path / "a.jsonl")
    cpath = str(tmp_path / "b.csv")

    class _Exploding:
        def add_scalar(self, tag, value, step):
            raise RuntimeError("backend down")

        def close(self):
            raise RuntimeError("close down")

    w = ScalarWriter(
        [JsonlScalarBackend(jpath), _Exploding(), CsvScalarBackend(cpath)]
    )
    w.add_scalar("loss", 1.5, 0)
    w.close()  # the exploding close must not skip the CSV close
    assert _jsonl_rows(jpath) == [("loss", 1.5, 0)]
    assert _csv_rows(cpath) == [("loss", 1.5, 0)]


def pytest_for_run_honors_format_knob_and_rank(tmp_path, monkeypatch):
    from hydragnn_tpu.obs import scalars as sc
    from hydragnn_tpu.parallel import distributed as dist

    # break TensorBoard so the file backend is the only one (and silence
    # the warn-once for this test)
    monkeypatch.setattr(sc, "_tb_warned", True)
    monkeypatch.setattr(
        sc.TensorBoardScalarBackend,
        "__init__",
        lambda self, log_dir: (_ for _ in ()).throw(ImportError("no tb")),
    )
    monkeypatch.setenv("HYDRAGNN_SCALAR_FORMAT", "csv")
    w = ScalarWriter.for_run("fmt", path=str(tmp_path))
    w.add_scalar("x", 2.0, 0)
    w.close()
    assert _csv_rows(str(tmp_path / "fmt" / "scalars.csv")) == [
        ("x", 2.0, 0)
    ]
    assert not os.path.exists(tmp_path / "fmt" / "scalars.jsonl")

    # non-zero ranks get None — same contract as the old summary writer
    monkeypatch.setattr(
        dist, "get_comm_size_and_rank", lambda: (2, 1)
    )
    assert ScalarWriter.for_run("rank1", path=str(tmp_path)) is None


def pytest_for_run_warns_once_and_keeps_recording(tmp_path, monkeypatch):
    from hydragnn_tpu.obs import scalars as sc

    monkeypatch.delenv("HYDRAGNN_SCALAR_FORMAT", raising=False)
    monkeypatch.setattr(sc, "_tb_warned", False)
    monkeypatch.setattr(
        sc.TensorBoardScalarBackend,
        "__init__",
        lambda self, log_dir: (_ for _ in ()).throw(ImportError("no tb")),
    )
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        w1 = ScalarWriter.for_run("w1", path=str(tmp_path))
        w2 = ScalarWriter.for_run("w2", path=str(tmp_path))
    assert (
        len([c for c in caught if "TensorBoard" in str(c.message)]) == 1
    )
    # tracer-totals forwarding still lands in the surviving backend
    w1.add_regions({"train": 1.0}, step=3)
    w1.close()
    w2.close()
    assert _jsonl_rows(str(tmp_path / "w1" / "scalars.jsonl")) == [
        ("tracer/train_seconds", 1.0, 3)
    ]
