"""Worker for the 4-process COMPOSED subsystems test (NOT a pytest module).

Each process: 1 virtual CPU device, ``jax.distributed`` bootstrap, then the
round-4 composition the dryrun modes only proved one-process at a time:

  - a C++ TCP **DistDataset** serving each rank's local partition (every
    batch sample is fetched through the store transport),
  - **bucketed layouts** (heterogeneous graph sizes, multi-program epoch;
    processes stay in bucket lockstep because every rank derives the same
    global plan),
  - **ZeRO stage-3** sharding (optimizer moments AND parameters over the
    4-device global data axis),

driving a real streaming training epoch with cross-process loss agreement,
plus a first-step loss printed for the test's single-process parity check.

Usage: python _composed_worker.py <proc_id> <num_procs> <port> <dds_addrs>
(``dds_addrs``: comma-separated host:port, one per rank — each port
individually verified free by the test.)
"""

import os
import sys


def make_sized_samples(rank, per_rank=8):
    """Deterministic per-rank shard with HETEROGENEOUS graph sizes (4-16
    nodes) so the bucketed layout actually buckets."""
    import numpy as np

    class _S:
        pass

    rng = np.random.default_rng(1000 + rank)
    out = []
    for _ in range(per_rank):
        n = int(rng.integers(4, 17))
        s = _S()
        s.x = rng.random((n, 1)).astype(np.float32)
        s.pos = rng.random((n, 3)).astype(np.float32)
        src = np.arange(n)
        dst = (src + 1) % n
        s.edge_index = np.stack(
            [np.concatenate([src, dst]), np.concatenate([dst, src])]
        ).astype(np.int64)
        s.edge_attr = None
        s.y = None
        s.num_nodes = n
        s.num_edges = 2 * n
        s.targets = [np.array([s.x.sum()], np.float32), s.x.copy()]
        s.target_types = ["graph", "node"]
        out.append(s)
    return out


def composed_layout(world, batch_size=4, device_multiple=4):
    """The bucketed layout every process derives from the (deterministic)
    global data — in memory, so layout derivation needs no store traffic."""
    from hydragnn_tpu.data.loaders import compute_layout

    global_samples = [
        s for r in range(world) for s in make_sized_samples(r)
    ]
    return compute_layout(
        [global_samples],
        batch_size,
        device_multiple=device_multiple,
        num_buckets=2,
    )


def worker_arch():
    from _multiprocess_worker import worker_arch as base

    return base()


def main():
    proc_id, num_procs = int(sys.argv[1]), int(sys.argv[2])
    port, dds_addrs = sys.argv[3], sys.argv[4].split(",")
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=1"
    ).strip()
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["HYDRAGNN_TPU_COORDINATOR"] = f"127.0.0.1:{port}"
    os.environ["HYDRAGNN_TPU_NUM_PROCESSES"] = str(num_procs)
    os.environ["HYDRAGNN_TPU_PROCESS_ID"] = str(proc_id)

    import jax

    jax.config.update("jax_platforms", "cpu")
    sys.path.insert(
        0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import numpy as np

    from hydragnn_tpu.data.distdataset import DistDataset
    from hydragnn_tpu.data.loaders import GraphLoader
    from hydragnn_tpu.models import create_model_config
    from hydragnn_tpu.parallel.distributed import (
        host_allreduce,
        setup_distributed,
    )
    from hydragnn_tpu.parallel.mesh import make_mesh
    from hydragnn_tpu.train.trainer import Trainer

    world, rank = setup_distributed()
    assert world == num_procs and rank == proc_id
    assert len(jax.devices()) == num_procs

    # data plane: every rank serves its partition over the C++ TCP store
    ds = DistDataset(
        make_sized_samples(rank), rank=rank, world=world,
        addresses=dds_addrs,
    )
    ds.epoch_begin()
    try:
        layout = composed_layout(world)
        assert len(layout.layouts) == 2, "expected 2 buckets"
        loader = GraphLoader(
            ds, 4, layout, shuffle=True, seed=7,
            contiguous_buckets=True,
        )
        plan = loader._batch_plan()
        assert len({b for b, _ in plan}) == 2, "both buckets must run"

        model = create_model_config(worker_arch())
        mesh = make_mesh(None, "data")
        trainer = Trainer(
            model,
            training_config={
                "Optimizer": {
                    "type": "AdamW",
                    "learning_rate": 1e-3,
                    "zero_stage": 3,
                },
                "steps_per_dispatch": 2,
            },
            mesh=mesh,
        )
        it = iter(loader)
        first = next(it)
        state = trainer.init_state(first)
        # stage-3 proof: some parameter leaf is genuinely sharded
        from jax.sharding import PartitionSpec as P

        specs = [
            getattr(leaf.sharding, "spec", None)
            for leaf in jax.tree_util.tree_leaves(state.params)
            if hasattr(leaf, "sharding")
        ]
        assert any(s == P("data") for s in specs), specs

        state, metrics = trainer._train_step(
            state, trainer.put_batch(first), jax.random.PRNGKey(0)
        )
        loss0 = float(metrics["loss"])
        assert np.isfinite(loss0)
        agree = host_allreduce(np.array([loss0]), "max")
        assert abs(float(agree[0]) - loss0) < 1e-6, (agree, loss0)

        # full streaming epoch: diststore fetches + bucketed multi-program
        # dispatch + stage-3 sharded update, every process in lockstep
        state, _rng, ep_loss, _tasks = trainer.train_epoch(
            state, loader, jax.random.PRNGKey(1)
        )
        assert np.isfinite(ep_loss), ep_loss
        agree = host_allreduce(np.array([ep_loss]), "max")
        assert abs(float(agree[0]) - ep_loss) < 1e-6, (agree, ep_loss)
    finally:
        ds.epoch_end()
        ds.close()

    print(
        f"CWOK rank={rank} world={world} loss0={loss0:.6f} "
        f"epoch={ep_loss:.6f}",
        flush=True,
    )


if __name__ == "__main__":
    main()
