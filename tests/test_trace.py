"""Request tracing (obs/trace.py) + router retry budget + trace echo.

Unit coverage for ISSUE 18's tentpole machinery, no fleet needed:
header propagation encoding, deterministic head sampling, the
tail-based flush rules (SLO-missed and errored requests flush at ANY
non-zero rate), replica span merging, segment accounting summing to the
root, the anatomy rollup, the RetryBudget token bucket (direct unit
tests — the fleet tests only exercise it incidentally), the replica's
trace-id echo on ERROR response bodies, and torn-tail repair of a
stream holding interleaved span flushes from concurrent requests.
"""

import json
import os
import threading

import pytest

from hydragnn_tpu.obs.events import RunEventLog, validate_events
from hydragnn_tpu.obs import trace as trace_mod
from hydragnn_tpu.obs.trace import (
    RequestTrace,
    TraceContext,
    Tracer,
    anatomy,
    build_traces,
    decode_header,
    dominant_segment,
    encode_header,
    head_sampled,
    load_span_events,
    new_id,
    segment_durations,
)
from hydragnn_tpu.serve.router import RetryBudget


class _Sink:
    """Collecting emit target (the schema-gated emitter's shape)."""

    def __init__(self):
        self.events = []

    def __call__(self, event, **fields):
        self.events.append((event, fields))


# ---- header propagation ----------------------------------------------------


def pytest_header_roundtrip():
    tid, sid = new_id(8), new_id()
    assert len(tid) == 16 and len(sid) == 16
    value = encode_header(tid, sid)
    assert decode_header(value) == (tid, sid)
    ctx = TraceContext.from_header(value)
    assert ctx.trace_id == tid and ctx.parent_id == sid


def pytest_header_malformed_disarms():
    for bad in (None, "", "justonepart", "-", "a-", "-b"):
        assert decode_header(bad) is None
        assert TraceContext.from_header(bad) is None


# ---- sampling --------------------------------------------------------------


def pytest_head_sampling_deterministic_and_bounded():
    tid = new_id(8)
    assert head_sampled(tid, 0.0) is False
    assert head_sampled(tid, 1.0) is True
    # same id, same rate -> same answer, every time
    assert all(
        head_sampled(tid, 0.37) == head_sampled(tid, 0.37)
        for _ in range(10)
    )
    # the decision threshold is the id's leading 32 bits
    assert head_sampled("00000000" + "0" * 8, 0.01)
    assert not head_sampled("ffffffff" + "0" * 8, 0.99)


def pytest_tracer_off_costs_nothing():
    assert Tracer(sample=0.0, emit=_Sink()).start() is None
    assert Tracer(sample=0.5, emit=None).start() is None
    assert not Tracer(sample=0.0, emit=None).enabled


# ---- tail-based flush rules ------------------------------------------------


def _forced(sink, sampled):
    tracer = Tracer(sample=1.0, emit=sink)
    tr = tracer.start(tenant="acme", lane="default")
    tr.sampled = sampled
    return tr


def pytest_unsampled_ok_does_not_flush():
    sink = _Sink()
    tr = _forced(sink, sampled=False)
    assert tr.finish("ok") is False
    assert sink.events == []


def pytest_slo_missed_always_flushes():
    sink = _Sink()
    tr = _forced(sink, sampled=False)
    tr.record("queue_wait", 0.0, 0.5)
    assert tr.finish("ok", slo_missed=True) is True
    names = [f["name"] for _, f in sink.events]
    assert "route" in names and "queue_wait" in names
    root = next(f for _, f in sink.events if f["name"] == "route")
    assert root["attrs"]["slo_missed"] is True
    assert root["parent"] == ""


def pytest_error_always_flushes():
    sink = _Sink()
    tr = _forced(sink, sampled=False)
    assert tr.finish("shed", error=True) is True
    assert [f["name"] for _, f in sink.events] == ["route"]


def pytest_head_sampled_flushes_and_finish_idempotent():
    sink = _Sink()
    tr = _forced(sink, sampled=True)
    assert tr.finish("ok") is True
    n = len(sink.events)
    assert tr.finish("ok") is False  # second finish: no double emit
    assert len(sink.events) == n


def pytest_tail_capture_rate_is_total_for_slo_missed():
    """At sample=0.01 essentially no trace head-samples, yet every
    SLO-missed request flushes — the tail acceptance rule."""
    sink = _Sink()
    tracer = Tracer(sample=0.01, emit=sink)
    flushed = 0
    for _ in range(50):
        tr = tracer.start()
        tr.sampled = False  # force the head decision to "reject"
        flushed += bool(tr.finish("ok", slo_missed=True))
    assert flushed == 50


# ---- replica span merging --------------------------------------------------


def pytest_merge_keeps_own_trace_reparents_orphans():
    tr = RequestTrace(Tracer(sample=1.0, emit=_Sink()), "a" * 16, True)
    attempt = new_id()
    tr.merge([
        {"trace": "a" * 16, "span": "s1", "parent": attempt,
         "name": "queue_wait", "start": 1.0, "dur_s": 0.2, "attrs": {}},
        {"trace": "b" * 16, "span": "s2", "parent": attempt,
         "name": "dispatch", "start": 1.2, "dur_s": 0.1},  # wrong trace
        {"trace": "a" * 16, "span": "s3", "parent": None,
         "name": "dispatch", "start": 1.2, "dur_s": 0.1},  # orphan
        "garbage", {"trace": "a" * 16},  # malformed
    ])
    spans = {s["span"]: s for s in tr._spans}
    assert set(spans) == {"s1", "s3"}
    assert spans["s1"]["parent"] == attempt
    assert spans["s3"]["parent"] == tr.root_id  # re-parented to root
    tr.merge(None)  # tolerant of absent field


# ---- segment accounting ----------------------------------------------------


def _synthetic_trace():
    """route(1.0s) -> admit(0.1) + attempt(0.8) -> queue_wait(0.5) +
    dispatch(0.2); attempt exclusive = 0.1 (transport), route exclusive
    = 0.1 (other)."""
    root, att = "r" * 16, "a" * 16
    spans = [
        {"trace": "t1", "span": root, "parent": "", "name": "route",
         "start": 0.0, "dur_s": 1.0,
         "attrs": {"tenant": "acme", "lane": "default", "status": "ok",
                   "slo_missed": True}},
        {"trace": "t1", "span": "s1", "parent": root, "name": "admit",
         "start": 0.0, "dur_s": 0.1, "attrs": {}},
        {"trace": "t1", "span": att, "parent": root, "name": "attempt",
         "start": 0.1, "dur_s": 0.8, "attrs": {}},
        {"trace": "t1", "span": "s2", "parent": att, "name": "queue_wait",
         "start": 0.15, "dur_s": 0.5, "attrs": {}},
        {"trace": "t1", "span": "s3", "parent": att, "name": "dispatch",
         "start": 0.65, "dur_s": 0.2, "attrs": {}},
    ]
    return [dict(s, event="span") for s in spans]


def pytest_segments_sum_to_root():
    traces = build_traces(_synthetic_trace())
    assert set(traces) == {"t1"}
    segments = segment_durations(traces["t1"])
    assert segments["admit"] == pytest.approx(0.1)
    assert segments["queue_wait"] == pytest.approx(0.5)
    assert segments["dispatch"] == pytest.approx(0.2)
    assert segments["transport"] == pytest.approx(0.1)  # attempt excl.
    assert segments["other"] == pytest.approx(0.1)  # route exclusive
    root_dur = traces["t1"]["root"]["dur_s"]
    assert sum(segments.values()) == pytest.approx(root_dur)
    assert dominant_segment(traces["t1"]) == "queue_wait"


def pytest_anatomy_rollup():
    rollup = anatomy(build_traces(_synthetic_trace()))
    assert rollup["traces"] == 1
    assert rollup["segments"]["queue_wait"]["count"] == 1
    assert rollup["segments"]["queue_wait"]["p99_s"] == pytest.approx(
        0.5, abs=1e-6
    )
    assert "acme/default" in rollup["groups"]
    row = rollup["slowest"][0]
    assert row["dominant"] == "queue_wait"
    assert row["slo_missed"] is True
    assert row["tenant"] == "acme"


def pytest_trace_cli_renders(tmp_path, capsys):
    from hydragnn_tpu.obs.__main__ import main as obs_main

    log = RunEventLog(str(tmp_path / "events.jsonl"))
    for rec in _synthetic_trace():
        fields = {k: v for k, v in rec.items() if k != "event"}
        log.emit("span", **fields)
    assert obs_main(["trace", str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert "queue_wait" in out and "dominant=queue_wait" in out
    assert obs_main(["trace", str(tmp_path / "missing")]) == 2


# ---- RetryBudget (direct unit tests) --------------------------------------


def pytest_retry_budget_starts_at_reserve():
    budget = RetryBudget(ratio=0.1, reserve=10.0)
    assert budget.tokens == pytest.approx(10.0)


def pytest_retry_budget_refill_ratio_and_cap():
    budget = RetryBudget(ratio=0.25, reserve=2.0)
    # drain the reserve
    assert budget.try_acquire() and budget.try_acquire()
    assert not budget.try_acquire()
    assert budget.tokens == pytest.approx(0.0)
    # each success refills `ratio` tokens: 4 successes buy ONE retry
    for _ in range(3):
        budget.on_success()
        assert not budget.try_acquire()
    budget.on_success()
    assert budget.tokens == pytest.approx(1.0)
    assert budget.try_acquire()
    # refill never exceeds the reserve cap
    for _ in range(1000):
        budget.on_success()
    assert budget.tokens == pytest.approx(2.0)


def pytest_retry_budget_storm_exhausts():
    """A retry storm dies at the budget: with no successes, acquires
    stop after `reserve` grants no matter how many requests want one."""
    budget = RetryBudget(ratio=0.1, reserve=5.0)
    grants = sum(budget.try_acquire() for _ in range(1000))
    assert grants == 5
    assert budget.tokens == pytest.approx(0.0)


def pytest_retry_budget_tokens_monotone_under_successes():
    budget = RetryBudget(ratio=0.25, reserve=8.0)
    for _ in range(3):
        budget.try_acquire()
    seen = [budget.tokens]
    for _ in range(20):
        budget.on_success()
        seen.append(budget.tokens)
    assert all(b >= a for a, b in zip(seen, seen[1:]))
    assert seen[-1] <= 8.0


# ---- replica error bodies echo the trace id (satellite) -------------------


def _bare_replica():
    """A ReplicaServer shell exercising handle_predict without a real
    InferenceServer — exactly the attributes the request path touches
    before submit."""
    from hydragnn_tpu.serve.fleet import ReplicaServer

    replica = ReplicaServer.__new__(ReplicaServer)
    replica._lock = threading.Lock()
    replica._served = 0
    replica.is_canary = False
    replica.replica_id = 0
    return replica


def pytest_error_response_echoes_trace_id():
    replica = _bare_replica()
    tid = new_id(8)
    header = encode_header(tid, new_id())
    code, body, _headers = replica.handle_predict(
        {"graph": "not-a-graph"}, trace_header=header
    )
    assert code == 400
    assert body["trace"] == tid
    assert body["spans"] == []


def pytest_overload_response_echoes_trace_id():
    from hydragnn_tpu.serve.server import ServerOverloaded

    replica = _bare_replica()

    class _Shedding:
        max_wait_s = 0.01

        def submit(self, *a, **kw):
            raise ServerOverloaded(retry_after_s=0.05)

    replica.server = _Shedding()
    graph = {  # minimal decodable payload (fleet.decode_graph shape)
        "x": [[1.0], [2.0]],
        "edge_index": [[0, 1], [1, 0]],
        "pos": [[0.0, 0.0, 0.0], [1.0, 0.0, 0.0]],
    }
    tid = new_id(8)
    code, body, _headers = replica.handle_predict(
        {"graph": graph},
        trace_header=encode_header(tid, new_id()),
    )
    assert code == 503
    assert body["trace"] == tid


def pytest_untraced_error_body_has_no_trace_field():
    replica = _bare_replica()
    code, body, _headers = replica.handle_predict({"graph": "nope"})
    assert code == 400
    assert "trace" not in body and "spans" not in body


# ---- torn-tail repair with interleaved concurrent flushes -----------------


def pytest_torn_tail_repair_interleaved_span_flushes(tmp_path):
    path = str(tmp_path / "events.jsonl")
    log = RunEventLog(path)
    tracer = Tracer(sample=1.0, emit=log.emit)

    def one_request(k):
        tr = tracer.start(tenant=f"t{k % 2}", lane="default")
        tr.sampled = True
        tr.record("admit", 0.0, 0.001)
        tr.record("queue_wait", 0.0, 0.01 * k)
        tr.finish("ok", slo_missed=(k % 3 == 0))

    threads = [
        threading.Thread(target=one_request, args=(k,)) for k in range(8)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    # simulate a crash mid-append: a torn, newline-less partial record
    with open(path, "a") as f:
        f.write('{"event": "span", "trace": "dead')
    # reopen repairs the tail and resumes the seq; the stream then
    # passes the STRICT validator including the new span schema
    log2 = RunEventLog(path)
    tracer2 = Tracer(sample=1.0, emit=log2.emit)
    tr = tracer2.start()
    tr.sampled = True
    assert tr.finish("ok") is True
    # raises on any schema/seq violation — repair must leave a stream
    # the STRICT validator accepts, span schema included
    records = validate_events(path, require=["span"])
    assert all(r["event"] == "span" for r in records)
    spans = load_span_events(path)
    traces = build_traces(spans)
    assert len(traces) >= 8  # every concurrent request's trace survived
    for t in traces.values():
        assert t["root"] is not None
