"""Mixed-precision (bf16 compute / f32 master) training accuracy.

No reference counterpart — HydraGNN trains pure f32. The bf16 path must
still clear the SAME accuracy ceilings as f32 training
(``tests/test_graphs.py`` / reference ``tests/test_graphs.py:139-156``),
otherwise it would be a perf knob that silently costs accuracy.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from test_graphs import unittest_train_model


def pytest_mixed_precision_pna_multihead():
    unittest_train_model(
        "PNA",
        "ci_multihead.json",
        False,
        overwrite_config={
            "NeuralNetwork": {"Training": {"mixed_precision": True}}
        },
    )
