"""Multi-tenant serving (serve/tenants.py + autoscale.py + router).

Acceptance (ISSUE 17): tenant isolation proven against a REAL 2-replica
fleet — tenant A flooding 10x its quota must leave tenant B's latency
and SLO-miss profile within tolerance of B's solo baseline, with zero
cross-tenant responses; the router's shed handling must be per-tenant
(regression for the lane-global retry-after bug); every
``HYDRAGNN_TENANT_*`` / ``HYDRAGNN_AUTOSCALE_*`` knob validates through
envparse; the autoscaler's control loop is unit-tested deterministically
against a fake fleet.
"""

import os
import threading
import time

import numpy as np
import pytest

from hydragnn_tpu import coord
from hydragnn_tpu.serve import (
    AutoscalePolicy,
    FleetAutoscaler,
    FleetRouter,
    InferenceServer,
    LoadForecast,
    ModelRegistry,
    ReplicaServer,
    ServerOverloaded,
    TenantManager,
    TenantOverQuota,
    TenantSpec,
)
from hydragnn_tpu.utils.envparse import env_float

from test_serve import _graph, _harness


# ---- envparse knobs --------------------------------------------------------


def pytest_env_float_validates(monkeypatch):
    monkeypatch.delenv("HYDRAGNN_X", raising=False)
    assert env_float("HYDRAGNN_X", 2.5) == 2.5
    monkeypatch.setenv("HYDRAGNN_X", " 0.75 ")
    assert env_float("HYDRAGNN_X", 2.5) == 0.75
    monkeypatch.setenv("HYDRAGNN_X", "fast")
    with pytest.raises(ValueError, match="HYDRAGNN_X"):
        env_float("HYDRAGNN_X", 2.5)
    monkeypatch.setenv("HYDRAGNN_X", "nan")
    with pytest.raises(ValueError, match="HYDRAGNN_X"):
        env_float("HYDRAGNN_X", 2.5)
    monkeypatch.setenv("HYDRAGNN_X", "-1")
    with pytest.raises(ValueError, match=">= 0"):
        env_float("HYDRAGNN_X", 2.5)
    assert env_float("HYDRAGNN_X", 2.5, minimum=None) == -1.0


def pytest_tenant_env_knobs(monkeypatch):
    monkeypatch.setenv("HYDRAGNN_TENANT_DEFAULT_QUOTA", "7")
    monkeypatch.setenv("HYDRAGNN_TENANT_QUANTUM", "2")
    mgr = TenantManager([TenantSpec("a", "m")])
    assert mgr.default_quota == 7 and mgr.quantum == 2
    assert mgr.quota_for("a") == 7
    monkeypatch.setenv("HYDRAGNN_TENANT_DEFAULT_QUOTA", "zero")
    with pytest.raises(ValueError, match="HYDRAGNN_TENANT_DEFAULT_QUOTA"):
        TenantManager()
    monkeypatch.setenv("HYDRAGNN_TENANT_DEFAULT_QUOTA", "0")
    with pytest.raises(ValueError, match=">= 1"):
        TenantManager()


def pytest_autoscale_env_knobs(monkeypatch):
    monkeypatch.setenv("HYDRAGNN_AUTOSCALE_MIN", "2")
    monkeypatch.setenv("HYDRAGNN_AUTOSCALE_MAX", "6")
    monkeypatch.setenv("HYDRAGNN_AUTOSCALE_CAPACITY_RPS", "12.5")
    monkeypatch.setenv("HYDRAGNN_AUTOSCALE_SLO_BUDGET", "0.02")
    monkeypatch.setenv("HYDRAGNN_AUTOSCALE_DOWN_COOLDOWN_S", "90")
    p = AutoscalePolicy.from_env()
    assert (p.min_replicas, p.max_replicas) == (2, 6)
    assert p.capacity_rps == 12.5 and p.slo_budget == 0.02
    assert p.down_cooldown_s == 90.0
    monkeypatch.setenv("HYDRAGNN_AUTOSCALE_MAX", "1")
    with pytest.raises(ValueError, match="max_replicas"):
        AutoscalePolicy.from_env()
    monkeypatch.setenv("HYDRAGNN_AUTOSCALE_MAX", "big")
    with pytest.raises(ValueError, match="HYDRAGNN_AUTOSCALE_MAX"):
        AutoscalePolicy.from_env()


# ---- TenantSpec / TenantManager units --------------------------------------


def pytest_tenant_spec_validates_eagerly():
    with pytest.raises(ValueError, match="non-empty"):
        TenantSpec("", "m")
    with pytest.raises(ValueError, match="model"):
        TenantSpec("a", "")
    with pytest.raises(ValueError, match="quota"):
        TenantSpec("a", "m", quota=0)
    with pytest.raises(ValueError, match="weight"):
        TenantSpec("a", "m", weight=0.0)
    spec = TenantSpec.from_dict({"name": "a", "quota": 3, "weight": 2})
    assert spec.model == "a" and spec.quota == 3 and spec.weight == 2.0


def pytest_tenant_manager_quota_admission():
    mgr = TenantManager(
        [TenantSpec("a", "m", quota=2), TenantSpec("b", "m")],
        default_quota=5, quantum=4,
    )
    mgr.admit("a")
    mgr.admit("a")
    with pytest.raises(TenantOverQuota) as exc:
        mgr.admit("a", retry_after_s=0.25)
    assert exc.value.tenant == "a" and exc.value.quota == 2
    assert exc.value.retry_after_s == 0.25
    assert isinstance(exc.value, ServerOverloaded)  # 503/retry machinery
    mgr.admit("b")  # a's flood does not touch b's quota
    assert mgr.in_flight("a") == 2 and mgr.in_flight("b") == 1
    mgr.release("a")
    mgr.admit("a")  # freed slot readmits
    with pytest.raises(KeyError, match="unknown tenant"):
        mgr.admit("nope")
    desc = mgr.describe()
    assert desc["a"]["shed"] == 1 and desc["a"]["admitted"] == 3
    assert desc["b"]["quota"] == 5  # default applied
    with pytest.raises(ValueError, match="already registered"):
        mgr.register(TenantSpec("a", "m"))


def pytest_tenant_dwrr_flush_order_weight_share():
    """DWRR ordering: a weight-2 tenant earns first dispatch roughly
    twice as often as a weight-1 tenant, the weight-1 tenant is never
    starved (its credit accrues until it outranks the heavy), and idle
    tenants' credit resets."""
    mgr = TenantManager(
        [TenantSpec("heavy", "m", weight=2.0),
         TenantSpec("light", "m", weight=1.0)],
        default_quota=64, quantum=4,
    )
    backlog = {"heavy": 8, "light": 8}
    # round 1: heavy credited 2*4=8, light 4 -> heavy leads; the device
    # slot goes to heavy (full group of 8), debiting its credit
    assert mgr.flush_order(backlog) == ["heavy", "light"]
    mgr.on_served("heavy", 8)
    # round 2: heavy back to 8, light at 8 -> deterministic name tie-
    # break keeps heavy first; light's credit keeps accruing
    assert mgr.flush_order(backlog) == ["heavy", "light"]
    mgr.on_served("heavy", 8)
    # round 3: light (12) now outranks heavy (8) — no starvation
    assert mgr.flush_order(backlog) == ["light", "heavy"]
    mgr.on_served("light", 8)
    # heavy led 2 of 3 contended rounds: the 2:1 weight share
    # untenanted (None) traffic participates at weight 1
    order = mgr.flush_order({None: 4, "light": 4})
    assert set(order) == {None, "light"}
    # idle reset: after a round with no backlog the stored credit is gone
    mgr.flush_order({})
    assert mgr._deficit == {}


def pytest_server_batches_never_mix_tenants():
    """The packing key is (tenant, model, version, bucket): two tenants
    submitting identically-sized graphs into one flush window still land
    in separate micro-batches — cross-tenant mixing is impossible by
    construction, not by scheduling luck."""
    h = _harness()
    registry = ModelRegistry()
    registry.register("m", h["model"], h["state"].params,
                      h["state"].batch_stats)
    mgr = TenantManager(
        [TenantSpec("a", "m"), TenantSpec("b", "m")], default_quota=8,
    )
    server = InferenceServer(
        registry, h["plan"], default_model="m", tenants=mgr,
        max_wait_s=0.05,
    )
    # batcher NOT started: groups accumulate deterministically
    rng = np.random.default_rng(6)
    g = _graph(8, rng, with_targets=False)
    for tenant in ("a", "b", "a", "b"):
        server.submit(g, tenant=tenant)
    import queue as _queue

    while True:
        try:
            server._admit_pending(server._queue.get_nowait())
        except _queue.Empty:
            break
    keys = list(server._pending)
    assert len(keys) == 2  # one group per tenant, same bucket
    assert {k[0] for k in keys} == {"a", "b"}
    assert len({k[3] for k in keys}) == 1  # same bucket, still split
    server.stop()


# ---- autoscaler control loop (deterministic, fake fleet) -------------------


class _FakeFleet:
    def __init__(self, coord_dir, target=1):
        self.coord_dir = coord_dir
        self.target = target
        self.calls = []

    def resize(self, n, reason="manual"):
        self.calls.append((int(n), reason))
        self.target = int(n)
        return self.target


class _Signals:
    """Mutable cumulative-counter source standing in for ServeMetrics."""

    def __init__(self):
        self.requests = 0
        self.shed = 0
        self.met = 0
        self.missed = 0

    def __call__(self):
        return {
            "requests_total": self.requests,
            "shed_total": self.shed,
            "slo": {"deadline_met": self.met,
                    "deadline_missed": self.missed},
        }


def _scaler(tmp_path, target=1, **policy_kw):
    policy_kw.setdefault("capacity_rps", 10.0)
    policy_kw.setdefault("up_cooldown_s", 0.0)
    policy_kw.setdefault("down_cooldown_s", 0.0)
    policy_kw.setdefault("period_s", 240.0)
    policy_kw.setdefault("n_phases", 24)
    fleet = _FakeFleet(str(tmp_path), target=target)
    sig = _Signals()
    scaler = FleetAutoscaler(
        fleet, sig, policy=AutoscalePolicy(**policy_kw), interval_s=1.0
    )
    return fleet, sig, scaler


def pytest_autoscaler_grows_on_slo_pressure(tmp_path):
    fleet, sig, scaler = _scaler(tmp_path)
    assert scaler.tick(now=0.0) is None  # priming tick: baseline only
    sig.requests += 10
    sig.met, sig.missed = 5, 5  # 50% miss >> 5% budget
    decision = scaler.tick(now=1.0)
    assert decision["reason"] == "slo_pressure"
    assert fleet.calls == [(2, "slo_pressure")]
    # sheds alone also count as pressure
    sig.requests += 10
    sig.met += 10
    sig.shed += 3
    scaler.tick(now=2.0)
    assert fleet.calls[-1] == (3, "slo_pressure")


def pytest_autoscaler_forecast_scaling_and_bounds(tmp_path):
    fleet, sig, scaler = _scaler(tmp_path, max_replicas=4)
    scaler.tick(now=0.0)
    # 100 rps observed, 10 rps/replica capacity, 1.2 headroom -> wants
    # 12 replicas; the max bound clamps to 4
    sig.requests += 100
    sig.met += 100
    decision = scaler.tick(now=1.0)
    assert decision["reason"] == "forecast" and decision["applied"] == 4
    assert fleet.calls == [(4, "forecast")]
    # load vanishes: EWMA decays across quiet ticks, then scale-down
    # (healthy fleet, cooldowns zeroed) walks back to min
    coord.write_json(
        os.path.join(str(tmp_path), "fleet.json"),
        {"live": 4, "target": 4, "degraded": False},
    )
    for i in range(40):
        sig.met += 0
        scaler.tick(now=2.0 + i)
    assert fleet.target == 1
    assert fleet.calls[-1][1] == "scale_down"


def pytest_autoscaler_up_cooldown_limits_flapping(tmp_path):
    fleet, sig, scaler = _scaler(tmp_path, up_cooldown_s=10.0)
    scaler.tick(now=0.0)
    sig.requests += 10
    sig.missed += 10
    scaler.tick(now=1.0)
    assert fleet.calls == [(2, "slo_pressure")]
    sig.requests += 10
    sig.missed += 10
    scaler.tick(now=2.0)  # still inside the up-cooldown: desired but held
    assert fleet.calls == [(2, "slo_pressure")]
    sig.requests += 10
    sig.missed += 10
    scaler.tick(now=12.0)  # cooldown expired
    assert fleet.calls[-1] == (3, "slo_pressure")


def pytest_autoscaler_never_shrinks_degraded_fleet(tmp_path):
    fleet, sig, scaler = _scaler(tmp_path, target=3)
    coord.write_json(
        os.path.join(str(tmp_path), "fleet.json"),
        {"live": 2, "target": 3, "degraded": True},
    )
    scaler.tick(now=0.0)
    for i in range(10):
        scaler.tick(now=1.0 + i)  # zero load: wants min_replicas=1
    assert fleet.calls == []  # held: the monitor owns the live dip
    assert scaler.decisions[-1]["desired"] == 1
    coord.write_json(
        os.path.join(str(tmp_path), "fleet.json"),
        {"live": 3, "target": 3, "degraded": False},
    )
    scaler.tick(now=20.0)
    assert fleet.calls == [(1, "scale_down")]  # healthy again: applied


def pytest_load_forecast_anticipates_diurnal_phase():
    """After two observed periods, the forecast one phase ahead of a
    known-busy phase exceeds the current-phase estimate — the property
    that buys replica boot time before the recurring ramp."""
    f = LoadForecast(alpha=0.9, period_s=100.0, n_phases=10)
    for period in range(2):
        base = period * 100.0
        for phase in range(10):
            rps = 100.0 if phase == 2 else 5.0
            f.observe(rps, base + phase * 10.0 + 5.0)
    now = 215.0  # period 3, phase 1 (quiet)
    ahead = f.forecast(now, horizon_s=10.0)  # lands in busy phase 2
    here = f.forecast(now)
    assert ahead > 50.0 > here


# ---- the real-fleet isolation e2e ------------------------------------------


def _tenant_server(quota_a=4, max_wait_s=0.002):
    """Registry with TWO models (distinct weights) + two tenants: a
    (small quota, floodable) on 'ma', b on 'mb'."""
    import jax

    h = _harness()
    registry = ModelRegistry()
    registry.register("ma", h["model"], h["state"].params,
                      h["state"].batch_stats)
    bumped = jax.tree_util.tree_map(lambda x: x + 0.05, h["state"].params)
    registry.register("mb", h["model"], bumped, h["state"].batch_stats)
    mgr = TenantManager(
        [TenantSpec("a", "ma", quota=quota_a, weight=1.0),
         TenantSpec("b", "mb", weight=1.0)],
        default_quota=32, quantum=4,
    )
    return InferenceServer(
        registry, h["plan"], default_model="ma", tenants=mgr,
        max_wait_s=max_wait_s, queue_capacity=256,
    )


def pytest_tenant_isolation_flood_vs_solo_baseline(tmp_path):
    """Two real replicas behind the router. Tenant B's solo profile is
    measured, then tenant A floods 10x its quota from 3 threads while B
    repeats the same traffic: B must see ZERO sheds/misses, a p99 within
    tolerance of its baseline, and only mb-model responses."""
    servers = [_tenant_server(quota_a=2), _tenant_server(quota_a=2)]
    reps = [
        ReplicaServer(servers[i], str(tmp_path), i, heartbeat_s=0.05)
        for i in range(2)
    ]
    for rep in reps:
        rep.start()
    try:
        router = FleetRouter(str(tmp_path), target_replicas=2,
                             scan_interval_s=0.05)
        rng = np.random.default_rng(41)
        graphs = [
            _graph(int(n), rng, with_targets=False)
            for n in rng.integers(4, 30, 20)
        ]
        expected = [
            servers[0].predict(g, model="mb", timeout=30) for g in graphs
        ]

        def run_b():
            lat, bad = [], 0
            for g, want in zip(graphs, expected):
                t0 = time.monotonic()
                raw = router.route(
                    g, tenant="b", deadline_s=30.0, raw=True
                )
                lat.append(time.monotonic() - t0)
                if raw.get("model") not in ("mb", None):
                    bad += 1
                np.testing.assert_allclose(
                    np.asarray(raw["heads"][0]),
                    np.asarray(want[0]), atol=1e-6,
                )
            return np.percentile(lat, 99), bad

        solo_p99, solo_bad = run_b()
        assert solo_bad == 0

        # tenant A floods: 10 concurrent clients against a quota of 2
        # per replica — sustained pressure far past 10x the quota
        stop = threading.Event()
        a_out = {"ok": 0, "shed": 0}
        a_lock = threading.Lock()

        def flood():
            frng = np.random.default_rng(threading.get_ident() % 2**31)
            while not stop.is_set():
                g = _graph(int(frng.integers(4, 30)), frng,
                           with_targets=False)
                try:
                    router.route(g, tenant="a", deadline_s=30.0)
                    out = "ok"
                except ServerOverloaded:
                    out = "shed"
                except Exception:
                    out = "shed"
                with a_lock:
                    a_out[out] += 1

        floods = [threading.Thread(target=flood) for _ in range(10)]
        for t in floods:
            t.start()
        try:
            time.sleep(0.2)  # flood established
            flood_p99, flood_bad = run_b()
        finally:
            stop.set()
            for t in floods:
                t.join(timeout=30.0)
        assert flood_bad == 0  # zero cross-tenant responses
        assert a_out["ok"] + a_out["shed"] >= 40  # >= 10x quota attempted
        assert a_out["shed"] > 0  # the flood really was shed
        # B's profile held: nothing shed, every deadline met, p99 within
        # tolerance of solo (generous: CPU CI boxes jitter)
        assert flood_p99 <= max(solo_p99 * 5.0, 1.0)
        for server in servers:
            desc = server.tenants.describe()
            assert desc["b"]["shed"] == 0
            assert desc["a"]["in_flight"] <= 2  # quota never overshot
        snap = router.metrics.snapshot()
        assert snap["deadline_missed_total"] == 0
    finally:
        for rep in reps:
            rep.shutdown()


def pytest_router_backoff_is_per_tenant_not_lane_global(tmp_path):
    """Regression: a tenant-quota 503 must back off THAT tenant only.
    The old behavior parked the whole lane, so one noisy tenant's
    retry-after starved every other tenant sharing the lane."""
    # max_wait_s is the quota-shed retry-after hint: make the backoff
    # window long enough to observe the local shed deterministically
    server = _tenant_server(quota_a=1, max_wait_s=0.5)
    rep = ReplicaServer(server, str(tmp_path), 0, heartbeat_s=0.05)
    rep.start()
    try:
        router = FleetRouter(str(tmp_path), target_replicas=1,
                             scan_interval_s=0.05)
        g = _graph(10, np.random.default_rng(42), with_targets=False)
        # occupy a's whole quota in-process, then route: the replica
        # answers a tenant-tagged 503 the router must scope to 'a'
        server.tenants.admit("a", retry_after_s=30.0)
        try:
            with pytest.raises(ServerOverloaded):
                router.route(g, tenant="a", deadline_s=10.0)
            assert "a" in router._tenant_backoff
            # within the backoff window 'a' sheds LOCALLY (no HTTP)
            posted_before = server.metrics.requests_total
            with pytest.raises(ServerOverloaded) as exc:
                router.route(g, tenant="a", deadline_s=10.0)
            assert exc.value.retry_after_s > 0
            assert server.metrics.requests_total == posted_before
            # ...while 'b' and untenanted traffic on the SAME lane route
            heads = router.route(g, tenant="b", deadline_s=30.0)
            assert all(np.isfinite(h).all() for h in heads)
            router.route(g, deadline_s=30.0)
            shed = router.fleet_metrics.snapshot()["tenant_shed_total"]
            assert shed == {"tenant=a": 2}
        finally:
            server.tenants.release("a")
    finally:
        rep.shutdown()


def pytest_router_autoscale_signals_fold_in_tenant_sheds(tmp_path):
    """``autoscale_signals`` must expose quota sheds as shed pressure:
    the tenant-503 path books ``errors_total`` (admission convention),
    which would leave the autoscaler blind to a flooding tenant."""
    router = FleetRouter(str(tmp_path), target_replicas=1,
                         scan_interval_s=0.05)
    base = router.autoscale_signals()
    assert base["shed_total"] == 0
    router.fleet_metrics.on_tenant_shed("acme")
    router.fleet_metrics.on_tenant_shed("acme")
    router.fleet_metrics.on_tenant_shed("beta")
    router.metrics.on_shed()  # a lane-level local shed still counts
    snap = router.autoscale_signals()
    assert snap["shed_total"] == 4
    # ServeMetrics itself is untouched: the fold is read-side only
    assert router.metrics.snapshot()["shed_total"] == 1
