"""jaxlint (hydragnn_tpu/analysis): the static-analysis gate.

Per rule: a bad snippet that must flag and a good snippet that must not;
plus the suppression/baseline machinery, the CLI exit-code contract, and
the two acceptance regressions — the merged tree is clean, and
reintroducing a per-batch ``float()`` in a trainer hot loop or dropping
``donate_argnums`` from a train step fails the gate.

Everything here is pure-AST: no jax execution, so the whole file runs in
well under a second.
"""

import json
import os
import textwrap

import pytest

from hydragnn_tpu.analysis import all_rules, analyze_paths
from hydragnn_tpu.analysis.__main__ import main as jaxlint_main
from hydragnn_tpu.analysis.baseline import (
    apply_baseline,
    load_baseline,
    save_baseline,
)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _lint(tmp_path, files, **kw):
    """Write {relpath: source} under tmp_path, analyze, return findings."""
    for rel, src in files.items():
        path = tmp_path / rel
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(textwrap.dedent(src))
    return analyze_paths([str(tmp_path)], root=str(tmp_path), **kw).findings


def _rules_of(findings):
    return sorted({f.rule for f in findings})


# ---- host-sync-in-hot-loop ------------------------------------------------

_HOT_BAD = """
    import numpy as np

    class Trainer:
        def train_epoch(self, state, loader, rng):
            tot = 0.0
            for batch in loader:
                state, metrics = self._train_step(state, batch, rng)
                tot += float(metrics["loss"])
                np.asarray(metrics["tasks"])
                metrics["loss"].item()
            return tot
"""

_HOT_GOOD = """
    import numpy as np

    class Trainer:
        def train_epoch(self, state, loader, rng):
            acc = None
            for batch in loader:
                state, metrics = self._train_step(state, batch, rng)
                acc = self._acc_add(acc, metrics)
            return self._acc_read(acc)
"""


def pytest_host_sync_flags_per_batch_conversions(tmp_path):
    findings = _lint(tmp_path, {"train/trainer.py": _HOT_BAD})
    hs = [f for f in findings if f.rule == "host-sync-in-hot-loop"]
    assert len(hs) == 3, findings  # float, np.asarray, .item()


def pytest_host_sync_clean_on_device_accumulation(tmp_path):
    findings = _lint(tmp_path, {"train/trainer.py": _HOT_GOOD})
    assert not [f for f in findings if f.rule == "host-sync-in-hot-loop"]


def pytest_host_sync_ignores_non_dispatching_loops(tmp_path):
    # a host-side collection loop (no step dispatch) converts freely
    src = """
        import numpy as np

        class Trainer:
            def collect(self, batches):
                out = []
                for b in batches:
                    out.append(np.asarray(b.targets))
                return out
    """
    findings = _lint(tmp_path, {"train/trainer.py": src})
    assert not findings, findings


def pytest_host_sync_scoped_to_hot_files(tmp_path):
    # the same bad loop outside the hot set is not this rule's business
    findings = _lint(tmp_path, {"data/loaders.py": _HOT_BAD})
    assert not [f for f in findings if f.rule == "host-sync-in-hot-loop"]


def pytest_host_sync_reaches_same_file_helpers(tmp_path):
    src = """
        class Trainer:
            def _acc(self, acc, metrics):
                return acc + metrics["loss"].item()

            def train_epoch(self, state, loader):
                acc = 0.0
                for batch in loader:
                    m = self._eval_step(state, batch)
                    acc = self._acc(acc, m)
                return acc
    """
    findings = _lint(tmp_path, {"serve/server.py": src})
    hs = [f for f in findings if f.rule == "host-sync-in-hot-loop"]
    assert len(hs) == 1 and "_acc" in hs[0].message, findings


# ---- jit rules ------------------------------------------------------------


def pytest_jit_in_loop_and_immediate_invocation(tmp_path):
    src = """
        import jax

        def bad_loop(fns, x):
            for f in fns:
                g = jax.jit(f)
                g(x)

        def bad_immediate(f, x):
            return jax.jit(f)(x)

        def good(f):
            return jax.jit(f)
    """
    findings = _lint(tmp_path, {"m.py": src})
    ji = [f for f in findings if f.rule == "jit-in-loop"]
    assert len(ji) == 2, findings


def pytest_missing_donate_flags_train_steps_only(tmp_path):
    src = """
        import jax

        def train_step(state, batch, rng):
            return state

        def eval_step(params, batch):
            return params

        bad = jax.jit(train_step)
        good = jax.jit(train_step, donate_argnums=(0,))
        fine = jax.jit(eval_step)
    """
    findings = _lint(tmp_path, {"m.py": src})
    md = [f for f in findings if f.rule == "missing-donate"]
    assert len(md) == 1 and "train_step" in md[0].message, findings


def pytest_recompile_hazard_static_data_arg(tmp_path):
    src = """
        import jax

        def step(state, batch):
            return state

        bad = jax.jit(step, static_argnums=(1,))
        good = jax.jit(step)
    """
    findings = _lint(tmp_path, {"m.py": src})
    rh = [f for f in findings if f.rule == "recompile-hazard"]
    assert len(rh) == 1 and "batch" in rh[0].message, findings


# ---- prng-key-reuse -------------------------------------------------------


def pytest_prng_sequential_reuse_flags(tmp_path):
    src = """
        import jax

        def bad(rng):
            a = jax.random.normal(rng, (3,))
            b = jax.random.uniform(rng, (3,))
            return a + b

        def good(rng):
            rng, k1 = jax.random.split(rng)
            a = jax.random.normal(k1, (3,))
            rng, k2 = jax.random.split(rng)
            b = jax.random.uniform(k2, (3,))
            return a + b
    """
    findings = _lint(tmp_path, {"m.py": src})
    pr = [f for f in findings if f.rule == "prng-key-reuse"]
    assert len(pr) == 1, findings


def pytest_prng_use_after_split_flags(tmp_path):
    src = """
        import jax

        def bad(rng):
            k1, k2 = jax.random.split(rng)
            return jax.random.normal(rng, (3,))
    """
    findings = _lint(tmp_path, {"m.py": src})
    assert _rules_of(findings) == ["prng-key-reuse"], findings


def pytest_prng_loop_reuse_flags_and_chain_is_clean(tmp_path):
    src = """
        import jax

        def bad(rng, batches, step, state):
            for b in batches:
                state, m = step(state, b, rng)
            return state

        def good(rng, batches, step, state):
            for b in batches:
                rng, sub = jax.random.split(rng)
                state, m = step(state, b, sub)
            return state
    """
    findings = _lint(tmp_path, {"m.py": src})
    pr = [f for f in findings if f.rule == "prng-key-reuse"]
    assert len(pr) == 1 and "bad" in pr[0].message, findings


# ---- hygiene --------------------------------------------------------------


def pytest_mutable_default_and_float64(tmp_path):
    src = """
        import jax.numpy as jnp
        import numpy as np

        def bad_default(x, acc=[]):
            acc.append(x)
            return acc

        def bad_dtype(x):
            return jnp.asarray(x, dtype=jnp.float64)

        def host_accumulation_is_fine(x):
            return np.asarray(x, np.float64)

        def good_default(x, acc=None):
            return [x] if acc is None else acc + [x]
    """
    findings = _lint(tmp_path, {"m.py": src})
    assert _rules_of(findings) == ["float64-literal", "mutable-default-arg"]
    assert len(findings) == 2, findings


# ---- suppressions / baseline / CLI ---------------------------------------


def pytest_inline_suppression_same_line_and_line_above(tmp_path):
    src = """
        import jax

        def train_step(state):
            return state

        a = jax.jit(train_step)  # jaxlint: disable=missing-donate
        # jaxlint: disable=missing-donate
        b = jax.jit(train_step)
        c = jax.jit(train_step)  # jaxlint: disable
        d = jax.jit(train_step)
    """
    findings = _lint(tmp_path, {"m.py": src})
    md = [f for f in findings if f.rule == "missing-donate"]
    assert len(md) == 1, findings  # only `d` survives


def pytest_baseline_ratchets(tmp_path):
    src = """
        import jax

        def train_step(state):
            return state

        a = jax.jit(train_step)
    """
    findings = _lint(tmp_path, {"m.py": src})
    assert len(findings) == 1
    bl_path = tmp_path / "baseline.json"
    save_baseline(str(bl_path), findings)
    bl = load_baseline(str(bl_path))
    new, baselined, stale = apply_baseline(findings, bl)
    assert not new and len(baselined) == 1 and stale == 0
    # a SECOND identical finding is new — the baseline caps at its count
    new, baselined, _ = apply_baseline(findings * 2, bl)
    assert len(new) == 1 and len(baselined) == 1
    # fixing the finding leaves a stale entry the gate reports for pruning
    new, baselined, stale = apply_baseline([], bl)
    assert not new and not baselined and stale == 1


def pytest_cli_exit_codes_and_formats(tmp_path, capsys):
    bad = tmp_path / "m.py"
    bad.write_text(
        "import jax\n\ndef train_step(s):\n    return s\n\n"
        "a = jax.jit(train_step)\n"
    )
    # findings -> exit 1
    assert jaxlint_main([str(bad), "--format=json"]) == 1
    out = json.loads(capsys.readouterr().out)
    assert out["new"] and out["new"][0]["rule"] == "missing-donate"
    # github format -> workflow command annotations
    assert jaxlint_main([str(bad), "--format=github"]) == 1
    assert "::error file=" in capsys.readouterr().out
    # write baseline -> exit 0, then gate passes against it
    bl = tmp_path / "bl.json"
    assert jaxlint_main([str(bad), f"--write-baseline={bl}"]) == 0
    capsys.readouterr()
    assert jaxlint_main([str(bad), f"--baseline={bl}"]) == 0
    capsys.readouterr()
    # unknown rule -> usage error
    assert jaxlint_main([str(bad), "--select=no-such-rule"]) == 2
    capsys.readouterr()
    # --list-rules mentions every registered rule
    assert jaxlint_main(["--list-rules"]) == 0
    listed = capsys.readouterr().out
    for name in all_rules():
        assert name in listed


def pytest_select_and_ignore(tmp_path):
    files = {"m.py": "def f(x, a=[]):\n    return a\n"}
    assert _lint(tmp_path, files, select={"mutable-default-arg"})
    assert not _lint(tmp_path, files, ignore={"mutable-default-arg"})


def pytest_syntax_error_reported_not_crashed(tmp_path):
    (tmp_path / "broken.py").write_text("def f(:\n")
    result = analyze_paths([str(tmp_path)], root=str(tmp_path))
    assert result.parse_errors and not result.findings


# ---- acceptance -----------------------------------------------------------


def pytest_merged_tree_is_clean():
    """`python -m hydragnn_tpu.analysis` exits 0 on the committed tree —
    every true positive fixed or suppressed with a justification."""
    paths = [
        os.path.join(REPO_ROOT, d)
        for d in ("hydragnn_tpu", "examples", "benchmarks")
    ]
    result = analyze_paths(paths, root=REPO_ROOT)
    assert not result.findings, [
        f"{f.path}:{f.line}: {f.rule}" for f in result.findings
    ]
    assert not result.parse_errors, result.parse_errors


def pytest_reintroduced_regressions_fail_the_gate(tmp_path):
    """The ISSUE acceptance pair: a per-batch float() back in a trainer
    epoch loop, and steps.train_step without donate_argnums."""
    findings = _lint(
        tmp_path,
        {
            "train/trainer.py": _HOT_BAD,
            "train/steps.py": (
                "import jax\n\n"
                "def train_step(state, batch, rng):\n"
                "    return state\n\n"
                "compiled = jax.jit(train_step)\n"
            ),
        },
    )
    rules = _rules_of(findings)
    assert "host-sync-in-hot-loop" in rules, findings
    assert "missing-donate" in rules, findings
