"""Async/overlapped checkpointing: the save cost leaves the training
critical path without weakening any durability guarantee.

Proven here: byte-identical output vs the sync writer, submission-order
writes with rolling retention intact, the drain barrier, loud background
failures, the overlap split in ``checkpoint_saved`` events, the
measured removal of write cost from the epoch loop (flight-recorder step
timings stay flat while the same slowed write serializes the sync loop),
and a SIGKILL mid-background-write leaving the previous CRC-verified
checkpoint (and its rolling fallbacks) fully intact.
"""

import json
import os
import subprocess
import sys
import tempfile
import textwrap
import time

import numpy as np
import pytest

import jax

from hydragnn_tpu.obs import runtime as obs
from hydragnn_tpu.obs.events import validate_events
from hydragnn_tpu.train import checkpoint as ck
from hydragnn_tpu.train.checkpoint import (
    AsyncCheckpointWriter,
    load_state_dict,
    pop_train_meta,
    rolling_checkpoints,
    save_model,
)


def _state_dict_fixture(step=5):
    return {
        "params": {"w": np.arange(4, dtype=np.float32) + step},
        "batch_stats": {},
        "opt_state": {},
        "step": np.int32(step),
    }


def pytest_async_save_bytes_identical_to_sync():
    with tempfile.TemporaryDirectory() as tmp:
        meta = {"epoch": 3, "rng": np.asarray(jax.random.PRNGKey(1))}
        save_model(_state_dict_fixture(), "sync", path=tmp, train_meta=meta)
        writer = AsyncCheckpointWriter()
        try:
            save_model(
                _state_dict_fixture(), "async", path=tmp,
                train_meta=meta, writer=writer,
            )
            assert writer.drain(timeout=60)
        finally:
            writer.close()
        sync_raw = open(os.path.join(tmp, "sync", "sync.pk"), "rb").read()
        async_raw = open(os.path.join(tmp, "async", "async.pk"), "rb").read()
        assert sync_raw == async_raw
        restored = load_state_dict("async", path=tmp)
        assert int(pop_train_meta(restored)["epoch"]) == 3


def pytest_async_saves_write_in_order_with_rolling_history():
    with tempfile.TemporaryDirectory() as tmp:
        writer = AsyncCheckpointWriter()
        try:
            for ep in range(5):
                save_model(
                    _state_dict_fixture(ep), "m", path=tmp,
                    train_meta={"epoch": ep}, keep_last=3, writer=writer,
                )
            assert writer.drain(timeout=60)
        finally:
            writer.close()
        # the primary is the LAST submitted save
        restored = load_state_dict("m", path=tmp)
        assert int(pop_train_meta(restored)["epoch"]) == 4
        # rolling retention pruned to 3, newest first, monotone seq
        rolls = rolling_checkpoints("m", path=tmp)
        assert len(rolls) == 3
        metas = [
            int(pop_train_meta(ck._parse_checkpoint_bytes(
                open(p, "rb").read(), p))["epoch"])
            for p in rolls
        ]
        assert metas == [4, 3, 2]


def pytest_submit_blocks_at_max_pending():
    """Backpressure, not unbounded buffering: with max_pending writes in
    flight the next submit waits for the writer."""
    import threading

    writer = AsyncCheckpointWriter(max_pending=1)
    release = threading.Event()
    started = threading.Event()

    def slow_job():
        started.set()
        assert release.wait(timeout=30)

    try:
        writer.submit(slow_job)
        assert started.wait(timeout=10)
        # max_pending counts IN-FLIGHT snapshots (executing included),
        # not just queued ones: with one write running, the very next
        # submit must block — the executing job's host snapshot is still
        # resident, and the bound exists to cap that memory
        t0 = time.perf_counter()
        blocked = {"t": None}

        def second():
            writer.submit(lambda: None)
            blocked["t"] = time.perf_counter() - t0

        t = threading.Thread(target=second, daemon=True)
        t.start()
        time.sleep(0.2)
        assert blocked["t"] is None  # still blocked at the bound
        release.set()
        t.join(timeout=30)
        assert blocked["t"] is not None
        assert writer.drain(timeout=30)
    finally:
        writer.close()


def pytest_background_failure_is_loud():
    writer = AsyncCheckpointWriter()
    try:
        writer.submit(lambda: (_ for _ in ()).throw(OSError("disk full")))
        # drain blocks until the job finished, then surfaces its failure
        with pytest.raises(RuntimeError, match="NO newer durable"):
            writer.drain(timeout=30)
        # the failure must not leak a pending count: the writer stays
        # usable — a later submit works and a later drain terminates
        done = []
        writer.submit(lambda: done.append(1))
        assert writer.drain(timeout=30)
        assert done == [1]
    finally:
        writer.close()  # the error was consumed; close is clean


def pytest_failure_surfaces_on_submit_without_wedging():
    """An error surfaced BY submit must raise before booking the new job
    — otherwise the un-run job's pending count wedges every later
    drain."""
    writer = AsyncCheckpointWriter()
    try:
        writer.submit(lambda: (_ for _ in ()).throw(OSError("boom")))
        deadline = time.time() + 30
        while not writer._errors and time.time() < deadline:
            time.sleep(0.01)
        assert writer._errors, "background job never recorded its failure"
        with pytest.raises(RuntimeError, match="NO newer durable"):
            writer.submit(lambda: None)
        # the refused job booked nothing: drain terminates immediately
        assert writer.drain(timeout=30)
    finally:
        writer.close()


def pytest_checkpoint_saved_event_carries_overlap_split(tmp_path):
    t = obs.RunTelemetry("t", str(tmp_path))
    obs.activate(t)
    writer = AsyncCheckpointWriter()
    try:
        save_model(
            _state_dict_fixture(), "m", path=str(tmp_path),
            train_meta={"epoch": 0}, writer=writer,
        )
        assert writer.drain(timeout=60)
    finally:
        writer.close()
        obs.deactivate()
    recs = validate_events(
        str(tmp_path / "events.jsonl"), require=["checkpoint_saved"]
    )
    ev = [r for r in recs if r["event"] == "checkpoint_saved"][0]
    assert ev["async"] is True
    assert ev["snapshot_s"] >= 0 and ev["write_s"] >= 0
    assert "queued_s" in ev
    assert ev["resumable"] is True


def pytest_async_removes_write_cost_from_step_critical_path(monkeypatch):
    """The acceptance measurement: with an artificially slow serializer,
    per-'epoch' loop time with ASYNC checkpointing stays at the no-save
    baseline (the flight-recorder step timings see no stall), while the
    SAME slow save inline serializes the loop."""
    from hydragnn_tpu.obs.runtime import FlightRecorder

    delay = 0.25
    real = ck.serialization.msgpack_serialize

    def slow_serialize(sd):
        time.sleep(delay)
        return real(sd)

    monkeypatch.setattr(ck.serialization, "msgpack_serialize", slow_serialize)

    def run_epochs(writer):
        """3 fake epochs of 20ms 'steps' + one per-epoch save; returns
        (per-epoch wall times, flight recorder over steps)."""
        fr = FlightRecorder(capacity=32, stall_factor=6.0, min_fill=4)
        times = []
        with tempfile.TemporaryDirectory() as tmp:
            for ep in range(3):
                t0 = time.perf_counter()
                for _ in range(6):
                    s0 = time.perf_counter()
                    time.sleep(0.02)  # the training step
                    fr.record(time.perf_counter() - s0)
                save_model(
                    _state_dict_fixture(ep), "m", path=tmp,
                    train_meta={"epoch": ep}, writer=writer,
                )
                times.append(time.perf_counter() - t0)
            if writer is not None:
                assert writer.drain(timeout=60)
                # durability is intact once the barrier returns
                restored = load_state_dict("m", path=tmp)
                assert int(pop_train_meta(restored)["epoch"]) == 2
        return times, fr

    sync_times, _ = run_epochs(None)
    writer = AsyncCheckpointWriter()
    try:
        async_times, fr = run_epochs(writer)
    finally:
        writer.close()

    base = 6 * 0.02
    # sync epochs pay the serializer on the critical path...
    assert min(sync_times) > base + delay * 0.8, sync_times
    # ...async epochs do not (generous slack for CI noise: the whole
    # write must have left the loop, not just part of it)
    assert max(async_times) < base + delay * 0.5, async_times
    # and no step ever stalled on the background write
    assert max(fr.snapshot()) < 6.0 * np.median(fr.snapshot())


_KILL_MID_WRITE_SCRIPT = textwrap.dedent(
    """
    import os, sys, time
    sys.path.insert(0, {root!r})
    import numpy as np
    from hydragnn_tpu.train import checkpoint as ck

    tmp = sys.argv[1]
    sd = lambda step: {{
        "params": {{"w": np.arange(4, dtype=np.float32) + step}},
        "batch_stats": {{}}, "opt_state": {{}}, "step": np.int32(step),
    }}
    # one durable save first — the state a mid-write kill must preserve
    ck.save_model(sd(0), "m", path=tmp, train_meta={{"epoch": 0}},
                  keep_last=3)

    real = ck.serialization.msgpack_serialize
    def slow(x):
        # signal the parent mid-serialization, then dawdle so the
        # SIGKILL lands while this write is in flight
        open(os.path.join(tmp, "WRITING"), "w").close()
        time.sleep(30)
        return real(x)
    ck.serialization.msgpack_serialize = slow

    writer = ck.AsyncCheckpointWriter()
    ck.save_model(sd(1), "m", path=tmp, train_meta={{"epoch": 1}},
                  keep_last=3, writer=writer)
    print("SUBMITTED", flush=True)
    writer.drain(timeout=60)
    """
)


@pytest.mark.slow  # subprocess + SIGKILL choreography (~10 s)
def pytest_kill_mid_async_write_preserves_previous_checkpoint(tmp_path):
    import signal

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = tmp_path / "kill_mid_write.py"
    script.write_text(_KILL_MID_WRITE_SCRIPT.format(root=root))
    ckdir = str(tmp_path / "ck")
    os.makedirs(ckdir)
    proc = subprocess.Popen(
        [sys.executable, str(script), ckdir],
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    try:
        marker = os.path.join(ckdir, "WRITING")
        deadline = time.time() + 120
        while not os.path.exists(marker) and time.time() < deadline:
            assert proc.poll() is None, "script died before mid-write"
            time.sleep(0.02)
        assert os.path.exists(marker), "never reached the in-flight write"
        proc.send_signal(signal.SIGKILL)
        proc.wait(timeout=30)
    finally:
        if proc.poll() is None:
            proc.kill()
    # the interrupted epoch-1 write left no trace the loader trusts: the
    # epoch-0 primary still loads, CRC-verified, rolling fallback intact
    restored = load_state_dict("m", path=ckdir)
    assert int(pop_train_meta(restored)["epoch"]) == 0
    rolls = rolling_checkpoints("m", path=ckdir)
    assert len(rolls) == 1
    strict = load_state_dict("m", path=ckdir, fallback=False)
    assert int(strict["step"]) == 0


def pytest_resolve_async_writer_knobs(monkeypatch):
    from hydragnn_tpu.train.checkpoint import (
        async_checkpoint_enabled,
        resolve_async_writer,
    )

    monkeypatch.delenv("HYDRAGNN_ASYNC_CKPT", raising=False)
    assert not async_checkpoint_enabled({})
    assert resolve_async_writer({}) is None
    assert async_checkpoint_enabled({"async_checkpoint": True})
    monkeypatch.setenv("HYDRAGNN_ASYNC_CKPT", "0")
    assert not async_checkpoint_enabled({"async_checkpoint": True})
    monkeypatch.setenv("HYDRAGNN_ASYNC_CKPT", "1")
    assert async_checkpoint_enabled({})
