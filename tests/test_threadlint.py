"""threadlint (analysis --suite=concurrency): the concurrency rule suite.

Per rule: a bad snippet that must flag and a good snippet that must not,
plus the suite-selection CLI, the threadlint suppression tag, and the
acceptance regression — the merged tree runs clean against the committed
(empty) ``.threadlint-baseline.json``.

Everything here is pure-AST: no threads are started, so the whole file
runs in well under a second. The RUNTIME half of the suite
(``lock_sanitizer``, the deadlock watchdog) lives in
``tests/test_lock_sanitizer.py``.
"""

import os
import textwrap

from hydragnn_tpu.analysis import analyze_paths
from hydragnn_tpu.analysis.__main__ import main as lint_main
from hydragnn_tpu.analysis.core import all_suites, rules_in_suite

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

CONCURRENCY_RULES = {
    "lock-order-inversion",
    "blocking-under-lock",
    "thread-leak",
    "unguarded-shared-state",
    "queue-misuse",
}


def _lint(tmp_path, files, **kw):
    for rel, src in files.items():
        path = tmp_path / rel
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(textwrap.dedent(src))
    return analyze_paths([str(tmp_path)], root=str(tmp_path), **kw).findings


def _rules_of(findings):
    return sorted({f.rule for f in findings})


def pytest_suite_registry_is_partitioned():
    assert all_suites() == {"jax", "concurrency", "sharding", "numerics"}
    assert rules_in_suite("concurrency") == CONCURRENCY_RULES
    # jax suite still carries every pre-existing rule
    assert "host-sync-in-hot-loop" in rules_in_suite("jax")
    assert not rules_in_suite("jax") & CONCURRENCY_RULES
    assert not rules_in_suite("sharding") & (
        rules_in_suite("jax") | CONCURRENCY_RULES
    )
    assert not rules_in_suite("numerics") & (
        rules_in_suite("jax")
        | rules_in_suite("sharding")
        | CONCURRENCY_RULES
    )


# ---- lock-order-inversion -------------------------------------------------

_INVERSION_BAD = """
    import threading

    class Server:
        def __init__(self):
            self._queue_lock = threading.Lock()
            self._state_lock = threading.Lock()

        def submit(self):
            with self._queue_lock:
                with self._state_lock:
                    pass

        def stop(self):
            with self._state_lock:
                with self._queue_lock:
                    pass
"""

_INVERSION_GOOD = """
    import threading

    class Server:
        def __init__(self):
            self._queue_lock = threading.Lock()
            self._state_lock = threading.Lock()

        def submit(self):
            with self._queue_lock:
                with self._state_lock:
                    pass

        def stop(self):
            with self._queue_lock:
                with self._state_lock:
                    pass
"""


def pytest_lock_order_inversion_flags_cycle(tmp_path):
    findings = _lint(tmp_path, {"m.py": _INVERSION_BAD})
    li = [f for f in findings if f.rule == "lock-order-inversion"]
    assert len(li) == 1, findings
    assert "reverse order" in li[0].message


def pytest_lock_order_consistent_nesting_is_clean(tmp_path):
    findings = _lint(tmp_path, {"m.py": _INVERSION_GOOD})
    assert not [f for f in findings if f.rule == "lock-order-inversion"]


def pytest_lock_order_distinct_classes_do_not_merge(tmp_path):
    # two classes each nesting their own self-locks in opposite textual
    # orders are NOT a cycle — self.X is qualified per class
    src = """
        import threading

        class A:
            def f(self):
                with self._a_lock:
                    with self._b_lock:
                        pass

        class B:
            def g(self):
                with self._b_lock:
                    with self._a_lock:
                        pass
    """
    findings = _lint(tmp_path, {"m.py": src})
    assert not [f for f in findings if f.rule == "lock-order-inversion"]


def pytest_lock_order_transitive_cycle_flags(tmp_path):
    # a -> b in one function, b -> c and c -> a elsewhere: a 3-cycle no
    # direct-edge check would see
    src = """
        def f(a_lock, b_lock):
            with a_lock:
                with b_lock:
                    pass

        def g(b_lock, c_lock):
            with b_lock:
                with c_lock:
                    pass

        def h(c_lock, a_lock):
            with c_lock:
                with a_lock:
                    pass
    """
    findings = _lint(tmp_path, {"m.py": src})
    assert [f for f in findings if f.rule == "lock-order-inversion"]


# ---- blocking-under-lock --------------------------------------------------

_BLOCKING_BAD = """
    import queue
    import threading
    import time

    class Worker:
        def __init__(self):
            self._lock = threading.Lock()
            self._queue = queue.Queue(8)

        def tick(self, jax, batch):
            with self._lock:
                time.sleep(0.1)
                item = self._queue.get()
                out = jax.device_get(batch)
                self._event.wait()
            return out
"""

_BLOCKING_GOOD = """
    import queue
    import threading
    import time

    class Worker:
        def __init__(self):
            self._lock = threading.Lock()
            self._queue = queue.Queue(8)

        def tick(self, jax, batch):
            with self._lock:
                depth = self._depth
                item = self._queue.get_nowait()
            time.sleep(0.1)
            out = jax.device_get(batch)
            return depth, item, out
"""


def pytest_blocking_under_lock_flags_each_call(tmp_path):
    findings = _lint(tmp_path, {"m.py": _BLOCKING_BAD})
    bl = [f for f in findings if f.rule == "blocking-under-lock"]
    # sleep, queue.get, device_get, event.wait
    assert len(bl) == 4, findings


def pytest_blocking_snapshot_then_act_is_clean(tmp_path):
    findings = _lint(tmp_path, {"m.py": _BLOCKING_GOOD})
    assert not [f for f in findings if f.rule == "blocking-under-lock"]


def pytest_blocking_file_io_and_nested_lock_scoping(tmp_path):
    # file writes on a file-ish receiver flag; a nested with-lock body
    # reports against its own (innermost) lock only — one finding each
    src = """
        import threading

        class Log:
            def __init__(self):
                self._lock = threading.Lock()

            def emit(self, line):
                with self._lock:
                    self._f.write(line)

            def emit2(self, line):
                with self._lock:
                    with self._io_lock:
                        self._f.write(line)
    """
    findings = _lint(tmp_path, {"m.py": src})
    bl = [f for f in findings if f.rule == "blocking-under-lock"]
    assert len(bl) == 2, findings
    assert "_io_lock" in bl[1].message  # innermost lock named


# ---- thread-leak ----------------------------------------------------------


def pytest_thread_leak_flags_unjoined_nondaemon(tmp_path):
    src = """
        import threading

        def serve():
            t = threading.Thread(target=print)
            t.start()
            return t
    """
    findings = _lint(tmp_path, {"m.py": src})
    tl = [f for f in findings if f.rule == "thread-leak"]
    assert len(tl) == 1 and "`t`" in tl[0].message, findings


def pytest_thread_leak_join_or_daemon_is_clean(tmp_path):
    src = """
        import threading

        class S:
            def start(self):
                self._thread = threading.Thread(target=print)
                self._thread.start()
                self._backstop = threading.Thread(
                    target=print, daemon=True
                )
                self._backstop.start()

            def stop(self):
                self._thread.join(5.0)
    """
    findings = _lint(tmp_path, {"m.py": src})
    assert not [f for f in findings if f.rule == "thread-leak"], findings


def pytest_thread_leak_executor_without_shutdown(tmp_path):
    src = """
        from concurrent.futures import ThreadPoolExecutor

        def leak(items, fn):
            ex = ThreadPoolExecutor(max_workers=4)
            return [ex.submit(fn, i) for i in items]

        def fine_ctx(items, fn):
            with ThreadPoolExecutor(max_workers=4) as ex:
                return [f.result() for f in map(ex.submit, items)]

        class Pool:
            def start(self):
                self._ex = ThreadPoolExecutor(max_workers=2)

            def stop(self):
                self._ex.shutdown(wait=True)
    """
    findings = _lint(tmp_path, {"m.py": src})
    tl = [f for f in findings if f.rule == "thread-leak"]
    assert len(tl) == 1 and "shutdown" in tl[0].message, findings


# ---- unguarded-shared-state -----------------------------------------------

_UNGUARDED_BAD = """
    import threading

    class Metrics:
        def __init__(self):
            self._lock = threading.Lock()
            self.count = 0

        def record(self):
            with self._lock:
                self.count += 1

        def reset(self):
            self.count = 0
"""

_UNGUARDED_GOOD = """
    import threading

    class Metrics:
        def __init__(self):
            self._lock = threading.Lock()
            self.count = 0

        def record(self):
            with self._lock:
                self.count += 1

        def reset(self):
            with self._lock:
                self.count = 0
"""


def pytest_unguarded_shared_state_flags_lock_free_write(tmp_path):
    findings = _lint(tmp_path, {"m.py": _UNGUARDED_BAD})
    us = [f for f in findings if f.rule == "unguarded-shared-state"]
    assert len(us) == 1, findings
    assert "reset" in us[0].message and "count" in us[0].message


def pytest_unguarded_shared_state_guarded_everywhere_is_clean(tmp_path):
    findings = _lint(tmp_path, {"m.py": _UNGUARDED_GOOD})
    assert not [f for f in findings if f.rule == "unguarded-shared-state"]


def pytest_unguarded_shared_state_init_and_lockless_attrs_exempt(tmp_path):
    # __init__ constructs before sharing; attrs NEVER touched under the
    # lock are (assumed) single-thread-owned and not this rule's business
    src = """
        import threading

        class S:
            def __init__(self):
                self._lock = threading.Lock()
                self.guarded = {}
                self.private = 0

            def record(self, k, v):
                with self._lock:
                    self.guarded[k] = v

            def bookkeeping(self):
                self.private += 1
    """
    findings = _lint(tmp_path, {"m.py": src})
    assert not [f for f in findings if f.rule == "unguarded-shared-state"]


def pytest_unguarded_shared_state_mutating_method_calls_count(tmp_path):
    src = """
        import threading

        class S:
            def __init__(self):
                self._lock = threading.Lock()
                self.pending = []

            def add(self, x):
                with self._lock:
                    self.pending.append(x)

            def sweep(self):
                self.pending.clear()
    """
    findings = _lint(tmp_path, {"m.py": src})
    us = [f for f in findings if f.rule == "unguarded-shared-state"]
    assert len(us) == 1 and "sweep" in us[0].message, findings


# ---- queue-misuse ---------------------------------------------------------


def pytest_queue_misuse_unbounded_on_serving_path(tmp_path):
    src = """
        import queue

        def make():
            return queue.Queue()
    """
    findings = _lint(tmp_path, {"serve/server.py": src})
    qm = [f for f in findings if f.rule == "queue-misuse"]
    assert len(qm) == 1 and "maxsize" in qm[0].message, findings


def pytest_queue_misuse_bounded_and_off_path_clean(tmp_path):
    bounded = """
        import queue

        def make(cap):
            return queue.Queue(maxsize=cap)
    """
    unbounded_elsewhere = """
        import queue

        def make():
            return queue.Queue()
    """
    findings = _lint(
        tmp_path,
        {
            "serve/server.py": bounded,
            "postprocess/tools.py": unbounded_elsewhere,
        },
    )
    assert not [f for f in findings if f.rule == "queue-misuse"], findings


def pytest_queue_misuse_blocking_get_in_stop_path(tmp_path):
    src = """
        class S:
            def stop(self):
                while True:
                    item = self._queue.get()
                    if item is None:
                        break

            def drain_ok(self):
                self._queue.get(timeout=0.1)
                self._queue.get_nowait()
    """
    findings = _lint(tmp_path, {"serve/server.py": src})
    qm = [f for f in findings if f.rule == "queue-misuse"]
    assert len(qm) == 1 and "stop" in qm[0].message, findings


# ---- suppression / suite CLI ---------------------------------------------


def pytest_threadlint_suppression_tag(tmp_path):
    src = """
        import queue

        q1 = queue.Queue()  # threadlint: disable=queue-misuse
        # justification: test fixture, consumed synchronously below
        # threadlint: disable=queue-misuse
        q2 = queue.Queue()
        q3 = queue.Queue()
    """
    findings = _lint(tmp_path, {"serve/s.py": src})
    qm = [f for f in findings if f.rule == "queue-misuse"]
    assert len(qm) == 1, findings  # only q3 survives


def pytest_suite_cli_selects_and_rejects(tmp_path, capsys):
    bad = tmp_path / "serve" / "s.py"
    bad.parent.mkdir(parents=True)
    # one finding per suite: an unbounded queue (concurrency) and a
    # mutable default (jax)
    bad.write_text(
        "import queue\n\nq = queue.Queue()\n\n"
        "def f(x, acc=[]):\n    return acc\n"
    )
    assert lint_main([str(bad), "--suite=concurrency", "--format=json"]) == 1
    import json

    out = json.loads(capsys.readouterr().out)
    assert _rules_of_json(out) == ["queue-misuse"]
    assert lint_main([str(bad), "--suite=jax", "--format=json"]) == 1
    out = json.loads(capsys.readouterr().out)
    assert _rules_of_json(out) == ["mutable-default-arg"]
    # no suite: both
    assert lint_main([str(bad), "--format=json"]) == 1
    out = json.loads(capsys.readouterr().out)
    assert _rules_of_json(out) == ["mutable-default-arg", "queue-misuse"]
    # unknown suite is a usage error
    assert lint_main([str(bad), "--suite=nope"]) == 2
    # contradictory flag combinations that leave NO rule to run must be
    # a usage error, never a silent zero-rule "clean" run
    assert (
        lint_main([str(bad), "--suite=jax", "--select=queue-misuse"]) == 2
    )
    assert (
        lint_main(
            [
                str(bad),
                "--suite=concurrency",
                "--ignore=" + ",".join(sorted(CONCURRENCY_RULES)),
            ]
        )
        == 2
    )
    assert (
        lint_main(
            [str(bad), "--suite=jax", "--select=mutable-default-arg"]
        )
        == 1
    )
    capsys.readouterr()


def _rules_of_json(payload):
    return sorted({f["rule"] for f in payload["new"]})


# ---- acceptance -----------------------------------------------------------


def pytest_merged_tree_clean_against_committed_empty_baseline(capsys):
    """The CI gate invocation, verbatim: the committed baseline is EMPTY
    — every true positive on the tree is fixed, every intentional
    pattern suppressed with a justification."""
    import json

    baseline = os.path.join(REPO_ROOT, ".threadlint-baseline.json")
    assert os.path.exists(baseline), "commit .threadlint-baseline.json"
    with open(baseline) as f:
        payload = json.load(f)
    assert payload["findings"] == [], (
        "the threadlint baseline must stay EMPTY — fix or suppress with "
        "a justification instead of baselining"
    )
    cwd = os.getcwd()
    os.chdir(REPO_ROOT)
    try:
        rc = lint_main(
            [
                "--suite=concurrency",
                "--format=github",
                "--baseline",
                ".threadlint-baseline.json",
            ]
        )
    finally:
        os.chdir(cwd)
    out = capsys.readouterr().out
    assert rc == 0, out


def pytest_reintroduced_shutdown_hazards_fail_the_gate(tmp_path):
    """The acceptance pair for this suite: an unbounded request queue on
    the serving path, and a stop() that blocks on queue.get()."""
    findings = _lint(
        tmp_path,
        {
            "serve/server.py": (
                "import queue\n\n"
                "class Server:\n"
                "    def __init__(self):\n"
                "        self._queue = queue.Queue()\n\n"
                "    def stop(self):\n"
                "        self._queue.get()\n"
            ),
        },
    )
    qm = [f for f in findings if f.rule == "queue-misuse"]
    assert len(qm) == 2, findings
