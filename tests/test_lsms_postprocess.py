"""LSMS post-processing utilities: formation Gibbs conversion + composition
cutoff (capability parity with the reference's ``utils/lsms`` scripts)."""

import math
import os

import numpy as np

from hydragnn_tpu.postprocess.lsms import (
    compositional_histogram_cutoff,
    compute_formation_enthalpy,
    convert_raw_data_energy_to_gibbs,
    find_bin,
)


def _write_lsms(path, total_energy, species, n_cols=5):
    """One header line (total energy first token), then one row per atom."""
    rows = [
        " ".join([str(s)] + ["0.0"] * (n_cols - 1)) for s in species
    ]
    with open(path, "w") as f:
        f.write(f"{total_energy} extra header tokens\n")
        f.write("\n".join(rows) + "\n")


def _make_dataset(tmpdir):
    d = os.path.join(tmpdir, "raw")
    os.makedirs(d)
    # pure phases anchor the mixing line: per-atom energies -1.0 and -2.0
    _write_lsms(os.path.join(d, "pure_a.txt"), -4.0, [26.0] * 4)
    _write_lsms(os.path.join(d, "pure_b.txt"), -8.0, [78.0] * 4)
    # mixed: 1 Fe + 3 Pt, total -7.6 -> enthalpy = -7.6 - (0.25*-1 + 0.75*-2)*4
    _write_lsms(os.path.join(d, "mix.txt"), -7.6, [26.0, 78.0, 78.0, 78.0])
    return d


def pytest_formation_enthalpy_values():
    pure = {26.0: -1.0, 78.0: -2.0}
    atoms = np.array([[26.0, 0, 0], [78.0, 0, 0], [78.0, 0, 0], [78.0, 0, 0]])
    comp, lin, enthalpy, entropy = compute_formation_enthalpy(
        [26.0, 78.0], pure, -7.6, atoms
    )
    assert comp == 0.25
    np.testing.assert_allclose(lin, (-1.0 * 0.25 + -2.0 * 0.75) * 4)
    np.testing.assert_allclose(enthalpy, -7.6 - lin)
    # ideal mixing entropy: k_B ln C(4,1)
    np.testing.assert_allclose(
        entropy / (1.380649e-23 * 4.5874208973812e17), math.log(4.0), rtol=1e-12
    )


def pytest_gibbs_conversion_roundtrip(tmp_path):
    d = _make_dataset(str(tmp_path))
    gibbs = convert_raw_data_energy_to_gibbs(
        d, [26.0, 78.0], temperature_kelvin=0.0, create_plots=False
    )
    out = d + "_gibbs_energy/"
    assert sorted(os.listdir(out)) == ["mix.txt", "pure_a.txt", "pure_b.txt"]
    # pure phases sit ON the mixing line: formation energy 0
    with open(os.path.join(out, "pure_a.txt")) as f:
        assert float(f.readline().split()[0]) == 0.0
    # the mixed sample: -7.6 - (-7.0) = -0.6
    with open(os.path.join(out, "mix.txt")) as f:
        np.testing.assert_allclose(float(f.readline().split()[0]), -0.6)
    # atom rows preserved
    with open(os.path.join(out, "mix.txt")) as f:
        assert len(f.readlines()) == 5
    np.testing.assert_allclose(sorted(gibbs), [-0.6, 0.0, 0.0], atol=1e-12)


def pytest_histogram_cutoff(tmp_path):
    d = os.path.join(str(tmp_path), "raw")
    os.makedirs(d)
    # 5 samples at composition 0.25, 1 at 0.5
    for i in range(5):
        _write_lsms(
            os.path.join(d, f"c25_{i}.txt"), -1.0, [26.0, 78.0, 78.0, 78.0]
        )
    _write_lsms(os.path.join(d, "c50.txt"), -1.0, [26.0, 26.0, 78.0, 78.0])
    kept = compositional_histogram_cutoff(
        d, [26.0, 78.0], histogram_cutoff=3, num_bins=4, create_plots=False
    )
    out = d + "_histogram_cutoff/"
    files = sorted(os.listdir(out))
    # composition-0.25 bin capped below the cutoff; 0.5 sample kept
    assert sum(f.startswith("c25") for f in files) == 2
    assert "c50.txt" in files
    assert len(kept) == len(files)
    # symlinks resolve to the originals
    for f in files:
        assert os.path.isfile(os.path.join(out, f))


def pytest_find_bin_edges():
    assert find_bin(0.0, 4) == 3  # exact edge falls through to the last bin
    assert find_bin(0.2, 4) == 0
    assert find_bin(0.4, 4) == 1
    assert find_bin(0.99, 4) == 2
