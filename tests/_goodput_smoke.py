"""CI goodput & fleet smoke (standalone, NOT a pytest module).

Reuses the elastic 2-proc smoke machinery (``tests/_elastic_worker.py``):
2 agent-supervised CPU training processes with ``HYDRAGNN_FAULT_SLOW_STEP``
injected on ONE host (rank 0, via HYDRAGNN_FAULT_SLOW_STEP_RANK) and the
other host fault-killed mid-run, so the produced directory carries every
fleet signal at once — per-host event streams (rank 0's ``events.jsonl``
+ host 1's ``events-host1.jsonl``), heartbeat leases with step-time
digests, a ``world_resize`` recovery window, and per-epoch ``goodput``
events.

Asserts the PR's acceptance bar:

- ``goodput`` events validate against the documented schema and their
  category fractions sum to 1.0 +- 1e-6;
- ``obs fleet`` merges BOTH hosts' streams, flags the fault-slowed host
  as a straggler, and prices the world_resize recovery as lost goodput.

Usage: python tests/_goodput_smoke.py <workdir>
"""

import json
import os
import subprocess
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import _elastic_worker  # noqa: E402

# the straggler's per-step sleep. Deliberately LARGE: under CI's CPU
# contention the victim host's first compile can take >10s, and the
# slowed survivor must still be mid-run when the kill lands (2 steps/
# epoch x 8 epochs) or there is no world_resize window to price.
SLOW_S = 1.0


def main(workdir):
    os.makedirs(workdir, exist_ok=True)
    rcs = _elastic_worker.run_elastic(
        workdir,
        n_hosts=2,
        extra_env={
            # host 1 vanishes on its 8th optimizer step (epoch 3 at 2
            # steps/epoch): late enough that COMPILE-FREE goodput
            # windows (epochs 1-2, >= 3 steps — the straggler
            # baseline's qualification bar) exist for it, early enough
            # that the survivor's re-mesh recovery window is in the
            # stream
            "HYDRAGNN_FAULT_LOSE_HOST_AT_STEP": "1:7",
            # ONE host (rank 0 — the survivor) is the straggler
            "HYDRAGNN_FAULT_SLOW_STEP": f"0:@{SLOW_S}",
            "HYDRAGNN_FAULT_SLOW_STEP_RANK": "0",
        },
        timeout=300,
    )
    from hydragnn_tpu.obs.events import validate_events
    from hydragnn_tpu.utils.faults import KILL_EXIT_CODE

    assert rcs[1] == KILL_EXIT_CODE, f"killed host agent rc: {rcs}"
    assert rcs[0] == 0, f"survivor agent rc: {rcs}"

    log_dir = os.path.join(workdir, "logs", "elastic")

    # rank 0's stream: schema-valid with goodput + the resize record
    recs = validate_events(
        os.path.join(log_dir, "events.jsonl"),
        require=["goodput", "world_resize", "host_lost"],
    )
    goodput = [r for r in recs if r["event"] == "goodput"]
    for g in goodput:
        total = sum(g["fractions"].values())
        assert abs(total - 1.0) < 1e-6, (g["epoch"], total)
        assert set(g["seconds"]) >= {"compute", "data_stall", "compile",
                                     "checkpoint", "eval", "other"}
    # the straggler's own stream shows the slowdown as compute-dominated
    # step time (>= the injected sleep per step once warmed up)
    warmed = [g for g in goodput if g["steps"] and not g["seconds"]["compile"]]
    if warmed:
        per_step = warmed[-1]["step_s"] / warmed[-1]["steps"]
        assert per_step >= SLOW_S, warmed[-1]

    # host 1's per-host stream exists and validates (no run_end: the host
    # was hard-killed — a valid prefix is the contract)
    host1 = os.path.join(log_dir, "events-host1.jsonl")
    assert os.path.exists(host1), "host 1 wrote no per-host stream"
    recs1 = validate_events(host1, require=["run_manifest", "goodput"])
    assert any(r.get("host") == 1 for r in recs1
               if r["event"] == "run_manifest")

    # the fleet rollup over the whole directory
    out = subprocess.run(
        [sys.executable, "-m", "hydragnn_tpu.obs", "fleet", workdir,
         "--format", "json"],
        capture_output=True, text=True, timeout=120,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    assert out.returncode == 0, out.stderr
    fleet = json.loads(out.stdout)
    assert set(fleet["streams"]) >= {"events.jsonl", "events-host1.jsonl"}, (
        fleet["streams"]
    )
    assert "0" in fleet["hosts"] and "1" in fleet["hosts"], fleet["hosts"]
    assert fleet["stragglers"] == ["0"], (
        f"fault-slowed host not flagged: {fleet['stragglers']} "
        f"(hosts: {fleet['hosts']})"
    )
    assert len(fleet["resizes"]) >= 1, "world_resize never priced"
    assert fleet["lost_goodput_s"] > 0.0, fleet["resizes"]
    assert 0.0 < fleet["lost_goodput_fraction"] <= 1.0

    # human-readable render exercises the text path too
    text = subprocess.run(
        [sys.executable, "-m", "hydragnn_tpu.obs", "fleet", workdir],
        capture_output=True, text=True, timeout=120,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    assert text.returncode == 0 and "STRAGGLER" in text.stdout

    print(
        "goodput smoke OK: straggler host 0 flagged "
        f"(p50 {fleet['hosts']['0'].get('p50')}s vs "
        f"{fleet['hosts']['1'].get('p50')}s), "
        f"{len(goodput)} goodput events sum to 1, "
        f"recovery priced at {fleet['lost_goodput_s']}s lost goodput"
    )


if __name__ == "__main__":
    main(sys.argv[1])
