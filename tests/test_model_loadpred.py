"""Persistence: save -> reload -> identical predictions (reference
``tests/test_model_loadpred.py:18-92`` asserts reloaded-model MAE below
threshold; here we assert prediction closeness (atol 1e-6/1e-7) between the
saved and reloaded model, which is stronger)."""

import os
import tempfile

import numpy as np

import jax

from hydragnn_tpu.models import create_model_config
from hydragnn_tpu.train.checkpoint import (
    load_state_dict,
    restore_into,
    save_model,
)
from hydragnn_tpu.train.trainer import Trainer

from test_models_forward import arch_config, make_batch


def pytest_checkpoint_roundtrip():
    batch = make_batch()
    model = create_model_config(arch_config("PNA"))
    trainer = Trainer(
        model, {"Optimizer": {"type": "AdamW", "learning_rate": 1e-3}}
    )
    state = trainer.init_state(batch)
    rng = jax.random.PRNGKey(0)
    for _ in range(3):
        rng, sub = jax.random.split(rng)
        state, _ = trainer._train_step(state, trainer.put_batch(batch), sub)

    dev_batch = trainer.put_batch(batch)
    ref = trainer._eval_step(state.params, state.batch_stats, dev_batch)

    with tempfile.TemporaryDirectory() as tmp:
        save_model(state, "roundtrip", path=tmp)
        # fresh trainer + state, then restore
        trainer2 = Trainer(
            model, {"Optimizer": {"type": "AdamW", "learning_rate": 1e-3}}
        )
        state2 = trainer2.init_state(batch)
        state2 = restore_into(state2, load_state_dict("roundtrip", path=tmp))
        out = trainer2._eval_step(state2.params, state2.batch_stats, dev_batch)

    for a, b in zip(ref["outputs"], out["outputs"]):
        assert np.allclose(np.asarray(a), np.asarray(b), atol=1e-6)
    assert np.allclose(float(ref["loss"]), float(out["loss"]), atol=1e-7)
