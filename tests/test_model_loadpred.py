"""Persistence: save -> reload -> identical predictions (reference
``tests/test_model_loadpred.py:18-92`` asserts reloaded-model MAE below
threshold; here we assert prediction closeness (atol 1e-6/1e-7) between the
saved and reloaded model, which is stronger)."""

import os
import tempfile

import numpy as np

import jax

from hydragnn_tpu.models import create_model_config
from hydragnn_tpu.train.checkpoint import (
    load_state_dict,
    restore_into,
    save_model,
)
from hydragnn_tpu.train.trainer import Trainer

from test_models_forward import arch_config, make_batch


def pytest_checkpoint_roundtrip():
    batch = make_batch()
    model = create_model_config(arch_config("PNA"))
    trainer = Trainer(
        model, {"Optimizer": {"type": "AdamW", "learning_rate": 1e-3}}
    )
    state = trainer.init_state(batch)
    rng = jax.random.PRNGKey(0)
    for _ in range(3):
        rng, sub = jax.random.split(rng)
        state, _ = trainer._train_step(state, trainer.put_batch(batch), sub)

    dev_batch = trainer.put_batch(batch)
    ref = trainer._eval_step(state.params, state.batch_stats, dev_batch)

    with tempfile.TemporaryDirectory() as tmp:
        save_model(state, "roundtrip", path=tmp)
        # fresh trainer + state, then restore
        trainer2 = Trainer(
            model, {"Optimizer": {"type": "AdamW", "learning_rate": 1e-3}}
        )
        state2 = trainer2.init_state(batch)
        state2 = restore_into(state2, load_state_dict("roundtrip", path=tmp))
        out = trainer2._eval_step(state2.params, state2.batch_stats, dev_batch)

    for a, b in zip(ref["outputs"], out["outputs"]):
        assert np.allclose(np.asarray(a), np.asarray(b), atol=1e-6)
    assert np.allclose(float(ref["loss"]), float(out["loss"]), atol=1e-7)


def pytest_checkpoint_integrity_and_versioning():
    """Hardened format: corruption is detected (CRC), future versions are
    refused, legacy headerless blobs still load."""
    import pytest as _pytest

    from hydragnn_tpu.train import checkpoint as ck

    batch = make_batch()
    model = create_model_config(arch_config("SAGE"))
    trainer = Trainer(
        model, {"Optimizer": {"type": "AdamW", "learning_rate": 1e-3}}
    )
    state = trainer.init_state(batch)
    with tempfile.TemporaryDirectory() as tmp:
        save_model(state, "ck", path=tmp)
        fname = os.path.join(tmp, "ck", "ck.pk")
        raw = open(fname, "rb").read()
        assert raw[:8] == ck._MAGIC
        # no stray tmp file left behind by the atomic write
        assert not os.path.exists(fname + ".tmp")

        # flip one payload byte -> CRC mismatch
        bad = bytearray(raw)
        bad[len(raw) // 2] ^= 0xFF
        open(fname, "wb").write(bytes(bad))
        with _pytest.raises(ValueError, match="corrupt"):
            load_state_dict("ck", path=tmp)

        # future version -> refused with a clear message
        import struct as _struct

        fut = ck._MAGIC + _struct.pack("<II", 99, 0) + raw[16:]
        open(fname, "wb").write(fut)
        with _pytest.raises(ValueError, match="version"):
            load_state_dict("ck", path=tmp)

        # legacy headerless msgpack still loads
        open(fname, "wb").write(raw[16:])
        legacy = load_state_dict("ck", path=tmp)
        assert "params" in legacy


def pytest_checkpoint_restore_across_config_change():
    """Resume after the TRAINING config changed: params/batch-stats restore,
    optimizer state is rebuilt fresh (reference reloads model_state_dict and
    reconstructs the optimizer the same way)."""
    from hydragnn_tpu.train.checkpoint import restore_params_only

    batch = make_batch()
    model = create_model_config(arch_config("SAGE"))
    trainer = Trainer(
        model, {"Optimizer": {"type": "AdamW", "learning_rate": 1e-3}}
    )
    state = trainer.init_state(batch)
    rng = jax.random.PRNGKey(0)
    for _ in range(2):
        rng, sub = jax.random.split(rng)
        state, _ = trainer._train_step(state, trainer.put_batch(batch), sub)

    with tempfile.TemporaryDirectory() as tmp:
        save_model(state, "xcfg", path=tmp)
        # resume with a DIFFERENT optimizer (SGD): opt_state trees differ,
        # restore_into would fail — restore_params_only is the resume path
        trainer2 = Trainer(
            model, {"Optimizer": {"type": "SGD", "learning_rate": 1e-2}}
        )
        state2 = trainer2.init_state(batch)
        state2 = restore_params_only(state2, load_state_dict("xcfg", path=tmp))

    dev_batch = trainer.put_batch(batch)
    ref = trainer._eval_step(state.params, state.batch_stats, dev_batch)
    out = trainer2._eval_step(state2.params, state2.batch_stats, dev_batch)
    for a, b in zip(ref["outputs"], out["outputs"]):
        assert np.allclose(np.asarray(a), np.asarray(b), atol=1e-6)
    # and training continues under the new optimizer
    rng, sub = jax.random.split(rng)
    state2, metrics = trainer2._train_step(state2, dev_batch, sub)
    assert np.isfinite(float(np.asarray(metrics["loss"])))
