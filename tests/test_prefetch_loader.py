"""Prefetching GraphLoader: background-thread collation must be order- and
content-identical to the synchronous path, and must propagate errors."""

import numpy as np
import pytest

from hydragnn_tpu.data.dataobj import GraphData
from hydragnn_tpu.data.loaders import GraphLoader, compute_layout


def _dataset(n=13, seed=0):
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(n):
        k = int(rng.integers(3, 7))
        src = np.arange(k)
        dst = (src + 1) % k
        g = GraphData(
            x=rng.random((k, 2)).astype(np.float32),
            pos=rng.random((k, 3)).astype(np.float32),
            edge_index=np.stack(
                [np.concatenate([src, dst]), np.concatenate([dst, src])]
            ),
            edge_attr=None,
        )
        g.targets = [np.array([1.0], np.float32), np.zeros((k, 1), np.float32)]
        g.target_types = ["graph", "node"]
        out.append(g)
    return out


def pytest_prefetch_matches_sync():
    ds = _dataset()
    layout = compute_layout([ds], batch_size=4, need_triplets=False)
    sync = GraphLoader(ds, 4, layout, shuffle=True, prefetch=0)
    pre = GraphLoader(ds, 4, layout, shuffle=True, prefetch=3)
    sync.set_epoch(2)
    pre.set_epoch(2)
    a = list(sync)
    b = list(pre)
    assert len(a) == len(b) == len(sync)
    for ba, bb in zip(a, b):
        np.testing.assert_array_equal(np.asarray(ba.x), np.asarray(bb.x))
        np.testing.assert_array_equal(
            np.asarray(ba.senders), np.asarray(bb.senders)
        )
        np.testing.assert_array_equal(
            np.asarray(ba.targets[1]), np.asarray(bb.targets[1])
        )


def pytest_prefetch_propagates_errors():
    ds = _dataset(6)
    layout = compute_layout([ds], batch_size=3, need_triplets=False)
    loader = GraphLoader(ds, 3, layout, shuffle=False, prefetch=2)
    ds[4] = None  # poison a sample the second batch will touch
    with pytest.raises(Exception):
        list(loader)
