"""Prefetching GraphLoader: background-thread collation must be order- and
content-identical to the synchronous path, and must propagate errors."""

import numpy as np
import pytest

from hydragnn_tpu.data.dataobj import GraphData
from hydragnn_tpu.data.loaders import GraphLoader, compute_layout


def _dataset(n=13, seed=0):
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(n):
        k = int(rng.integers(3, 7))
        src = np.arange(k)
        dst = (src + 1) % k
        g = GraphData(
            x=rng.random((k, 2)).astype(np.float32),
            pos=rng.random((k, 3)).astype(np.float32),
            edge_index=np.stack(
                [np.concatenate([src, dst]), np.concatenate([dst, src])]
            ),
            edge_attr=None,
        )
        g.targets = [np.array([1.0], np.float32), np.zeros((k, 1), np.float32)]
        g.target_types = ["graph", "node"]
        out.append(g)
    return out


def pytest_prefetch_matches_sync():
    ds = _dataset()
    layout = compute_layout([ds], batch_size=4, need_triplets=False)
    sync = GraphLoader(ds, 4, layout, shuffle=True, prefetch=0)
    pre = GraphLoader(ds, 4, layout, shuffle=True, prefetch=3)
    sync.set_epoch(2)
    pre.set_epoch(2)
    a = list(sync)
    b = list(pre)
    assert len(a) == len(b) == len(sync)
    for ba, bb in zip(a, b):
        np.testing.assert_array_equal(np.asarray(ba.x), np.asarray(bb.x))
        np.testing.assert_array_equal(
            np.asarray(ba.senders), np.asarray(bb.senders)
        )
        np.testing.assert_array_equal(
            np.asarray(ba.targets[1]), np.asarray(bb.targets[1])
        )


def pytest_prefetch_propagates_errors():
    ds = _dataset(6)
    layout = compute_layout([ds], batch_size=3, need_triplets=False)
    loader = GraphLoader(ds, 3, layout, shuffle=False, prefetch=2)
    ds[4] = None  # poison a sample the second batch will touch
    with pytest.raises(Exception):
        list(loader)


def pytest_worker_error_surfaces_with_full_queue():
    """A worker exception must reach the consumer even when the bounded
    queue is FULL at failure time (the sentinel put must not wedge), and
    the worker thread must be reaped."""
    import threading
    import time

    from hydragnn_tpu.data.loaders import prefetch_iter

    def source():
        for i in range(50):  # far more items than the queue can hold
            yield i
            if i == 5:
                raise OSError("boom mid-stream")

    before = {t.name for t in threading.enumerate()}
    got = []
    with pytest.raises(OSError, match="boom"):
        it = prefetch_iter(source(), depth=2, name="errq-test")
        time.sleep(0.2)  # let the worker fill the queue and then die
        for item in it:
            got.append(item)
    assert got == list(range(6))  # everything before the failure arrived
    deadline = time.time() + 5.0
    while time.time() < deadline:
        leaked = [
            t for t in threading.enumerate()
            if t.name.startswith("errq-test") and t.name not in before
        ]
        if not leaked:
            break
        time.sleep(0.05)
    assert not leaked, f"prefetch worker thread leaked: {leaked}"


def pytest_abandoned_consumer_does_not_wedge_worker():
    """Early consumer exit (break) with a full queue: the stop-aware puts
    must let the worker shut down instead of blocking forever."""
    import threading
    import time

    from hydragnn_tpu.data.loaders import prefetch_iter

    produced = []

    def source():
        for i in range(1000):
            produced.append(i)
            yield i

    it = prefetch_iter(source(), depth=1, name="abandon-test")
    assert next(it) == 0
    it.close()  # abandon: generator finally -> stop.set() + join
    deadline = time.time() + 5.0
    while time.time() < deadline:
        alive = [
            t for t in threading.enumerate()
            if t.name.startswith("abandon-test")
        ]
        if not alive:
            break
        time.sleep(0.05)
    assert not alive, "worker still running after consumer abandoned"
    assert len(produced) < 1000  # it stopped early, not after draining all


def pytest_loader_prefetch_error_with_deep_queue():
    """GraphLoader integration: a poisoned sample mid-dataset with a
    prefetch depth smaller than the remaining batches surfaces the
    collation error and the loader remains reusable afterwards."""
    ds = _dataset(24)
    layout = compute_layout([ds], batch_size=2, need_triplets=False)
    loader = GraphLoader(ds, 2, layout, shuffle=False, prefetch=2)
    poisoned = ds[9]
    ds[9] = None
    with pytest.raises(Exception):
        list(loader)
    ds[9] = poisoned  # heal: the same loader must iterate cleanly again
    assert len(list(loader)) == len(loader)


def pytest_multi_worker_matches_sync(monkeypatch):
    """HYDRAGNN_NUM_WORKERS > 1 (the reference HydraDataLoader's worker
    pool, ``load_data.py:94-204``) must be order- and content-identical
    to the synchronous path."""
    ds = _dataset(26)
    layout = compute_layout([ds], batch_size=4, need_triplets=False)
    sync = list(GraphLoader(ds, 4, layout, shuffle=False))
    monkeypatch.setenv("HYDRAGNN_NUM_WORKERS", "3")
    pooled = list(GraphLoader(ds, 4, layout, shuffle=False))
    assert len(sync) == len(pooled)
    for ba, bb in zip(sync, pooled):
        np.testing.assert_array_equal(np.asarray(ba.x), np.asarray(bb.x))
        np.testing.assert_array_equal(
            np.asarray(ba.senders), np.asarray(bb.senders)
        )


def pytest_omp_places_parsing():
    from hydragnn_tpu.data.loaders import _parse_omp_places

    assert _parse_omp_places("{0:4},{4:4}") == [[0, 1, 2, 3], [4, 5, 6, 7]]
    assert _parse_omp_places("{0,2,4},{1,3,5}") == [[0, 2, 4], [1, 3, 5]]
    assert _parse_omp_places("{0:2:4}") == [[0, 4]]  # start:len:stride
    assert _parse_omp_places("") == []
    assert _parse_omp_places("cores") == []  # abstract names: pinning off
    assert _parse_omp_places("{bad}") == []


def pytest_affinity_pinning_is_safe_noop_here(monkeypatch):
    """With HYDRAGNN_AFFINITY=1 and OMP_PLACES set, the pinned worker pool
    still produces correct batches (on this 1-core host every place maps
    to... whatever the OS grants — pinning failures are silent no-ops)."""
    ds = _dataset(10)
    layout = compute_layout([ds], batch_size=5, need_triplets=False)
    sync = list(GraphLoader(ds, 5, layout, shuffle=False))
    monkeypatch.setenv("HYDRAGNN_NUM_WORKERS", "2")
    monkeypatch.setenv("HYDRAGNN_AFFINITY", "1")
    monkeypatch.setenv("OMP_PLACES", "{0:1},{0:1}")
    pinned = list(GraphLoader(ds, 5, layout, shuffle=False))
    assert len(sync) == len(pinned)
    for ba, bb in zip(sync, pinned):
        np.testing.assert_array_equal(np.asarray(ba.x), np.asarray(bb.x))
