"""Fault-tolerant training: preemption resume, rolling last-good
checkpoints, divergence guard, retry-on-flaky-read — all proven with
injected faults (``hydragnn_tpu/utils/faults.py``), not hope.

The e2e piece runs train -> SIGKILL-equivalent (``os._exit`` via
``HYDRAGNN_FAULT_KILL_AT_STEP``) -> resume in subprocesses through the
real epoch driver and asserts the resumed trajectory matches the
uninterrupted one exactly at the resume point AND at the end.
"""

import json
import os
import subprocess
import sys
import tempfile

import numpy as np
import pytest

import jax

from hydragnn_tpu.train import checkpoint as ck
from hydragnn_tpu.train.checkpoint import (
    load_state_dict,
    pop_train_meta,
    rolling_checkpoints,
    save_model,
)
from hydragnn_tpu.train.scheduler import (
    BestCheckpoint,
    EarlyStopping,
    ReduceLROnPlateau,
)
from hydragnn_tpu.utils import faults

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from _resilience_worker import make_samples  # noqa: E402

FAST = int(os.getenv("HYDRAGNN_FAST_TEST", "0")) == 1


def _state_dict_fixture(step=5):
    return {
        "params": {"w": np.arange(4, dtype=np.float32)},
        "batch_stats": {},
        "opt_state": {},
        "step": np.int32(step),
    }


# ---- scheduler state round trips (v2-resume prerequisite) ----------------


def pytest_plateau_scheduler_state_roundtrip():
    a = ReduceLROnPlateau(lr=1e-3, patience=1)
    for v in [1.0, 1.1, 1.2, 1.3]:
        a.step(v)
    b = ReduceLROnPlateau(lr=1e-3, patience=1)
    b.load_state_dict(a.state_dict())
    assert (b.lr, b.best, b.num_bad_epochs) == (a.lr, a.best, a.num_bad_epochs)
    # continued stepping must stay in lockstep
    for v in [1.4, 1.5, 0.1, 0.2]:
        assert a.step(v) == b.step(v)
    assert a.num_bad_epochs == b.num_bad_epochs


def pytest_early_stopping_state_roundtrip():
    a = EarlyStopping(patience=3)
    for v in [1.0, 1.1, 1.2]:
        a(v)
    b = EarlyStopping(patience=3)
    b.load_state_dict(a.state_dict())
    assert (b.best, b.counter, b.early_stop) == (a.best, a.counter, a.early_stop)
    assert a(1.3) == b(1.3)  # the next bad epoch trips both identically
    assert a.early_stop == b.early_stop


def pytest_best_checkpoint_state_roundtrip():
    saves = []
    a = BestCheckpoint("x", warmup=0)
    a({}, 0, 1.0, lambda *args: saves.append(args))
    b = BestCheckpoint("x", warmup=0)
    b.load_state_dict(a.state_dict())
    assert b.best == a.best == 1.0
    # a worse loss does not save, a better one does
    assert not b({}, 1, 2.0, lambda *args: saves.append(args))
    assert b({}, 2, 0.5, lambda *args: saves.append(args))


def pytest_fresh_state_dicts_roundtrip_none_best():
    for cls in (lambda: ReduceLROnPlateau(lr=1e-3), EarlyStopping):
        a = cls()
        b = cls()
        b.load_state_dict(a.state_dict())
        assert b.best is None


# ---- checkpoint format v2 ------------------------------------------------


def pytest_v2_train_meta_roundtrip():
    meta = {
        "format": 2,
        "epoch": 7,
        "rng": np.asarray(jax.random.PRNGKey(42)),
        "plateau": {"lr": 5e-4, "best": 0.25, "num_bad_epochs": 2},
        "early": {"best": 0.25, "counter": 1, "early_stop": False},
    }
    with tempfile.TemporaryDirectory() as tmp:
        save_model(_state_dict_fixture(), "m", path=tmp, train_meta=meta)
        restored = load_state_dict("m", path=tmp)
        got = pop_train_meta(restored)
        assert "train_meta" not in restored  # detached for restore_into
        assert int(got["epoch"]) == 7
        np.testing.assert_array_equal(
            np.asarray(got["rng"]), np.asarray(jax.random.PRNGKey(42))
        )
        assert float(got["plateau"]["lr"]) == 5e-4
        assert int(got["early"]["counter"]) == 1
        sched = ReduceLROnPlateau(lr=1.0)
        sched.load_state_dict(got["plateau"])
        assert sched.lr == 5e-4 and sched.num_bad_epochs == 2


def pytest_v1_and_legacy_checkpoints_still_load():
    """A v1 (headered, no train_meta) file and a legacy headerless blob
    both load byte-identically; resume metadata is simply absent."""
    import binascii
    import struct

    with tempfile.TemporaryDirectory() as tmp:
        sd = _state_dict_fixture()
        save_model(dict(sd), "m", path=tmp)  # no meta
        fname = os.path.join(tmp, "m", "m.pk")
        raw = open(fname, "rb").read()
        blob = raw[16:]

        # rewrite as format version 1 (what pre-resilience builds wrote)
        v1 = ck._MAGIC + struct.pack(
            "<II", 1, binascii.crc32(blob) & 0xFFFFFFFF
        ) + blob
        open(fname, "wb").write(v1)
        r1 = load_state_dict("m", path=tmp)
        assert pop_train_meta(r1) is None
        np.testing.assert_array_equal(r1["params"]["w"], sd["params"]["w"])
        assert int(r1["step"]) == 5

        # legacy headerless msgpack
        open(fname, "wb").write(blob)
        r0 = load_state_dict("m", path=tmp)
        assert pop_train_meta(r0) is None
        np.testing.assert_array_equal(r0["params"]["w"], sd["params"]["w"])


# ---- rolling retention + last-good fallback ------------------------------


def pytest_rolling_retention_prunes_to_keep_last():
    with tempfile.TemporaryDirectory() as tmp:
        for ep in range(5):
            save_model(
                _state_dict_fixture(ep), "m", path=tmp,
                train_meta={"epoch": ep}, keep_last=2,
            )
        rolls = rolling_checkpoints("m", path=tmp)
        assert len(rolls) == 2
        # newest first, carrying the two most recent epochs
        metas = [
            int(pop_train_meta(ck._parse_checkpoint_bytes(
                open(p, "rb").read(), p
            ))["epoch"])
            for p in rolls
        ]
        assert metas == [4, 3]


def pytest_corrupt_primary_falls_back_to_last_good():
    with tempfile.TemporaryDirectory() as tmp:
        for ep in range(3):
            save_model(
                _state_dict_fixture(ep), "m", path=tmp,
                train_meta={"epoch": ep}, keep_last=3,
            )
        fname = os.path.join(tmp, "m", "m.pk")
        raw = bytearray(open(fname, "rb").read())
        raw[len(raw) // 2] ^= 0xFF  # bit corruption of the primary
        open(fname, "wb").write(bytes(raw))
        # rolling copies are INDEPENDENT bytes (not hard links), so the
        # newest one still holds the corrupted save's content intact —
        # zero progress lost
        with pytest.warns(UserWarning, match="last-good"):
            restored = load_state_dict("m", path=tmp)
        assert int(pop_train_meta(restored)["epoch"]) == 2
        assert int(restored["step"]) == 2

        # strict mode (fallback off) still fails loudly
        with pytest.raises(ValueError, match="corrupt"):
            load_state_dict("m", path=tmp, fallback=False)


def pytest_truncated_primary_falls_back():
    with tempfile.TemporaryDirectory() as tmp:
        save_model(_state_dict_fixture(0), "m", path=tmp,
                   train_meta={"epoch": 0}, keep_last=3)
        save_model(_state_dict_fixture(1), "m", path=tmp,
                   train_meta={"epoch": 1}, keep_last=3)
        fname = os.path.join(tmp, "m", "m.pk")
        raw = open(fname, "rb").read()
        open(fname, "wb").write(raw[: len(raw) // 3])  # torn write
        with pytest.warns(UserWarning, match="last-good"):
            restored = load_state_dict("m", path=tmp)
        assert int(pop_train_meta(restored)["epoch"]) == 1

        # truncation INSIDE the 16-byte header must also fall back, not
        # escape as a struct error
        open(fname, "wb").write(raw[:12])
        with pytest.warns(UserWarning, match="last-good"):
            restored = load_state_dict("m", path=tmp)
        assert int(pop_train_meta(restored)["epoch"]) == 1


def pytest_all_copies_corrupt_raises():
    with tempfile.TemporaryDirectory() as tmp:
        for ep in range(2):
            save_model(_state_dict_fixture(ep), "m", path=tmp,
                       train_meta={"epoch": ep}, keep_last=2)
        targets = [os.path.join(tmp, "m", "m.pk")] + rolling_checkpoints(
            "m", path=tmp
        )
        for i, p in enumerate(targets):
            b = bytearray(open(p, "rb").read())
            b[20 + i] ^= 0xFF
            open(p, "wb").write(bytes(b))
        with pytest.raises(ValueError, match="corrupt"):
            load_state_dict("m", path=tmp)


def pytest_corrupt_checkpoint_injection(monkeypatch):
    """The ``HYDRAGNN_FAULT_CORRUPT_CHECKPOINT`` injection point: the
    selected save's primary is corrupted post-write; detection + fallback
    recover the same save's independent rolling copy."""
    faults.reset()
    monkeypatch.setenv("HYDRAGNN_FAULT_CORRUPT_CHECKPOINT", "2")
    with tempfile.TemporaryDirectory() as tmp:
        save_model(_state_dict_fixture(0), "m", path=tmp,
                   train_meta={"epoch": 0}, keep_last=3)
        save_model(_state_dict_fixture(1), "m", path=tmp,
                   train_meta={"epoch": 1}, keep_last=3)  # primary corrupted
        with pytest.warns(UserWarning, match="last-good"):
            restored = load_state_dict("m", path=tmp)
        assert int(pop_train_meta(restored)["epoch"]) == 1
    faults.reset()


# ---- retry with jittered backoff on flaky reads --------------------------


def pytest_flaky_shard_reads_are_retried(monkeypatch):
    from hydragnn_tpu.data.shard_store import ShardDataset, ShardWriter

    samples = make_samples(4)
    with tempfile.TemporaryDirectory() as tmp:
        label = os.path.join(tmp, "trainset")
        w = ShardWriter(label)
        w.add(samples)
        w.save()
        monkeypatch.setenv("HYDRAGNN_IO_RETRY_BASE_S", "0.001")
        monkeypatch.setenv("HYDRAGNN_FAULT_FLAKY_READ", "2")
        faults.reset()
        ds = ShardDataset(label)  # meta read retries through the failures
        got = ds[2]
        np.testing.assert_allclose(np.asarray(got.x), samples[2].x)
        faults.reset()


def pytest_flaky_pickle_reads_are_retried(monkeypatch):
    from hydragnn_tpu.data.pickledataset import (
        SimplePickleDataset,
        SimplePickleWriter,
    )

    samples = make_samples(3)
    with tempfile.TemporaryDirectory() as tmp:
        SimplePickleWriter(list(samples), tmp, label="t")
        monkeypatch.setenv("HYDRAGNN_IO_RETRY_BASE_S", "0.001")
        monkeypatch.setenv("HYDRAGNN_FAULT_FLAKY_READ", "2")
        faults.reset()
        ds = SimplePickleDataset(tmp, label="t")
        got = ds[1]
        np.testing.assert_allclose(np.asarray(got.x), samples[1].x)
        faults.reset()


def pytest_retry_gives_up_after_budget(monkeypatch):
    from hydragnn_tpu.utils.retry import retry_io

    monkeypatch.setenv("HYDRAGNN_FAULT_FLAKY_READ", "10")
    faults.reset()
    attempts = []

    def read():
        attempts.append(1)
        faults.flaky_read("t")
        return 1

    with pytest.raises(OSError, match="injected"):
        retry_io(read, attempts=3, base_delay=0.001)
    assert len(attempts) == 3  # bounded, not infinite
    faults.reset()


def pytest_missing_file_is_not_retried():
    from hydragnn_tpu.utils.retry import retry_io

    attempts = []

    def read():
        attempts.append(1)
        raise FileNotFoundError("gone")

    with pytest.raises(FileNotFoundError):
        retry_io(read, attempts=5, base_delay=0.001)
    assert len(attempts) == 1  # a wrong path is not transient


# ---- divergence guard ----------------------------------------------------


def _tiny_trainer(training_extra=None):
    from hydragnn_tpu.data.loaders import GraphLoader, compute_layout
    from hydragnn_tpu.models.create import create_model_config
    from hydragnn_tpu.train.trainer import Trainer

    arch = {
        "model_type": "GIN",
        "input_dim": 1,
        "hidden_dim": 8,
        "num_conv_layers": 2,
        "output_dim": [1, 1],
        "output_type": ["graph", "node"],
        "output_heads": {
            "graph": {
                "num_sharedlayers": 1,
                "dim_sharedlayers": 8,
                "num_headlayers": 1,
                "dim_headlayers": [8],
            },
            "node": {
                "num_headlayers": 1,
                "dim_headlayers": [8],
                "type": "mlp",
            },
        },
        "task_weights": [1.0, 1.0],
    }
    training = {"Optimizer": {"type": "AdamW", "learning_rate": 1e-2}}
    training.update(training_extra or {})
    samples = make_samples(16)
    layout = compute_layout([samples], batch_size=4, need_triplets=False)
    loader = GraphLoader(samples, 4, layout, shuffle=False)
    trainer = Trainer(create_model_config(arch), training)
    state = trainer.init_state(next(iter(loader)), seed=0)
    return trainer, state, loader


def pytest_nan_step_is_skipped_and_training_converges(monkeypatch):
    monkeypatch.setenv("HYDRAGNN_DIVERGENCE_GUARD", "1")
    monkeypatch.setenv("HYDRAGNN_FAULT_NAN_AT_STEP", "2")
    trainer, state, loader = _tiny_trainer()
    rng = jax.random.PRNGKey(0)
    losses = []
    for epoch in range(4):
        loader.set_epoch(epoch)
        state, rng, loss, _ = trainer.train_epoch(state, loader, rng)
        losses.append(loss)
    assert trainer.guard.skipped == 1 and trainer.guard.restores == 0
    assert all(np.isfinite(l) for l in losses)
    # params stayed finite and training still converges on the synthetic set
    for leaf in jax.tree_util.tree_leaves(jax.device_get(state.params)):
        assert np.isfinite(np.asarray(leaf)).all()
    assert losses[-1] < losses[0]


def pytest_consecutive_bad_steps_restore_with_halved_lr(monkeypatch):
    from hydragnn_tpu.train.optimizer import get_learning_rate

    monkeypatch.setenv("HYDRAGNN_DIVERGENCE_GUARD", "1")
    # guard_max_bad_steps default 3: steps 0-2 poisoned -> one restore
    monkeypatch.setenv("HYDRAGNN_FAULT_NAN_AT_STEP", "0:3")
    trainer, state, loader = _tiny_trainer()
    state, _, loss, _ = trainer.train_epoch(
        state, loader, jax.random.PRNGKey(0)
    )
    assert trainer.guard.skipped == 3
    assert trainer.guard.restores == 1
    assert abs(get_learning_rate(state.opt_state) - 5e-3) < 1e-9
    assert np.isfinite(loss)  # the post-restore steps trained normally


def pytest_unbounded_divergence_fails_loudly(monkeypatch):
    monkeypatch.setenv("HYDRAGNN_DIVERGENCE_GUARD", "1")
    monkeypatch.setenv("HYDRAGNN_FAULT_NAN_AT_STEP", "0:")  # every step
    monkeypatch.setenv("HYDRAGNN_GUARD_MAX_RESTORES", "1")
    trainer, state, loader = _tiny_trainer()
    rng = jax.random.PRNGKey(0)
    with pytest.raises(RuntimeError, match="divergence guard"):
        for epoch in range(4):
            loader.set_epoch(epoch)
            state, rng, *_ = trainer.train_epoch(state, loader, rng)


def pytest_guard_off_means_no_finite_metric():
    """Without the guard the compiled step must NOT pay for the all-grads
    finiteness reduction."""
    trainer, state, loader = _tiny_trainer()
    batch = trainer.put_batch(next(iter(loader)))
    _, metrics = trainer._train_step(state, batch, jax.random.PRNGKey(0))
    assert "finite" not in metrics
    assert trainer.guard is None


# ---- kill -> resume e2e --------------------------------------------------


def _run_worker(workdir, mode, extra_env=None):
    env = {
        k: v
        for k, v in os.environ.items()
        if not k.startswith(("HYDRAGNN_FAULT_", "HYDRAGNN_RESUME",
                             "HYDRAGNN_CKPT_", "HYDRAGNN_GUARD_"))
    }
    env.update(extra_env or {})
    worker = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "_resilience_worker.py"
    )
    return subprocess.run(
        [sys.executable, worker, workdir, mode],
        env=env,
        capture_output=True,
        text=True,
        timeout=420,
    )


def _meta_of(path_pk):
    return pop_train_meta(
        ck._parse_checkpoint_bytes(open(path_pk, "rb").read(), path_pk)
    )


@pytest.mark.skipif(FAST, reason="subprocess e2e — full tier only")
def pytest_kill_and_resume_matches_uninterrupted_run():
    """Preemption e2e: a run hard-killed mid-epoch-2 resumes from the
    epoch-1 checkpoint, trains ONLY the remaining epochs, and lands on the
    uninterrupted run's exact trajectory — restored epoch, LR and
    scheduler counters match at the resume point, final parameters match
    at the end."""
    with tempfile.TemporaryDirectory() as killdir, \
            tempfile.TemporaryDirectory() as refdir:
        # uninterrupted reference (same seeds, same data)
        ref = _run_worker(refdir, "run")
        assert ref.returncode == 0, ref.stderr[-2000:]

        # 4 steps/epoch; killing at step 9 is mid-epoch-2 — epochs 0 and 1
        # are checkpointed, epoch 2's partial progress is lost by design
        killed = _run_worker(
            killdir, "run", {"HYDRAGNN_FAULT_KILL_AT_STEP": "9"}
        )
        assert killed.returncode == faults.KILL_EXIT_CODE, (
            killed.returncode, killed.stderr[-2000:]
        )
        assert not os.path.exists(os.path.join(killdir, "result.json"))

        # the surviving checkpoint is epoch 1, with loop state
        kmeta = _meta_of(os.path.join(killdir, "logs", "resil", "resil.pk"))
        assert int(kmeta["epoch"]) == 1

        # ...and it matches the uninterrupted run's state at that epoch
        # (recorded in its rolling history)
        ref_roll = {
            int(_meta_of(p)["epoch"]): p
            for p in rolling_checkpoints(
                "resil", path=os.path.join(refdir, "logs")
            )
        }
        rmeta = _meta_of(ref_roll[1])
        np.testing.assert_array_equal(
            np.asarray(kmeta["rng"]), np.asarray(rmeta["rng"])
        )
        for key in ("lr", "best", "num_bad_epochs"):
            assert float(kmeta["plateau"][key]) == float(
                rmeta["plateau"][key]
            ), key

        resumed = _run_worker(killdir, "resume")
        assert resumed.returncode == 0, resumed.stderr[-2000:]
        got = json.load(open(os.path.join(killdir, "result.json")))
        ref_res = json.load(open(os.path.join(refdir, "result.json")))

        # resumed at the exact epoch; trained the REMAINING epochs only
        assert got["resumed_from_epoch"] == 2
        assert got["epochs_run"] == [2, 3, 4]
        assert ref_res["epochs_run"] == [0, 1, 2, 3, 4]

        # ...onto the identical trajectory
        assert got["final_lr"] == ref_res["final_lr"]
        np.testing.assert_allclose(
            got["final_params_digest"],
            ref_res["final_params_digest"],
            rtol=0,
            atol=1e-7,
        )
