"""shardlint's compiled-HLO ratchet (analysis/hlo.py) + sharding sentinel.

Text-level: fingerprint parsing (per-op collectives via the generalized
``parallel/collectives.parse_collectives``, host-transfer and bf16->f32
convert counting), budget save/load/check semantics (new collective,
byte growth vs tolerance, host-transfer regression, stale notes), and
the injection regression — a synthetic all-gather appended to a
program's HLO MUST fail the check with a diff naming the program, the
collective and the bytes.

Runtime: :func:`~hydragnn_tpu.analysis.guards.sharding_sentinel` against
really-placed arrays on the 8-device CPU mesh, and one compiled e2e —
two real step programs fingerprinted, budgeted, checked clean, then
caught regressing.
"""

import json

import pytest

from hydragnn_tpu.analysis.hlo import (
    INJECTED_ALL_GATHER,
    check_fingerprints,
    count_bf16_upcasts,
    count_host_transfers,
    fingerprint_hlo,
    load_budget,
    prove_injection,
    save_budget,
)

AXES = ("data", "model")
SHAPE = (4, 2)

# a hand-written optimized-HLO module exercising both replica-group
# spellings, both convert spellings and a host transfer
_HLO = """\
HloModule canonical_test

ENTRY main {
  %p0 = f32[32,16]{1,0} parameter(0)
  %h = bf16[8]{0} parameter(1)
  %h2 = bf16[4]{0} parameter(2)
  %tok = token[] after-all()
  %ar = f32[32,16]{1,0} all-reduce(f32[32,16]{1,0} %p0), replica_groups={{0,2,4,6},{1,3,5,7}}, to_apply=%add
  %ag = f32[64,16]{1,0} all-gather(f32[32,16]{1,0} %p0), replica_groups=[4,2]<=[8], dimensions={0}
  %c1 = f32[8]{0} convert(bf16[8]{0} %h)
  %c2 = f32[4]{0} convert(%h2)
  %c3 = f32[4]{0} convert(%c2)
  %of = token[] outfeed(f32[32,16]{1,0} %p0, token[] %tok)
}
"""


def pytest_parse_collectives_per_op_records():
    from hydragnn_tpu.parallel.collectives import (
        collective_bytes_by_axis,
        parse_collectives,
    )

    recs = parse_collectives(_HLO, AXES, SHAPE)
    assert {(r["op"], r["axis"], r["bytes"]) for r in recs} == {
        # {{0,2,4,6},{1,3,5,7}}: stride-2 groups on a (4,2) mesh = data
        ("all-reduce", "data", 32 * 16 * 4.0),
        # iota [4,2]<=[8]: consecutive pairs = model
        ("all-gather", "model", 64 * 16 * 4.0),
    }
    # the summed view is the same records aggregated — the two APIs
    # cannot drift
    totals = collective_bytes_by_axis(_HLO, AXES, SHAPE)
    assert totals == {"data": 2048.0, "model": 4096.0}


def pytest_host_transfer_and_upcast_counting():
    assert count_host_transfers(_HLO) == 1  # the outfeed
    assert count_host_transfers("  %x = f32[2]{0} add(%a, %b)\n") == 0
    # send marked as host transfer counts too
    assert (
        count_host_transfers(
            '  %s = (f32[2],token[]) send(%a,%tok), is_host_transfer=true\n'
        )
        == 1
    )
    # c1 (inline bf16 operand) + c2 (resolved through the def table);
    # c3 converts an f32 — not an upcast
    assert count_bf16_upcasts(_HLO) == 2


def pytest_fingerprint_aggregates_by_op_and_axis():
    fp = fingerprint_hlo(_HLO + _HLO, AXES, SHAPE)  # duplicated module
    assert fp["collectives"] == [
        {"op": "all-gather", "axis": "model", "bytes": 2 * 4096},
        {"op": "all-reduce", "axis": "data", "bytes": 2 * 2048},
    ]
    assert fp["host_transfers"] == 2
    assert fp["bf16_to_f32_converts"] == 4


def pytest_budget_roundtrip_and_version_gate(tmp_path):
    fp = fingerprint_hlo(_HLO, AXES, SHAPE)
    path = tmp_path / "budget.json"
    save_budget(str(path), {"train_step": fp}, AXES, SHAPE, tolerance=0.5)
    budget = load_budget(str(path))
    assert budget["programs"]["train_step"] == fp
    assert budget["mesh"] == {"axes": ["data", "model"], "shape": [4, 2]}
    assert budget["tolerance"] == 0.5
    bad = json.loads(path.read_text())
    bad["version"] = 99
    path.write_text(json.dumps(bad))
    with pytest.raises(ValueError, match="version"):
        load_budget(str(path))


def pytest_check_semantics():
    base = fingerprint_hlo(_HLO, AXES, SHAPE)
    budget = {"train_step": base}

    # identical -> clean
    v, n = check_fingerprints({"train_step": base}, budget)
    assert not v and not n

    # byte growth within tolerance -> clean; beyond -> violation naming
    # program, collective and bytes
    grown = json.loads(json.dumps(base))
    grown["collectives"][1]["bytes"] = int(2048 * 1.2)
    v, _ = check_fingerprints({"train_step": grown}, budget, tolerance=0.25)
    assert not v
    grown["collectives"][1]["bytes"] = int(2048 * 1.3)
    v, _ = check_fingerprints({"train_step": grown}, budget, tolerance=0.25)
    assert len(v) == 1 and "train_step" in v[0] and "all-reduce@data" in v[0]
    assert "2048" in v[0]

    # a NEW (op, axis) pair -> violation even at zero byte growth
    extra = json.loads(json.dumps(base))
    extra["collectives"].append(
        {"op": "reduce-scatter", "axis": "model", "bytes": 8}
    )
    v, _ = check_fingerprints({"train_step": extra}, budget)
    assert len(v) == 1 and "NEW collective reduce-scatter" in v[0]

    # host transfers / upcasts above budget -> violations
    hot = json.loads(json.dumps(base))
    hot["host_transfers"] += 1
    hot["bf16_to_f32_converts"] += 1
    v, _ = check_fingerprints({"train_step": hot}, budget)
    assert len(v) == 2 and any("host-transfer" in x for x in v)

    # an unbudgeted program -> violation; a stale budgeted one -> note
    v, n = check_fingerprints({"new_prog": base}, budget)
    assert any("new_prog" in x for x in v)
    assert any("train_step" in x and "stale" in x for x in n)

    # a disappeared collective is a tightening note, not a failure
    shrunk = json.loads(json.dumps(base))
    shrunk["collectives"] = shrunk["collectives"][:1]
    v, n = check_fingerprints({"train_step": shrunk}, budget)
    assert not v and len(n) == 1 and "no longer emitted" in n[0]


def pytest_injection_is_caught():
    """The reintroduction regression: an implicit-resharding all-gather
    appended to a budgeted program MUST fail the check."""
    base = fingerprint_hlo(_HLO, AXES, SHAPE)
    budget = {"train_step": base}
    doctored = fingerprint_hlo(_HLO + INJECTED_ALL_GATHER, AXES, SHAPE)
    v, _ = check_fingerprints({"train_step": doctored}, budget)
    assert v and "all-gather" in v[0] and "global" in v[0], v
    # and the CLI's self-proof helper agrees
    assert prove_injection(
        {"train_step": _HLO}, budget, AXES, SHAPE, tolerance=0.25
    )


def pytest_jit_replicated_respects_explicit_contracts():
    """jit_replicated must not override a caller-declared contract even
    when the value is falsy (out_shardings=None is jit's explicit
    'infer from inputs'; an empty PartitionSpec is a falsy tuple)."""
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from hydragnn_tpu.parallel.mesh import (
        jit_replicated,
        make_mesh2d,
        set_active_mesh,
    )

    mesh = make_mesh2d(2, 2)
    set_active_mesh(mesh)
    try:
        x = jnp.zeros((8, 8))
        # no contract given: replicated outputs on the active mesh
        out = jit_replicated(lambda a: a * 2)(x)
        assert tuple(out.sharding.spec) == ()
        assert getattr(out.sharding, "mesh", None) is not None
        # explicit falsy contracts are preserved, not overridden
        out = jit_replicated(lambda a: a * 2, out_shardings=None)(x)
        assert out.shape == (8, 8)
        sharded = jit_replicated(
            lambda a: a, out_shardings=NamedSharding(mesh, P("data"))
        )(x)
        assert tuple(sharded.sharding.spec) == ("data",)
    finally:
        set_active_mesh(None)


# ---- sharding sentinel (runtime) ------------------------------------------


def pytest_sharding_sentinel_checks_landed_placement():
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from hydragnn_tpu.analysis.guards import (
        ShardingSentinel,
        ShardingViolation,
        sharding_sentinel,
        tree_sharding_mismatches,
    )
    from hydragnn_tpu.parallel.mesh import make_mesh2d

    mesh = make_mesh2d(2, 2)
    sharded = jax.device_put(
        jnp.zeros((8, 8)), NamedSharding(mesh, P("data"))
    )
    replicated = jax.device_put(jnp.zeros((8, 8)), NamedSharding(mesh, P()))
    tree = {"w": sharded, "b": replicated}

    # declared == landed -> clean (P('data') vs P('data', None) equal)
    want = {
        "w": NamedSharding(mesh, P("data", None)),
        "b": NamedSharding(mesh, P()),
    }
    assert not tree_sharding_mismatches(tree, want)
    ShardingSentinel().check(tree, want)

    # a leaf landed off its declaration -> violation naming the path
    want_bad = {"w": NamedSharding(mesh, P()), "b": P("model")}
    mism = tree_sharding_mismatches(tree, want_bad)
    assert len(mism) == 2
    with pytest.raises(ShardingViolation, match=r"\['w'\]"):
        ShardingSentinel().check(tree, want_bad, what="step outputs")

    # deferred context form collects everything, raises at exit
    with pytest.raises(ShardingViolation, match="2 output"):
        with sharding_sentinel() as sen:
            sen.check(tree, want_bad, defer=True)

    # None expectations and host leaves are skipped
    assert not tree_sharding_mismatches(
        {"w": sharded, "host": 3.0}, {"w": None, "host": P("data")}
    )


# ---- compiled e2e (two real programs) -------------------------------------


def pytest_compiled_programs_fingerprint_and_ratchet(tmp_path):
    """Compile train_step + eval_step on a real 2x2 mesh, budget them,
    check clean, then prove the injected all-gather fails — the CI
    ratchet smoke in miniature."""
    from hydragnn_tpu.analysis.hlo import (
        compile_step_programs,
        run_sharding_sentinel,
    )
    from hydragnn_tpu.parallel.mesh import active_mesh

    prev = active_mesh()
    texts, axes, shape, context = compile_step_programs(
        (2, 2), programs=("train_step", "eval_step")
    )
    assert active_mesh() is prev  # harness mesh did not leak
    assert axes == ("data", "model") and shape == (2, 2)
    current = {
        name: fingerprint_hlo(t, axes, shape) for name, t in texts.items()
    }
    # a 2-D-sharded train step MUST communicate: gradients all-reduce
    # over data, activations/params over model
    assert current["train_step"]["collectives"], current["train_step"]
    axes_seen = {c["axis"] for c in current["train_step"]["collectives"]}
    assert "data" in axes_seen and "model" in axes_seen
    assert current["train_step"]["host_transfers"] == 0

    path = tmp_path / "hlo.json"
    save_budget(str(path), current, axes, shape)
    budget = load_budget(str(path))
    v, n = check_fingerprints(current, budget["programs"])
    assert not v and not n
    assert prove_injection(
        texts, budget["programs"], axes, shape, tolerance=0.25
    )
    # the runtime half: outputs really land at the declared shardings
    run_sharding_sentinel(context)
