"""Forward/init smoke tests: every stack builds, runs, and yields finite
outputs and losses on a padded random batch (single-head graph + node)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from hydragnn_tpu.graph import GraphBatch, collate_graphs, pad_sizes_for
from hydragnn_tpu.models import (
    MODEL_TYPES,
    compute_triplets,
    create_model_config,
    init_model_params,
)


class FakeData:
    def __init__(self, rng, n):
        self.x = rng.random((n, 1)).astype(np.float32)
        self.pos = rng.random((n, 3)).astype(np.float32)
        # ring graph, both directions
        src = np.arange(n)
        dst = (src + 1) % n
        self.edge_index = np.stack(
            [np.concatenate([src, dst]), np.concatenate([dst, src])]
        ).astype(np.int64)
        d = np.linalg.norm(
            self.pos[self.edge_index[0]] - self.pos[self.edge_index[1]], axis=1
        )
        self.edge_attr = d[:, None].astype(np.float32)
        self.targets = [
            np.array([self.x.sum()], dtype=np.float32),  # graph head
            self.x.astype(np.float32),  # node head
        ]


def make_batch(num_graphs=3, max_n=6, with_triplets=False):
    rng = np.random.default_rng(0)
    samples = [FakeData(rng, rng.integers(3, max_n + 1)) for _ in range(num_graphs)]
    n_pad, e_pad, g_pad = pad_sizes_for(
        max_n, 2 * max_n, num_graphs, graph_multiple=8
    )
    batch = collate_graphs(
        samples,
        n_pad,
        e_pad,
        g_pad,
        head_types=("graph", "node"),
        head_dims=(1, 1),
    )
    if with_triplets:
        t_pad = 8 * e_pad
        ti = np.full((t_pad,), n_pad - 1, np.int32)
        tj = np.full((t_pad,), n_pad - 1, np.int32)
        tk = np.full((t_pad,), n_pad - 1, np.int32)
        tkj = np.zeros((t_pad,), np.int32)
        tji = np.zeros((t_pad,), np.int32)
        tmask = np.zeros((t_pad,), bool)
        off_n = 0
        off_e = 0
        off_t = 0
        for s in samples:
            a, b, c, kj, ji = compute_triplets(s.edge_index, s.x.shape[0])
            t = a.shape[0]
            ti[off_t : off_t + t] = a + off_n
            tj[off_t : off_t + t] = b + off_n
            tk[off_t : off_t + t] = c + off_n
            tkj[off_t : off_t + t] = kj + off_e
            tji[off_t : off_t + t] = ji + off_e
            tmask[off_t : off_t + t] = True
            off_t += t
            off_n += s.x.shape[0]
            off_e += s.edge_index.shape[1]
        batch = batch.replace(
            extras={
                "trip_i": ti,
                "trip_j": tj,
                "trip_k": tk,
                "trip_kj": tkj,
                "trip_ji": tji,
                "trip_mask": tmask,
            }
        )
    return jax.tree_util.tree_map(jnp.asarray, batch)


def arch_config(model_type):
    cfg = {
        "model_type": model_type,
        "input_dim": 1,
        "hidden_dim": 8,
        "output_dim": [1, 1],
        "output_type": ["graph", "node"],
        "output_heads": {
            "graph": {
                "num_sharedlayers": 2,
                "dim_sharedlayers": 4,
                "num_headlayers": 2,
                "dim_headlayers": [10, 10],
            },
            "node": {"num_headlayers": 2, "dim_headlayers": [4, 4], "type": "mlp"},
        },
        "task_weights": [1.0, 1.0],
        "num_conv_layers": 2,
        "num_nodes": 6,
        "max_neighbours": 10,
        "edge_dim": None,
        "pna_deg": [0, 2, 10, 4],
        "num_gaussians": 50,
        "num_filters": 16,
        "radius": 2.0,
        "basis_emb_size": 8,
        "envelope_exponent": 5,
        "int_emb_size": 16,
        "out_emb_size": 16,
        "num_after_skip": 2,
        "num_before_skip": 1,
        "num_radial": 6,
        "num_spherical": 7,
        "equivariance": False,
    }
    return cfg


@pytest.mark.parametrize("model_type", MODEL_TYPES)
def pytest_forward_finite(model_type):
    batch = make_batch(with_triplets=(model_type == "DimeNet"))
    model = create_model_config(arch_config(model_type))
    variables = init_model_params(model, batch)
    outputs, _ = model.apply(
        variables,
        batch,
        train=True,
        mutable=["batch_stats"],
        rngs={"dropout": jax.random.PRNGKey(2)},
    )
    assert len(outputs) == 2
    assert outputs[0].shape == (batch.num_graphs, 1)
    assert outputs[1].shape == (batch.num_nodes, 1)
    tot, tasks = model.loss(outputs, batch)
    assert jnp.isfinite(tot), f"{model_type} loss not finite"
    for t in tasks:
        assert jnp.isfinite(t)


@pytest.mark.parametrize("model_type", ["SchNet", "EGNN"])
def pytest_equivariant_forward(model_type):
    batch = make_batch()
    cfg = arch_config(model_type)
    cfg["equivariance"] = True
    model = create_model_config(cfg)
    variables = init_model_params(model, batch)
    outputs = model.apply(variables, batch, train=False)
    tot, _ = model.loss(outputs, batch)
    assert jnp.isfinite(tot)


def pytest_egnn_fused_edge_mlp_matches_concat():
    """The E_GCL algebraic edge-MLP fusion (node-axis projections of the
    first Linear) must reproduce the naive concat formulation exactly
    (same parameters, same math — only float contraction order differs)."""
    from hydragnn_tpu.graph import segment_sum
    from hydragnn_tpu.models.egnn import E_GCL, _safe_sqrt

    batch = make_batch()
    x, pos = batch.x, batch.pos
    conv = E_GCL(
        in_dim=1, out_dim=8, hidden_dim=8, edge_attr_dim=1, equivariant=True
    )
    variables = conv.init(jax.random.PRNGKey(3), x, pos, batch)
    h_fused, pos_fused = conv.apply(variables, x, pos, batch)

    p = variables["params"]
    row, col = batch.senders, batch.receivers
    n = x.shape[0]
    coord_diff = pos[row] - pos[col]
    radial = (coord_diff * coord_diff).sum(-1, keepdims=True)
    coord_diff = coord_diff / (_safe_sqrt(radial) + 1.0)
    parts = jnp.concatenate([x[row], x[col], radial, batch.edge_attr], axis=-1)
    e = jax.nn.relu(parts @ p["edge_mlp_0"]["kernel"] + p["edge_mlp_0"]["bias"])
    e = jax.nn.relu(e @ p["edge_mlp_1"]["kernel"] + p["edge_mlp_1"]["bias"])
    e = jnp.where(batch.edge_mask[:, None], e, 0.0)
    cw = jax.nn.relu(e @ p["coord_mlp_0"]["kernel"] + p["coord_mlp_0"]["bias"])
    cw = jnp.tanh(cw @ p["coord_mlp_1"])
    trans = jnp.clip(coord_diff * cw, -100.0, 100.0)
    trans = jnp.where(batch.edge_mask[:, None], trans, 0.0)
    agg = segment_sum(e, row, n)
    coord_agg = segment_sum(trans, row, n)
    cnt = segment_sum(batch.edge_mask.astype(trans.dtype), row, n)
    pos_naive = pos + coord_agg / jnp.maximum(cnt, 1.0)[:, None]
    h = jnp.concatenate([x, agg], axis=-1)
    h = jax.nn.relu(h @ p["node_mlp_0"]["kernel"] + p["node_mlp_0"]["bias"])
    h_naive = h @ p["node_mlp_1"]["kernel"] + p["node_mlp_1"]["bias"]

    np.testing.assert_allclose(h_fused, h_naive, atol=2e-5, rtol=1e-5)
    np.testing.assert_allclose(pos_fused, pos_naive, atol=2e-5, rtol=1e-5)


def pytest_egnn_fused_dense_edge_attr_matches_segment():
    """The dense-frame E_GCL fusion with edge attributes (the
    project-then-gather edge-attr branch) must agree with the segment path
    on the same parameters — covers the ('EGNN', edge_attr) combination no
    other test exercises."""
    from hydragnn_tpu.models.egnn import E_GCL
    from hydragnn_tpu.ops.dense_agg import attach_neighbor_lists

    batch = make_batch()
    x, pos = batch.x, batch.pos
    conv = E_GCL(
        in_dim=1, out_dim=8, hidden_dim=8, edge_attr_dim=1, equivariant=True
    )
    variables = conv.init(jax.random.PRNGKey(5), x, pos, batch)
    h_seg, pos_seg = conv.apply(variables, x, pos, batch)
    dense_batch = attach_neighbor_lists(batch)
    h_dense, pos_dense = conv.apply(variables, x, pos, dense_batch)
    np.testing.assert_allclose(h_dense, h_seg, atol=2e-5, rtol=1e-5)
    np.testing.assert_allclose(pos_dense, pos_seg, atol=2e-5, rtol=1e-5)
