"""CI elastic kill-and-rejoin smoke (standalone, NOT a pytest module).

The bounded-wall-time version of the e2e in ``tests/test_elastic.py``:
2 agent-supervised CPU processes, one fault-killed mid-epoch, survivor
re-meshes to world 1 and finishes; the produced event stream is validated
against the documented schema, the measured recovery time is printed, and
the post-resize trajectory is checked bitwise against a clean 1-process
restart from the same rolling checkpoint.

Usage: python tests/_elastic_smoke.py <workdir>
"""

import json
import os
import subprocess
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import _elastic_worker  # noqa: E402


def main(workdir):
    os.makedirs(workdir, exist_ok=True)
    rcs = _elastic_worker.run_elastic(
        workdir,
        n_hosts=2,
        extra_env={
            "HYDRAGNN_FAULT_LOSE_HOST_AT_STEP": "1:3",
            "HYDRAGNN_FAULT_SLOW_STEP": "0:@0.3",
        },
        timeout=240,
    )
    from hydragnn_tpu.obs.events import validate_events
    from hydragnn_tpu.utils.faults import KILL_EXIT_CODE

    assert rcs[1] == KILL_EXIT_CODE, f"killed host agent rc: {rcs}"
    assert rcs[0] == 0, f"survivor agent rc: {rcs}"

    result = json.load(open(os.path.join(workdir, "result.json")))
    num_epoch = _elastic_worker.NUM_EPOCH
    assert result["world"] == 1 and result["gen"] >= 1, result
    resumed = result["resumed_from_epoch"]
    assert resumed is not None and 1 <= resumed < num_epoch, result
    assert result["epochs_run"] == list(range(resumed, num_epoch)), result

    recs = validate_events(
        os.path.join(workdir, "logs", "elastic", "events.jsonl"),
        require=["host_lost", "world_resize", "checkpoint_saved"],
    )
    resize = [r for r in recs if r["event"] == "world_resize"][-1]
    assert resize["old_world"] == 2 and resize["new_world"] == 1, resize
    assert 0.0 < resize["recovery_s"] < 240.0, resize
    n_async = sum(
        1 for r in recs
        if r["event"] == "checkpoint_saved" and r.get("async")
    )
    assert n_async > 0, "async checkpointing never engaged"

    # trajectory acceptance: a clean 1-process restart from the rolling
    # checkpoint the resized world resumed from lands on the identical
    # final parameters
    from hydragnn_tpu.train import checkpoint as ck

    roll_by_epoch = {}
    for p in ck.rolling_checkpoints(
        "elastic", path=os.path.join(workdir, "logs")
    ):
        meta = ck.pop_train_meta(
            ck._parse_checkpoint_bytes(open(p, "rb").read(), p)
        )
        roll_by_epoch.setdefault(int(meta["epoch"]), p)
    refdir = os.path.join(workdir, "ref")
    ref_ck = os.path.join(refdir, "logs", "elastic")
    os.makedirs(ref_ck, exist_ok=True)
    with open(roll_by_epoch[resumed - 1], "rb") as src, open(
        os.path.join(ref_ck, "elastic.pk"), "wb"
    ) as dst:
        dst.write(src.read())
    env = {
        k: v
        for k, v in os.environ.items()
        if not k.startswith(("HYDRAGNN_FAULT_", "HYDRAGNN_ELASTIC_",
                             "HYDRAGNN_TPU_"))
    }
    worker = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "_elastic_worker.py"
    )
    ref = subprocess.run(
        [sys.executable, worker, "worker", refdir], env=env, timeout=240
    )
    assert ref.returncode == 0, f"reference restart rc {ref.returncode}"
    ref_res = json.load(open(os.path.join(refdir, "result.json")))
    assert ref_res["resumed_from_epoch"] == resumed, ref_res
    assert ref_res["final_params_digest"] == result["final_params_digest"], (
        "post-resize trajectory diverged from the clean restart"
    )
    print(
        "elastic smoke OK: 2->1 re-mesh, resumed at epoch "
        f"{resumed}, recovery {resize['recovery_s']:.2f}s, "
        f"{n_async} async checkpoint saves, trajectory == clean restart"
    )


if __name__ == "__main__":
    main(sys.argv[1])
