"""Worker for the multi-process distributed test (NOT a pytest module).

Each process: 2 virtual CPU devices, `jax.distributed` bootstrap through the
framework's env-var path, host-side collectives, then a REAL data-parallel
training step on the global cross-process mesh with per-process local batch
shards — the reference's `mpirun -n 2 --with-mpi` CI story (SURVEY.md §4)
without MPI.

Usage: python _multiprocess_worker.py <proc_id> <num_procs> <port>
"""

import os
import sys


def make_samples(num, seed):
    """Deterministic local-shard samples (shared with the test's
    reference-loss computation)."""
    import numpy as np

    class _S:
        pass

    rng = np.random.default_rng(seed)
    out = []
    for _ in range(num):
        n = 6
        s = _S()
        s.x = rng.random((n, 1)).astype(np.float32)
        s.pos = rng.random((n, 3)).astype(np.float32)
        src = np.arange(n)
        dst = (src + 1) % n
        s.edge_index = np.stack(
            [np.concatenate([src, dst]), np.concatenate([dst, src])]
        ).astype(np.int64)
        s.edge_attr = None
        s.targets = [np.array([s.x.sum()], np.float32), s.x.copy()]
        out.append(s)
    return out


def worker_arch():
    return {
        "model_type": "GIN",
        "input_dim": 1,
        "hidden_dim": 8,
        "output_dim": [1, 1],
        "output_type": ["graph", "node"],
        "output_heads": {
            "graph": {
                "num_sharedlayers": 1,
                "dim_sharedlayers": 8,
                "num_headlayers": 1,
                "dim_headlayers": [8],
            },
            "node": {
                "num_headlayers": 1,
                "dim_headlayers": [8],
                "type": "mlp",
            },
        },
        "task_weights": [1.0, 1.0],
        "num_conv_layers": 2,
    }


def main():
    proc_id, num_procs, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=2"
    ).strip()
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["HYDRAGNN_TPU_COORDINATOR"] = f"127.0.0.1:{port}"
    os.environ["HYDRAGNN_TPU_NUM_PROCESSES"] = str(num_procs)
    os.environ["HYDRAGNN_TPU_PROCESS_ID"] = str(proc_id)

    import jax

    jax.config.update("jax_platforms", "cpu")

    sys.path.insert(
        0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    import numpy as np

    from hydragnn_tpu.parallel.distributed import (
        host_allreduce,
        setup_distributed,
    )

    world, rank = setup_distributed()
    assert world == num_procs, f"world {world} != {num_procs}"
    assert rank == proc_id, f"rank {rank} != {proc_id}"
    assert len(jax.devices()) == 2 * num_procs, jax.devices()

    # host-side collective (data-plane statistics path)
    total = host_allreduce(np.array([float(rank + 1)]), "sum")
    expect = num_procs * (num_procs + 1) / 2
    assert float(total[0]) == expect, (total, expect)

    # ---- real sharded training step over the global mesh ----------------
    from hydragnn_tpu.graph import collate_graphs, pad_sizes_for
    from hydragnn_tpu.models import create_model_config
    from hydragnn_tpu.parallel.mesh import make_mesh
    from hydragnn_tpu.train.trainer import Trainer

    samples = make_samples

    # every process collates ITS OWN local shard (different data per rank);
    # put_batch assembles the global array from the local shards
    local_graphs = 4
    n_pad, e_pad, g_pad = pad_sizes_for(
        6, 12, local_graphs, node_multiple=8, edge_multiple=8, graph_multiple=8
    )
    batch = collate_graphs(
        samples(local_graphs, seed=100 + rank),
        n_pad,
        e_pad,
        g_pad,
        head_types=("graph", "node"),
        head_dims=(1, 1),
    )

    model = create_model_config(worker_arch())
    mesh = make_mesh(None, "data")  # all 2*num_procs global devices
    trainer = Trainer(
        model,
        training_config={"Optimizer": {"type": "AdamW", "learning_rate": 1e-3}},
        mesh=mesh,
    )
    state = trainer.init_state(batch)
    dev_batch = trainer.put_batch(batch)
    state, metrics = trainer._train_step(state, dev_batch, jax.random.PRNGKey(0))
    loss = float(metrics["loss"])
    assert np.isfinite(loss), loss

    # the loss is a global reduction — every process must agree exactly
    agree = host_allreduce(np.array([loss]), "max")
    assert abs(float(agree[0]) - loss) < 1e-6, (agree, loss)

    # multi-host predict: each process collects its OWN shard's samples
    class _Loader(list):
        dataset = ()

    _, _, true_vals, pred_vals = trainer.predict(state, _Loader([batch]))
    assert true_vals[0].shape[0] == local_graphs, true_vals[0].shape
    assert true_vals[1].shape[0] == local_graphs * 6, true_vals[1].shape
    assert pred_vals[0].shape == true_vals[0].shape

    # multi-host device-resident whole-training dispatch: each process
    # stages ITS local shard of every microbatch; fit_staged runs epochs
    # on-device over the global mesh and all processes agree on the series
    batch2 = collate_graphs(
        samples(local_graphs, seed=200 + rank),
        n_pad,
        e_pad,
        g_pad,
        head_types=("graph", "node"),
        head_dims=(1, 1),
    )
    staged = trainer.stage_batches([batch, batch2])
    state, best_state, sched, _rng, series = trainer.fit_staged(
        state, staged, 2, jax.random.PRNGKey(1), shuffle=False
    )
    assert np.isfinite(series["train_loss"]).all(), series["train_loss"]
    assert int(np.asarray(sched.epoch)) == 2
    agree = host_allreduce(np.array([series["train_loss"][-1]]), "max")
    assert abs(float(agree[0]) - series["train_loss"][-1]) < 1e-6

    # streaming epoch across hosts (exercises the multi-host metric
    # accumulation path: per-batch host fetch of replicated scalars)
    class _EpochLoader(list):
        def set_epoch(self, e):
            pass

    state, _rng2, ep_loss, ep_tasks = trainer.train_epoch(
        state, _EpochLoader([batch, batch2]), jax.random.PRNGKey(2)
    )
    assert np.isfinite(ep_loss), ep_loss
    agree = host_allreduce(np.array([ep_loss]), "max")
    assert abs(float(agree[0]) - ep_loss) < 1e-6, (agree, ep_loss)

    # ZeRO-style sharded optimizer state -> single consolidated checkpoint
    # (reference: consolidate_state_dict, utils/model.py:60-74)
    import tempfile

    from hydragnn_tpu.parallel.mesh import shard_optimizer_state
    from hydragnn_tpu.train.checkpoint import load_state_dict, save_model

    sharded = state.replace(
        opt_state=shard_optimizer_state(state.opt_state, mesh)
    )
    ckdir = os.environ["HYDRAGNN_TPU_TEST_CKPT"]  # shared across ranks
    save_model(sharded, "mp_ckpt", path=ckdir)
    if rank == 0:
        restored = load_state_dict("mp_ckpt", path=ckdir)
        want = jax.tree_util.tree_leaves(jax.device_get(state.params))
        got = jax.tree_util.tree_leaves(restored["params"])
        assert len(want) == len(got)
        for w, g in zip(want, got):
            np.testing.assert_allclose(np.asarray(g), np.asarray(w), rtol=1e-6)
        # sharded moments came back whole (same leaf count and shapes)
        n_opt_leaves = len(jax.tree_util.tree_leaves(state.opt_state))
        assert len(jax.tree_util.tree_leaves(restored["opt_state"])) == n_opt_leaves

    print(f"MPOK rank={rank} world={world} loss={loss:.6f}", flush=True)


if __name__ == "__main__":
    main()
