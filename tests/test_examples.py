"""Example smoke tests: run example workloads as subprocesses, assert exit 0.

Covers ALL example entry points (the reference smokes only qm9+md17,
``tests/test_examples.py:18-26``; round-1 verdict asked for full coverage).
On this 1-core CI host each subprocess costs ~20-30 s, so the default tier
runs a subset chosen to exercise every MECHANISM — raw-format generation +
real parsers (qm9), multihead forces (md17), the shard-store preonly->train
->ddstore chain (open_catalyst_2020), real-MPtrj-format ingestion (mptrj),
graph partitioning (giant_graph), HPO (qm9_hpo) — and
``HYDRAGNN_FULL_TEST=1`` runs every example.

Children run with ``-S`` + explicit paths so they get the CPU backend
deterministically regardless of the container's site hooks.
"""

import os
import subprocess
import sys
import sysconfig

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FULL = int(os.getenv("HYDRAGNN_FULL_TEST", "0")) == 1


def _run_example(script, *flags, cwd, env_extra=None):
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "PYTHONPATH": sysconfig.get_paths()["purelib"] + os.pathsep + _REPO,
        **(env_extra or {}),
    }
    return subprocess.run(
        [sys.executable, "-S", "-u", os.path.join(_REPO, script), *flags],
        cwd=cwd,
        env=env,
        capture_output=True,
        text=True,
        timeout=540,
    )


# every standalone example entry point: script + tiny-size flags.
# (open_catalyst_2020 and the HPO examples have dedicated tests below.)
_EXAMPLES = {
    "qm9": ("examples/qm9/qm9.py", ["--num_samples=60", "--num_epoch=2"]),
    "md17": ("examples/md17/md17.py", ["--num_samples=60", "--num_epoch=2"]),
    "mptrj": ("examples/mptrj/train.py", ["--num_samples=10", "--num_epoch=2"]),
    # lsms uses compositional stratified splitting: needs enough samples
    # for every composition class to appear in each split
    "lsms": ("examples/lsms/lsms.py", ["--num_samples=100", "--num_epoch=2"]),
    "eam": ("examples/eam/eam.py", ["--num_samples=120", "--num_epoch=2"]),
    "ising": (
        "examples/ising_model/train_ising.py",
        ["--num_samples=40", "--num_epoch=2"],
    ),
    "csce": ("examples/csce/train_gap.py", ["--num_samples=40", "--num_epoch=2"]),
    "ogb": ("examples/ogb/train_gap.py", ["--num_samples=40", "--num_epoch=2"]),
    "dftb": (
        "examples/dftb_uv_spectrum/train_smooth_uv_spectrum.py",
        ["--num_samples=40", "--num_epoch=2"],
    ),
    "qm7x": ("examples/qm7x/train.py", ["--num_samples=40", "--num_epoch=2"]),
    "alexandria": (
        "examples/alexandria/train.py",
        ["--num_samples=40", "--num_epoch=2"],
    ),
}

# examples whose data plane needs a --preonly shard-writing pass first
# (the reference's canonical two-phase flow)
_CHAINED = {
    "oc22": ("examples/open_catalyst_2022/train.py", ["--num_samples=40"]),
    "ani1_x": ("examples/ani1_x/train.py", ["--num_samples=120"]),
    "multidataset": ("examples/multidataset/train.py", ["--num_samples=30"]),
}

# default tier: one example per mechanism; FULL: everything
_DEFAULT = ["qm9", "md17", "mptrj"]


@pytest.mark.parametrize(
    "example", sorted(_EXAMPLES) if FULL else _DEFAULT
)
def pytest_example_smoke(example, tmp_path):
    script, flags = _EXAMPLES[example]
    res = _run_example(script, *flags, cwd=str(tmp_path))
    assert res.returncode == 0, res.stdout[-2000:] + res.stderr[-2000:]
    assert "Val Loss:" in res.stdout


@pytest.mark.parametrize(
    "example", sorted(_CHAINED) if FULL else []
)
def pytest_example_preonly_chain(example, tmp_path):
    script, flags = _CHAINED[example]
    res = _run_example(script, "--preonly", *flags, cwd=str(tmp_path))
    assert res.returncode == 0, res.stdout[-2000:] + res.stderr[-2000:]
    res = _run_example(script, *flags, "--num_epoch=2", cwd=str(tmp_path))
    assert res.returncode == 0, res.stdout[-2000:] + res.stderr[-2000:]
    assert "Val Loss:" in res.stdout


def pytest_example_giant_graph(tmp_path):
    """Graph-partition demo: one graph over a 4-device virtual CPU mesh."""
    res = _run_example(
        "examples/giant_graph/train.py",
        "--num_atoms", "512", "--steps", "6", "--cpu_devices", "4",
        cwd=str(tmp_path),
    )
    assert res.returncode == 0, res.stdout[-2000:] + res.stderr[-2000:]
    assert "loss" in res.stdout


def pytest_example_shard_pipeline(tmp_path):
    """open_catalyst: the full preonly -> mmap train -> ddstore chain
    (the reference's canonical --preonly / --adios / --ddstore flow)."""
    res = _run_example(
        "examples/open_catalyst_2020/train.py",
        "--preonly", "--num_samples=80", cwd=str(tmp_path),
    )
    assert res.returncode == 0, res.stdout[-2000:] + res.stderr[-2000:]
    res = _run_example(
        "examples/open_catalyst_2020/train.py",
        "--num_epoch=2", cwd=str(tmp_path),
    )
    assert res.returncode == 0, res.stdout[-2000:] + res.stderr[-2000:]
    assert "Val Loss:" in res.stdout
    if FULL:
        res = _run_example(
            "examples/open_catalyst_2020/train.py",
            "--num_epoch=1", "--ddstore", cwd=str(tmp_path),
        )
        assert res.returncode == 0, res.stdout[-2000:] + res.stderr[-2000:]
        # subgroup replication (ddstore_width): width 1 = every rank its
        # own block holding a full replica — the degenerate-but-real
        # subgroup path end-to-end through the example surface
        res = _run_example(
            "examples/open_catalyst_2020/train.py",
            "--num_epoch=1", "--ddstore", "--ddstore_width=1",
            cwd=str(tmp_path),
        )
        assert res.returncode == 0, res.stdout[-2000:] + res.stderr[-2000:]


def pytest_example_hpo(tmp_path):
    """qm9_hpo with 2 trials (the reference's Optuna/DeepHyper analog)."""
    res = _run_example(
        "examples/qm9_hpo/qm9_hpo.py",
        "--num_samples=40", "--n_trials=2", "--num_epoch=1",
        cwd=str(tmp_path),
    )
    assert res.returncode == 0, res.stdout[-2000:] + res.stderr[-2000:]
    assert "best" in res.stdout.lower() or "Val Loss:" in res.stdout


@pytest.mark.skipif(not FULL, reason="multi-node HPO launcher: FULL tier")
def pytest_example_hpo_multi(tmp_path):
    """multidataset_hpo launcher with 2 in-process trials."""
    res = _run_example(
        "examples/multidataset_hpo/gfm_hpo_multi.py",
        cwd=str(tmp_path),
        env_extra={"HPO_NUM_TRIALS": "2", "HPO_NUM_SAMPLES": "30"},
    )
    assert res.returncode == 0, res.stdout[-2000:] + res.stderr[-2000:]
