"""Example smoke tests: run example workloads as subprocesses, assert exit 0.

Mirrors ``tests/test_examples.py:18-26`` in the reference (qm9 + md17 run
as subprocesses). Children run with ``-S`` + explicit paths so they get the
CPU backend deterministically regardless of the container's site hooks.
"""

import os
import subprocess
import sys
import sysconfig

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_example(script, *flags, cwd):
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "PYTHONPATH": sysconfig.get_paths()["purelib"] + os.pathsep + _REPO,
    }
    return subprocess.run(
        [sys.executable, "-S", "-u", os.path.join(_REPO, script), *flags],
        cwd=cwd,
        env=env,
        capture_output=True,
        text=True,
        timeout=540,
    )


@pytest.mark.parametrize("example", ["qm9", "md17"])
def pytest_example_smoke(example, tmp_path):
    script = {
        "qm9": "examples/qm9/qm9.py",
        "md17": "examples/md17/md17.py",
    }[example]
    res = _run_example(
        script, "--num_samples=60", "--num_epoch=2", cwd=str(tmp_path)
    )
    assert res.returncode == 0, res.stdout[-2000:] + res.stderr[-2000:]
    assert "Val Loss:" in res.stdout


def pytest_example_giant_graph(tmp_path):
    """Graph-partition demo: one graph over a 4-device virtual CPU mesh."""
    res = _run_example(
        "examples/giant_graph/train.py",
        "--num_atoms", "512", "--steps", "6", "--cpu_devices", "4",
        cwd=str(tmp_path),
    )
    assert res.returncode == 0, res.stdout[-2000:] + res.stderr[-2000:]
    assert "loss" in res.stdout


def pytest_example_shard_pipeline(tmp_path):
    """open_catalyst: preonly shard write then a training run reading it."""
    res = _run_example(
        "examples/open_catalyst_2020/train.py",
        "--preonly", "--num_samples=80", cwd=str(tmp_path),
    )
    assert res.returncode == 0, res.stdout[-2000:] + res.stderr[-2000:]
    res = _run_example(
        "examples/open_catalyst_2020/train.py",
        "--num_epoch=2", cwd=str(tmp_path),
    )
    assert res.returncode == 0, res.stdout[-2000:] + res.stderr[-2000:]
    assert "Val Loss:" in res.stdout
