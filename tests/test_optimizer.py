"""Optimizer factory smoke tests (reference ``tests/test_optimizer.py:
40-100``): every supported optimizer takes a few steps; the ZeRO-parity
opt-state sharding helper places state on the mesh."""

import numpy as np
import pytest

import jax

from hydragnn_tpu.models import create_model_config
from hydragnn_tpu.parallel.mesh import make_mesh, shard_optimizer_state
from hydragnn_tpu.train.trainer import Trainer

from test_models_forward import arch_config, make_batch

OPTIMIZERS = [
    "SGD",
    "Adam",
    "Adadelta",
    "Adagrad",
    "Adamax",
    "AdamW",
    "RMSprop",
    "FusedLAMB",
]


@pytest.mark.parametrize("opt_type", OPTIMIZERS)
def pytest_optimizers(opt_type):
    batch = make_batch()
    model = create_model_config(arch_config("SAGE"))
    trainer = Trainer(
        model, {"Optimizer": {"type": opt_type, "learning_rate": 1e-3}}
    )
    state = trainer.init_state(batch)
    rng = jax.random.PRNGKey(0)
    for _ in range(2):
        rng, sub = jax.random.split(rng)
        state, metrics = trainer._train_step(state, trainer.put_batch(batch), sub)
    assert np.isfinite(float(metrics["loss"]))


def pytest_zero_redundancy_sharding():
    """The ZeRO helper routes through the rule engine: weight-like
    (ndim >= 2) moments shard over data, 1-D bias moments REPLICATE (the
    old shape heuristic sharded a divisible-size bias silently). The
    step programs now declare explicit in_shardings, so arbitrary
    external reshards are corrected by place_state — which restores the
    step contract and training still steps."""
    from jax.sharding import PartitionSpec as P

    batch = make_batch()
    model = create_model_config(arch_config("SAGE"))
    mesh = make_mesh()
    trainer = Trainer(
        model, {"Optimizer": {"type": "AdamW", "learning_rate": 1e-3}}, mesh=mesh
    )
    state = trainer.init_state(batch)
    sharded = shard_optimizer_state(state.opt_state, mesh)
    import jax.tree_util as jtu

    def name_of(path):
        return "/".join(
            str(getattr(k, "key", getattr(k, "name", getattr(k, "idx", k))))
            for k in path
        )

    specs = {
        name_of(path): tuple(leaf.sharding.spec)
        for path, leaf in jtu.tree_flatten_with_path(sharded)[0]
        if hasattr(leaf, "sharding")
    }
    kernels = {k: v for k, v in specs.items() if k.endswith("kernel")}
    biases = {
        k: v
        for k, v in specs.items()
        if k.endswith("bias") or k.endswith("scale")
    }
    assert kernels and any(v and v[0] == "data" for v in kernels.values()), specs
    # THE fix: divisible-size biases no longer shard silently
    assert all(v == () for v in biases.values()), biases
    # an externally resharded state re-enters the step via place_state
    state = trainer.place_state(state.replace(opt_state=sharded))
    rng = jax.random.PRNGKey(0)
    state, metrics = trainer._train_step(state, trainer.put_batch(batch), rng)
    assert np.isfinite(float(metrics["loss"]))


def pytest_zero_redundancy_config_key():
    """The reference's Optimizer.use_zero_redundancy switch must actually
    shard the optimizer state over the mesh (not just exist in docs)."""
    from jax.sharding import PartitionSpec as P

    batch = make_batch()
    model = create_model_config(arch_config("SAGE"))
    mesh = make_mesh()
    trainer = Trainer(
        model,
        {
            "Optimizer": {
                "type": "AdamW",
                "learning_rate": 1e-3,
                "use_zero_redundancy": True,
            }
        },
        mesh=mesh,
    )
    state = trainer.init_state(batch)
    specs = [
        getattr(leaf.sharding, "spec", None)
        for leaf in jax.tree_util.tree_leaves(state.opt_state)
        if hasattr(leaf, "sharding")
    ]
    assert any(s == P("data") for s in specs), specs
    rng = jax.random.PRNGKey(0)
    state, metrics = trainer._train_step(state, trainer.put_batch(batch), rng)
    assert np.isfinite(float(metrics["loss"]))


def pytest_zero_stage3_shards_parameters():
    """Optimizer.zero_stage: 3 (DeepSpeed stage-3 parity) shards the
    PARAMETERS over the data axis too; training still steps and the first
    loss matches stage 1 (sharding is placement, not arithmetic)."""
    from jax.sharding import PartitionSpec as P

    batch = make_batch()
    model = create_model_config(arch_config("SAGE"))
    mesh = make_mesh()
    rng = jax.random.PRNGKey(0)
    losses = {}
    for stage in (1, 3):
        trainer = Trainer(
            model,
            {
                "Optimizer": {
                    "type": "AdamW",
                    "learning_rate": 1e-3,
                    "zero_stage": stage,
                }
            },
            mesh=mesh,
        )
        state = trainer.init_state(batch)
        specs = [
            getattr(leaf.sharding, "spec", None)
            for leaf in jax.tree_util.tree_leaves(state.params)
            if hasattr(leaf, "sharding")
        ]
        if stage == 3:
            assert any(s == P("data") for s in specs), specs
        else:
            assert all(s != P("data") for s in specs), specs
        state, metrics = trainer._train_step(
            state, trainer.put_batch(batch), rng
        )
        losses[stage] = float(metrics["loss"])
        assert np.isfinite(losses[stage])
    np.testing.assert_allclose(losses[1], losses[3], rtol=1e-5)


def pytest_freeze_conv():
    """freeze_conv_layers: encoder params must not change, heads must."""
    batch = make_batch()
    model = create_model_config(arch_config("SAGE"))
    trainer = Trainer(
        model,
        {"Optimizer": {"type": "SGD", "learning_rate": 0.1}},
        freeze_conv=True,
    )
    state = trainer.init_state(batch)
    before = jax.device_get(state.params)
    rng = jax.random.PRNGKey(0)
    state, _ = trainer._train_step(state, trainer.put_batch(batch), rng)
    after = jax.device_get(state.params)
    for key in before:
        changed = any(
            not np.allclose(a, b)
            for (_, a), (_, b) in zip(
                jax.tree_util.tree_leaves_with_path(before[key]),
                jax.tree_util.tree_leaves_with_path(after[key]),
            )
        )
        if str(key).startswith("encoder_"):
            assert not changed, f"frozen {key} changed"
        else:
            assert changed, f"head {key} did not change"
