"""Banded window gather/scatter kernels: forward and VJP parity against
plain XLA gather/scatter on banded indices (the packed-batch contract),
plus the PNA dense-path equivalence with the kernels forced on/off."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from hydragnn_tpu.ops.pallas_window import (
    window_enabled,
    window_gather,
    window_scatter_add,
)


def _banded_idx(rng, n, band, rows_per_anchor):
    """[n*rows_per_anchor] indices with |idx[r] - anchor(r)| < band; ~10%
    marked invalid (-1)."""
    anchors = np.repeat(np.arange(n), rows_per_anchor)
    lo = np.maximum(anchors - band + 1, 0)
    hi = np.minimum(anchors + band, n)
    idx = rng.integers(lo, hi).astype(np.int32)
    idx[rng.random(idx.shape) < 0.1] = -1
    return idx


@pytest.mark.parametrize("n,k,band,halo", [(300, 4, 90, 1), (520, 7, 250, 2)])
def pytest_window_gather_matches_xla(n, k, band, halo):
    rng = np.random.default_rng(0)
    d = 24
    table = jnp.asarray(rng.standard_normal((n, d)), jnp.float32)
    idx = _banded_idx(rng, n, band, k)
    valid = idx >= 0
    ref = np.where(valid[:, None], np.asarray(table)[np.maximum(idx, 0)], 0.0)
    out = jax.jit(
        lambda t: window_gather(t, jnp.asarray(idx), halo, k)
    )(table)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-6, atol=1e-6)

    # VJP: d/d_table of sum(w * gather) == scatter-add of w
    w = rng.standard_normal((idx.shape[0], d)).astype(np.float32)

    def loss(t):
        return jnp.sum(window_gather(t, jnp.asarray(idx), halo, k) * w)

    g = jax.jit(jax.grad(loss))(table)
    ref_g = np.zeros((n, d), np.float32)
    np.add.at(ref_g, idx[valid], w[valid])
    np.testing.assert_allclose(np.asarray(g), ref_g, rtol=1e-5, atol=1e-5)


def pytest_window_scatter_matches_xla():
    rng = np.random.default_rng(1)
    n, k, d, band, halo = 260, 5, 16, 120, 1
    idx = _banded_idx(rng, n, band, k)
    valid = idx >= 0
    vals = jnp.asarray(rng.standard_normal((idx.shape[0], d)), jnp.float32)
    out = jax.jit(
        lambda v: window_scatter_add(v, jnp.asarray(idx), n, halo, k)
    )(vals)
    ref = np.zeros((n, d), np.float32)
    np.add.at(ref, idx[valid], np.asarray(vals)[valid])
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-5, atol=1e-5)

    # VJP of scatter is the gather
    w = rng.standard_normal((n, d)).astype(np.float32)

    def loss(v):
        return jnp.sum(window_scatter_add(v, jnp.asarray(idx), n, halo, k) * w)

    g = jax.jit(jax.grad(loss))(vals)
    ref_g = np.where(valid[:, None], w[np.maximum(idx, 0)], 0.0)
    np.testing.assert_allclose(np.asarray(g), ref_g, rtol=1e-5, atol=1e-5)


def pytest_window_gather_anchor_ratio():
    """Edge-table gathers: idx blocks target a denser table (ratio num/den
    maps idx block i to table block (i*num)//den)."""
    rng = np.random.default_rng(2)
    n, k, d = 256, 4, 8
    ratio = (2, 1)  # table has ~2 rows per anchor row
    table = jnp.asarray(rng.standard_normal((2 * n, d)), jnp.float32)
    anchors = np.repeat(np.arange(n), k)
    idx = (2 * anchors + rng.integers(-60, 60, anchors.shape)).astype(np.int32)
    idx = np.clip(idx, 0, 2 * n - 1)
    idx[rng.random(idx.shape) < 0.1] = -1
    valid = idx >= 0
    out = jax.jit(
        lambda t: window_gather(t, jnp.asarray(idx), 1, k, ratio)
    )(table)
    ref = np.where(valid[:, None], np.asarray(table)[np.maximum(idx, 0)], 0.0)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-6, atol=1e-6)


def pytest_pna_dense_window_matches_xla_gather(monkeypatch):
    """The PNA dense path with the banded kernel on vs off: identical
    outputs and gradients through the public model API."""
    from test_models_forward import FakeData, arch_config
    from hydragnn_tpu.graph import collate_graphs, pad_sizes_for
    from hydragnn_tpu.models import create_model_config, init_model_params
    from hydragnn_tpu.ops.dense_agg import attach_neighbor_lists

    rng = np.random.default_rng(3)
    samples = [FakeData(rng, int(rng.integers(4, 9))) for _ in range(6)]
    n_pad, e_pad, g_pad = pad_sizes_for(8, 16, 6, graph_multiple=8)
    batch = collate_graphs(
        samples, n_pad, e_pad, g_pad,
        head_types=("graph", "node"), head_dims=(1, 1),
    )
    batch = attach_neighbor_lists(batch)
    cfg = arch_config("PNA")
    cfg["hidden_dim"] = 64  # the kernel gate needs >=64 features
    cfg["max_graph_nodes"] = 8  # the halo needs the guaranteed size bound
    model = create_model_config(cfg)
    assert model.window_halo() == 1
    variables = init_model_params(model, batch)

    def run():
        def loss(v):
            outs = model.apply(v, batch, train=False)
            tot, _ = model.loss(outs, batch)
            return tot

        val, grads = jax.jit(jax.value_and_grad(loss))(variables)
        return float(val), jax.tree_util.tree_map(np.asarray, grads)

    monkeypatch.setenv("HYDRAGNN_WINDOW", "1")
    assert window_enabled(1, 4, 64)
    v_on, g_on = run()
    monkeypatch.setenv("HYDRAGNN_WINDOW", "0")
    jax.clear_caches()  # enablement is read at trace time
    v_off, g_off = run()
    assert np.isclose(v_on, v_off, rtol=1e-5), (v_on, v_off)
    for a, b in zip(
        jax.tree_util.tree_leaves(g_on), jax.tree_util.tree_leaves(g_off)
    ):
        np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-5)


def pytest_window_gather_stats_matches_dense_ops():
    """Fused kernel == gather + dense_moments + dense_minmax, values AND
    gradients (incl. min/max tie splitting and the variance clamp)."""
    from hydragnn_tpu.ops.dense_agg import dense_minmax, dense_moments
    from hydragnn_tpu.ops.pallas_window import window_gather_stats

    rng = np.random.default_rng(5)
    n, k, d, band, halo = 300, 6, 16, 90, 1
    table = jnp.asarray(rng.standard_normal((n, d)), jnp.float32)
    idx = _banded_idx(rng, n, band, k).reshape(n, k)
    mask = idx >= 0
    # duplicate some entries to force min/max ties
    idx[:, 1] = np.where(mask[:, 0], idx[:, 0], idx[:, 1])
    mask[:, 1] = mask[:, 1] | mask[:, 0]
    idx = np.maximum(idx, 0)
    mask[5] = False  # an empty anchor

    def ref(t):
        h = t[jnp.asarray(idx)]
        h = jnp.where(jnp.asarray(mask)[..., None], h, 0.0)
        mean, std, deg, has = dense_moments(h, jnp.asarray(mask))
        mn, mx = dense_minmax(h, jnp.asarray(mask), has)
        return mean, std, mn, mx, deg

    def fused(t):
        mean, std, mn, mx, cnt = window_gather_stats(
            t, jnp.asarray(idx.reshape(-1)),
            jnp.asarray(mask.reshape(-1)), halo, k,
        )
        return mean, std, mn, mx, jnp.maximum(cnt, 1.0)

    r_ref = jax.jit(ref)(table)
    r_fus = jax.jit(fused)(table)
    for a, b, name in zip(r_ref, r_fus, ["mean", "std", "mn", "mx", "deg"]):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-5,
            err_msg=name,
        )

    w = [rng.standard_normal(np.asarray(x).shape).astype(np.float32)
         for x in r_ref]

    def loss(fn, t):
        outs = fn(t)
        return sum(jnp.sum(o * wi) for o, wi in zip(outs[:4], w))

    g_ref = jax.jit(jax.grad(lambda t: loss(ref, t)))(table)
    g_fus = jax.jit(jax.grad(lambda t: loss(fused, t)))(table)
    # rtol 5e-4: the slot-loop vs vectorized reduce order differs by ulps
    # in the f32 mean, which the std gradient amplifies near the variance
    # clamp (observed max 2e-4 relative on a single element)
    np.testing.assert_allclose(
        np.asarray(g_fus), np.asarray(g_ref), rtol=5e-4, atol=1e-5
    )
