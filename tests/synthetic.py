"""Deterministic synthetic dataset with closed-form targets.

Same data contract as the reference fixture
(``tests/deterministic_graph_data.py:19-173``): BCC supercells written as
LSMS-style text files where node feature = type id, node outputs are the
KNN-smoothed feature x and x^2 + type, x^3, and the graph output is the sum of
all node outputs. File format:

    GRAPH_OUTPUT [GRAPH_OUTPUT_LINEAR]
    feature  index  x  y  z  out1  out2  out3
"""

import os

import numpy as np
from sklearn.neighbors import KNeighborsRegressor


def deterministic_graph_data(
    path: str,
    number_configurations: int = 500,
    configuration_start: int = 0,
    unit_cell_x_range=(1, 3),
    unit_cell_y_range=(1, 3),
    unit_cell_z_range=(1, 2),
    number_types: int = 3,
    types=None,
    number_neighbors: int = 2,
    linear_only: bool = False,
    seed: int = 97,
):
    if types is None:
        types = range(number_types)
    os.makedirs(path, exist_ok=True)
    rng = np.random.default_rng(seed + configuration_start)
    ux = rng.integers(unit_cell_x_range[0], unit_cell_x_range[1], number_configurations)
    uy = rng.integers(unit_cell_y_range[0], unit_cell_y_range[1], number_configurations)
    uz = rng.integers(unit_cell_z_range[0], unit_cell_z_range[1], number_configurations)
    for c in range(number_configurations):
        _write_configuration(
            path,
            c + configuration_start,
            int(ux[c]),
            int(uy[c]),
            int(uz[c]),
            list(types),
            number_neighbors,
            linear_only,
            rng,
        )


def _write_configuration(
    path, index, uc_x, uc_y, uc_z, types, number_neighbors, linear_only, rng
):
    n = 2 * uc_x * uc_y * uc_z
    positions = np.zeros((n, 3))
    k = 0
    for x in range(uc_x):
        for y in range(uc_y):
            for z in range(uc_z):
                positions[k] = (x, y, z)
                positions[k + 1] = (x + 0.5, y + 0.5, z + 0.5)
                k += 2
    node_feature = rng.integers(min(types), max(types) + 1, (n, 1)).astype(
        np.float64
    )
    if linear_only:
        out_x = node_feature.copy()
    else:
        knn = KNeighborsRegressor(number_neighbors)
        knn.fit(positions, node_feature)
        out_x = knn.predict(positions).reshape(n, 1)
    out_x2 = out_x ** 2 + node_feature
    out_x3 = out_x ** 3

    total = float(out_x.sum() + out_x2.sum() + out_x3.sum())
    total_linear = float(out_x.sum())
    lines = []
    if linear_only:
        lines.append(f"{total_linear:.6g}")
    else:
        lines.append(f"{total:.6g}\t{total_linear:.6g}")
    for i in range(n):
        row = [
            node_feature[i, 0],
            float(i),
            positions[i, 0],
            positions[i, 1],
            positions[i, 2],
            out_x[i, 0],
            out_x2[i, 0],
            out_x3[i, 0],
        ]
        lines.append("\t".join(f"{v:.2f}" for v in row))
    with open(os.path.join(path, f"output{index}.txt"), "w") as f:
        f.write("\n".join(lines))
