"""Rotational-invariance of the NormalizeRotation transform (reference:
``tests/test_rotational_invariance.py``): rotating the input positions must
not change the principal-axes-aligned geometry (up to sign conventions), so
edge lengths and radius graphs are identical."""

import numpy as np

from hydragnn_tpu.data.dataobj import GraphData
from hydragnn_tpu.data.radius_graph import radius_graph
from hydragnn_tpu.data.transforms import add_edge_lengths, normalize_rotation


def _rot(theta_z, theta_y):
    cz, sz = np.cos(theta_z), np.sin(theta_z)
    cy, sy = np.cos(theta_y), np.sin(theta_y)
    rz = np.array([[cz, -sz, 0], [sz, cz, 0], [0, 0, 1]])
    ry = np.array([[cy, 0, sy], [0, 1, 0], [-sy, 0, cy]])
    return rz @ ry


def pytest_rotated_geometry_matches():
    rng = np.random.default_rng(7)
    pos = rng.random((10, 3)).astype(np.float32) * 3
    d1 = GraphData(x=np.ones((10, 1), np.float32), pos=pos.copy())
    d2 = GraphData(
        x=np.ones((10, 1), np.float32),
        pos=(pos @ _rot(0.7, -0.3).T).astype(np.float32),
    )
    normalize_rotation(d1)
    normalize_rotation(d2)

    for d in (d1, d2):
        d.edge_index = radius_graph(d.pos, radius=2.0, max_neighbors=100)
        d.edge_attr = None
        add_edge_lengths(d)

    assert d1.edge_index.shape == d2.edge_index.shape
    # compare sorted edge-length multisets (node order preserved, so direct)
    assert np.allclose(
        np.sort(d1.edge_attr.ravel()), np.sort(d2.edge_attr.ravel()), atol=1e-4
    )
